"""Headline benchmark: the north-star configuration — a 100k-node x 1M-pod
problem with topology spread, inter-pod anti-affinity, and Open-Local
storage demand (BASELINE.md north-star row) — through the bulk rounds
engine, plus the min-node-add CAPACITY PLAN at the same scale (the second
half of the BASELINE.json metric). Smaller continuity points (the r01
20k x 100k soft mix and a hard-constraint mix riding the domain-quota
rounds) are timed alongside on stderr, as are the serial-scan rate and a
serial per-pod numpy baseline with the reference's algorithmic shape.

The reference publishes no numbers (BASELINE.md); its cost model is a
strictly serial pod loop doing an O(nodes) filter+score per pod
(`pkg/simulator/simulator.go:219-244`, `core/generic_scheduler.go:271-341`,
`PercentageOfNodesToScore=100`), and its planner re-simulates from scratch
per candidate count (`pkg/apply/apply.go:183`). The baseline below
reproduces the serial loop shape host-side with vectorized numpy per pod —
a *generous* stand-in (numpy's C loops beat the Go plugin chain per node).

Prints ONE JSON line:
  {"metric": "north_star_place_1m_pods_100k_nodes", "value": <warm seconds>,
   "unit": "s",
   "vs_target": 60/value            # the < 60 s BASELINE.json target
   "vs_baseline": <bulk pods/s / serial-baseline pods/s>,
   "cold_s": gen+tensorize+first run,
   "placed": N, "unplaced": N, "unplaced_reasons": {reason: count},
   "plan_s": warm plan search (tensorize+base+probes, unverified),
   "plan_verified_s": warm plan incl. the fresh full-placement verification,
   "plan_cold_s": first-call wall incl. compilation,
   "plan_nodes_added": N,
   "hard_point_s"/"hard_point_rate", "matrix_point_s"/"matrix_point_rate",
   "big_point_s"/"big_point_nodes"/"big_point_placed":   # 400k x 1M, runs
   LAST (docs/memory.md measured row)}
vs_target > 1 means the target is met on this chip alone (the target names
a v5e-8; the sharded engines split the node axis over chips, so single-chip
is the conservative bound).

The cold path additionally splits into `expand_s` / `tensorize_s` /
`compile_s` (AOT pipeline wall) / `compile_serial_s` (summed per-executable
compile seconds — wall < serial shows the parallel-compile overlap) /
`first_dispatch_s`, and warm runs report `fetches` (blocking device→host
round-trips; `matrix_point_fetches` tracks the coalesced stretch-group
fetch floor).

The exact-scan slice is timed twice: `scan_pods_per_s` (the pod-at-a-time
floor) and `scan_wavefront_pods_per_s` (the speculative wavefront
dispatcher, engine/scan.py — bit-identical placements), with
`scan_wavefront_speedup`, the speculation acceptance rate
(`wavefront_accept_rate`) and rollback volume
(`wavefront_rollbacks`/`wavefront_rollback_pods`) alongside.

The fault-injection point (ISSUE 4, simtpu/faults) reports
`fault_scenarios_per_s` (batched sweep), the serial drain/requeue replay
floor, their ratio `fault_sweep_speedup`, and `plan_resilience` counters
from a small N+k survivability search.

Env knobs: SIMTPU_BENCH_NODES (default 100000), SIMTPU_BENCH_PODS (default
1000000), SIMTPU_BENCH_SCAN_PODS (scan-rate slice, default 2000),
SIMTPU_BENCH_BASELINE_PODS (default 300), SIMTPU_BENCH_SMALL=0 /
SIMTPU_BENCH_HARD=0 / SIMTPU_BENCH_MATRIX=0 / SIMTPU_BENCH_PLAN=0 /
SIMTPU_BENCH_BIG=0 to skip the extra points, SIMTPU_BENCH_FAULTS=1/0 to
force/skip the fault-injection point (default: north-star runs only;
`make bench-faults` = the small-shape smoke), SIMTPU_BENCH_PRECOMPILE=0/1
to force the background AOT precompile pipeline off/on (unset = auto: on
for accelerator backends; `make bench-cold` runs a small-shape cold-start
smoke with the persistent cache off), SIMTPU_BENCH_LAYOUT=1/0 to force/skip
the carried-state layout A/B point (`state_bytes` vs `state_bytes_dense`,
SIMTPU_COMPACT A/B, `make bench-layout` = the small-shape asserting smoke),
SIMTPU_BENCH_DURABLE=1/0 to force/skip the durable-execution smoke
(checkpoint→kill→resume bit-identity + injected-OOM backoff A/B, `make
bench-durable` = the asserting smoke; `backoff_events`/`backoff_chunk_min`
ride every run's JSON line), SIMTPU_BENCH_SERVE=1/0 to force/skip the
long-lived service smoke (tools/serve_loadgen.py against a real `simtpu
serve` subprocess; serve_qps/serve_coalesce_ratio/serve_p99_s in the JSON
line; `make bench-serve` = the asserting robustness-matrix smoke with
SIMTPU_BENCH_SERVE_ASSERT=1), SIMTPU_BENCH_TIMELINE=1/0 to force/skip the
trace-driven continuous-time replay point (simtpu/timeline: a multi-day
seeded arrival stream on SIMTPU_BENCH_TIMELINE_NODES, default 20k;
timeline_events_per_s / timeline_pending_p50_s / timeline_preemptions in
the JSON line; `make bench-timeline` = the small-shape smoke asserting
batched == serial-oracle end state with SIMTPU_BENCH_TIMELINE_ASSERT=1).

Byte telemetry rides every run: `fetch_bytes` (device→host payload of one
warm placement, next to the `fetches` round-trip count),
`engine_state_bytes` (the carried scheduling state under the active
layout, per-plane gauge via the registry's `state.*` gauges), and
`device_peak_bytes` (accelerator memory_stats high-water; None on CPU).

`bench.py --multihost` is the separate MULTIHOST bench point: a fresh
subprocess (8 forced host devices by default,
SIMTPU_BENCH_MULTIHOST_DEVICES / _FORCE_HOST=0 for real TPU/GPU meshes)
places the north-star mix through the GSPMD ShardedRoundsEngine with the
node axis sharded over the mesh. It is ONE process over that mesh — the
same computation tests/test_multihost.py pins bit-identical when the
8-device mesh spans two real jax.distributed processes, but the walls
include no cross-process DCN overhead (the record says so:
`processes`) — and it emits the `multihost_place_*` record
(`value`, `trajectory` = expand_tensorize_s / place_cold_s / optional
place_warm_s when SIMTPU_BENCH_MULTIHOST_RUNS > 1 / end_to_end_s, full
registry snapshot). `--record-out FILE` saves the raw record (the
committed MULTIHOST_r*.json provenance artifacts); `--publish` /
`bench.py --publish-multihost RECORD.json` write BASELINE.json's
`published` block through publish_multihost() — the only writer, which
recomputes every derived field (vs_target = round(60/value, 2)) so the
published number is always reproducible from a committed measured record.
`make bench-multihost` is the small-shape asserting smoke.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def note(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


def _bench_precompile() -> bool:
    """Whether to AOT-precompile the cold run's executables on a background
    pool (engine/precompile.py).  SIMTPU_BENCH_PRECOMPILE=0/1 forces;
    unset = auto, on for accelerator backends only — on CPU the compiles
    contend with the placement compute for the same host cores (the same
    gating `simtpu apply` auto applies)."""
    env = os.environ.get("SIMTPU_BENCH_PRECOMPILE")
    if env is not None:
        return env != "0"
    import jax

    return jax.default_backend() != "cpu"


def build_problem(n_nodes: int, n_pods: int, mix: str = "north", with_state: bool = True):
    from simtpu.core.tensorize import Tensorizer
    from simtpu.core.objects import set_label
    from simtpu import constants as C
    from simtpu.engine.scan import build_pod_arrays, statics_from
    from simtpu.engine.state import build_state
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    t0 = time.perf_counter()
    note(f"generating {n_nodes} nodes x {n_pods} pods (mix={mix})")
    # the north-star constraint mix: zone spread constraints, preferred
    # inter-pod anti-affinity, node selectors/tolerations, and Open-Local
    # storage demand against storage-annotated nodes. The "hard" variant
    # makes half the spread constraints DoNotSchedule and a third of the
    # anti-affinity REQUIRED, exercising the domain-quota rounds. The
    # "matrix" variant loads the mixes that fell to the serial scan before
    # round 4 — multi-GPU shares, multi-claim LVM, preset-free GPU pools,
    # required colocate-with-self — through the matrix/self-aff rounds.
    hard = mix == "hard"
    matrix = mix == "matrix"
    cluster = synth_cluster(
        n_nodes, seed=3, zones=16, taint_frac=0.1,
        storage_frac=0.3, gpu_frac=0.4 if matrix else 0.0,
    )
    apps = synth_apps(
        n_pods,
        seed=4,
        zones=16,
        # 1000-replica deployments: realistic shape for a 1M-pod app list,
        # and the [T, N] topology-count planes scale with the number of
        # groups — ~2.5 terms/group keeps state within single-chip HBM at
        # 100k nodes (int(os.environ.get(...)) for experiments)
        pods_per_deployment=int(os.environ.get("SIMTPU_BENCH_PODS_PER_DEP", 1000)),
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.2,
        anti_affinity_hard_frac=0.34 if hard else 0.0,
        spread_frac=0.3,
        spread_hard_frac=0.5 if hard else 0.0,
        gpu_frac=0.25 if matrix else 0.0,
        gpu_multi_frac=0.6 if matrix else 0.0,
        storage_frac=0.25 if matrix else 0.2,
        storage_device_frac=0.0 if matrix else 0.3,
        lvm_multi_frac=0.6 if matrix else 0.0,
        affinity_frac=0.15 if matrix else 0.0,
    )
    pods = []
    for app in apps:
        expanded = get_valid_pods_exclude_daemonset(app.resource)
        for pod in expanded:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        pods.extend(expanded)
    gen_s = time.perf_counter() - t0
    note(f"generated in {gen_s:.1f}s; tensorizing")

    t0 = time.perf_counter()
    tensorizer = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    batch = tensorizer.add_pods(pods)
    tensors = tensorizer.freeze()
    tensorize_s = time.perf_counter() - t0
    note(f"tensorized in {tensorize_s:.1f}s")

    if not with_state:
        # big_point needs only (tensors, batch): the rounds engine builds
        # its own state, and a discarded build_state at 400k nodes would
        # transiently allocate multi-GB device buffers at the HBM edge
        return tensors, batch

    statics = statics_from(tensors)
    r = tensors.alloc.shape[1]
    req, pod_arrays = build_pod_arrays(batch, r)
    state = build_state(
        tensors,
        np.zeros(0, np.int32),
        np.zeros(0, np.int32),
        np.zeros((0, r), np.float32),
        None,
    )
    return tensors, batch, statics, state, pod_arrays, req, gen_s, tensorize_s


def time_engine(
    statics, state, pod_arrays, flags=None, tensors=None, groups=None,
    speculate=False,
):
    """(seconds, placed_nodes) for one full placement scan (compiled,
    post-warmup) through the engine's chunked + term-row-sliced dispatch
    (run_scan_chunked) — the path `Engine.place` actually uses for
    serial-only shapes.  `speculate` routes eligible same-group runs
    through the speculative wavefront dispatcher (bit-identical
    placements; the A/B behind `scan_wavefront_pods_per_s`).

    Timing runs to full host materialization of the placement vector:
    `block_until_ready` alone under-reports on tunneled TPU backends (it can
    return before the executable finishes), so the device→host copy is the
    only trustworthy completion barrier (run_scan_chunked's outputs are
    host arrays already).
    """
    import jax
    import jax.numpy as jnp

    from simtpu.engine.scan import StepFlags, default_wave_call, run_scan_chunked

    step_flags = flags if flags is not None else StepFlags()

    def run(st):
        _, outs = run_scan_chunked(
            statics, st, pod_arrays, step_flags, tensors, groups,
            wave_call=default_wave_call if speculate else None,
        )
        return outs[0]

    # run_scan_chunked's dispatches donate the state, so each run gets its
    # own copy (made OUTSIDE the timed region)
    run(jax.tree.map(jnp.copy, state))  # compile + warm
    fresh = jax.tree.map(jnp.copy, state)
    jax.block_until_ready(fresh)
    t0 = time.perf_counter()
    placed_nodes = run(fresh)
    return time.perf_counter() - t0, placed_nodes


def time_serial_baseline(tensors, batch, req, limit: int) -> float:
    """Reference-shaped serial loop: per pod, filter+score every node, argmax,
    update. Returns seconds-per-pod."""
    free = tensors.alloc.astype(np.float64).copy()
    alloc = tensors.alloc.astype(np.float64)
    static_mask = tensors.static_mask
    n_pods = min(limit, len(batch.group))
    t0 = time.perf_counter()
    for i in range(n_pods):
        g = int(batch.group[i])
        r = req[i].astype(np.float64)
        mask = static_mask[g] & np.all(free >= r, axis=1)
        if not mask.any():
            continue
        with np.errstate(divide="ignore", invalid="ignore"):
            frac = np.where(alloc > 0, (free - r) / alloc, 0.0)
        least = frac.mean(axis=1) * 100.0  # NodeResourcesLeastAllocated
        balance = (1.0 - np.abs(frac[:, 0] - frac[:, 1])) * 100.0
        post = np.where(alloc > 0, (alloc - free + r) / alloc, 0.0)
        dominant = post.max(axis=1)  # Simon dominant-share score
        score = least + balance + (1.0 - dominant) * 100.0
        score[~mask] = -np.inf
        chosen = int(np.argmax(score))
        free[chosen] -= r
    return (time.perf_counter() - t0) / max(n_pods, 1)


class _FrozenTensorizer:
    """Engine-constructor shim for an already-frozen tensor set: the
    engines only ever call `.freeze()` on the tensorizer they are given."""

    def __init__(self, tensors):
        self._tensors = tensors

    def freeze(self):
        return self._tensors


def time_bulk(tensors, batch, precompile: bool = False):
    """Seconds for a full bulk (rounds-engine) placement of the batch: the
    best of two fresh-engine runs, so the reported rate is the steady state a
    capacity-planning sweep sees after the first jit compilation. Also
    returns the first (cold) run's wall-clock, the reason codes, and an
    `extra` dict: the cold breakdown (`first_dispatch_s` = the first
    place() wall, `compile_s`/`compile_serial_s` = the AOT pipeline's
    wall/summed compile seconds when `precompile` is on — wall < serial is
    the parallel-compile overlap) and the final run's blocking-fetch count
    (`fetches`, one per device→host round-trip)."""
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.obs.metrics import REGISTRY

    nodes = reasons = None
    best, cold = float("inf"), None
    extra = {}
    pipe = None
    for i in range(2):
        eng = RoundsEngine(_FrozenTensorizer(tensors))
        t0 = time.perf_counter()
        if precompile and i == 0:
            from simtpu.engine.precompile import precompile_place

            pipe = precompile_place(eng, batch)
        elif pipe is not None:
            # warm runs share the registry the way the planner's probe and
            # verify engines do — an AOT executable does not warm the jit
            # path's own cache, so a pipeline-less rerun would recompile
            eng.pipeline = pipe
        t_dispatch = time.perf_counter()
        f0 = REGISTRY.snapshot("fetch.")
        nodes, reasons, _ = eng.place(batch)
        run_s = time.perf_counter() - t0
        f1 = REGISTRY.snapshot("fetch.")
        extra["fetches"] = f1["fetch.get"] - f0["fetch.get"]
        extra["fetch_bytes"] = f1["fetch.bytes"] - f0["fetch.bytes"]
        note(f"bulk run {i}: {run_s:.1f}s")
        if cold is None:
            cold = run_s
            extra["first_dispatch_s"] = round(
                time.perf_counter() - t_dispatch, 2
            )
            if pipe is not None:
                pipe.wait_all()
                s = pipe.stats()
                extra["compile_s"] = round(s["compile_wall_s"], 2)
                extra["compile_serial_s"] = round(s["compile_serial_s"], 2)
                note(
                    f"precompile: {s['submitted']} executables, wall "
                    f"{s['compile_wall_s']:.1f}s vs serial "
                    f"{s['compile_serial_s']:.1f}s, hits {s['hits']} "
                    f"misses {s['misses']} failures {s['failures']}"
                )
        best = min(best, run_s)
    if pipe is not None:
        pipe.shutdown()
    return best, cold, nodes, reasons, extra


def reason_histogram(nodes, reasons) -> dict:
    """Every unplaced pod accounted for by failure class (the reference's
    per-pod taxonomy, `pkg/simulator/simulator.go:232-241`)."""
    from collections import Counter

    from simtpu.engine.scan import REASON_TEXT

    failed = np.asarray(nodes) < 0
    hist = Counter(int(r) for r in np.asarray(reasons)[failed])
    return {
        REASON_TEXT.get(code, str(code)): cnt for code, cnt in hist.most_common()
    }


def big_point() -> dict:
    """The beyond-headline scale point (docs/memory.md measured row): 400k
    nodes x 1M pods on one chip — fits only because constant [G, N] planes
    collapse to [1, N] rows (statics_from).  Runs in its own frame and
    LAST, so the GB-scale tensors (and the device statics memoized on
    them) are unreachable while the headline points run."""
    tensors, batch = build_problem(400_000, 1_000_000, with_state=False)
    wall, _, nodes, reasons, _ = time_bulk(tensors, batch)
    placed = int((nodes >= 0).sum())
    total = len(batch.group)
    note(
        f"big-point nodes=400000 pods={total} bulk-wall={wall:.2f}s "
        f"rate={total / wall:.0f} pods/s placed={placed}"
    )
    for reason, cnt in reason_histogram(nodes, reasons).items():
        note(f"  {cnt:8d}  {reason}")
    return {
        "big_point_s": round(wall, 2),
        "big_point_nodes": 400_000,
        "big_point_placed": placed,
    }


def device_peak_bytes():
    """Accelerator peak-memory high-water (jax memory_stats), None on
    backends that publish none (CPU) — the on-device half of the byte
    telemetry next to `state_bytes` and `fetch_bytes`.  Sampled onto the
    metrics registry (`device.peak_bytes` gauge, ISSUE 8) so the
    registry snapshot every BENCH point records carries it too."""
    import jax

    from simtpu.obs.metrics import REGISTRY

    try:
        stats = jax.devices()[0].memory_stats()
    except (RuntimeError, AttributeError):
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    if peak is not None:
        REGISTRY.gauge("device.peak_bytes").set(int(peak))
    return peak


def layout_point() -> dict:
    """Carried-state layout A/B (ISSUE 5): the same multi-domain synthetic
    problem placed twice through the rounds engine — once carrying the
    domain-tabular CompactState between dispatches, once carrying dense
    SchedState — pinning bit-identical placements and reporting the carried
    byte reduction (`state_bytes` vs `state_bytes_dense`) plus the warm
    placement walls for the throughput-no-worse check.  Zones x racks plus
    zone spread/anti-affinity make most topology keys small-domain (the
    representative 'multi-domain' shape); hostname selector-spread rows
    stay dense by design.  Env: SIMTPU_BENCH_LAYOUT_NODES (default 20000) /
    SIMTPU_BENCH_LAYOUT_PODS (default 100000);
    SIMTPU_BENCH_LAYOUT_ASSERT=1 (the `make bench-layout` smoke) fails the
    run unless the carry shrank >= 2x."""
    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.obs.metrics import REGISTRY
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    n_nodes = int(os.environ.get("SIMTPU_BENCH_LAYOUT_NODES", 20_000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_LAYOUT_PODS", 100_000))
    note(f"layout point: {n_nodes} nodes x {n_pods} pods, compact-carry A/B")
    cluster = synth_cluster(
        n_nodes, seed=21, zones=16, racks_per_zone=8, taint_frac=0.1
    )
    # domain-keyed constraint mix: zone spread + zone affinity on most
    # groups, NO hostname anti-affinity — hostname rows (SelectorSpread's
    # per-host term) are unique-per-node and stay dense by design, so this
    # measures the tabular win on the rows that can compress
    apps = synth_apps(
        n_pods, seed=22, zones=16, pods_per_deployment=500,
        selector_frac=0.2, toleration_frac=0.1, anti_affinity_frac=0.0,
        spread_frac=0.8, affinity_frac=0.5,
    )
    pods = []
    for app in apps:
        pods.extend(get_valid_pods_exclude_daemonset(app.resource))

    def run(compact: bool):
        """(warm wall, nodes, gauge) — best of two fresh-engine runs, the
        same steady-state protocol as time_bulk."""
        best, nodes, gauge = float("inf"), None, None
        for _ in range(2):
            tz = Tensorizer(
                cluster.nodes, storage_classes=cluster.storage_classes
            )
            eng = RoundsEngine(tz)
            eng.compact = compact
            batch = tz.add_pods(pods)
            t0 = time.perf_counter()
            nodes, _, _ = eng.place(batch)
            best = min(best, time.perf_counter() - t0)
            # registry-backed carried-state gauge (obs/metrics.py)
            gauge = {
                k.split(".", 1)[1]: v
                for k, v in REGISTRY.snapshot("state.").items()
            }
        return best, nodes, gauge

    compact_s, compact_nodes, g = run(True)
    dense_s, dense_nodes, _ = run(False)
    if not np.array_equal(compact_nodes, dense_nodes):
        note("WARNING: compact-carry placements diverged from dense")
    ratio = g["dense_bytes"] / max(g["carried_bytes"], 1)
    note(
        f"layout: carried {g['carried_bytes']} B compact vs "
        f"{g['dense_bytes']} B dense ({ratio:.2f}x); warm wall "
        f"{compact_s:.2f}s compact vs {dense_s:.2f}s dense"
    )
    top = sorted(g["planes"].items(), key=lambda kv: -kv[1])[:4]
    note("layout: largest carried planes: " + ", ".join(
        f"{name}={b}" for name, b in top
    ))
    if os.environ.get("SIMTPU_BENCH_LAYOUT_ASSERT", "0") == "1":
        assert np.array_equal(compact_nodes, dense_nodes), (
            "compact-carry placements must be bit-identical to dense"
        )
        assert ratio >= 2.0, (
            f"carried-state bytes shrank only {ratio:.2f}x (< 2x) on the "
            "multi-domain synthetic cluster"
        )
    return {
        "layout_nodes": n_nodes,
        "state_bytes": g["carried_bytes"],
        "state_bytes_dense": g["dense_bytes"],
        "state_compact_ratio": round(ratio, 2),
        "layout_compact_s": round(compact_s, 2),
        "layout_dense_s": round(dense_s, 2),
    }


def serve_point() -> dict:
    """Long-lived service smoke (ISSUE 14, docs/serving.md): drive
    tools/serve_loadgen.py against a real `simtpu serve` subprocess —
    seeded mixed burst (coalescible sweep queries, one over-deadline, one
    malformed, overload tail past the admission queue), reading the
    daemon's own serve.* registry counters.  serve_qps /
    serve_coalesce_ratio / serve_p99_s land in the JSON line.  With
    SIMTPU_BENCH_SERVE_ASSERT=1 (`make bench-serve`) the loadgen runs
    --smoke and this point FAILS unless the whole robustness matrix held:
    structured 504s, 429s with Retry-After and unharmed admitted work,
    kill -9 + restart bit-identical session recovery, SIGTERM drain to
    exit 0, and a coalesce ratio above zero."""
    import subprocess
    import sys as _sys

    assert_on = os.environ.get("SIMTPU_BENCH_SERVE_ASSERT", "0") == "1"
    # cwd-independent, like the multihost point: the loadgen lives next
    # to this file, and the example config's inner paths resolve against
    # the repo root, so the subprocess runs THERE whatever cwd bench got
    repo = os.path.dirname(os.path.abspath(__file__))
    args = [
        _sys.executable,
        os.path.join(repo, "tools", "serve_loadgen.py"),
        "--json",
    ]
    if assert_on:
        args.append("--smoke")
    # sustained open-loop fit-query arrival sweep (warm-engine serving):
    # p50/p99 under a fixed arrival rate + the zero-retensorize assertion
    arrival = os.environ.get("SIMTPU_BENCH_SERVE_ARRIVAL", "4,12")
    if arrival:
        args += ["--arrival-sweep", arrival]
    burst = os.environ.get("SIMTPU_BENCH_SERVE_BURST", "")
    if burst:
        args += ["--burst", burst]
    # timeout comfortably inside the CI tier budget: a wedged daemon must
    # become a recorded serve_error in the JSON line, not a killed job
    out = subprocess.run(
        args, capture_output=True, text=True, timeout=600, cwd=repo
    )
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.startswith("{")]
    if not lines:
        raise RuntimeError(
            f"serve loadgen produced no JSON (rc={out.returncode}): "
            f"{out.stderr[-400:]}"
        )
    doc = json.loads(lines[-1])
    rec = {
        k: doc[k]
        for k in (
            "serve_qps", "serve_p50_s", "serve_p99_s",
            "serve_coalesce_ratio", "serve_requests", "serve_coalesced",
            "serve_sweeps", "serve_shed", "serve_timeouts",
            "serve_fit_p50_s", "serve_fit_p99_s",
            "serve_warm_fits", "serve_warm_fallbacks",
        )
        if k in doc
    }
    rec["serve_ok"] = bool(doc.get("ok"))
    if assert_on:
        assert out.returncode == 0 and doc.get("ok"), (
            f"serve smoke failed: {doc.get('checks')}"
        )
        assert rec["serve_coalesce_ratio"] > 0, rec
        assert rec["serve_sweeps"] < rec["serve_requests"], rec
        if rec.get("serve_warm_fits", 0) > 0:
            # warm-engine acceptance: a repeating fit mix must never
            # fall back to a re-tensorize
            assert rec.get("serve_warm_fallbacks", 0) == 0, rec
    return rec


def obs_point() -> dict:
    """Observability overhead gate (ISSUE 8, docs/observability.md): the
    same warm bulk placement timed three ways — tracer disabled (the
    no-op baseline), tracer armed (ring-buffer spans recording), and a
    no-op sanity check that disabled spans record nothing and allocate
    no span objects.  The tracing-on wall must stay within 3% of the
    baseline (`SIMTPU_BENCH_OBS_ASSERT=1`, the `make bench-obs` smoke,
    fails the run otherwise), and the exported Chrome trace must be
    Perfetto-valid JSON (traceEvents with name/ph/ts/pid/tid on every
    entry).  Env: SIMTPU_BENCH_OBS_NODES / SIMTPU_BENCH_OBS_PODS
    (default 2000 x 20000 — big enough that per-dispatch work dominates
    the span bookkeeping, the regime the <3% bound is about)."""
    import tempfile

    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.obs import trace as obs_trace
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    n_nodes = int(os.environ.get("SIMTPU_BENCH_OBS_NODES", 2_000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_OBS_PODS", 20_000))
    note(f"obs point: {n_nodes} nodes x {n_pods} pods, tracing on/off A/B")
    cluster = synth_cluster(n_nodes, seed=31, zones=8, taint_frac=0.1)
    apps = synth_apps(
        n_pods, seed=32, zones=8, pods_per_deployment=200,
        selector_frac=0.2, anti_affinity_frac=0.1, spread_frac=0.3,
    )
    pods = []
    for app in apps:
        pods.extend(get_valid_pods_exclude_daemonset(app.resource))

    was_enabled = obs_trace.enabled()

    def run(tracing: bool):
        """Best-of-3 warm walls under the given tracer state (fresh
        engine per run, the steady-state protocol every smoke uses)."""
        if tracing:
            obs_trace.enable()
        else:
            obs_trace.disable()
        best, nodes = float("inf"), None
        for _ in range(3):
            tz = Tensorizer(
                cluster.nodes, storage_classes=cluster.storage_classes
            )
            eng = RoundsEngine(tz)
            batch = tz.add_pods(pods)
            t0 = time.perf_counter()
            nodes, _, _ = eng.place(batch)
            best = min(best, time.perf_counter() - t0)
        return best, nodes

    # no-op contract first: with the tracer off, span() returns ONE
    # shared singleton (no per-span object) and records nothing
    obs_trace.disable()
    assert obs_trace.span("a") is obs_trace.span("b"), (
        "disabled span() must return the shared no-op singleton"
    )
    with obs_trace.span("noop", pods=1):
        pass
    assert obs_trace.events() == [], "disabled tracer recorded an event"

    # one untimed warmup first: the A/B must compare steady-state walls,
    # not charge the off-series with the first-run XLA compiles
    run(False)
    off_s, off_nodes = run(False)
    on_s, on_nodes = run(True)
    span_count = len(obs_trace.events())
    overhead = on_s / max(off_s, 1e-9) - 1.0
    note(
        f"obs: warm wall {off_s:.2f}s off vs {on_s:.2f}s on "
        f"({overhead * 100:+.2f}%), {span_count} spans buffered"
    )

    # trace-file validation: exported JSON must be loadable and carry the
    # Chrome trace-event required keys on every entry
    with tempfile.TemporaryDirectory() as td:
        path = obs_trace.export_trace(os.path.join(td, "bench-obs.json"))
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert events, "exported trace has no events"
        for ev in events:
            for key in ("name", "ph", "pid", "tid"):
                assert key in ev, f"trace event missing {key!r}: {ev}"
            if ev["ph"] == "X":
                assert "ts" in ev and "dur" in ev, ev
        trace_valid = True
    identical = bool(np.array_equal(np.asarray(off_nodes), np.asarray(on_nodes)))
    if not identical:
        note("WARNING: placements diverged under tracing (must be impossible)")
    if not was_enabled:
        obs_trace.disable()
    if os.environ.get("SIMTPU_BENCH_OBS_ASSERT", "0") == "1":
        assert identical, "tracing changed placements"
        assert span_count > 0, "tracing-on run recorded no spans"
        assert overhead < 0.03, (
            f"span tracing added {overhead * 100:.2f}% to the warm wall "
            "(>= 3% bound, docs/observability.md)"
        )
    return {
        "obs_nodes": n_nodes,
        "obs_off_s": round(off_s, 3),
        "obs_on_s": round(on_s, 3),
        "obs_overhead_pct": round(overhead * 100, 2),
        "obs_spans": span_count,
        "obs_trace_valid": trace_valid,
        "obs_identical": identical,
    }


def explain_point() -> dict:
    """Decision-observability smoke (ISSUE 13, simtpu/explain): one
    fuzz-generated gnarly case (the audit fuzzer's generator) made
    partially infeasible, placed twice — plain, and with the full explain
    pipeline (failure breakdown + bottleneck + capped score attribution)
    after it.  Asserts (`SIMTPU_BENCH_EXPLAIN_ASSERT=1`, the `make
    bench-explain` smoke): placements bit-identical with explain on/off
    (explanation never perturbs the engine), every unplaced pod's
    per-stage elimination counts (+ feasible survivors) sum to N and
    match the pure-numpy twin, and the explain wall stays bounded
    relative to the placement wall (the off path is separately pinned to
    ZERO extra dispatches by tests/test_explain.py).  JSON keys:
    explain_s / explain_pods / explain_groups."""
    from simtpu.audit.fuzz import gen_case
    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.scan import Engine
    from simtpu.explain import (
        attribute_scores,
        bottleneck_analysis,
        explain_failures,
        extras_from_log,
    )
    from simtpu.synth import make_deployment
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    n_nodes = int(os.environ.get("SIMTPU_BENCH_EXPLAIN_NODES", 200))
    n_pods = int(os.environ.get("SIMTPU_BENCH_EXPLAIN_PODS", 1_200))
    note(f"explain point: gnarly {n_nodes} nodes x {n_pods} pods")
    cluster, apps, _mix = gen_case(seed=13, n_nodes=n_nodes, n_pods=n_pods)
    # strand pods on two axes: a deployment no node can hold (resources)
    # rides on top of whatever hard anti-affinity/spread pressure the
    # drawn mix already creates
    apps[0].resource.deployments.append(
        make_deployment("bench-fat", 4, 50_000_000, 16)
    )
    pods = []
    for app in apps:
        pods.extend(get_valid_pods_exclude_daemonset(app.resource))

    def place():
        # the SERIAL-equivalent engine: score attribution's prefix-state
        # exactness (argmax == recorded node) is a serial-scan contract —
        # the bulk rounds engine deliberately tie-breaks differently
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        eng = Engine(tz)
        batch = tz.add_pods(pods)
        t0 = time.perf_counter()
        nodes, reasons, extras = eng.place(batch)
        nodes = np.asarray(nodes)
        return tz, eng, batch, nodes, np.asarray(reasons), extras, (
            time.perf_counter() - t0
        )

    place()  # untimed warmup (compiles)
    _, _, _, nodes_a, _, _, _ = place()
    tz, eng, batch, nodes_b, reasons, extras, place_s = place()
    identical = bool(np.array_equal(nodes_a, nodes_b))
    tensors = tz.freeze()
    unplaced = np.flatnonzero(nodes_b < 0)
    state = eng.carried_state()

    def run_explain():
        t0 = time.perf_counter()
        bd = explain_failures(tensors, batch, unplaced, state, reasons=reasons)
        bn = bottleneck_analysis(
            tensors, batch, nodes_b, reasons, rows=unplaced,
            free=np.asarray(state.free),
        )
        scores = attribute_scores(
            tensors, batch, nodes_b,
            extras_from_log(tensors, nodes_b, eng.ext_log), max_pods=4,
        )
        return bd, bn, scores, time.perf_counter() - t0

    # cold first (traces the pow2-chunk + per-pod executables), then the
    # warm steady-state wall the overhead bound is about
    _, _, _, explain_cold_s = run_explain()
    bd, bn, scores, explain_s = run_explain()
    # the on/off identity must compare a placement AFTER the explain
    # pipeline ran against one before it — comparing two pre-explain
    # placements would pass even if explaining polluted shared state
    _, _, _, nodes_c, _, _, _ = place()
    identical = identical and bool(np.array_equal(nodes_b, nodes_c))

    n_valid = bd.n_nodes
    sums = bd.counts.sum(axis=1) + bd.feasible
    sum_ok = bool(np.all(sums == n_valid))
    prev = os.environ.get("SIMTPU_EXPLAIN_JIT")
    os.environ["SIMTPU_EXPLAIN_JIT"] = "0"
    try:
        twin = explain_failures(tensors, batch, unplaced, state, reasons=reasons)
    finally:
        if prev is None:
            os.environ.pop("SIMTPU_EXPLAIN_JIT", None)
        else:
            os.environ["SIMTPU_EXPLAIN_JIT"] = prev
    twin_ok = bool(
        np.array_equal(bd.counts, twin.counts)
        and np.array_equal(bd.feasible, twin.feasible)
        and np.array_equal(bd.fail_code, twin.fail_code)
    )
    groups = bd.to_doc().get("groups", [])
    note(
        f"explain: {len(unplaced)} unplaced pods in {explain_s:.3f}s warm "
        f"({explain_cold_s:.2f}s cold, placement {place_s:.2f}s), "
        f"{len(groups)} failure shape(s), sum-to-N={sum_ok} twin={twin_ok} "
        f"identical={identical}, {len(scores)} pods score-attributed"
    )
    if os.environ.get("SIMTPU_BENCH_EXPLAIN_ASSERT", "0") == "1":
        assert identical, "an explain run changed placements"
        assert len(unplaced) > 0, "the gnarly case must strand pods"
        assert sum_ok, f"per-stage counts do not sum to N: {sums[:8]} vs {n_valid}"
        assert twin_ok, "jit pass diverged from the pure-numpy twin"
        assert bn.get("binding"), "bottleneck found no binding resource"
        assert all(s["consistent"] for s in scores), (
            "score attribution argmax diverged from the recorded node"
        )
        # overhead bound: explaining every unplaced pod must stay well
        # under the placement it explains (one vmapped pass per 64 pods)
        assert explain_s < 0.5 * place_s + 1.0, (
            f"explain pass took {explain_s:.2f}s against a {place_s:.2f}s "
            "placement — over the overhead bound"
        )
    return {
        "explain_nodes": n_nodes,
        "explain_s": round(explain_s, 3),
        "explain_cold_s": round(explain_cold_s, 3),
        "explain_pods": int(len(unplaced)),
        "explain_groups": len(groups),
        "explain_sum_ok": sum_ok,
        "explain_twin_ok": twin_ok,
        "explain_identical": identical,
        "explain_scored": len(scores),
    }


def audit_point() -> dict:
    """Trust-but-verify smoke (ISSUE 7, docs/robustness.md): (1)
    mutation-kill — corrupt accepted placements across every corruption
    class (invalid node, overcommit, affinity/anti-affinity/spread
    breaks, port conflicts, illegal evictions) and count auditor
    detections (the contract is 100%); (2) audit overhead — a small
    incremental plan with the auditor auto-on, recording the audit wall
    against the total plan wall (the < 10% acceptance bound).  `make
    bench-audit` runs this alone with SIMTPU_BENCH_AUDIT_ASSERT=1, which
    fails the run on a missed mutation, a dirty audit, or overhead
    beyond the bound."""
    from simtpu.audit.fuzz import run_mutation_kill
    from simtpu.plan.incremental import plan_capacity_incremental
    from simtpu.synth import synth_apps, synth_cluster

    out = {}
    note("audit point: mutation-kill over every corruption class")
    mk = run_mutation_kill(seed=0, per_class=3, n_nodes=16, progress=note)
    out["audit_mutation_classes"] = mk["classes"]
    out["audit_mutations_tried"] = mk["tried"]
    out["audit_mutations_killed"] = mk["killed"]
    out["audit_kill_rate"] = round(mk["kill_rate"], 4)

    n_nodes = int(os.environ.get("SIMTPU_BENCH_AUDIT_NODES", 500))
    n_pods = int(os.environ.get("SIMTPU_BENCH_AUDIT_PODS", 4000))
    note(f"audit point: plan overhead at {n_nodes} nodes / {n_pods} pods")
    cluster = synth_cluster(n_nodes, seed=3, zones=4, taint_frac=0.1)
    apps = synth_apps(
        n_pods, seed=5, zones=4, pods_per_deployment=200,
        anti_affinity_frac=0.2, spread_frac=0.3,
    )
    # cold/warm pair (the time_plan pattern): the cold run pays the
    # audit's one trace+compile (a fixed ~0.5s bench-cold already
    # accounts for in its own lane); the WARM fraction is the
    # steady-state overhead the <10% acceptance bound means — at the
    # standard (north-star) bench point the compile is noise against a
    # minutes-long plan, but at this smoke shape it would dominate
    plan = None
    for label in ("cold", "warm"):
        t0 = time.perf_counter()
        plan = plan_capacity_incremental(
            cluster, apps, cluster.nodes[0], max_new_nodes=32,
            materialize=False,
        )
        wall = time.perf_counter() - t0
        audit_s = float((plan.audit or {}).get("wall_s", 0.0))
        frac = audit_s / wall if wall else 0.0
        out[f"audit_{label}_s" if label == "cold" else "audit_s"] = round(
            audit_s, 3
        )
        note(
            f"audit point ({label}): audit_s={audit_s:.3f} "
            f"plan_wall={wall:.2f}s overhead={frac:.1%}"
        )
    out["audit_violations"] = int((plan.audit or {}).get("violations", -1))
    out["audit_overhead_frac"] = round(frac, 4)
    note(f"audit point: kill={mk['killed']}/{mk['tried']}")
    if os.environ.get("SIMTPU_BENCH_AUDIT_ASSERT", "0") == "1":
        assert (
            mk["kill_rate"] >= 1.0
            and mk["classes"] == mk["classes_total"]
            and not mk["missed"]
        ), (
            f"auditor missed seeded corruptions: {mk['by_class']} "
            f"(missed {mk['missed']})"
        )
        assert plan.audit and plan.audit.get("ok"), (
            f"plan audit must be clean on the bench point: {plan.audit}"
        )
        assert frac < 0.10, (
            f"warm audit overhead {frac:.1%} >= 10% of plan wall "
            f"({audit_s:.3f}s / {wall:.2f}s)"
        )
    return out


def solve_point() -> dict:
    """Global-solver backend smoke (ISSUE 19, docs/solver.md): time the
    exact doubling+bisection capacity search against one solver consult
    (`plan_capacity(..., solver=True)`) on a solver-eligible mix —
    uniform pod shapes whose request vectors divide every node capacity,
    ordered big-first so the heuristic scheduler packs optimally and the
    certified LP minimum EQUALS the exact search's answer (on
    ratio-mismatched mixes the solver legitimately beats the heuristic;
    docs/solver.md's when-it-loses table owns that story).  `make
    bench-solve` runs this alone with SIMTPU_BENCH_SOLVE_ASSERT=1, which
    fails the run unless both backends agree, both audits are clean, and
    the solver's answer was accepted (accept rate > 0)."""
    from simtpu import AppResource, ResourceTypes
    from simtpu.obs.metrics import REGISTRY
    from simtpu.plan.capacity import plan_capacity
    from simtpu.synth import make_deployment, make_node, synth_cluster

    n_nodes = int(os.environ.get("SIMTPU_BENCH_SOLVE_NODES", 2000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_SOLVE_PODS", n_nodes * 60))
    max_new = int(
        os.environ.get("SIMTPU_BENCH_SOLVE_MAX_NEW", max(2 * n_nodes, 64))
    )

    def mk_cluster():
        return synth_cluster(n_nodes, seed=7, zones=4, taint_frac=0.0)

    def mk_apps():
        # solver-eligible by construction: no storage/GPU demand, no
        # anti-affinity/spread; two nested pod shapes (2:1) that divide
        # every synth node capacity, largest first (first-fit-decreasing)
        res = ResourceTypes()
        per = max(n_pods // 40, 1)
        d = 0
        for cpu, mem in ((2000, 8192), (1000, 4096)):
            for _ in range(20):
                res.deployments.append(
                    make_deployment(f"solve-dep-{d}", per, cpu, mem)
                )
                d += 1
        return [AppResource(name="solve-bench", resource=res)]

    template = make_node("solve-template", 32000, 128)
    note(
        f"solve point: {n_nodes} nodes / ~{n_pods} pods, "
        f"max_new={max_new}"
    )
    t0 = time.perf_counter()
    solved = plan_capacity(
        mk_cluster(), mk_apps(), template, max_new, solver=True
    )
    solve_s = time.perf_counter() - t0
    note(
        f"solve point: solver {'ACCEPTED' if solved.solve.get('status') == 'accepted' else solved.solve.get('status')} "
        f"{solved.nodes_added} node(s) in {solve_s:.2f}s "
        f"(relax+round+audit {solved.solve.get('wall_s', 0.0)}s)"
    )
    t0 = time.perf_counter()
    exact = plan_capacity(
        mk_cluster(), mk_apps(), template, max_new, solver=False
    )
    exact_s = time.perf_counter() - t0
    note(
        f"solve point: exact search {exact.nodes_added} node(s) in "
        f"{exact_s:.2f}s over {len(exact.probes)} probes"
    )

    attempts = REGISTRY.counter("solve.attempts").value
    accepted = REGISTRY.counter("solve.accepted").value
    out = {
        "solve_nodes_added": int(solved.nodes_added),
        "solve_exact_nodes_added": int(exact.nodes_added),
        "solve_status": solved.solve.get("status"),
        "solve_s": round(solve_s, 3),
        "solve_exact_s": round(exact_s, 3),
        "solve_speedup": round(exact_s / max(solve_s, 1e-9), 2),
        "solve_accept_rate": round(accepted / attempts, 4) if attempts else 0.0,
        "solve_consult_s": round(float(solved.solve.get("wall_s", 0.0)), 3),
    }
    note(
        f"solve point: speedup {out['solve_speedup']}x, "
        f"accept rate {out['solve_accept_rate']:.0%}"
    )
    if os.environ.get("SIMTPU_BENCH_SOLVE_ASSERT", "0") == "1":
        assert solved.success and exact.success, (
            f"both backends must succeed: solver={solved.message!r} "
            f"exact={exact.message!r}"
        )
        assert out["solve_accept_rate"] > 0, (
            f"the solver must ACCEPT on the feasible bench mix: "
            f"{solved.solve}"
        )
        assert solved.nodes_added == exact.nodes_added, (
            f"certified answers must agree on the aligned mix: "
            f"solver={solved.nodes_added} exact={exact.nodes_added}"
        )
        assert solved.audit and solved.audit.get("ok"), (
            f"the shipped solver answer must audit clean: {solved.audit}"
        )
        assert exact.audit and exact.audit.get("ok"), (
            f"the exact answer must audit clean: {exact.audit}"
        )
    return out


def durable_point() -> dict:
    """Durable-execution smoke (ISSUE 6, docs/robustness.md): (1) a small
    incremental plan checkpointed, killed mid-search, and resumed — the
    resumed PlanResult must be bit-identical (node count, per-node pod
    names) to the uninterrupted checkpointed run; (2) an injected
    RESOURCE_EXHAUSTED on the bulk dispatcher's first chunk — the
    chunk-halving backoff must converge to bit-identical placements and
    record its events.  `make bench-durable` runs this alone with
    SIMTPU_BENCH_DURABLE_ASSERT=1, which fails the run on any divergence."""
    import shutil
    import tempfile

    from simtpu.core.objects import ResourceTypes
    from simtpu.core.tensorize import Tensorizer
    from simtpu.durable import (
        PlanCheckpoint,
        PlanInterrupted,
        RunControl,
        plan_fingerprint,
    )
    from simtpu.obs.metrics import family as metrics_family

    from simtpu.durable.backoff import BACKOFF_KEYS

    def backoff_counts():
        # registry-backed backoff counters (obs/metrics.py)
        return metrics_family("backoff", BACKOFF_KEYS)
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.plan.incremental import plan_capacity_incremental
    from simtpu.synth import make_node, synth_apps
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    n_pods = int(os.environ.get("SIMTPU_BENCH_DURABLE_PODS", 60))
    note(f"durable point: checkpoint→kill→resume on a {n_pods}-pod plan")
    cluster = ResourceTypes()
    cluster.nodes = [
        make_node(
            f"node-{i}", 8000, 16,
            {"topology.kubernetes.io/zone": f"zone-{i % 2}",
             "kubernetes.io/hostname": f"node-{i}"},
        )
        for i in range(3)
    ]
    apps = synth_apps(
        n_pods, seed=7, zones=2, pods_per_deployment=10,
        anti_affinity_frac=0.2, spread_frac=0.3,
    )
    template = make_node(
        "tmpl", 16000, 64,
        {"kubernetes.io/hostname": "tmpl",
         "topology.kubernetes.io/zone": "zone-0"},
    )
    fp = plan_fingerprint(cluster, apps, template, extra={})

    class _Kill(RunControl):
        """Interrupt after `n` candidate boundaries — the deterministic
        stand-in for a mid-bisection kill."""

        def __init__(self, n):
            super().__init__()
            self.n = n

        def check(self):
            self.n -= 1
            if self.n < 0:
                raise PlanInterrupted("bench kill")
            super().check()

    def placements(plan):
        return {
            s.node["metadata"]["name"]: sorted(
                p["metadata"]["name"] for p in s.pods
            )
            for s in plan.result.node_status
        }

    tmp = tempfile.mkdtemp(prefix="simtpu-durable-")
    try:
        ck_full = PlanCheckpoint(
            os.path.join(tmp, "full"), kind="incremental", fingerprint=fp
        )
        t0 = time.perf_counter()
        full = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30, checkpoint=ck_full
        )
        full_s = time.perf_counter() - t0
        ck_dir = os.path.join(tmp, "killed")
        ck = PlanCheckpoint(ck_dir, kind="incremental", fingerprint=fp)
        part = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30,
            checkpoint=ck, control=_Kill(2),
        )
        ck_r = PlanCheckpoint(
            ck_dir, kind="incremental", fingerprint=fp, resume=True
        )
        t0 = time.perf_counter()
        resumed = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30, checkpoint=ck_r
        )
        resume_s = time.perf_counter() - t0
        resume_ok = (
            part.partial
            and full.success
            and resumed.success
            and resumed.nodes_added == full.nodes_added
            and placements(resumed) == placements(full)
        )
        records = len(ck_full)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    note(
        f"durable: resume bit-identical={resume_ok} "
        f"(full {full_s:.2f}s, resumed {resume_s:.2f}s, "
        f"{records} checkpoint records)"
    )

    # injected-OOM backoff A/B on the bulk dispatcher: first chunk OOMs,
    # the halving replay must land the exact same placements
    pods = []
    for a in apps:
        pods.extend(get_valid_pods_exclude_daemonset(a.resource))

    def place():
        tz = Tensorizer(
            cluster.nodes, storage_classes=cluster.storage_classes
        )
        eng = RoundsEngine(tz)
        nodes, _, _ = eng.place(tz.add_pods(pods))
        return np.asarray(nodes)

    clean = place()
    b0 = backoff_counts()
    real = RoundsEngine._dispatch_bulk_chunk
    hits = [0]

    def fail_first(self, *args, **kwargs):
        hits[0] += 1
        if hits[0] <= 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected bench OOM")
        return real(self, *args, **kwargs)

    RoundsEngine._dispatch_bulk_chunk = fail_first
    try:
        oomed = place()
    finally:
        RoundsEngine._dispatch_bulk_chunk = real
    b1 = backoff_counts()
    backoff_ok = (
        np.array_equal(clean, oomed) and b1["events"] - b0["events"] >= 1
    )
    note(
        f"durable: backoff bit-identical={backoff_ok} "
        f"({b1['events'] - b0['events']} events, "
        f"min chunk {b1['chunk_min']})"
    )
    if os.environ.get("SIMTPU_BENCH_DURABLE_ASSERT", "0") == "1":
        assert resume_ok, (
            "resumed PlanResult diverged from the uninterrupted run"
        )
        assert backoff_ok, (
            "backoff replay diverged or recorded no events"
        )
    return {
        "durable_resume_identical": bool(resume_ok),
        "durable_checkpoint_records": records,
        "durable_full_plan_s": round(full_s, 2),
        "durable_resume_s": round(resume_s, 2),
        "durable_backoff_identical": bool(backoff_ok),
    }


def fault_point() -> dict:
    """Fault-injection sweep point (ISSUE 4 acceptance): an exhaustive
    single-node failure sweep at >= 1k nodes through the batched scenario
    engine (simtpu/faults/sweep.py) against the serial drain/requeue/
    restore replay floor, plus a small N+k `plan_resilience` search.  The
    batched rate is the steady state (second sweep, first compiles); the
    serial floor is timed after a one-scenario warmup for the same reason.
    Env: SIMTPU_BENCH_FAULT_NODES (default 2000), SIMTPU_BENCH_FAULT_PODS
    (default 20000), SIMTPU_BENCH_FAULT_SERIAL (replayed scenarios for the
    floor, default 8)."""
    from simtpu.faults import (
        place_cluster,
        serial_replay,
        single_node_scenarios,
        sweep_scenarios,
    )
    from simtpu.plan.resilience import plan_resilience
    from simtpu.synth import make_node, synth_apps, synth_cluster

    n_nodes = int(os.environ.get("SIMTPU_BENCH_FAULT_NODES", 2000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_FAULT_PODS", 20000))
    serial_n = int(os.environ.get("SIMTPU_BENCH_FAULT_SERIAL", 8))
    note(f"fault point: {n_nodes} nodes x {n_pods} pods, exhaustive k=1 sweep")
    cluster = synth_cluster(n_nodes, seed=11, zones=16, taint_frac=0.1)
    apps = synth_apps(
        n_pods, seed=12, zones=16, pods_per_deployment=200,
        selector_frac=0.1, toleration_frac=0.1, anti_affinity_frac=0.2,
        spread_frac=0.2,
    )
    pc = place_cluster(cluster, apps)
    placed = int((pc.nodes >= 0).sum())
    scen = single_node_scenarios(pc.n_nodes, nodes=cluster.nodes)
    sweep_scenarios(pc, scen)  # compile + warm
    sw = sweep_scenarios(pc, scen)
    batched_rate = sw.timings["scenarios_per_s"]
    # serial floor: drain + requeue + restore per scenario; a first pass
    # over the same scenarios warms the probe-shape executables, then the
    # timed pass replays all 1 + serial_n of them warm
    serial_replay(pc, scen, limit=1 + serial_n)
    t0 = time.perf_counter()
    serial_counts, _ = serial_replay(pc, scen, limit=1 + serial_n)
    serial_rate = (1 + serial_n) / max(time.perf_counter() - t0, 1e-9)
    if not np.array_equal(serial_counts, sw.unplaced[: len(serial_counts)]):
        note("WARNING: fault sweep diverged from the serial replay")
    speedup = batched_rate / max(serial_rate, 1e-9)
    note(
        f"fault sweep: {len(scen)} scenarios, batched "
        f"{batched_rate:.0f}/s vs serial {serial_rate:.1f}/s "
        f"({speedup:.1f}x); survival {sw.survival_rate:.3f}"
    )
    out = {
        "fault_nodes": n_nodes,
        "fault_scenarios": len(scen),
        "fault_scenarios_per_s": round(batched_rate, 1),
        "fault_serial_scenarios_per_s": round(serial_rate, 2),
        "fault_sweep_speedup": round(speedup, 1),
        "fault_survival_rate": round(sw.survival_rate, 4),
    }
    # a small N+k search riding the same machinery: how many template
    # clones until every single-node failure is survivable
    plan_nodes = int(os.environ.get("SIMTPU_BENCH_RESILIENCE_NODES", 400))
    plan_pods = int(os.environ.get("SIMTPU_BENCH_RESILIENCE_PODS", 6000))
    p_cluster = synth_cluster(plan_nodes, seed=13, zones=8, taint_frac=0.0)
    p_apps = synth_apps(
        plan_pods, seed=14, zones=8, pods_per_deployment=100,
        selector_frac=0.0, toleration_frac=0.0, anti_affinity_frac=0.1,
    )
    template = make_node(
        "tmpl", 64000, 256,
        {"kubernetes.io/hostname": "tmpl",
         "topology.kubernetes.io/zone": "zone-plan"},
    )
    t0 = time.perf_counter()
    plan = plan_resilience(
        p_cluster, p_apps, template, k=1, max_new_nodes=32, seed=15
    )
    plan_s = time.perf_counter() - t0
    note(
        f"plan_resilience: nodes_added={plan.nodes_added} "
        f"success={plan.success} wall={plan_s:.1f}s probes={plan.probes}"
    )
    out["plan_resilience_s"] = round(plan_s, 2)
    out["resilience_nodes_added"] = plan.nodes_added
    out["resilience_success"] = plan.success
    if plan.sweep is not None:
        out["resilience_scenarios_per_s"] = round(
            plan.sweep.timings.get("scenarios_per_s", 0.0), 1
        )
    out["fault_placed"] = placed
    return out


def timeline_point() -> dict:
    """Trace-driven continuous-time replay point (ISSUE 15 acceptance):
    a multi-day seeded Alibaba-shaped arrival stream (synth.make_trace —
    Poisson-ish gang arrivals, lognormal durations, CronJob firings, node
    maintenance windows) replayed on a 20k-node cluster through
    `simtpu/timeline`, events/s as the headline.  Env:
    SIMTPU_BENCH_TIMELINE_NODES (default 20000), _PODS (default 100000),
    _DAYS (default 3).  SIMTPU_BENCH_TIMELINE_ASSERT=1 (the `make
    bench-timeline` smoke) additionally replays the stream through the
    serial one-event-at-a-time oracle and ASSERTS the batched end state
    is bit-identical (planes, placement log, landing vectors, event
    timestamps), the auditor certified both, the sim clock is monotone,
    and the `timeline.*` registry counters moved."""
    from simtpu.engine.state import diff_state_planes
    from simtpu.obs.metrics import REGISTRY
    from simtpu.synth import make_trace
    from simtpu.timeline import ReplayOptions, replay_trace, trace_from_doc

    n_nodes = int(os.environ.get("SIMTPU_BENCH_TIMELINE_NODES", 20_000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_TIMELINE_PODS", 100_000))
    days = float(os.environ.get("SIMTPU_BENCH_TIMELINE_DAYS", 3.0))
    do_assert = os.environ.get("SIMTPU_BENCH_TIMELINE_ASSERT", "") == "1"
    note(
        f"timeline point: {n_nodes} nodes, ~{n_pods} pods over {days:g} "
        f"day(s){' (asserting smoke)' if do_assert else ''}"
    )
    doc = make_trace(
        n_nodes, n_pods, seed=21, days=days, mean_gang=16,
        cron_jobs=3, elastic_frac=0.1, node_event_frac=0.02,
        duration_mean_s=4 * 3600.0,
    )
    before = REGISTRY.snapshot("timeline.")
    res = replay_trace(
        trace_from_doc(doc, source="<bench>"),
        ReplayOptions(speculate=True, progress=note),
    )
    note(
        f"timeline: {res.events} events at "
        f"{res.timings['events_per_s']:.1f} events/s, "
        f"pending p50={res.pending_p50_s:.1f}s, "
        f"preemptions={res.counts['preemptions']}, "
        f"audit ok={bool(res.audit and res.audit['ok'])}"
    )
    out = {
        "timeline_nodes": n_nodes,
        "timeline_days": days,
        "timeline_events": res.events,
        "timeline_events_per_s": round(res.timings["events_per_s"], 2),
        "timeline_pending_p50_s": round(res.pending_p50_s, 3),
        "timeline_pending_p90_s": round(res.pending_p90_s, 3),
        "timeline_preemptions": res.counts["preemptions"],
        "timeline_gang_rollbacks": res.counts["gang_rollbacks"],
        "timeline_placed_pods": int((np.asarray(res.nodes) >= 0).sum()),
        "timeline_util_avg": round(res.util_avg, 4),
        "timeline_audit_ok": bool(res.audit and res.audit.get("ok")),
    }
    if do_assert:
        assert res.audit and res.audit["ok"], "timeline audit dirty"
        ts = [s[0] for s in res.samples]
        assert ts == sorted(ts), "sim clock not monotone"
        after = REGISTRY.snapshot("timeline.")
        moved = [
            k for k in ("timeline.events", "timeline.arrivals",
                        "timeline.admitted", "timeline.attempts")
            if after.get(k, 0) > before.get(k, 0)
        ]
        assert len(moved) == 4, f"timeline.* counters absent: {after}"
        note("timeline smoke: replaying the serial one-event oracle")
        oracle = replay_trace(
            trace_from_doc(doc, source="<bench>"),
            ReplayOptions(serial=True),
        )
        assert res.event_log == oracle.event_log, "event timelines differ"
        assert np.array_equal(res.nodes, oracle.nodes), (
            "final landing vectors differ"
        )
        assert list(res.engine.placed_node) == list(
            oracle.engine.placed_node
        ), "placement logs differ"
        diffs = diff_state_planes(res.end_state(), oracle.end_state())
        assert not diffs, f"end-state planes differ: {diffs}"
        assert oracle.audit and oracle.audit["ok"], "oracle audit dirty"
        out["timeline_serial_events_per_s"] = round(
            oracle.timings["events_per_s"], 2
        )
        out["timeline_oracle_identical"] = True
        note("timeline smoke: batched == serial oracle, audits clean")
    return out


def scan_smoke_point() -> dict:
    """Round-16 perf-lever point (`make bench-scan` = the small-shape
    asserting smoke, SIMTPU_BENCH_SCAN_SMOKE_ASSERT=1).  Three A/Bs:

    (a) universal wavefront drafting: an ALL-heavy storage+GPU+ports mix
        (every pod carries LVM, exclusive-device, GPU-share, or hostPort
        demand — pods the pre-round-16 mask never drafted) through the
        serial scan vs the wavefront dispatcher.  Asserts bit-identical
        placements, `wavefront.draft_hard` engaged, accepts > 0, and the
        wavefront rate >= 1.5x the pod-at-a-time floor.
    (b) direct compact-delta preemption: engine-level evict/restore churn
        on a compact carry under SIMTPU_DELTA_DIRECT=1 vs 0.  Asserts the
        direct counter fires (zero expand/recompress), the round-trip
        path reproduces the carry bit-identically, and direct throughput
        beats the expand->apply->recompress round trip.
    (c) a small timeline replay (departures/faults ride the same delta
        arithmetic) is bit-identical between the two settings.
    """
    import jax
    import jax.numpy as jnp

    from simtpu import constants as C
    from simtpu.core.objects import AppResource, ResourceTypes, set_label
    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.scan import (
        WAVE_KEYS,
        build_pod_arrays,
        default_wave_call,
        flags_from,
        run_scan_chunked,
        statics_from,
    )
    from simtpu.engine.state import CompactState, build_state
    from simtpu.obs.metrics import REGISTRY
    from simtpu.obs.metrics import family as metrics_family
    from simtpu.synth import make_deployment, synth_apps, synth_cluster
    from simtpu.workloads.expand import (
        get_valid_pods_exclude_daemonset,
        seed_name_hashes,
    )

    do_assert = os.environ.get("SIMTPU_BENCH_SCAN_SMOKE_ASSERT", "") == "1"
    out = {}

    # ---- (a) heavy wavefront drafting --------------------------------
    note("scan smoke: all-heavy storage+GPU+ports wavefront A/B")
    cluster = synth_cluster(
        48, seed=17, zones=3, taint_frac=0.0, gpu_frac=0.6, storage_frac=0.6
    )
    res = ResourceTypes()
    res.deployments = [
        make_deployment("lvmy", 128, 400, 200, lvm_gib=4),
        make_deployment("gpuey", 128, 400, 200, gpu_mem_mib=512),
        make_deployment("devy", 64, 300, 200, device_gib=10),
        make_deployment("porty", 40, 100, 128, host_port=8080),
    ]
    seed_name_hashes(0)
    pods = []
    for app in [AppResource(name="heavy", resource=res)]:
        for pod in get_valid_pods_exclude_daemonset(app.resource):
            set_label(pod, C.LABEL_APP_NAME, app.name)
            pods.append(pod)
    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    batch = tz.add_pods(pods)
    tensors = tz.freeze()
    statics = statics_from(tensors)
    r = tensors.alloc.shape[1]
    _req, pod_arrays = build_pod_arrays(batch, r)
    state0 = build_state(
        tensors, np.zeros(0, np.int32), np.zeros(0, np.int32),
        np.zeros((0, r), np.float32), None,
    )
    flags = flags_from(tensors, batch.ext)
    groups = np.asarray(batch.group)

    def timed_scan(speculate):
        def go(st):
            _, outs = run_scan_chunked(
                statics, st, pod_arrays, flags, tensors, groups,
                wave_call=default_wave_call if speculate else None,
            )
            return outs[0]

        go(jax.tree.map(jnp.copy, state0))  # compile + warm
        best = None
        nodes = None
        for _ in range(2):  # best-of-2: one noisy wall must not flake CI
            fresh = jax.tree.map(jnp.copy, state0)
            jax.block_until_ready(fresh)
            t0 = time.perf_counter()
            nodes = go(fresh)
            best = min(best, time.perf_counter() - t0) if best else (
                time.perf_counter() - t0
            )
        return best, nodes

    before = metrics_family("wavefront", WAVE_KEYS)
    serial_wall, serial_nodes = timed_scan(False)
    wave_wall, wave_nodes = timed_scan(True)
    after = metrics_family("wavefront", WAVE_KEYS)
    n_pods = len(groups)
    floor_rate = n_pods / serial_wall
    wave_rate = n_pods / wave_wall
    drafted = after["pods"] - before["pods"]
    accepted = after["accepted"] - before["accepted"]
    hard = after["draft_hard"] - before["draft_hard"]
    identical = bool(np.array_equal(serial_nodes, wave_nodes))
    note(
        f"scan smoke: heavy mix floor={floor_rate:.0f} pods/s "
        f"wavefront={wave_rate:.0f} pods/s "
        f"({wave_rate / floor_rate:.2f}x), drafted={drafted} "
        f"hard={hard} accepted={accepted} identical={identical}"
    )
    out["scan_smoke_heavy_floor_pods_per_s"] = round(floor_rate, 1)
    out["scan_smoke_heavy_wavefront_pods_per_s"] = round(wave_rate, 1)
    out["scan_smoke_heavy_speedup"] = round(wave_rate / floor_rate, 2)
    out["scan_smoke_heavy_accepted"] = accepted
    out["scan_smoke_heavy_draft_hard"] = hard
    if do_assert:
        assert identical, "heavy wavefront diverged from the serial scan"
        assert hard > 0, "heavy mix never rode the hard verifier"
        assert accepted > 0, "wavefront accept rate is 0 on the heavy mix"
        assert wave_rate >= 1.5 * floor_rate, (
            f"wavefront {wave_rate:.0f} pods/s under 1.5x the "
            f"{floor_rate:.0f} pods/s pod-at-a-time floor"
        )

    # ---- (b) direct compact-delta preemption churn -------------------
    note("scan smoke: direct compact-delta evict/restore A/B")
    from simtpu.faults import place_cluster

    pcluster = synth_cluster(
        1000, seed=5, zones=8, taint_frac=0.1, gpu_frac=0.2, storage_frac=0.3
    )
    papps = synth_apps(
        4000, seed=6, zones=8, pods_per_deployment=50,
        selector_frac=0.2, anti_affinity_frac=0.3, spread_frac=0.4,
        gpu_frac=0.1, storage_frac=0.2,
    )
    pc = place_cluster(pcluster, papps)
    eng = pc.engine
    idx = list(range(0, len(eng.placed_node), 7))
    base_carry = jax.tree_util.tree_map(
        lambda a: np.asarray(a).copy(), eng.last_state
    )

    def churn(cycles):
        t0 = time.perf_counter()
        for _ in range(cycles):
            saved = eng.remove_placements(idx)
            eng.restore_placements(saved)
        jax.block_until_ready(eng.last_state.free)
        return time.perf_counter() - t0

    walls = {}
    counters = {}
    prev = os.environ.get("SIMTPU_DELTA_DIRECT")
    try:
        for mode in ("1", "0"):
            os.environ["SIMTPU_DELTA_DIRECT"] = mode
            churn(1)  # compile + warm this mode's dispatches
            s0 = REGISTRY.snapshot()
            walls[mode] = churn(4)
            s1 = REGISTRY.snapshot()
            counters[mode] = {
                k: s1.get(k, 0) - s0.get(k, 0)
                for k in ("state.delta_direct", "state.expand", "state.compress")
            }
    finally:
        if prev is None:
            os.environ.pop("SIMTPU_DELTA_DIRECT", None)
        else:
            os.environ["SIMTPU_DELTA_DIRECT"] = prev
    deltas = 8  # 4 cycles x (evict + restore)
    note(
        f"scan smoke: {deltas} deltas of {len(idx)} entries — "
        f"direct {walls['1']:.3f}s {counters['1']}, "
        f"round-trip {walls['0']:.3f}s {counters['0']}"
    )
    out["scan_smoke_delta_direct_s"] = round(walls["1"], 3)
    out["scan_smoke_delta_roundtrip_s"] = round(walls["0"], 3)
    out["scan_smoke_delta_speedup"] = round(walls["0"] / walls["1"], 2)
    if do_assert:
        assert isinstance(eng.last_state, CompactState), "carry not compact"
        assert counters["1"]["state.delta_direct"] == deltas, counters
        assert counters["1"]["state.expand"] == 0, counters
        assert counters["1"]["state.compress"] == 0, counters
        assert counters["0"]["state.delta_direct"] == 0, counters
        assert counters["0"]["state.expand"] == deltas, counters
        for name in base_carry._fields:
            assert np.array_equal(
                np.asarray(getattr(eng.last_state, name)),
                getattr(base_carry, name),
            ), f"carry plane {name} drifted across the churn A/B"
        # the direct scatter must actually beat the expand->apply->
        # recompress round trip (measured ~13x at this shape; 2x keeps a
        # wide flake margin on loaded CI hosts)
        assert walls["1"] * 2 < walls["0"], (
            f"direct {walls['1']:.3f}s not faster than round trip "
            f"{walls['0']:.3f}s"
        )

    # ---- (c) timeline replay bit-identity across the A/B -------------
    note("scan smoke: timeline replay delta-direct A/B")
    from simtpu.engine.state import diff_state_planes
    from simtpu.synth import make_trace
    from simtpu.timeline import ReplayOptions, replay_trace, trace_from_doc

    doc = make_trace(
        16, 360, seed=21, days=0.2, mean_gang=8,
        cron_jobs=2, elastic_frac=0.1, node_event_frac=0.05,
        duration_mean_s=3600.0,
    )
    runs = {}
    prev = os.environ.get("SIMTPU_DELTA_DIRECT")
    try:
        for mode in ("1", "0"):
            os.environ["SIMTPU_DELTA_DIRECT"] = mode
            s0 = REGISTRY.snapshot()
            runs[mode] = replay_trace(
                trace_from_doc(doc, source="<bench-scan>"),
                ReplayOptions(speculate=True),
            )
            s1 = REGISTRY.snapshot()
            runs[mode + "_direct"] = s1.get("state.delta_direct", 0) - s0.get(
                "state.delta_direct", 0
            )
    finally:
        if prev is None:
            os.environ.pop("SIMTPU_DELTA_DIRECT", None)
        else:
            os.environ["SIMTPU_DELTA_DIRECT"] = prev
    same_nodes = bool(np.array_equal(runs["1"].nodes, runs["0"].nodes))
    plane_diffs = diff_state_planes(
        runs["1"].end_state(), runs["0"].end_state()
    )
    note(
        f"scan smoke: timeline identical={same_nodes} "
        f"(direct deltas {runs['1_direct']} vs {runs['0_direct']})"
    )
    out["scan_smoke_timeline_identical"] = same_nodes and not plane_diffs
    out["scan_smoke_timeline_direct_deltas"] = runs["1_direct"]
    if do_assert:
        assert same_nodes, "timeline landing vectors differ across the A/B"
        assert not plane_diffs, f"timeline end-state differs: {plane_diffs}"
        assert runs["1"].event_log == runs["0"].event_log, (
            "timeline event logs differ across the A/B"
        )
        assert runs["1_direct"] > 0, (
            "timeline departures never rode the direct delta path"
        )
        assert runs["0_direct"] == 0, "A/B off-leg still took the direct path"
        note("scan smoke asserts passed")
    return out


def grow_point() -> dict:
    """Warm-engine serving point (`make bench-grow` = the asserting
    smoke, SIMTPU_BENCH_GROW_ASSERT=1).  Two measurements:

    (a) append-only vocabulary growth at the engine level: a warm grow
        engine absorbs successive query waves (each interning new
        interpod terms) through `extend_state`, against the
        re-tensorize-from-scratch + `build_state` cost the pre-round-20
        serve path paid per query.  Asserts placements bit-identical,
        recompiles bounded by the pow2 buckets touched (trace-once-per-
        bucket), and the append path faster than the rebuild.
    (b) warm serve fit QPS before/after: the SAME alternating fit-query
        mix through an in-process SessionStore/Batcher with
        SIMTPU_SERVE_WARM on vs off.  Asserts >= 10x warm throughput and
        ZERO retensorize fallbacks on the warm mix.
    """
    from simtpu import constants as C
    from simtpu.core.objects import AppResource, ResourceTypes, set_label
    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.obs.metrics import REGISTRY
    from simtpu.synth import make_deployment, synth_cluster
    from simtpu.workloads.expand import (
        get_valid_pods_exclude_daemonset,
        seed_name_hashes,
    )

    do_assert = os.environ.get("SIMTPU_BENCH_GROW_ASSERT", "") == "1"
    n_nodes = int(os.environ.get("SIMTPU_BENCH_GROW_NODES", 64))
    n_waves = int(os.environ.get("SIMTPU_BENCH_GROW_WAVES", 6))
    out = {}

    # ---- (a) extend_state vs re-tensorize+build_state ------------------
    note("grow point: append-only growth vs re-tensorize rebuild")
    cluster = synth_cluster(n_nodes, seed=11, zones=2)

    def expand(name, deployments, seed):
        res = ResourceTypes()
        res.deployments = deployments
        app = AppResource(name=name, resource=res)
        seed_name_hashes(seed)
        pods = []
        for pod in get_valid_pods_exclude_daemonset(app.resource):
            set_label(pod, C.LABEL_APP_NAME, app.name)
            pods.append(pod)
        return pods

    # wave 0 is the session base; waves 1.. are the serving mix — two
    # query SHAPES that each intern their vocabulary once (an extend,
    # traced once per bucket) and then repeat with fresh pod names, the
    # zero-retensorize common path every later wave rides
    def query_wave(i):
        shape = i % 2
        return expand(f"shape-{shape}", [
            make_deployment(
                f"shape-{shape}", 24, 200, 128,
                anti_affinity_topo="kubernetes.io/hostname",
            )
        ], 2000 + i)

    waves = [expand("base", [
        make_deployment(
            f"svc-{j}", 12, 200, 128,
            anti_affinity_topo="kubernetes.io/hostname",
        )
        for j in range(6)
    ], 1000)]
    waves += [query_wave(i) for i in range(1, n_waves)]
    steady_from = 3  # both query shapes interned by wave 2
    assert n_waves > steady_from + 1, "need steady-state waves to time"
    tz = Tensorizer(cluster.nodes)
    eng = RoundsEngine(tz)
    eng.enable_grow()
    batch0 = tz.add_pods(waves[0])
    eng.place(batch0)  # compile + warm (first bucket traces here)
    s0 = REGISTRY.snapshot()
    warm_nodes, warm_s, steady = [], 0.0, {}
    for i, pods in enumerate(waves[1:], 1):
        if i == steady_from:
            steady = REGISTRY.snapshot()
        batch = tz.add_pods(pods)
        t0 = time.perf_counter()
        nodes, _r, _e = eng.place(batch)
        if i >= steady_from:
            warm_s += time.perf_counter() - t0
        warm_nodes.append(np.asarray(nodes))
    end = REGISTRY.snapshot()
    d = {
        k: end.get(k, 0) - s0.get(k, 0)
        for k in ("grow.extends", "grow.bucket_promotions", "grow.rebuilds",
                  "compile.grow")
    }
    # the trace-once-per-bucket contract, asserted where it bites: once
    # the mix's shapes are interned, MORE waves compile NOTHING
    steady_traces = sum(
        end.get(k, 0) - steady.get(k, 0)
        for k in ("compile.grow", "compile.rounds", "compile.scan",
                  "compile.wave")
    )

    # the rebuild leg: per steady-state wave, a from-scratch tensorizer
    # + a replay of the whole placement history before the query wave
    # lands — what the pre-round-20 serve path paid per fit query.  The
    # shape progression matches the warm leg's, so the jit cache is
    # already warm and the clock measures the re-tensorize + replay work
    # itself.
    def cold_wave(i):
        t0 = time.perf_counter()
        tz2 = Tensorizer(cluster.nodes)
        eng2 = RoundsEngine(tz2)
        eng2.compact = False  # match the grow layout's dense carry
        last = None
        for pods in waves[: i + 1]:
            batch2 = tz2.add_pods(pods)
            last, _r2, _e2 = eng2.place(batch2)
        return np.asarray(last), time.perf_counter() - t0

    rebuild_s = 0.0
    cold_wave(1)  # compile the cold leg's own dense-path kernels
    cold_nodes = []
    for i in range(1, n_waves):
        last, dt = cold_wave(i)
        cold_nodes.append(last)
        if i >= steady_from:
            rebuild_s += dt
    identical = all(
        np.array_equal(a, b) for a, b in zip(warm_nodes, cold_nodes)
    )
    n_steady = n_waves - steady_from
    warm_ms = 1000 * warm_s / n_steady
    rebuild_ms = 1000 * rebuild_s / n_steady
    note(
        f"grow point: steady warm wave {warm_ms:.1f}ms vs rebuild "
        f"{rebuild_ms:.1f}ms ({rebuild_ms / max(warm_ms, 1e-9):.1f}x), "
        f"extends={d['grow.extends']} "
        f"promotions={d['grow.bucket_promotions']} "
        f"rebuilds={d['grow.rebuilds']} traces={d['compile.grow']} "
        f"steady_traces={steady_traces} identical={identical}"
    )
    out["grow_warm_wave_ms"] = round(warm_ms, 2)
    out["grow_rebuild_wave_ms"] = round(rebuild_ms, 2)
    out["grow_speedup"] = round(rebuild_ms / max(warm_ms, 1e-9), 2)
    out["grow_identical"] = identical
    out["grow_steady_traces"] = int(steady_traces)
    out.update({
        f"grow_{k.split('.', 1)[-1]}": int(v) for k, v in d.items()
    })
    if do_assert:
        assert identical, "grow placements diverged from the rebuild leg"
        assert d["grow.rebuilds"] == 0, d
        assert d["grow.extends"] >= 1, d
        assert steady_traces == 0, (
            f"steady-state waves recompiled {steady_traces}x"
        )
        assert out["grow_speedup"] > 1.0, out

    # ---- (b) warm serve fit QPS before/after ---------------------------
    note("grow point: warm vs cold serve fit QPS")
    from simtpu.durable.deadline import RunControl
    from simtpu.serve.batching import Batcher, Query
    from simtpu.serve.session import SessionStore

    def fit_payload(i):
        shape = i % 2
        name = f"bench-fit-{shape}"
        return {"workloads": [{
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "replicas": 1 + shape,
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {"containers": [{
                        "name": "c", "image": "app",
                        "resources": {"requests": {
                            "cpu": "250m" if shape else "100m",
                            "memory": "128Mi",
                        }},
                    }]},
                },
            },
        }]}

    def fit_qps(warm_on, n_queries):
        prev = os.environ.get("SIMTPU_SERVE_WARM")
        os.environ["SIMTPU_SERVE_WARM"] = "1" if warm_on else "0"
        try:
            store = SessionStore(state_dir="", audit=False)
            session, _created = store.create("examples/simtpu-config.yaml")
            batcher = Batcher(store)

            def one(i):
                q = Query(kind="fit", session=session,
                          payload=fit_payload(i), control=RunControl())
                with session.lock:
                    return batcher._run_fit(q)

            one(0), one(1)  # per-shape warm-up (compile off the clock)
            t0 = time.perf_counter()
            for i in range(n_queries):
                doc = one(i)
                assert doc["ok"], doc
            wall = time.perf_counter() - t0
            return n_queries / wall, doc
        finally:
            if prev is None:
                os.environ.pop("SIMTPU_SERVE_WARM", None)
            else:
                os.environ["SIMTPU_SERVE_WARM"] = prev

    s1 = REGISTRY.snapshot()
    warm_qps, warm_doc = fit_qps(True, 40)
    fallbacks = (
        REGISTRY.snapshot().get("grow.retensorize_fallbacks", 0)
        - s1.get("grow.retensorize_fallbacks", 0)
    )
    cold_qps, _cold_doc = fit_qps(False, 6)
    note(
        f"grow point: warm fit {warm_qps:.1f} q/s vs cold "
        f"{cold_qps:.1f} q/s ({warm_qps / max(cold_qps, 1e-9):.1f}x), "
        f"warm fallbacks={fallbacks}"
    )
    out["grow_serve_warm_qps"] = round(warm_qps, 1)
    out["grow_serve_cold_qps"] = round(cold_qps, 1)
    out["grow_serve_speedup"] = round(warm_qps / max(cold_qps, 1e-9), 1)
    out["grow_serve_fallbacks"] = int(fallbacks)
    if do_assert:
        assert warm_doc.get("warm") is True, warm_doc
        assert fallbacks == 0, f"warm mix re-tensorized {fallbacks}x"
        assert out["grow_serve_speedup"] >= 10.0, out
        note("grow point asserts passed")
    return out


def time_plan():
    """The min-node-add plan at north-star scale: a 100k-node cluster whose
    Open-Local capacity strands ~28k LVM pods of a 1M-pod selector-free mix,
    planned against a storage-rich template (109 clones expected). Returns
    the JSON fields; see simtpu/plan/incremental.py for the strategy."""
    from simtpu.plan.incremental import plan_capacity_incremental
    from simtpu.synth import make_node, synth_apps, synth_cluster
    from simtpu.workloads.expand import seed_name_hashes

    note("building the plan scenario (100k nodes, 1M pods, LVM-starved)")
    cluster = synth_cluster(
        100_000, seed=3, zones=16, taint_frac=0.1, storage_frac=0.09
    )
    apps = synth_apps(
        1_000_000,
        seed=5,
        zones=16,
        pods_per_deployment=1000,
        selector_frac=0.0,
        toleration_frac=0.1,
        anti_affinity_frac=0.2,
        spread_frac=0.3,
        storage_frac=0.25,
        storage_device_frac=0.0,
    )
    template = make_node(
        "tmpl",
        256000,
        512,
        {
            "kubernetes.io/hostname": "tmpl",
            "topology.kubernetes.io/zone": "zone-plan",
        },
        storage_gib=(4000, 4000),
    )
    # ONE shared pipeline across the cold and warm plans: AOT executables
    # never warm the jit path's own cache, so a per-call pipeline would
    # make the warm plan recompile everything it just compiled
    pipe = None
    if _bench_precompile():
        from simtpu.engine.precompile import AotPipeline

        pipe = AotPipeline()
    out = {}
    for label in ("cold", "warm"):
        seed_name_hashes(7)
        t0 = time.perf_counter()
        plan = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=128,
            materialize=False, verify=True, pipeline=pipe,
        )
        wall = time.perf_counter() - t0
        t = plan.timings
        search = t.get("tensorize", 0) + t.get("base", 0) + t.get("probes", 0)
        compiles = {
            phase: sum(counts.values()) for phase, counts in plan.compiles.items()
        }
        note(
            f"plan {label}: nodes_added={plan.nodes_added} wall={wall:.1f}s "
            f"search={search:.1f}s verify={t.get('verify', 0):.1f}s "
            f"probes={plan.probes} compiles={plan.compiles}"
        )
        if label == "cold":
            # distinct-executable accounting (trajectory files track the
            # cold-path target through these): total jit traces, plus the
            # probe-sweep round-body count the bucketing pins at <= 2
            probe_rounds = plan.compiles.get("probes", {}).get("rounds", 0) + (
                plan.compiles.get("verify", {}).get("rounds", 0)
            )
            out["plan_cold_s"] = round(wall, 2)
            out["plan_cold_compiles"] = sum(compiles.values())
            out["plan_cold_probe_round_compiles"] = probe_rounds
            # the plan's AOT-pipeline split (wall < serial = the probe
            # sweep's compiles overlapped each other and the host work)
            if "compile_wall" in t:
                out["plan_compile_s"] = round(t["compile_wall"], 2)
                out["plan_compile_serial_s"] = round(t["compile_serial"], 2)
        else:
            out["plan_s"] = round(search, 2)
            out["plan_verified_s"] = round(wall, 2)
            out["plan_warm_compiles"] = sum(compiles.values())
            # the independent audit of the shipped candidate rides the
            # plan (auto-on); its wall against plan_verified_s is the
            # overhead the <10% acceptance bound tracks at full scale
            out["plan_audit_s"] = round(
                float((plan.audit or {}).get("wall_s", 0.0)), 3
            )
        out["plan_nodes_added"] = plan.nodes_added
        assert plan.success, "plan scenario must be feasible"
    if pipe is not None:
        pipe.shutdown()
    return out


# the north-star constraint mix in words (build_problem mix="north"):
# what the multihost published record certifies it ran
_NORTH_CONSTRAINTS = (
    "zone topology spread + preferred inter-pod anti-affinity + "
    "node selectors/tolerations + Open-Local storage"
)

# the one published multihost metric: BASELINE.json's `published` block
# carries ONLY the north-star shape (100k nodes x 1M pods — the <60 s
# target vs_target measures distance to is DEFINED at that shape);
# publish_multihost refuses anything else
_NORTH_STAR_METRIC = "multihost_place_1m_pods_100k_nodes"
_NORTH_STAR_PODS = 1_000_000

# exactly the keys publish_multihost() copies into BASELINE.json's
# `published` block, in published order — a worker record missing any of
# them is rejected, extra worker keys (unplaced_reasons, ...) stay out of
# the published block so its shape is stable
_PUBLISH_KEYS = (
    "metric", "value", "unit", "measured_at", "backend", "devices",
    "engine", "constraints", "affinity", "spread", "trajectory", "metrics",
)


def _count_tag(n: int) -> str:
    """1_000_000 -> '1m', 100_000 -> '100k', 200 -> '200' — exact counts
    only, so no shape ever degrades to a colliding '0k' tag."""
    if n >= 1_000_000 and n % 1_000_000 == 0:
        return f"{n // 1_000_000}m"
    if n >= 1_000 and n % 1_000 == 0:
        return f"{n // 1_000}k"
    return str(n)


def multihost_worker_main() -> int:
    """`bench.py --multihost-worker`: the in-subprocess half of
    multihost_point(). Runs the north-star constraint mix through the bulk
    GSPMD `ShardedRoundsEngine` with the node axis sharded over every
    visible device (the launcher forces the host-platform device count
    before this process imports jax) and prints ONE JSON record line —
    the measured record publish_multihost() accepts.

    Env knobs: SIMTPU_BENCH_MULTIHOST_NODES (default 100000),
    SIMTPU_BENCH_MULTIHOST_PODS (default 1000000),
    SIMTPU_BENCH_MULTIHOST_RUNS (default 1 — with a single run only
    `place_cold_s` exists; warm timings appear only when runs > 1 actually
    measured one)."""
    from datetime import datetime, timezone

    import jax

    from simtpu.cache import enable_compilation_cache
    from simtpu.obs.metrics import REGISTRY
    from simtpu.parallel import ShardedRoundsEngine
    from simtpu.parallel.mesh import make_mesh

    cache_dir = enable_compilation_cache()
    note(f"compilation cache: {cache_dir or 'disabled'}")
    n_nodes = int(os.environ.get("SIMTPU_BENCH_MULTIHOST_NODES", 100_000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_MULTIHOST_PODS", 1_000_000))
    runs = max(int(os.environ.get("SIMTPU_BENCH_MULTIHOST_RUNS", 1)), 1)

    t0 = time.perf_counter()
    tensors, batch = build_problem(n_nodes, n_pods, with_state=False)
    expand_tensorize_s = time.perf_counter() - t0
    mesh = make_mesh(sweep=1)
    n_devices = len(jax.devices())
    note(
        f"multihost point: {n_nodes} nodes x {n_pods} pods over "
        f"{n_devices} {jax.default_backend()} devices (runs={runs})"
    )

    nodes = reasons = None
    walls = []
    for i in range(runs):
        # a fresh engine per run: the first run pays jit compilation
        # (place_cold_s), later runs ride the in-process executable cache
        # (place_warm_s) — the same cold/warm split time_bulk() reports
        eng = ShardedRoundsEngine(_FrozenTensorizer(tensors), mesh)
        t0 = time.perf_counter()
        nodes, reasons, _ = eng.place(batch)
        walls.append(time.perf_counter() - t0)
        note(f"multihost run {i}: {walls[-1]:.1f}s")
    place_cold_s = walls[0]
    # the headline `value` is the steady-state (warm) wall when it was
    # measured, else the single cold run — never a copy of the other
    value = min(walls[1:]) if runs > 1 else place_cold_s
    total = len(batch.group)
    placed = int((np.asarray(nodes) >= 0).sum())
    hist = reason_histogram(nodes, reasons)
    if hist:
        note(f"unplaced={total - placed}; reasons:")
        for reason, cnt in hist.items():
            note(f"  {cnt:8d}  {reason}")

    trajectory = {
        "expand_tensorize_s": round(expand_tensorize_s, 1),
        "place_cold_s": round(place_cold_s, 2),
        "end_to_end_s": round(expand_tensorize_s + place_cold_s, 2),
        "pods_per_s": round(total / value, 1),
        "placed": placed,
        "unplaced": total - placed,
        "runs": runs,
    }
    if runs > 1:
        trajectory["place_warm_s"] = round(value, 2)
    record = {
        "metric": (
            f"multihost_place_{_count_tag(n_pods)}_pods_"
            f"{_count_tag(n_nodes)}_nodes"
        ),
        "value": round(value, 2),
        "unit": "s",
        "measured_at": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "backend": jax.default_backend(),
        "devices": n_devices,
        # honesty marker for the "multihost" name: this point runs ONE
        # process GSPMD-sharding the node axis over the mesh — the
        # computation tests/test_multihost.py pins bit-identical when the
        # same 8-device mesh spans 2 real processes (jax.distributed);
        # cross-process DCN overhead is NOT in these walls
        "processes": jax.process_count(),
        "engine": (
            f"ShardedRoundsEngine (GSPMD, node axis over "
            f"{n_devices}-device mesh)"
        ),
        "constraints": _NORTH_CONSTRAINTS,
        "affinity": True,
        "spread": True,
        "trajectory": trajectory,
        "unplaced_reasons": hist,
        "metrics": REGISTRY.snapshot(),
    }
    print(json.dumps(record))
    return 0


def publish_multihost(record: dict, baseline_path: str | None = None) -> dict:
    """Write a measured multihost record into BASELINE.json's `published`
    block — the ONLY writer of that block. Derived fields are recomputed
    here from the measured primitives, never copied through: `vs_target`
    always follows the one documented formula (round(60.0 / value, 2) —
    the same <60 s target distance main() publishes for the north-star
    point), `pods_per_s`/`end_to_end_s` are re-derived, and a runs==1
    record publishes NO `place_warm_s` (a single measurement is a cold run
    only). Raises ValueError on a record missing any measured primitive,
    so a hand-assembled block can't slip through the door."""
    path = baseline_path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
    )
    missing = [k for k in _PUBLISH_KEYS if k not in record]
    if missing:
        raise ValueError(f"multihost record missing measured keys: {missing}")
    if record["metric"] != _NORTH_STAR_METRIC:
        # only the north-star shape is publishable: the <60 s target that
        # vs_target measures distance to is defined at 100k x 1M, so a
        # smoke-shape record must never overwrite the headline block
        raise ValueError(
            f"only {_NORTH_STAR_METRIC!r} is publishable, "
            f"got {record['metric']!r}"
        )
    traj = dict(record["trajectory"])
    for k in ("expand_tensorize_s", "place_cold_s", "placed", "unplaced"):
        if k not in traj:
            raise ValueError(f"multihost trajectory missing {k!r}")
    runs = int(traj.get("runs", 1))
    value = float(record["value"])
    total = int(traj["placed"]) + int(traj["unplaced"])
    if value <= 0:
        raise ValueError(f"degenerate record: value={value}")
    if total != _NORTH_STAR_PODS:
        raise ValueError(
            f"pod accounting ({total}) does not match the north-star shape"
        )
    traj["runs"] = runs
    traj["end_to_end_s"] = round(
        float(traj["expand_tensorize_s"]) + float(traj["place_cold_s"]), 2
    )
    traj["pods_per_s"] = round(total / value, 1)
    if runs <= 1:
        traj.pop("place_warm_s", None)
    published = {}
    for key in _PUBLISH_KEYS:
        published[key] = record[key]
        if key == "unit":
            # distance to the <60 s BASELINE.json target, right after the
            # headline value it qualifies
            published["vs_target"] = round(60.0 / value, 2)
    published["value"] = round(value, 2)
    published["trajectory"] = traj
    published["source"] = "bench.py multihost_point (publish_multihost)"
    with open(path) as f:
        baseline = json.load(f)
    baseline["published"] = published
    with open(path, "w") as f:
        json.dump(baseline, f, indent=2)
        f.write("\n")
    note(f"published {published['metric']} = {published['value']}s -> {path}")
    return published


def multihost_point(argv) -> int:
    """`bench.py --multihost`: launcher half of the multihost bench point.
    Spawns the measurement in a FRESH subprocess (the forced host-platform
    device count must be set before jax is imported, so an in-process run
    can never see the requested mesh), echoes the worker's one-line JSON
    record, and optionally: saves the raw record (`--record-out FILE` —
    the MULTIHOST_r*.json provenance artifact) and publishes it into
    BASELINE.json (`--publish`). SIMTPU_BENCH_MULTIHOST_DEVICES (default
    8) sizes the forced mesh; SIMTPU_BENCH_MULTIHOST_FORCE_HOST=0 uses the
    real visible devices instead (TPU/GPU pods). `make bench-multihost`
    runs the small-shape asserting smoke (SIMTPU_BENCH_MULTIHOST_ASSERT=1:
    schema + accounting + publish round-trip into a scratch BASELINE)."""
    import subprocess
    import tempfile

    devices = int(os.environ.get("SIMTPU_BENCH_MULTIHOST_DEVICES", 8))
    env = dict(os.environ)
    forced = False
    if env.get("SIMTPU_BENCH_MULTIHOST_FORCE_HOST", "1") != "0":
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
            forced = True
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multihost-worker"],
        env=env,
        stdout=subprocess.PIPE,
        text=True,
    )
    if proc.returncode != 0:
        note(f"multihost worker failed (exit {proc.returncode})")
        return proc.returncode or 1
    line = proc.stdout.strip().splitlines()[-1]
    record = json.loads(line)
    print(line)
    if "--record-out" in argv:
        out = argv[argv.index("--record-out") + 1]
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        note(f"raw record -> {out}")
    if os.environ.get("SIMTPU_BENCH_MULTIHOST_ASSERT", "0") == "1":
        traj = record["trajectory"]
        total = traj["placed"] + traj["unplaced"]
        assert record["metric"].startswith("multihost_place_"), record["metric"]
        assert record["unit"] == "s" and record["value"] > 0, record
        # the devices knob is only a promise when this launcher actually
        # forced the host platform count (a preset XLA_FLAGS or a real
        # TPU/GPU mesh decides its own size)
        if forced:
            assert record["devices"] == devices, (record["devices"], devices)
        assert record["devices"] >= 1 and record["processes"] >= 1, record
        assert total == int(env.get("SIMTPU_BENCH_MULTIHOST_PODS", 1_000_000))
        assert (
            abs(
                traj["end_to_end_s"]
                - (traj["expand_tensorize_s"] + traj["place_cold_s"])
            )
            < 0.2
        ), traj
        with tempfile.TemporaryDirectory() as tmp:
            scratch = os.path.join(tmp, "BASELINE.json")
            with open(scratch, "w") as f:
                json.dump({"published": {}}, f)
            # smoke shapes must be refused by the published-block door...
            if record["metric"] != _NORTH_STAR_METRIC:
                try:
                    publish_multihost(dict(record), scratch)
                except ValueError:
                    pass
                else:
                    raise AssertionError(
                        "non-north-star record was publishable"
                    )
            # ...and the publish round-trip (exercised on a north-star-
            # LABELED copy) recomputes the derived fields; a lone cold run
            # publishes no warm number
            labeled = dict(record, metric=_NORTH_STAR_METRIC)
            labeled["trajectory"] = dict(
                traj,
                placed=_NORTH_STAR_PODS - traj["unplaced"],
            )
            published = publish_multihost(labeled, scratch)
            assert published["vs_target"] == round(60.0 / record["value"], 2)
            assert published["source"].startswith("bench.py multihost_point")
            if published["trajectory"]["runs"] <= 1:
                assert "place_warm_s" not in published["trajectory"]
            with open(scratch) as f:
                assert json.load(f)["published"] == published
        note("multihost smoke asserts passed")
    if "--publish" in argv:
        publish_multihost(record)
    return 0


def publish_multihost_main(argv) -> int:
    """`bench.py --publish-multihost RECORD.json [--baseline FILE]`:
    (re)publish a saved measured record (a `--record-out` artifact, e.g.
    the committed MULTIHOST_r*.json) into BASELINE.json — the derived
    fields are recomputed by publish_multihost(), so the published block
    is always reproducible from the committed record + this code path."""
    rec_path = argv[argv.index("--publish-multihost") + 1]
    with open(rec_path) as f:
        record = json.load(f)
    baseline = None
    if "--baseline" in argv:
        baseline = argv[argv.index("--baseline") + 1]
    publish_multihost(record, baseline)
    return 0


def main() -> int:
    from simtpu.cache import enable_compilation_cache

    cache_dir = enable_compilation_cache()
    note(f"compilation cache: {cache_dir or 'disabled'}")
    n_nodes = int(os.environ.get("SIMTPU_BENCH_NODES", 100_000))
    n_pods = int(os.environ.get("SIMTPU_BENCH_PODS", 1_000_000))
    # informational serial-rate slice; 2k pods keeps it under ~15 s at the
    # ~180 pods/s tunneled serial rate
    scan_pods = int(os.environ.get("SIMTPU_BENCH_SCAN_PODS", 2_000))
    base_pods = int(os.environ.get("SIMTPU_BENCH_BASELINE_PODS", 300))

    import jax

    north_star = (n_nodes, n_pods) == (100_000, 1_000_000)

    def side_point(label, env, mix, record_to=None):
        """A 20k x 100k continuity point on `mix`; every point prints its
        unplaced-reason histogram (no silent stranding on ANY point)."""
        if os.environ.get(env, "1") == "0" or not north_star:
            return
        p_tensors, p_batch = build_problem(20_000, 100_000, mix=mix, with_state=False)
        wall, _, p_nodes, p_reasons, p_extra = time_bulk(p_tensors, p_batch)
        placed = int((p_nodes >= 0).sum())
        total = len(p_batch.group)
        note(
            f"{label} nodes=20000 pods={total} bulk-wall={wall:.2f}s "
            f"rate={total / wall:.0f} pods/s placed={placed} "
            f"fetches={p_extra['fetches']}"
        )
        hist = reason_histogram(p_nodes, p_reasons)
        for reason, cnt in hist.items():
            note(f"  {cnt:8d}  {reason}")
        if record_to is not None:
            record_to[f"{mix}_point_s"] = round(wall, 2)
            record_to[f"{mix}_point_rate"] = round(total / wall)
            # blocking device→host round-trips of one warm placement (the
            # matrix point's measured floor was its ~54 per-stretch
            # fetches; stretch-group coalescing is the lever)
            record_to[f"{mix}_point_fetches"] = p_extra["fetches"]

    side_records = {}
    # the r01-continuity point: same constraint mix at 20k x 100k
    side_point("small-point", "SIMTPU_BENCH_SMALL", "north")
    # hard-constraint mix (DoNotSchedule spread + required anti) through
    # the domain-quota rounds — the serial-fallback cost r2 footnoted
    side_point("hard-point", "SIMTPU_BENCH_HARD", "hard", side_records)
    # round-4 matrix mix: multi-GPU / multi-claim LVM / self-affinity runs
    # that previously fell to the ~172 pods/s serial scan
    side_point("matrix-point", "SIMTPU_BENCH_MATRIX", "matrix", side_records)

    (
        tensors,
        batch,
        statics,
        state,
        pod_arrays,
        req,
        gen_s,
        tensorize_s,
    ) = build_problem(n_nodes, n_pods)

    from simtpu.engine.scan import WAVE_KEYS, flags_from
    from simtpu.obs.metrics import REGISTRY
    from simtpu.obs.metrics import family as metrics_family

    def wave_counts():
        # registry-backed speculation counters (obs/metrics.py)
        return metrics_family("wavefront", WAVE_KEYS)

    precompile = _bench_precompile()
    note("problem built; timing scan slice (pod-at-a-time floor)")
    scan_slice = tuple(arr[:scan_pods] for arr in pod_arrays)
    scan_flags = flags_from(tensors, batch.ext)
    scan_groups = np.asarray(batch.group)[:scan_pods]
    engine_s, scan_nodes = time_engine(
        statics, state, scan_slice, scan_flags,
        tensors=tensors, groups=scan_groups,
    )
    scan_rate = scan_pods / engine_s
    note(f"scan={scan_rate:.0f} pods/s; timing speculative wavefront scan")
    # the same slice through the speculative wavefront dispatcher
    # (engine/scan.py wavefronts): the exact engine's batched
    # verify-and-rollback path — placements are pinned bit-identical, the
    # acceptance/rollback counters ride the same run
    w0 = wave_counts()
    wave_s, wave_nodes = time_engine(
        statics, state, scan_slice, scan_flags,
        tensors=tensors, groups=scan_groups, speculate=True,
    )
    w1 = wave_counts()
    wave_rate = scan_pods / wave_s
    wave_stats = {k: w1[k] - w0[k] for k in w1}
    # two timed runs (warm+timed each): normalize counters to one pass
    wave_stats = {k: v // 2 for k, v in wave_stats.items()}
    if not np.array_equal(np.asarray(scan_nodes), np.asarray(wave_nodes)):
        note("WARNING: wavefront scan diverged from the pod-at-a-time scan")
    note(
        f"wavefront scan={wave_rate:.0f} pods/s "
        f"({wave_rate / max(scan_rate, 1e-9):.1f}x the serial floor); "
        f"accept={wave_stats['accepted']}/{wave_stats['pods']} "
        f"rollbacks={wave_stats['rollbacks']}; timing bulk"
    )

    bulk_s, cold_run_s, placed_nodes, reasons, cold_extra = time_bulk(
        tensors, batch, precompile=precompile
    )
    placed = int((placed_nodes >= 0).sum())
    unplaced = len(batch.group) - placed
    pods_per_sec = len(batch.group) / bulk_s
    hist = reason_histogram(placed_nodes, reasons)
    if hist:
        note(f"unplaced={unplaced}; reasons:")
        for reason, cnt in hist.items():
            note(f"  {cnt:8d}  {reason}")

    base_spp = time_serial_baseline(tensors, batch, req, base_pods)
    base_pods_per_sec = 1.0 / base_spp if base_spp > 0 else float("inf")

    note(
        f"nodes={n_nodes} pods={n_pods} placed={placed} "
        f"gen={gen_s:.1f}s tensorize={tensorize_s:.1f}s "
        f"scan={scan_rate:.0f} pods/s wavefront={wave_rate:.0f} pods/s "
        f"bulk={pods_per_sec:.0f} pods/s "
        f"bulk-wall={bulk_s:.1f}s cold-run={cold_run_s:.1f}s "
        f"serial-baseline={base_pods_per_sec:.0f} pods/s "
        f"backend={jax.default_backend()}"
    )

    record = {
        "metric": (
            "north_star_place_1m_pods_100k_nodes"
            if north_star
            else f"bulk_place_{n_pods//1000}k_pods_{n_nodes//1000}k_nodes"
        ),
        "value": round(bulk_s, 2),
        "unit": "s",
        # real baseline ratio: bulk throughput over the reference-shaped
        # serial loop's throughput (valid at any configuration)
        "vs_baseline": round(pods_per_sec / base_pods_per_sec, 1),
        "cold_s": round(gen_s + tensorize_s + cold_run_s, 2),
        # the cold split: first-run overhead above steady state is XLA
        # compilation (or, with a warm persistent cache, cache loading)
        "cold_compile_s": round(cold_run_s - bulk_s, 2),
        "cold_run_s": round(cold_run_s, 2),
        # cold-path breakdown (ISSUE 2): expand → tensorize → parallel AOT
        # compile (wall vs the summed per-executable seconds serializing
        # them would cost) → first dispatch; plus the warm run's blocking
        # device→host round-trip count
        "expand_s": round(gen_s, 2),
        "tensorize_s": round(tensorize_s, 2),
        "first_dispatch_s": cold_extra.get("first_dispatch_s"),
        "compile_s": cold_extra.get("compile_s"),
        "compile_serial_s": cold_extra.get("compile_serial_s"),
        "precompile": precompile,
        "fetches": cold_extra.get("fetches"),
        # byte-level transfer + residency telemetry (ISSUE 5): device→host
        # payload of one warm placement, the carried-state layout in effect
        # and its per-plane gauge, and the accelerator's peak residency
        # (None on CPU backends, which publish no memory_stats)
        "fetch_bytes": cold_extra.get("fetch_bytes"),
        "compact": REGISTRY.value("state.compact", default=False),
        "engine_state_bytes": REGISTRY.value("state.carried_bytes"),
        "device_peak_bytes": device_peak_bytes(),
        "compilation_cache": bool(cache_dir),
        # exact-scan throughput: the pod-at-a-time floor vs the speculative
        # wavefront dispatcher on the same slice (bit-identical placements;
        # ISSUE 3 — acceptance rate and rollback volume ride along)
        "scan_pods_per_s": round(scan_rate, 1),
        "scan_wavefront_pods_per_s": round(wave_rate, 1),
        "scan_wavefront_speedup": round(wave_rate / max(scan_rate, 1e-9), 2),
        "wavefront_pods": wave_stats["pods"],
        "wavefront_accept_rate": round(
            wave_stats["accepted"] / max(wave_stats["pods"], 1), 4
        ),
        "wavefront_rollbacks": wave_stats["rollbacks"],
        "wavefront_rollback_pods": wave_stats["rollback_pods"],
        "placed": placed,
        "unplaced": unplaced,
        "unplaced_reasons": hist,
    }
    record.update(side_records)
    if north_star:
        # distance to the BASELINE.json < 60 s target (north-star config only)
        record["vs_target"] = round(60.0 / bulk_s, 2)
        del tensors, batch, statics, state, pod_arrays, req
        if os.environ.get("SIMTPU_BENCH_PLAN", "1") != "0":
            # a plan-phase failure must not lose the placement record — the
            # JSON line below is the driver's only read of this run
            try:
                record.update(time_plan())
            except Exception as exc:  # noqa: BLE001 - report, keep the line
                note(f"plan bench failed: {type(exc).__name__}: {exc}")
                record["plan_error"] = f"{type(exc).__name__}: {exc}"
        if os.environ.get("SIMTPU_BENCH_BIG", "1") != "0":
            try:
                record.update(big_point())
            except Exception as exc:  # noqa: BLE001 - report, keep the line
                note(f"big point failed: {type(exc).__name__}: {exc}")
                record["big_point_error"] = f"{type(exc).__name__}: {exc}"
    # fault-injection point (ISSUE 4): on by default at north-star runs,
    # SIMTPU_BENCH_FAULTS=1 forces it at any configuration (`make
    # bench-faults` = the small-shape smoke), =0 skips
    faults_env = os.environ.get("SIMTPU_BENCH_FAULTS", "")
    if faults_env != "0" and (north_star or faults_env == "1"):
        try:
            record.update(fault_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"fault point failed: {type(exc).__name__}: {exc}")
            record["fault_error"] = f"{type(exc).__name__}: {exc}"
    # carried-state layout A/B (ISSUE 5): on by default at north-star runs,
    # SIMTPU_BENCH_LAYOUT=1 forces it at any configuration (`make
    # bench-layout` = the small-shape asserting smoke), =0 skips
    layout_env = os.environ.get("SIMTPU_BENCH_LAYOUT", "")
    if layout_env != "0" and (north_star or layout_env == "1"):
        try:
            record.update(layout_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"layout point failed: {type(exc).__name__}: {exc}")
            record["layout_error"] = f"{type(exc).__name__}: {exc}"
    # durable-execution smoke (ISSUE 6): on by default at north-star runs,
    # SIMTPU_BENCH_DURABLE=1 forces it at any configuration (`make
    # bench-durable` = the small-shape asserting smoke), =0 skips
    durable_env = os.environ.get("SIMTPU_BENCH_DURABLE", "")
    if durable_env != "0" and (north_star or durable_env == "1"):
        try:
            record.update(durable_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"durable point failed: {type(exc).__name__}: {exc}")
            record["durable_error"] = f"{type(exc).__name__}: {exc}"
    # trust-but-verify smoke (ISSUE 7): on by default at north-star runs,
    # SIMTPU_BENCH_AUDIT=1 forces it at any configuration (`make
    # bench-audit` = the small-shape asserting smoke), =0 skips
    audit_env = os.environ.get("SIMTPU_BENCH_AUDIT", "")
    if audit_env != "0" and (north_star or audit_env == "1"):
        try:
            record.update(audit_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"audit point failed: {type(exc).__name__}: {exc}")
            record["audit_error"] = f"{type(exc).__name__}: {exc}"
    # global-solver backend smoke (ISSUE 19): on by default at north-star
    # runs, SIMTPU_BENCH_SOLVE=1 forces it at any configuration (`make
    # bench-solve` = the small-shape asserting smoke), =0 skips
    solve_env = os.environ.get("SIMTPU_BENCH_SOLVE", "")
    if solve_env != "0" and (north_star or solve_env == "1"):
        try:
            record.update(solve_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"solve point failed: {type(exc).__name__}: {exc}")
            record["solve_error"] = f"{type(exc).__name__}: {exc}"
    # observability overhead gate (ISSUE 8): on by default at north-star
    # runs, SIMTPU_BENCH_OBS=1 forces it at any configuration (`make
    # bench-obs` = the small-shape asserting smoke), =0 skips
    obs_env = os.environ.get("SIMTPU_BENCH_OBS", "")
    if obs_env != "0" and (north_star or obs_env == "1"):
        try:
            record.update(obs_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"obs point failed: {type(exc).__name__}: {exc}")
            record["obs_error"] = f"{type(exc).__name__}: {exc}"
    # decision-observability smoke (ISSUE 13): on by default at north-star
    # runs, SIMTPU_BENCH_EXPLAIN=1 forces it at any configuration (`make
    # bench-explain` = the small-shape asserting smoke), =0 skips
    explain_env = os.environ.get("SIMTPU_BENCH_EXPLAIN", "")
    if explain_env != "0" and (north_star or explain_env == "1"):
        try:
            record.update(explain_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"explain point failed: {type(exc).__name__}: {exc}")
            record["explain_error"] = f"{type(exc).__name__}: {exc}"
    # long-lived service smoke (ISSUE 14): on by default at north-star
    # runs, SIMTPU_BENCH_SERVE=1 forces it at any configuration (`make
    # bench-serve` = the asserting smoke via tools/serve_loadgen.py), =0
    # skips
    serve_env = os.environ.get("SIMTPU_BENCH_SERVE", "")
    if serve_env != "0" and (north_star or serve_env == "1"):
        try:
            record.update(serve_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"serve point failed: {type(exc).__name__}: {exc}")
            record["serve_error"] = f"{type(exc).__name__}: {exc}"
    # trace-driven timeline replay (ISSUE 15): on by default at north-star
    # runs, SIMTPU_BENCH_TIMELINE=1 forces it at any configuration (`make
    # bench-timeline` = the small-shape asserting smoke), =0 skips
    timeline_env = os.environ.get("SIMTPU_BENCH_TIMELINE", "")
    if timeline_env != "0" and (north_star or timeline_env == "1"):
        try:
            record.update(timeline_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"timeline point failed: {type(exc).__name__}: {exc}")
            record["timeline_error"] = f"{type(exc).__name__}: {exc}"
    # round-16 scan/delta perf levers (ISSUE 16): on by default at
    # north-star runs, SIMTPU_BENCH_SCAN_SMOKE=1 forces it at any
    # configuration (`make bench-scan` = the small-shape asserting
    # smoke), =0 skips
    scan_env = os.environ.get("SIMTPU_BENCH_SCAN_SMOKE", "")
    if scan_env != "0" and (north_star or scan_env == "1"):
        try:
            record.update(scan_smoke_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"scan smoke point failed: {type(exc).__name__}: {exc}")
            record["scan_smoke_error"] = f"{type(exc).__name__}: {exc}"
    # round-20 warm-engine serving (append-only vocabulary growth): on by
    # default at north-star runs, SIMTPU_BENCH_GROW=1 forces it at any
    # configuration (`make bench-grow` = the asserting smoke), =0 skips
    grow_env = os.environ.get("SIMTPU_BENCH_GROW", "")
    if grow_env != "0" and (north_star or grow_env == "1"):
        try:
            record.update(grow_point())
        except Exception as exc:  # noqa: BLE001 - report, keep the line
            note(f"grow point failed: {type(exc).__name__}: {exc}")
            record["grow_error"] = f"{type(exc).__name__}: {exc}"
    # OOM-backoff telemetry (durable/backoff.py): process-lifetime
    # counters — nonzero only when a dispatch really hit
    # RESOURCE_EXHAUSTED (or the durable point injected one)
    record["backoff_events"] = REGISTRY.value("backoff.events")
    record["backoff_chunk_min"] = REGISTRY.value("backoff.chunk_min")
    # the full registry snapshot rides every point (ISSUE 8): the perf
    # trajectory's BENCH_*.json lines carry the unified metrics alongside
    # the derived headline numbers above
    record["metrics"] = REGISTRY.snapshot()
    print(json.dumps(record))
    # a failed plan/big/fault/layout/durable phase keeps the placement
    # record but signals the failure through the exit status (drivers
    # record both)
    return 1 if any(
        key in record
        for key in (
            "plan_error", "big_point_error", "fault_error", "layout_error",
            "durable_error", "audit_error", "obs_error", "explain_error",
            "serve_error", "timeline_error", "scan_smoke_error",
            "grow_error",
        )
    ) else 0


if __name__ == "__main__":
    if "--multihost-worker" in sys.argv:
        sys.exit(multihost_worker_main())
    if "--multihost" in sys.argv:
        sys.exit(multihost_point(sys.argv[1:]))
    if "--publish-multihost" in sys.argv:
        sys.exit(publish_multihost_main(sys.argv[1:]))
    sys.exit(main())
