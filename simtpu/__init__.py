"""simtpu — a TPU-native cluster simulator and capacity planner.

A ground-up JAX/XLA re-design of the capabilities of Open-Simulator
(`/root/reference`, alibaba/open-simulator): simulated all-or-nothing
deployment of Kubernetes app lists onto a modeled cluster, minimum-node-count
capacity planning, and per-node placement reports — with the kube-scheduler
replay loop replaced by batched tensor kernels scanning the pod axis.
"""

from .api import Simulator, simulate
from .core.objects import (
    AppResource,
    NodeStatus,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
)
from .obs.trace import init_from_env as _obs_init_from_env

# arm the span tracer when SIMTPU_TRACE asks for it (obs/trace.py; one
# env read when tracing is off — spans stay shared no-ops)
_obs_init_from_env()

__version__ = "0.1.0"

__all__ = [
    "AppResource",
    "NodeStatus",
    "ResourceTypes",
    "SchedulerConfig",
    "SimulateResult",
    "Simulator",
    "UnscheduledPod",
    "plan_capacity",
    "plan_resilience",
    "simulate",
    "__version__",
]


def __getattr__(name):
    # lazy: the planners pull in the full engine/parallel/faults stack
    if name == "plan_capacity":
        from .plan.capacity import plan_capacity

        return plan_capacity
    if name == "plan_resilience":
        from .plan.resilience import plan_resilience

        return plan_resilience
    if name == "SchedulerConfig":
        from .schedconfig import SchedulerConfig

        return SchedulerConfig
    raise AttributeError(name)
