"""simtpu — a TPU-native cluster simulator and capacity planner.

A ground-up JAX/XLA re-design of the capabilities of Open-Simulator
(`/root/reference`, alibaba/open-simulator): simulated all-or-nothing
deployment of Kubernetes app lists onto a modeled cluster, minimum-node-count
capacity planning, and per-node placement reports — with the kube-scheduler
replay loop replaced by batched tensor kernels scanning the pod axis.
"""

from .api import Simulator, simulate
from .core.objects import (
    AppResource,
    NodeStatus,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
)

__version__ = "0.1.0"

__all__ = [
    "AppResource",
    "NodeStatus",
    "ResourceTypes",
    "SimulateResult",
    "Simulator",
    "UnscheduledPod",
    "simulate",
    "__version__",
]
