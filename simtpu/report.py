"""Placement report tables.

Mirrors `report()` (`pkg/apply/apply.go:306-578`): Pod Info and Node Info
tables, plus Node Local Storage / GPU tables when the matching extended
resource is enabled. Rendered with a small built-in grid writer standing in
for tablewriter.
"""

from __future__ import annotations

import json
from typing import List, Sequence

from . import constants as C
from .core.objects import annotations_of, labels_of, name_of, namespace_of, pod_requests
from .core.quantity import format_quantity, parse_quantity


def render_table(header: List[str], rows: List[List[str]], merge_col0: bool = True) -> str:
    """ASCII grid with per-row separators and repeated-value merging in the
    first column (tablewriter's SetAutoMergeCellsByColumnIndex([0]))."""
    if merge_col0:
        prev = None
        merged = []
        for row in rows:
            row = list(row)
            if row and row[0] == prev:
                row[0] = ""
            else:
                prev = row[0]
            merged.append(row)
        rows = merged
    widths = [len(h) for h in header]
    for row in rows:
        for i, cell in enumerate(row):
            for line in str(cell).split("\n"):
                widths[i] = max(widths[i], len(line))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"

    def fmt(row):
        cells = [str(c).split("\n") for c in row]
        height = max(len(c) for c in cells) if cells else 1
        lines = []
        for k in range(height):
            parts = []
            for i, c in enumerate(cells):
                text = c[k] if k < len(c) else ""
                parts.append(f" {text:<{widths[i]}} ")
            lines.append("|" + "|".join(parts) + "|")
        return "\n".join(lines)

    out = [sep, fmt(header).upper(), sep]
    for row in rows:
        out.append(fmt(row))
        out.append(sep)
    return "\n".join(out)


def _pct(num: float, den: float) -> int:
    return int(num / den * 100) if den else 0


def resilience_report(sweep, top: int = 10) -> str:
    """Survivability tables for one fault sweep (`faults.sweep.SweepResult`):
    the per-kind scenario tally, the worst scenarios, and the single-node
    criticality ranking — the section `simtpu resilience` (and
    `simtpu apply --faults`) prints under the placement report."""
    out = ["Resilience"]
    by_kind: dict = {}
    for s in range(len(sweep.scenarios)):
        kind = sweep.scenarios.labels[s].split(":", 1)[0]
        rec = by_kind.setdefault(kind, [0, 0, 0])
        rec[0] += 1
        rec[1] += int(sweep.unplaced[s] == 0)
        rec[2] = max(rec[2], int(sweep.unplaced[s]))
    rows = [
        [kind, str(total), str(ok), f"{_pct(ok, total)}%", str(worst)]
        for kind, (total, ok, worst) in sorted(by_kind.items())
    ]
    out.append(
        render_table(
            ["Failure Kind", "Scenarios", "Survived", "Survival", "Max Unplaced"],
            rows,
            merge_col0=False,
        )
    )
    worst = sweep.worst(top)
    if worst:
        out.append("\nWorst Scenarios")
        out.append(
            render_table(
                ["Scenario", "Unplaced Pods"],
                [[lbl, str(n)] for lbl, n in worst],
                merge_col0=False,
            )
        )
    crit = sweep.critical_nodes(top)
    if crit:
        out.append("\nMost Critical Nodes")
        out.append(
            render_table(
                ["Node", "Pods Stranded By Its Loss"],
                [[node, str(n)] for node, n in crit],
                merge_col0=False,
            )
        )
    return "\n".join(out)


def audit_report(doc: dict) -> str:
    """Render one audit record (simtpu/audit `AuditReport.counters()` /
    a planner's `PlanResult.audit` doc) as the section the CLI prints
    under the placement report.

    Clean audits render one line; a dirty audit renders the per-violation
    witness table (pod, node, constraint class, witness values) and —
    when the divergence-safe fallback ran — the divergence diagnostic
    (first divergent pod, differing state planes) and the fallback's own
    verdict."""
    if not doc:
        return "Audit: not run (--no-audit)"
    out: List[str] = []
    if doc.get("fallback"):
        fb = doc.get("fallback_audit") or {}
        verdict = "certified" if fb.get("ok") else "NOT certified"
        out.append(
            f"Audit: PRIMARY ENGINE DIVERGED — {doc.get('violations', 0)} "
            f"violation(s) over {doc.get('checked', 0)} placements; "
            f"serial-exact fallback {verdict}"
        )
    elif doc.get("ok", False):
        return (
            f"Audit: clean ({doc.get('checked', 0)} placements certified, "
            f"{doc.get('wall_s', 0.0):.3f}s, {doc.get('mode', '?')} mode)"
        )
    else:
        out.append(
            f"Audit: FAILED — {doc.get('violations', 0)} violation(s) "
            f"over {doc.get('checked', 0)} placements"
        )
    detail = doc.get("detail") or []
    if detail:
        rows = [
            [
                v.get("class", ""),
                v.get("pod", ""),
                v.get("node", ""),
                ", ".join(f"{k}={w}" for k, w in (v.get("witness") or {}).items()),
            ]
            for v in detail
        ]
        out.append(
            render_table(
                ["Constraint Class", "Pod", "Node", "Witness"],
                rows,
                merge_col0=False,
            )
        )
    div = doc.get("divergence") or {}
    if div:
        lines = ["Divergence diagnostic:"]
        for key in (
            "divergent_pods",
            "first_divergent_row",
            "first_divergent_pod",
            "audited_node",
            "serial_node",
            "nodes_changed",
            "first_changed_node",
        ):
            if key in div and div[key] not in ("", None):
                lines.append(f"  {key}: {div[key]}")
        planes = div.get("state_planes") or []
        if planes:
            lines.append("  differing state planes: " + "; ".join(planes))
        out.append("\n".join(lines))
    return "\n".join(out)


def solve_report(doc: dict) -> str:
    """Render one global-solver consult record (`PlanResult.solve` /
    `ResiliencePlan.solve`, docs/solver.md) as a one-to-two line section
    under the placement report.

    Advisory mode means every status is a legitimate outcome: accepted
    answers name the certified count, everything else names why the
    exact search took over (and what warm start, if any, it inherited)."""
    if not doc or not doc.get("enabled"):
        return "Solver: not consulted (--no-solver / SIMTPU_SOLVER unset)"
    status = doc.get("status", "?")
    wall = doc.get("wall_s", 0.0)
    if status == "accepted":
        return (
            f"Solver: accepted — {doc.get('k', '?')} node(s), minimality "
            f"certified at k-1, residual {doc.get('residual', 0.0):.2e}, "
            f"{wall:.3f}s"
        )
    if status == "accepted_fallback":
        return (
            f"Solver: audit rejected the rounded placement — serial exact "
            f"engine re-placed at the certified count "
            f"{doc.get('k', '?')} ({wall:.3f}s)"
        )
    if status == "certified":  # lower-bound-only mode (resilience)
        return (
            f"Solver: certified lower bound {doc.get('lower_bound', 0)} "
            f"(warm-started the survivability search, {wall:.3f}s)"
        )
    out = [
        f"Solver: {status} — exact search answered "
        f"({doc.get('reason', 'no reason recorded')}, {wall:.3f}s)"
    ]
    if doc.get("certified_lb") and doc.get("lower_bound"):
        out.append(
            f"  certified lower bound {doc['lower_bound']} warm-started "
            "the exact search"
        )
    return "\n".join(out)


def _fmt_res(name: str, val: float) -> str:
    if name == "cpu":
        return format_quantity(val, "cpu")
    if name in ("memory", "ephemeral-storage"):
        return format_quantity(val, "mem")
    return f"{val:g}"


def explain_report(doc: dict) -> str:
    """Render one decision-observability record (simtpu/explain — the
    versioned `explain` block of `--json`) as the section `simtpu
    explain` and `--explain` print under the placement report.

    Three sub-sections, each present only when its data is: the per-pod
    failure breakdown (kube-scheduler-style status strings, grouped by
    identical failure shape), the binding-constraint bottleneck table,
    and the per-plugin score attribution rows."""
    if not doc:
        return "Explain: nothing to explain (no unplaced pods selected)"
    out: List[str] = []
    failures = doc.get("failures") or {}
    groups = failures.get("groups") or []
    if groups:
        out.append(
            f"Why Unschedulable ({failures.get('unplaced', 0)} pod(s), "
            f"{failures.get('n_nodes', 0)} node(s), "
            f"{failures.get('mode', '?')} pass)"
        )
        rows = []
        for g in groups:
            lines = [
                f"{cnt} {stage}"
                for stage, cnt in (g.get("stages") or {}).items()
            ]
            wit = g.get("witnesses") or {}
            wit_lines = [
                f"{stage}: {', '.join(names)}"
                for stage, names in wit.items()
                if names
            ]
            rows.append(
                [
                    str(g.get("pods", 0)),
                    g.get("example", ""),
                    g.get("status", ""),
                    "\n".join(lines),
                    "\n".join(wit_lines),
                ]
            )
        out.append(
            render_table(
                ["Pods", "Example", "Status", "Stage Counts", "Witness Nodes"],
                rows,
                merge_col0=False,
            )
        )
        if failures.get("truncated_groups"):
            out.append(
                f"... {failures['truncated_groups']} more failure shape(s) "
                "truncated (raise --top to see them)"
            )
    bottleneck = doc.get("bottleneck") or {}
    if bottleneck:
        scope = (
            f" — worst scenario {doc['worst_scenario']!r}"
            if doc.get("worst_scenario")
            else ""
        )
        out.append(
            "\nBottleneck (binding constraints over the unplaced set"
            f"{scope})"
        )
        by_reason = bottleneck.get("by_reason") or {}
        if by_reason:
            out.append(
                render_table(
                    ["Failure Reason", "Pods"],
                    [[r, str(n)] for r, n in by_reason.items()],
                    merge_col0=False,
                )
            )
        rows = [
            [
                r.get("resource", ""),
                _fmt_res(r.get("resource", ""), r.get("requested", 0.0)),
                _fmt_res(r.get("resource", ""), r.get("free", 0.0)),
                f"{r.get('share', 0.0):g}",
                "√" if r.get("fragmented") else "",
            ]
            for r in bottleneck.get("resources") or []
        ]
        if rows:
            out.append(
                render_table(
                    ["Resource", "Requested (unplaced)", "Free", "Share", "Fragmented"],
                    rows,
                    merge_col0=False,
                )
            )
        binding = bottleneck.get("binding")
        if binding:
            out.append(
                f"binding constraint: {binding.get('resource')} — unplaced "
                f"pods request "
                f"{_fmt_res(binding.get('resource', ''), binding.get('requested', 0.0))} "
                f"against "
                f"{_fmt_res(binding.get('resource', ''), binding.get('free', 0.0))} free"
            )
        out.append(
            f"failure shapes: {bottleneck.get('capacity_shaped', 0)} "
            "capacity-shaped (more/larger nodes can help), "
            f"{bottleneck.get('constraint_shaped', 0)} constraint-shaped "
            "(capacity alone cannot)"
        )
        template = bottleneck.get("template") or {}
        if template:
            line = (
                f"template verdict: {template.get('helpable', 0)} of "
                f"{template.get('probed', 0)} probed pod(s) could land on "
                "another template node"
            )
            if template.get("never_helpable"):
                line += (
                    f"; {template['never_helpable']} never can "
                    f"({template.get('never_reason', '')})"
                )
            if template.get("template_nodes_hint"):
                line += (
                    f"; resource deficit ≈ "
                    f"{template['template_nodes_hint']} template node(s)"
                )
            out.append(line)
    scores = doc.get("scores") or []
    if scores:
        out.append("\nScore Attribution (per-plugin decomposition)")
        rows = []
        for s in scores:
            top_terms = sorted(
                (t for t in s.get("terms") or [] if t.get("delta")),
                key=lambda t: -abs(t.get("delta") or 0.0),
            )[:3]
            rows.append(
                [
                    s.get("pod", ""),
                    s.get("node", ""),
                    s.get("runner_up", ""),
                    "" if s.get("margin") is None else f"{s['margin']:g}",
                    "\n".join(
                        f"{t['plugin']}: {t['delta']:+g} (w={t['weight']:g})"
                        for t in top_terms
                    ),
                    "" if s.get("consistent", True) else "recompute diverged",
                ]
            )
        out.append(
            render_table(
                ["Pod", "Node", "Runner-Up", "Margin", "Deciding Terms", "Note"],
                rows,
                merge_col0=False,
            )
        )
    if not out:
        return "Explain: nothing to explain (no unplaced pods selected)"
    return "\n".join(out)


def timeline_report(res, buckets: int = 12) -> str:
    """The `simtpu replay` tables (`timeline.replay.TimelineResult`):
    a bucketed utilization/pending time series, the admission/preemption
    tally, and the pending-time distribution — the continuous-time
    answers the one-shot report cannot give (docs/timeline.md)."""

    def dur(seconds: float) -> str:
        seconds = float(seconds)
        if seconds >= 5400:
            return f"{seconds / 3600:.1f}h"
        if seconds >= 120:
            return f"{seconds / 60:.1f}m"
        return f"{seconds:.0f}s"

    out = ["Timeline"]
    samples = res.samples
    if samples:
        # bucket the per-event samples into ~`buckets` rows, reporting
        # each bucket's LAST state (a level, not a flow) and peak pending
        step = max(len(samples) // buckets, 1)
        rows = []
        for b0 in range(0, len(samples), step):
            chunk = samples[b0: b0 + step]
            t, util, placed, _pending = chunk[-1]
            peak_pending = max(s[3] for s in chunk)
            rows.append(
                [dur(t), f"{util * 100:.1f}%", str(placed),
                 str(peak_pending)]
            )
        out.append(
            render_table(
                ["Sim Clock", "Utilization", "Placed Pods", "Peak Pending"],
                rows,
                merge_col0=False,
            )
        )
    c = res.counts
    out.append("\nAdmission")
    out.append(
        render_table(
            ["Counter", "Value"],
            [
                ["events", str(res.events)],
                ["arrivals (cron fires)",
                 f"{c['arrivals']} ({c['cron_fires']})"],
                ["gang admissions", str(c["admitted"])],
                ["gang rollbacks (all-or-nothing)",
                 str(c["gang_rollbacks"])],
                ["retries / dropped pods",
                 f"{c['retries']} / {c['dropped_pods']}"],
                ["preemptions (pods)",
                 f"{c['preemptions']} ({c['preempted_pods']})"],
                ["departures", str(c["departures"])],
                ["node down / up", f"{c['node_down']} / {c['node_up']}"],
                ["HPA scale up / down pods",
                 f"{c['scale_up_pods']} / {c['scale_down_pods']}"],
                ["pool nodes armed / disarmed",
                 f"{c['pool_up']} / {c['pool_down']}"],
            ],
            merge_col0=False,
        )
    )
    if res.pending_s:
        out.append("\nPending Time")
        out.append(
            render_table(
                ["P50", "P90", "Max", "Still Pending At End"],
                [[dur(res.pending_p50_s), dur(res.pending_p90_s),
                  dur(max(res.pending_s)), str(res.still_pending)]],
                merge_col0=False,
            )
        )
    rate = res.timings.get("events_per_s", 0.0)
    out.append(
        f"{res.events} event(s) replayed ({rate:.1f} events/s"
        + (", PARTIAL — interrupted)" if res.partial else ")")
    )
    return "\n".join(out)


def contain_local_storage(extended: Sequence[str]) -> bool:
    return "open-local" in extended


def contain_gpu(extended: Sequence[str]) -> bool:
    return "gpu" in extended


def report(node_statuses, extended_resources: Sequence[str] = ()) -> str:
    """Build the full report text (`pkg/apply/apply.go:306-578`)."""
    out = []
    with_storage = contain_local_storage(extended_resources)
    with_gpu = contain_gpu(extended_resources)

    # ---- Pod Info -------------------------------------------------------
    header = ["Node", "Pod", "CPU Requests", "Memory Requests"]
    if with_storage:
        header.append("Volume Request")
    if with_gpu:
        header.append("GPU Mem Requests")
    header.append("APP Name")
    rows = []
    for status in node_statuses:
        node = status.node
        alloc = ((node.get("status") or {}).get("allocatable")) or {}
        cpu_alloc = parse_quantity(alloc.get("cpu"))
        mem_alloc = parse_quantity(alloc.get("memory"))
        gpu_alloc = parse_quantity(alloc.get(C.RES_GPU_MEM))
        for pod in status.pods:
            req = pod_requests(pod)
            cpu = req.get("cpu", 0.0)
            mem = req.get("memory", 0.0)
            row = [
                name_of(node),
                f"{namespace_of(pod)}/{name_of(pod)}",
                f"{format_quantity(cpu, 'cpu')}({_pct(cpu, cpu_alloc)}%)",
                f"{format_quantity(mem, 'mem')}({_pct(mem, mem_alloc)}%)",
            ]
            if with_storage:
                vol_lines = []
                raw = annotations_of(pod).get(C.ANNO_POD_LOCAL_STORAGE)
                if raw:
                    vols = (json.loads(raw) or {}).get("volumes") or []
                    for i, vol in enumerate(vols):
                        size = parse_quantity(vol.get("size"))
                        vol_lines.append(f"<{i}> {vol.get('kind')}: {format_quantity(size, 'mem')}")
                row.append("\n".join(vol_lines))
            if with_gpu:
                annos = annotations_of(pod)
                gpu_mem = parse_quantity(annos.get(C.ANNO_POD_GPU_MEM, 0))
                gpu_cnt = parse_quantity(annos.get(C.ANNO_POD_GPU_COUNT, 0))
                total = gpu_mem * gpu_cnt
                row.append(f"{format_quantity(total, 'mem')}({_pct(total, gpu_alloc)}%)")
            row.append(labels_of(pod).get(C.LABEL_APP_NAME, ""))
            rows.append(row)
    out.append("Pod Info")
    out.append(render_table(header, rows))
    out.append("")

    # ---- Node Info ------------------------------------------------------
    header = ["Node", "CPU Allocatable", "CPU Requests", "Memory Allocatable", "Memory Requests"]
    if with_gpu:
        header += ["GPU Mem Allocatable", "GPU Mem Requests"]
    header += ["Pod Count", "New Node"]
    rows = []
    for status in node_statuses:
        node = status.node
        alloc = ((node.get("status") or {}).get("allocatable")) or {}
        cpu_alloc = parse_quantity(alloc.get("cpu"))
        mem_alloc = parse_quantity(alloc.get("memory"))
        cpu_req = mem_req = gpu_req = 0.0
        for pod in status.pods:
            req = pod_requests(pod)
            cpu_req += req.get("cpu", 0.0)
            mem_req += req.get("memory", 0.0)
            annos = annotations_of(pod)
            gpu_req += parse_quantity(annos.get(C.ANNO_POD_GPU_MEM, 0)) * parse_quantity(
                annos.get(C.ANNO_POD_GPU_COUNT, 0)
            )
        row = [
            name_of(node),
            format_quantity(cpu_alloc, "cpu"),
            f"{format_quantity(cpu_req, 'cpu')}({_pct(cpu_req, cpu_alloc)}%)",
            format_quantity(mem_alloc, "mem"),
            f"{format_quantity(mem_req, 'mem')}({_pct(mem_req, mem_alloc)}%)",
        ]
        if with_gpu:
            gpu_alloc = parse_quantity(alloc.get(C.RES_GPU_MEM))
            row += [
                format_quantity(gpu_alloc, "mem"),
                f"{format_quantity(gpu_req, 'mem')}({_pct(gpu_req, gpu_alloc)}%)",
            ]
        row += [
            str(len(status.pods)),
            "√" if C.LABEL_NEW_NODE in labels_of(node) else "",
        ]
        rows.append(row)
    out.append("Node Info")
    out.append(render_table(header, rows, merge_col0=False))
    out.append("")

    # ---- Extended Resource Info ----------------------------------------
    if with_storage or with_gpu:
        out.append("Extended Resource Info")
    if with_storage:
        out.append("Node Local Storage")
        rows = []
        for status in node_statuses:
            node = status.node
            raw = annotations_of(node).get(C.ANNO_NODE_LOCAL_STORAGE)
            if not raw:
                continue
            storage = json.loads(raw)
            for vg in storage.get("vgs") or []:
                cap = parse_quantity(vg.get("capacity"))
                req = parse_quantity(vg.get("requested"))
                rows.append(
                    [
                        name_of(node),
                        "VG",
                        vg.get("name", ""),
                        format_quantity(cap, "mem"),
                        f"{format_quantity(req, 'mem')}({_pct(req, cap)}%)",
                    ]
                )
            for dev in storage.get("devices") or []:
                cap = parse_quantity(dev.get("capacity"))
                used = "used" if str(dev.get("isAllocated")).lower() == "true" else "unused"
                rows.append(
                    [
                        name_of(node),
                        f"Device({dev.get('mediaType')})",
                        dev.get("device", ""),
                        format_quantity(cap, "mem"),
                        used,
                    ]
                )
        out.append(
            render_table(
                ["Node", "Storage Kind", "Storage Name", "Storage Allocatable", "Storage Requests"],
                rows,
            )
        )
    if with_gpu:
        out.append("GPU Node Resource")
        rows = []
        pod_rows = []
        for status in node_statuses:
            node = status.node
            raw = annotations_of(node).get(C.ANNO_NODE_GPU_SHARE)
            if raw:
                info = json.loads(raw)
                model = labels_of(node).get(C.LABEL_GPU_CARD_MODEL, "N/A")
                total = info.get("gpuTotalMemory", 0)
                used = info.get("gpuUsedMemory", 0)
                rows.append(
                    [
                        f"{name_of(node)} ({model})",
                        f"{info.get('gpuCount', 0)} GPUs",
                        f"{format_quantity(used, 'mem')}/{format_quantity(total, 'mem')}"
                        f"({_pct(used, total)}%)",
                        f"{info.get('numPods', 0)} Pods",
                    ]
                )
                for idx, dev in sorted((info.get("devs") or {}).items(), key=lambda kv: int(kv[0])):
                    dtotal, dused = dev.get("gpuTotalMemory", 0), dev.get("gpuUsedMemory", 0)
                    rows.append(
                        [
                            f"{name_of(node)} ({model})",
                            str(idx),
                            f"{format_quantity(dused, 'mem')}/{format_quantity(dtotal, 'mem')}"
                            f"({_pct(dused, dtotal)}%)",
                            "",
                        ]
                    )
            for pod in status.pods:
                annos = annotations_of(pod)
                req = pod_requests(pod)
                gpu_mem = parse_quantity(annos.get(C.ANNO_POD_GPU_MEM, 0))
                gpu_cnt = parse_quantity(annos.get(C.ANNO_POD_GPU_COUNT, 0))
                pod_rows.append(
                    [
                        name_of(pod),
                        format_quantity(req.get("cpu", 0.0), "cpu"),
                        format_quantity(req.get("memory", 0.0), "mem"),
                        format_quantity(gpu_mem * gpu_cnt, "mem"),
                        (pod.get("spec") or {}).get("nodeName", ""),
                        annos.get(C.ANNO_POD_GPU_INDEX, ""),
                    ]
                )
        out.append(render_table(["Node", "GPU ID", "GPU Request/Capacity", "Pod List"], rows))
        out.append("\nPod -> Node Map")
        pod_rows.sort(key=lambda r: r[0])
        out.append(
            render_table(
                ["Pod", "CPU Req", "Mem Req", "GPU Req", "Host Node", "GPU IDX"],
                pod_rows,
                merge_col0=False,
            )
        )
    return "\n".join(out)
