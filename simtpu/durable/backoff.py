"""OOM-aware adaptive chunk backoff: shared predicate + telemetry.

The chunk dispatchers (the serial scan's pow2 segments in
`engine/scan.run_scan_chunked`, the bulk stretch chunks in
`engine/rounds.RoundsEngine._dispatch`, and the scenario blocks in
`faults/sweep.sweep_scenarios`) catch an XLA allocation failure, halve the
failed chunk, and replay it from the same carried state.  Correctness
rests on the chunking contracts those loops already pin:

- serial scan segments are serial-EQUIVALENT — a chunk boundary never
  changes a per-pod step, so any split of [a, b) replays to bit-identical
  placements;
- bulk backoff splits a chunk's SEGMENT list, never a segment: each run
  still dispatches as its own consecutive rounds in the same order (the
  round-start normalizers see the same state), so placements are
  bit-identical.  A single round too large for memory propagates — a
  mid-run split would move the normalizer boundary (the MAX_RUN contract);
- fault-sweep scenario rows are independent — any block split is exact.

Halved sizes stay powers of two, so retries re-snap into the existing
shape buckets (PR 1) instead of tracing a fresh executable per shrink:
at most log2(chunk) new shapes can ever appear under backoff.

Donation caveat (docs/robustness.md): the dispatchers donate their
carried state.  An allocation failure raised while XLA sets up the launch
(the common RESOURCE_EXHAUSTED shape, and the injected-failure tests)
leaves the donated buffers intact, so the replay reuses them; a failure
after execution started invalidates them, in which case the replay's own
error propagates and `Engine.place`'s dirty-carry guard rebuilds from the
placement log on the next call.
"""

from __future__ import annotations

import threading

from ..obs.metrics import REGISTRY
from ..obs.trace import instant

#: Monotone process-wide counters: "events" RESOURCE_EXHAUSTED catches,
#: "splits" sub-dispatches created by the halving replays, "chunk_min"
#: the smallest chunk/block size any backoff re-dispatched at (0 = no
#: backoff yet).  Backing store since ISSUE 8: registry counters
#: `backoff.events`/`backoff.splits` plus the `backoff.chunk_min` gauge
#: (a process-lifetime floor, not a flow) — read them via
#: `obs.metrics.family("backoff", BACKOFF_KEYS)` (the legacy
#: `backoff_counts()` alias view is gone).
BACKOFF_KEYS = ("events", "splits", "chunk_min")
_EVENTS = REGISTRY.counter("backoff.events")
_SPLITS = REGISTRY.counter("backoff.splits")
_CHUNK_MIN = REGISTRY.gauge("backoff.chunk_min")
# the chunk_min floor is a read-modify-write over a gauge — concurrent
# OOMs (scan loop + fault sweep on different threads) need the whole RMW
# atomic, not just each instrument op
_MIN_LOCK = threading.Lock()

#: substrings that identify an allocator failure across jaxlib versions
#: (XlaRuntimeError renders the status code name; older paths render the
#: allocator message) — and the injected test fakes, by contract
_MARKERS = ("RESOURCE_EXHAUSTED", "resource exhausted", "out of memory")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA RESOURCE_EXHAUSTED / allocation failures (and the
    injected test fakes, which carry the same marker in their message).
    Deliberately message-based: jaxlib's XlaRuntimeError carries no typed
    status code, and the class itself moved modules across versions."""
    msg = str(exc).lower()
    return any(m.lower() in msg for m in _MARKERS)


def record_backoff(size_from: int, size_to: int) -> None:
    """Count one caught RESOURCE_EXHAUSTED that split a chunk of
    `size_from` into replays of `size_to`."""
    _EVENTS.inc()
    _SPLITS.inc(2)
    with _MIN_LOCK:
        lo = _CHUNK_MIN.value
        _CHUNK_MIN.set(int(size_to) if lo == 0 else min(lo, int(size_to)))
    # point event on the span timeline: OOM backoffs are exactly the
    # anomalies a post-mortem trace read hunts for
    instant("backoff.oom", size_from=int(size_from), size_to=int(size_to))
