"""Versioned on-disk plan checkpoints.

One checkpoint directory holds one plan's search progress: a
`manifest.json` (format version, planner kind, config/cluster
fingerprint, record index) plus one `.npz` per completed search candidate
(its placement vectors and verdict scalars).  Every write is atomic
(tmp + os.replace), and the manifest is rewritten after each record — a
kill at ANY point leaves a loadable checkpoint describing exactly the
candidates that completed.  Transient filesystem errors (EINTR, rename
races between concurrent writers) get one jittered retry before
surfacing as `CheckpointError`; ENOSPC fails immediately and loudly
(`_retry_transient`).

Resume contract: the planners re-run their deterministic search, and
every candidate with a record returns its persisted outcome instead of
dispatching — so the resumed `PlanResult` is bit-identical to an
uninterrupted run (pinned by tests/test_durable.py).  Bit-identity rests
on two existing pins: candidate evaluation is deterministic given the
ingest objects, and an engine carry rebuilt from the placement log equals
the dispatched carry (the donated-state reuse guard tests).

The fingerprint refuses cross-problem resumes loudly: it hashes the RAW
ingest objects (cluster / apps / new-node manifests, before expansion —
pod-name hash suffixes are random per process and deliberately excluded)
plus the options that steer the search (engine selection, occupancy caps,
fault spec...).  A mismatch raises `CheckpointMismatch` instead of
silently replaying records from a different problem.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import random
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..obs.trace import span

#: bump when the record layout changes; older checkpoints refuse to resume
CHECKPOINT_VERSION = 1

_MANIFEST = "manifest.json"

#: jitter window (seconds) before the single transient-error retry — the
#: write path is contended by design (a daemon's session store checkpoints
#: from worker threads), so an immediate retry would replay the same race
_RETRY_JITTER_S = (0.005, 0.05)


class CheckpointError(ValueError):
    """The checkpoint directory or its records are unusable — an
    unwritable or non-directory `--checkpoint` path, an empty/corrupt
    manifest, or an unreadable record file.  Raised UP FRONT (path
    problems surface before any planning work) and rendered as one
    actionable line by the CLI, never a mid-plan traceback
    (docs/robustness.md)."""


class CheckpointMismatch(ValueError):
    """The checkpoint on disk does not match this plan (format version,
    planner kind, or config/cluster fingerprint) — resuming would replay
    records from a different problem, so we refuse loudly."""


def _is_transient(exc: BaseException, racy: bool) -> bool:
    """Filesystem errors worth ONE retry.

    EINTR: defense-in-depth.  CPython's PEP 475 auto-retries
    syscalls when a signal handler returns normally (so the flag-setting
    handlers of durable/deadline.py never surface it), but handlers that
    RAISE, non-CPython-controlled callers, and exotic filesystems can
    still deliver it — and swallowing one spurious EINTR costs a jittered
    sleep, while surfacing it costs an operator a failed plan.

    ENOENT, on the WRITE path only (`racy`): the shape of an
    atomic-write race against concurrent directory surgery — a session
    DELETE (serve/session.py rmtree) or checkpoint-dir cleanup sweeping
    the tmp file between write and rename.  Re-running the whole write
    transaction is exact (the payload is deterministic); if the
    directory itself is gone the retry fails too and surfaces as one
    CheckpointError line.  (Writers never share tmp NAMES — `_tmp_path`
    is writer-unique — so this is about the directory, not the file.)
    ENOENT on the read path stays a real missing-record error.

    ENOSPC is deliberately not transient: retrying a full disk only
    delays the loud failure the operator needs to see."""
    if not isinstance(exc, OSError):
        return False
    return exc.errno == errno.EINTR or (racy and exc.errno == errno.ENOENT)


def _tmp_path(path: str) -> str:
    """A writer-unique tmp name for the atomic write: concurrent writers
    of the same record (a daemon's worker threads, two processes sharing
    a checkpoint dir) must never share one tmp file, or one writer's
    os.replace could publish the other's half-written bytes — breaking
    the 'a kill at ANY point leaves a loadable checkpoint' guarantee.
    Stale tmps from killed writers are harmless: the manifest is the
    index, and resume never reads unindexed files."""
    return f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"


def _retry_transient(what: str, fn, racy: bool = True):
    """Run one filesystem transaction with a single jittered retry on
    transient errors (ISSUE 14 satellite, pinned by
    tests/test_durable.py):

    - ENOSPC surfaces IMMEDIATELY as a loud `CheckpointError` — no retry;
    - EINTR / rename-race ENOENT gets exactly one retry after a small
      random sleep; a second failure surfaces as `CheckpointError` (one
      actionable line, never a raw OSError mid-plan);
    - every other error propagates untouched for the caller's own
      handling."""

    def _enospc(exc: OSError) -> CheckpointError:
        return CheckpointError(
            f"checkpoint: no space left on device while {what}; free "
            "disk space and re-run (the checkpoint directory may hold a "
            "partial .tmp file, which is ignored on resume)"
        )

    try:
        return fn()
    except OSError as exc:
        if exc.errno == errno.ENOSPC:
            raise _enospc(exc) from exc
        if not _is_transient(exc, racy):
            raise
        time.sleep(random.uniform(*_RETRY_JITTER_S))
        try:
            return fn()
        except OSError as exc2:
            if exc2.errno == errno.ENOSPC:
                raise _enospc(exc2) from exc2
            if _is_transient(exc2, racy):
                raise CheckpointError(
                    f"checkpoint: {what} failed twice on a transient "
                    f"filesystem error ({exc2}); check the checkpoint "
                    "directory's filesystem and re-run"
                ) from exc2
            raise


def file_digest(path: Optional[str]) -> str:
    """Content digest of a config file for fingerprint `extra` entries
    ("" when no path).  Hashing the CONTENT, not the path: editing e.g.
    the scheduler-config between a kill and a --resume must change the
    fingerprint and refuse, even though the path string is unchanged."""
    if not path:
        return ""
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _strip_provenance(obj):
    """Drop the YAML loader's source-file stamp (`expand.SOURCE_KEY`)
    from ingest objects before hashing: the stamp varies with how the
    config path was spelled (relative vs absolute, cwd), and the
    fingerprint must identify the PROBLEM, not the path to it."""
    from ..workloads.expand import SOURCE_KEY

    if isinstance(obj, list):
        return [_strip_provenance(x) for x in obj]
    if isinstance(obj, dict):
        # the loader stamps top-level only, but recurse anyway — one
        # nested copy leaking in later must not silently split problems
        return {
            k: _strip_provenance(v) for k, v in obj.items()
            if k != SOURCE_KEY
        }
    return obj


def plan_fingerprint(cluster, apps, new_node: Optional[dict], extra: Optional[dict] = None) -> str:
    """Config/cluster fingerprint of one planning problem.

    Hashes the raw ingest objects (pre-expansion: the YAML-shaped dicts,
    stable across processes; manifest-path provenance stamps stripped)
    and the search-steering options in `extra`.  Two runs with equal
    fingerprints walk the same candidate sequence and produce identical
    per-candidate outcomes — the precondition for replaying checkpoint
    records.
    """
    h = hashlib.sha256()

    def upd(tag: str, obj) -> None:
        h.update(tag.encode())
        h.update(b"\x00")
        h.update(
            json.dumps(
                _strip_provenance(obj), sort_keys=True, default=str
            ).encode()
        )
        h.update(b"\x01")

    upd("cluster", {k: v for k, v in sorted(vars(cluster).items())})
    for app in apps:
        upd(f"app:{app.name}", {k: v for k, v in sorted(vars(app.resource).items())})
    upd("new_node", new_node or {})
    upd("extra", extra or {})
    return h.hexdigest()


def name_seed(fingerprint: str, cand: int = 0) -> int:
    """Deterministic pod-name-suffix stream seed for one checkpointed
    candidate evaluation.

    Generated pod names carry a random hash suffix drawn from a process-
    global stream (`workloads.expand`), so the same candidate evaluated at
    a different stream position — a resumed run skips the recorded
    candidates — would expand differently-named pods.  Checkpointed plans
    therefore re-seed the stream per candidate from (fingerprint, cand):
    every candidate's expansion becomes a pure function of the problem,
    and a resumed run is bit-identical to the uninterrupted one INCLUDING
    pod names, across processes."""
    h = hashlib.sha256(f"{fingerprint}:{int(cand)}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class PlanCheckpoint:
    """Record store for one plan's completed search candidates.

    `get(phase, cand)` returns the persisted record dict (numpy arrays /
    0-d scalars) or None; `put(phase, cand, **entries)` persists one
    atomically and updates the manifest — "persist after each completed
    candidate" is exactly one `put` per candidate.  Records are keyed by
    (phase, candidate index), phases being planner-defined ("base",
    "probe", "verify", "cand", ...).
    """

    def __init__(
        self,
        directory: str,
        kind: str,
        fingerprint: str,
        resume: bool = False,
    ):
        self.directory = directory
        self.kind = kind
        self.fingerprint = fingerprint
        self._records: Dict[str, str] = {}  # "phase:cand" -> npz filename
        # fail UP FRONT on an unusable path — before any planning work,
        # not as an OSError traceback when the first candidate persists
        if os.path.exists(directory) and not os.path.isdir(directory):
            raise CheckpointError(
                f"--checkpoint: {directory!r} exists and is not a "
                "directory; pass a directory path"
            )
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError as exc:
            raise CheckpointError(
                f"--checkpoint: cannot create {directory!r} ({exc.strerror or exc}); "
                "pass a writable directory"
            ) from exc
        if not os.access(directory, os.W_OK):
            raise CheckpointError(
                f"--checkpoint: {directory!r} is not writable; "
                "pass a writable directory"
            )
        self._sweep_stale_tmps()
        mpath = os.path.join(directory, _MANIFEST)
        if resume:
            if not os.path.isfile(mpath):
                raise CheckpointMismatch(
                    f"--resume: no checkpoint manifest under {directory!r}"
                )
            try:
                with open(mpath) as f:
                    man = json.load(f)
            except (json.JSONDecodeError, UnicodeDecodeError, OSError) as exc:
                raise CheckpointError(
                    f"--resume: checkpoint manifest {mpath!r} is empty or "
                    f"corrupt ({exc}); delete the checkpoint directory and "
                    "re-run without --resume"
                ) from exc
            if man.get("version") != CHECKPOINT_VERSION:
                raise CheckpointMismatch(
                    f"checkpoint format v{man.get('version')} != "
                    f"v{CHECKPOINT_VERSION}; refusing to resume"
                )
            if man.get("kind") != kind:
                raise CheckpointMismatch(
                    f"checkpoint was written by the {man.get('kind')!r} "
                    f"planner, this run selected {kind!r}; refusing to "
                    "resume (pass the same engine flags)"
                )
            if man.get("fingerprint") != fingerprint:
                raise CheckpointMismatch(
                    "checkpoint config/cluster fingerprint mismatch: the "
                    "records under "
                    f"{directory!r} were written for a different problem "
                    "or different options; refusing to resume"
                )
            self._records = dict(man.get("records") or {})
        else:
            # fresh plan: start a clean index (stale record files from an
            # unrelated plan are harmless — the manifest is the index)
            self._write_manifest()

    #: tmp files older than this are orphans from a killed writer and
    #: are swept at checkpoint open; younger ones may belong to a LIVE
    #: concurrent writer (the rename-race scenario) and are left alone
    STALE_TMP_S = 300.0

    def _sweep_stale_tmps(self) -> None:
        """Best-effort cleanup of orphaned `*.tmp` files: writer-unique
        tmp names (`_tmp_path`) mean a kill mid-write leaves a file no
        later writer ever reuses, so without this sweep a crash-looping
        process would grow the directory monotonically."""
        cutoff = time.time() - self.STALE_TMP_S
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for name in entries:
            if not name.endswith(".tmp"):
                continue
            path = os.path.join(self.directory, name)
            try:
                if os.path.getmtime(path) < cutoff:
                    os.unlink(path)
            except OSError:
                continue  # raced away or unreadable — someone else's

    # -- record IO --------------------------------------------------------

    @staticmethod
    def _key(phase: str, cand: int) -> str:
        return f"{phase}:{int(cand)}"

    def get(self, phase: str, cand: int) -> Optional[dict]:
        """The persisted record for (phase, cand), or None.  Values load
        as numpy arrays (scalars as 0-d arrays; strings as 0-d unicode)."""
        fname = self._records.get(self._key(phase, cand))
        if fname is None:
            return None
        path = os.path.join(self.directory, fname)
        def read():
            with np.load(path, allow_pickle=False) as z:
                return {k: z[k] for k in z.files}

        try:
            with span("checkpoint.get", phase=phase, cand=int(cand)):
                # EINTR-only retry on the read path (racy=False): a
                # missing record file is a real error, not a race
                return _retry_transient(
                    f"reading record {fname!r}", read, racy=False
                )
        except CheckpointError:
            raise
        except (OSError, ValueError, KeyError, EOFError) as exc:
            # a truncated/empty/garbage record (a kill mid-rename window,
            # disk-full, manual edits) must read as ONE actionable line,
            # not a zipfile traceback mid-plan
            raise CheckpointError(
                f"--resume: checkpoint record {path!r} is empty or corrupt "
                f"({exc}); delete it (or the whole checkpoint directory) "
                "and re-run"
            ) from exc

    def put(self, phase: str, cand: int, **entries) -> None:
        """Persist one completed candidate's record atomically and index
        it in the manifest (also rewritten atomically)."""
        key = self._key(phase, cand)
        fname = f"rec_{phase}_{int(cand)}.npz"
        path = os.path.join(self.directory, fname)
        tmp = _tmp_path(path)
        with span("checkpoint.put", phase=phase, cand=int(cand)) as sp:

            def write():
                # the whole transaction re-runs on a transient retry —
                # rewriting the tmp file is what makes an ENOENT rename
                # race (the tmp was renamed/swept by the racing writer)
                # recoverable
                with open(tmp, "wb") as f:
                    np.savez_compressed(
                        f, **{k: np.asarray(v) for k, v in entries.items()}
                    )
                sp.set(bytes=os.path.getsize(tmp))
                os.replace(tmp, path)

            _retry_transient(f"writing record {fname!r}", write)
            self._records[key] = fname
            self._write_manifest()

    def __len__(self) -> int:
        return len(self._records)

    def _write_manifest(self) -> None:
        mpath = os.path.join(self.directory, _MANIFEST)
        tmp = _tmp_path(mpath)

        def write():
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "version": CHECKPOINT_VERSION,
                        "kind": self.kind,
                        "fingerprint": self.fingerprint,
                        "records": self._records,
                    },
                    f,
                    indent=1,
                )
            os.replace(tmp, mpath)

        _retry_transient("writing the manifest", write)
