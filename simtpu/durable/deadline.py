"""Deadline + interrupt safety for long plans.

`RunControl` is the cooperative cancellation token the planners poll
between search candidates (`control.check()`): a wall-clock deadline or a
delivered SIGINT/SIGTERM turns the NEXT check into a `PlanInterrupted`,
which the planners catch to flush a final checkpoint and return a
structured partial result (`PlanResult.partial`) instead of dying with a
traceback.  SIGTERM gets the same first-signal grace as ^C because that
is what daemons, `timeout(1)`, and CI runners actually send.

Polling granularity is the candidate boundary by design: a candidate's
placement is one pipelined device workload (interrupting it mid-flight
would discard it anyway), and every completed candidate is exactly what
the checkpoint persists — so the deadline can overshoot by at most one
candidate's wall-clock, documented in docs/robustness.md.
"""

from __future__ import annotations

import contextlib
import signal
import time
from typing import Optional


class PlanInterrupted(RuntimeError):
    """A plan was cooperatively interrupted (deadline or SIGINT).  The
    planners catch this and produce a partial PlanResult; it escaping to
    the user is a bug."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def partial_message(
    reason: str,
    best: Optional[int],
    checkpoint=None,
    what: str = "plan",
    none_note: str = "no feasible candidate found yet",
) -> str:
    """The one partial-result message all three planners emit — drivers
    parse it, so the wording lives in exactly one place."""
    note = f"best candidate so far: {best} node(s)" if best is not None else none_note
    msg = f"{what} interrupted ({reason}): {note}"
    if checkpoint is not None:
        msg += f"; checkpoint flushed to {checkpoint.directory}"
    return msg


class RunControl:
    """Cooperative deadline/interrupt token threaded through a plan.

    `deadline` is seconds from construction (None = none).  `trigger()`
    flags an external interrupt (the SIGINT handler calls it); the next
    `check()` raises `PlanInterrupted`.  Construction is cheap and the
    object is single-plan: the deadline clock starts at __init__.
    """

    def __init__(self, deadline: Optional[float] = None):
        self._t0 = time.monotonic()
        self.deadline = deadline
        self._interrupt: Optional[str] = None

    @property
    def interrupted(self) -> Optional[str]:
        return self._interrupt

    def trigger(self, reason: str = "interrupt") -> None:
        self._interrupt = reason

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() - self._t0)

    def check(self) -> None:
        """Raise PlanInterrupted when flagged or past the deadline."""
        if self._interrupt is not None:
            raise PlanInterrupted(self._interrupt)
        rem = self.remaining()
        if rem is not None and rem <= 0:
            raise PlanInterrupted(
                f"deadline of {self.deadline:g}s exceeded"
            )

    #: the signals sigint() makes cooperative.  SIGTERM rides along
    #: because daemons and CI runners send it where a human sends ^C —
    #: without the handler it kills the process with no partial result,
    #: no flushed checkpoint, and no flight bundle (docs/robustness.md).
    SIGNALS = (signal.SIGINT, signal.SIGTERM)

    @contextlib.contextmanager
    def sigint(self):
        """Install SIGINT *and* SIGTERM handlers that flag this control
        (first delivery of either = graceful partial result; a second
        delivery = KeyboardInterrupt so a stuck run can still be killed).
        Restores the previous handlers on exit.  No-op outside the main
        thread (signal.signal refuses there — library callers on worker
        threads just don't get the handlers)."""

        def handler(signum, frame):
            if self._interrupt is not None:
                raise KeyboardInterrupt
            self.trigger(signal.Signals(signum).name)

        prev = {}
        try:
            for sig in self.SIGNALS:
                prev[sig] = signal.signal(sig, handler)
        except ValueError:
            # not the main thread: signal.signal refuses EVERY call
            # there, so the first one failed and nothing was installed
            yield self
            return
        try:
            yield self
        finally:
            for sig, old in prev.items():
                signal.signal(sig, old)
