"""Durable execution: checkpoint/resume, OOM-aware backoff, deadlines.

The planners and dispatch loops are minutes-to-hours long at the
north-star shape, and before this package every run was all-or-nothing: a
device RESOURCE_EXHAUSTED, a SIGINT, or a wall-clock limit threw away all
completed search candidates, every placed chunk, and the warm AOT
registry.  Three independent levers (docs/robustness.md):

- `checkpoint`  — versioned on-disk plan checkpoints (`PlanCheckpoint`):
  every completed search candidate's placement record persists under
  `--checkpoint DIR`, and `--resume` replays the search from the records,
  producing a `PlanResult` bit-identical to an uninterrupted run.  A
  config/cluster fingerprint mismatch refuses loudly
  (`CheckpointMismatch`).
- `backoff`     — the chunk dispatchers (engine/scan.py, engine/rounds.py,
  faults/sweep.py) catch XLA RESOURCE_EXHAUSTED, halve the chunk /
  scenario-block size, and replay the failed chunk; placements are
  chunk-size-invariant by construction, so results stay bit-identical.
  The `backoff.*` registry instruments (obs/metrics.py) are the
  telemetry the bench and `--json` report.
- `deadline`    — `RunControl` turns `--deadline SECONDS` and SIGINT into
  a `PlanInterrupted` raised between candidates; the planners flush a
  final checkpoint and return a structured partial result
  (`PlanResult.partial`) instead of a traceback.
"""

from .backoff import is_resource_exhausted, record_backoff
from .checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointError,
    CheckpointMismatch,
    PlanCheckpoint,
    name_seed,
    plan_fingerprint,
)
from .deadline import PlanInterrupted, RunControl

__all__ = [
    "CHECKPOINT_VERSION",
    "CheckpointError",
    "CheckpointMismatch",
    "PlanCheckpoint",
    "PlanInterrupted",
    "RunControl",
    "is_resource_exhausted",
    "name_seed",
    "plan_fingerprint",
    "record_backoff",
]
