"""Decision observability: *why* did the scheduler do what it did.

The reference's whole value proposition is answering why — its capacity
loop emits per-pod failure diagnostics (`apply.go:213-231` →
`utils.NodeShouldRunPod`) and kube-scheduler renders per-node filter
verdicts into the "0/N nodes are available: 3 Insufficient cpu, ..."
status string.  The engine (simtpu/engine) compresses every failure into
ONE coarse code (`StepEval.fail_code` — the first mask stage that emptied
the candidate set); this package rebuilds the full per-node story on top
of the PR-8 observability plumbing:

- `breakdown` — one jitted, vmapped [P, N] explanation pass re-evaluates
  every unplaced pod's full filter cascade (reusing `StepEval`'s stage
  masks via `filter_and_score`) against a carried state, yielding per-pod
  × per-stage node-elimination counts, capped per-node witnesses, and the
  exact kube-scheduler-style status string.  A pure-numpy twin
  (`SIMTPU_EXPLAIN_JIT=0`, the audit/checker.py pattern) pins the counts.
- `scores` — per-plugin decomposition of a placed pod's winning score,
  with the runner-up node and margin: the weight-sensitivity surface a
  scoring-tuning harness optimizes over.
- `bottleneck` — binding-constraint analysis over an unplaced set: which
  resource (or constraint class) is binding, whether another template
  node can ever help, and a what-to-buy hint for infeasible plans.

Surfaces: `simtpu explain`, `--explain` on apply/resilience, the
versioned `explain` block in `--json`, `report.explain_report` tables,
`explain.*` metrics + `explain.pass` spans on the PR-8 registry, and the
flight recorder's top-K failure bundle on exit 3/4.  The off path is
zero-cost: nothing here imports or dispatches unless explanation was
requested (pinned by tests/test_explain.py via `compile.*`/`fetch.*`
registry deltas).
"""

from .breakdown import (
    EXPLAIN_VERSION,
    STAGES,
    FailureBreakdown,
    build_explain_doc,
    explain_failures,
    jit_enabled,
)
from .bottleneck import bottleneck_analysis
from .scores import attribute_scores, extras_from_log

__all__ = [
    "EXPLAIN_VERSION",
    "STAGES",
    "FailureBreakdown",
    "attribute_scores",
    "bottleneck_analysis",
    "build_explain_doc",
    "explain_failures",
    "extras_from_log",
    "jit_enabled",
]
