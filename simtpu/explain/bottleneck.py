"""Binding-constraint analysis: *what to buy*, not just *how many*.

When a plan is infeasible (or a placement strands pods), the planners
today answer "we added K nodes and it still failed" plus a first-pod
diagnosis.  This module aggregates over the WHOLE unplaced set:

- per-resource pressure: total requested by the unplaced pods vs total
  free on the valid nodes, the dominant (binding) resource, and a
  fragmentation signal (the largest single-pod request vs the largest
  single-node free block — aggregate room with no node big enough);
- constraint-class split: how many failures are resource-shaped (more
  capacity helps) vs topology/affinity/storage-shaped (capacity alone
  cannot help);
- the template verdict: folds the planners' existing `diagnose` logic
  (`node_should_run_pod` + `meet_resource_requests`) over the unplaced
  set — how many pods another template clone could EVER host — and, when
  a resource deficit exists, a template-node count hint
  (ceil(deficit / template capacity), the "what to buy" number).

Everything here is host-side numpy over arrays the planners already
hold — no device dispatches, so attaching it to a failing plan is free
relative to the plan itself.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..engine.scan import (
    FAIL_ATTACH,
    FAIL_GPU,
    FAIL_PORTS,
    FAIL_RESOURCES,
    FAIL_STORAGE,
    FAIL_VOLUME,
    REASON_TEXT,
)
from ..obs.trace import span

#: failure classes where buying capacity (more/larger nodes) can help;
#: everything else (selector/affinity/spread/volume-bind) is a
#: constraint-shaped failure capacity alone cannot fix
_CAPACITY_SHAPED = {
    FAIL_RESOURCES,
    FAIL_STORAGE,
    FAIL_GPU,
    FAIL_PORTS,
    FAIL_VOLUME,
    FAIL_ATTACH,
}

#: unplaced pods probed against the template (a handful decides the
#: verdict; the cap is reported, never silent)
_TEMPLATE_PROBE_CAP = 64


def bottleneck_analysis(
    tensors,
    batch,
    nodes_arr: np.ndarray,
    reasons: np.ndarray,
    *,
    rows: Optional[Sequence[int]] = None,
    node_valid: Optional[np.ndarray] = None,
    new_node: Optional[dict] = None,
    daemon_sets: Sequence[dict] = (),
    corrected_ds_overhead: bool = False,
    free: Optional[np.ndarray] = None,
) -> Dict[str, object]:
    """The binding-constraint record for one placement's unplaced set.

    `rows` restricts the unplaced set (planners pass the non-phantom
    failures); `node_valid` the candidate cluster's membership mask.
    With `new_node` (the template), the can-another-node-ever-help
    verdict and the node-count hint are folded in.  `free` overrides the
    free-capacity matrix ([N, R] — e.g. a carried state's `free` plane,
    which also accounts placements `nodes_arr` cannot see: a probe run
    resumed from a base snapshot, a preemption-surgered log)."""
    with span("explain.bottleneck"):
        return _bottleneck(
            tensors, batch, nodes_arr, reasons, rows, node_valid,
            new_node, daemon_sets, corrected_ds_overhead, free,
        )


def _bottleneck(
    tensors, batch, nodes_arr, reasons, rows, node_valid,
    new_node, daemon_sets, corrected_ds_overhead, free,
) -> Dict[str, object]:
    nodes_arr = np.asarray(nodes_arr)
    reasons = np.asarray(reasons)
    if rows is None:
        rows = np.flatnonzero(nodes_arr < 0)
    else:
        rows = np.asarray(list(rows), np.int64)
    if not len(rows):
        return {}
    n, r = tensors.alloc.shape
    valid = (
        np.ones(n, bool) if node_valid is None else np.asarray(node_valid, bool)
    )
    req_pad = np.asarray(batch.req, np.float32)
    if req_pad.shape[1] < r:
        req_pad = np.pad(req_pad, ((0, 0), (0, r - req_pad.shape[1])))

    # free capacity on the valid nodes after every successful placement
    if free is None:
        placed = np.flatnonzero(nodes_arr >= 0)
        used = np.zeros((n, r), np.float32)
        np.add.at(used, nodes_arr[placed], req_pad[placed])
        free = tensors.alloc - used
    free = np.where(valid[:, None], np.asarray(free, np.float32), 0.0)

    demand = req_pad[rows].sum(axis=0)  # [r]
    free_total = free.sum(axis=0)
    free_max = free.max(axis=0) if n else np.zeros(r, np.float32)
    demand_max = req_pad[rows].max(axis=0)

    resources: List[Dict[str, object]] = []
    names = list(tensors.resource_names)
    binding = None
    binding_share = -1.0
    for i in range(r):
        if demand[i] <= 0:
            continue
        ft = float(free_total[i])
        share = float(demand[i]) / ft if ft > 0 else math.inf
        rec = {
            "resource": names[i] if i < len(names) else f"res[{i}]",
            "requested": float(demand[i]),
            "free": ft,
            "share": round(min(share, 1e9), 4),
            # fragmentation: the biggest single request vs the biggest
            # single free block — aggregate room that no one node offers
            "max_pod_request": float(demand_max[i]),
            "max_node_free": float(free_max[i]),
            "fragmented": bool(demand_max[i] > free_max[i] + 1e-6)
            and ft >= float(demand[i]),
        }
        resources.append(rec)
        if share > binding_share:
            binding_share = share
            binding = rec

    by_reason: Dict[str, int] = {}
    capacity_shaped = 0
    for code in reasons[rows].astype(int):
        by_reason[REASON_TEXT.get(code, "unschedulable")] = (
            by_reason.get(REASON_TEXT.get(code, "unschedulable"), 0) + 1
        )
        if code in _CAPACITY_SHAPED:
            capacity_shaped += 1

    doc: Dict[str, object] = {
        "unplaced": int(len(rows)),
        "by_reason": dict(sorted(by_reason.items(), key=lambda kv: -kv[1])),
        "capacity_shaped": int(capacity_shaped),
        "constraint_shaped": int(len(rows) - capacity_shaped),
        "resources": resources,
    }
    if binding is not None:
        doc["binding"] = dict(binding)

    if new_node is not None:
        doc["template"] = _template_verdict(
            batch, rows, new_node, daemon_sets, corrected_ds_overhead,
            demand, free_total, names,
        )
    return doc


def _template_verdict(
    batch, rows, new_node, daemon_sets, corrected, demand, free_total, names
) -> Dict[str, object]:
    """Fold the planners' can-never-help diagnosis over the unplaced set
    and size the deficit in template nodes (the what-to-buy hint)."""
    from ..core.match import node_should_run_pod
    from ..core.quantity import parse_quantity
    from ..plan.capacity import meet_resource_requests

    helpable = never = 0
    first_never = ""
    probe = rows[:_TEMPLATE_PROBE_CAP]
    for j in probe:
        pod = batch.pods[int(j)] if batch.pods else None
        if pod is None:
            continue
        if not node_should_run_pod(new_node, pod):
            never += 1
            if not first_never:
                first_never = "pod does not fit new node affinity or taints"
            continue
        if not meet_resource_requests(
            new_node, pod, list(daemon_sets), corrected=corrected
        ):
            never += 1
            if not first_never:
                first_never = (
                    "new node cannot meet resource requests of pod: the "
                    "total requested resource of daemonset pods in new "
                    "node is too large"
                )
            continue
        helpable += 1
    alloc = ((new_node.get("status") or {}).get("allocatable")) or {}
    nodes_hint = 0
    for rname in ("cpu", "memory"):
        if rname not in names:
            continue
        i = names.index(rname)
        cap = float(parse_quantity(alloc.get(rname)))
        deficit = float(demand[i]) - float(free_total[i])
        if cap > 0 and deficit > 0:
            nodes_hint = max(nodes_hint, int(math.ceil(deficit / cap)))
    out: Dict[str, object] = {
        "probed": int(len(probe)),
        "helpable": int(helpable),
        "never_helpable": int(never),
    }
    if len(probe) < len(rows):
        out["probe_truncated"] = int(len(rows) - len(probe))
    if first_never:
        out["never_reason"] = first_never
    if nodes_hint:
        out["template_nodes_hint"] = nodes_hint
    return out
