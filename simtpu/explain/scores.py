"""Score attribution: why did THIS node win, and by how much.

For a placed pod, decompose the winning score into its per-plugin terms
(the registry score plugins + the Simon/Open-Local/GPU-share extensions,
weights from `schedconfig`), name the runner-up node, and report the
margin — per-term.  This is the weight-sensitivity surface a scoring
tuner needs: `d(margin)/d(w_i) = raw_i(winner) - raw_i(runner_up)`, so
the attribution rows carry the RAW (pre-weight) normalized term values
alongside the weighted contributions.

Exactness: each attributed pod is re-evaluated against the state built
from the placement-log prefix BEFORE it (one `build_state` per pod —
which is why attribution is opt-in and capped): for engine-level runs
(planners, `simtpu explain`) that is exactly the state its scheduling
cycle saw, and the recomputed argmax is pinned to equal the recorded
node (`consistent` flags the rare divergence — e.g. preemption log
surgery reordered the log after the fact).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..engine.scan import filter_and_score, flags_from, statics_from
from ..engine.state import build_state, take_rows
from ..kernels.scores import (
    MAX_NODE_SCORE,
    balanced_allocation,
    least_allocated,
    maxabs_normalize,
    minmax_normalize,
    selector_spread_score,
    simon_share,
    taint_toleration_score,
    topology_spread_score,
)
from ..obs.metrics import REGISTRY
from ..obs.trace import span

#: attributed pods per call unless the caller asks for more — one
#: build_state + one [N] evaluation each
DEFAULT_MAX_PODS = 8


def extras_from_log(tensors, nodes_arr: np.ndarray, ext_log: dict) -> Dict[str, np.ndarray]:
    """Reconstruct `Engine.place`-shaped extras arrays ([P, ...] per-pod
    extended-resource allocations) from an engine's ext_log — the log
    appends placed pods in batch order, so scattering its rows back onto
    the placed batch rows recovers the per-row view `attribute_scores`
    consumes.  Valid for engine-level runs whose log was not surgered
    (no preemption), the same contract as prefix-state exactness."""
    nodes_arr = np.asarray(nodes_arr)
    p = len(nodes_arr)
    ext = tensors.ext
    v = ext.vg_cap.shape[1]
    sd = ext.sdev_cap.shape[1]
    gd = ext.gpu_dev_total.shape[1]
    lvm = np.zeros((p, v), np.float32)
    dev = np.zeros((p, sd), bool)
    gpu = np.zeros((p, gd), np.float32)
    placed = np.flatnonzero(nodes_arr >= 0)
    for pos, j in enumerate(placed):
        if pos >= len(ext_log["vg_alloc"]):
            break
        lvm[j] = np.asarray(ext_log["vg_alloc"][pos])
        dev[j] = np.asarray(ext_log["sdev_take"][pos])
        gpu[j] = np.asarray(ext_log["gpu_shares"][pos])
    return {"lvm_alloc": lvm, "dev_take": dev, "gpu_shares": gpu}


#: the attribution's plugin rows, in `score_pod`'s term order:
#: (plugin name, schedconfig weight index)
PLUGIN_TERMS = (
    ("NodeResourcesLeastAllocated", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("Simon", 2),
    ("Open-Gpu-Share", 3),
    ("NodeAffinity", 4),
    ("TaintToleration", 5),
    ("InterPodAffinity", 6),
    ("PodTopologySpread", 7),
    ("SelectorSpread", 8),
    ("ImageLocality", 9),
    ("NodePreferAvoidPods", 11),
    ("Open-Local", 10),
)


@partial(jax.jit, static_argnums=(3,))
def _eval_pod_terms(statics, state, pod, flags):
    """One jitted evaluation of a pod against `state`: the engine's total
    score vector plus the per-plugin RAW (pre-weight) normalized term
    vectors, stacked in PLUGIN_TERMS order.

    Terms are computed unconditionally — the per-pod lax.cond skips they
    mirror return the same constants the unconditional kernels produce
    for term-free pods (the wavefront verifier's pinned fact), so the
    decomposition matches the engine's score term-for-term."""
    import jax.numpy as jnp

    (g, req, _pin, _forced, *_rest) = pod
    ev = filter_and_score(statics, state, pod, flags)
    m_all = ev.m_all
    n = statics.alloc.shape[0]
    w = statics.score_w
    t_cap = statics.g_terms.shape[1]

    least = least_allocated(state.free, statics.alloc, req)
    balanced = balanced_allocation(state.free, statics.alloc, req)
    simon = minmax_normalize(simon_share(statics.alloc, req), m_all)
    node_pref = minmax_normalize(statics.node_pref[g], m_all)
    taint = taint_toleration_score(statics.taint_intol[g], m_all)
    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        cnt_sub = take_rows(state.cnt_match, terms_g)
        ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)
        from ..kernels.scores import interpod_score

        ipa = maxabs_normalize(
            interpod_score(
                cnt_sub,
                take_rows(state.cnt_own_aff, ip_eff),
                take_rows(state.w_own_aff_pref, ip_eff),
                take_rows(state.w_own_anti_pref, ip_eff),
                statics.s_match[g],
                statics.w_aff_pref[g],
                statics.w_anti_pref[g],
            ),
            m_all,
        )
        spread = topology_spread_score(cnt_sub, statics.spread_soft[g], m_all)
        ss = selector_spread_score(
            cnt_sub, statics.ss_host[g], statics.ss_zone[g], m_all
        )
    else:
        ipa = jnp.zeros(n, jnp.float32)
        spread = jnp.full(n, MAX_NODE_SCORE, jnp.float32)
        ss = jnp.full(n, MAX_NODE_SCORE, jnp.float32)
    # the Open-Local term is owned by filter_and_score (the storage plans
    # live there); its WEIGHTED value is exactly score - score_nostorage
    storage_weighted = ev.score - ev.score_nostorage
    w10 = w[10]
    storage_raw = jnp.where(
        w10 != 0, storage_weighted / jnp.where(w10 == 0, 1.0, w10), 0.0
    )
    terms = jnp.stack([
        jnp.asarray(v, jnp.float32)
        for v in (
            least, balanced, simon, simon, node_pref, taint, ipa, spread,
            ss, statics.static_score[g], statics.avoid_pen[g], storage_raw,
        )
    ])
    return ev.score, terms


def attribute_scores(
    tensors,
    batch,
    nodes_arr: np.ndarray,
    extras: Dict[str, np.ndarray],
    *,
    rows: Optional[Sequence[int]] = None,
    max_pods: int = DEFAULT_MAX_PODS,
    sched_config=None,
    node_valid: Optional[np.ndarray] = None,
) -> List[Dict[str, object]]:
    """Per-plugin score decomposition for up to `max_pods` placed pods.

    `nodes_arr`/`extras` are one engine placement's outputs over `batch`
    (`Engine.place`); `rows` selects batch rows to attribute (default:
    the first `max_pods` placed rows).  Returns one document per pod:
    winner, runner-up, margin, and per-term rows with weight, raw
    winner/runner-up values, and the weighted delta (the term's
    contribution to the margin)."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    nodes_arr = np.asarray(nodes_arr)
    placed = np.flatnonzero(nodes_arr >= 0)
    if rows is None:
        rows = placed[: max(int(max_pods), 0)]
    else:
        rows = np.asarray(list(rows), np.int64)[: max(int(max_pods), 0)]
    if not len(rows):
        return []
    flags = flags_from(tensors, batch.ext)
    statics = statics_from(tensors, sched_config)
    if node_valid is not None:
        statics = statics._replace(
            node_valid=statics.node_valid & jnp.asarray(np.asarray(node_valid, bool))
        )
    r_res = tensors.alloc.shape[1]
    req_pad = batch.req
    if req_pad.shape[1] < r_res:
        req_pad = np.pad(req_pad, ((0, 0), (0, r_res - req_pad.shape[1])))
    from ..engine.scan import build_pod_arrays

    _, pods = build_pod_arrays(batch, r_res)
    from ..schedconfig import DEFAULT_WEIGHTS

    weights = np.asarray(
        sched_config.score_weights if sched_config is not None else DEFAULT_WEIGHTS,
        np.float32,
    )
    from ..core.objects import name_of, namespace_of

    node_names = list(tensors.node_names)
    out: List[Dict[str, object]] = []
    with span("explain.scores", pods=int(len(rows))):
        for j in rows:
            j = int(j)
            # the placement-log prefix before batch row j: every earlier
            # placed row, in batch order (engine-level log order)
            prefix = placed[placed < j]
            state = build_state(
                tensors,
                np.asarray(batch.group)[prefix].astype(np.int32),
                nodes_arr[prefix].astype(np.int32),
                req_pad[prefix].astype(np.float32),
                {
                    "node": nodes_arr[prefix].tolist(),
                    "vg_alloc": list(np.asarray(extras["lvm_alloc"])[prefix]),
                    "sdev_take": list(np.asarray(extras["dev_take"])[prefix]),
                    "gpu_shares": list(np.asarray(extras["gpu_shares"])[prefix]),
                    "gpu_mem": np.asarray(batch.ext["gpu_mem"])[prefix].tolist(),
                },
            )
            pod = tuple(jnp.asarray(np.asarray(arr)[j]) for arr in pods)
            score_dev, terms_dev = _eval_pod_terms(statics, state, pod, flags)
            score = np.asarray(score_dev)
            term_mat = np.asarray(terms_dev)
            order = np.argsort(-score, kind="stable")
            winner = int(order[0])
            runner = int(order[1]) if len(order) > 1 and np.isfinite(score[order[1]]) else -1
            recorded = int(nodes_arr[j])
            margin = (
                float(score[winner] - score[runner]) if runner >= 0 else None
            )
            terms = []
            for t, (name, widx) in enumerate(PLUGIN_TERMS):
                rw = float(term_mat[t, winner])
                rr = float(term_mat[t, runner]) if runner >= 0 else None
                wgt = float(weights[widx])
                terms.append(
                    {
                        "plugin": name,
                        "weight": wgt,
                        "winner_raw": round(rw, 6),
                        "runner_up_raw": None if rr is None else round(rr, 6),
                        "delta": None if rr is None else round(wgt * (rw - rr), 6),
                    }
                )
            pod_obj = batch.pods[j] if batch.pods else None
            out.append(
                {
                    "pod": (
                        f"{namespace_of(pod_obj)}/{name_of(pod_obj)}"
                        if pod_obj is not None
                        else f"pod[{j}]"
                    ),
                    "row": j,
                    "node": node_names[recorded] if 0 <= recorded < len(node_names) else "",
                    "winner": node_names[winner] if 0 <= winner < len(node_names) else "",
                    "runner_up": (
                        node_names[runner] if 0 <= runner < len(node_names) else ""
                    ),
                    "margin": None if margin is None else round(margin, 6),
                    # pinned for engine-level runs: the recomputed argmax IS
                    # the recorded landing node (prefix-state exactness)
                    "consistent": winner == recorded,
                    "terms": terms,
                }
            )
    REGISTRY.counter("explain.scored_pods").inc(int(len(rows)))
    REGISTRY.histogram("explain.scores_wall_s").observe(time.perf_counter() - t0)
    return out
