"""Per-pod failure breakdowns: the kube-scheduler status string, exactly.

kube-scheduler's PodScheduled condition reads "0/N nodes are available:
3 Insufficient cpu, 5 node(s) had untolerated taint." — every node
accounted to the filter stage that eliminated it.  The engine's
`StepEval.fail_code` reports only the FIRST stage that emptied the
candidate set; this module re-evaluates each unplaced pod's full filter
cascade (the same `filter_and_score` the scan steps run, so the stage
masks are the engine's own) against a carried state and renders the full
per-stage split.

Semantics: the breakdown is evaluated against the state the caller hands
in — for `simtpu explain` / `--explain` that is the END-OF-RUN carry, so
the counts answer "why does this pod not fit the cluster as it now
stands".  The recorded `fail_code` (evaluated at the pod's attempt) stays
the headline reason, bit-equal to the legacy report; the breakdown's own
first-failing stage (`fail_code` recomputed from the same masks) is
reported alongside, and the two coincide whenever the carried state did
not tighten past the pod's attempt.  A pod whose constraints were
satisfied by LATER placements (required affinity on a pod placed after
it) can show `feasible > 0` — an ordering artifact worth surfacing, not
an error; the per-stage counts plus `feasible` always sum to the valid
node count (pinned against the pure-numpy twin, `SIMTPU_EXPLAIN_JIT=0`).

Cost model: one jitted, vmapped [chunk, N] pass per pow2 chunk of
unplaced pods (shape-bounded executables, `compile.explain` trace
counter), dispatched only when an explanation was requested — the off
path adds zero device dispatches (pinned via `compile.*`/`fetch.*`
registry deltas, tests/test_explain.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.scan import (
    FAIL_NO_NODE,
    FILTER_CASCADE,
    OK,
    REASON_TEXT,
    StepFlags,
    build_pod_arrays,
    count_trace,
    fetch_outputs,
    filter_and_score,
    flags_from,
    pad_pods_pow2,
    statics_from,
)
from ..obs.metrics import REGISTRY
from ..obs.trace import span

#: versions the `explain` block in `--json` / flight bundles — bump when
#: the document layout (stage keys, group fields, message grammar) changes
EXPLAIN_VERSION = 1

#: (stage key, failure code) in cascade order — FILTER_CASCADE with the
#: StepEval field names shortened to the stage vocabulary the JSON block
#: and docs/observability.md use.  The final stage ("interpod", the
#: cascade default) owns every node the full mask chain eliminated last.
STAGES = tuple(
    (fld[2:] if fld != "m_all" else "interpod", code)
    for fld, code in FILTER_CASCADE
)

#: pods explained per jitted dispatch (pow2-padded tail) — bounds the
#: [chunk, N] mask planes and the per-chunk executable set
EXPLAIN_CHUNK = 64

#: per-stage witness nodes recorded per pod (lowest-index eliminated)
WITNESS_K = 4


def jit_enabled() -> bool:
    """SIMTPU_EXPLAIN_JIT=0 routes the breakdown through the pure-numpy
    twin instead of the jitted pass (the audit/checker.py A/B pattern —
    the twin is also what the tests pin the jit counts against)."""
    return os.environ.get("SIMTPU_EXPLAIN_JIT", "1") != "0"


def _witness_cap(n: int, k: int) -> int:
    return max(1, min(int(k), int(n)))


@partial(jax.jit, static_argnums=(3, 4))
def _explain_call(statics, state, pods, flags: StepFlags, k: int):
    """[P]-vmapped filter cascade + per-stage elimination accounting.

    Returns (counts [P, S] i32, feasible [P] i32, witnesses [P, S, k] i32
    node indices (-1 pad), fail_code [P] i32) — fail_code is
    `StepEval.fail_code` on the same masks, so the first-failing stage
    and the headline reason agree by construction."""
    count_trace("explain")
    n = statics.alloc.shape[0]
    node_ids = jnp.arange(n)

    def first_k(elim):
        # lowest eliminated node indices via top_k on a distinct-value key
        vals = jnp.where(elim, (n - node_ids).astype(jnp.float32), 0.0)
        top, _ = jax.lax.top_k(vals, k)
        return jnp.where(top > 0, (n - top).astype(jnp.int32), -1)

    def one(pod):
        ev = filter_and_score(statics, state, pod, flags)
        alive = statics.node_valid
        counts: List = []
        wits: List = []
        for fld, _code in FILTER_CASCADE:
            m = getattr(ev, fld)
            elim = alive & ~m
            counts.append(jnp.sum(elim).astype(jnp.int32))
            wits.append(first_k(elim))
            alive = alive & m
        return (
            jnp.stack(counts),
            jnp.sum(alive).astype(jnp.int32),
            jnp.stack(wits),
            ev.fail_code(),
        )

    return jax.vmap(one)(pods)


# ---------------------------------------------------------------------------
# Pure-numpy twin (SIMTPU_EXPLAIN_JIT=0; the count oracle the tests pin)
# ---------------------------------------------------------------------------
#
# Mirrors `filter_and_score`'s stage semantics on the HOST tensors over the
# full term axis — structurally different from the jit pass (no per-group
# term compaction, no one-hot matmuls), which is what makes the twin worth
# pinning against: a compaction or lowering bug shows up as a count
# mismatch.  Formulas follow simtpu/kernels one-for-one, float32 like the
# device pass so the epsilon comparisons agree bitwise.

_RES_EPS = np.float32(1e-5)
_BIG = np.float32(3.4e38)


def _np_lvm_fits(vg_free, vg_name_id, sizes, vg_ids):
    """numpy twin of kernels.storage.lvm_plan (fits mask only)."""
    n, v = vg_free.shape
    exists = vg_name_id >= 0
    has_any = exists.any(axis=1)
    fits = np.ones(n, bool)
    free = vg_free.astype(np.float32).copy()
    for i in range(sizes.shape[0]):
        size, vid = np.float32(sizes[i]), int(vg_ids[i])
        active = size > 0
        named = vid >= 0
        slot_named = exists & (vg_name_id == vid)
        has_named = slot_named.any(axis=1)
        eligible = exists & (free >= size)
        key = np.where(eligible, free, _BIG)
        slot_binpack = np.zeros((n, v), bool)
        slot_binpack[np.arange(n), np.argmin(key, axis=1)] = eligible.any(axis=1)
        slot = slot_named if named else slot_binpack
        room = (slot & (free >= size)).any(axis=1)
        ok = (has_named & room if named else eligible.any(axis=1))
        ok = ok & (vid != -2) & has_any
        take = slot & (free >= size)
        upd = np.where(active & ok[:, None] & take, size, np.float32(0.0))
        free = free - upd
        if active:
            fits = fits & ok
    return fits


def _np_device_fits(sdev_free, sdev_cap, sdev_media, sizes, medias):
    """numpy twin of kernels.storage.device_plan (fits mask only)."""
    n, sd = sdev_cap.shape
    fits = np.ones(n, bool)
    free = sdev_free.astype(bool).copy()
    for i in range(sizes.shape[0]):
        size, media = np.float32(sizes[i]), int(medias[i])
        active = size > 0
        eligible = free & (sdev_media == media) & (sdev_cap >= size)
        key = np.where(eligible, sdev_cap.astype(np.float32), _BIG)
        choice = np.argmin(key, axis=1)
        found = eligible.any(axis=1)
        sel = np.zeros((n, sd), bool)
        sel[np.arange(n), choice] = found
        sel = sel & active
        free = free & ~sel
        if active:
            fits = fits & found
    return fits


def _np_gpu_fits(gpu_free, dev_exists, gpu_total, mem, count, preset):
    """numpy twin of kernels.gpushare.gpu_plan (fits mask only)."""
    n, gd = gpu_free.shape
    mem = np.float32(mem)
    count = np.float32(count)
    is_gpu = mem > 0
    valid_req = count > 0
    free = np.where(dev_exists, gpu_free.astype(np.float32), np.float32(-1.0))
    per_dev = np.where(
        free >= mem,
        np.floor(free / np.maximum(mem, np.float32(1e-30))),
        np.float32(0.0),
    )
    cum = np.cumsum(per_dev, axis=1)
    prev = cum - per_dev
    greedy = np.clip(np.minimum(cum, count) - prev, 0.0, per_dev)
    fit1 = free >= mem
    key = np.where(fit1, free, _BIG)
    tight = np.zeros((n, gd), np.float32)
    tight[np.arange(n), np.argmin(key, axis=1)] = np.where(
        fit1.any(axis=1), np.float32(1.0), np.float32(0.0)
    )
    shares = tight if count == 1 else greedy
    enough = shares.sum(axis=1) >= count
    node_total_ok = gpu_total >= mem
    has_dev = dev_exists.any(axis=1)
    fits = np.where(is_gpu, node_total_ok & has_dev & valid_req & enough, True)
    if preset is not None and np.sum(preset) > 0:
        fits = np.where(is_gpu, node_total_ok & has_dev & valid_req, True)
    return fits.astype(bool)


def _np_spread_filter(cnt_at, valid, max_skew, elig_nodes):
    """numpy twin of kernels.filters.topology_spread_filter."""
    t, n = cnt_at.shape
    if t == 0:
        return np.ones(n, bool)
    active = max_skew > 0
    elig = valid & elig_nodes[None, :]
    inf = np.float32(3.4e38)
    min_cnt = np.min(np.where(elig, cnt_at, inf), axis=1)
    min_cnt = np.where(min_cnt >= inf, np.float32(0.0), min_cnt)
    ok = (~active[:, None]) | (
        valid & (cnt_at + np.float32(1.0) - min_cnt[:, None] <= max_skew[:, None])
    )
    return ok.all(axis=0)


def _np_interpod_filter(cnt_at, own_anti_at, valid, cnt_total, s_match, a_aff, a_anti):
    """numpy twin of kernels.filters.interpod_filter."""
    t, n = cnt_at.shape
    if t == 0:
        return np.ones(n, bool)
    anti_violated = (a_anti[:, None] & (cnt_at > 0)).any(axis=0)
    sym_violated = (s_match[:, None] & (own_anti_at > 0)).any(axis=0)
    aff_ok = ((~a_aff[:, None]) | (valid & (cnt_at > 0))).all(axis=0)
    # first-pod-in-series escape: no matching pod anywhere for any
    # required term AND the pod matches all its own terms AND the node
    # carries every required topology key
    total_match = np.sum(np.where(a_aff, cnt_total, np.float32(0.0)))
    self_ok = (
        (total_match == 0)
        & np.all(np.where(a_aff, s_match, True))
        & ((~a_aff[:, None]) | valid).all(axis=0)
    )
    aff_ok = aff_ok | (a_aff.any() & self_ok)
    return aff_ok & ~anti_violated & ~sym_violated


def numpy_breakdown(
    tensors,
    batch,
    rows: np.ndarray,
    state_host,
    node_valid: np.ndarray,
    flags: StepFlags,
    k: int,
):
    """The twin pass: (counts [U, S], feasible [U], witnesses [U, S, k],
    fail_code [U]) from host numpy alone.  `state_host` is a SchedState of
    numpy arrays (a fetched carry)."""
    n = tensors.alloc.shape[0]
    t = tensors.n_terms
    ext = tensors.ext
    node_ids = np.arange(n)
    free = np.asarray(state_host.free, np.float32)
    from ..engine.state import interpod_term_index

    ip_of = interpod_term_index(tensors)
    own_anti_full = np.zeros((t, n), np.float32)
    if t:
        cnt_own_anti = np.asarray(state_host.cnt_own_anti, np.float32)
        has_row = ip_of >= 0
        own_anti_full[has_row] = cnt_own_anti[ip_of[has_row]]
        cnt_match = np.asarray(state_host.cnt_match, np.float32)
        cnt_total = np.asarray(state_host.cnt_total, np.float32)
        dom_full = tensors.node_dom[tensors.term_topo_key]  # [T, N]
        valid_full = dom_full >= 0
    counts = np.zeros((len(rows), len(STAGES)), np.int32)
    feas = np.zeros(len(rows), np.int32)
    wits = np.full((len(rows), len(STAGES), k), -1, np.int32)
    codes = np.zeros(len(rows), np.int32)
    bext = batch.ext
    for u, r in enumerate(np.asarray(rows)):
        r = int(r)
        g = int(batch.group[r])
        req = np.asarray(batch.req[r], np.float32)
        if req.shape[0] < tensors.alloc.shape[1]:
            req = np.pad(req, (0, tensors.alloc.shape[1] - req.shape[0]))
        pin = int(batch.pin[r])
        pin_m = (node_ids == pin) if pin >= 0 else np.full(n, pin > -2)
        m_static = tensors.static_mask[g] & pin_m & node_valid

        m_ports = m_static
        if flags.ports and tensors.n_ports:
            want = tensors.ports[g]
            used = np.asarray(state_host.ports_used, np.float32)
            m_ports = m_static & ~((want[None, :] & (used > 0)).any(axis=1))

        slack = _RES_EPS * np.maximum(np.abs(free), np.float32(1.0))
        m_res = m_ports & np.all(free + slack >= req[None, :], axis=1)

        m_vol = m_res
        if flags.vols and tensors.n_vols:
            vols_any = np.asarray(state_host.vols_any, np.float32)
            vols_rw = np.asarray(state_host.vols_rw, np.float32)
            rw_conf = (tensors.vol_rw[g][None, :] & (vols_any > 0)).any(axis=1)
            ro_conf = (tensors.vol_ro[g][None, :] & (vols_rw > 0)).any(axis=1)
            m_vol = m_res & ~(rw_conf | ro_conf)

        m_att = m_vol
        if flags.attach and tensors.n_vols:
            vols_any = np.asarray(state_host.vols_any, np.float32)
            present = (vols_any > 0).astype(np.float32)
            cm = tensors.vol_class_mask.astype(np.float32)
            used_c = present @ cm.T
            new_c = (
                (np.float32(1.0) - present)
                * tensors.vol_att[g].astype(np.float32)[None, :]
            ) @ cm.T
            m_att = m_vol & np.all(
                (new_c == 0) | (used_c + new_c <= tensors.attach_limits), axis=1
            )

        m_bind = m_att & tensors.vol_mask[g]

        m_storage = m_bind
        if flags.storage:
            lvm_size = np.asarray(bext["lvm_size"][r], np.float32)
            dev_size = np.asarray(bext["dev_size"][r], np.float32)
            if (lvm_size > 0).any() or (dev_size > 0).any():
                lvm_ok = _np_lvm_fits(
                    np.asarray(state_host.vg_free, np.float32),
                    ext.vg_name_id,
                    lvm_size,
                    np.asarray(bext["lvm_vg"][r]),
                )
                dev_ok = _np_device_fits(
                    np.asarray(state_host.sdev_free),
                    ext.sdev_cap.astype(np.float32),
                    ext.sdev_media,
                    dev_size,
                    np.asarray(bext["dev_media"][r]),
                )
                m_storage = m_bind & ext.has_storage & lvm_ok & dev_ok

        m_gpu = m_storage
        if flags.gpu and float(bext["gpu_mem"][r]) > 0:
            m_gpu = m_storage & _np_gpu_fits(
                np.asarray(state_host.gpu_free, np.float32),
                ext.gpu_dev_total > 0,
                ext.gpu_total.astype(np.float32),
                float(bext["gpu_mem"][r]),
                float(bext["gpu_count"][r]),
                np.asarray(bext["gpu_preset"][r]),
            )

        m_spread = m_gpu
        if flags.spread_hard and t and (tensors.spread_hard[g] > 0).any():
            m_spread = m_gpu & _np_spread_filter(
                cnt_match, valid_full, tensors.spread_hard[g],
                tensors.static_mask[g] & pin_m & node_valid,
            )

        m_all = m_spread
        if flags.interpod_req and t:
            m_all = m_spread & _np_interpod_filter(
                cnt_match, own_anti_full, valid_full, cnt_total,
                tensors.s_match[g], tensors.a_aff_req[g], tensors.a_anti_req[g],
            )

        alive = node_valid.copy()
        cascade_masks = (
            m_static, m_ports, m_res, m_vol, m_att, m_bind,
            m_storage, m_gpu, m_spread, m_all,
        )
        code = STAGES[-1][1]
        for s, m in enumerate(cascade_masks):
            elim = alive & ~m
            counts[u, s] = int(elim.sum())
            first = node_ids[elim][:k]
            wits[u, s, : len(first)] = first
            alive = alive & m
        feas[u] = int(alive.sum())
        for s in range(len(cascade_masks) - 1, -1, -1):
            if not cascade_masks[s].any():
                code = STAGES[s][1]
        codes[u] = code
    return counts, feas, wits, codes


# ---------------------------------------------------------------------------
# Host driver + rendering
# ---------------------------------------------------------------------------


@dataclass
class FailureBreakdown:
    """One explanation pass's result over a set of unplaced pods."""

    n_nodes: int  # valid-node universe ("0/N nodes are available")
    rows: np.ndarray  # [U] batch row of each explained pod
    names: List[str]  # [U] "namespace/name"
    reasons: np.ndarray  # [U] RECORDED fail codes (the legacy headline)
    fail_code: np.ndarray  # [U] first-failing stage vs the explained state
    counts: np.ndarray  # [U, S] nodes eliminated per cascade stage
    feasible: np.ndarray  # [U] nodes surviving the whole cascade
    witnesses: np.ndarray  # [U, S, K] example node indices (-1 pad)
    node_names: List[str] = field(default_factory=list)
    mode: str = "jit"  # jit | numpy (SIMTPU_EXPLAIN_JIT=0)
    wall_s: float = 0.0

    def __len__(self) -> int:
        return len(self.rows)

    def headline(self, i: int) -> str:
        """The legacy reason — REASON_TEXT of the recorded fail code,
        bit-equal to what the placement report already prints."""
        return REASON_TEXT.get(int(self.reasons[i]), "unschedulable")

    def status(self, i: int) -> str:
        """The kube-scheduler-style status string: per-stage elimination
        counts in cascade order, each rendered with the stage's
        REASON_TEXT — so the entry for the first failing stage reads
        exactly like the legacy headline reason."""
        parts = [
            f"{int(self.counts[i, s])} {REASON_TEXT[code]}"
            for s, (_key, code) in enumerate(STAGES)
            if int(self.counts[i, s]) > 0
        ]
        if int(self.feasible[i]) > 0:
            parts.append(
                f"{int(self.feasible[i])} node(s) would now be feasible "
                "(ordering artifact: later placements satisfied this pod's "
                "constraints after it failed)"
            )
        avail = int(self.feasible[i])
        if parts:
            tail = ", ".join(parts)
        elif self.n_nodes == 0:
            tail = "no nodes in the cluster"
        else:
            # a pod that never ran the cascade (spec.nodeName forced onto
            # a node outside this cluster) has zero stage counts on a
            # non-empty cluster: the recorded reason is the whole story
            tail = self.headline(i)
        return f"{avail}/{self.n_nodes} nodes are available: {tail}."

    def witness_names(self, i: int, s: int) -> List[str]:
        out = []
        for w in self.witnesses[i, s]:
            if int(w) >= 0 and int(w) < len(self.node_names):
                out.append(self.node_names[int(w)])
        return out

    def groups(self, top: int = 10) -> List[Dict[str, object]]:
        """Pods grouped by identical (headline code, per-stage counts) —
        one entry per distinct failure shape, largest first, capped."""
        by_key: Dict[tuple, Dict[str, object]] = {}
        for i in range(len(self.rows)):
            key = (int(self.reasons[i]), tuple(int(c) for c in self.counts[i]))
            got = by_key.get(key)
            if got is None:
                by_key[key] = {
                    "pods": 1,
                    "example": self.names[i],
                    "reason": self.headline(i),
                    "fail_code": int(self.reasons[i]),
                    "final_fail_code": int(self.fail_code[i]),
                    "status": self.status(i),
                    "stages": {
                        STAGES[s][0]: int(self.counts[i, s])
                        for s in range(len(STAGES))
                        if int(self.counts[i, s]) > 0
                    },
                    "feasible": int(self.feasible[i]),
                    "witnesses": {
                        STAGES[s][0]: self.witness_names(i, s)
                        for s in range(len(STAGES))
                        if int(self.counts[i, s]) > 0
                    },
                }
            else:
                got["pods"] += 1
        groups = sorted(by_key.values(), key=lambda d: -d["pods"])
        return groups[:top]

    def to_doc(self, top: int = 10) -> Dict[str, object]:
        groups = self.groups(top=top)
        distinct = len(
            {
                (int(self.reasons[i]), tuple(int(c) for c in self.counts[i]))
                for i in range(len(self.rows))
            }
        )
        doc = {
            "version": EXPLAIN_VERSION,
            "n_nodes": int(self.n_nodes),
            "unplaced": int(len(self.rows)),
            "mode": self.mode,
            "wall_s": round(self.wall_s, 4),
            "groups": groups,
        }
        if distinct > top:
            # no silent caps: a truncated view must say what was dropped
            doc["truncated_groups"] = distinct - top
        return doc


def build_explain_doc(
    tensors,
    batch,
    rows: Sequence[int],
    state,
    nodes_arr: np.ndarray,
    reasons: np.ndarray,
    *,
    node_valid: Optional[np.ndarray] = None,
    sched_config=None,
    new_node: Optional[dict] = None,
    daemon_sets: Sequence[dict] = (),
    corrected_ds_overhead: bool = False,
    top: int = 10,
    free: Optional[np.ndarray] = None,
) -> Dict[str, object]:
    """The ONE assembly of the versioned explain document — failures
    breakdown (when a carried state is available) + bottleneck analysis —
    shared by `Simulator.explain_result`, the planners' failure paths,
    and the `simtpu explain` subcommand, so the EXPLAIN_VERSION-stamped
    shape cannot drift across surfaces.  `state=None` (e.g. a
    checkpoint-replayed candidate with no carry) degrades to the
    bottleneck block alone, its free capacity taken from `free` when the
    caller can supply the full picture (the incremental planner's probe
    batches see only a slice of the placements) and otherwise derived
    from the visible placements in `nodes_arr`."""
    from .bottleneck import bottleneck_analysis

    rows = np.asarray(list(rows), np.int64)
    doc: Dict[str, object] = {"version": EXPLAIN_VERSION}
    if not len(rows):
        return {}
    if state is not None:
        bd = explain_failures(
            tensors, batch, rows, state, reasons=reasons,
            node_valid=node_valid, sched_config=sched_config,
        )
        doc["failures"] = bd.to_doc(top=top)
        free = np.asarray(state.free)  # the carry is exact — it wins
    doc["bottleneck"] = bottleneck_analysis(
        tensors, batch, np.asarray(nodes_arr), np.asarray(reasons),
        rows=rows, node_valid=node_valid, new_node=new_node,
        daemon_sets=daemon_sets,
        corrected_ds_overhead=corrected_ds_overhead, free=free,
    )
    return doc


def explain_failures(
    tensors,
    batch,
    rows: Sequence[int],
    state,
    *,
    reasons: Optional[np.ndarray] = None,
    node_valid: Optional[np.ndarray] = None,
    sched_config=None,
    names: Optional[List[str]] = None,
    witnesses: int = WITNESS_K,
    chunk: int = EXPLAIN_CHUNK,
) -> FailureBreakdown:
    """Explain the unplaced pods at `rows` against `state` (a dense
    SchedState — `Engine.carried_state()` or a `build_state` output).

    `reasons` carries the recorded per-row fail codes (the legacy
    headline); when omitted, the breakdown's own first-failing stage is
    the headline too.  Forced pods (spec.nodeName) that failed with
    FAIL_NO_NODE never ran the cascade — they are reported with zero
    stage counts and the recorded reason alone."""
    t0 = time.perf_counter()
    rows = np.asarray(list(rows), np.int64)
    n = tensors.alloc.shape[0]
    valid = (
        np.ones(n, bool) if node_valid is None else np.asarray(node_valid, bool)
    )
    n_valid = int(valid.sum())
    k = _witness_cap(n, witnesses)
    s_n = len(STAGES)
    counts = np.zeros((len(rows), s_n), np.int32)
    feas = np.zeros(len(rows), np.int32)
    wits = np.full((len(rows), s_n, k), -1, np.int32)
    codes = np.zeros(len(rows), np.int32)

    # forced-fail pods (FAIL_NO_NODE) skip the cascade: their failure is
    # "the pinned node does not exist / is outside this cluster", not a
    # filter verdict
    forced = np.asarray(batch.forced)[rows].astype(bool)
    codes[forced] = FAIL_NO_NODE
    run_rows = rows[~forced]
    run_idx = np.flatnonzero(~forced)

    flags = flags_from(tensors, batch.ext)
    mode = "jit" if jit_enabled() else "numpy"
    if len(run_rows):
        with span("explain.pass", pods=int(len(run_rows)), mode=mode):
            if mode == "numpy":
                state_host = type(state)(*(np.asarray(p) for p in state))
                c, f, w, fc = numpy_breakdown(
                    tensors, batch, run_rows, state_host, valid, flags, k
                )
                counts[run_idx], feas[run_idx] = c, f
                wits[run_idx], codes[run_idx] = w, fc
            else:
                statics = statics_from(tensors, sched_config)
                statics = statics._replace(
                    node_valid=statics.node_valid & jnp.asarray(valid)
                )
                r_res = tensors.alloc.shape[1]
                _, pods = build_pod_arrays(batch, r_res)
                pos = 0
                while pos < len(run_rows):
                    sel = run_rows[pos : pos + chunk]
                    seg = tuple(np.asarray(arr)[sel] for arr in pods)
                    real = len(sel)
                    pad = 1 << max(real - 1, 0).bit_length()
                    seg = pad_pods_pow2(tuple(jnp.asarray(a) for a in seg), pad)
                    out = fetch_outputs(
                        _explain_call(statics, state, seg, flags, k)
                    )
                    c, f, w, fc = (np.asarray(o)[:real] for o in out)
                    dst = run_idx[pos : pos + chunk]
                    counts[dst], feas[dst] = c, f
                    wits[dst], codes[dst] = w, fc
                    pos += real
    if reasons is not None:
        recorded = np.asarray(reasons)[rows].astype(np.int32)
        # placed/OK rows explained by mistake keep the recomputed code
        recorded = np.where(recorded == OK, codes, recorded)
    else:
        recorded = codes.copy()
    if names is None:
        from ..core.objects import name_of, namespace_of

        names = [
            f"{namespace_of(batch.pods[int(r)])}/{name_of(batch.pods[int(r)])}"
            if batch.pods
            else f"pod[{int(r)}]"
            for r in rows
        ]
    wall = time.perf_counter() - t0
    REGISTRY.counter("explain.passes").inc()
    REGISTRY.counter("explain.pods").inc(int(len(rows)))
    REGISTRY.histogram("explain.wall_s").observe(wall)
    return FailureBreakdown(
        n_nodes=n_valid,
        rows=rows,
        names=list(names),
        reasons=recorded,
        fail_code=codes,
        counts=counts,
        feasible=feas,
        witnesses=wits,
        node_names=list(tensors.node_names),
        mode=mode,
        wall_s=wall,
    )
