"""Multi-chip execution: device meshes, node-axis sharding, batched sweeps.

SURVEY.md §2.3 mapping — the reference's 16-goroutine node loop and serial
candidate-size loop become two mesh axes:

- "nodes": cluster-state arrays sharded over ICI (`sharded.ShardedEngine`);
- "sweep": capacity-planner candidate counts over chips/hosts
  (`sweep.plan_capacity_batched`).
"""

from .mesh import (
    NODE_AXIS,
    SWEEP_AXIS,
    initialize_multihost,
    make_mesh,
    node_shard_count,
    planner_mesh,
)
from .sharded import (
    MaskedShardedRoundsEngine,
    ShardedEngine,
    ShardedRoundsEngine,
    build_sharded_scan,
    pad_state,
    pad_statics,
)
from .sweep import plan_capacity_batched, sweep_feasibility

__all__ = [
    "NODE_AXIS",
    "SWEEP_AXIS",
    "MaskedShardedRoundsEngine",
    "ShardedEngine",
    "ShardedRoundsEngine",
    "build_sharded_scan",
    "initialize_multihost",
    "make_mesh",
    "node_shard_count",
    "pad_state",
    "pad_statics",
    "plan_capacity_batched",
    "planner_mesh",
    "sweep_feasibility",
]
