"""Node-axis-sharded placement engine.

Lays every node-indexed array of the scan (`simtpu/engine/scan.py`) out across
the "nodes" axis of a `jax.sharding.Mesh` and jits the same scan under GSPMD:
the per-pod filter/score kernels become local elementwise work on each chip's
node shard, and the argmax select + scatter state-update lower to XLA
collectives over ICI. This replaces the reference's 16-goroutine chunked node
loop (`vendor/.../internal/parallelize/parallelism.go:27`) with true
multi-chip data parallelism over nodes — the scaling axis SURVEY.md §5 calls
out for 100k-node clusters.

The node count is padded up to a multiple of the shard count with "dead"
nodes (static_mask=False, node_valid=False, zero resources) that no pod can
select, so placement results are bit-identical to the unsharded engine.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.rounds import RoundsEngine
from ..engine.scan import (
    Engine,
    SchedState,
    StaticArrays,
    StepFlags,
    count_trace,
    schedule_step,
    wavefront_scan,
)
from ..engine.state import (
    CompactState,
    _apply_placement_deltas_compact_fn,
    _compress_state_fn,
    _expand_state_fn,
)
from .mesh import NODE_AXIS, node_shard_count


def _pad_axis(x, axis: int, pad: int, value):
    if pad == 0:
        return x
    if isinstance(x, jax.ShapeDtypeStruct):
        # shape-only padding: the precompiler (engine/precompile.py) runs
        # pad_statics/pad_state over ShapeDtypeStruct trees to enumerate
        # the shard-padded executable signatures without touching a device
        shape = list(x.shape)
        shape[axis] += pad
        return jax.ShapeDtypeStruct(tuple(shape), x.dtype)
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def pad_statics(statics: StaticArrays, multiple: int) -> Tuple[StaticArrays, int]:
    """Pad the node axis to a multiple of the shard count with dead nodes."""
    n = statics.alloc.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return statics, 0
    return (
        statics._replace(
            alloc=_pad_axis(statics.alloc, 0, pad, 0.0),
            static_mask=_pad_axis(statics.static_mask, 1, pad, False),
            vol_mask=_pad_axis(statics.vol_mask, 1, pad, False),
            node_pref=_pad_axis(statics.node_pref, 1, pad, 0.0),
            taint_intol=_pad_axis(statics.taint_intol, 1, pad, 0.0),
            static_score=_pad_axis(statics.static_score, 1, pad, 0.0),
            avoid_pen=_pad_axis(statics.avoid_pen, 1, pad, 0.0),
            node_dom=_pad_axis(statics.node_dom, 1, pad, -1),
            node_dom_small=_pad_axis(statics.node_dom_small, 1, pad, -1),
            has_storage=_pad_axis(statics.has_storage, 0, pad, False),
            vg_cap=_pad_axis(statics.vg_cap, 0, pad, 0.0),
            vg_name_id=_pad_axis(statics.vg_name_id, 0, pad, -1),
            sdev_cap=_pad_axis(statics.sdev_cap, 0, pad, 0.0),
            sdev_media=_pad_axis(statics.sdev_media, 0, pad, -1),
            gpu_dev_exists=_pad_axis(statics.gpu_dev_exists, 0, pad, False),
            gpu_total=_pad_axis(statics.gpu_total, 0, pad, 0.0),
            attach_limits=_pad_axis(statics.attach_limits, 0, pad, 0.0),
            node_valid=_pad_axis(statics.node_valid, 0, pad, False),
        ),
        pad,
    )


def pad_state(state: SchedState, pad: int) -> SchedState:
    if pad == 0:
        return state
    return state._replace(
        free=_pad_axis(state.free, 0, pad, 0.0),
        cnt_match=_pad_axis(state.cnt_match, 1, pad, 0.0),
        cnt_own_anti=_pad_axis(state.cnt_own_anti, 1, pad, 0.0),
        cnt_own_aff=_pad_axis(state.cnt_own_aff, 1, pad, 0.0),
        w_own_aff_pref=_pad_axis(state.w_own_aff_pref, 1, pad, 0.0),
        w_own_anti_pref=_pad_axis(state.w_own_anti_pref, 1, pad, 0.0),
        vg_free=_pad_axis(state.vg_free, 0, pad, 0.0),
        sdev_free=_pad_axis(state.sdev_free, 0, pad, False),
        gpu_free=_pad_axis(state.gpu_free, 0, pad, 0.0),
        ports_used=_pad_axis(state.ports_used, 0, pad, 0.0),
        vols_any=_pad_axis(state.vols_any, 0, pad, 0.0),
        vols_rw=_pad_axis(state.vols_rw, 0, pad, 0.0),
    )


def statics_sharding(mesh: Mesh) -> StaticArrays:
    """A StaticArrays pytree of NamedShardings: node axis split over `mesh`."""
    lead = NamedSharding(mesh, P(NODE_AXIS))  # [N] / [N, ...]
    lead2 = NamedSharding(mesh, P(NODE_AXIS, None))
    trail = NamedSharding(mesh, P(None, NODE_AXIS))  # [G, N] / [K, N]
    rep = NamedSharding(mesh, P())
    return StaticArrays(
        alloc=lead2,
        static_mask=trail,
        vol_mask=trail,
        node_pref=trail,
        taint_intol=trail,
        static_score=trail,
        avoid_pen=trail,
        node_dom=trail,
        key_kind=rep,
        node_dom_small=trail,
        term_topo=rep,
        ip_of=rep,
        g_terms=rep,
        s_match=rep,
        a_aff_req=rep,
        a_anti_req=rep,
        w_aff_pref=rep,
        w_anti_pref=rep,
        spread_hard=rep,
        spread_soft=rep,
        ss_host=rep,
        ss_zone=rep,
        ports_req=rep,
        vol_rw_req=rep,
        vol_ro_req=rep,
        vol_att_req=rep,
        vol_class_mask=rep,
        attach_limits=lead2,
        has_storage=lead,
        vg_cap=lead2,
        vg_name_id=lead2,
        sdev_cap=lead2,
        sdev_media=lead2,
        gpu_dev_exists=lead2,
        gpu_total=lead,
        score_w=rep,
        node_valid=lead,
    )


def compact_state_sharding(mesh: Mesh) -> CompactState:
    """Shardings for the domain-tabular carried state (engine/state.py):
    dense [., N] row planes keep the node axis split, the [Rt, D]
    histograms and [T] totals replicate (they are the small part — a few
    KB — which is exactly why the compact carry moves fewer bytes per
    GSPMD reshard)."""
    lead2 = NamedSharding(mesh, P(NODE_AXIS, None))
    trail = NamedSharding(mesh, P(None, NODE_AXIS))
    rep = NamedSharding(mesh, P())
    return CompactState(
        free=lead2,
        cm_tab=rep,
        cm_dense=trail,
        cnt_total=rep,
        oa_tab=rep,
        oa_dense=trail,
        of_tab=rep,
        of_dense=trail,
        wa_tab=rep,
        wa_dense=trail,
        wn_tab=rep,
        wn_dense=trail,
        vg_free=lead2,
        sdev_free=lead2,
        gpu_free=lead2,
        ports_used=lead2,
        vols_any=lead2,
        vols_rw=lead2,
    )


def state_sharding(mesh: Mesh) -> SchedState:
    lead2 = NamedSharding(mesh, P(NODE_AXIS, None))
    trail = NamedSharding(mesh, P(None, NODE_AXIS))  # [T, N] per-node counts
    rep = NamedSharding(mesh, P())
    return SchedState(
        free=lead2,
        cnt_match=trail,
        cnt_total=rep,
        cnt_own_anti=trail,
        cnt_own_aff=trail,
        w_own_aff_pref=trail,
        w_own_anti_pref=trail,
        vg_free=lead2,
        sdev_free=lead2,
        gpu_free=lead2,
        ports_used=lead2,
        vols_any=lead2,
        vols_rw=lead2,
    )


# Compiled-callable cache shared by every sharded engine on the same mesh.
# The per-instance caches this replaces made compiled executables die with
# their engine: the incremental planner builds a FRESH engine per candidate
# probe, so each probe re-jitted (and re-compiled) every scan and round
# body.  jax.jit callables internally cache per input shape, so one callable
# per (mesh, static config) shared across instances is exactly the reuse the
# probe sweep needs.  Keyed by the Mesh object itself (hashable; equal
# meshes share).  LRU-capped: keys carry per-workload statics (k_cap,
# n_domains), so a long-lived process running many different simulations
# would otherwise grow compiled-executable memory monotonically — one plan's
# working set is a handful of entries, far under the cap.
_SHARDED_JITS: OrderedDict = OrderedDict()
_SHARDED_JITS_CAP = 64


def _cached_jit(key, build):
    fn = _SHARDED_JITS.get(key)
    if fn is None:
        fn = _SHARDED_JITS[key] = build()
        while len(_SHARDED_JITS) > _SHARDED_JITS_CAP:
            _SHARDED_JITS.popitem(last=False)
    else:
        _SHARDED_JITS.move_to_end(key)
    return fn


def build_sharded_scan(mesh: Mesh, flags: StepFlags = StepFlags()):
    """Compile the placement scan with the node axis laid out over `mesh`."""
    st_spec = statics_sharding(mesh)
    state_spec = state_sharding(mesh)
    rep = NamedSharding(mesh, P())
    pods_rep = None  # resolved at call time: every per-pod array is replicated

    def _scan_fn(statics, state, pods):
        count_trace("scan")
        return jax.lax.scan(partial(schedule_step, statics, flags=flags), state, pods)

    return jax.jit(
        _scan_fn,
        in_shardings=(st_spec, state_spec, pods_rep),
        out_shardings=(state_spec, (rep, rep, rep, rep, rep)),
        donate_argnums=(1,),
    )


def build_sharded_wavefront(mesh: Mesh, flags: StepFlags, spec: tuple):
    """Compile the speculative wavefront call (scan.wavefront_scan — the
    verify-and-rollback batcher for same-group lean runs) with the node
    axis laid out over `mesh`.  `spec` is scan.wave_static_spec's
    (hard, pref, heavy, key_kinds, n_domains) specialization tail.  Placements
    stay bit-identical to the unsharded wavefront (dead-node padding is
    unselectable and the reduced carries shard with the node axis)."""
    st_spec = statics_sharding(mesh)
    state_spec = state_sharding(mesh)
    rep = NamedSharding(mesh, P())

    def fn(statics, state, pods):
        count_trace("wave")
        return wavefront_scan(statics, state, pods, flags, *spec)

    return jax.jit(
        fn,
        in_shardings=(st_spec, state_spec, None),
        out_shardings=(state_spec, (rep, rep, rep, rep, rep), rep),
        donate_argnums=(1,),
    )


class _MeshMixin:
    """Shared mesh plumbing for the sharded engines: input padding/layout and
    the mesh-wide compiled-scan cache."""

    def _init_mesh(self, mesh: Mesh) -> None:
        self.mesh = mesh
        self._shards = node_shard_count(mesh)

    def _shard_inputs(self, statics: StaticArrays, state: SchedState):
        statics, _ = pad_statics(statics, self._shards)
        # a state carried over from the previous batch is already padded
        state = pad_state(state, statics.alloc.shape[0] - state.free.shape[0])
        statics = jax.device_put(statics, statics_sharding(self.mesh))
        state = jax.device_put(state, state_sharding(self.mesh))
        return statics, state

    def _sharded_scan_for(self, flags: StepFlags):
        return _cached_jit(
            ("scan", self.mesh, flags),
            lambda: build_sharded_scan(self.mesh, flags),
        )

    def _aot_scan(self, flags: StepFlags):
        # flags are baked into the mesh-compiled callable; the pipeline key
        # carries them through the name (the mesh itself is engine-fixed)
        return ("sharded_scan", flags), self._sharded_scan_for(flags), ()

    def _aot_wave(self, flags: StepFlags, spec: tuple):
        fn = _cached_jit(
            ("wave", self.mesh, flags, spec),
            lambda: build_sharded_wavefront(self.mesh, flags, spec),
        )
        return ("sharded_wave", flags, spec), fn, ()

    @staticmethod
    def _prefetch_pods(tree):
        # no-op: the sharded jits shard replicated pod inputs on entry; a
        # prefetch committed to one device would fight the mesh layout
        return tree

    def _compress_call(self, spec_dev, state):
        # mesh-compiled compression: carried compact planes keep the
        # node-axis layout (compact_state_sharding) between batches, so
        # the next expansion resharding moves only the small histograms.
        # No donation — the dtype-narrowed outputs cannot alias the f32
        # inputs (see the audit note in engine/state.py).
        fn = _cached_jit(
            ("compress", self.mesh),
            lambda: jax.jit(
                _compress_state_fn,
                out_shardings=compact_state_sharding(self.mesh),
            ),
        )
        return fn(spec_dev, state)

    def _expand_call(self, spec_dev, cstate, nds):
        fn = _cached_jit(
            ("expand", self.mesh),
            lambda: jax.jit(
                _expand_state_fn, out_shardings=state_sharding(self.mesh)
            ),
        )
        return fn(spec_dev, cstate, nds)

    def _delta_direct_call(self, statics, dspec, ndom, nds, cstate, entries):
        # mesh-compiled direct compact-delta apply: outputs keep the
        # carried compact layout between batches.  The step reads no
        # node-axis statics field (only group/term-axis rows), so pairing
        # the unpadded statics with a shard-padded carry is safe — the
        # explicit ndom/nds maps are built at carry width.  Non-donating,
        # like the base call (shared compact snapshots).
        fn = _cached_jit(
            ("delta_direct", self.mesh),
            lambda: jax.jit(
                _apply_placement_deltas_compact_fn,
                out_shardings=compact_state_sharding(self.mesh),
            ),
        )
        return fn(statics, dspec, ndom, nds, cstate, entries)

    def _precompile_shapes(self, statics_sds, state_sds):
        """Shard-padded executable signatures for the precompiler: the
        node axis grows to the shard multiple exactly as `_shard_inputs`
        pads the concrete arrays."""
        statics_sds, _ = pad_statics(statics_sds, self._shards)
        state_sds = pad_state(
            state_sds,
            statics_sds.alloc.shape[0] - state_sds.free.shape[0],
        )
        return statics_sds, state_sds


class ShardedEngine(_MeshMixin, Engine):
    """Engine whose scan runs with the node axis sharded over a mesh.

    Drop-in for `Engine` inside `simtpu.api.Simulator`: identical placements
    (dead-node padding is unselectable), multi-chip execution.
    """

    def __init__(self, tensorizer, mesh: Mesh):
        super().__init__(tensorizer)
        self._init_mesh(mesh)

    def _dispatch(self, statics: StaticArrays, state: SchedState, pods, flags: StepFlags):
        # shard the node axis once, then let the base class chunk the scan
        # (pow2 pod chunks + term-row-sliced count planes); _scan_call
        # routes every chunk through the mesh-compiled scan
        statics, state = self._shard_inputs(statics, state)
        return super()._dispatch(statics, state, pods, flags)


def build_sharded_rounds(
    mesh: Mesh,
    n_domains: int,
    k_cap: int,
    flags: StepFlags,
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    """Compile the bulk multi-round scan with the node axis over `mesh`."""
    from ..engine.rounds import rounds_scan

    st_spec = statics_sharding(mesh)
    state_spec = state_sharding(mesh)
    rep = NamedSharding(mesh, P())

    def fn(statics, state, seg_pods, ks):
        count_trace("rounds")
        return rounds_scan(
            statics, state, seg_pods, ks, n_domains, k_cap, flags, quota,
            self_aff, ext_mats,
        )

    return jax.jit(
        fn,
        in_shardings=(st_spec, state_spec, None, rep),
        out_shardings=(state_spec, (rep, rep, rep, rep)),
        donate_argnums=(1,),
    )


def build_sharded_rounds_sliced(
    mesh: Mesh,
    n_domains: int,
    k_cap: int,
    flags: StepFlags,
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    """Compile the fused slice→rounds→scatter bulk call over `mesh` (the
    sharded analog of `rounds._round_place_many_sliced`)."""
    from ..engine.rounds import rounds_scan_sliced

    st_spec = statics_sharding(mesh)
    state_spec = state_sharding(mesh)
    rep = NamedSharding(mesh, P())

    def fn(statics, state, rows, g_terms_c, term_topo_c, ip_of_c, seg_pods, ks):
        count_trace("rounds")
        return rounds_scan_sliced(
            statics, state, rows, g_terms_c, term_topo_c, ip_of_c,
            seg_pods, ks, n_domains, k_cap, flags, quota, self_aff,
            ext_mats,
        )

    return jax.jit(
        fn,
        in_shardings=(st_spec, state_spec, rep, rep, rep, rep, None, rep),
        out_shardings=(state_spec, (rep, rep, rep, rep)),
        donate_argnums=(1,),
    )


class ShardedRoundsEngine(_MeshMixin, RoundsEngine):
    """Bulk rounds engine with every node-indexed array laid out over a
    device mesh: rounds, serial fallbacks and leftovers all execute under
    GSPMD, composing the two parallel axes of this framework (bulk pod
    runs × sharded nodes). Placements are identical to the unsharded
    RoundsEngine (dead-node padding is unselectable)."""

    def __init__(self, tensorizer, mesh: Mesh):
        super().__init__(tensorizer)
        self._init_mesh(mesh)

    def _dispatch(self, statics, state, pods, flags):
        statics, state = self._shard_inputs(statics, state)
        # pods stay host-side: segments slice them and the jits shard
        # replicated inputs on entry
        return super()._dispatch(statics, state, pods, flags)

    def _aot_bulk(
        self, n_domains, k_cap, flags, quota=False, self_aff=False,
        ext_mats=False,
    ):
        fn = _cached_jit(
            ("rounds", self.mesh, n_domains, k_cap, flags, quota, self_aff,
             ext_mats),
            lambda: build_sharded_rounds(
                self.mesh, n_domains, k_cap, flags, quota, self_aff, ext_mats
            ),
        )
        name = (
            "sharded_rounds", n_domains, k_cap, flags, quota, self_aff,
            ext_mats,
        )
        return name, fn, ()

    def _aot_bulk_sliced(
        self, n_domains, k_cap, flags, quota=False, self_aff=False,
        ext_mats=False,
    ):
        fn = _cached_jit(
            ("rounds_sliced", self.mesh, n_domains, k_cap, flags, quota,
             self_aff, ext_mats),
            lambda: build_sharded_rounds_sliced(
                self.mesh, n_domains, k_cap, flags, quota, self_aff, ext_mats
            ),
        )
        name = (
            "sharded_rounds_sliced", n_domains, k_cap, flags, quota,
            self_aff, ext_mats,
        )
        return name, fn, ()


class MaskedShardedRoundsEngine(ShardedRoundsEngine):
    """`ShardedRoundsEngine` restricted to a candidate cluster: the planner's
    `node_valid` mask (dead rows for clone nodes beyond the candidate's
    size) composes with the statics BEFORE the shard padding, so the
    sharding's own dead-node pad mask stacks on top and placements stay
    bit-identical to the single-device `MaskedRoundsEngine` path.  The
    mesh-sharded counterpart the incremental planner uses for base
    placement, completion probes, and the `verify=True` fresh re-runs."""

    def __init__(self, tensorizer, mesh: Mesh, node_valid: np.ndarray):
        super().__init__(tensorizer, mesh)
        self.node_valid = np.asarray(node_valid, bool)

    def _shard_inputs(self, statics: StaticArrays, state: SchedState):
        statics = statics._replace(
            node_valid=statics.node_valid & jnp.asarray(self.node_valid)
        )
        return super()._shard_inputs(statics, state)
