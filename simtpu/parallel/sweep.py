"""Batched capacity-planning sweep: all candidate cluster sizes at once.

The reference finds the minimum node-add count with up to 101 *serial* full
re-simulations, building a fresh simulator per candidate
(`pkg/apply/apply.go:183-233`, `pkg/type/const.go:51`). Here the candidate
axis becomes a tensor dimension: tensorize ONE cluster containing the base
nodes plus `max_new` template clones, mark per-candidate membership with a
`node_valid [S, N]` mask, and `vmap` the placement scan over S. One XLA
compilation evaluates every candidate; on a mesh the S axis shards over
"sweep" (DCN/ICI data parallelism) and the node axis over "nodes".

DaemonSet semantics: clone nodes get their DaemonSet pods expanded like real
nodes, so candidate i must ignore failures of pods pinned to clones >= i
(those pods don't exist in candidate i's cluster — the reference equivalently
only ever creates DS pods for nodes present in that iteration,
`pkg/simulator/core.go:72-82`).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants as C
from ..core.objects import AppResource, ResourceTypes, set_label
from ..core.tensorize import Tensorizer
from ..engine.scan import (
    StaticArrays,
    StepFlags,
    build_pod_arrays,
    flags_from,
    schedule_step,
    statics_from,
)
from ..engine.state import build_state
from ..workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)
from .mesh import NODE_AXIS, SWEEP_AXIS
from .sharded import pad_state, pad_statics, state_sharding, statics_sharding


@partial(jax.jit, static_argnums=(4,))
def _sweep_scan(
    statics: StaticArrays,
    valid_s: jnp.ndarray,
    state,
    pods,
    flags: StepFlags = StepFlags(),
):
    """vmap the scan over the candidate axis; only node_valid varies.

    Deliberately NOT donated (donation audit, docs/memory.md): donation
    only enables input→output aliasing, and no input here can alias an
    output — the [N]-shaped base carry and [S]-masks come out vmapped to
    [S, N] — so donate_argnums would buy nothing and emit the
    donated-buffers-unusable warning on every sweep.  XLA frees the
    inputs at last use regardless."""

    def one(valid):
        st = statics._replace(node_valid=statics.node_valid & valid)
        return jax.lax.scan(partial(schedule_step, st, flags=flags), state, pods)

    return jax.vmap(one)(valid_s)


def assemble_planning_problem(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    max_new: int,
    extended_resources: Sequence[str] = (),
):
    """One tensorization covering the base cluster plus `max_new` template
    clones, with the ordered pod sequence exactly as simulate() submits it
    (cluster pods + DaemonSet expansion over ALL nodes incl. clones, then
    each app's sorted pods). Shared by the batched sweep and the
    incremental planner — candidate membership is expressed afterwards via
    `node_valid` masks, never by re-tensorizing.

    Returns (tensorizer, all_nodes, n_base, ordered_pods).
    """
    from ..plan.capacity import new_fake_nodes

    base_nodes = list(cluster.nodes)
    n_base = len(base_nodes)
    all_nodes = base_nodes + new_fake_nodes(new_node, max_new)

    ordered: List[dict] = []
    work = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    work.nodes = all_nodes
    cluster_pods = get_valid_pods_exclude_daemonset(work)
    for ds in work.daemon_sets:
        cluster_pods.extend(make_valid_pods_by_daemonset(ds, all_nodes))
    ordered.extend(cluster_pods)
    from ..api import _sort_app_pods

    for app in apps:
        pods = get_valid_pods_exclude_daemonset(app.resource)
        for ds in app.resource.daemon_sets:
            pods.extend(make_valid_pods_by_daemonset(ds, all_nodes))
        for pod in pods:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        ordered.extend(_sort_app_pods(pods))

    tensorizer = Tensorizer(
        all_nodes,
        extended_resources,
        storage_classes=list(cluster.storage_classes),
        services=list(cluster.services),
        pvcs=list(cluster.persistent_volume_claims),
        pvs=list(cluster.persistent_volumes),
    )
    return tensorizer, all_nodes, n_base, ordered


def sweep_feasibility(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    candidates: Sequence[int],
    extended_resources: Sequence[str] = (),
    mesh=None,
    sched_config=None,
):
    """Run every candidate clone-count in one batched placement.

    Returns (failures [S] int array — unscheduled-pod count per candidate,
    n_base, pods) where `pods` is the concatenated ordered pod list.
    """
    candidates = np.asarray(list(candidates), np.int32)
    max_new = int(candidates.max()) if len(candidates) else 0
    tensorizer, all_nodes, n_base, ordered = assemble_planning_problem(
        cluster, apps, new_node, max_new, extended_resources
    )
    batch = tensorizer.add_pods(ordered)
    tensors = tensorizer.freeze()
    statics = statics_from(tensors, sched_config)
    r = tensors.alloc.shape[1]
    _, pods_arrays = build_pod_arrays(batch, r)
    state = build_state(
        tensors,
        np.zeros(0, np.int32),
        np.zeros(0, np.int32),
        np.zeros((0, r), np.float32),
        None,
    )

    n_total = len(all_nodes)
    # valid_s[s, j]: base nodes always; clone j-n_base iff < candidates[s]
    clone_idx = np.arange(n_total) - n_base
    valid_s = (clone_idx[None, :] < candidates[:, None]) | (clone_idx[None, :] < 0)

    n_cand = len(candidates)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shards = mesh.shape[NODE_AXIS]
        statics, pad = pad_statics(statics, shards)
        state = pad_state(state, pad)
        if pad:
            valid_s = np.pad(valid_s, ((0, 0), (0, pad)))
        # the candidate axis must also divide its mesh axis: replicate the
        # last candidate row as padding and drop those rows from the output
        s_pad = (-n_cand) % mesh.shape[SWEEP_AXIS]
        if s_pad:
            valid_s = np.concatenate(
                [valid_s, np.repeat(valid_s[-1:], s_pad, axis=0)]
            )
        statics = jax.device_put(statics, statics_sharding(mesh))
        state = jax.device_put(state, state_sharding(mesh))
        valid_arr = jax.device_put(
            jnp.asarray(valid_s), NamedSharding(mesh, P(SWEEP_AXIS, NODE_AXIS))
        )
        pods_arrays = jax.device_put(pods_arrays, NamedSharding(mesh, P()))
    else:
        valid_arr = jnp.asarray(valid_s)

    _, outs = _sweep_scan(
        statics, valid_arr, state, pods_arrays, flags_from(tensors, batch.ext)
    )
    nodes_sp = np.asarray(outs[0])[:n_cand]  # [S, P] chosen node (-1 = failed)

    # per-candidate failure count, ignoring pods that only exist on clones
    # beyond the candidate's size (pins into invalid clone rows)
    pin = np.asarray(batch.pin)
    failures = np.zeros(len(candidates), np.int64)
    for s, cand in enumerate(candidates):
        phantom = (pin >= 0) & (pin - n_base >= cand)
        failures[s] = int(((nodes_sp[s] < 0) & ~phantom).sum())
    return failures, n_base, ordered


def plan_capacity_batched(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    max_new_nodes: int = C.MAX_NUM_NEW_NODE,
    extended_resources: Sequence[str] = (),
    mesh=None,
    progress=None,
    sched_config=None,
    corrected_ds_overhead: bool = False,
):
    """Batched replacement for the serial min-node-add search.

    Evaluates all candidate counts 0..max_new_nodes in one compiled sweep,
    then re-runs the precise serial simulation at the winning count to
    produce the full report-grade `SimulateResult` (the sweep's phantom-pod
    bookkeeping makes its placements candidate-exact, but reports want node
    annotations built for exactly the winning cluster).
    """
    from ..plan.capacity import PlanResult, plan_capacity, satisfy_resource_setting
    from ..api import simulate

    say = progress or (lambda s: None)
    # parity with the serial planner: the largest candidate ever simulated is
    # max_new_nodes-1 (the reference's `for i := 0; i < MaxNumNewNode` walk,
    # apply.go:183; see plan_capacity)
    candidates = list(range(max_new_nodes))
    say(f"sweeping {len(candidates)} candidate sizes in one batch")
    failures, _, _ = sweep_feasibility(
        cluster, apps, new_node, candidates, extended_resources, mesh, sched_config
    )
    feasible = np.flatnonzero(failures == 0)
    probes = {int(c): int(f) for c, f in zip(candidates, failures)}
    if len(feasible) == 0:
        # fall back to the serial planner for its rich infeasibility
        # diagnostics (apply.go:213-231 semantics)
        return plan_capacity(
            cluster,
            apps,
            new_node,
            max_new_nodes,
            extended_resources,
            search="binary",
            progress=progress,
            sched_config=sched_config,
            corrected_ds_overhead=corrected_ds_overhead,
        )
    from ..plan.capacity import new_fake_nodes

    # occupancy caps (MaxCPU/MaxMemory/MaxVG) are part of feasibility and
    # monotone in node count — the reference keeps adding nodes on a cap
    # miss (`apply.go:199-207`), so walk the schedulable candidates upward
    result, reason = None, ""
    for best in (int(c) for c in feasible):
        say(f"candidate add = {best} node(s); re-simulating exactly")
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.nodes = list(cluster.nodes) + new_fake_nodes(new_node, best)
        result = simulate(
            trial, apps, extended_resources=extended_resources, sched_config=sched_config
        )
        ok, reason = satisfy_resource_setting(result)
        if ok:
            return PlanResult(True, best, result, "Success!", probes)
        say(reason.rstrip("\n"))
    return PlanResult(False, int(feasible[-1]), result, reason, probes)
