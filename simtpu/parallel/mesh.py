"""Device-mesh construction for the sharded simulator.

The reference's only intra-cycle parallelism is 16 goroutines chunking the
node loop (`vendor/k8s.io/kubernetes/pkg/scheduler/internal/parallelize/
parallelism.go:27`, used from `core/generic_scheduler.go:292,333`). On TPU the
node axis becomes a sharded tensor dimension instead: a 2-D logical mesh

    ("sweep", "nodes")

where "nodes" shards cluster-state arrays across ICI (filter = elementwise
mask on the local shard, select = cross-shard argmax collective) and "sweep"
is the embarrassingly-parallel candidate-cluster-size axis of the capacity
planner (`pkg/apply/apply.go:183`'s 0..100 loop, run as a batch instead).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SWEEP_AXIS = "sweep"
NODE_AXIS = "nodes"


def make_mesh(
    devices: Optional[Sequence] = None,
    sweep: int = 1,
    n_devices: Optional[int] = None,
) -> Mesh:
    """Build the ("sweep", "nodes") mesh over `devices`.

    `sweep` devices are dedicated to the candidate-size axis; the rest of the
    chips form the node-sharding axis. With sweep=1 (default) all chips shard
    the node axis — the right layout for a single large simulation.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    devices = list(devices)
    if len(devices) % sweep:
        raise ValueError(f"{len(devices)} devices not divisible by sweep={sweep}")
    grid = np.asarray(devices).reshape(sweep, len(devices) // sweep)
    return Mesh(grid, (SWEEP_AXIS, NODE_AXIS))


def planner_mesh() -> Optional[Mesh]:
    """The mesh the capacity planner shards over when left on auto: every
    visible device on the flat "nodes" axis (sweep=1 — one plan is one
    simulation at a time; the candidate axis is searched, not vmapped).
    None on single-device topologies — the caller should then stay on the
    unsharded engines rather than pay mesh-layout overhead for no
    parallelism."""
    devices = jax.devices()
    if len(devices) < 2:
        return None
    return make_mesh(devices, sweep=1)


def node_sharding(mesh: Mesh, rank_after_node: int = 0) -> NamedSharding:
    """Sharding for an array whose LEADING axis is the node axis."""
    return NamedSharding(mesh, P(NODE_AXIS, *([None] * rank_after_node)))


def trailing_node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [G, N]-shaped array (node axis last)."""
    return NamedSharding(mesh, P(None, NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def node_shard_count(mesh: Mesh) -> int:
    return mesh.shape[NODE_AXIS]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    sweep: Optional[int] = None,
) -> Mesh:
    """Join a multi-host run and build the global ("sweep", "nodes") mesh.

    The reference has no distributed backend at all (single process,
    SURVEY.md §2.3); this is the TPU-native equivalent: `jax.distributed`
    wires the hosts (ICI within a slice, DCN across slices), and the returned
    mesh spans every global device. The natural layout is "sweep" across DCN
    (each slice evaluates candidate cluster sizes independently — zero
    cross-slice traffic inside a simulation) and "nodes" across ICI, which
    `sweep=<number of slices>` produces when slices are enumerated
    contiguously, the JAX default.

    Arguments default to the TPU environment's auto-detection (GKE/Cloud TPU
    set them via environment); pass them explicitly elsewhere. Call once per
    process before any other JAX use.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    if sweep is None:
        # one sweep row per slice when the topology exposes slice indices,
        # else a flat node axis
        slice_ids = {getattr(d, "slice_index", 0) for d in jax.devices()}
        sweep = len(slice_ids) if len(slice_ids) > 1 else 1
    return make_mesh(jax.devices(), sweep=sweep)
