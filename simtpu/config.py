"""The `simon/v1alpha1 Config` CR schema and loader.

Mirrors `pkg/api/v1alpha1/types.go:1-29` and the validation in
`pkg/apply/apply.go:247-284`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List

import yaml


@dataclass
class AppInfo:
    name: str
    path: str
    chart: bool = False


@dataclass
class Cluster:
    custom_config: str = ""
    kube_config: str = ""


@dataclass
class SimonConfig:
    cluster: Cluster
    app_list: List[AppInfo] = field(default_factory=list)
    new_node: str = ""

    @classmethod
    def from_file(cls, path: str) -> "SimonConfig":
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if doc.get("kind") != "Config":
            raise ValueError(f"{path}: not a simon Config CR (kind={doc.get('kind')!r})")
        spec = doc.get("spec") or {}
        cluster = spec.get("cluster") or {}
        apps = [
            AppInfo(
                name=a.get("name", ""),
                path=a.get("path", ""),
                chart=bool(a.get("chart", False)),
            )
            for a in spec.get("appList") or []
        ]
        return cls(
            cluster=Cluster(
                custom_config=cluster.get("customConfig", "") or "",
                kube_config=cluster.get("kubeConfig", "") or "",
            ),
            app_list=apps,
            new_node=spec.get("newNode", "") or "",
        )


def validate_config(cfg: SimonConfig, scheduler_config: str = "") -> None:
    """Path/exclusivity validation (`pkg/apply/apply.go:247-284`)."""
    has_kube = bool(cfg.cluster.kube_config)
    has_custom = bool(cfg.cluster.custom_config)
    if has_kube == has_custom:
        raise ValueError("only one of kubeConfig and customConfig must be set")
    for what, path in (
        ("kubeConfig", cfg.cluster.kube_config),
        ("customConfig", cfg.cluster.custom_config),
        ("scheduler config", scheduler_config),
        ("newNode", cfg.new_node),
    ):
        if path and not os.path.exists(path):
            raise ValueError(f"invalid path of {what}: {path}")
    for app in cfg.app_list:
        if not os.path.exists(app.path):
            raise ValueError(f"invalid path of {app.name} app: {app.path}")
