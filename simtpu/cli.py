"""The `simtpu` command-line interface.

Mirrors the reference's cobra tree `simon {apply, version, gen-doc}`
(`cmd/simon/simon.go:26-42`) with the same `apply` flags
(`cmd/apply/apply.go:26-37`): -f/--simon-config, --default-scheduler-config,
--use-greed, -i/--interactive, --extended-resources. Log level comes from the
`LogLevel` env var (`cmd/simon/simon.go:44-64`).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import List, Optional

from . import __version__, constants as C
from .plan.capacity import Applier, ApplierOptions
from .report import report

log = logging.getLogger("simtpu")


def _setup_logging() -> None:
    level = os.environ.get("LogLevel", "info").lower()
    logging.basicConfig(
        level={"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING}.get(
            level, logging.INFO
        ),
        format="%(levelname)s %(message)s",
    )


def _interactive_select(names: List[str]) -> List[str]:
    """Multi-select stand-in for survey.Ask (`pkg/apply/apply.go:153-169`)."""
    print("Confirm your apps (comma-separated indices, empty = all):")
    for i, n in enumerate(names):
        print(f"  [{i}] {n}")
    raw = input("> ").strip()
    if not raw:
        return names
    picked = []
    for token in raw.split(","):
        token = token.strip()
        if token.isdigit() and int(token) < len(names):
            picked.append(names[int(token)])
        elif token in names:
            picked.append(token)
    return picked


def _plan_json(plan, resilience: dict = None) -> str:
    """Machine-readable plan summary for scripted/CI consumers: includes
    the engine record (search/bulk/shards + auto flags), so the
    non-reference-exact fast path is detectable from the OUTPUT, not just
    a stderr notice that pipelines routinely drop.  `resilience` attaches
    the post-plan fault-sweep counters (`--faults`).

    `schema_version` stamps the document layout (obs.metrics
    SCHEMA_VERSION — also reported by `simtpu version --json`), and
    `metrics` is the unified observability block (ISSUE 8): one flat
    name → value dict whose values the legacy engine-block families
    (engine.fetch / engine.backoff / engine.wavefront /
    engine.state_bytes / engine.audit) alias bit-equally for one
    release."""
    import json

    doc = {
        "schema_version": plan.schema_version,
        "success": plan.success,
        "nodes_added": plan.nodes_added,
        "message": plan.message,
        # the partial-result contract (docs/robustness.md): True when a
        # deadline/SIGINT interrupted the search and nodes_added reports
        # only the best candidate verified so far (-1 = none)
        "partial": plan.partial,
        "engine": plan.engine,
        "metrics": plan.metrics,
        "probes": {str(k): v for k, v in sorted(plan.probes.items())},
        "timings": {k: round(v, 3) for k, v in plan.timings.items()},
        "compiles": plan.compiles,
        "unscheduled": (
            len(plan.result.unscheduled_pods) if plan.result is not None else None
        ),
    }
    if isinstance(doc.get("engine"), dict):
        # grow.* counter family (append-only vocabulary growth): zero for
        # a one-shot apply unless the run extended a warm carry, but the
        # block is ALWAYS present so consumers need no feature probe
        from .engine.state import grow_counters_doc

        doc["engine"] = dict(doc["engine"])
        doc["engine"]["grow"] = grow_counters_doc()
    if plan.explain:
        # the versioned decision-observability block (simtpu/explain,
        # --explain): failure breakdowns + bottleneck analysis
        doc["explain"] = plan.explain
    if resilience is not None:
        doc["resilience"] = resilience
    return json.dumps(doc)


def _with_obs(args, fn):
    """Run one CLI command body under the --trace/--profile flags
    (ISSUE 8, docs/observability.md): arm the span tracer for --trace,
    wrap the body in a jax.profiler capture for --profile, and export
    the Perfetto trace file on the way out — success or failure, so an
    aborted run still leaves its timeline behind."""
    import contextlib

    from .obs import trace as obs_trace
    from .obs.profile import profile_capture

    trace_path = getattr(args, "trace", "") or ""
    if trace_path and not obs_trace.enabled():
        obs_trace.enable()
    prof = getattr(args, "profile", "") or ""
    try:
        with profile_capture(prof) if prof else contextlib.nullcontext():
            return fn()
    finally:
        if trace_path:
            path = obs_trace.export_trace(trace_path)
            print(
                f"simtpu: span trace written to {path} "
                "(load at https://ui.perfetto.dev)",
                file=sys.stderr,
            )


def _flight_exit(code: int, reason: str, args, plan=None) -> int:
    """Dump a flight-recorder bundle (obs/flight.py) for a structured
    failure exit — partial (3), audit (4), OOM exhaustion — and return
    `code`.  The bundle lands next to the --checkpoint dir when one was
    given, else the working directory (SIMTPU_FLIGHT_DIR overrides,
    SIMTPU_FLIGHT=0 disables).  When the plan carries a decision-
    observability block (--explain), its top-K failure breakdown rides
    the bundle — the post-mortem then says WHY the pods didn't place,
    not just that they didn't."""
    from .obs.flight import dump_flight

    extra = None
    explain_doc = getattr(plan, "explain", None) if plan is not None else None
    if explain_doc:
        extra = {"explain": explain_doc}
    dump_flight(
        reason,
        code,
        checkpoint=getattr(args, "checkpoint", None) or "",
        engine=plan.engine if plan is not None else None,
        extra=extra,
    )
    return code


class _SweepAuditFailure(Exception):
    """The --faults sweep's base placement failed its audit AND the
    serial-exact fallback did not certify either — the hardest audit
    outcome.  Carries the audit doc so cmd_apply can surface the
    violations/divergence record and return EXIT_AUDIT (a generic
    sweep-failure ValueError would exit 0 with the diagnostics lost)."""

    def __init__(self, message: str, audit_doc: dict):
        super().__init__(message)
        self.audit_doc = audit_doc


def _apply_faults_sweep(applier, plan, spec: str, samples: int, seed: int, progress):
    """Post-plan survivability assessment for `simtpu apply --faults`: one
    batched fault sweep over the WINNING cluster (base + the clones the
    plan added).  Placement for the sweep runs engine-level without
    preemption (the capacity-sweep contract, plan/resilience.py).

    Returns (sweep, base_unplaced, audit_doc): the sweep's drain-from
    placement is independently audited (simtpu/audit) unless opted out,
    with the serial-exact fallback re-placing on failure — a corrupted
    base would silently skew EVERY scenario's verdict."""
    from .audit.checker import (
        audit_enabled,
        audit_placed_cluster,
        inject_divergence_enabled,
    )
    from .core.objects import ResourceTypes
    from .faults import generate_scenarios, place_cluster, sweep_scenarios
    from .plan.capacity import new_fake_nodes

    cluster = applier.load_cluster()
    apps = applier.load_apps()
    if plan.nodes_added:
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.nodes = list(cluster.nodes) + new_fake_nodes(
            applier.load_new_node(), plan.nodes_added
        )
        cluster = trial
    progress(
        f"fault sweep over the winning cluster ({len(cluster.nodes)} nodes, "
        f"faults={spec})"
    )
    pc = place_cluster(
        cluster,
        apps,
        extended_resources=applier.opts.extended_resources,
        sched_config=applier._sched_config(),
    )
    opt_audit = applier.opts.audit
    audit_doc = None
    if audit_enabled() if opt_audit is None else opt_audit:
        pc, audit_doc, hard_fail = audit_placed_cluster(
            pc, progress, inject=inject_divergence_enabled()
        )
        if hard_fail is not None:
            raise _SweepAuditFailure(hard_fail, audit_doc)
    # the sweep's own base placement can differ from the plan's (engine-
    # level, simulate() pod order, no preemption) — pods it strands never
    # enter a requeue, so the count MUST ride the output or the counters
    # silently assess a smaller pod set
    base_unplaced = int((pc.nodes < 0).sum())
    if base_unplaced:
        progress(
            f"{base_unplaced} pod(s) do not place in the sweep's base "
            "placement — survivability is assessed over the placed set only"
        )
    scen = generate_scenarios(cluster.nodes, spec, samples=samples, seed=seed)
    return sweep_scenarios(pc, scen), base_unplaced, audit_doc


def _sweep_json_doc(sweep, spec: str, samples: int, seed: int) -> dict:
    doc = dict(sweep.counters())
    doc.update(
        {
            "spec": spec,
            "samples": samples,
            "seed": seed,
            "worst": [[lbl, n] for lbl, n in sweep.worst()],
            "critical_nodes": [[node, n] for node, n in sweep.critical_nodes()],
            "timings": {k: round(v, 3) for k, v in sweep.timings.items()},
        }
    )
    return doc


def cmd_apply(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_apply(args))


def _cmd_apply(args: argparse.Namespace) -> int:
    opts = ApplierOptions(
        simon_config=args.simon_config,
        default_scheduler_config=args.default_scheduler_config or "",
        use_greed=args.use_greed,
        interactive=args.interactive,
        extended_resources=args.extended_resources or [],
        search=args.search,
        bulk=args.bulk,
        shard=args.shard,
        precompile=args.precompile,
        corrected_ds_overhead=args.corrected_ds_overhead,
        checkpoint=args.checkpoint or "",
        resume=args.resume,
        deadline=args.deadline,
        # first ^C = graceful partial result + flushed checkpoint; second
        # ^C = the default KeyboardInterrupt (durable/deadline.py)
        install_sigint=True,
        audit=args.audit,
        solver=args.solver,
        explain=args.explain,
    )
    def fail_early(exc: Exception) -> int:
        # the --json contract holds on EVERY exit: config/load failures
        # still emit a parseable document on stdout
        if args.json:
            import json

            print(json.dumps({"success": False, "message": str(exc)}))
        print(exc, file=sys.stderr)
        return 1

    if args.json and opts.interactive:
        # the selection menu and input prompt write to stdout, which --json
        # reserves for the machine-readable document
        return fail_early(
            ValueError("--json and --interactive are mutually exclusive")
        )
    if args.faults and opts.interactive:
        # the post-plan fault sweep re-loads the app list from the config;
        # an interactive selection would silently not apply to it
        return fail_early(
            ValueError("--faults and --interactive are mutually exclusive")
        )
    if args.faults:
        # reject a malformed spec BEFORE the (potentially minutes-long)
        # plan runs, not after it succeeded
        from .faults import parse_fault_spec

        try:
            parse_fault_spec(args.faults)
        except ValueError as exc:
            return fail_early(exc)
    try:
        applier = Applier(opts)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    select = _interactive_select if opts.interactive else None

    # with --json, stdout is the machine-readable document — progress
    # narration moves to stderr so the stream stays parseable end-to-end
    progress_stream = sys.stderr if args.json else sys.stdout

    def progress(msg: str) -> None:
        print(f"{C.COLOR_YELLOW}{msg}{C.COLOR_RESET}", file=progress_stream)

    try:
        plan = applier.run(select_apps=select, progress=progress)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    except Exception as exc:
        # an OOM backoff that exhausted its halving budget (single-pod /
        # single-scenario chunk still RESOURCE_EXHAUSTED) escapes here —
        # leave a flight-recorder bundle behind before the traceback
        # (docs/observability.md)
        from .durable.backoff import is_resource_exhausted

        if is_resource_exhausted(exc):
            _flight_exit(1, f"OOM-backoff exhaustion: {exc}", args)
        raise
    fault_sweep, fault_base_unplaced, fault_error = None, 0, None
    fault_audit = None
    if args.faults and plan.success:
        try:
            fault_sweep, fault_base_unplaced, fault_audit = _apply_faults_sweep(
                applier, plan, args.faults, args.fault_samples,
                args.fault_seed, progress,
            )
        except _SweepAuditFailure as exc:
            # the hardest audit outcome: neither the sweep's base
            # placement nor the serial-exact fallback certified — keep
            # the audit doc so the exit code and --json carry it
            fault_error = str(exc)
            fault_audit = exc.audit_doc
            print(f"fault sweep audit failed: {exc}", file=sys.stderr)
        except ValueError as exc:
            # a failed post-plan sweep must not discard the successful
            # plan: record the error alongside it instead
            fault_error = str(exc)
            print(f"fault sweep failed: {exc}", file=sys.stderr)
    if args.json:
        resilience = None
        if fault_sweep is not None:
            resilience = _sweep_json_doc(
                fault_sweep, args.faults, args.fault_samples, args.fault_seed
            )
            resilience["base_unplaced"] = fault_base_unplaced
            if fault_audit is not None:
                resilience["audit"] = fault_audit
        elif fault_error is not None:
            resilience = {"error": fault_error}
            if fault_audit is not None:
                resilience["audit"] = fault_audit
        print(_plan_json(plan, resilience=resilience))
        if plan.partial:
            return _flight_exit(
                EXIT_PARTIAL, "partial result (deadline/SIGINT)", args, plan
            )
        if _audit_failed(plan.audit) or _audit_failed(fault_audit):
            return _flight_exit(
                EXIT_AUDIT, "audit divergence on the primary engine", args,
                plan,
            )
        return 0 if plan.success else 1
    if plan.success:
        print(f"{C.COLOR_GREEN}Success!{C.COLOR_RESET}")
        print(C.COLOR_GREEN, end="")
        print(report(plan.result.node_status, opts.extended_resources))
        print(C.COLOR_RESET, end="")
        if plan.audit:
            from .report import audit_report

            color = C.COLOR_RED if _audit_failed(plan.audit) else C.COLOR_GREEN
            print(f"{color}{audit_report(plan.audit)}{C.COLOR_RESET}")
        if plan.solve:
            from .report import solve_report

            print(solve_report(plan.solve))
        if getattr(plan, "preemption_ignored", False):
            print(
                f"{C.COLOR_YELLOW}warning: specs carry pod priorities, but "
                "the incremental planner never runs preemption — "
                "priority/eviction semantics were IGNORED (use --search "
                f"binary/linear for the preemption path){C.COLOR_RESET}"
            )
        if _audit_failed(fault_audit):
            from .report import audit_report

            print(f"{C.COLOR_RED}{audit_report(fault_audit)}{C.COLOR_RESET}")
        if plan.explain:
            from .report import explain_report

            print(explain_report(plan.explain))
        if fault_sweep is not None:
            from .report import resilience_report

            print(resilience_report(fault_sweep))
            if fault_base_unplaced:
                print(
                    f"{C.COLOR_RED}warning: {fault_base_unplaced} pod(s) "
                    "unplaced before any failure; survivability covers the "
                    f"placed set only{C.COLOR_RESET}"
                )
        if plan.timings:
            phases = "  ".join(f"{k}={v:.2f}s" for k, v in plan.timings.items())
            print(f"phase timings: {phases}")
        if plan.engine:
            # dict-valued entries (the solve record) have their own
            # report section — the one-liner keeps the scalar knobs only
            eng = " ".join(
                f"{k}={v}"
                for k, v in plan.engine.items()
                if not isinstance(v, dict)
            )
            print(f"engine selection: {eng}")
        if _audit_failed(plan.audit) or _audit_failed(fault_audit):
            return _flight_exit(
                EXIT_AUDIT, "audit divergence on the primary engine", args,
                plan,
            )
        return 0
    print(f"{C.COLOR_RED}{plan.message}{C.COLOR_RESET}")
    if _audit_failed(plan.audit):
        from .report import audit_report

        print(f"{C.COLOR_RED}{audit_report(plan.audit)}{C.COLOR_RESET}")
    if plan.explain:
        from .report import explain_report

        print(explain_report(plan.explain))
    if plan.result is not None:
        print(C.COLOR_RED, end="")
        print(report(plan.result.node_status, opts.extended_resources))
        print(C.COLOR_RESET, end="")
    if plan.partial:
        return _flight_exit(
            EXIT_PARTIAL, "partial result (deadline/SIGINT)", args, plan
        )
    if _audit_failed(plan.audit):
        return _flight_exit(
            EXIT_AUDIT, "audit divergence on the primary engine", args, plan
        )
    return 1


def cmd_resilience(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_resilience(args))


def _cmd_resilience(args: argparse.Namespace) -> int:
    """Survivability assessment / N+k planning over the configured cluster
    (simtpu/faults, plan/resilience.py).  Default mode drains + requeues
    every generated failure scenario against the as-is cluster; `--plan`
    searches the minimum newNode clone count whose cluster survives them
    (requires `newNode` in the Config CR)."""
    import json

    opts = ApplierOptions(
        simon_config=args.simon_config,
        default_scheduler_config=args.default_scheduler_config or "",
        extended_resources=args.extended_resources or [],
    )

    def fail_early(exc: Exception) -> int:
        if args.json:
            print(json.dumps({"success": False, "message": str(exc)}))
        print(exc, file=sys.stderr)
        return 1

    try:
        applier = Applier(opts)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    progress_stream = sys.stderr if args.json else sys.stdout

    def progress(msg: str) -> None:
        print(f"{C.COLOR_YELLOW}{msg}{C.COLOR_RESET}", file=progress_stream)

    if not args.plan and (
        args.checkpoint or args.resume or args.deadline is not None
    ):
        # the assessment mode is ONE sweep — there are no candidate
        # boundaries to checkpoint between or to poll a deadline at
        return fail_early(
            ValueError("--checkpoint/--resume/--deadline require --plan "
                       "(the assessment sweep has no candidate "
                       "boundaries)")
        )
    try:
        cluster = applier.load_cluster()
        apps = applier.load_apps()
        sched_config = applier._sched_config()
        if args.plan:
            from .durable import PlanCheckpoint, RunControl, plan_fingerprint
            from .durable.checkpoint import file_digest
            from .plan.resilience import plan_resilience

            new_node = applier.load_new_node()
            checkpoint = None
            if args.checkpoint:
                checkpoint = PlanCheckpoint(
                    args.checkpoint,
                    kind="resilience",
                    fingerprint=plan_fingerprint(
                        cluster, apps, new_node,
                        extra={
                            "spec": args.faults,
                            "quantile": args.quantile,
                            "samples": args.samples,
                            "seed": args.seed,
                            "max_new_nodes": args.max_new_nodes,
                            "extended_resources": list(
                                opts.extended_resources
                            ),
                            # CONTENT digest (see plan/capacity.py):
                            # editing the sched-config between a kill
                            # and a --resume must refuse
                            "sched_config": file_digest(
                                opts.default_scheduler_config
                            ),
                        },
                    ),
                    resume=args.resume,
                )
            elif args.resume:
                raise ValueError("--resume requires --checkpoint DIR")
            control = RunControl(deadline=args.deadline)
            with control.sigint():
                plan = plan_resilience(
                    cluster,
                    apps,
                    new_node,
                    spec=args.faults,
                    quantile=args.quantile,
                    samples=args.samples,
                    seed=args.seed,
                    max_new_nodes=args.max_new_nodes,
                    extended_resources=opts.extended_resources,
                    progress=progress,
                    sched_config=sched_config,
                    checkpoint=checkpoint,
                    control=control,
                    audit=args.audit,
                    solver=args.solver,
                    explain=args.explain,
                )
            if args.json:
                doc = plan.counters()
                doc["partial"] = plan.partial
                doc["message"] = plan.message
                doc["probes"] = {
                    str(i): rec for i, rec in sorted(plan.probes.items())
                }
                if plan.sweep is not None:
                    doc["worst"] = [[lbl, n] for lbl, n in plan.sweep.worst()]
                print(json.dumps(doc))
            else:
                color = C.COLOR_GREEN if plan.success else C.COLOR_RED
                print(f"{color}{plan.message}{C.COLOR_RESET}")
                if plan.success:
                    print(
                        "minimum nodes added for survivability: "
                        f"{plan.nodes_added}"
                    )
                if plan.audit:
                    from .report import audit_report

                    a_color = (
                        C.COLOR_RED if _audit_failed(plan.audit) else C.COLOR_GREEN
                    )
                    print(f"{a_color}{audit_report(plan.audit)}{C.COLOR_RESET}")
                if plan.solve:
                    from .report import solve_report

                    print(solve_report(plan.solve))
                if plan.explain:
                    from .report import explain_report

                    print(explain_report(plan.explain))
                if plan.sweep is not None:
                    from .report import resilience_report

                    print(resilience_report(plan.sweep))
            if plan.partial:
                return _flight_exit(
                    EXIT_PARTIAL, "partial resilience plan (deadline/SIGINT)",
                    args,
                )
            if _audit_failed(plan.audit):
                return _flight_exit(
                    EXIT_AUDIT, "audit divergence on the resilience base "
                    "placement", args,
                )
            return 0 if plan.success else 1

        from .faults import generate_scenarios, place_cluster, sweep_scenarios

        progress(
            f"placing workloads ({len(cluster.nodes)} nodes), then sweeping "
            f"faults={args.faults}"
        )
        pc = place_cluster(
            cluster,
            apps,
            extended_resources=opts.extended_resources,
            bulk=not args.no_bulk,
            sched_config=sched_config,
        )
        from .audit.checker import audit_enabled, inject_divergence_enabled

        audit_doc = None
        if audit_enabled() if args.audit is None else args.audit:
            # the assessment's drain-from placement feeds EVERY scenario
            # verdict — certify it (serial-exact fallback on failure)
            from .audit.checker import audit_placed_cluster

            pc, audit_doc, hard_fail = audit_placed_cluster(
                pc, progress, inject=inject_divergence_enabled()
            )
            if hard_fail is not None:
                if args.json:
                    print(json.dumps({
                        "success": False, "message": hard_fail,
                        "audit": audit_doc,
                    }))
                print(hard_fail, file=sys.stderr)
                return _flight_exit(
                    EXIT_AUDIT, "audit: nothing certified (assessment base "
                    "placement)", args,
                )
        base_unplaced = int((pc.nodes < 0).sum())
        if base_unplaced:
            progress(
                f"{base_unplaced} pod(s) do not place before any failure — "
                "the sweep assesses only the placed set"
            )
        scen = generate_scenarios(
            cluster.nodes, args.faults, samples=args.samples, seed=args.seed
        )
        sweep = sweep_scenarios(pc, scen)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    survived_all = bool(sweep.survival_rate >= 1.0) and base_unplaced == 0
    if args.json:
        doc = _sweep_json_doc(sweep, args.faults, args.samples, args.seed)
        doc["success"] = survived_all
        doc["base_unplaced"] = base_unplaced
        if audit_doc is not None:
            doc["audit"] = audit_doc
        print(json.dumps(doc))
        if _audit_failed(audit_doc):
            return _flight_exit(
                EXIT_AUDIT, "audit divergence on the assessment base "
                "placement", args,
            )
        return 0 if survived_all else 1
    from .report import resilience_report

    color = C.COLOR_GREEN if survived_all else C.COLOR_RED
    print(color, end="")
    print(resilience_report(sweep))
    print(C.COLOR_RESET, end="")
    if _audit_failed(audit_doc):
        from .report import audit_report

        print(f"{C.COLOR_RED}{audit_report(audit_doc)}{C.COLOR_RESET}")
    rate = sweep.timings.get("scenarios_per_s", 0.0)
    print(
        f"{len(scen)} scenario(s), {int(sweep.survived.sum())} survived "
        f"({rate:.0f} scenarios/s)"
    )
    if _audit_failed(audit_doc):
        return _flight_exit(
            EXIT_AUDIT, "audit divergence on the assessment base placement",
            args,
        )
    return 0 if survived_all else 1


def cmd_fuzz(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_fuzz(args))


def _cmd_fuzz(args: argparse.Namespace) -> int:
    """Differential fuzz / mutation-kill driver (simtpu/audit/fuzz.py).

    Exit codes: 0 = every case bit-identical and audit-clean (or 100%
    mutation kill); EXIT_AUDIT = a divergence, dirty audit, or missed
    mutation — the finding IS the failure."""
    import json

    progress_stream = sys.stderr if args.json else sys.stdout

    def progress(msg: str) -> None:
        print(f"{C.COLOR_YELLOW}{msg}{C.COLOR_RESET}", file=progress_stream)

    if args.mutation_kill:
        from .audit.fuzz import run_mutation_kill

        counters = run_mutation_kill(
            seed=args.seed, per_class=args.per_class, progress=progress
        )
        ok = (
            counters["kill_rate"] >= 1.0
            and counters["classes"] == counters["classes_total"]
            and not counters["missed"]
        )
        if args.json:
            print(json.dumps({"ok": ok, **counters}))
        else:
            color = C.COLOR_GREEN if ok else C.COLOR_RED
            print(
                f"{color}mutation-kill: {counters['killed']}/"
                f"{counters['tried']} corruptions detected across "
                f"{counters['classes']} classes{C.COLOR_RESET}"
            )
            if counters["missed"]:
                print(f"{C.COLOR_RED}missed: {counters['missed']}{C.COLOR_RESET}")
        return 0 if ok else EXIT_AUDIT

    if args.replay:
        from .audit.fuzz import replay_case

        try:
            bad = replay_case(args.replay, include_shard=args.shard)
        except (ValueError, FileNotFoundError) as exc:
            if args.json:
                print(json.dumps({"ok": False, "message": str(exc)}))
            print(exc, file=sys.stderr)
            return 1
        if args.json:
            doc = {"ok": bad is None, "replay": args.replay}
            if bad is not None:
                doc.update(config=bad[0], kind=bad[1], detail=bad[2])
            print(json.dumps(doc))
        elif bad is None:
            print(f"{C.COLOR_GREEN}replay clean: every engine config "
                  f"bit-identical and audit-clean{C.COLOR_RESET}")
        else:
            print(f"{C.COLOR_RED}replay FAILED on config {bad[0]} "
                  f"({bad[1]}): {bad[2]}{C.COLOR_RESET}")
        return 0 if bad is None else EXIT_AUDIT

    from .audit.fuzz import run_differential

    result = run_differential(
        cases=args.cases,
        seed=args.seed,
        n_nodes=args.nodes,
        n_pods=args.pods,
        out_dir=args.out,
        include_shard=args.shard,
        progress=progress,
    )
    if args.json:
        print(json.dumps(result.counters()))
    elif result.ok:
        print(
            f"{C.COLOR_GREEN}fuzz clean: {result.cases} case(s), "
            f"{result.configs_run} engine-config runs, all bit-identical "
            f"and audit-clean{C.COLOR_RESET}"
        )
    else:
        for f in result.failures:
            repro = f" reproducer={f.reproducer}" if f.reproducer else ""
            print(
                f"{C.COLOR_RED}seed {f.seed} config {f.config}: {f.kind} "
                f"— {f.detail}{repro}{C.COLOR_RESET}"
            )
    return 0 if result.ok else EXIT_AUDIT


def cmd_explain(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_explain(args))


def _cmd_explain(args: argparse.Namespace) -> int:
    """One engine-level placement of the configured problem, explained
    (simtpu/explain).  Engine-level deliberately: score attribution's
    log-prefix exactness and the breakdown's end-state semantics both
    rest on the un-surgered placement log (no preemption), the same
    contract the planners' probes run under."""
    import json

    import numpy as np

    opts = ApplierOptions(
        simon_config=args.simon_config,
        default_scheduler_config=args.default_scheduler_config or "",
        extended_resources=args.extended_resources or [],
    )

    def fail_early(exc: Exception) -> int:
        if args.json:
            print(json.dumps({"success": False, "message": str(exc)}))
        print(exc, file=sys.stderr)
        return 1

    try:
        applier = Applier(opts)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    progress_stream = sys.stderr if args.json else sys.stdout

    def progress(msg: str) -> None:
        print(f"{C.COLOR_YELLOW}{msg}{C.COLOR_RESET}", file=progress_stream)

    try:
        cluster = applier.load_cluster()
        apps = applier.load_apps()
        sched_config = applier._sched_config()
        new_node = None
        try:
            new_node = applier.load_new_node()
        except (ValueError, FileNotFoundError, OSError):
            # the template is optional here: without it the bottleneck
            # block simply omits the can-another-node-help verdict
            pass
        from .explain import (
            EXPLAIN_VERSION,
            attribute_scores,
            build_explain_doc,
            extras_from_log,
        )
        from .faults import place_cluster

        # score attribution's prefix-state exactness (recomputed argmax
        # == recorded node) is a SERIAL-scan contract — the bulk rounds
        # engine deliberately tie-breaks differently.  --scores therefore
        # forces the serial-equivalent engine for the whole placement
        # (the wavefront dispatcher keeps it fast and bit-identical).
        use_bulk = not args.no_bulk and args.scores <= 0
        if args.scores > 0 and not args.no_bulk:
            progress(
                "--scores: placing with the serial-equivalent engine "
                "(score attribution's exactness contract)"
            )
        progress(
            f"placing workloads ({len(cluster.nodes)} nodes), then "
            "explaining the outcome"
        )
        pc = place_cluster(
            cluster,
            apps,
            extended_resources=opts.extended_resources,
            bulk=use_bulk,
            sched_config=sched_config,
        )
        nodes = np.asarray(pc.nodes)
        reasons = np.asarray(pc.reasons)
        unplaced = np.flatnonzero(nodes < 0)
        state = pc.engine.carried_state()
        all_ds = list(cluster.daemon_sets)
        for app in apps:
            all_ds += app.resource.daemon_sets
        doc = {
            "version": EXPLAIN_VERSION,
            "pods": int(len(nodes)),
            "placed": int((nodes >= 0).sum()),
            "unplaced": int(len(unplaced)),
        }
        doc.update(
            build_explain_doc(
                pc.tensors, pc.batch, unplaced, state, nodes, reasons,
                sched_config=sched_config, new_node=new_node,
                daemon_sets=all_ds, top=args.top,
            )
        )
        if args.scores > 0:
            extras = extras_from_log(pc.tensors, nodes, pc.engine.ext_log)
            doc["scores"] = attribute_scores(
                pc.tensors, pc.batch, nodes, extras,
                max_pods=args.scores, sched_config=sched_config,
            )
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    if args.json:
        print(json.dumps(doc))
        return 0
    from .report import explain_report

    print(
        f"{C.COLOR_GREEN}{doc['placed']}/{doc['pods']} pods placed"
        f"{C.COLOR_RESET}"
        + (
            f" {C.COLOR_RED}({doc['unplaced']} unplaced){C.COLOR_RESET}"
            if doc["unplaced"]
            else ""
        )
    )
    print(explain_report(doc))
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_serve(args))


def _cmd_serve(args: argparse.Namespace) -> int:
    """The long-lived simulation daemon (simtpu/serve, docs/serving.md).

    The serve package imports ONLY here — `simtpu apply`/every other
    subcommand runs with the daemon-off cost provably zero (no
    simtpu.serve import, pinned by tests/test_serve.py)."""
    from .serve import ServeOptions, serve_main

    opts = ServeOptions(
        host=args.host,
        port=args.port,
        state_dir=args.state_dir or "",
        max_sessions=args.max_sessions,
        queue_depth=args.queue_depth,
        default_deadline_s=args.default_deadline,
        coalesce_window_s=args.coalesce_window,
        audit=args.audit,
        sched_config=args.default_scheduler_config or "",
        extended_resources=args.extended_resources or [],
        drain_timeout_s=args.drain_timeout,
    )

    def progress(msg: str) -> None:
        print(msg, flush=True)

    try:
        return serve_main(opts, progress=progress)
    except OSError as exc:
        # startup failures (port taken, bad host, unwritable state dir)
        # are config errors, not tracebacks — the same one-line contract
        # as apply's fail_early; the message stays phase-neutral because
        # the bind and the state-dir setup both land here
        print(f"simtpu serve: startup failed: {exc}", file=sys.stderr)
        return 1


def cmd_replay(args: argparse.Namespace) -> int:
    return _with_obs(args, lambda: _cmd_replay(args))


def _cmd_replay(args: argparse.Namespace) -> int:
    """Trace-driven continuous-time replay (simtpu/timeline,
    docs/timeline.md).  The timeline package imports ONLY here — every
    other subcommand runs with the replay-off cost provably zero, the
    same contract as `simtpu serve`."""
    import json

    from .durable.deadline import RunControl
    from .workloads.validate import SpecError

    progress_stream = sys.stderr if args.json else sys.stdout

    def progress(msg: str) -> None:
        print(f"{C.COLOR_YELLOW}{msg}{C.COLOR_RESET}", file=progress_stream)

    def fail_early(exc: Exception) -> int:
        # malformed traces die as ONE structured line (the SpecError
        # ingest contract, docs/robustness.md) — on stderr AND under
        # --json's "message", never a traceback
        if args.json:
            print(json.dumps({"success": False, "message": str(exc)}))
        print(exc, file=sys.stderr)
        return 1

    if bool(args.trace_file) == bool(args.synth):
        return fail_early(
            ValueError(
                "exactly one input required: a TRACE file argument, or "
                "--synth (seeded generated stream; --nodes/--pods/--days)"
            )
        )
    try:
        from .timeline import (
            ReplayOptions,
            load_trace,
            replay_trace,
            trace_from_doc,
        )

        if args.synth:
            from .synth import make_trace

            progress(
                f"synthesizing trace: {args.nodes} nodes, ~{args.pods} "
                f"pods over {args.days:g} day(s), seed {args.seed}"
            )
            doc = make_trace(
                args.nodes, args.pods, seed=args.seed, days=args.days,
                cron_jobs=args.cron_jobs,
                elastic_frac=args.elastic_frac,
                node_event_frac=args.node_event_frac,
                autoscale_pool=args.autoscale_pool,
            )
            trace = trace_from_doc(doc, source="<synth>")
        else:
            trace = load_trace(args.trace_file)
        progress(
            f"replaying {len(trace.jobs)} job(s) over "
            f"{trace.horizon_s / 86400:g} day(s) on "
            f"{len(trace.cluster.nodes)} nodes"
            + (" [serial oracle]" if args.serial else "")
        )
        control = RunControl(deadline=args.deadline)
        opts = ReplayOptions(
            serial=args.serial,
            preempt=not args.no_preempt,
            retry_backoff_s=args.retry_backoff,
            max_retries=args.max_retries,
            extended_resources=tuple(args.extended_resources or ()),
            audit=args.audit,
            control=control,
            progress=progress,
        )
        with control.sigint():
            res = replay_trace(trace, opts)
        check_ok = None
        if args.check and not res.partial:
            # differential self-check: the serial one-event-at-a-time
            # oracle must reproduce the batched end state bit-identically.
            # The oracle gets its OWN control carrying the REMAINING
            # deadline — reusing the expired-by-now first control would
            # report a truncated oracle as a false divergence (exit 4)
            # instead of the documented cooperative partial (exit 3)
            progress("--check: replaying through the serial oracle")
            if args.synth:
                trace2 = trace_from_doc(doc, source="<synth>")
            else:
                trace2 = load_trace(args.trace_file)
            check_control = RunControl(deadline=control.remaining())
            with check_control.sigint():
                oracle = replay_trace(
                    trace2,
                    ReplayOptions(
                        serial=True,
                        preempt=not args.no_preempt,
                        retry_backoff_s=args.retry_backoff,
                        max_retries=args.max_retries,
                        extended_resources=tuple(
                            args.extended_resources or ()
                        ),
                        audit=args.audit,
                        control=check_control,
                        progress=progress,
                    ),
                )
            if oracle.partial:
                # the check itself was interrupted: a partial oracle
                # proves nothing — surface the cooperative partial
                res.partial = True
                res.message = f"--check {oracle.message}"
            else:
                from .engine.state import diff_state_planes

                import numpy as np

                check_ok = (
                    res.event_log == oracle.event_log
                    and np.array_equal(res.nodes, oracle.nodes)
                    and list(res.engine.placed_node)
                    == list(oracle.engine.placed_node)
                    and not diff_state_planes(
                        res.end_state(), oracle.end_state()
                    )
                )
    except SpecError as exc:
        return fail_early(exc)
    except (ValueError, FileNotFoundError) as exc:
        return fail_early(exc)
    audit_bad = res.audit is not None and not res.audit.get("ok", True)
    if args.json:
        doc_out = res.counters()
        doc_out["success"] = not res.partial and not audit_bad
        doc_out["message"] = res.message
        doc_out["timings"] = {
            k: round(v, 3) for k, v in res.timings.items()
        }
        if res.audit is not None:
            doc_out["audit"] = res.audit
        if check_ok is not None:
            doc_out["check"] = check_ok
        print(json.dumps(doc_out))
    else:
        from .report import timeline_report

        color = C.COLOR_RED if (res.partial or audit_bad) else C.COLOR_GREEN
        print(color, end="")
        print(timeline_report(res))
        print(C.COLOR_RESET, end="")
        if check_ok is not None:
            verdict = (
                f"{C.COLOR_GREEN}check: batched == serial oracle "
                f"(bit-identical){C.COLOR_RESET}"
                if check_ok
                else f"{C.COLOR_RED}check: batched path DIVERGED from "
                f"the serial oracle{C.COLOR_RESET}"
            )
            print(verdict)
        if res.partial:
            print(f"{C.COLOR_RED}{res.message}{C.COLOR_RESET}")
    if res.partial:
        # the cooperative partial-timeline contract: the processed event
        # prefix is a consistent simulation, exit 3 (docs/robustness.md)
        return _flight_exit(
            EXIT_PARTIAL, "partial timeline (deadline/SIGINT)", args
        )
    if audit_bad or check_ok is False:
        return _flight_exit(
            EXIT_AUDIT,
            "timeline end-state audit/divergence failure",
            args,
        )
    return 0


def cmd_version(args: argparse.Namespace) -> int:
    if getattr(args, "json", False):
        # downstream consumers of the --json metrics block detect layout
        # changes on schema_version (obs/metrics.py), not key probing
        import json

        from .obs.metrics import SCHEMA_VERSION

        print(json.dumps(
            {"version": __version__, "schema_version": SCHEMA_VERSION}
        ))
        return 0
    print(f"simtpu version {__version__}")
    return 0


#: exit code for a plan interrupted by --deadline or SIGINT: the run ended
#: cleanly with a flushed checkpoint and a `partial=true` report, but the
#: search did not complete — distinct from 1 ("the plan ran and failed")
EXIT_PARTIAL = 3

#: exit code for an audit failure (docs/robustness.md): the independent
#: placement auditor caught the primary engine violating its claimed
#: constraints.  When the serial-exact fallback certified, the SHIPPED
#: plan is the fallback's (correct) answer — the nonzero code still fires
#: so CI and scripts notice the engine divergence; when even the fallback
#: failed certification, no plan ships at all.  Distinct from 1 ("the
#: plan ran and found the problem infeasible") and 3 (interrupted)
EXIT_AUDIT = 4


def _audit_failed(doc: Optional[dict]) -> bool:
    """True when an audit record describes a caught divergence — the
    primary engine's answer failed certification (whether or not the
    serial-exact fallback then certified)."""
    return bool(doc) and (bool(doc.get("fallback")) or not doc.get("ok", True))


def _add_audit_flags(p: argparse.ArgumentParser) -> None:
    """Independent-auditor opt-out shared by the planning commands
    (docs/robustness.md, simtpu/audit)."""
    p.add_argument(
        "--audit",
        dest="audit",
        action="store_true",
        default=None,
        help="certify the accepted placement through the independent "
        "auditor (default: on, SIMTPU_AUDIT=0 disables globally); an "
        "audit failure falls back to the serial exact engines, ships "
        "THEIR certified answer, and exits with code "
        f"{EXIT_AUDIT}",
    )
    p.add_argument(
        "--no-audit",
        dest="audit",
        action="store_false",
        help="skip the independent placement audit (the plan ships "
        "uncertified)",
    )


def _add_solver_flags(p: argparse.ArgumentParser) -> None:
    """Global-solver backend opt-in shared by the planning commands
    (docs/solver.md, simtpu/solve).  Advisory mode: the solver PROPOSES
    a placement at a certified-minimal node count, the independent
    auditor DISPOSES — any rejected or uncertified answer falls back to
    the exact search with at most a warm-start lower bound."""
    p.add_argument(
        "--solver",
        dest="solver",
        action="store_true",
        default=None,
        help="consult the global-solver planning backend first: one "
        "vmapped convex relaxation over ALL candidate node counts "
        "replaces the doubling+bisection capacity search; the rounded "
        "placement ships only when the independent auditor certifies it "
        "AND minimality is proven by an infeasibility certificate at the "
        "count below (default: off, SIMTPU_SOLVER=1 enables globally; "
        "the '--json' engine block records which backend answered)",
    )
    p.add_argument(
        "--no-solver",
        dest="solver",
        action="store_false",
        help="never consult the global-solver backend (exact search "
        "only, even when SIMTPU_SOLVER=1)",
    )


def _add_explain_flag(p: argparse.ArgumentParser) -> None:
    """Decision-observability opt-in shared by the planning commands
    (simtpu/explain, docs/observability.md)."""
    p.add_argument(
        "--explain",
        action="store_true",
        help="attach the decision-observability block to the result: "
        "kube-scheduler-style per-stage failure breakdowns for every "
        "unplaced pod ('0/N nodes are available: 3 insufficient ..., 5 "
        "node(s) didn't match ...') and a binding-constraint bottleneck "
        "analysis (what to buy) for infeasible plans; rides --json under "
        "'explain' and the report as extra tables (off = zero cost: no "
        "extra device dispatches)",
    )


def _add_durable_flags(p: argparse.ArgumentParser) -> None:
    """Durable-execution flags shared by the planning commands
    (docs/robustness.md)."""
    p.add_argument(
        "--checkpoint",
        metavar="DIR",
        help="persist a versioned checkpoint record after each completed "
        "search candidate under DIR; a killed or interrupted run loses at "
        "most the in-flight candidate",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="replay the completed candidates recorded under --checkpoint "
        "DIR instead of re-simulating them (refuses loudly when the "
        "config/cluster fingerprint does not match); the resumed result "
        "is bit-identical to an uninterrupted run",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="wall-clock budget for the plan search; on expiry (or on the "
        "first ^C) the run flushes a final checkpoint and exits with code "
        f"{EXIT_PARTIAL} and a structured partial result (best candidate "
        "verified so far, partial=true under --json) instead of a "
        "traceback",
    )


def _add_obs_flags(p: argparse.ArgumentParser) -> None:
    """Observability flags shared by apply/resilience/fuzz (ISSUE 8,
    docs/observability.md)."""
    p.add_argument(
        "--trace",
        metavar="FILE",
        default="",
        help="record the run's spans (obs/trace.py) and write a "
        "Perfetto-loadable Chrome trace-event JSON to FILE on exit — "
        "success or failure (SIMTPU_TRACE=1 arms the tracer without a "
        "file; SIMTPU_TRACE=FILE is the env equivalent of this flag)",
    )
    p.add_argument(
        "--profile",
        metavar="DIR",
        default="",
        help="capture a jax.profiler (TensorBoard-loadable) device trace "
        "under DIR, with TraceAnnotation names matching the span "
        "vocabulary (SIMTPU_PROFILE=DIR is the env equivalent)",
    )


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="simtpu",
        description="TPU-native cluster simulator and capacity planner "
        "(Open-Simulator capabilities, JAX engine)",
    )
    sub = parser.add_subparsers(dest="command")

    apply_p = sub.add_parser("apply", help="simulate deploying applications in a cluster")
    apply_p.add_argument(
        "-f", "--simon-config", required=True, help="path of simon config (required)"
    )
    apply_p.add_argument(
        "-d",
        "--default-scheduler-config",
        help="path of scheduler-config overrides",
    )
    apply_p.add_argument(
        "-g",
        "--use-greed",
        action="store_true",
        # reference-parity no-op: the flag exists upstream (`cmd/apply/
        # apply.go:33`) but GreedQueue is never constructed outside tests —
        # ScheduleApp always sorts by Affinity+Toleration only
        # (`pkg/simulator/simulator.go:172-176`)
        help="use greed algorithm to queue pods (accepted for parity; the "
        "reference never wires this to its scheduler either)",
    )
    apply_p.add_argument(
        "-i", "--interactive", action="store_true", help="interactively choose apps"
    )
    apply_p.add_argument(
        "-e",
        "--extended-resources",
        nargs="*",
        choices=["open-local", "gpu"],
        help="show extended resources in the report (open-local, gpu)",
    )
    apply_p.add_argument(
        "--search",
        choices=["binary", "linear", "incremental"],
        default=None,
        help="min-node-add search strategy (default: auto by problem size; "
        "linear = reference-exact walk; incremental = one tensorization + "
        "completion probes + fresh verification, the fast path for large "
        "clusters)",
    )
    apply_p.add_argument(
        "--bulk",
        dest="bulk",
        action="store_true",
        default=None,
        help="place replica runs with the bulk rounds engine (default: auto "
        "by problem size; faster on large app lists; tie-breaking may "
        "differ from the serial scan)",
    )
    apply_p.add_argument(
        "--no-bulk",
        dest="bulk",
        action="store_false",
        help="force the serial scan engine even at scale",
    )
    apply_p.add_argument(
        "--shard",
        dest="shard",
        action="store_true",
        default=None,
        help="shard the incremental planner's node axis over all visible "
        "devices (default: auto — sharded on multi-device accelerator "
        "backends; placements are identical to single-device execution)",
    )
    apply_p.add_argument(
        "--no-shard",
        dest="shard",
        action="store_false",
        help="force single-device execution of the incremental planner",
    )
    apply_p.add_argument(
        "--precompile",
        dest="precompile",
        action="store_true",
        default=None,
        help="AOT-precompile the run's jit executables on a background "
        "thread pool as soon as the shapes are known, so the cold first "
        "run overlaps compilation with host work instead of serializing "
        "compiles at first dispatch (default: auto — on for accelerator "
        "backends, off on CPU where the compiles would contend with the "
        "placement compute for the same cores; placements are identical "
        "either way)",
    )
    apply_p.add_argument(
        "--no-precompile",
        dest="precompile",
        action="store_false",
        help="compile each executable at its first dispatch (the "
        "pre-pipeline cold path)",
    )
    apply_p.add_argument(
        "--json",
        action="store_true",
        help="print a machine-readable JSON plan summary (success, "
        "nodes added, probes, timings, and the engine/search selection) "
        "instead of the report tables",
    )
    apply_p.add_argument(
        "--corrected-ds-overhead",
        action="store_true",
        help="account daemonset overhead on the template node in the "
        "can-ever-fit diagnostic (the reference pins its probe pod to a node "
        "named 'simon', so the overhead silently contributes nothing)",
    )
    apply_p.add_argument(
        "--faults",
        metavar="SPEC",
        help="after a successful plan, sweep failure scenarios over the "
        "winning cluster and report survivability (e.g. 'k=1' = every "
        "single-node outage, 'k=2:500,zone' = 500 two-node samples plus "
        "zone outages); counters ride --json under 'resilience'",
    )
    apply_p.add_argument(
        "--fault-samples",
        type=int,
        default=256,
        metavar="N",
        help="sample budget per k>=2 fault term (default 256)",
    )
    apply_p.add_argument(
        "--fault-seed",
        type=int,
        default=0,
        metavar="SEED",
        help="deterministic seed for sampled fault scenarios (default 0)",
    )
    _add_audit_flags(apply_p)
    _add_solver_flags(apply_p)
    _add_durable_flags(apply_p)
    _add_obs_flags(apply_p)
    _add_explain_flag(apply_p)
    apply_p.set_defaults(func=cmd_apply)

    res_p = sub.add_parser(
        "resilience",
        help="fault-injection survivability: drain + requeue batched "
        "failure scenarios (and optionally plan N+k capacity)",
    )
    res_p.add_argument(
        "-f", "--simon-config", required=True, help="path of simon config (required)"
    )
    res_p.add_argument(
        "-d",
        "--default-scheduler-config",
        help="path of scheduler-config overrides",
    )
    res_p.add_argument(
        "-e",
        "--extended-resources",
        nargs="*",
        choices=["open-local", "gpu"],
        help="extended resources to model (open-local, gpu)",
    )
    res_p.add_argument(
        "--faults",
        metavar="SPEC",
        default="k=1",
        help="failure model: comma-separated k=<int>[:<samples>] terms and "
        "domain outages (zone, rack, host, label:<key>); default k=1 = "
        "every single-node outage",
    )
    res_p.add_argument(
        "--samples",
        type=int,
        default=256,
        metavar="N",
        help="sample budget per k>=2 fault term (default 256; exhaustive "
        "when the combination count fits)",
    )
    res_p.add_argument(
        "--seed",
        type=int,
        default=0,
        metavar="SEED",
        help="deterministic seed for sampled scenarios (default 0)",
    )
    res_p.add_argument(
        "--quantile",
        type=float,
        default=1.0,
        metavar="Q",
        help="with --plan: accept a candidate when at least this fraction "
        "of scenarios fully re-places (default 1.0 = every scenario)",
    )
    res_p.add_argument(
        "--plan",
        action="store_true",
        help="search the minimum newNode clone count whose cluster "
        "survives the failure model (requires newNode in the Config CR)",
    )
    res_p.add_argument(
        "--max-new-nodes",
        type=int,
        default=C.MAX_NUM_NEW_NODE,
        metavar="N",
        help=f"--plan search ceiling (default {C.MAX_NUM_NEW_NODE})",
    )
    res_p.add_argument(
        "--no-bulk",
        action="store_true",
        help="place the base workloads with the serial scan engine instead "
        "of the bulk rounds engine",
    )
    res_p.add_argument(
        "--json",
        action="store_true",
        help="print machine-readable survivability counters (scenarios, "
        "survived, fault_scenarios_per_s, worst scenarios, critical nodes) "
        "instead of the report tables",
    )
    _add_audit_flags(res_p)
    _add_solver_flags(res_p)
    _add_durable_flags(res_p)
    _add_obs_flags(res_p)
    _add_explain_flag(res_p)
    res_p.set_defaults(func=cmd_resilience)

    exp_p = sub.add_parser(
        "explain",
        help="explain one placement: per-stage failure breakdowns, "
        "per-plugin score attribution, bottleneck analysis",
        description="Decision observability (simtpu/explain, "
        "docs/observability.md): place the configured cluster + apps "
        "through ONE engine (no capacity search, no preemption — the "
        "planners' engine-level contract) and explain the outcome.  "
        "Every unplaced pod gets the kube-scheduler-style status string "
        "with per-stage node-elimination counts and witness nodes; "
        "--scores N additionally decomposes the first N placed pods' "
        "winning scores into per-plugin terms with the runner-up node "
        "and margin (the weight-sensitivity surface); the bottleneck "
        "section names the binding resource and whether another "
        "template node can ever help.",
    )
    exp_p.add_argument(
        "-f", "--simon-config", required=True, help="path of simon config (required)"
    )
    exp_p.add_argument(
        "-d",
        "--default-scheduler-config",
        help="path of scheduler-config overrides",
    )
    exp_p.add_argument(
        "-e",
        "--extended-resources",
        nargs="*",
        choices=["open-local", "gpu"],
        help="extended resources to model (open-local, gpu)",
    )
    exp_p.add_argument(
        "--scores",
        type=int,
        default=0,
        metavar="N",
        help="attribute the first N placed pods' scores (per-plugin "
        "decomposition, runner-up, margin; default 0 = off — each pod "
        "costs one log-prefix state rebuild; forces the serial-"
        "equivalent engine: attribution exactness is a serial-scan "
        "contract)",
    )
    exp_p.add_argument(
        "--top",
        type=int,
        default=10,
        metavar="K",
        help="distinct failure shapes kept in the breakdown (default 10; "
        "truncation is reported, never silent)",
    )
    exp_p.add_argument(
        "--no-bulk",
        action="store_true",
        help="place with the serial scan engine instead of the bulk "
        "rounds engine",
    )
    exp_p.add_argument(
        "--json",
        action="store_true",
        help="print the versioned explain document (the same block "
        "apply --explain --json embeds) instead of the report tables",
    )
    _add_obs_flags(exp_p)
    exp_p.set_defaults(func=cmd_explain)

    fuzz_p = sub.add_parser(
        "fuzz",
        help="differential fuzz the engine-config matrix against the "
        "serial baseline + the independent auditor (simtpu/audit)",
        description="Seeded differential fuzzing (docs/robustness.md): "
        "generate gnarly spec/cluster cases, place each across the "
        "engine-config matrix (wavefront on/off x compact on/off x "
        "GSPMD shard on/off x injected-OOM backoff), and assert "
        "bit-identical, audit-clean placements.  Failing cases shrink "
        "to a minimal reproducer YAML under --out.  --mutation-kill "
        "instead corrupts accepted placements across every corruption "
        "class and asserts the auditor flags 100% of them.",
    )
    fuzz_p.add_argument(
        "--cases", type=int, default=16, metavar="N",
        help="generated cases for the differential mode (default 16)",
    )
    fuzz_p.add_argument(
        "--seed", type=int, default=0, metavar="SEED",
        help="base seed; case i draws from seed + 1000*i (default 0)",
    )
    fuzz_p.add_argument(
        "--nodes", type=int, default=32, metavar="N",
        help="synthetic cluster size per case (default 32)",
    )
    fuzz_p.add_argument(
        "--pods", type=int, default=160, metavar="N",
        help="pods per case (default 160)",
    )
    fuzz_p.add_argument(
        "--out", metavar="DIR", default="",
        help="write auto-shrunk minimal reproducer YAMLs for failing "
        "cases under DIR (skipping shrink when unset)",
    )
    fuzz_p.add_argument(
        "--replay", metavar="FILE",
        help="re-run one reproducer YAML (written by --out) across the "
        "engine-config matrix instead of generating cases",
    )
    fuzz_p.add_argument(
        "--mutation-kill", action="store_true",
        help="corrupt accepted placements across every corruption class "
        "(invalid node, overcommit, affinity/anti-affinity/spread "
        "breaks, port conflicts, illegal evictions) and assert the "
        "auditor flags every one",
    )
    fuzz_p.add_argument(
        "--per-class", type=int, default=4, metavar="N",
        help="mutation trials per corruption class (default 4)",
    )
    fuzz_p.add_argument(
        "--shard", dest="shard", action="store_true", default=None,
        help="force the GSPMD-sharded matrix cell (default: auto when "
        ">1 device is visible)",
    )
    fuzz_p.add_argument(
        "--no-shard", dest="shard", action="store_false",
        help="skip the GSPMD-sharded matrix cell",
    )
    fuzz_p.add_argument(
        "--json", action="store_true",
        help="print machine-readable counters instead of progress text",
    )
    _add_obs_flags(fuzz_p)
    fuzz_p.set_defaults(func=cmd_fuzz)

    serve_p = sub.add_parser(
        "serve",
        help="run the persistent simulation daemon: warm snapshot "
        "sessions, coalesced what-if queries, HTTP/JSON API",
        description="Long-lived simulation service (simtpu/serve, "
        "docs/serving.md): hold cluster snapshots warm in checkpointed "
        "sessions and answer concurrent what-if queries — fit / drain / "
        "capacity / resilience — over HTTP/JSON.  Queued sweep-shaped "
        "queries against one snapshot coalesce into a single vmapped "
        "dispatch.  Robustness contract: per-request cooperative "
        "deadlines (structured 504), bounded-queue load shedding (429 + "
        "Retry-After), OOM chunk-halving backoff with session eviction "
        "under pressure (503 + Retry-After), crash-safe session "
        "recovery from --state-dir after kill -9, SIGTERM graceful "
        "drain (exit 0), /healthz /readyz /metrics endpoints, span "
        "tracing with flight-recorder bundles on request failure, and "
        "the independent auditor certifying every served answer.",
    )
    serve_p.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1 — front a reverse proxy "
        "for anything wider)",
    )
    serve_p.add_argument(
        "--port", type=int, default=8090,
        help="bind port (default 8090; 0 = ephemeral, printed at start)",
    )
    serve_p.add_argument(
        "--state-dir", metavar="DIR", default="",
        help="session checkpoint directory (durable/checkpoint.py): "
        "sessions created here survive kill -9 and rehydrate "
        "bit-identically on restart (default: memory-only sessions)",
    )
    serve_p.add_argument(
        "--max-sessions", type=int, default=8, metavar="N",
        help="in-memory session cap; past it the least-recently-used "
        "session is evicted (rehydratable from --state-dir; default 8)",
    )
    serve_p.add_argument(
        "--queue-depth", type=int, default=64, metavar="N",
        help="admission-control queue bound; a full queue sheds new "
        "queries with 429 + Retry-After (default 64)",
    )
    serve_p.add_argument(
        "--default-deadline", type=float, default=30.0, metavar="SECONDS",
        help="per-request deadline when the body carries no deadline_s "
        "(default 30; expiry answers a structured 504 partial and the "
        "daemon is unharmed)",
    )
    serve_p.add_argument(
        "--coalesce-window", type=float, default=0.0, metavar="SECONDS",
        help="extra wait for more coalescible queries after the first "
        "(default 0 = fuse only what is already queued; bursts queued "
        "behind an executing batch coalesce either way)",
    )
    serve_p.add_argument(
        "--drain-timeout", type=float, default=30.0, metavar="SECONDS",
        help="SIGTERM drain budget: how long to wait for the queue and "
        "in-flight requests before abandoning them (default 30)",
    )
    serve_p.add_argument(
        "-d", "--default-scheduler-config",
        help="path of scheduler-config overrides applied to every "
        "session",
    )
    serve_p.add_argument(
        "-e", "--extended-resources", nargs="*",
        choices=["open-local", "gpu"],
        help="extended resources to model in every session",
    )
    _add_audit_flags(serve_p)
    _add_obs_flags(serve_p)
    serve_p.set_defaults(func=cmd_serve)

    replay_p = sub.add_parser(
        "replay",
        help="trace-driven continuous-time simulation: gang admission, "
        "preemption, CronJob firings, node events, autoscaler emulation",
        description="Continuous-time replay (simtpu/timeline, "
        "docs/timeline.md): advance one engine through a time-ordered "
        "event stream — pod-group arrivals with durations, CronJob "
        "firings from real spec.schedule cron expressions, node down/up "
        "events — via the batch delta apply/undo machinery, never "
        "re-placing from scratch.  Gang admission is all-or-nothing "
        "(partial placements roll back), failed gangs wait in a "
        "priority-ordered pending queue with exponential retry/backoff, "
        "arrivals may preempt strictly-lower-priority gangs, and an "
        "HPA/template-node-pool autoscaler emulation scales replicas "
        "off simulated utilization.  The input is a trace file, or "
        "--synth for a seeded generated arrival stream.  The serial "
        "one-event-at-a-time oracle (--serial / --check) is pinned "
        "bit-identical to the batched path; the independent auditor "
        "certifies the end state.",
    )
    replay_p.add_argument(
        "trace_file", nargs="?", default="",
        help="trace JSON file (docs/timeline.md has the format); "
        "mutually exclusive with --synth",
    )
    replay_p.add_argument(
        "--synth", action="store_true",
        help="generate the trace instead of reading a file "
        "(synth.make_trace: seeded Poisson-ish arrivals, lognormal "
        "durations, gang sizes, CronJob mix)",
    )
    replay_p.add_argument(
        "--nodes", type=int, default=100, metavar="N",
        help="--synth cluster size (default 100)",
    )
    replay_p.add_argument(
        "--pods", type=int, default=2000, metavar="N",
        help="--synth total arriving pods (default 2000)",
    )
    replay_p.add_argument(
        "--days", type=float, default=1.0, metavar="D",
        help="--synth horizon in days (default 1)",
    )
    replay_p.add_argument(
        "--seed", type=int, default=0, metavar="SEED",
        help="--synth trace seed (default 0)",
    )
    replay_p.add_argument(
        "--cron-jobs", type=int, default=2, metavar="N",
        help="--synth CronJob count (default 2)",
    )
    replay_p.add_argument(
        "--elastic-frac", type=float, default=0.0, metavar="F",
        help="--synth fraction of HPA-scalable workloads (default 0)",
    )
    replay_p.add_argument(
        "--node-event-frac", type=float, default=0.0, metavar="F",
        help="--synth fraction of nodes with a maintenance down/up "
        "window (default 0)",
    )
    replay_p.add_argument(
        "--autoscale-pool", type=int, default=0, metavar="N",
        help="--synth pre-provisioned template-node pool the autoscaler "
        "arms under pending demand (default 0)",
    )
    replay_p.add_argument(
        "--serial", action="store_true",
        help="replay through the serial one-event-at-a-time oracle "
        "(one pod per dispatch, dense carry, from-log state rebuilds) "
        "instead of the batched path — the pinning baseline",
    )
    replay_p.add_argument(
        "--check", action="store_true",
        help="after the batched replay, re-replay through the serial "
        "oracle and verify the end state is bit-identical (a divergence "
        f"exits {EXIT_AUDIT})",
    )
    replay_p.add_argument(
        "--no-preempt", action="store_true",
        help="disable preemption on gang arrival (failed arrivals only "
        "wait in the pending queue)",
    )
    replay_p.add_argument(
        "--retry-backoff", type=float, default=30.0, metavar="SECONDS",
        help="pending-queue retry backoff base; attempt k waits "
        "base*2^(k-1) (default 30)",
    )
    replay_p.add_argument(
        "--max-retries", type=int, default=8, metavar="N",
        help="admission attempts per job before the remainder is "
        "dropped (default 8)",
    )
    replay_p.add_argument(
        "-e", "--extended-resources", nargs="*",
        choices=["open-local", "gpu"],
        help="extended resources to model (open-local, gpu)",
    )
    replay_p.add_argument(
        "--deadline", type=float, default=None, metavar="SECONDS",
        help="wall-clock budget; on expiry (or first ^C/SIGTERM) the "
        "replay stops cooperatively at an event boundary and exits "
        f"{EXIT_PARTIAL} with the consistent partial timeline",
    )
    replay_p.add_argument(
        "--json", action="store_true",
        help="print machine-readable replay counters (events, "
        "events_per_s, pending_p50_s, preemptions, audit verdict) "
        "instead of the report tables",
    )
    _add_audit_flags(replay_p)
    _add_obs_flags(replay_p)
    replay_p.set_defaults(func=cmd_replay)

    ver_p = sub.add_parser("version", help="print version")
    ver_p.add_argument(
        "--json", action="store_true",
        help="print {version, schema_version} — schema_version stamps the "
        "--json document layout (incl. the metrics block); consumers pin "
        "on it instead of probing keys",
    )
    ver_p.set_defaults(func=cmd_version)

    doc_p = sub.add_parser("gen-doc", help="generate CLI markdown docs")
    doc_p.add_argument("--output", default="docs/commandline", help="output directory")
    doc_p.set_defaults(func=cmd_gen_doc)
    # gen-doc walks the command tree; hand it the subparsers action rather
    # than having it spelunk argparse privates
    parser._simtpu_subcommands = sub
    return parser


def cmd_gen_doc(args: argparse.Namespace) -> int:
    """Markdown docs from the parser tree — one page per command, like the
    reference's cobra doc generator (`cmd/doc/generate_markdown.go` →
    simon.md + simon_<cmd>.md)."""
    parser = build_parser()
    os.makedirs(args.output, exist_ok=True)
    pages = [("simtpu.md", "simtpu", parser)]
    for name, sub in parser._simtpu_subcommands.choices.items():
        pages.append((f"simtpu_{name}.md", f"simtpu {name}", sub))
    for fname, title, p in pages:
        path = os.path.join(args.output, fname)
        with open(path, "w") as f:
            f.write(f"## {title}\n\n```\n{p.format_help()}\n```\n")
        print(f"wrote {path}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    from .cache import enable_compilation_cache

    enable_compilation_cache()  # one-shot CLI runs are compile-dominated
    _setup_logging()
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "command", None):
        parser.print_help()
        return 0
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
