"""YAML manifest ingestion.

Mirrors the reference's file-walking + decode pipeline:
- recursive directory walk, files sorted per directory, only .yaml/.yml loaded
  (`pkg/utils/utils.go:44-71,90-101,117-131`)
- multi-document YAML decode with unknown kinds skipped
  (`pkg/simulator/utils.go:139-183`)
"""

from __future__ import annotations

import os
from typing import List

import yaml

from ..core.objects import ResourceTypes


def parse_file_paths(path: str) -> List[str]:
    """Recursively collect regular files under path, directory-sorted.

    The top-level path must exist; odd directory entries (broken symlinks,
    sockets) are skipped, and symlinked directories are visited once.
    """
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        raise FileNotFoundError(f"invalid path: {path}")
    out: List[str] = []
    seen_dirs = {os.path.realpath(path)}

    def walk(d: str) -> None:
        for entry in sorted(os.listdir(d)):
            p = os.path.join(d, entry)
            if os.path.isfile(p):
                out.append(p)
            elif os.path.isdir(p):
                real = os.path.realpath(p)
                if real not in seen_dirs:
                    seen_dirs.add(real)
                    walk(p)

    walk(path)
    return out


class SourcedText(str):
    """A YAML document string that remembers its manifest file, so spec
    diagnostics (`workloads.validate.SpecError`) can name it.  Plain-str
    everywhere else — consumers that don't care never notice."""

    source: str = ""

    def __new__(cls, text: str, source: str):
        self = super().__new__(cls, text)
        self.source = source
        return self


def get_yaml_content_from_directory(path: str) -> List[str]:
    """Return raw YAML strings for every .yaml/.yml under path (each one
    a `SourcedText` carrying its file path)."""
    docs = []
    for fp in parse_file_paths(path):
        if os.path.splitext(fp)[1] in (".yaml", ".yml"):
            with open(fp) as f:
                docs.append(SourcedText(f.read(), fp))
    return docs


def decode_yaml_content(text: str) -> List[dict]:
    """Split a (possibly multi-document) YAML string into object dicts."""
    objs = []
    for doc in yaml.safe_load_all(text):
        if isinstance(doc, dict) and doc.get("kind"):
            objs.append(doc)
    return objs


def get_objects_from_yaml_content(docs: List[str]) -> ResourceTypes:
    """Type-switch decoded docs into ResourceTypes; unknown kinds are
    skipped (reference parity — app bundles legitimately carry Services,
    ConfigMaps...).  Objects from `SourcedText` docs are stamped with
    their manifest file for spec diagnostics."""
    from ..workloads.expand import SOURCE_KEY

    resources = ResourceTypes()
    for text in docs:
        source = getattr(text, "source", None)
        for obj in decode_yaml_content(text):
            if source:
                obj[SOURCE_KEY] = source
            resources.add(obj)
    return resources


def load_resources(path: str) -> ResourceTypes:
    """Load every manifest under a file or directory path."""
    return get_objects_from_yaml_content(get_yaml_content_from_directory(path))
