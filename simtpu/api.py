"""Public simulation API.

Mirrors the reference's facade (`pkg/simulator/core.go:14-103`): `simulate()`
is the one-shot entry (`Simulate`), `Simulator` the incremental interface
(`Interface{RunCluster, ScheduleApp, Close}`, `core.go:50-54`). The fake
clientset + informer + scheduler goroutine machinery is replaced by the
Tensorizer + scan Engine: cluster state lives in dense arrays, each app batch
is one compiled scan.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import constants as C
from .core.objects import (
    AppResource,
    NodeStatus,
    PreemptedPod,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
    annotations_of,
    deep_copy,
    name_of,
    namespace_of,
    pod_priority,
    set_annotation,
    set_label,
    shallow_pod_copy,
)
from .core.quantity import parse_quantity
from .core.tensorize import Tensorizer, _group_of_pod
from .engine.scan import (
    FAIL_ATTACH,
    FAIL_GPU,
    FAIL_INTERPOD,
    FAIL_PORTS,
    FAIL_RESOURCES,
    FAIL_SPREAD,
    FAIL_STORAGE,
    FAIL_VOLUME,
    OK,
    REASON_TEXT,
    Engine,
)

# Failure classes where evicting lower-priority pods can help — the analog of
# DefaultPreemption's PostFilter eligibility (static/affinity failures are
# priority-independent, `plugins/defaultpreemption/default_preemption.go`).
_PREEMPTIBLE_REASONS = {
    FAIL_RESOURCES,
    FAIL_PORTS,
    FAIL_STORAGE,
    FAIL_GPU,
    FAIL_INTERPOD,
    FAIL_SPREAD,
    FAIL_VOLUME,
    FAIL_ATTACH,
}
from .workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)


def _sort_app_pods(pods: List[dict], nodes: Sequence[dict] = (), use_greed: bool = False) -> List[dict]:
    """Stable emulation of the reference's app-pod ordering: AffinityQueue
    (nodeSelector pods first) then TolerationQueue (tolerations pods first),
    applied in that order (`pkg/simulator/simulator.go:172-176`). With
    `use_greed`, GreedQueue's DRF dominant-share order is applied first — the
    working version of the reference's dead `--use-greed` flag
    (`cmd/apply/apply.go:33`, never constructed outside tests)."""
    from .algo import affinity_sort, greed_sort, toleration_sort

    if use_greed:
        pods = greed_sort(pods, nodes)
    return toleration_sort(affinity_sort(pods))


class Simulator:
    """One in-memory cluster simulation."""

    def __init__(
        self,
        extra_resources: Sequence[str] = (),
        engine_factory=None,
        use_greed: bool = False,
        sched_config=None,
    ):
        self._extra_resources = extra_resources
        self._use_greed = use_greed
        self._sched_config = sched_config
        self._engine_factory = engine_factory or Engine
        self._tensorizer: Optional[Tensorizer] = None
        self._engine: Optional[Engine] = None
        self._nodes: List[dict] = []
        self._scheduled: List[dict] = []  # placed pods, nodeName set; parallel
        self._placed_prio: List[float] = []  # ... to the engine placement log
        self._preempted: List[PreemptedPod] = []
        self._unscheduled: List[UnscheduledPod] = []
        self._storage_classes: List[dict] = []
        self._pdbs: List[dict] = []

    # -- lifecycle ---------------------------------------------------------

    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        """Install nodes and schedule the cluster's own pods
        (`pkg/simulator/simulator.go:159-164,251-332`)."""
        self._nodes = [deep_copy(n) for n in cluster.nodes]
        self._storage_classes = list(cluster.storage_classes)
        # cluster PDBs constrain preemption (syncClusterResourceList creates
        # them, `pkg/simulator/simulator.go:253-258`; app PDBs are never
        # created — GenerateValidPodsFromAppResources generates pods only)
        self._pdbs = [deep_copy(p) for p in cluster.pod_disruption_budgets]
        self._tensorizer = Tensorizer(
            self._nodes,
            self._extra_resources,
            storage_classes=self._storage_classes,
            services=list(cluster.services),
            pvcs=list(cluster.persistent_volume_claims),
            pvs=list(cluster.persistent_volumes),
        )
        self._engine = self._engine_factory(self._tensorizer)
        self._engine.sched_config = self._sched_config
        self._schedule_pods(cluster.pods)
        return self._result()

    def schedule_app(self, app: AppResource) -> SimulateResult:
        """Expand one app into pods and schedule them in order
        (`pkg/simulator/simulator.go:166-184`).

        Reference parity: only the app's *pods* enter the simulation — its
        services/PDBs/etc. are never created in the fake cluster
        (`GenerateValidPodsFromAppResources` generates pods only), so
        SelectorSpread intentionally counts against cluster services alone.
        """
        pods = get_valid_pods_exclude_daemonset(app.resource)
        for ds in app.resource.daemon_sets:
            pods.extend(make_valid_pods_by_daemonset(ds, self._nodes))
        for pod in pods:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        pods = _sort_app_pods(pods, self._nodes, self._use_greed)
        self._schedule_pods(pods)
        return self._result()

    def close(self) -> None:
        self._tensorizer = None
        self._engine = None

    # -- internals ---------------------------------------------------------

    def _record_placed(self, pod: dict, node_idx: int, gpu_shares) -> None:
        placed = shallow_pod_copy(pod)
        placed["spec"]["nodeName"] = self._nodes[node_idx]["metadata"]["name"]
        placed.setdefault("status", {})["phase"] = "Running"
        # GPU device assignment annotation (GpuSharePlugin.Bind applies
        # the pod copy with the gpu-index annotation,
        # open-gpu-share.go:221-241 + utils/pod.go:117-127)
        already = annotations_of(placed).get(C.ANNO_POD_GPU_INDEX)
        if gpu_shares.sum() > 0 and not already:
            ids = []
            for dev_id, cnt in enumerate(gpu_shares):
                ids.extend([str(dev_id)] * int(round(float(cnt))))
            set_annotation(placed, C.ANNO_POD_GPU_INDEX, "-".join(ids))
        self._scheduled.append(placed)
        self._placed_prio.append(pod_priority(pod))

    def _record_failed(self, pod: dict, reason: int) -> None:
        msg = REASON_TEXT.get(int(reason), "unschedulable")
        self._unscheduled.append(
            UnscheduledPod(
                pod=pod,
                reason=(
                    f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
                    f"Unschedulable: 0/{len(self._nodes)} nodes are available: {msg}"
                ),
            )
        )

    def _schedule_pods(self, pods: Sequence[dict]) -> None:
        # Only default-scheduler pods enter the *scheduling* path: the
        # reference's pod informer filters on SchedulerName ==
        # DefaultSchedulerName (`pkg/simulator/simulator.go:100-104`), so an
        # unbound pod addressed to a foreign scheduler is never placed and
        # never reported failed. Pods already bound via spec.nodeName still
        # occupy capacity regardless of schedulerName (the reference creates
        # them in the fake cluster; only the event handler is filtered).
        # (Normalization defaults an *empty* schedulerName, workloads/expand.py,
        # so only explicitly foreign pods are excluded.)
        pods = [
            p
            for p in pods
            if (p.get("spec") or {}).get("nodeName")
            # falsy covers absent, "" and YAML null — Go unmarshals all three
            # to "" and the scheduler treats "" as the default profile
            or ((p.get("spec") or {}).get("schedulerName") or C.DEFAULT_SCHEDULER_NAME)
            == C.DEFAULT_SCHEDULER_NAME
        ]
        if not pods:
            return
        batch = self._tensorizer.add_pods(pods)
        nodes, reasons, extras = self._engine.place(batch)
        # record every batch outcome FIRST so _scheduled/_placed_prio stay
        # index-parallel with the engine's placement log (Engine.place logged
        # the whole batch already); preemption then runs against a consistent
        # view — the analog of failed pods re-entering via the backoff queue
        failed = []
        for i, (pod, node_idx, reason) in enumerate(zip(batch.pods, nodes, reasons)):
            if node_idx >= 0:
                self._record_placed(pod, node_idx, extras["gpu_shares"][i])
            else:
                failed.append((pod, int(reason)))
        for pod, reason in failed:
            if not self._try_preempt(pod, reason):
                self._record_failed(pod, reason)

    # -- preemption (DefaultPreemption PostFilter analog) -------------------

    def _try_preempt(self, pod: dict, reason: int) -> bool:
        """Evict lower-priority placed pods to make room, then retry.

        Mirrors the DefaultPreemption flow: find candidate nodes where
        removing victims plausibly fits the pod, pick the node minimizing
        (PDB violations, highest victim priority, summed priorities, victim
        count) — `defaultpreemption/default_preemption.go`
        pickOneNodeForPreemption — evict, and re-run the real filter
        pipeline; the eviction is undone if the retry still fails, so the
        cheap host-side victim model only needs to *propose* sets, never to
        be exact. Victim greed prefers PDB-free pods (lowest priority first,
        most recent first on ties) the way the reference reprieves
        PDB-violating victims preferentially (selectVictimsOnNode,
        default_preemption.go:639-668), and the violation count follows
        filterPodsWithPDBViolation's budget accounting: each matching victim
        decrements the PDB's disruptionsAllowed, violating once it goes
        negative. The simulation runs no disruption controller, so the
        budget is `status.disruptionsAllowed` as ingested (absent = 0, like
        the reference's fake cluster). Victims are reported in
        `SimulateResult.preempted_pods`, not re-queued.
        """
        import numpy as np

        from .core.objects import labels_of

        if reason not in _PREEMPTIBLE_REASONS or not self._engine.placed_node:
            return False
        prio = pod_priority(pod)
        prios = np.asarray(self._placed_prio)
        placed_nodes = np.asarray(self._engine.placed_node)
        if not np.any(prios < prio):
            return False
        tz = self._tensorizer
        g, pin_name = _group_of_pod(pod)
        gid = tz._group_ids.get(g.signature())
        if gid is None:
            return False
        static = tz._static_mask[gid]
        alloc = tz.alloc
        r = alloc.shape[1]

        def padded(row):
            return np.pad(row, (0, r - row.shape[0])) if row.shape[0] < r else row

        placed_req = np.stack(
            [padded(q) for q in self._engine.placed_req]
        ) if self._engine.placed_req else np.zeros((0, r), np.float32)
        used = np.zeros_like(alloc)
        np.add.at(used, placed_nodes, placed_req)
        pod_req = padded(self._pod_req_vector(pod))

        # per-reason victim relevance + plausibility (the retry verifies)
        ext_log = self._engine.ext_log
        placed_groups = self._engine.placed_group
        pod_ports = set(tz._port_rows[gid].keys())
        anti_terms = {t for t, v in tz._a_anti[gid].items() if v}
        spread_terms = {t for t, v in tz._spread_hard[gid].items() if v > 0}
        pod_conflict_keys = set(tz._vol_rw_rows[gid]) | set(tz._vol_ro_rows[gid])
        pod_att_classes = {
            tz._vol_class[w] for w in tz._vol_att_rows[gid] if w in tz._vol_class
        }
        probe = tz.add_pods([pod])
        gpu_need = float(probe.ext["gpu_mem"][0]) * max(
            float(probe.ext["gpu_count"][0]), 1.0
        )
        lvm_need = float(np.sum(probe.ext["lvm_size"][0]))

        # PDB bookkeeping (filterPodsWithPDBViolation semantics): a PDB with
        # a nil or EMPTY selector matches nothing here — unlike the general
        # LabelSelector rule — and unlabeled pods match no PDB (upstream
        # short-circuits on `len(pod.Labels) != 0`,
        # default_preemption.go:745-746, even though a DoesNotExist selector
        # would otherwise match them; parity kept deliberately)
        pdb_list = [
            (
                namespace_of(p),
                (p.get("spec") or {}).get("selector"),
                int(((p.get("status") or {}).get("disruptionsAllowed")) or 0),
            )
            for p in self._pdbs
        ]
        _pdb_cache: dict = {}

        def pdbs_matching(i: int) -> tuple:
            got = _pdb_cache.get(i)
            if got is None:
                from .core.match import match_label_selector

                victim = self._scheduled[i]
                labels = labels_of(victim)
                got = tuple(
                    j
                    for j, (ns, sel, _) in enumerate(pdb_list)
                    if labels
                    and ns == namespace_of(victim)
                    and sel
                    and (sel.get("matchLabels") or sel.get("matchExpressions"))
                    and match_label_selector(sel, labels)
                )
                _pdb_cache[i] = got
            return got

        def pdb_violations(victim_idx) -> int:
            """How many victims push a matching PDB's budget negative."""
            allowed = [a for (_, _, a) in pdb_list]
            count = 0
            for i in victim_idx:
                violated = False
                for j in pdbs_matching(i):
                    allowed[j] -= 1
                    if allowed[j] < 0:
                        violated = True
                count += violated
            return count

        def victim_helps(i: int) -> bool:
            vg = placed_groups[i]
            if reason == FAIL_PORTS:
                return bool(pod_ports & set(tz._port_rows[vg].keys()))
            if reason == FAIL_GPU:
                return ext_log["gpu_mem"][i] > 0
            if reason == FAIL_STORAGE:
                return (
                    float(np.sum(ext_log["vg_alloc"][i])) > 0
                    or bool(np.any(ext_log["sdev_take"][i]))
                )
            if reason == FAIL_INTERPOD:
                return any(tz._s_match[vg].get(t) for t in anti_terms)
            if reason == FAIL_SPREAD:
                return any(tz._s_match[vg].get(t) for t in spread_terms)
            if reason == FAIL_VOLUME:
                # the victim must hold one of the conflicting volume
                # identities via a rw/ro mount — attach-only usage (resolved
                # PVC attachables) cannot cause a VolumeRestrictions conflict
                victim_keys = set(tz._vol_rw_rows[vg]) | set(tz._vol_ro_rows[vg])
                return bool(pod_conflict_keys & victim_keys)
            if reason == FAIL_ATTACH:
                # evicting any holder of a same-class attachable frees a slot
                victim_classes = {
                    tz._vol_class[w]
                    for w in set(tz._vol_att_rows[vg]) | set(tz._vol_rw_rows[vg])
                    if w in tz._vol_class
                }
                return bool(pod_att_classes & victim_classes)
            return True  # FAIL_RESOURCES: any eviction frees resources

        best = None  # (key, node, victim_indices)
        for n in range(len(self._nodes)):
            if not static[n]:
                continue
            if pin_name is not None and name_of(self._nodes[n]) != pin_name:
                continue
            cand = np.flatnonzero((placed_nodes == n) & (prios < prio))
            cand = [int(i) for i in cand if victim_helps(int(i))]
            if not cand:
                continue
            # budget-aware reprieve split (filterPodsWithPDBViolation over
            # the node's potential victims in MoreImportantPod order): a
            # victim whose PDB budget still absorbs the eviction is
            # NON-violating and ranks purely by priority; then greedy order =
            # non-violating first, lowest priority first, later placements
            # first on ties
            allowed_n = [a for (_, _, a) in pdb_list]
            violating = set()
            for i in sorted(cand, key=lambda i: (-prios[i], i)):
                viol = False
                for j in pdbs_matching(i):
                    allowed_n[j] -= 1
                    if allowed_n[j] < 0:
                        viol = True
                if viol:
                    violating.add(i)
            cand.sort(key=lambda i: (i in violating, prios[i], -i))
            on_node = np.flatnonzero(placed_nodes == n)
            gpu_free = float(np.sum(tz.ext.gpu_dev_total[n])) - sum(
                float(np.sum(ext_log["gpu_shares"][i])) * ext_log["gpu_mem"][i]
                for i in on_node
            )
            vg_free = float(
                np.sum(tz.ext.vg_cap[n]) - np.sum(tz.ext.vg_req0[n])
            ) - sum(float(np.sum(ext_log["vg_alloc"][i])) for i in on_node)
            free = alloc[n] - used[n]
            victims: List[int] = []

            def plausible() -> bool:
                if not np.all(free >= pod_req - 1e-6):
                    return False
                if reason in (FAIL_PORTS, FAIL_INTERPOD, FAIL_SPREAD, FAIL_VOLUME, FAIL_ATTACH):
                    # every relevant victim on this node must be gone (a
                    # single eviction may leave another conflicting holder or
                    # an attach-limit class still saturated)
                    return all(i in victims for i in cand)
                if reason == FAIL_GPU:
                    return gpu_free >= gpu_need - 1e-6
                if reason == FAIL_STORAGE:
                    return vg_free >= lvm_need - 1e-6
                return True

            for i in cand:
                if victims and plausible():
                    break
                free = free + placed_req[i]
                gpu_free += float(np.sum(ext_log["gpu_shares"][i])) * ext_log["gpu_mem"][i]
                vg_free += float(np.sum(ext_log["vg_alloc"][i]))
                victims.append(i)
            if not victims or not plausible():
                continue
            varr = np.asarray(victims)
            key = (
                pdb_violations(victims),  # pickOneNode criterion 1
                float(prios[varr].max()),
                float(prios[varr].sum()),
                len(victims),
                n,
            )
            if best is None or key < best[0]:
                best = (key, n, victims)
        if best is None:
            return False
        _, node, victims = best

        saved = self._engine.remove_placements(victims)
        saved_pods = [(i, self._scheduled[i], self._placed_prio[i]) for i in saved["indices"]]
        for i in reversed(saved["indices"]):
            del self._scheduled[i]
            del self._placed_prio[i]

        nodes, reasons, extras = self._engine.place(probe)
        if nodes[0] < 0:
            # the cheap resource model was too optimistic — undo the eviction
            self._engine.restore_placements(saved)
            for i, victim, vprio in saved_pods:
                self._scheduled.insert(i, victim)
                self._placed_prio.insert(i, vprio)
            return False
        who = f"{namespace_of(pod)}/{name_of(pod)}"
        for _, victim, _ in saved_pods:
            self._preempted.append(
                PreemptedPod(
                    pod=victim,
                    preempted_by=who,
                    node=victim["spec"].get("nodeName", ""),
                )
            )
        self._record_placed(pod, nodes[0], extras["gpu_shares"][0])
        return True

    def _pod_req_vector(self, pod: dict):
        """The pod's request row in the tensorizer's resource vocabulary."""
        import numpy as np

        from .core.objects import pod_requests
        from .core.tensorize import RES_PODS

        req = np.zeros(len(self._tensorizer.resources), np.float32)
        req[RES_PODS] = 1.0
        for rname, val in pod_requests(pod).items():
            ridx = self._tensorizer.resources.get(rname)
            if ridx >= 0:
                req[ridx] = val
        return req

    def _result(self) -> SimulateResult:
        by_node = {name_of(n): [] for n in self._nodes}
        for pod in self._scheduled:
            by_node[pod["spec"]["nodeName"]].append(shallow_pod_copy(pod))
        nodes = [deep_copy(n) for n in self._nodes]
        self._write_extended_annotations(nodes)
        statuses = [NodeStatus(node=n, pods=by_node[name_of(n)]) for n in nodes]
        return SimulateResult(
            unscheduled_pods=list(self._unscheduled),
            node_status=statuses,
            preempted_pods=list(self._preempted),
        )

    def _write_extended_annotations(self, nodes: List[dict]) -> None:
        """Mirror the storage/GPU state the reference's Bind/Reserve plugins
        write back into node annotations (`plugin/open-local.go:218-249`,
        `plugin/open-gpu-share.go:146-189`)."""
        import json as _json

        import numpy as np

        from .core.extended import NodeStorage

        ext = self._tensorizer.ext
        log = self._engine.ext_log
        n = len(nodes)
        v = ext.vg_cap.shape[1]
        sd = ext.sdev_cap.shape[1]
        gd = ext.gpu_dev_total.shape[1]
        vg_used = np.zeros((n, v), np.float64)
        sdev_taken = np.zeros((n, sd), bool)
        gpu_used = np.zeros((n, gd), np.float64)
        gpu_pods = np.zeros(n, np.int64)
        for node_idx, vg_alloc, take, shares, mem in zip(
            log["node"], log["vg_alloc"], log["sdev_take"], log["gpu_shares"], log["gpu_mem"]
        ):
            vg_used[node_idx] += vg_alloc
            sdev_taken[node_idx] |= take
            gpu_used[node_idx] += np.asarray(shares) * mem
            if mem > 0:
                gpu_pods[node_idx] += 1
        for i, node in enumerate(nodes):
            storage = NodeStorage.from_node(node)
            if storage is not None:
                for j, vg in enumerate(storage.vgs):
                    if j < v:
                        prev = parse_quantity(vg.get("requested") or 0)
                        vg["requested"] = int(prev + vg_used[i, j])
                        if isinstance(vg.get("capacity"), str):
                            vg["capacity"] = int(parse_quantity(vg["capacity"]))
                for j, dev in enumerate(storage.devices):
                    if j < sd and sdev_taken[i, j]:
                        dev["isAllocated"] = True
                set_annotation(
                    node,
                    C.ANNO_NODE_LOCAL_STORAGE,
                    _json.dumps({"vgs": storage.vgs, "devices": storage.devices}),
                )
            if ext.gpu_total[i] > 0:
                devs = {
                    str(j): {
                        "gpuTotalMemory": int(ext.gpu_dev_total[i, j]),
                        "gpuUsedMemory": int(gpu_used[i, j]),
                    }
                    for j in range(gd)
                    if ext.gpu_dev_total[i, j] > 0
                }
                info = {
                    "gpuCount": int((ext.gpu_dev_total[i] > 0).sum()),
                    "gpuAllocatable": int(
                        ((ext.gpu_dev_total[i] > 0) & (gpu_used[i] == 0)).sum()
                    ),
                    "gpuTotalMemory": int(ext.gpu_total[i]),
                    "gpuUsedMemory": int(gpu_used[i].sum()),
                    "numPods": int(gpu_pods[i]),
                    "devs": devs,
                }
                set_annotation(node, C.ANNO_NODE_GPU_SHARE, _json.dumps(info))


def simulate(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    extended_resources: Sequence[str] = (),
    engine_factory=None,
    use_greed: bool = False,
    bulk: bool = False,
    sched_config=None,
) -> SimulateResult:
    """One-shot simulation (`pkg/simulator/core.go:64-103`): expand cluster
    workloads, run the cluster, then schedule each app in configured order.
    Unscheduled pods accumulate across the cluster and every app; node status
    reflects the final cluster. Pass
    `engine_factory=lambda t: ShardedEngine(t, mesh)` to run the scan with the
    node axis sharded over a device mesh (simtpu/parallel), or `bulk=True`
    to place same-spec pod runs in bulk rounds (engine/rounds.py —
    feasibility-exact, tie-breaking may differ from the serial scan). The two
    are mutually exclusive.

    Result pods are copied at the levels the simulation wrote (top level,
    metadata incl. labels/annotations, spec, status); deeper sub-structures
    (containers, volumes, affinity, ...) are shared READ-ONLY with the input
    objects — treat returned pods as immutable below those layers, or
    deep-copy before mutating (at million-pod scale a full deep copy per
    placed pod costs more than the placement itself)."""
    if bulk:
        if engine_factory is not None:
            raise ValueError("bulk=True and engine_factory are mutually exclusive")
        from .engine.rounds import RoundsEngine

        engine_factory = RoundsEngine
    sim = Simulator(
        extra_resources=extended_resources,
        engine_factory=engine_factory,
        use_greed=use_greed,
        sched_config=sched_config,
    )
    cluster = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    cluster_pods = get_valid_pods_exclude_daemonset(cluster)
    for ds in cluster.daemon_sets:
        cluster_pods.extend(make_valid_pods_by_daemonset(ds, cluster.nodes))
    cluster.pods = cluster_pods
    try:
        result = sim.run_cluster(cluster)
        for app in apps:
            result = sim.schedule_app(app)
        return result
    finally:
        sim.close()
