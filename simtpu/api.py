"""Public simulation API.

Mirrors the reference's facade (`pkg/simulator/core.go:14-103`): `simulate()`
is the one-shot entry (`Simulate`), `Simulator` the incremental interface
(`Interface{RunCluster, ScheduleApp, Close}`, `core.go:50-54`). The fake
clientset + informer + scheduler goroutine machinery is replaced by the
Tensorizer + scan Engine: cluster state lives in dense arrays, each app batch
is one compiled scan.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import constants as C
from .core.objects import (
    AppResource,
    NodeStatus,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
    deep_copy,
    name_of,
    namespace_of,
    set_label,
)
from .core.tensorize import Tensorizer
from .engine.scan import OK, REASON_TEXT, Engine
from .workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)


def _sort_app_pods(pods: List[dict]) -> List[dict]:
    """Stable emulation of the reference's app-pod ordering: AffinityQueue
    (nodeSelector pods first) then TolerationQueue (tolerations pods first),
    applied in that order (`pkg/simulator/simulator.go:172-176`;
    `pkg/algo/affinity.go:21-23`, `toleration.go:19-21`)."""
    pods = sorted(pods, key=lambda p: (p.get("spec") or {}).get("nodeSelector") is None)
    return sorted(pods, key=lambda p: (p.get("spec") or {}).get("tolerations") is None)


class Simulator:
    """One in-memory cluster simulation."""

    def __init__(self, extra_resources: Sequence[str] = ()):
        self._extra_resources = extra_resources
        self._tensorizer: Optional[Tensorizer] = None
        self._engine: Optional[Engine] = None
        self._nodes: List[dict] = []
        self._scheduled: List[dict] = []  # placed pods, nodeName set
        self._unscheduled: List[UnscheduledPod] = []

    # -- lifecycle ---------------------------------------------------------

    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        """Install nodes and schedule the cluster's own pods
        (`pkg/simulator/simulator.go:159-164,251-332`)."""
        self._nodes = [deep_copy(n) for n in cluster.nodes]
        self._tensorizer = Tensorizer(self._nodes, self._extra_resources)
        self._engine = Engine(self._tensorizer)
        self._schedule_pods(cluster.pods)
        return self._result()

    def schedule_app(self, app: AppResource) -> SimulateResult:
        """Expand one app into pods and schedule them in order
        (`pkg/simulator/simulator.go:166-184`)."""
        pods = get_valid_pods_exclude_daemonset(app.resource)
        for ds in app.resource.daemon_sets:
            pods.extend(make_valid_pods_by_daemonset(ds, self._nodes))
        for pod in pods:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        pods = _sort_app_pods(pods)
        self._schedule_pods(pods)
        return self._result()

    def close(self) -> None:
        self._tensorizer = None
        self._engine = None

    # -- internals ---------------------------------------------------------

    def _schedule_pods(self, pods: Sequence[dict]) -> None:
        if not pods:
            return
        batch = self._tensorizer.add_pods(pods)
        nodes, reasons = self._engine.place(batch)
        n_total = len(self._nodes)
        for pod, node_idx, reason in zip(batch.pods, nodes, reasons):
            if node_idx >= 0:
                placed = deep_copy(pod)
                placed["spec"]["nodeName"] = self._nodes[node_idx]["metadata"]["name"]
                placed.setdefault("status", {})["phase"] = "Running"
                self._scheduled.append(placed)
            else:
                msg = REASON_TEXT.get(int(reason), "unschedulable")
                self._unscheduled.append(
                    UnscheduledPod(
                        pod=pod,
                        reason=(
                            f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
                            f"Unschedulable: 0/{n_total} nodes are available: {msg}"
                        ),
                    )
                )

    def _result(self) -> SimulateResult:
        by_node = {name_of(n): [] for n in self._nodes}
        for pod in self._scheduled:
            by_node[pod["spec"]["nodeName"]].append(deep_copy(pod))
        statuses = [
            NodeStatus(node=deep_copy(n), pods=by_node[name_of(n)]) for n in self._nodes
        ]
        return SimulateResult(
            unscheduled_pods=list(self._unscheduled), node_status=statuses
        )


def simulate(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    extended_resources: Sequence[str] = (),
) -> SimulateResult:
    """One-shot simulation (`pkg/simulator/core.go:64-103`): expand cluster
    workloads, run the cluster, then schedule each app in configured order.
    Unscheduled pods accumulate across the cluster and every app; node status
    reflects the final cluster."""
    sim = Simulator(extra_resources=extended_resources)
    cluster = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    cluster_pods = get_valid_pods_exclude_daemonset(cluster)
    for ds in cluster.daemon_sets:
        cluster_pods.extend(make_valid_pods_by_daemonset(ds, cluster.nodes))
    cluster.pods = cluster_pods
    try:
        result = sim.run_cluster(cluster)
        for app in apps:
            result = sim.schedule_app(app)
        return result
    finally:
        sim.close()
