"""Public simulation API.

Mirrors the reference's facade (`pkg/simulator/core.go:14-103`): `simulate()`
is the one-shot entry (`Simulate`), `Simulator` the incremental interface
(`Interface{RunCluster, ScheduleApp, Close}`, `core.go:50-54`). The fake
clientset + informer + scheduler goroutine machinery is replaced by the
Tensorizer + scan Engine: cluster state lives in dense arrays, each app batch
is one compiled scan.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import constants as C
from .core.objects import (
    AppResource,
    NodeStatus,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
    annotations_of,
    deep_copy,
    name_of,
    namespace_of,
    set_annotation,
    set_label,
)
from .core.quantity import parse_quantity
from .core.tensorize import Tensorizer
from .engine.scan import OK, REASON_TEXT, Engine
from .workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)


def _sort_app_pods(pods: List[dict]) -> List[dict]:
    """Stable emulation of the reference's app-pod ordering: AffinityQueue
    (nodeSelector pods first) then TolerationQueue (tolerations pods first),
    applied in that order (`pkg/simulator/simulator.go:172-176`;
    `pkg/algo/affinity.go:21-23`, `toleration.go:19-21`)."""
    pods = sorted(pods, key=lambda p: (p.get("spec") or {}).get("nodeSelector") is None)
    return sorted(pods, key=lambda p: (p.get("spec") or {}).get("tolerations") is None)


class Simulator:
    """One in-memory cluster simulation."""

    def __init__(self, extra_resources: Sequence[str] = (), engine_factory=None):
        self._extra_resources = extra_resources
        self._engine_factory = engine_factory or Engine
        self._tensorizer: Optional[Tensorizer] = None
        self._engine: Optional[Engine] = None
        self._nodes: List[dict] = []
        self._scheduled: List[dict] = []  # placed pods, nodeName set
        self._unscheduled: List[UnscheduledPod] = []
        self._storage_classes: List[dict] = []

    # -- lifecycle ---------------------------------------------------------

    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        """Install nodes and schedule the cluster's own pods
        (`pkg/simulator/simulator.go:159-164,251-332`)."""
        self._nodes = [deep_copy(n) for n in cluster.nodes]
        self._storage_classes = list(cluster.storage_classes)
        self._tensorizer = Tensorizer(
            self._nodes, self._extra_resources, storage_classes=self._storage_classes
        )
        self._engine = self._engine_factory(self._tensorizer)
        self._schedule_pods(cluster.pods)
        return self._result()

    def schedule_app(self, app: AppResource) -> SimulateResult:
        """Expand one app into pods and schedule them in order
        (`pkg/simulator/simulator.go:166-184`)."""
        pods = get_valid_pods_exclude_daemonset(app.resource)
        for ds in app.resource.daemon_sets:
            pods.extend(make_valid_pods_by_daemonset(ds, self._nodes))
        for pod in pods:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        pods = _sort_app_pods(pods)
        self._schedule_pods(pods)
        return self._result()

    def close(self) -> None:
        self._tensorizer = None
        self._engine = None

    # -- internals ---------------------------------------------------------

    def _schedule_pods(self, pods: Sequence[dict]) -> None:
        if not pods:
            return
        batch = self._tensorizer.add_pods(pods)
        nodes, reasons, extras = self._engine.place(batch)
        n_total = len(self._nodes)
        for i, (pod, node_idx, reason) in enumerate(zip(batch.pods, nodes, reasons)):
            if node_idx >= 0:
                placed = deep_copy(pod)
                placed["spec"]["nodeName"] = self._nodes[node_idx]["metadata"]["name"]
                placed.setdefault("status", {})["phase"] = "Running"
                # GPU device assignment annotation (GpuSharePlugin.Bind applies
                # the pod copy with the gpu-index annotation,
                # open-gpu-share.go:221-241 + utils/pod.go:117-127)
                shares = extras["gpu_shares"][i]
                already = annotations_of(placed).get(C.ANNO_POD_GPU_INDEX)
                if shares.sum() > 0 and not already:
                    ids = []
                    for dev_id, cnt in enumerate(shares):
                        ids.extend([str(dev_id)] * int(round(float(cnt))))
                    set_annotation(placed, C.ANNO_POD_GPU_INDEX, "-".join(ids))
                self._scheduled.append(placed)
            else:
                msg = REASON_TEXT.get(int(reason), "unschedulable")
                self._unscheduled.append(
                    UnscheduledPod(
                        pod=pod,
                        reason=(
                            f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
                            f"Unschedulable: 0/{n_total} nodes are available: {msg}"
                        ),
                    )
                )

    def _result(self) -> SimulateResult:
        by_node = {name_of(n): [] for n in self._nodes}
        for pod in self._scheduled:
            by_node[pod["spec"]["nodeName"]].append(deep_copy(pod))
        nodes = [deep_copy(n) for n in self._nodes]
        self._write_extended_annotations(nodes)
        statuses = [NodeStatus(node=n, pods=by_node[name_of(n)]) for n in nodes]
        return SimulateResult(
            unscheduled_pods=list(self._unscheduled), node_status=statuses
        )

    def _write_extended_annotations(self, nodes: List[dict]) -> None:
        """Mirror the storage/GPU state the reference's Bind/Reserve plugins
        write back into node annotations (`plugin/open-local.go:218-249`,
        `plugin/open-gpu-share.go:146-189`)."""
        import json as _json

        import numpy as np

        from .core.extended import NodeStorage

        ext = self._tensorizer.ext
        log = self._engine.ext_log
        n = len(nodes)
        v = ext.vg_cap.shape[1]
        sd = ext.sdev_cap.shape[1]
        gd = ext.gpu_dev_total.shape[1]
        vg_used = np.zeros((n, v), np.float64)
        sdev_taken = np.zeros((n, sd), bool)
        gpu_used = np.zeros((n, gd), np.float64)
        gpu_pods = np.zeros(n, np.int64)
        for node_idx, vg_alloc, take, shares, mem in zip(
            log["node"], log["vg_alloc"], log["sdev_take"], log["gpu_shares"], log["gpu_mem"]
        ):
            vg_used[node_idx] += vg_alloc
            sdev_taken[node_idx] |= take
            gpu_used[node_idx] += np.asarray(shares) * mem
            if mem > 0:
                gpu_pods[node_idx] += 1
        for i, node in enumerate(nodes):
            storage = NodeStorage.from_node(node)
            if storage is not None:
                for j, vg in enumerate(storage.vgs):
                    if j < v:
                        prev = parse_quantity(vg.get("requested") or 0)
                        vg["requested"] = int(prev + vg_used[i, j])
                        if isinstance(vg.get("capacity"), str):
                            vg["capacity"] = int(parse_quantity(vg["capacity"]))
                for j, dev in enumerate(storage.devices):
                    if j < sd and sdev_taken[i, j]:
                        dev["isAllocated"] = True
                set_annotation(
                    node,
                    C.ANNO_NODE_LOCAL_STORAGE,
                    _json.dumps({"vgs": storage.vgs, "devices": storage.devices}),
                )
            if ext.gpu_total[i] > 0:
                devs = {
                    str(j): {
                        "gpuTotalMemory": int(ext.gpu_dev_total[i, j]),
                        "gpuUsedMemory": int(gpu_used[i, j]),
                    }
                    for j in range(gd)
                    if ext.gpu_dev_total[i, j] > 0
                }
                info = {
                    "gpuCount": int((ext.gpu_dev_total[i] > 0).sum()),
                    "gpuAllocatable": int(
                        ((ext.gpu_dev_total[i] > 0) & (gpu_used[i] == 0)).sum()
                    ),
                    "gpuTotalMemory": int(ext.gpu_total[i]),
                    "gpuUsedMemory": int(gpu_used[i].sum()),
                    "numPods": int(gpu_pods[i]),
                    "devs": devs,
                }
                set_annotation(node, C.ANNO_NODE_GPU_SHARE, _json.dumps(info))


def simulate(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    extended_resources: Sequence[str] = (),
    engine_factory=None,
) -> SimulateResult:
    """One-shot simulation (`pkg/simulator/core.go:64-103`): expand cluster
    workloads, run the cluster, then schedule each app in configured order.
    Unscheduled pods accumulate across the cluster and every app; node status
    reflects the final cluster. Pass
    `engine_factory=lambda t: ShardedEngine(t, mesh)` to run the scan with the
    node axis sharded over a device mesh (simtpu/parallel)."""
    sim = Simulator(extra_resources=extended_resources, engine_factory=engine_factory)
    cluster = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    cluster_pods = get_valid_pods_exclude_daemonset(cluster)
    for ds in cluster.daemon_sets:
        cluster_pods.extend(make_valid_pods_by_daemonset(ds, cluster.nodes))
    cluster.pods = cluster_pods
    try:
        result = sim.run_cluster(cluster)
        for app in apps:
            result = sim.schedule_app(app)
        return result
    finally:
        sim.close()
