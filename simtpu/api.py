"""Public simulation API.

Mirrors the reference's facade (`pkg/simulator/core.go:14-103`): `simulate()`
is the one-shot entry (`Simulate`), `Simulator` the incremental interface
(`Interface{RunCluster, ScheduleApp, Close}`, `core.go:50-54`). The fake
clientset + informer + scheduler goroutine machinery is replaced by the
Tensorizer + scan Engine: cluster state lives in dense arrays, each app batch
is one compiled scan.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

from . import constants as C
from .core.objects import (
    AppResource,
    NodeStatus,
    PreemptedPod,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
    annotations_of,
    deep_copy,
    name_of,
    namespace_of,
    pod_priority,
    set_annotation,
    set_label,
    shallow_pod_copy,
)
from .core.quantity import parse_quantity
from .core.tensorize import Tensorizer, _group_of_pod
from .workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)
from .engine.scan import (
    FAIL_ATTACH,
    FAIL_GPU,
    FAIL_INTERPOD,
    FAIL_PORTS,
    FAIL_RESOURCES,
    FAIL_SPREAD,
    FAIL_STORAGE,
    FAIL_VOLUME,
    REASON_TEXT,
    Engine,
)
from .obs.trace import span

# Failure classes where evicting lower-priority pods can help — the analog of
# DefaultPreemption's PostFilter eligibility (static/affinity failures are
# priority-independent, `plugins/defaultpreemption/default_preemption.go`).
_PREEMPTIBLE_REASONS = {
    FAIL_RESOURCES,
    FAIL_PORTS,
    FAIL_STORAGE,
    FAIL_GPU,
    FAIL_INTERPOD,
    FAIL_SPREAD,
    FAIL_VOLUME,
    FAIL_ATTACH,
}
log = logging.getLogger("simtpu.api")

#: reason suffix for pods finalized by the preemption wave cap — a tripped
#: cap is a termination-insurance abort, not a genuine verify failure, and
#: must be distinguishable in the report (ADVICE r5, `waves_left`)
PREEMPT_WAVE_CAP_NOTE = "preemption retry aborted: wave cap exhausted"


def _anti_topo_keys(pod: dict) -> set:
    """topologyKeys of the pod's REQUIRED anti-affinity terms."""
    from .core.objects import pod_affinity

    anti = pod_affinity(pod).get("podAntiAffinity") or {}
    return {
        t.get("topologyKey")
        for t in anti.get("requiredDuringSchedulingIgnoredDuringExecution") or []
        if t.get("topologyKey")
    }


def _head_affinity_depends_on(pod: dict, wave_pods) -> bool:
    """True when `pod`'s REQUIRED positive pod-affinity selects another
    wave pod's labels — the case where finalizing the pod's retry failure
    would be unsound: a fresh-retried head verifies FIRST in its wave, so
    its verdict never saw the selected pod placed, and that pod (demoted or
    later in the wave) may yet place.  Mirrors the demote predicate's
    conservatism (namespace scoping is deliberately ignored: a false
    positive just defers finality, bounded by the wave cap; a false
    negative would finalize a failure the serial evict/retry order could
    avoid — ADVICE r5 #3)."""
    from .core.match import match_label_selector
    from .core.objects import labels_of, pod_affinity

    aff = pod_affinity(pod).get("podAffinity") or {}
    terms = aff.get("requiredDuringSchedulingIgnoredDuringExecution") or []
    if not terms:
        return False
    return any(
        match_label_selector(t.get("labelSelector"), labels_of(dp))
        for t in terms
        for dp in wave_pods
    )


def _restore_topo_keys(pod: dict) -> set:
    """topologyKeys along which re-adding previously evicted pods can turn
    this pod's filter verdict from pass to fail on a node the victims do
    NOT occupy.  Only domain-scoped negative constraints can: required pod
    anti-affinity and DoNotSchedule topology spread (a restore raises
    domain counts).  Positive required affinity can't — restores only add
    satisfiers."""
    from .core.objects import pod_topology_spread_constraints

    keys = _anti_topo_keys(pod)
    keys |= {
        c.get("topologyKey")
        for c in pod_topology_spread_constraints(pod)
        if (c.get("whenUnsatisfiable") or "DoNotSchedule") == "DoNotSchedule"
        and c.get("topologyKey")
    }
    return keys


def _sort_app_pods(pods: List[dict], nodes: Sequence[dict] = (), use_greed: bool = False) -> List[dict]:
    """Stable emulation of the reference's app-pod ordering: AffinityQueue
    (nodeSelector pods first) then TolerationQueue (tolerations pods first),
    applied in that order (`pkg/simulator/simulator.go:172-176`). With
    `use_greed`, GreedQueue's DRF dominant-share order is applied first — the
    working version of the reference's dead `--use-greed` flag
    (`cmd/apply/apply.go:33`, never constructed outside tests)."""
    from .algo import affinity_sort, greed_sort, toleration_sort

    if use_greed:
        pods = greed_sort(pods, nodes)
    return toleration_sort(affinity_sort(pods))


class Simulator:
    """One in-memory cluster simulation."""

    #: slack term of the preemption wave-loop termination cap
    #: (`_preempt_failed_batch`): waves_left = WAVE_CAP_SLACK + 2 * len(failed)
    WAVE_CAP_SLACK = 4

    def __init__(
        self,
        extra_resources: Sequence[str] = (),
        engine_factory=None,
        use_greed: bool = False,
        sched_config=None,
        precompile: bool = False,
    ):
        self._extra_resources = extra_resources
        self._use_greed = use_greed
        self._sched_config = sched_config
        # AOT-precompile each batch's jit executables on a background
        # thread pool before dispatching it (engine/precompile.py); the
        # pipeline registry persists across batches of one simulation
        self._precompile = precompile
        self._pipeline = None
        self._engine_factory = engine_factory or Engine
        self._tensorizer: Optional[Tensorizer] = None
        self._engine: Optional[Engine] = None
        self._nodes: List[dict] = []
        self._scheduled: List[dict] = []  # placed pods, nodeName set; parallel
        self._placed_prio: List[float] = []  # ... to the engine placement log
        # ... and whether each entry was BOUND via spec.nodeName before
        # scheduling (statically bound pods die with their node under fault
        # drains, faults/drain.py — the placed copies are indistinguishable
        # after record_placed_pod sets nodeName on everything)
        self._placed_forced: List[bool] = []
        self._preempted: List[PreemptedPod] = []
        self._unscheduled: List[UnscheduledPod] = []
        # recorded failure codes, index-parallel to _unscheduled — the
        # legacy headline reasons the explain pass keeps bit-equal
        # (simtpu/explain)
        self._unscheduled_codes: List[int] = []
        self._storage_classes: List[dict] = []
        self._pdbs: List[dict] = []

    # -- lifecycle ---------------------------------------------------------

    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        """Install nodes and schedule the cluster's own pods
        (`pkg/simulator/simulator.go:159-164,251-332`)."""
        self._nodes = [deep_copy(n) for n in cluster.nodes]
        self._storage_classes = list(cluster.storage_classes)
        # cluster PDBs constrain preemption (syncClusterResourceList creates
        # them, `pkg/simulator/simulator.go:253-258`; app PDBs are never
        # created — GenerateValidPodsFromAppResources generates pods only)
        self._pdbs = [deep_copy(p) for p in cluster.pod_disruption_budgets]
        with span("tensorize", nodes=len(self._nodes)):
            self._tensorizer = Tensorizer(
                self._nodes,
                self._extra_resources,
                storage_classes=self._storage_classes,
                services=list(cluster.services),
                pvcs=list(cluster.persistent_volume_claims),
                pvs=list(cluster.persistent_volumes),
            )
        self._engine = self._engine_factory(self._tensorizer)
        self._engine.sched_config = self._sched_config
        with span("schedule.cluster", pods=len(cluster.pods)):
            self._schedule_pods(cluster.pods)
        return self._result()

    def schedule_app(self, app: AppResource) -> SimulateResult:
        """Expand one app into pods and schedule them in order
        (`pkg/simulator/simulator.go:166-184`).

        Reference parity: only the app's *pods* enter the simulation — its
        services/PDBs/etc. are never created in the fake cluster
        (`GenerateValidPodsFromAppResources` generates pods only), so
        SelectorSpread intentionally counts against cluster services alone.
        """
        with span("expand", app=app.name):
            pods = get_valid_pods_exclude_daemonset(app.resource)
            for ds in app.resource.daemon_sets:
                pods.extend(make_valid_pods_by_daemonset(ds, self._nodes))
            for pod in pods:
                set_label(pod, C.LABEL_APP_NAME, app.name)
            pods = _sort_app_pods(pods, self._nodes, self._use_greed)
        with span("schedule.app", app=app.name, pods=len(pods)):
            self._schedule_pods(pods)
        return self._result()

    def close(self) -> None:
        if self._pipeline is not None:
            # cancel enumerated-but-undispatched background compiles so a
            # one-shot run doesn't linger at exit finishing unused work
            self._pipeline.shutdown()
            self._pipeline = None
        self._tensorizer = None
        self._engine = None

    # -- internals ---------------------------------------------------------

    def _record_placed(
        self, pod: dict, node_idx: int, gpu_shares, forced: bool = False
    ) -> None:
        self._scheduled.append(
            record_placed_pod(
                pod, self._nodes[node_idx]["metadata"]["name"], gpu_shares
            )
        )
        self._placed_prio.append(pod_priority(pod))
        self._placed_forced.append(forced)

    def _record_failed(self, pod: dict, reason: int, note: str = "") -> None:
        # the .get fallback is provably unreachable: every FAIL_* code has
        # a REASON_TEXT entry (engine/scan._check_reason_text fails the
        # import otherwise) and reasons here come from the engine's codes
        msg = REASON_TEXT.get(int(reason), "unschedulable")
        if note:
            msg = f"{msg} ({note})"
        self._unscheduled.append(
            UnscheduledPod(
                pod=pod,
                reason=(
                    f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
                    f"Unschedulable: 0/{len(self._nodes)} nodes are available: {msg}"
                ),
            )
        )
        self._unscheduled_codes.append(int(reason))

    def _schedule_pods(self, pods: Sequence[dict]) -> None:
        # Only default-scheduler pods enter the *scheduling* path: the
        # reference's pod informer filters on SchedulerName ==
        # DefaultSchedulerName (`pkg/simulator/simulator.go:100-104`), so an
        # unbound pod addressed to a foreign scheduler is never placed and
        # never reported failed. Pods already bound via spec.nodeName still
        # occupy capacity regardless of schedulerName (the reference creates
        # them in the fake cluster; only the event handler is filtered).
        # (Normalization defaults an *empty* schedulerName, workloads/expand.py,
        # so only explicitly foreign pods are excluded.)
        pods = [
            p
            for p in pods
            if (p.get("spec") or {}).get("nodeName")
            # falsy covers absent, "" and YAML null — Go unmarshals all three
            # to "" and the scheduler treats "" as the default profile
            or ((p.get("spec") or {}).get("schedulerName") or C.DEFAULT_SCHEDULER_NAME)
            == C.DEFAULT_SCHEDULER_NAME
        ]
        if not pods:
            return
        batch = self._tensorizer.add_pods(pods)
        if self._precompile:
            from .engine.precompile import precompile_place

            self._pipeline = precompile_place(
                self._engine, batch, self._pipeline
            )
        nodes, reasons, extras = self._engine.place(batch)
        # record every batch outcome FIRST so _scheduled/_placed_prio stay
        # index-parallel with the engine's placement log (Engine.place logged
        # the whole batch already); preemption then runs against a consistent
        # view — the analog of failed pods re-entering via the backoff queue
        failed = []
        for i, (pod, node_idx, reason) in enumerate(zip(batch.pods, nodes, reasons)):
            if node_idx >= 0:
                self._record_placed(
                    pod, node_idx, extras["gpu_shares"][i],
                    forced=bool(batch.forced[i]),
                )
            else:
                failed.append((pod, int(reason)))
        self._preempt_failed_batch(failed)

    # -- preemption (DefaultPreemption PostFilter analog) -------------------

    def _build_preempt_model(self) -> dict:
        """Whole-log host arrays shared by a preemption WAVE: priorities,
        per-entry node/request/extended usage, and per-node usage sums.
        Built once per wave (O(log)) and updated incrementally per victim
        proposal — the r3 implementation rebuilt all of it per preemption,
        which dominated at 10^5-entry logs (VERDICT r3 weak #1)."""
        import numpy as np

        tz = self._tensorizer
        alloc = tz.alloc
        r = alloc.shape[1]
        eng = self._engine

        def padded(row):
            return np.pad(row, (0, r - row.shape[0])) if row.shape[0] < r else row

        placed_req = (
            np.stack([padded(q) for q in eng.placed_req])
            if eng.placed_req
            else np.zeros((0, r), np.float32)
        )
        placed_nodes = np.asarray(eng.placed_node, np.int64)
        used = np.zeros_like(alloc)
        np.add.at(used, placed_nodes, placed_req)
        ext_log = eng.ext_log
        m = len(placed_nodes)
        gpu_mem_log = (
            np.asarray(ext_log["gpu_mem"], np.float32) if m else np.zeros(0, np.float32)
        )
        gpu_use_log = (
            np.asarray(ext_log["gpu_shares"], np.float32).sum(axis=1) * gpu_mem_log
            if m
            else np.zeros(0, np.float32)
        )
        vg_use_log = (
            np.asarray(ext_log["vg_alloc"], np.float32).sum(axis=1)
            if m
            else np.zeros(0, np.float32)
        )
        sd_any_log = (
            np.asarray(ext_log["sdev_take"], bool).any(axis=1)
            if m
            else np.zeros(0, bool)
        )
        n_nodes = len(self._nodes)
        gpu_used_n = np.zeros(n_nodes, np.float32)
        np.add.at(gpu_used_n, placed_nodes, gpu_use_log)
        vg_used_n = np.zeros(n_nodes, np.float32)
        np.add.at(vg_used_n, placed_nodes, vg_use_log)
        return {
            "prios": np.asarray(self._placed_prio, np.float64),
            "placed_nodes": placed_nodes,
            "placed_req": placed_req,
            "placed_groups": np.asarray(eng.placed_group, np.int32),
            "used": used,
            "gpu_mem_log": gpu_mem_log,
            "gpu_use_log": gpu_use_log,
            "vg_use_log": vg_use_log,
            "sd_any_log": sd_any_log,
            "gpu_used_n": gpu_used_n,
            "vg_used_n": vg_used_n,
            "evicted": np.zeros(m, bool),
        }

    def _preempt_failed_batch(self, failed) -> None:
        """Preempt for a whole batch of failed pods with BATCHED device work.

        Mirrors the DefaultPreemption flow per pod — find candidate nodes
        where removing victims plausibly fits the pod, pick the node
        minimizing (PDB violations, highest victim priority, summed
        priorities, victim count; `defaultpreemption/default_preemption.go`
        pickOneNodeForPreemption) — but executes in WAVES so a thousand
        preemptions cost a handful of device dispatches instead of three
        each (VERDICT r3 task 2, the same batching the leftover probes got
        in r3):

        1. every pending pod's victim set is proposed HOST-side against a
           shared whole-log model updated incrementally per proposal (so
           later proposals see earlier evictions);
        2. all proposed evictions apply as ONE incremental log delta;
        3. all preemptors re-run the real filter pipeline as ONE batched
           placement (sequentially exact within the batch, like the serial
           engine's retry order);
        4. on the first verify failure f: pods before f commit — EXCEPT
           pods whose verdict may have ridden f's evictions (their node
           hosts one of f's victims, or a domain-scoped negative
           constraint — theirs or a victim's — could flip when the victims
           return; committing them would break a no-overcommit /
           hard-constraint invariant), which are demoted and re-verified
           next wave with their evictions kept; pod f's
           evictions are restored and the pod re-proposes FRESH next wave
           (a fresh proposal runs against the wave-start model, i.e. the
           true log state, so its verify verdict is serial-authoritative —
           a second failure is final and the pod records its original
           reason); later pods' placements are reverted (they saw a state
           missing f's restored victims) and re-verify next wave with
           their evictions kept.

        The cheap host model only *proposes* sets — the batched retry
        verifies, so optimism (e.g. two preemptors counting the same free
        CPU) self-corrects exactly like the serial evict/retry/undo did.
        Victims are reported in `SimulateResult.preempted_pods`, not
        re-queued."""
        import numpy as np

        if not failed:
            return
        # (pod, reason, saved victim records or None, fresh-retry used)
        pending = [(pod, reason, None, False) for pod, reason in failed]
        # heads already granted the affinity-dependence finality deferral
        # (ADVICE r5 #3): one deferral per pod — enough for a placeable
        # anchor to land before the head's next fresh attempt, while two
        # mutually-dependent unplaceable pods finalize with their true
        # reasons instead of ping-ponging into the wave cap
        affinity_deferred: set = set()
        # termination insurance: the retried-finality rule below only
        # finalizes FRESH-attempt failures, so an adversarial geometry
        # could in principle ping-pong demotions between already-retried
        # pods; the serial flow's work is O(failed), so is this cap
        # (WAVE_CAP_SLACK is an attribute so tests can force the abort path)
        waves_left = self.WAVE_CAP_SLACK + 2 * len(failed)
        while pending:
            waves_left -= 1
            if waves_left < 0:
                # termination-insurance abort: these pods were still PENDING
                # (the serial evict/retry order might yet have placed them),
                # so their original failure reason is stale — tag it so a
                # tripped cap is observable, and say how many pods it cut off
                log.warning(
                    "preemption wave cap exhausted with %d pod(s) still "
                    "pending; recording them unscheduled with their original "
                    "failure reasons",
                    len(pending),
                )
                n_aborted = len(pending)
                for pod, reason, preev, _ in pending:
                    if preev:
                        self._restore_victims(preev)
                    self._record_failed(
                        pod,
                        reason,
                        note=(
                            f"{PREEMPT_WAVE_CAP_NOTE}, "
                            f"{n_aborted} pod(s) unresolved"
                        ),
                    )
                return
            model = self._build_preempt_model()
            wave = []  # (pod, reason, new victims, prior records, retried)
            for pod, reason, preev, retried in pending:
                if preev is not None:
                    # evicted in an earlier wave; only re-verification left
                    wave.append((pod, reason, [], preev, retried))
                    continue
                victims = self._propose_victims(pod, reason, model)
                if victims is None:
                    self._record_failed(pod, reason)
                else:
                    wave.append((pod, reason, victims, None, retried))
            if not wave:
                return
            owner = {}
            for w, (_, _, victims, _, _) in enumerate(wave):
                for i in victims:
                    owner[i] = w
            saved_per_pod = [
                list(preev) if preev is not None else []
                for (_, _, _, preev, _) in wave
            ]
            all_v = sorted(owner)
            if all_v:
                saved = self._engine.remove_placements(all_v)
                for i, entry in zip(saved["indices"], saved["entries"]):
                    saved_per_pod[owner[i]].append(
                        (
                            entry,
                            self._scheduled[i],
                            self._placed_prio[i],
                            self._placed_forced[i],
                        )
                    )
                for i in reversed(saved["indices"]):
                    del self._scheduled[i]
                    del self._placed_prio[i]
                    del self._placed_forced[i]
            probe = self._tensorizer.add_pods([p for p, _, _, _, _ in wave])
            log_base = len(self._engine.placed_node)
            nodes, _, extras = self._engine.place(probe)
            nodes = np.asarray(nodes)
            placed_mask = nodes >= 0
            fail_pos = np.flatnonzero(~placed_mask)
            f = int(fail_pos[0]) if len(fail_pos) else len(wave)
            ranks = np.cumsum(placed_mask) - 1  # log rank of each placed pod
            # A pod before f may have verify-landed on a placement that only
            # passed because of f's (about to be restored) evictions — the
            # batched placement saw ALL wave evictions, not just the pod's
            # own.  Committing it while restoring f's victims would silently
            # violate an invariant the serial evict/retry/undo flow never
            # can: node resource overcommit (pod sits on a victim's node),
            # a required anti-affinity or DoNotSchedule-spread verdict that
            # flips when the victims return (domain-scoped — demote when the
            # pod's node shares a relevant topology domain with a victim's
            # node), or a restored victim's own required anti-affinity now
            # matching the new pod (same domain test, victim's keys).
            # Demote those pods instead: skip their commit, drop their log
            # entries, and re-verify them next wave with their own evictions
            # kept (advisor finding, round 4).
            # An eviction is PERMANENT only once its proposer commits.  The
            # victims of f (restored this wave), of after-f pods, and of
            # demoted pods (carried as preev, restorable in a LATER wave or
            # the cap-abort path) are all provisional — so the demote scan
            # runs to a fixpoint: demoting a pod makes its own victims
            # provisional too.
            demote: set = set()
            if f < len(wave):

                def _labels(idx: int) -> dict:
                    meta = self._nodes[idx].get("metadata") or {}
                    return meta.get("labels") or {}

                prov_nodes: set = set()
                prov_victims: list = []

                def _absorb(records):
                    for entry, vpod, _prio, _forced in records:
                        prov_nodes.add(entry[1])
                        prov_victims.append(
                            (_labels(entry[1]), _anti_topo_keys(vpod))
                        )

                for w in range(f, len(wave)):
                    _absorb(saved_per_pod[w])
                # hoisted per-pod spec parses / label lookups: the fixpoint
                # below rescans range(f) once per demotion
                w_node = [int(nodes[w]) for w in range(f)]
                w_keys = [_restore_topo_keys(wave[w][0]) for w in range(f)]
                w_labels = [_labels(n) for n in w_node]
                changed = True
                while changed:
                    changed = False
                    for w in range(f):
                        if w in demote:
                            continue
                        rides = w_node[w] in prov_nodes
                        if not rides:
                            wl = w_labels[w]
                            rides = any(
                                k in wl and k in vl and wl[k] == vl[k]
                                for vl, vkeys in prov_victims
                                for k in (*w_keys[w], *vkeys)
                            )
                        if rides:
                            demote.add(w)
                            _absorb(saved_per_pod[w])
                            changed = True
            for w in range(f):
                if w in demote:
                    continue
                pod = wave[w][0]
                who = f"{namespace_of(pod)}/{name_of(pod)}"
                for _, vpod, _prio, _forced in saved_per_pod[w]:
                    self._preempted.append(
                        PreemptedPod(
                            pod=vpod,
                            preempted_by=who,
                            node=vpod["spec"].get("nodeName", ""),
                        )
                    )
                self._record_placed(pod, int(nodes[w]), extras["gpu_shares"][w])
            if f == len(wave):
                return
            # demoted pods and pods after f placed against a state that is
            # about to change (f's victims return) — revert their log
            # entries; they re-verify next wave
            revert = [
                log_base + int(ranks[w])
                for w in list(demote) + list(range(f + 1, len(wave)))
                if placed_mask[w]
            ]
            if revert:
                self._engine.remove_placements(revert)  # permanent, no undo
            self._restore_victims(saved_per_pod[f])
            pod_f, reason_f, _, preev_f, retried_f = wave[f]
            # retry-finality exemption (ADVICE r5 #3): a fresh-retried head
            # whose required positive affinity selects another wave pod is
            # NOT finalized — the head verifies first in its wave, so its
            # verdict never saw that pod placed, and the serial evict/retry
            # order could still place both.  The exempted head re-queues
            # BEHIND the pods it depends on (deliberately trading the
            # victim-node re-grab protection below for the chance that the
            # anchor pod lands first); termination stays bounded by the
            # wave cap.
            affinity_dependent = id(pod_f) not in affinity_deferred and (
                _head_affinity_depends_on(
                    pod_f, [wave[w][0] for w in range(len(wave)) if w != f]
                )
            )
            if affinity_dependent and retried_f and preev_f is None:
                # this exemption actually skipped finality — consume the
                # pod's one deferral (ordering-only moves don't)
                affinity_deferred.add(id(pod_f))
            if retried_f and preev_f is None and not affinity_dependent:
                # the failed attempt was a FRESH proposal against the true
                # wave-start log state — the verify verdict is
                # serial-authoritative.  (A retried pod failing a
                # preev-carried MID-WAVE re-verify — it was demoted after
                # its fresh attempt placed — is NOT final: its victims were
                # just restored, so it re-proposes fresh next wave.)
                self._record_failed(pod_f, reason_f)
                head = []
            else:
                head = [(pod_f, reason_f, None, True)]
            # the retried head verifies FIRST: a demoted pod verifying ahead
            # of it could re-grab the head's victim node (wave evictions
            # apply before every verify), wrongly finalizing the head's
            # failure; demoted pods re-verify right after, before after-f
            # pods, keeping their relative serial order.  (Exception: an
            # affinity-dependent head queues LAST, see above.)
            rest = [
                (wave[w][0], wave[w][1], saved_per_pod[w], wave[w][4])
                for w in [*sorted(demote), *range(f + 1, len(wave))]
            ]
            pending = rest + head if affinity_dependent else head + rest

    def _restore_victims(self, records) -> None:
        """Re-insert evicted victims (a failed preemptor's) at the END of
        the placement log — append positions keep the engine log and the
        _scheduled/_placed_prio mirrors trivially parallel; log order only
        influences the most-recent-first victim tie-break, the same
        divergence class as the round-start score approximations."""
        if not records:
            return
        base = len(self._engine.placed_node)
        saved = {
            "indices": list(range(base, base + len(records))),
            "entries": [entry for entry, _, _, _ in records],
        }
        self._engine.restore_placements(saved)
        for _, vpod, vprio, vforced in records:
            self._scheduled.append(vpod)
            self._placed_prio.append(vprio)
            self._placed_forced.append(vforced)

    def _propose_victims(self, pod: dict, reason: int, model: dict):
        """Host-side victim proposal for one failed pod against the wave
        model; returns wave-start log indices of the victims (and debits
        them from the model so later proposals see the eviction), or None
        when no plausible set exists. Victim greed prefers PDB-free pods
        (lowest priority first, most recent first on ties) the way the
        reference reprieves PDB-violating victims preferentially
        (selectVictimsOnNode, default_preemption.go:639-668), and the
        violation count follows filterPodsWithPDBViolation's budget
        accounting: each matching victim decrements the PDB's
        disruptionsAllowed, violating once it goes negative. The simulation
        runs no disruption controller, so the budget is
        `status.disruptionsAllowed` as ingested (absent = 0, like the
        reference's fake cluster)."""
        import numpy as np

        from .core.objects import labels_of

        if reason not in _PREEMPTIBLE_REASONS or not len(model["prios"]):
            return None
        prio = pod_priority(pod)
        prios = np.where(model["evicted"], np.inf, model["prios"])
        placed_nodes = model["placed_nodes"]
        if not np.any(prios < prio):
            return None
        tz = self._tensorizer
        g, pin_name = _group_of_pod(pod)
        gid = tz._group_ids.get(g.signature())
        if gid is None:
            return None
        static = tz._static_mask[gid]
        alloc = tz.alloc
        r = alloc.shape[1]

        def padded(row):
            return np.pad(row, (0, r - row.shape[0])) if row.shape[0] < r else row

        placed_req = model["placed_req"]
        used = model["used"]
        pod_req = padded(self._pod_req_vector(pod))

        # per-reason victim relevance + plausibility (the retry verifies)
        pod_ports = set(tz._port_rows[gid].keys())
        anti_terms = {t for t, v in tz._a_anti[gid].items() if v}
        spread_terms = {t for t, v in tz._spread_hard[gid].items() if v > 0}
        pod_conflict_keys = set(tz._vol_rw_rows[gid]) | set(tz._vol_ro_rows[gid])
        pod_att_classes = {
            tz._vol_class[w] for w in tz._vol_att_rows[gid] if w in tz._vol_class
        }
        probe = tz.add_pods([pod])
        gpu_need = float(probe.ext["gpu_mem"][0]) * max(
            float(probe.ext["gpu_count"][0]), 1.0
        )
        lvm_need = float(np.sum(probe.ext["lvm_size"][0]))

        # PDB bookkeeping (filterPodsWithPDBViolation semantics): a PDB with
        # a nil or EMPTY selector matches nothing here — unlike the general
        # LabelSelector rule — and unlabeled pods match no PDB (upstream
        # short-circuits on `len(pod.Labels) != 0`,
        # default_preemption.go:745-746, even though a DoesNotExist selector
        # would otherwise match them; parity kept deliberately)
        pdb_list = [
            (
                namespace_of(p),
                (p.get("spec") or {}).get("selector"),
                int(((p.get("status") or {}).get("disruptionsAllowed")) or 0),
            )
            for p in self._pdbs
        ]
        _pdb_cache: dict = {}

        def pdbs_matching(i: int) -> tuple:
            got = _pdb_cache.get(i)
            if got is None:
                from .core.match import match_label_selector

                victim = self._scheduled[i]
                labels = labels_of(victim)
                got = tuple(
                    j
                    for j, (ns, sel, _) in enumerate(pdb_list)
                    if labels
                    and ns == namespace_of(victim)
                    and sel
                    and (sel.get("matchLabels") or sel.get("matchExpressions"))
                    and match_label_selector(sel, labels)
                )
                _pdb_cache[i] = got
            return got

        def pdb_violations(victim_idx) -> int:
            """How many victims push a matching PDB's budget negative."""
            allowed = [a for (_, _, a) in pdb_list]
            count = 0
            for i in victim_idx:
                violated = False
                for j in pdbs_matching(i):
                    allowed[j] -= 1
                    if allowed[j] < 0:
                        violated = True
                count += violated
            return count

        # ---- vectorized victim search -----------------------------------
        # The per-node Python loop this replaces cost O(nodes × placed) per
        # failed pod — unusable against 10^5-node clusters with 10^6-entry
        # placement logs (VERDICT r2 task 5). Everything below is whole-log
        # numpy: candidate relevance by reason, the PDB reprieve split, the
        # greedy per-node eviction prefix, and the pickOneNode key all
        # evaluate per placement-log ENTRY over sorted node segments.
        n_nodes = len(self._nodes)
        placed_groups_a = model["placed_groups"]
        g_count = len(tz.groups)

        # victim relevance per reason, at group granularity where possible
        if reason == FAIL_PORTS:
            rel_g = np.array(
                [bool(pod_ports & set(tz._port_rows[vg].keys())) for vg in range(g_count)]
            )
            relevant = rel_g[placed_groups_a]
        elif reason == FAIL_INTERPOD:
            rel_g = np.array(
                [any(tz._s_match[vg].get(t) for t in anti_terms) for vg in range(g_count)]
            )
            relevant = rel_g[placed_groups_a]
        elif reason == FAIL_SPREAD:
            rel_g = np.array(
                [any(tz._s_match[vg].get(t) for t in spread_terms) for vg in range(g_count)]
            )
            relevant = rel_g[placed_groups_a]
        elif reason == FAIL_VOLUME:
            # the victim must hold one of the conflicting volume identities
            # via a rw/ro mount — attach-only usage (resolved PVC
            # attachables) cannot cause a VolumeRestrictions conflict
            rel_g = np.array(
                [
                    bool(
                        pod_conflict_keys
                        & (set(tz._vol_rw_rows[vg]) | set(tz._vol_ro_rows[vg]))
                    )
                    for vg in range(g_count)
                ]
            )
            relevant = rel_g[placed_groups_a]
        elif reason == FAIL_ATTACH:
            # evicting any holder of a same-class attachable frees a slot
            rel_g = np.array(
                [
                    bool(
                        pod_att_classes
                        & {
                            tz._vol_class[w]
                            for w in set(tz._vol_att_rows[vg]) | set(tz._vol_rw_rows[vg])
                            if w in tz._vol_class
                        }
                    )
                    for vg in range(g_count)
                ]
            )
            relevant = rel_g[placed_groups_a]
        elif reason == FAIL_GPU:
            relevant = model["gpu_mem_log"] > 0
        elif reason == FAIL_STORAGE:
            relevant = (model["vg_use_log"] > 0) | model["sd_any_log"]
        else:  # FAIL_RESOURCES: any eviction frees resources
            relevant = np.ones(len(placed_groups_a), bool)

        node_ok = np.asarray(static, bool).copy()
        if getattr(self._engine, "node_valid", None) is not None:
            # fault-masked nodes (simtpu/faults/drain.py) are not landing
            # sites: the engine's filter pipeline is guaranteed to reject
            # them at verify, so proposing one only burns a wave
            node_ok &= np.asarray(self._engine.node_valid, bool)
        if pin_name is not None:
            # the pin restricts WITHIN the static mask (the serial loop
            # checked static first): a pinned node the pod can never place
            # on must not trigger a doomed evict/retry/restore round-trip
            pin_idx = tz.node_idx.get(pin_name, -1)
            keep = node_ok[pin_idx] if pin_idx >= 0 else False
            node_ok[:] = False
            if keep:
                node_ok[pin_idx] = True
        cand_mask = (prios < prio) & relevant & node_ok[placed_nodes]
        cand = np.flatnonzero(cand_mask)
        if not len(cand):
            return None
        c_nodes = placed_nodes[cand]
        c_prios = prios[cand]

        # PDB reprieve split (filterPodsWithPDBViolation): walk each node's
        # candidates in MoreImportantPod order (priority desc, index asc)
        # decrementing budgets; a victim is VIOLATING once a matching PDB's
        # budget goes negative. Vectorized as per-(pdb, node) running counts
        # along the sorted order.
        j_pdbs = len(pdb_list)
        violating1 = np.zeros(len(cand), bool)
        pdb_match_c = None
        if j_pdbs:
            pdb_match_c = np.zeros((j_pdbs, len(cand)), bool)
            for ci, i in enumerate(cand):
                for j in pdbs_matching(int(i)):
                    pdb_match_c[j, ci] = True
            order1 = np.lexsort((cand, -c_prios, c_nodes))
            n_sorted1 = c_nodes[order1]
            seg_start1 = np.concatenate(
                [[True], n_sorted1[1:] != n_sorted1[:-1]]
            )
            seg_id1 = np.cumsum(seg_start1) - 1
            first_pos = np.flatnonzero(seg_start1)
            for j in range(j_pdbs):
                mj = pdb_match_c[j][order1].astype(np.int64)
                cum = np.cumsum(mj)
                base = (cum - mj)[first_pos]  # exclusive cum at segment start
                rank = cum - base[seg_id1]  # inclusive count within segment
                violating1[order1] |= (mj > 0) & (rank > pdb_list[j][2])

        # greedy eviction order per node: non-violating first, lowest
        # priority first, later placements first on ties
        order2 = np.lexsort((-cand, c_prios, violating1, c_nodes))
        n2 = c_nodes[order2]
        seg_start2 = np.concatenate([[True], n2[1:] != n2[:-1]])
        seg_id2 = np.cumsum(seg_start2) - 1
        n_segs = int(seg_id2[-1]) + 1
        seg_first = np.flatnonzero(seg_start2)
        seg_node = n2[seg_first]

        def seg_cumsum(vals):
            """Within-segment inclusive cumulative sum along order2."""
            cum = np.cumsum(vals, axis=0)
            base = (cum - vals)[seg_first]
            return cum - base[seg_id2]

        req2 = placed_req[cand][order2]  # [C, R]
        cum_req = seg_cumsum(req2)
        free0 = (alloc - used)[seg_node[seg_id2]]  # [C, R] start free per row
        res_ok = np.all(
            free0 + cum_req >= pod_req[None, :] - 1e-6, axis=1
        )
        if reason == FAIL_GPU:
            gpu_free0 = tz.ext.gpu_dev_total.sum(axis=1) - model["gpu_used_n"]
            cum_gpu = seg_cumsum(model["gpu_use_log"][cand][order2])
            res_ok &= (
                gpu_free0[seg_node[seg_id2]] + cum_gpu >= gpu_need - 1e-6
            )
        elif reason == FAIL_STORAGE:
            vg_free0 = (
                tz.ext.vg_cap.sum(axis=1) - tz.ext.vg_req0.sum(axis=1)
            ) - model["vg_used_n"]
            cum_vg = seg_cumsum(model["vg_use_log"][cand][order2])
            res_ok &= vg_free0[seg_node[seg_id2]] + cum_vg >= lvm_need - 1e-6
        elif reason in (FAIL_PORTS, FAIL_INTERPOD, FAIL_SPREAD, FAIL_VOLUME, FAIL_ATTACH):
            # every relevant victim on the node must go (a single eviction
            # may leave another conflicting holder or a saturated class)
            is_last = np.concatenate([seg_start2[1:], [True]])
            res_ok &= is_last

        # minimal qualifying prefix per segment
        pos_in_seg = np.arange(len(order2)) - seg_first[seg_id2]
        first_ok = np.full(n_segs, np.iinfo(np.int64).max)
        ok_pos = np.flatnonzero(res_ok)
        np.minimum.at(first_ok, seg_id2[ok_pos], pos_in_seg[ok_pos])
        valid_seg = first_ok < np.iinfo(np.int64).max
        if not valid_seg.any():
            return None

        # pickOneNode key on each segment's prefix: (PDB violations counted
        # in eviction order, highest victim priority, summed priorities,
        # victim count, node index)
        prio2 = c_prios[order2].astype(np.float64)
        cum_prio = seg_cumsum(prio2)
        # segmented running max via monotone per-segment offsets: shift
        # priorities to [0, range] and add seg_id*(range+1) — offsets stay
        # far below 2^53, so the subtraction is exact
        p_min = float(prio2.min())
        span = float(prio2.max()) - p_min + 1.0
        off = seg_id2.astype(np.float64) * span
        cum_max = np.maximum.accumulate(prio2 - p_min + off) - off + p_min
        if j_pdbs:
            viol2 = np.zeros(len(order2), bool)
            for j in range(j_pdbs):
                mj = pdb_match_c[j][order2].astype(np.int64)
                rank = seg_cumsum(mj)
                viol2 |= (mj > 0) & (rank > pdb_list[j][2])
            cum_viol = seg_cumsum(viol2.astype(np.int64))
        else:
            cum_viol = np.zeros(len(order2), np.int64)
        sel = seg_first + np.where(valid_seg, first_ok, 0)
        keys = np.lexsort(
            (
                seg_node,
                first_ok + 1,
                cum_prio[sel],
                cum_max[sel],
                cum_viol[sel],
                ~valid_seg,  # invalid segments last
            )
        )
        best_seg = int(keys[0])
        if not valid_seg[best_seg]:
            return None
        node = int(seg_node[best_seg])
        a = int(seg_first[best_seg])
        b = a + int(first_ok[best_seg]) + 1
        victims = [int(cand[i]) for i in order2[a:b]]

        # debit the model so later proposals in this wave see the eviction
        # AND the preemptor's own predicted landing on the freed node —
        # without the latter, every later proposal chases the phantom free
        # space of the first eviction (a 1-victim set on an already-freed
        # node wins the fewest-victims key) and the whole wave fails
        # verification. The prediction can be wrong (the batched verify
        # places wherever the real pipeline says); the verify corrects it.
        model["evicted"][victims] = True
        model["prios"][victims] = np.inf
        model["used"][node] -= placed_req[victims].sum(axis=0)
        model["used"][node] += pod_req
        model["gpu_used_n"][node] -= model["gpu_use_log"][victims].sum()
        model["gpu_used_n"][node] += gpu_need
        model["vg_used_n"][node] -= model["vg_use_log"][victims].sum()
        model["vg_used_n"][node] += lvm_need
        return victims

    def _pod_req_vector(self, pod: dict):
        """The pod's request row in the tensorizer's resource vocabulary."""
        import numpy as np

        from .core.objects import pod_requests
        from .core.tensorize import RES_PODS

        req = np.zeros(len(self._tensorizer.resources), np.float32)
        req[RES_PODS] = 1.0
        for rname, val in pod_requests(pod).items():
            ridx = self._tensorizer.resources.get(rname)
            if ridx >= 0:
                req[ridx] = val
        return req

    def _result(self) -> SimulateResult:
        by_node = {name_of(n): [] for n in self._nodes}
        for pod in self._scheduled:
            by_node[pod["spec"]["nodeName"]].append(shallow_pod_copy(pod))
        nodes = [deep_copy(n) for n in self._nodes]
        self._write_extended_annotations(nodes)
        statuses = [NodeStatus(node=n, pods=by_node[name_of(n)]) for n in nodes]
        return SimulateResult(
            unscheduled_pods=list(self._unscheduled),
            node_status=statuses,
            preempted_pods=list(self._preempted),
        )

    def _write_extended_annotations(self, nodes: List[dict]) -> None:
        write_extended_annotations(self._tensorizer.ext, self._engine.ext_log, nodes)

    # -- decision observability (simtpu/explain) ---------------------------

    def explain_result(self, opts: Optional[dict] = None) -> dict:
        """The versioned explain block for this simulation's unscheduled
        pods: the per-stage failure breakdown (against the end-of-run
        carried state) plus the binding-constraint bottleneck analysis.

        `opts` keys (all optional): `top` — failure-shape groups kept
        (default 10); `new_node`/`daemon_sets`/`corrected` — the capacity
        planners' template context, folded into the bottleneck's
        can-another-node-ever-help verdict.  Pure read: re-adding the
        already-interned unscheduled pods grows no vocabulary and the
        carried state is only peeked (`Engine.carried_state`)."""
        import numpy as np

        from .explain import build_explain_doc

        opts = opts or {}
        if not self._unscheduled or self._engine is None:
            # nothing to explain: return a FALSY doc so callers' `if
            # explain_block:` guards skip it — a successful plan must not
            # print/emit a vestigial version-only stub
            return {}
        with span("explain", pods=len(self._unscheduled)):
            pods = [u.pod for u in self._unscheduled]
            codes = np.asarray(self._unscheduled_codes, np.int32)
            batch = self._tensorizer.add_pods(pods)
            tensors = self._tensorizer.freeze()
            node_valid = (
                np.asarray(self._engine.node_valid, bool)
                if self._engine.node_valid is not None
                else None
            )
            try:
                state = self._engine.carried_state()
            except ValueError:
                # a preemption fallback left the carry dirty (rebuild-on-
                # next-place) — the placement log is still authoritative
                state = None
            if state is None:
                from .engine.state import build_state

                r = tensors.alloc.shape[1]
                state = build_state(
                    tensors,
                    np.asarray(self._engine.placed_group, np.int32),
                    np.asarray(self._engine.placed_node, np.int32),
                    self._engine.log_req_matrix(r),
                    self._engine.ext_log,
                )
            return build_explain_doc(
                tensors, batch, np.arange(len(pods)), state,
                np.full(len(pods), -1, np.int64), codes,
                node_valid=node_valid, sched_config=self._sched_config,
                new_node=opts.get("new_node"),
                daemon_sets=opts.get("daemon_sets") or (),
                corrected_ds_overhead=bool(opts.get("corrected", False)),
                top=int(opts.get("top", 10)),
            )


def record_placed_pod(pod: dict, node_name: str, gpu_shares) -> dict:
    """The placed copy of `pod`: nodeName bound, phase Running, and the
    GPU device-assignment annotation the reference's GpuSharePlugin.Bind
    applies (`open-gpu-share.go:221-241` + `utils/pod.go:117-127`)."""
    placed = shallow_pod_copy(pod)
    placed["spec"]["nodeName"] = node_name
    placed.setdefault("status", {})["phase"] = "Running"
    already = annotations_of(placed).get(C.ANNO_POD_GPU_INDEX)
    if gpu_shares.sum() > 0 and not already:
        ids = []
        for dev_id, cnt in enumerate(gpu_shares):
            ids.extend([str(dev_id)] * int(round(float(cnt))))
        set_annotation(placed, C.ANNO_POD_GPU_INDEX, "-".join(ids))
    return placed


def write_extended_annotations(ext, log: dict, nodes: List[dict]) -> None:
    """Mirror the storage/GPU state the reference's Bind/Reserve plugins
    write back into node annotations (`plugin/open-local.go:218-249`,
    `plugin/open-gpu-share.go:146-189`). `ext` is the tensorizer's
    ExtendedNodeArrays, `log` an engine ext_log (node-parallel lists)."""
    import json as _json

    import numpy as np

    from .core.extended import NodeStorage

    n = len(nodes)
    v = ext.vg_cap.shape[1]
    sd = ext.sdev_cap.shape[1]
    gd = ext.gpu_dev_total.shape[1]
    vg_used = np.zeros((n, v), np.float64)
    sdev_taken = np.zeros((n, sd), bool)
    gpu_used = np.zeros((n, gd), np.float64)
    gpu_pods = np.zeros(n, np.int64)
    for node_idx, vg_alloc, take, shares, mem in zip(
        log["node"], log["vg_alloc"], log["sdev_take"], log["gpu_shares"], log["gpu_mem"]
    ):
        vg_used[node_idx] += vg_alloc
        sdev_taken[node_idx] |= take
        gpu_used[node_idx] += np.asarray(shares) * mem
        if mem > 0:
            gpu_pods[node_idx] += 1
    for i, node in enumerate(nodes):
        storage = NodeStorage.from_node(node)
        if storage is not None:
            for j, vg in enumerate(storage.vgs):
                if j < v:
                    prev = parse_quantity(vg.get("requested") or 0)
                    vg["requested"] = int(prev + vg_used[i, j])
                    if isinstance(vg.get("capacity"), str):
                        vg["capacity"] = int(parse_quantity(vg["capacity"]))
            for j, dev in enumerate(storage.devices):
                if j < sd and sdev_taken[i, j]:
                    dev["isAllocated"] = True
            set_annotation(
                node,
                C.ANNO_NODE_LOCAL_STORAGE,
                _json.dumps({"vgs": storage.vgs, "devices": storage.devices}),
            )
        if ext.gpu_total[i] > 0:
            devs = {
                str(j): {
                    "gpuTotalMemory": int(ext.gpu_dev_total[i, j]),
                    "gpuUsedMemory": int(gpu_used[i, j]),
                }
                for j in range(gd)
                if ext.gpu_dev_total[i, j] > 0
            }
            info = {
                "gpuCount": int((ext.gpu_dev_total[i] > 0).sum()),
                "gpuAllocatable": int(
                    ((ext.gpu_dev_total[i] > 0) & (gpu_used[i] == 0)).sum()
                ),
                "gpuTotalMemory": int(ext.gpu_total[i]),
                "gpuUsedMemory": int(gpu_used[i].sum()),
                "numPods": int(gpu_pods[i]),
                "devs": devs,
            }
            set_annotation(node, C.ANNO_NODE_GPU_SHARE, _json.dumps(info))


def simulate(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    extended_resources: Sequence[str] = (),
    engine_factory=None,
    use_greed: bool = False,
    bulk: bool = False,
    sched_config=None,
    precompile: bool = False,
    audit: bool = False,
    explain=False,
    trace: Optional[str] = None,
    profile: Optional[str] = None,
    _audit_inject: bool = False,
) -> SimulateResult:
    """One-shot simulation (`pkg/simulator/core.go:64-103`): expand cluster
    workloads, run the cluster, then schedule each app in configured order.
    Unscheduled pods accumulate across the cluster and every app; node status
    reflects the final cluster. Pass
    `engine_factory=lambda t: ShardedEngine(t, mesh)` to run the scan with the
    node axis sharded over a device mesh (simtpu/parallel), or `bulk=True`
    to place same-spec pod runs in bulk rounds (engine/rounds.py —
    feasibility-exact, tie-breaking may differ from the serial scan). The two
    are mutually exclusive. `precompile=True` AOT-compiles each batch's jit
    executables on a background thread pool before dispatching
    (engine/precompile.py; placements are bit-identical either way).

    Result pods are copied at the levels the simulation wrote (top level,
    metadata incl. labels/annotations, spec, status); deeper sub-structures
    (containers, volumes, affinity, ...) are shared READ-ONLY with the input
    objects — treat returned pods as immutable below those layers, or
    deep-copy before mutating (at million-pod scale a full deep copy per
    placed pod costs more than the placement itself).

    With `audit=True` the independent placement auditor (simtpu/audit)
    certifies the final state — engine placement log, preemption
    legality — and attaches its `AuditReport` as `result.audit` before
    the simulator closes.  `_audit_inject` is the SIMTPU_AUDIT_INJECT
    test lever: it corrupts the audit's VIEW (never the result) so the
    planners' divergence-fallback path can be driven end-to-end.

    With `explain=True` (or an options dict — `{"top", "new_node",
    "daemon_sets", "corrected"}`) the decision-observability block
    (simtpu/explain: per-stage failure breakdowns against the end-of-run
    state + the binding-constraint bottleneck analysis) is attached as
    `result.explain` before the simulator closes.  Off (the default) is
    zero-cost: no explain module import, no extra device dispatch.

    Observability (ISSUE 8, docs/observability.md): `trace="t.json"`
    arms the span tracer for this call and exports the Perfetto-loadable
    Chrome trace to that path before returning (a tracer armed by the
    caller — SIMTPU_TRACE, an enclosing Applier --trace — keeps its
    buffer and export schedule; this kwarg only adds its own export);
    `profile=DIR` wraps the whole simulation in a jax.profiler capture
    with span-named TraceAnnotations."""
    if bulk:
        if engine_factory is not None:
            raise ValueError("bulk=True and engine_factory are mutually exclusive")
        from .engine.rounds import RoundsEngine

        engine_factory = RoundsEngine
    from .obs import trace as obs_trace
    from .obs.profile import profile_capture

    own_trace = bool(trace) and not obs_trace.enabled()
    if own_trace:
        obs_trace.enable()
    sim = Simulator(
        extra_resources=extended_resources,
        engine_factory=engine_factory,
        use_greed=use_greed,
        sched_config=sched_config,
        precompile=precompile,
    )
    cluster = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    try:
        with profile_capture(profile or ""):
            with span("expand") as sp:
                cluster_pods = get_valid_pods_exclude_daemonset(cluster)
                for ds in cluster.daemon_sets:
                    cluster_pods.extend(
                        make_valid_pods_by_daemonset(ds, cluster.nodes)
                    )
                cluster.pods = cluster_pods
                sp.set(pods=len(cluster_pods))
            result = sim.run_cluster(cluster)
            for app in apps:
                result = sim.schedule_app(app)
            if audit:
                from .audit.checker import audit_simulation

                result.audit = audit_simulation(sim, inject=_audit_inject)
            if explain:
                # decision observability (simtpu/explain): the failure
                # breakdown + bottleneck block, computed before the
                # simulator closes.  `explain` may be True or an options
                # dict ({"top", "new_node", "daemon_sets", "corrected"})
                result.explain = sim.explain_result(
                    explain if isinstance(explain, dict) else None
                )
        return result
    finally:
        # export in the finally: an aborted simulation must still leave
        # its timeline behind (the same contract as the CLI's --trace),
        # and the export must land BEFORE disable() drops the buffer
        if trace:
            obs_trace.export_trace(trace)
        if own_trace:
            obs_trace.disable()
        sim.close()
