"""Cold-start pipeline: parallel AOT precompilation of the engine's jit
executables.

The reference pays zero compile cost (`simon apply` is an AOT-compiled Go
binary, `pkg/apply/apply.go:88`); simtpu's cold path is XLA-compile-dominated
— each scan/round body costs seconds to compile and, without this module,
those compiles serialize one-by-one at the moment each shape is first
dispatched.  PR 1 made every executable shape deterministic (the pow2 chunk
plans, `RoundsEngine.snap_shapes` bucketing), which is exactly the
precondition for compiling them *ahead of and in parallel with* the host
work:

1. ENUMERATE: as soon as tensorization fixes the shape buckets, walk the
   same deterministic chunk plans the dispatch path will walk
   (`scan.plan_scan_chunks`, `RoundsEngine._segments`/`_chunk_runs`/
   `_chunk_shape`) and derive the abstract (shape, dtype) signature of every
   distinct jit callable the run will need — scan bodies, bulk round bodies,
   quota/matrix variants, sharded variants.
2. COMPILE IN PARALLEL: drive `jit(...).lower(...).compile()` for each on a
   background thread pool.  XLA releases the GIL during compilation, so the
   compiles overlap each other (multi-core hosts / backend compile servers)
   and the host-side work that precedes the first dispatch.
3. REGISTER: finished executables land in the pipeline's registry keyed by
   the exact dispatch signature; `Engine._scan_call` /
   `RoundsEngine._bulk_call(_sliced)` consult the registry first, so first
   dispatch finds the executable warm.  (In jax 0.4.x an AOT
   `lower().compile()` does NOT warm the jit function's own dispatch cache —
   tracing is shared, compilation is not — so the registry holds the
   `jax.stages.Compiled` objects and calls them directly.)

Race pinning (tested in tests/test_precompile.py):

- A dispatch whose signature has an IN-FLIGHT background compile blocks on
  that future and then calls the one finished executable — background
  compile and eager first dispatch can never produce two executables for
  one signature, and the registry holds at most one entry per key by
  construction (lock-guarded submit).
- A dispatch whose signature was never enumerated (data-dependent leftover
  probe shapes, snap fallbacks) misses the registry and takes the plain jit
  path — exactly yesterday's behavior.
- A failed background compile (AOT lowering unsupported on a backend, OOM,
  ...) is LOUD: one warning per executable names the failure, and the
  dispatch falls back to the jit path, which compiles as if the pipeline
  never existed.  Placements are bit-identical with the pipeline on or off
  in every case — the pipeline changes when and where compilation happens,
  never what executes.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..obs.trace import span

log = logging.getLogger("simtpu.precompile")


def tree_sig(tree) -> tuple:
    """Hashable (treedef, ((shape, dtype), ...)) signature of an argument
    pytree.  Dtypes are canonicalized the way jit canonicalizes its inputs
    (64-bit narrowing under the default x64-off config), so a host numpy
    array and the ShapeDtypeStruct that enumerated it agree."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return treedef, tuple(
        (tuple(np.shape(leaf)), jax.dtypes.canonicalize_dtype(leaf.dtype).name)
        for leaf in leaves
    )


def _as_sds(tree):
    """Map a pytree of concrete arrays (or SDS) to ShapeDtypeStructs with
    jit-canonicalized dtypes."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            tuple(np.shape(x)), jax.dtypes.canonicalize_dtype(x.dtype)
        ),
        tree,
    )


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(
        tuple(shape), jax.dtypes.canonicalize_dtype(dtype)
    )


# public names for out-of-package enumerators (the fault sweep registers
# its scenario-batched executable under these, simtpu/faults/sweep.py)
as_sds = _as_sds
sds = _sds


def state_sds(tensors):
    """The SchedState signature a fresh engine carries for `tensors`,
    derived from build_state ITSELF via jax.eval_shape (tracing its
    empty-log path allocates nothing) — definitionally in sync with
    engine/state.py, so a future state-field change cannot silently
    desynchronize the enumerated signatures from the real dispatches."""
    import jax

    from .state import build_state

    r = tensors.alloc.shape[1]
    return jax.eval_shape(
        lambda: build_state(
            tensors,
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            np.zeros((0, r), np.float32),
            None,
        )
    )


class _Job:
    __slots__ = ("future", "seconds", "warned")

    def __init__(self):
        self.future = None
        self.seconds = 0.0
        self.warned = False


class AotPipeline:
    """Registry of background-AOT-compiled executables keyed by dispatch
    signature, plus the thread pool that fills it.

    One pipeline can be SHARED by several engines (the incremental planner
    hands one to its base, probe and verify engines the way it shares the
    bulk-shape registry): keys are pure (callable identity, static config,
    argument shapes) signatures, so engines over the same tensors
    deduplicate naturally."""

    def __init__(self, workers: Optional[int] = None):
        if workers is None:
            workers = min(8, max(2, os.cpu_count() or 2))
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="simtpu-aot"
        )
        self._lock = threading.Lock()
        self._jobs: dict = {}
        self._hits = 0
        self._misses = 0
        self._failures = 0
        self._done = 0
        self._compile_serial = 0.0
        self._t0 = None
        self._t_last = None

    # -- background side ---------------------------------------------------

    def submit(self, name, static_tail, fn, args_sds) -> bool:
        """Queue one AOT compile of `fn.lower(*args_sds, *static_tail)`.
        Returns False (and does nothing) when the signature is already
        queued or finished — at most one executable per key ever exists."""
        key = (name, static_tail, tree_sig(args_sds))
        with self._lock:
            if key in self._jobs:
                return False
            job = _Job()
            self._jobs[key] = job
            if self._t0 is None:
                self._t0 = time.perf_counter()
            job.future = self._pool.submit(
                self._compile, job, name, fn, args_sds, static_tail
            )
        return True

    def _compile(self, job, name, fn, args_sds, static_tail):
        t0 = time.perf_counter()
        # per-signature compile span ON the pool thread: the Perfetto view
        # shows the compile lanes overlapping the dispatch lane — the
        # pipelining win (and any straggler signature) made visible
        with span("aot.compile", sig=str(name)):
            compiled = fn.lower(*args_sds, *static_tail).compile()
        job.seconds = time.perf_counter() - t0
        with self._lock:
            self._done += 1
            self._compile_serial += job.seconds
            self._t_last = time.perf_counter()
        return compiled

    # -- dispatch side -----------------------------------------------------

    def call(self, name, static_tail, args, fallback):
        """Run one dispatch through the registry: a finished executable is
        called directly, an in-flight compile is awaited first (one
        executable per signature, never two), an unknown signature or a
        failed compile falls back to the plain jit path — the failure is
        warned ONCE per executable, never swallowed silently."""
        key = (name, static_tail, tree_sig(args))
        job = self._jobs.get(key)
        if job is None:
            with self._lock:
                self._misses += 1
            return fallback()
        try:
            compiled = job.future.result()
        except Exception as exc:  # noqa: BLE001 — loud fallback, by contract
            with self._lock:
                first = not job.warned
                job.warned = True
                self._failures += 1
            if first:
                log.warning(
                    "AOT precompile of %r failed (%s: %s); falling back to "
                    "plain jit dispatch for this executable",
                    name, type(exc).__name__, exc,
                )
            return fallback()
        with self._lock:
            self._hits += 1
        return compiled(*args)

    # -- lifecycle / observability ----------------------------------------

    def wait_all(self, timeout: Optional[float] = None) -> None:
        """Block until every queued compile settles (used by the bench's
        compile-wall accounting; dispatch never needs it)."""
        from concurrent.futures import wait

        with self._lock:
            futures = [j.future for j in self._jobs.values()]
        wait(futures, timeout=timeout)

    def stats(self) -> dict:
        """submitted/done/hits/misses/failures plus the two compile
        timings the bench reports: `compile_wall_s` (first submit → last
        completion — the pipelined cost) and `compile_serial_s` (sum of
        per-executable compile seconds — what serializing them would have
        cost; wall < serial is the overlap win)."""
        with self._lock:
            wall = 0.0
            if self._t0 is not None:
                wall = (self._t_last or time.perf_counter()) - self._t0
            return {
                "submitted": len(self._jobs),
                "done": self._done,
                "hits": self._hits,
                "misses": self._misses,
                "failures": self._failures,
                "compile_serial_s": self._compile_serial,
                "compile_wall_s": wall,
            }

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


# -- shape enumeration -------------------------------------------------------


def _pods_sds(pods, rows: int):
    """SDS tuple for a pod-tuple slice padded to `rows` (the layout of
    scan.build_pod_arrays, shared by scan segments and bulk
    representatives)."""
    return tuple(_sds((rows,) + arr.shape[1:], arr.dtype) for arr in pods)


def _plan_scan_jobs(
    pipe: AotPipeline, engine, tensors, st_sds, state_tree, pods,
    groups: np.ndarray, flags, pods_rows=None,
) -> None:
    """Enumerate + submit the scan AND wavefront executables
    `run_scan_chunked` will dispatch for `groups` — the same chunk plan
    (incl. its wavefront sub-plan), turned into signatures.  `pods_rows`
    is the host pod-tuple slice aligned with `groups` (defaults to `pods`
    whole) — the wavefront eligibility mask reads its pins/demands."""
    from .scan import (
        _pow2_up,
        _sliced_statics_fields,
        flatten_wave_segments,
        plan_scan_chunks,
        wave_eligibility,
        wave_static_spec,
    )

    if groups.shape[0] == 0:
        return
    n = state_tree.cnt_match.shape[1]
    t_cap = st_sds.g_terms.shape[1]
    name, fn, tail = engine._aot_scan(flags)
    wave_ok = None
    if getattr(engine, "speculate", False):
        wave_ok = wave_eligibility(
            pods if pods_rows is None else pods_rows, groups, tensors
        )
    for c0, c1, gs_p, rows_p, waves in plan_scan_chunks(
        groups, tensors, flags, wave_ok=wave_ok
    ):
        eff = st_sds
        if gs_p is not None:
            fields = _sliced_statics_fields(st_sds, rows_p)
            eff = eff._replace(**{
                f: _sds(
                    (len(gs_p),) + getattr(st_sds, f).shape[1:],
                    getattr(st_sds, f).dtype,
                )
                for f in fields
            })
            if rows_p is not None:
                eff = eff._replace(
                    g_terms=_sds((len(gs_p), t_cap), np.int32)
                )
        state_c = state_tree
        if rows_p is not None:
            r = len(rows_p)
            eff = eff._replace(
                term_topo=_sds((r,), np.int32),
                ip_of=_sds((r,), np.int32),
            )
            state_c = state_c._replace(
                cnt_match=_sds((r, n), np.float32),
                cnt_total=_sds((r,), np.float32),
            )
        for kind, a, b, w_mode in flatten_wave_segments(c0, c1, waves):
            seg = _pods_sds(pods, _pow2_up(b - a))
            if kind == "wave":
                w_name, w_fn, w_tail = engine._aot_wave(
                    flags,
                    wave_static_spec(tensors, w_mode[0], w_mode[1], w_mode[2]),
                )
                pipe.submit(w_name, w_tail, w_fn, (eff, state_c, seg))
            else:
                pipe.submit(name, tail, fn, (eff, state_c, seg))


def _plan_bulk_jobs(
    pipe: AotPipeline, engine, tensors, batch, st_sds, state_tree, pods,
    flags,
) -> None:
    """Enumerate + submit every executable a RoundsEngine `place(batch)`
    will dispatch: bulk round bodies per (variant, shape bucket) — walking
    `_chunk_shape` in dispatch order so the shape registry it seeds is
    exactly the one the dispatches later snap into — and the serial-scan
    bodies of the interleaved scan segments.  Leftover-probe shapes are
    data-dependent and stay on the plain jit path (registry misses)."""
    segments = engine._segments(batch, tensors)
    groups = np.asarray(batch.group)
    g_terms_shape = engine._host_term_maps(tensors)[0].shape
    idx = 0
    while idx < len(segments):
        kind, a, b = segments[idx]
        if kind == "scan":
            _plan_scan_jobs(
                pipe, engine, tensors, st_sds, state_tree, pods,
                groups[a:b], flags,
                pods_rows=tuple(np.asarray(p)[a:b] for p in pods),
            )
            idx += 1
            continue
        # the SAME stretch-group + chunk walk the dispatcher runs
        # (engine._stretch_group/_group_work_items) — shared code, so the
        # enumerated signatures cannot drift from the dispatched ones
        group_runs, idx = engine._stretch_group(segments, idx)
        for chunk, rows_p, quota, self_aff, ext_mats in (
            engine._group_work_items(group_runs, batch, tensors)
        ):
            s_pad, k_cap, rows_p = engine._chunk_shape(
                chunk, rows_p, tensors, flags, quota, self_aff, ext_mats
            )
            seg = _pods_sds(pods, s_pad)
            ks = _sds((s_pad,), np.int32)
            if rows_p is None:
                name, fn, tail = engine._aot_bulk(
                    tensors.n_domains, k_cap, flags, quota, self_aff,
                    ext_mats,
                )
                pipe.submit(name, tail, fn, (st_sds, state_tree, seg, ks))
            else:
                r = len(rows_p)
                name, fn, tail = engine._aot_bulk_sliced(
                    tensors.n_domains, k_cap, flags, quota, self_aff,
                    ext_mats,
                )
                args = (
                    st_sds, state_tree, _sds((r,), np.int32),
                    _sds(g_terms_shape, np.int32), _sds((r,), np.int32),
                    _sds((r,), np.int32), seg, ks,
                )
                pipe.submit(name, tail, fn, args)


def precompile_place(
    engine, batch, pipeline: Optional[AotPipeline] = None,
    workers: Optional[int] = None,
) -> AotPipeline:
    """Enumerate every jit executable `engine.place(batch)` will dispatch
    and queue their AOT compiles on the pipeline's thread pool; attaches
    the pipeline to the engine so the dispatches find the executables (or
    wait on their in-flight compiles).  Returns the pipeline — pass it
    back in for later batches/engines to share the registry.

    Cheap and side-effect-compatible by construction: the enumeration runs
    the same host-side planning the dispatch path runs (freeze, flags,
    segment/chunk plans, shape-bucket registration) and touches no device
    state beyond the memoized statics transfer `place()` would pay anyway.
    """
    from .rounds import RoundsEngine
    from .scan import build_pod_arrays, flags_from, statics_from

    pipe = pipeline if pipeline is not None else AotPipeline(workers)
    engine.pipeline = pipe
    tensors = engine.tensorizer.freeze()
    statics = statics_from(tensors, engine.sched_config)
    flags = flags_from(tensors, batch.ext)
    _, pods = build_pod_arrays(batch, tensors.alloc.shape[1])
    st_sds, state_tree = engine._precompile_shapes(
        _as_sds(statics), state_sds(tensors)
    )
    if isinstance(engine, RoundsEngine):
        _plan_bulk_jobs(
            pipe, engine, tensors, batch, st_sds, state_tree, pods, flags
        )
    else:
        _plan_scan_jobs(
            pipe, engine, tensors, st_sds, state_tree, pods,
            np.asarray(batch.group), flags,
        )
    return pipe
