"""Functional scheduling state — the scan carry.

Replaces the reference's mutable scheduler cache + assume-cache + node
annotations (`vendor/.../scheduler/internal/cache/cache.go:57`,
`pkg/simulator/plugin/open-local.go:174-253`) with a pytree of dense arrays
threaded through `lax.scan`. No locks, no event bus: every placement is a pure
state transition (SURVEY.md §2.3).
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensorize import COUNT_DTYPE, MASK_DTYPE
from ..native import scatter_add_rows

# plane height up to which the one-hot matmul forms pay: the matmul touches
# the WHOLE plane (fine for the rounds engine's ROW_BUDGET-bounded carried
# planes and the [K, N] domain map), while a tall plane (the serial scan's
# full [T, N] count state) is cheaper through the classic gather/scatter,
# which touches only the addressed rows
_MATMUL_ROWS = 512


def take_rows(plane: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """`plane[rows]` for a [K, N] plane and a small [Tc] int row vector.
    Negative row ids yield ZERO rows, subsuming the
    `where(valid, plane[clip(rows)], 0)` masking idiom at the call sites.

    For short planes this is a one-hot matmul: dynamic row gathers along
    the major axis lower to latency-bound kernels on TPU (measured ~4 ms
    for a 1.6 MB gather at 100k nodes — the single hottest op in a bulk
    round), while the [Tc, K] @ [K, N] product rides the MXU at memory
    bandwidth. Precision is pinned to HIGHEST: the TPU's default bf16
    matmul would round counts/domain ids above 256, while the f32-exact
    passes keep one-hot selection bit-identical to the gather. Tall planes
    keep the masked gather (the matmul would read the whole plane)."""
    if plane.shape[0] <= _MATMUL_ROWS:
        oh = jax.nn.one_hot(rows, plane.shape[0], dtype=jnp.float32)
        return jnp.matmul(
            oh, plane.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST
        )
    safe = jnp.clip(rows, 0)
    return jnp.where(
        (rows >= 0)[:, None], plane[safe].astype(jnp.float32), 0.0
    )


def take_rows_i32(plane: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Integer-plane row gather via take_rows; exact for values below 2^24
    (domain ids). Negative row ids yield 0 — callers that need a -1
    sentinel for invalid rows must mask separately."""
    if plane.shape[0] <= _MATMUL_ROWS:
        return take_rows(plane, rows).astype(jnp.int32)
    safe = jnp.clip(rows, 0)
    return jnp.where((rows >= 0)[:, None], plane[safe], 0)


def add_rows(plane: jnp.ndarray, rows: jnp.ndarray, delta: jnp.ndarray) -> jnp.ndarray:
    """`plane.at[rows].add(delta)`: duplicate and negative row ids behave
    like scatter-add with masked rows. Short planes use the full-plane
    matmul add (row scatters cost milliseconds each on TPU; the
    [T, Tc] @ [Tc, N] product plus a full-plane add runs at bandwidth —
    the rounds engine's carried planes are ROW_BUDGET-bounded, ~100 MB).
    Tall planes (the serial scan's full count state) keep the row scatter,
    which touches only the addressed rows."""
    if plane.shape[0] <= _MATMUL_ROWS:
        oh = jax.nn.one_hot(rows, plane.shape[0], dtype=delta.dtype)
        return plane + jnp.matmul(
            oh.T, delta, precision=jax.lax.Precision.HIGHEST
        )
    safe = jnp.clip(rows, 0)
    return plane.at[safe].add(jnp.where((rows >= 0)[:, None], delta, 0.0))


def interpod_term_index(tensors) -> np.ndarray:
    """[T] → row in the compacted interpod ("own") count planes, -1 when the
    term appears in no group's required/preferred (anti-)affinity. Ascending
    term order; shared by statics_from and build_state so plane rows agree.
    Memoized on the tensors object — the rounds engine's chunked dispatch
    asks per chunk."""
    cached = getattr(tensors, "_ip_of_cache", None)
    if cached is not None:
        return cached
    t = tensors.n_terms
    if not t:
        ip_of = np.zeros(0, np.int32)
    else:
        used = (
            tensors.a_aff_req.any(axis=0)
            | tensors.a_anti_req.any(axis=0)
            | (tensors.w_aff_pref != 0).any(axis=0)
            | (tensors.w_anti_pref != 0).any(axis=0)
        )
        ip_of = np.full(t, -1, np.int32)
        ip_of[used] = np.arange(int(used.sum()), dtype=np.int32)
    object.__setattr__(tensors, "_ip_of_cache", ip_of)
    return ip_of


def _add_at_rows(dst: np.ndarray, idx: np.ndarray, src: np.ndarray) -> None:
    """dst[idx[i], :] += src[i, :] — native C scatter when built, else
    np.add.at (which is ~50x slower on large placement logs)."""
    if dst.size == 0 or len(idx) == 0:
        return
    if not scatter_add_rows(dst, idx, src):
        np.add.at(dst, idx, src)


class SchedState(NamedTuple):
    """Mutable-under-scan cluster state.

    Topology counts are stored **per node**, not per domain: `cnt_*[t, n]` is
    the count in node n's domain for term t's topology key (0 where the node
    misses the key). Placing a pod updates them with one vectorized
    same-domain compare (`dom_sub == dom_sub[:, chosen]`) — no gather or
    scatter appears anywhere in the scan step, which is what keeps the step
    fast on TPU (gathers over the domain axis were the dominant cost), and
    the [T, N] layout shards over the node axis with everything else.

    free:            [N, R] remaining allocatable per node
    cnt_match:       [T, N] placed pods matching term t in node n's domain
    cnt_total:       [T] cluster-wide matching count per term (pods placed on
                     nodes carrying the key — interpod first-pod escape)

    The four "own" planes live on the compacted interpod axis (Ti rows,
    `interpod_term_index`): only terms appearing in some group's required or
    preferred (anti-)affinity have a row — T grows with the number of
    workloads (SelectorSpread interns ~2 terms per controller), while Ti
    stays at the handful that actually need owner bookkeeping, which is what
    keeps the state within single-chip HBM at 100k nodes.

    cnt_own_anti:    [Ti, N] placed pods owning required anti-affinity term
    cnt_own_aff:     [Ti, N] placed pods owning required affinity term
    w_own_aff_pref:  [Ti, N] summed preferred-affinity weights of placed owners
    w_own_anti_pref: [Ti, N] summed preferred-anti-affinity weights
    vg_free:         [N, V] free LVM volume-group space (Open-Local)
    sdev_free:       [N, SD] exclusive storage devices still unallocated
    gpu_free:        [N, GD] free GPU memory per device (GPU-share)
    ports_used:      [N, P] in-use (protocol, hostPort) pairs (NodePorts)
    vols_any:        [N, W] users of exclusive volume w (VolumeRestrictions)
    vols_rw:         [N, W] read-write users of exclusive volume w
    """

    free: jnp.ndarray
    cnt_match: jnp.ndarray
    cnt_total: jnp.ndarray
    cnt_own_anti: jnp.ndarray
    cnt_own_aff: jnp.ndarray
    w_own_aff_pref: jnp.ndarray
    w_own_anti_pref: jnp.ndarray
    vg_free: jnp.ndarray
    sdev_free: jnp.ndarray
    gpu_free: jnp.ndarray
    ports_used: jnp.ndarray
    vols_any: jnp.ndarray
    vols_rw: jnp.ndarray


def build_state(
    tensors,
    placed_group: np.ndarray,
    placed_node: np.ndarray,
    placed_req: np.ndarray,
    placed_ext: dict = None,
) -> SchedState:
    """Reconstruct the full scan carry from the host-side placement log.

    Called at the start of every app batch (group/term vocabularies may have
    grown since the last batch, so counts are recomputed from scratch — the
    reference equivalently recounts topology pairs from the live cache every
    PreFilter, `plugins/interpodaffinity/filtering.go`). O(P·T) numpy work.
    """
    n, r = tensors.alloc.shape
    t, d = tensors.n_terms, tensors.n_domains
    ip_of = interpod_term_index(tensors)
    ti = int(ip_of.max()) + 1 if t else 0
    ext = tensors.ext
    if not len(placed_group) and (placed_ext is None or not len(placed_ext.get("node", ()))):
        # empty log (fresh engine / first batch): everything derives from
        # the cluster tensors alone, and the count planes are zeros —
        # allocate them ON DEVICE rather than materializing ~hundreds of MB
        # host-side and transferring (this path is on the bench's critical
        # start-up, once per fresh engine)
        return SchedState(
            free=jnp.asarray(tensors.alloc.astype(np.float32)),
            cnt_match=jnp.zeros((t, n), jnp.float32),
            cnt_total=jnp.zeros(t, jnp.float32),
            # distinct buffers: the scan donates the carry, and donating one
            # buffer aliased into several fields is invalid
            cnt_own_anti=jnp.zeros((ti, n), jnp.float32),
            cnt_own_aff=jnp.zeros((ti, n), jnp.float32),
            w_own_aff_pref=jnp.zeros((ti, n), jnp.float32),
            w_own_anti_pref=jnp.zeros((ti, n), jnp.float32),
            vg_free=jnp.asarray((ext.vg_cap - ext.vg_req0).astype(np.float32)),
            sdev_free=jnp.asarray((ext.sdev_cap > 0) & ~ext.sdev_alloc0),
            gpu_free=jnp.asarray(ext.gpu_dev_total.astype(np.float32)),
            ports_used=jnp.zeros((n, tensors.n_ports), jnp.float32),
            vols_any=jnp.zeros((n, tensors.n_vols), jnp.float32),
            vols_rw=jnp.zeros((n, tensors.n_vols), jnp.float32),
        )
    free = tensors.alloc.astype(np.float32).copy()
    vg_free = (ext.vg_cap - ext.vg_req0).astype(np.float32)
    sdev_free = (ext.sdev_cap > 0) & ~ext.sdev_alloc0
    gpu_free = ext.gpu_dev_total.astype(np.float32).copy()
    if placed_ext and len(placed_ext.get("node", ())):
        pn = np.asarray(placed_ext["node"], np.int32)
        _add_at_rows(vg_free, pn, -np.asarray(placed_ext["vg_alloc"], np.float32))
        np.minimum.at(
            sdev_free, pn, ~np.asarray(placed_ext["sdev_take"], bool)
        )
        _add_at_rows(
            gpu_free,
            pn,
            -np.asarray(placed_ext["gpu_shares"], np.float32)
            * np.asarray(placed_ext["gpu_mem"], np.float32)[:, None],
        )
    ports_used = np.zeros((n, tensors.n_ports), np.float32)
    if len(placed_group) and tensors.n_ports:
        _add_at_rows(
            ports_used,
            placed_node,
            tensors.ports[placed_group].astype(np.float32),
        )
    vols_any = np.zeros((n, tensors.n_vols), np.float32)
    vols_rw = np.zeros((n, tensors.n_vols), np.float32)
    if len(placed_group) and tensors.n_vols:
        rw = tensors.vol_rw[placed_group]
        present = rw | tensors.vol_ro[placed_group] | tensors.vol_att[placed_group]
        _add_at_rows(vols_any, placed_node, present.astype(np.float32))
        _add_at_rows(vols_rw, placed_node, rw.astype(np.float32))
    if len(placed_group):
        req = placed_req
        if req.shape[1] < r:  # resource vocab grew after this pod was logged
            req = np.pad(req, ((0, 0), (0, r - req.shape[1])))
        _add_at_rows(free, placed_node, -req)
    # Topology counts rebuild via group-level aggregation — the count of
    # term t in node n's domain is Σ_g incid[g, t] · (placements of group g
    # in that domain), so ONE [P]-length (group, node) scatter plus a
    # per-domain segment sum per topology key replaces any per-placement
    # per-term work (the previous [P, T] formulation allocated tens of GB
    # at million-pod log sizes). Per-term rows then accumulate over the
    # sparse (group, term) incidence pairs.
    ip_terms = np.flatnonzero(ip_of >= 0)  # ascending = plane row order
    cnt_match = np.zeros((t, n), np.float32)
    own_n = np.zeros((4, len(ip_terms), n), np.float32)
    cnt_total = np.zeros(t, np.float32)
    if t and len(placed_group):
        g_n = len(tensors.groups)
        term_topo = tensors.term_topo_key
        key_valid = tensors.node_dom >= 0  # [K, N]
        # one [P]-length scatter via bincount (np.add.at's buffered path is
        # ~10x slower at million-entry logs)
        flat = placed_group.astype(np.int64) * n + placed_node
        cnt_gn = (
            np.bincount(flat, minlength=g_n * n)
            .reshape(g_n, n)
            .astype(np.float32)
        )
        # per-key [D, G] domain aggregates and cached safe domain indices
        # (rows without the key carry 0)
        cnt_dg, safe_k = {}, {}
        for k in {int(x) for x in term_topo[:t]}:
            safe_k[k] = np.where(key_valid[k], tensors.node_dom[k], 0)
            src = np.where(key_valid[k][None, :], cnt_gn, 0.0).T.copy()  # [N, G]
            buf = np.zeros((d, g_n), np.float32)
            _add_at_rows(buf, safe_k[k], src)
            cnt_dg[k] = buf
        tot_kg = {k: buf.sum(axis=0) for k, buf in cnt_dg.items()}

        row_cache = {}  # (key, group) → expanded [N] domain-count row

        def group_row(k, g_i):
            got = row_cache.get((k, g_i))
            if got is None:
                got = np.where(key_valid[k], cnt_dg[k][safe_k[k], g_i], 0.0)
                row_cache[(k, g_i)] = got
            return got

        def fill_rows(dst, term_ids, incid, totals=None):
            """dst[i] += Σ_g incid[g, term_ids[i]] · domain-count row of g;
            `totals` accumulates the per-term cluster-wide sum in the same
            pass over the sparse incidence pairs. Rows are cached per
            (topology key, group) — one group commonly matches many terms
            sharing a key (SelectorSpread interns several per controller)."""
            sub = incid if term_ids is None else incid[:, term_ids]
            for g_i, t_i in zip(*np.nonzero(sub)):
                tid = t_i if term_ids is None else term_ids[t_i]
                k = int(term_topo[tid])
                w = float(sub[g_i, t_i])
                dst[t_i] += w * group_row(k, g_i)
                if totals is not None:
                    totals[tid] += w * tot_kg[k][g_i]

        fill_rows(cnt_match, None, tensors.s_match, totals=cnt_total)
        for s_i, mat in enumerate(
            (
                tensors.a_anti_req,
                tensors.a_aff_req,
                tensors.w_aff_pref,
                tensors.w_anti_pref,
            )
        ):
            fill_rows(own_n[s_i], ip_terms, mat)
    return SchedState(
        free=jnp.asarray(free),
        cnt_match=jnp.asarray(cnt_match),
        cnt_total=jnp.asarray(cnt_total),
        cnt_own_anti=jnp.asarray(own_n[0]),
        cnt_own_aff=jnp.asarray(own_n[1]),
        w_own_aff_pref=jnp.asarray(own_n[2]),
        w_own_anti_pref=jnp.asarray(own_n[3]),
        vg_free=jnp.asarray(vg_free),
        sdev_free=jnp.asarray(sdev_free),
        gpu_free=jnp.asarray(gpu_free),
        ports_used=jnp.asarray(ports_used),
        vols_any=jnp.asarray(vols_any),
        vols_rw=jnp.asarray(vols_rw),
    )


# -- batch apply / undo of placement deltas ----------------------------------
#
# The functional analog of the scheduler cache's AddPod/RemovePod pair
# (`internal/cache/cache.go`): one compiled scan folds a batch of signed
# placement-log entries into the carried state — sign +1 re-places, -1
# evicts, 0 is a padding no-op — without rebuilding the state from the full
# log.  Drives incremental preemption (Engine._apply_saved_delta applies an
# eviction and its undo as the same call with opposite signs) and any other
# consumer that needs to roll a batch of placements forward or back.


def placement_delta_step(statics, state: SchedState, entry):
    """Apply one placement-log entry to the state with weight w (+1 =
    re-place, -1 = evict): exactly `schedule_step`'s state-update block,
    without filters or node choice. Drives incremental preemption — a full
    build_state from a million-entry log per eviction costs more than the
    whole preemption."""
    g, node, w, req, vg_alloc, sdev_take, gpu_vec = entry
    safe = jnp.clip(node, 0)
    updates = {"free": state.free.at[safe].add(-req * w)}
    if state.ports_used.shape[1]:
        updates["ports_used"] = state.ports_used.at[safe].add(
            statics.ports_req[g] * w
        )
    if state.vols_any.shape[1]:
        v_rw = statics.vol_rw_req[g]
        v_present = v_rw | statics.vol_ro_req[g] | statics.vol_att_req[g]
        updates["vols_any"] = state.vols_any.at[safe].add(v_present * w)
        updates["vols_rw"] = state.vols_rw.at[safe].add(v_rw * w)
    if state.vg_free.shape[1]:
        updates["vg_free"] = state.vg_free.at[safe].add(-vg_alloc * w)
    if state.sdev_free.shape[1]:
        # boolean devices: w>0 consumes (clear), w<0 releases (set)
        row = state.sdev_free[safe]
        row = jnp.where(w > 0, row & ~sdev_take, row | sdev_take)
        updates["sdev_free"] = state.sdev_free.at[safe].set(row)
    if state.gpu_free.shape[1]:
        updates["gpu_free"] = state.gpu_free.at[safe].add(-gpu_vec * w)
    t_cap = statics.g_terms.shape[1]
    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        dom_sub = take_rows_i32(
            statics.node_dom, jnp.where(tvalid, statics.term_topo[tsafe], -1)
        )
        valid_sub = (dom_sub >= 0) & tvalid[:, None]
        dom_chosen = dom_sub[:, safe]
        valid_chosen = (dom_chosen >= 0) & tvalid
        same = valid_sub & (dom_sub == dom_chosen[:, None]) & valid_chosen[:, None]
        inc = jnp.where(same, w, 0.0)

        updates["cnt_match"] = add_rows(
            state.cnt_match, terms_g, statics.s_match[g][:, None] * inc
        )
        updates["cnt_total"] = state.cnt_total.at[tsafe].add(
            statics.s_match[g] * jnp.where(valid_chosen, w, 0.0)
        )
        ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)

        def bump_ip(arr, vals):
            return add_rows(arr, ip_eff, vals[:, None] * inc)

        updates["cnt_own_anti"] = bump_ip(
            state.cnt_own_anti, statics.a_anti_req[g].astype(jnp.float32)
        )
        updates["cnt_own_aff"] = bump_ip(
            state.cnt_own_aff, statics.a_aff_req[g].astype(jnp.float32)
        )
        updates["w_own_aff_pref"] = bump_ip(state.w_own_aff_pref, statics.w_aff_pref[g])
        updates["w_own_anti_pref"] = bump_ip(
            state.w_own_anti_pref, statics.w_anti_pref[g]
        )
    return state._replace(**updates), ()


@partial(jax.jit, donate_argnums=(1,))
def apply_placement_deltas(statics, state: SchedState, entries):
    """Scan `placement_delta_step` over padded entry arrays (w = 0 rows are
    no-ops).  Entries with w = -1 undo what the same entries with w = +1
    applied — the batch-apply/undo pair behind preemption's eviction and
    restore paths and the fault sweep's scenario drains
    (simtpu/faults/)."""
    state, _ = jax.lax.scan(partial(placement_delta_step, statics), state, entries)
    return state


def pack_delta_entries(entries, n_resources: int, vg_w: int, sd_w: int, gd_w: int,
                       sign: float, pad_to: int = None):
    """Padded entry arrays for `apply_placement_deltas` from saved
    placement-log records in `Engine.remove_placements`' layout
    ((g, node, req, ext_node, vg_alloc, sdev_take, gpu_shares, gpu_mem) per
    entry).  Rows beyond len(entries) carry w = 0 and are exact no-ops
    through `placement_delta_step`; `pad_to` overrides the default
    pow2-bounded padding (the fault sweep pads every scenario of a batch
    to one shared length so all scenarios compile one executable).  The
    single packing used by the engine's eviction/undo path and the
    scenario sweep — shared code is what keeps their delta arithmetic
    bit-identical."""
    v = len(entries)
    v_pad = pad_to if pad_to is not None else 1 << max(v - 1, 0).bit_length()
    g_a = np.zeros(v_pad, np.int32)
    n_a = np.zeros(v_pad, np.int32)
    w_a = np.zeros(v_pad, np.float32)
    req_a = np.zeros((v_pad, n_resources), np.float32)
    vg_a = np.zeros((v_pad, vg_w), np.float32)
    sd_a = np.zeros((v_pad, sd_w), bool)
    gp_a = np.zeros((v_pad, gd_w), np.float32)
    for i, (g, node, req, _enode, vg, sdev, gpu_sh, gpu_mem) in enumerate(entries):
        g_a[i], n_a[i], w_a[i] = g, node, sign
        req_a[i, : req.shape[0]] = req
        vg_a[i] = vg
        sd_a[i] = sdev
        gp_a[i] = np.asarray(gpu_sh) * gpu_mem
    return (g_a, n_a, w_a, req_a, vg_a, sd_a, gp_a)


# -- append-only vocabulary growth (warm-engine serving) ---------------------
#
# Between place() calls the pod/term vocabulary only ever APPENDS (Interners
# never reassign ids), so a carried state can follow a grown vocabulary with
# a device-side extension instead of the O(P·T) host rebuild build_state
# performs: new term rows are computed host-side from the SAME group-level
# aggregation build_state uses (bit-identity by shared math — counts are
# integer-valued f32, and per-row contributions accumulate over the sparse
# (group, term) pairs in the same ascending-group order), the compacted
# interpod planes are re-laid-out by one gather (an old term newly marked
# interpod-used INSERTS a row mid-plane; its values are zero — only groups
# interned after the mark own it, and they have no placements yet), and
# everything else passes through.
#
# To bound recompiles, a grow-mode engine carries its term axes PRE-PADDED
# to pow2 shape buckets: cnt_match/cnt_total live at [T_cap, N]/[T_cap] and
# the own planes at [Ti_cap, N] with zero rows above the live watermark.
# Every consumer addresses term rows by id (< T), so padding rows are never
# read or written — dispatch executables, the delta apply/undo path and the
# chunked scan all key on the BUCKET shape and stay warm while the
# vocabulary grows within it.  Growth events trace `_extend_terms_kernel`
# once per (old bucket, new bucket, appended-row bucket) signature — the
# `compile.grow` trace-once-per-bucket contract (tests/test_grow.py).
# Grow-mode carries stay dense (compression re-derives its plan from the
# tensors' exact term partition and would re-trace per vocabulary size).


def snap_pow2(x: int, floor: int = 1) -> int:
    """Next power of two ≥ x (at least `floor`) — the shape-bucket snap for
    grow-mode carried planes and appended-row batches."""
    return max(floor, 1 << max(int(x) - 1, 0).bit_length())


#: counter names surfaced in the `engine.grow` response/CLI block — the
#: registry family tests/test_grow.py and `make bench-grow` pin.  Lives
#: here (not in simtpu.serve) so `apply --json` can report it without
#: importing the daemon (the off-path zero-cost pin, tests/test_serve.py).
GROW_COUNTERS = (
    "grow.extends",
    "grow.bucket_promotions",
    "grow.node_extends",
    "grow.rebuilds",
    "grow.retensorize_fallbacks",
    "compile.grow",
)


def grow_counters_doc() -> dict:
    """The append-only-growth counter block (process registry — monotone
    across queries, like the `compile.*` family), `grow.` prefix
    stripped.  serve/session.py's `grow_doc` layers the per-session
    warm/bucket fields on top."""
    from ..obs.metrics import REGISTRY

    snap = REGISTRY.snapshot()
    return {
        name.split("grow.", 1)[-1] if name.startswith("grow.") else name:
            int(snap.get(name, 0))
        for name in GROW_COUNTERS
    }


def _count_grow_trace() -> None:
    """Python-side trace counter: executes once per (re)trace of a growth
    kernel, never at run time — the `compile.grow` registry family
    (engine/scan.py COMPILE_COUNT_KINDS)."""
    from ..obs.metrics import REGISTRY

    REGISTRY.counter("compile.grow").inc()


@partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2,))
def _pad_terms_kernel(t_cap: int, ti_cap: int, state: SchedState) -> SchedState:
    """Copy a freshly built exact-shape state into its pow2 term buckets
    (zero rows above the live watermark) — the grow-mode entry copy, once
    per (exact shape, bucket) pair."""
    _count_grow_trace()

    def pad_rows(plane, cap):
        if plane.shape[0] == cap:
            return plane
        return (
            jnp.zeros((cap,) + plane.shape[1:], plane.dtype)
            .at[: plane.shape[0]]
            .set(plane)
        )

    return state._replace(
        cnt_match=pad_rows(state.cnt_match, t_cap),
        cnt_total=pad_rows(state.cnt_total, t_cap),
        cnt_own_anti=pad_rows(state.cnt_own_anti, ti_cap),
        cnt_own_aff=pad_rows(state.cnt_own_aff, ti_cap),
        w_own_aff_pref=pad_rows(state.w_own_aff_pref, ti_cap),
        w_own_anti_pref=pad_rows(state.w_own_anti_pref, ti_cap),
    )


@partial(jax.jit, static_argnums=(5, 6), donate_argnums=(0,))
def _extend_terms_kernel(
    state: SchedState, new_ids, new_rows, new_tot, own_perm,
    t_cap: int, ti_cap: int,
) -> SchedState:
    """Device-side term-axis extension: promote the count planes into the
    target buckets, scatter the host-computed appended term rows (padded
    ids are -1 → masked to zero adds), and re-gather the own planes
    through the new interpod layout (`own_perm[j]` = old row feeding new
    row j, -1 = fresh zero row)."""
    _count_grow_trace()
    cm, ct = state.cnt_match, state.cnt_total
    if cm.shape[0] != t_cap:
        cm = jnp.zeros((t_cap, cm.shape[1]), cm.dtype).at[: cm.shape[0]].set(cm)
        ct = jnp.zeros((t_cap,), ct.dtype).at[: ct.shape[0]].set(ct)
    if new_ids.shape[0]:
        safe = jnp.clip(new_ids, 0)
        live = new_ids >= 0
        cm = cm.at[safe].add(jnp.where(live[:, None], new_rows, 0.0))
        ct = ct.at[safe].add(jnp.where(live, new_tot, 0.0))

    def permute(plane):
        if not ti_cap:
            return plane
        if not plane.shape[0]:
            return jnp.zeros((ti_cap, state.cnt_match.shape[1]), plane.dtype)
        return jnp.where(
            (own_perm >= 0)[:, None],
            plane[jnp.clip(own_perm, 0)],
            jnp.zeros((), plane.dtype),
        )

    return state._replace(
        cnt_match=cm,
        cnt_total=ct,
        cnt_own_anti=permute(state.cnt_own_anti),
        cnt_own_aff=permute(state.cnt_own_aff),
        w_own_aff_pref=permute(state.w_own_aff_pref),
        w_own_anti_pref=permute(state.w_own_anti_pref),
    )


@partial(jax.jit, static_argnums=(7, 8), donate_argnums=(0,))
def _extend_nodes_kernel(
    state: SchedState, free_rows, cnt_cols, own_cols,
    vg_rows, sdev_rows, gpu_rows, n_ports: int, n_vols: int,
) -> SchedState:
    """Device-side node-axis extension: append the new nodes' free/storage
    rows and the host-computed count-plane columns (pods already placed in
    a domain the new node joins are visible from it immediately)."""
    _count_grow_trace()
    a = free_rows.shape[0]

    def cat_cols(plane, cols):
        return jnp.concatenate([plane, cols.astype(plane.dtype)], axis=1)

    return state._replace(
        free=jnp.concatenate([state.free, free_rows]),
        cnt_match=cat_cols(state.cnt_match, cnt_cols),
        cnt_own_anti=cat_cols(state.cnt_own_anti, own_cols[0]),
        cnt_own_aff=cat_cols(state.cnt_own_aff, own_cols[1]),
        w_own_aff_pref=cat_cols(state.w_own_aff_pref, own_cols[2]),
        w_own_anti_pref=cat_cols(state.w_own_anti_pref, own_cols[3]),
        vg_free=jnp.concatenate([state.vg_free, vg_rows]),
        sdev_free=jnp.concatenate([state.sdev_free, sdev_rows]),
        gpu_free=jnp.concatenate([state.gpu_free, gpu_rows]),
        ports_used=jnp.concatenate(
            [state.ports_used, jnp.zeros((a, n_ports), state.ports_used.dtype)]
        ),
        vols_any=jnp.concatenate(
            [state.vols_any, jnp.zeros((a, n_vols), state.vols_any.dtype)]
        ),
        vols_rw=jnp.concatenate(
            [state.vols_rw, jnp.zeros((a, n_vols), state.vols_rw.dtype)]
        ),
    )


def _grow_aggregates(tensors, placed_group, placed_node, keys):
    """The per-key [D, G] domain aggregates build_state derives its count
    rows from, restricted to the topology keys a growth event touches.
    One [P]-length bincount over the log plus a per-key row scatter —
    O(P) instead of build_state's O(P·T)."""
    n = tensors.alloc.shape[0]
    g_n = len(tensors.groups)
    d = tensors.n_domains
    key_valid = tensors.node_dom >= 0
    flat = placed_group.astype(np.int64) * n + placed_node
    cnt_gn = (
        np.bincount(flat, minlength=g_n * n).reshape(g_n, n).astype(np.float32)
    )
    cnt_dg, safe_k = {}, {}
    for k in keys:
        safe_k[k] = np.where(key_valid[k], tensors.node_dom[k], 0)
        src = np.where(key_valid[k][None, :], cnt_gn, 0.0).T.copy()
        buf = np.zeros((d, g_n), np.float32)
        _add_at_rows(buf, safe_k[k], src)
        cnt_dg[k] = buf
    return cnt_dg, safe_k, key_valid


def _term_rows_subset(tensors, placed_group, placed_node, term_ids):
    """Count rows + cluster totals for a SUBSET of terms, by the same
    aggregation build_state runs for all terms (integer-valued counts and
    identical ascending-group accumulation keep the rows bit-identical to
    a from-scratch rebuild's)."""
    n = tensors.alloc.shape[0]
    rows = np.zeros((len(term_ids), n), np.float32)
    tot = np.zeros(len(term_ids), np.float32)
    if not len(placed_group) or not len(term_ids):
        return rows, tot
    term_topo = tensors.term_topo_key
    keys = {int(term_topo[tid]) for tid in term_ids}
    cnt_dg, safe_k, key_valid = _grow_aggregates(
        tensors, placed_group, placed_node, keys
    )
    tot_kg = {k: buf.sum(axis=0) for k, buf in cnt_dg.items()}
    row_cache = {}

    def group_row(k, g_i):
        got = row_cache.get((k, g_i))
        if got is None:
            got = np.where(key_valid[k], cnt_dg[k][safe_k[k], g_i], 0.0)
            row_cache[(k, g_i)] = got
        return got

    sub = tensors.s_match[:, term_ids]
    for g_i, t_i in zip(*np.nonzero(sub)):
        tid = int(term_ids[t_i])
        k = int(term_topo[tid])
        w = float(sub[g_i, t_i])
        rows[t_i] += w * group_row(k, g_i)
        tot[t_i] += w * tot_kg[k][g_i]
    return rows, tot


def _node_cols_subset(tensors, placed_group, placed_node, node_ids):
    """Count-plane COLUMNS for appended nodes: cnt_match [T, a] plus the
    four own planes [Ti, a] evaluated at the new nodes' domains — a pod
    already placed in a zone a clone joins is counted on the clone."""
    t = tensors.n_terms
    ip_of = interpod_term_index(tensors)
    ip_terms = np.flatnonzero(ip_of >= 0)
    a = len(node_ids)
    cnt_cols = np.zeros((t, a), np.float32)
    own_cols = np.zeros((4, len(ip_terms), a), np.float32)
    if not len(placed_group) or not t:
        return cnt_cols, own_cols
    term_topo = tensors.term_topo_key
    keys = {int(x) for x in term_topo[:t]}
    cnt_dg, safe_k, key_valid = _grow_aggregates(
        tensors, placed_group, placed_node, keys
    )
    row_cache = {}

    def group_cols(k, g_i):
        got = row_cache.get((k, g_i))
        if got is None:
            got = np.where(
                key_valid[k][node_ids],
                cnt_dg[k][safe_k[k][node_ids], g_i],
                0.0,
            )
            row_cache[(k, g_i)] = got
        return got

    def fill(dst, term_ids, incid):
        sub = incid if term_ids is None else incid[:, term_ids]
        for g_i, t_i in zip(*np.nonzero(sub)):
            tid = t_i if term_ids is None else term_ids[t_i]
            k = int(term_topo[tid])
            dst[t_i] += float(sub[g_i, t_i]) * group_cols(k, g_i)

    fill(cnt_cols, None, tensors.s_match)
    for s_i, mat in enumerate(
        (
            tensors.a_anti_req,
            tensors.a_aff_req,
            tensors.w_aff_pref,
            tensors.w_anti_pref,
        )
    ):
        fill(own_cols[s_i], ip_terms, mat)
    return cnt_cols, own_cols


def grow_plan_terms(tensors, t_old: int, ip_terms_old, placed_group, placed_node):
    """Host-side plan for a term-axis growth event: appended term rows and
    totals (bucket-padded, ids -1 above the live count), the own-plane
    re-layout gather, and the target buckets.  `ip_terms_old` is the
    ascending term-id layout the carried own planes were built under."""
    t_new = tensors.n_terms
    ip_of = interpod_term_index(tensors)
    ip_terms_new = np.flatnonzero(ip_of >= 0)
    ti_new = len(ip_terms_new)
    m = t_new - t_old
    m_cap = snap_pow2(m) if m else 0
    ids = np.full(m_cap, -1, np.int32)
    rows = np.zeros((m_cap, tensors.alloc.shape[0]), np.float32)
    tot = np.zeros(m_cap, np.float32)
    if m:
        new_ids = np.arange(t_old, t_new, dtype=np.int32)
        ids[:m] = new_ids
        rows[:m], tot[:m] = _term_rows_subset(
            tensors, placed_group, placed_node, new_ids
        )
    ti_cap = snap_pow2(ti_new) if ti_new else 0
    perm = np.full(max(ti_cap, 1), -1, np.int32)[:ti_cap]
    pos_old = {int(tid): i for i, tid in enumerate(np.asarray(ip_terms_old))}
    for j, tid in enumerate(ip_terms_new):
        perm[j] = pos_old.get(int(tid), -1)
    return {
        "ids": ids,
        "rows": rows,
        "tot": tot,
        "perm": perm,
        "t": t_new,
        "ti": ti_new,
        "t_cap": snap_pow2(t_new) if t_new else 0,
        "ti_cap": ti_cap,
        "ip_terms": ip_terms_new,
    }


def extend_state(state: SchedState, plan: dict) -> SchedState:
    """Apply a `grow_plan_terms` plan to a grow-mode carried state — the
    jitted append-only alternative to build_state after a vocabulary
    growth (bit-identity pinned by tests/test_grow.py)."""
    return _extend_terms_kernel(
        state,
        jnp.asarray(plan["ids"]),
        jnp.asarray(plan["rows"]),
        jnp.asarray(plan["tot"]),
        jnp.asarray(plan["perm"]),
        plan["t_cap"],
        plan["ti_cap"],
    )


def grow_plan_nodes(tensors, n_old: int, placed_group, placed_node,
                    t_cap: int, ti_cap: int):
    """Host-side plan for a node-axis growth event (Tensorizer.add_clone_nodes
    appended rows [n_old:]): the new nodes' free/storage rows and the
    count-plane columns at the carried bucket heights."""
    n_new = tensors.alloc.shape[0]
    node_ids = np.arange(n_old, n_new)
    ext = tensors.ext
    cnt_cols, own_cols = _node_cols_subset(
        tensors, placed_group, placed_node, node_ids
    )
    t, ti = cnt_cols.shape[0], own_cols.shape[1]
    cnt_p = np.zeros((t_cap, len(node_ids)), np.float32)
    cnt_p[:t] = cnt_cols
    own_p = np.zeros((4, ti_cap, len(node_ids)), np.float32)
    own_p[:, :ti] = own_cols
    return {
        "free": tensors.alloc[n_old:].astype(np.float32),
        "cnt_cols": cnt_p,
        "own_cols": own_p,
        "vg": (ext.vg_cap[n_old:] - ext.vg_req0[n_old:]).astype(np.float32),
        "sdev": (ext.sdev_cap[n_old:] > 0) & ~ext.sdev_alloc0[n_old:],
        "gpu": ext.gpu_dev_total[n_old:].astype(np.float32),
        "n": n_new,
    }


def extend_state_nodes(state: SchedState, plan: dict, tensors) -> SchedState:
    """Apply a `grow_plan_nodes` plan: one jitted concatenate per plane."""
    return _extend_nodes_kernel(
        state,
        jnp.asarray(plan["free"]),
        jnp.asarray(plan["cnt_cols"]),
        jnp.asarray(plan["own_cols"]),
        jnp.asarray(plan["vg"]),
        jnp.asarray(plan["sdev"]),
        jnp.asarray(plan["gpu"]),
        tensors.n_ports,
        tensors.n_vols,
    )


def strip_term_padding(state: SchedState, t: int, ti: int) -> SchedState:
    """Exact-shape dense view of a grow-mode (bucket-padded) carry — what
    carried_state() hands to consumers expecting [T, N]/[Ti, N] planes."""
    if state.cnt_match.shape[0] == t and state.cnt_own_anti.shape[0] == ti:
        return state
    return state._replace(
        cnt_match=state.cnt_match[:t],
        cnt_total=state.cnt_total[:t],
        cnt_own_anti=state.cnt_own_anti[:ti],
        cnt_own_aff=state.cnt_own_aff[:ti],
        w_own_aff_pref=state.w_own_aff_pref[:ti],
        w_own_anti_pref=state.w_own_anti_pref[:ti],
    )


# -- compact carried state ---------------------------------------------------
#
# The carried count planes are [T, N] / [Ti, N] dense float32, but for every
# topology key with a small domain cardinality (key_kind == 1: zone / rack /
# region-sized keys, ≤ DOM_SMALL compact ids in node_dom_small) the per-node
# value is CONSTANT within a domain — cnt[t, n] is "matching pods in node n's
# domain", the same number for every node of the domain and 0 where the key
# is absent.  Those rows carry D_key ≤ DOM_SMALL numbers of information in N
# floats.  Between dispatches the state therefore travels in a domain-TABULAR
# form (CompactState): kind-1 term rows as [Rt, D] histograms indexed by
# node_dom_small, dense [N] rows only for unique-per-node keys (kind 2,
# where the row IS the information) and the scatter fallback (kind 0), with
# integer planes narrowed to COUNT_DTYPE (the conversion boundary documented
# in core/tensorize.py).  Expansion back to per-node form is ONE gather
# inside a jitted kernel (expand_state), so every filter/score/tie-break
# consumer sees bit-identical float32 planes; compression is a gather of one
# representative node per (key, domain) — no reduction, hence exact by
# construction (pinned by tests/test_compact.py round trips).
#
# SIMTPU_COMPACT=0 flips the engines back to carrying dense SchedState
# between dispatches — placements are bit-identical either way; the switch
# exists for A/B measurement (bench.py `state_bytes` / `make bench-layout`).


def compact_enabled() -> bool:
    """Default for Engine.compact: SIMTPU_COMPACT=0 disables the compact
    carried-state layout (1/unset = on)."""
    return os.environ.get("SIMTPU_COMPACT", "1") != "0"


class CompactSpecDev(NamedTuple):
    """Device-resident index arrays driving compress/expand (constant per
    tensors; memoized alongside the host spec)."""

    t_tab: jnp.ndarray  # [Rt] cnt_match rows with a kind-1 (tabular) key
    t_dense: jnp.ndarray  # [Rd] the rest (kind 0/2) — Rt + Rd == T
    t_keys: jnp.ndarray  # [Rt] topology key per tabular row
    t_rep: jnp.ndarray  # [Rt, D] representative node per domain (-1 none)
    ip_tab: jnp.ndarray  # [Rti] interpod-plane rows with a kind-1 key
    ip_dense: jnp.ndarray  # [Rdi] — Rti + Rdi == Ti
    ip_keys: jnp.ndarray  # [Rti]
    ip_rep: jnp.ndarray  # [Rti, D]


class CompactSpec(NamedTuple):
    """Host-side compaction plan for one frozen tensors object."""

    enabled: bool  # any tabular row exists (else carry dense SchedState)
    d: int  # histogram width (max small-domain count over kind-1 keys)
    dev: Optional[CompactSpecDev]


def compact_spec(tensors) -> CompactSpec:
    """The (memoized) compaction plan: partition the cnt_match and interpod
    plane rows by their topology key's reduction kind, and precompute one
    representative node per (kind-1 key, domain) for the exact
    representative-gather compression."""
    cached = getattr(tensors, "_compact_spec_cache", None)
    if cached is not None:
        return cached
    t = int(tensors.n_terms)
    kinds = (
        tensors.key_kind
        if tensors.key_kind is not None
        else np.zeros(0, np.int32)
    )
    nds = tensors.node_dom_small
    if not t or not kinds.shape[0]:
        spec = CompactSpec(False, 1, None)
        object.__setattr__(tensors, "_compact_spec_cache", spec)
        return spec
    term_keys = np.asarray(tensors.term_topo_key[:t], np.int32)
    tab_mask = kinds[term_keys] == 1
    t_tab = np.flatnonzero(tab_mask).astype(np.int32)
    t_dense = np.flatnonzero(~tab_mask).astype(np.int32)
    if not len(t_tab):
        spec = CompactSpec(False, 1, None)
        object.__setattr__(tensors, "_compact_spec_cache", spec)
        return spec
    d = 1
    for k in np.unique(term_keys[tab_mask]):
        d = max(d, int(nds[k].max(initial=-1)) + 1)
    # representative node per (key, small domain): the FIRST node carrying
    # the domain id — compression gathers the plane at it, which is exact
    # because kind-1 rows are domain-constant (the class invariant every
    # state update preserves; see the module comment)
    rep = np.full((kinds.shape[0], d), -1, np.int32)
    for k in range(kinds.shape[0]):
        if kinds[k] != 1:
            continue
        ids = nds[k]
        valid = np.flatnonzero(ids >= 0)
        rep[k, ids[valid][::-1]] = valid[::-1].astype(np.int32)
    ip_of = interpod_term_index(tensors)
    ip_terms = np.flatnonzero(ip_of >= 0)  # ascending = plane row order
    ip_tabm = tab_mask[ip_terms]
    ip_tab = np.flatnonzero(ip_tabm).astype(np.int32)
    ip_dense = np.flatnonzero(~ip_tabm).astype(np.int32)
    t_keys = term_keys[t_tab]
    ip_keys = term_keys[ip_terms[ip_tab]]
    dev = CompactSpecDev(
        t_tab=jnp.asarray(t_tab),
        t_dense=jnp.asarray(t_dense),
        t_keys=jnp.asarray(t_keys),
        t_rep=jnp.asarray(rep[t_keys]),
        ip_tab=jnp.asarray(ip_tab),
        ip_dense=jnp.asarray(ip_dense),
        ip_keys=jnp.asarray(ip_keys),
        ip_rep=jnp.asarray(rep[ip_keys]),
    )
    spec = CompactSpec(True, d, dev)
    object.__setattr__(tensors, "_compact_spec_cache", spec)
    return spec


def node_dom_small_for(tensors, n: int) -> jnp.ndarray:
    """tensors.node_dom_small as a device array whose node axis is padded to
    `n` with -1 (absent) — the sharded engines carry a shard-padded state,
    and padded (dead) nodes must expand to 0 exactly like key-less nodes.
    Memoized per width on the tensors object."""
    cache = getattr(tensors, "_nds_pad_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(tensors, "_nds_pad_cache", cache)
    got = cache.get(n)
    if got is None:
        nds = np.asarray(tensors.node_dom_small, np.int32)
        pad = n - nds.shape[1]
        if pad:
            nds = np.pad(nds, ((0, 0), (0, pad)), constant_values=-1)
        got = cache[n] = jnp.asarray(nds)
    return got


class CompactState(NamedTuple):
    """SchedState's between-dispatch form: domain-tabular count planes,
    COUNT_DTYPE integers, bool masks (see the section comment).  Field
    pairs (`*_tab`, `*_dense`) partition the corresponding SchedState
    plane's rows; continuous planes (free / vg_free / gpu_free) ride along
    unchanged."""

    free: jnp.ndarray  # [N, R] f32
    cm_tab: jnp.ndarray  # [Rt, D] cnt_match tabular rows
    cm_dense: jnp.ndarray  # [Rd, N] cnt_match dense rows
    cnt_total: jnp.ndarray  # [T]
    oa_tab: jnp.ndarray  # cnt_own_anti
    oa_dense: jnp.ndarray
    of_tab: jnp.ndarray  # cnt_own_aff
    of_dense: jnp.ndarray
    wa_tab: jnp.ndarray  # w_own_aff_pref
    wa_dense: jnp.ndarray
    wn_tab: jnp.ndarray  # w_own_anti_pref
    wn_dense: jnp.ndarray
    vg_free: jnp.ndarray  # [N, V] f32
    sdev_free: jnp.ndarray  # [N, SD] bool
    gpu_free: jnp.ndarray  # [N, GD] f32
    ports_used: jnp.ndarray  # [N, P]
    vols_any: jnp.ndarray  # [N, W]
    vols_rw: jnp.ndarray  # [N, W]


def _compress_rows(full, ids_tab, ids_dense, rep):
    """Split one [rows, N] plane into its ([Rt, D] histogram, [Rd, N] dense)
    carried pair.  The histogram is a representative-node GATHER (domains
    without a node read 0), not a reduction — exact for domain-constant
    rows by construction."""
    tab = jnp.take_along_axis(full[ids_tab], jnp.clip(rep, 0), axis=1)
    tab = jnp.where(rep >= 0, tab, 0.0)
    return tab.astype(COUNT_DTYPE), full[ids_dense].astype(COUNT_DTYPE)


def _expand_rows(tab, dense, ids_tab, ids_dense, keys, nds):
    """Rebuild the [rows, N] float32 plane: one gather of each histogram row
    through the key's node_dom_small ids, dense rows cast back.  Integer-
    valued casts both ways — bit-identical to never having compressed."""
    rows = tab.shape[0] + dense.shape[0]
    n = nds.shape[1]
    full = jnp.zeros((rows, n), jnp.float32)
    if tab.shape[0]:
        idx = nds[keys]  # [Rt, N]
        vals = jnp.take_along_axis(
            tab.astype(jnp.float32), jnp.clip(idx, 0), axis=1
        )
        full = full.at[ids_tab].set(jnp.where(idx >= 0, vals, 0.0))
    if dense.shape[0]:
        full = full.at[ids_dense].set(dense.astype(jnp.float32))
    return full


def _compress_state_fn(spec: CompactSpecDev, state: SchedState) -> CompactState:
    cm_tab, cm_dense = _compress_rows(
        state.cnt_match, spec.t_tab, spec.t_dense, spec.t_rep
    )
    oa = _compress_rows(state.cnt_own_anti, spec.ip_tab, spec.ip_dense, spec.ip_rep)
    of = _compress_rows(state.cnt_own_aff, spec.ip_tab, spec.ip_dense, spec.ip_rep)
    wa = _compress_rows(
        state.w_own_aff_pref, spec.ip_tab, spec.ip_dense, spec.ip_rep
    )
    wn = _compress_rows(
        state.w_own_anti_pref, spec.ip_tab, spec.ip_dense, spec.ip_rep
    )
    return CompactState(
        free=state.free,
        cm_tab=cm_tab,
        cm_dense=cm_dense,
        cnt_total=state.cnt_total.astype(COUNT_DTYPE),
        oa_tab=oa[0],
        oa_dense=oa[1],
        of_tab=of[0],
        of_dense=of[1],
        wa_tab=wa[0],
        wa_dense=wa[1],
        wn_tab=wn[0],
        wn_dense=wn[1],
        vg_free=state.vg_free,
        sdev_free=state.sdev_free.astype(MASK_DTYPE),
        gpu_free=state.gpu_free,
        ports_used=state.ports_used.astype(COUNT_DTYPE),
        vols_any=state.vols_any.astype(COUNT_DTYPE),
        vols_rw=state.vols_rw.astype(COUNT_DTYPE),
    )


def _expand_state_fn(
    spec: CompactSpecDev, cstate: CompactState, nds: jnp.ndarray
) -> SchedState:
    return SchedState(
        free=cstate.free,
        cnt_match=_expand_rows(
            cstate.cm_tab, cstate.cm_dense, spec.t_tab, spec.t_dense,
            spec.t_keys, nds,
        ),
        cnt_total=cstate.cnt_total.astype(jnp.float32),
        cnt_own_anti=_expand_rows(
            cstate.oa_tab, cstate.oa_dense, spec.ip_tab, spec.ip_dense,
            spec.ip_keys, nds,
        ),
        cnt_own_aff=_expand_rows(
            cstate.of_tab, cstate.of_dense, spec.ip_tab, spec.ip_dense,
            spec.ip_keys, nds,
        ),
        w_own_aff_pref=_expand_rows(
            cstate.wa_tab, cstate.wa_dense, spec.ip_tab, spec.ip_dense,
            spec.ip_keys, nds,
        ),
        w_own_anti_pref=_expand_rows(
            cstate.wn_tab, cstate.wn_dense, spec.ip_tab, spec.ip_dense,
            spec.ip_keys, nds,
        ),
        vg_free=cstate.vg_free,
        sdev_free=cstate.sdev_free,
        gpu_free=cstate.gpu_free,
        ports_used=cstate.ports_used.astype(jnp.float32),
        vols_any=cstate.vols_any.astype(jnp.float32),
        vols_rw=cstate.vols_rw.astype(jnp.float32),
    )


# Donation audit (docs/memory.md): neither conversion donates.  Compression
# CANNOT reuse the dense buffers it consumes — every narrowed plane changes
# dtype (f32 → COUNT_DTYPE), which XLA refuses to alias, so donate_argnums
# would only emit the donated-buffers-unusable warning (the dense planes are
# still freed at last use; the pass-through planes alias into the output
# with or without donation).  Expansion must not donate because the compact
# carry is routinely shared: the incremental planner copies one snapshot per
# probe and the fault sweep reads the engine's carry without owning it.
compress_state = jax.jit(_compress_state_fn)
expand_state = jax.jit(_expand_state_fn)


# -- direct compact-delta apply ----------------------------------------------
#
# Preemption's evict/restore pairs, timeline departure batches and fault
# drains replay small packed delta batches against the carried state.  With
# a compact carry the naive route is expand ([T, N] floats) → dense delta
# scan → recompress — three full-plane passes to move a handful of counts.
# The delta is instead applied STRAIGHT to the compact form: kind-1 term
# rows are domain-constant, so the dense update (add w on every node of the
# chosen node's domain) collapses to ONE histogram bucket add at
# [row, node_dom_small[key, node]]; dense (kind 0/2) rows and the continuous
# planes take the same per-row updates placement_delta_step issues, routed
# through the inverse row maps below.  Exact under the domain-constancy
# invariant compression already relies on, and exact in integer arithmetic:
# every count delta is an integer-valued f32, so accumulating in COUNT_DTYPE
# equals the dense f32 accumulate + truncating compress cast (pinned
# bit-identical against the expand→apply→recompress route by
# tests/test_state_deltas.py / tests/test_compact.py).
#
# SIMTPU_DELTA_DIRECT=0 falls the engines back to the round-trip route —
# placements and carries are bit-identical either way; the switch exists for
# A/B measurement (`make bench-scan`).


def delta_direct_enabled() -> bool:
    """Default for the engines' compact delta dispatch: SIMTPU_DELTA_DIRECT=0
    re-routes compact preemption deltas through expand→apply→recompress
    (1/unset = direct scatter)."""
    return os.environ.get("SIMTPU_DELTA_DIRECT", "1") != "0"


def node_dom_for(tensors, n: int) -> jnp.ndarray:
    """tensors.node_dom as a device array whose node axis is padded to `n`
    with -1 (absent), the full-domain-id companion of node_dom_small_for —
    the direct delta path gathers both maps at the chosen node, and sharded
    engines hand it a shard-padded carry width.  Memoized per width."""
    cache = getattr(tensors, "_ndom_pad_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(tensors, "_ndom_pad_cache", cache)
    got = cache.get(n)
    if got is None:
        ndom = np.asarray(tensors.node_dom, np.int32)
        if not ndom.shape[0]:
            ndom = np.full((1, ndom.shape[1]), -1, np.int32)
        pad = n - ndom.shape[1]
        if pad:
            ndom = np.pad(ndom, ((0, 0), (0, pad)), constant_values=-1)
        got = cache[n] = jnp.asarray(ndom)
    return got


class CompactDeltaSpec(NamedTuple):
    """Inverse row maps for scattering deltas into a CompactState: term axis
    → carried-plane row (or -1 when the term has no row on that plane).
    Device-resident, constant per tensors (memoized)."""

    t_tab_of: jnp.ndarray  # [T] → row in cm_tab, -1 if dense
    t_dense_of: jnp.ndarray  # [T] → row in cm_dense, -1 if tabular
    ip_tab_of: jnp.ndarray  # [Ti] → row in the *_tab interpod planes
    ip_dense_of: jnp.ndarray  # [Ti] → row in the *_dense interpod planes


def compact_delta_spec(tensors) -> CompactDeltaSpec:
    """The (memoized) inverse of compact_spec's row partition — built once
    host-side from the same t_tab/t_dense/ip_tab/ip_dense orderings so
    scatter targets agree with compression's row layout by construction."""
    cached = getattr(tensors, "_compact_delta_spec_cache", None)
    if cached is not None:
        return cached
    dev = compact_spec(tensors).dev
    t = int(tensors.n_terms)

    def inverse(ids, size):
        ids = np.asarray(ids, np.int32)
        of = np.full(size, -1, np.int32)
        of[ids] = np.arange(len(ids), dtype=np.int32)
        return jnp.asarray(of)

    ti = int(len(np.asarray(dev.ip_tab)) + len(np.asarray(dev.ip_dense)))
    spec = CompactDeltaSpec(
        t_tab_of=inverse(dev.t_tab, t),
        t_dense_of=inverse(dev.t_dense, t),
        ip_tab_of=inverse(dev.ip_tab, ti),
        ip_dense_of=inverse(dev.ip_dense, ti),
    )
    object.__setattr__(tensors, "_compact_delta_spec_cache", spec)
    return spec


def _scatter_rows_add(plane, rows, delta):
    """plane.at[rows].add(delta) with -1 rows masked to no-ops, casting the
    integer-valued f32 delta to the plane's dtype (exact below 2^24)."""
    return plane.at[jnp.clip(rows, 0)].add(
        jnp.where((rows >= 0)[:, None], delta, 0.0).astype(plane.dtype)
    )


def compact_delta_step(statics, dspec, ndom, nds, cstate: CompactState, entry):
    """placement_delta_step retargeted at the compact carry: identical
    continuous-plane updates (cast to the narrowed dtypes), topology counts
    as single-bucket histogram adds for tabular rows and [Rd, N]-row
    scatters for dense rows.  `ndom`/`nds` are the node_dom / node_dom_small
    maps at the CARRY's node width (shard-padded when the engine pads)."""
    g, node, w, req, vg_alloc, sdev_take, gpu_vec = entry
    safe = jnp.clip(node, 0)
    cd = COUNT_DTYPE
    updates = {"free": cstate.free.at[safe].add(-req * w)}
    if cstate.ports_used.shape[1]:
        updates["ports_used"] = cstate.ports_used.at[safe].add(
            (statics.ports_req[g] * w).astype(cd)
        )
    if cstate.vols_any.shape[1]:
        v_rw = statics.vol_rw_req[g]
        v_present = v_rw | statics.vol_ro_req[g] | statics.vol_att_req[g]
        updates["vols_any"] = cstate.vols_any.at[safe].add(
            (v_present * w).astype(cd)
        )
        updates["vols_rw"] = cstate.vols_rw.at[safe].add((v_rw * w).astype(cd))
    if cstate.vg_free.shape[1]:
        updates["vg_free"] = cstate.vg_free.at[safe].add(-vg_alloc * w)
    if cstate.sdev_free.shape[1]:
        row = cstate.sdev_free[safe]
        row = jnp.where(w > 0, row & ~sdev_take, row | sdev_take)
        updates["sdev_free"] = cstate.sdev_free.at[safe].set(row)
    if cstate.gpu_free.shape[1]:
        updates["gpu_free"] = cstate.gpu_free.at[safe].add(-gpu_vec * w)
    t_cap = statics.g_terms.shape[1]
    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        keys = jnp.clip(jnp.where(tvalid, statics.term_topo[tsafe], 0), 0)
        # domain of the chosen node under each term's key, in both the full
        # (node_dom) and small (node_dom_small) numbering — they agree on
        # validity, and the small id IS the histogram bucket
        dom_ch = jnp.where(tvalid, ndom[keys, safe], -1)
        ds_ch = jnp.where(tvalid, nds[keys, safe], -1)
        valid_ch = dom_ch >= 0
        s_val = statics.s_match[g] * jnp.where(valid_ch, w, 0.0)
        updates["cnt_total"] = cstate.cnt_total.at[tsafe].add(s_val.astype(cd))
        # tabular rows: the dense update adds the same value on every node
        # of the chosen domain, and compression gathers one representative —
        # so the whole row update is one bucket add at the small domain id
        t_row = jnp.where(tvalid, dspec.t_tab_of[tsafe], -1)
        tab_ok = (t_row >= 0) & (ds_ch >= 0) & valid_ch
        updates["cm_tab"] = cstate.cm_tab.at[
            jnp.clip(t_row, 0), jnp.clip(ds_ch, 0)
        ].add(jnp.where(tab_ok, s_val, 0.0).astype(cd))
        ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)
        wv = jnp.where(valid_ch, w, 0.0)
        ip_vals = (
            ("oa_tab", "oa_dense", statics.a_anti_req[g].astype(jnp.float32)),
            ("of_tab", "of_dense", statics.a_aff_req[g].astype(jnp.float32)),
            ("wa_tab", "wa_dense", statics.w_aff_pref[g]),
            ("wn_tab", "wn_dense", statics.w_anti_pref[g]),
        )
        if cstate.oa_tab.shape[0]:
            ip_row = jnp.where(
                ip_eff >= 0, dspec.ip_tab_of[jnp.clip(ip_eff, 0)], -1
            )
            ipt_ok = (ip_row >= 0) & (ds_ch >= 0) & valid_ch
            for tabf, _, vals in ip_vals:
                updates[tabf] = getattr(cstate, tabf).at[
                    jnp.clip(ip_row, 0), jnp.clip(ds_ch, 0)
                ].add(jnp.where(ipt_ok, vals * wv, 0.0).astype(cd))
        if cstate.cm_dense.shape[0] or cstate.oa_dense.shape[0]:
            # dense (kind 0/2) rows keep the per-node same-domain compare —
            # exactly placement_delta_step's, routed to the carried rows
            dom_sub = ndom[keys]  # [Tc, Ncarry]
            same = (
                (dom_sub >= 0)
                & tvalid[:, None]
                & (dom_sub == dom_ch[:, None])
                & valid_ch[:, None]
            )
            inc = jnp.where(same, w, 0.0)
            if cstate.cm_dense.shape[0]:
                d_row = jnp.where(tvalid, dspec.t_dense_of[tsafe], -1)
                updates["cm_dense"] = _scatter_rows_add(
                    cstate.cm_dense, d_row, statics.s_match[g][:, None] * inc
                )
            if cstate.oa_dense.shape[0]:
                ipd_row = jnp.where(
                    ip_eff >= 0, dspec.ip_dense_of[jnp.clip(ip_eff, 0)], -1
                )
                for _, densef, vals in ip_vals:
                    updates[densef] = _scatter_rows_add(
                        getattr(cstate, densef), ipd_row, vals[:, None] * inc
                    )
    return cstate._replace(**updates), ()


def _apply_placement_deltas_compact_fn(statics, dspec, ndom, nds, cstate, entries):
    cstate, _ = jax.lax.scan(
        partial(compact_delta_step, statics, dspec, ndom, nds), cstate, entries
    )
    return cstate


# NON-donating, like compress/expand above: the compact carry is routinely
# shared (the incremental planner hands one snapshot to every probe engine,
# the fault sweep reads the engine's carry without owning it) — donating it
# here would invalidate those aliases.  The copy is of the SMALL form, still
# a large net win over the dense round-trip.
apply_placement_deltas_compact = jax.jit(_apply_placement_deltas_compact_fn)


def ensure_dense(state, tensors):
    """The dense SchedState view of a FREE-STANDING carried state
    (expanding a CompactState through the memoized spec; dense states
    pass through).  For reading an ENGINE's carry use
    `Engine.carried_state()` instead — it enforces the dirty-carry and
    vocabulary-change preconditions this helper, which has no engine to
    consult, cannot."""
    if not isinstance(state, CompactState):
        return state
    spec = compact_spec(tensors)
    return expand_state(
        spec.dev, state, node_dom_small_for(tensors, state.free.shape[0])
    )


def diff_state_planes(a, b) -> list:
    """Names of carried planes whose values differ between two DENSE
    states, each tagged with its max absolute difference (or the shape
    mismatch) — the "differing state planes" witness of a divergence
    diagnostic (simtpu/audit): when a plan fails its audit and the serial
    fallback answers differently, this names WHICH state the diverging
    engine corrupted.  Audit-readable view only: callers hand in
    `Engine.carried_state()` / `build_state` outputs, never raw carries."""
    out = []
    for name, x, y in zip(a._fields, a, b):
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape != y.shape:
            out.append(f"{name}: shape {x.shape} vs {y.shape}")
        elif x.size and not np.array_equal(x, y):
            delta = np.max(np.abs(x.astype(np.float64) - y.astype(np.float64)))
            out.append(f"{name}: max|d|={float(delta):g}")
    return out


def state_nbytes(state) -> dict:
    """Per-plane byte sizes of a carried state (SchedState or CompactState)
    — shape/dtype arithmetic only, no device sync."""
    return {
        name: int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize
        for name, arr in zip(state._fields, state)
    }


# Carried-state byte gauge: refreshed by Engine.place each time it stores a
# carry, read by bench.py (`state_bytes`) and the CLI's --json engine block.
# `dense_bytes` is what the SAME carry costs in the dense layout (the A/B
# denominator); `compact` records which form is stored.  Backing store
# since ISSUE 8: obs metrics registry gauges `state.carried_bytes` /
# `state.dense_bytes` / `state.compact` / `state.planes` — read them via
# `obs.metrics.family("state", STATE_KEYS)` (the legacy `state_gauge()`
# alias view is gone).
STATE_KEYS = ("carried_bytes", "dense_bytes", "compact", "planes")


def update_state_gauge(stored, dense_bytes: int) -> None:
    from ..obs.metrics import REGISTRY

    planes = state_nbytes(stored)
    REGISTRY.gauge("state.carried_bytes").set(sum(planes.values()))
    REGISTRY.gauge("state.dense_bytes").set(int(dense_bytes))
    REGISTRY.gauge("state.compact").set(isinstance(stored, CompactState))
    REGISTRY.gauge("state.planes").set(planes)
