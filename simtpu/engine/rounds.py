"""Bulk placement engine: whole same-spec pod runs per compiled call.

The serial scan (`scan.py`) pays a fixed per-pod step cost, which bounds it
to ~10k pods/s regardless of how small the step gets. Real app lists are
dominated by *runs of identical pods* (a Deployment's replicas expand to the
same group and request, `workloads/expand.py`), and for those the whole run
can be placed in one round:

1. evaluate the filter cascade + score once for the run's pod spec
   (`scan.filter_and_score` — the same code the serial scan uses);
2. estimate each node's per-additional-pod score slope by re-scoring a
   hypothetical state in which every node received one such pod
   (resource/topology terms are node-local; normalization denominators stay
   at round-start values);
3. cap each node's intake: free resources / request; hostPort or exclusive
   read-write volume requests cap a node at one pod of the run;
4. pick the k best (node, slot) virtual placements from the per-node
   arithmetic sequences `score_n - m * slope_n` with a device-side threshold
   search (O(N log) — no [N x k] matrix, no per-pod work);
5. apply all state updates at once (free, ports/volumes, topology counts via
   one per-domain segment reduction per round).

Placement is *feasibility-exact* — the caps enforce every hard constraint
the serial engine enforces for these pods, and nothing is ever overcommitted
— but score-approximate: scores within a round use round-start normalizers,
so tie-breaking against the serial scan can differ, and under VG/device
fragmentation a different packing can strand or save a final pod of a run
(placed-count divergence bounded to a sliver in the equivalence fuzz;
the reference itself breaks score ties randomly,
`core/generic_scheduler.go:188-209`, so exact counts are not reproducible
even reference-vs-reference). Extended-resource runs ride the bulk path when each
pod consumes one slot of one container: a single LVM claim (named or
binpack), a single exclusive-device claim, or gpu_count == 1 without a
preset gpu-index — per-node intake caps are then sums of per-container slot
counts, and the greedy fill visits containers tightest-first like the serial
kernels. Runs whose pods interact with each other through exactly one
self-matching hard constraint term (DoNotSchedule topology spread and/or
required anti-affinity selecting the run's own labels) ride a DOMAIN-QUOTA
round variant: a per-domain water-fill reproduces the serial maxSkew /
one-per-domain semantics (`_quota_fill`). Self-matching required AFFINITY
(colocate-with-self) rides the plain threshold round with a domain
restriction: the eligible-domain set (domains already holding a matching
pod, `interpodaffinity/filtering.go` satisfyPodAffinity) is round-CONSTANT
— the run's own placements only deepen already-eligible domains — and in
the first-pod bootstrap case (no matching pod anywhere) the round is
confined to the domain of the best-scoring feasible node, exactly where
the serial scan's first pod would open the series.

MATRIX rounds (`ext_mats=True`) lift the one-slot-of-one-container
restriction for three more extended-resource shapes:
- MULTI-GPU (gpu_count > 1): the serial two-pointer greedy
  (`gpunodeinfo.go:271-288`) consumes per-device share capacities
  floor(free/mem) strictly in device-index order, so consecutive identical
  pods take consecutive share-pool prefixes — per-node intake is
  floor(pool/count), exactly, and each pod's per-device share split is
  interval arithmetic on the round-start cumulative capacities.
- PRESET gpu-index: the recorded assignment is honored verbatim without a
  per-device memory re-check (`gpunodeinfo.go:247-253`), so the GPU axis
  never caps intake; every pod consumes the preset share vector.
- MULTI-CLAIM LVM: every pod of the round reuses the ROUND-START binpack
  plan (`lvm_plan`'s claim-by-claim placement for the first pod); intake is
  capped so no VG overcommits. The serial engine re-binpacks per pod, so
  under fragmentation its packing can drift from the static plan — same
  divergence class as the round-start score normalizers, bounded by the
  equivalence fuzz, and the leftover probes recover any stranded remainder
  through the serial step.
Matrix rounds return dense per-slot allocation matrices ([k, V] LVM bytes,
[k, GD] GPU shares) instead of single container indices. Runs with
multiple self-matching hard terms, multi-device-claim demands, gpu-mem
without gpu-count, claims naming VGs no node carries, or forced/pinned
pods fall back to the serial scan pod-by-pod, so correctness never rests
on the bulk path. Pods a round cannot place are retried through the serial
step, which also produces their exact failure reason.

The reference has no analog — it schedules strictly pod-at-a-time
(`pkg/simulator/simulator.go:219-244`); this is the TPU-shaped replacement
SURVEY.md §2.3 sketches ("greedy parallel rounds ... verified against scan").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensorize import DOM_SMALL
from ..durable.backoff import is_resource_exhausted, record_backoff
from ..kernels.filters import _RES_EPS, interpod_filter, topology_spread_filter
from ..obs.trace import span
from .scan import (
    Engine,
    SchedState,
    StaticArrays,
    StepFlags,
    _pow2_up,
    add_rows,
    count_trace,
    fetch_outputs,
    filter_and_score,
    pad_pods_pow2,
    score_pod,
    take_rows,
    take_rows_i32,
)

# plain floats: a module-level jnp constant would initialize the JAX backend
# at import time, before callers can pick a platform
_NEG = -3.4e38
_BIG = 3.4e38


def _floor_slots(free: jnp.ndarray, size) -> jnp.ndarray:
    """floor(free / size) guarded against f32 division rounding up across an
    integer boundary (the serial kernels' compare-and-subtract never
    overshoots): if the admitted count would exceed the free space, drop one
    slot. Degenerate lanes (size 0/negative free) are masked by the caller."""
    c = jnp.floor(free / jnp.maximum(size, 1e-30))
    return jnp.where(c * size > free, c - 1.0, c)


# single jitted home in scan.py (the chunked serial scan flushes through it
# too); re-exported here for the bulk-chunk path
from .scan import _scatter_rows  # noqa: E402


def _fill_order(cap_x: jnp.ndarray, free_x: jnp.ndarray):
    """Greedy fill sequence over a node's containers (VGs / GPU devices):
    tightest-first means containers are visited in ascending initial free
    order — a partially-filled tightest container has strictly less free
    than it started with, so it stays tightest until exhausted — taking
    cap_x[v] pods each. Returns (perm [N, X, X] one-hot visit permutation,
    order [N, X] visit order, c_sorted, cum_sorted) for the rank arithmetic
    of caps, updates, and per-slot picks. The permuted reads/writes run as
    one-hot einsums: per-element take_along_axis/scatter over the container
    axis lowered to latency-bound kernels costing milliseconds per round,
    while X is tiny (≤ a handful of VGs/devices) so the [N, X, X] products
    run at bandwidth."""
    key = jnp.where(cap_x > 0, free_x, _BIG)
    order = jnp.argsort(key, axis=1)  # stable: ties by index, like the serial argmin
    perm = jax.nn.one_hot(order, cap_x.shape[1], dtype=jnp.float32)
    c_sorted = jnp.einsum(
        "nvw,nw->nv", perm, cap_x, precision=jax.lax.Precision.HIGHEST
    )
    return perm, order, c_sorted, jnp.cumsum(c_sorted, axis=1)


def _unsort_take(m_n, perm, c_sorted, cum_sorted):
    """Pods per container given m_n pods on each node, mapped back from the
    sorted visit order to container positions. [N, X]."""
    take_sorted = jnp.clip(m_n[:, None] - (cum_sorted - c_sorted), 0.0, c_sorted)
    return jnp.einsum(
        "nvw,nv->nw", perm, take_sorted, precision=jax.lax.Precision.HIGHEST
    )


def _quota_fill(
    statics: StaticArrays,
    state: SchedState,
    ev,
    g,
    cap: jnp.ndarray,  # [N] per-node resource/exclusive/extended intake caps
    k,  # i32 run length
    tsafe,
    tvalid,
    dom_sub,  # [Tc, N] node domain per relevant term
    valid_sub,  # [Tc, N]
    n_domains: int,
    flags: StepFlags,
) -> jnp.ndarray:
    """Per-node intake m_n for a run with ONE self-matching hard term t*.

    Serial semantics being reproduced (`kernels/filters.py`):
    - DoNotSchedule spread: each placement needs count(dom)+1-min_elig ≤
      maxSkew, with the eligible-domain minimum RISING as the run fills —
      a level ladder pours every domain up to (current min + maxSkew) per
      iteration, which is always legal (the min never decreases), and stops
      exactly where the serial filter would strand the remainder.
    - Required self-anti-affinity: at most one pod per domain, none where a
      matching pod or an anti-owner already sits; nodes missing the topology
      key are unconstrained (the serial filter treats them as conflict-free).
    The run's OTHER constraint terms are round-constant (no self-match) and
    stay enforced through the start-of-round masks; t*'s own filter is
    lifted and owned by the quota. Total intake is provably order-invariant
    (each placement consumes exactly one unit of its domain's capacity), so
    placed counts track the serial engine; node choice within a level is
    index-ordered, not score-ordered (documented divergence).
    """
    t_cap = statics.g_terms.shape[1]
    f = flags
    # locate the single self-matching hard term on the compacted axis
    self_hard = statics.s_match[g] & (
        statics.a_anti_req[g] | (statics.spread_hard[g] > 0)
    ) & tvalid
    t_star = jnp.argmax(self_hard).astype(jnp.int32)
    onehot = jnp.arange(t_cap) == t_star
    skew = statics.spread_hard[g][t_star]
    use_skew = skew > 0
    anti = statics.a_anti_req[g][t_star]
    dom_t = dom_sub[t_star]  # [N] global domain id for t*'s key (-1 absent)
    valid_t = valid_sub[t_star]
    cnt_sub = take_rows(state.cnt_match, jnp.where(tvalid, tsafe, -1))
    cnt_t = cnt_sub[t_star]
    ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)
    own_t = take_rows(state.cnt_own_anti, ip_eff)[t_star]

    # -- base feasibility: every constraint EXCEPT t*'s own filter --------
    base = ev.m_gpu
    if f.spread_hard:
        sh_excl = statics.spread_hard[g] * (~onehot)
        base = base & topology_spread_filter(
            cnt_sub, valid_sub, sh_excl, ev.m_static
        )
        # t*'s missing-key infeasibility survives the lift for spread terms
        base = base & (valid_t | ~use_skew)
    if f.interpod_req:
        base = base & interpod_filter(
            cnt_sub,
            take_rows(state.cnt_own_anti, ip_eff),
            valid_sub,
            jnp.where(tvalid, state.cnt_total[tsafe], 0.0),
            statics.s_match[g] & ~onehot,  # t*'s symmetry moves to the quota
            statics.a_aff_req[g],
            statics.a_anti_req[g] & ~onehot,
        )
    cap = jnp.where(base, cap, 0.0)

    # -- domain aggregates over t*'s key ----------------------------------
    d_n = n_domains
    safe_dom = jnp.where(valid_t, dom_t, 0)
    on_key = jnp.where(valid_t, 1.0, 0.0)
    k_dom = jnp.zeros(d_n, jnp.float32).at[safe_dom].add(cap * on_key)
    c_dom = jnp.zeros(d_n, jnp.float32).at[safe_dom].max(cnt_t * on_key)
    own_dom = jnp.zeros(d_n, jnp.float32).at[safe_dom].max(own_t * on_key)
    elig_dom = jnp.zeros(d_n, bool).at[safe_dom].max(valid_t & ev.m_static)
    # required anti: one pod per open domain (no matching pod, no anti-owner)
    open_dom = (c_dom <= 0) & (own_dom <= 0)
    k_dom = jnp.where(anti, jnp.minimum(k_dom, jnp.where(open_dom, 1.0, 0.0)), k_dom)

    # -- level ladder: pour to (min + skew) until stuck or k exhausted ----
    def cond(carry):
        _, rem, go = carry
        return go & (rem > 0)

    def body(carry):
        x, rem, _ = carry
        cc = c_dom + x
        level = jnp.min(jnp.where(elig_dom, cc, _BIG))
        level = jnp.where(level >= _BIG, 0.0, level)
        room = jnp.where(use_skew, jnp.clip(level + skew - cc, 0.0, _BIG), _BIG)
        pour = jnp.minimum(room, k_dom - x)
        # partial pour by ascending domain id when the run length limits
        cum = jnp.cumsum(pour)
        pour = jnp.clip(rem - (cum - pour), 0.0, pour)
        tot = jnp.sum(pour)
        return x + pour, rem - tot, tot > 0

    x_dom, _, _ = jax.lax.while_loop(
        cond, body, (jnp.zeros(d_n, jnp.float32), jnp.float32(k), jnp.bool_(True))
    )

    # -- split each domain's intake across its nodes in index order -------
    n = cap.shape[0]
    key_d = jnp.where(valid_t & (cap > 0), dom_t, d_n)  # keyless/capless last
    order = jnp.argsort(key_d)  # stable: index order within a domain
    key_o = key_d[order]
    cap_o = jnp.where(key_o < d_n, cap[order], 0.0)
    cum_o = jnp.cumsum(cap_o)
    excl_o = cum_o - cap_o  # global exclusive prefix
    is_start = jnp.concatenate(
        [jnp.ones(1, bool), key_o[1:] != key_o[:-1]]
    )
    # per-segment base = the exclusive prefix at the segment's first node,
    # propagated forward (prefixes are nondecreasing, so cummax carries the
    # most recent segment start)
    seg_base = jax.lax.cummax(jnp.where(is_start, excl_o, 0.0))
    before_o = excl_o - seg_base
    x_o = jnp.where(key_o < d_n, x_dom[jnp.clip(key_o, 0, d_n - 1)], 0.0)
    allow_o = jnp.clip(x_o - before_o, 0.0, cap_o)
    allow = jnp.zeros(n, jnp.float32).at[order].set(allow_o)
    # nodes missing t*'s key: unconstrained by the quota (anti semantics);
    # for spread terms `base` already zeroed their caps
    m_n = jnp.where(valid_t, allow, cap)
    # run-length clamp by ascending node index (keyless-node intake and the
    # quota allowance may jointly exceed k)
    cum_m = jnp.cumsum(m_n)
    return jnp.clip(jnp.float32(k) - (cum_m - m_n), 0.0, m_n)


def _round_core(
    statics: StaticArrays,
    state: SchedState,
    pod,  # the run's representative pod tuple (scan.build_pod_arrays layout)
    k,  # i32 scalar: number of pods in the run (0 = padding no-op)
    slots,  # [k_cap] f32 iota — virtual slot ids for the assignment expansion
    n_domains: int,
    flags: StepFlags = StepFlags(),
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    """Place up to k identical pods in one round.

    Returns (new_state, assign [k_cap], vg_idx [k_cap], dev_idx [k_cap],
    gpu_idx [k_cap]): slot j holds the node index of the round's j-th placed
    pod (-1 past the placed count) and, for runs with extended-resource
    demands, the VG / storage-device / GPU-device index the pod's single
    claim landed on (-1 when the pod has no such demand).

    `self_aff=True` compiles the SELF-AFFINITY variant for runs whose only
    self-matching hard term is a required affinity (colocate-with-self):
    outside the bootstrap case the start-of-round interpod mask already
    pins the run to its round-constant eligible domains; in the bootstrap
    case (no matching pod cluster-wide) the round is confined to the
    domain of the best-scoring feasible node. `ext_mats=True` compiles the
    MATRIX variant (multi-GPU / preset gpu-index / multi-claim LVM; module
    docstring) whose outputs are (assign [k_cap], dev_idx [k_cap],
    lvm_mat [k_cap, V] bytes, gpu_mat [k_cap, GD] shares).

    `quota=True` compiles the DOMAIN-QUOTA variant for runs whose pods
    interact with each other through exactly one self-matching hard
    constraint term (DoNotSchedule topology spread and/or required
    anti-affinity whose selector matches the run's own labels — the host
    classifier `_group_bulk_kind` guarantees exactly one such term). The
    per-node score-threshold intake is replaced by a per-domain water-fill:
    a level ladder pours pods domain by domain exactly as far as the serial
    maxSkew / ≤1-per-domain semantics allow (the constraint's own start-of-
    round filter is lifted — the ladder supersedes it, re-raising the
    eligible-domain minimum as it fills the way the serial filter would),
    then each domain's intake is split across its nodes in index order.
    Feasibility-exact like the plain round; within-run node choice is
    level/index-ordered rather than score-ordered (documented divergence,
    same class as the plain round's round-start normalizers).
    """
    (
        g,
        req,
        pin,
        forced,
        lvm_size,
        lvm_vg,
        dev_size,
        dev_media,
        gpu_mem,
        gpu_count,
        gpu_preset,
    ) = pod
    f = flags
    # the topology count state is only read when some topology feature is
    # compiled in — skip its (scatter-heavy) update entirely otherwise
    use_topo = f.spread_hard or f.spread_soft or f.selector_spread or f.interpod_req or f.interpod_pref
    t_cap = statics.g_terms.shape[1] if use_topo else 0
    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        dom_sub = take_rows_i32(
            statics.node_dom, jnp.where(tvalid, statics.term_topo[tsafe], -1)
        )  # [Tc, N]
        valid_sub = (dom_sub >= 0) & tvalid[:, None]

    ev = filter_and_score(statics, state, pod, flags)

    # -- per-node intake caps --------------------------------------------
    # same relative slack as filters.resources_fit, so a node that passes
    # the serial filter within tolerance also gets a non-zero bulk cap
    with_req = req > 0
    slack = _RES_EPS * jnp.maximum(jnp.abs(state.free), 1.0)
    ratio = jnp.where(
        with_req[None, :],
        jnp.floor((state.free + slack) / jnp.maximum(req, 1e-30)[None, :]),
        _BIG,
    )
    cap = jnp.min(ratio, axis=1)
    # a second pod of the run on one node would collide on its hostPorts or
    # exclusive read-write volumes
    exclusive = jnp.zeros((), bool)
    if f.ports:
        exclusive = exclusive | jnp.any(statics.ports_req[g])
    if f.vols:
        exclusive = exclusive | jnp.any(statics.vol_rw_req[g])
    cap = jnp.where(exclusive, jnp.minimum(cap, 1.0), cap)

    # extended-resource intake caps: segment eligibility (`_segments`)
    # guarantees at most ONE active LVM claim, ONE device claim, and
    # gpu_count == 1 without a preset, so each pod consumes one slot of one
    # container and the per-node capacity is a plain sum of per-container
    # slot counts (VERDICT r1 task 2; vendored semantics:
    # open-local algo/common.go:59-144, open-gpu-share gpunodeinfo.go:231-291)
    if f.storage:
        li = jnp.argmax(lvm_size)
        l_size, l_vid = lvm_size[li], lvm_vg[li]
        has_lvm = l_size > 0
        vg_exists = statics.vg_name_id >= 0
        elig_vg = vg_exists & jnp.where(
            l_vid >= 0, statics.vg_name_id == l_vid, True
        )
        c_vg = jnp.where(
            has_lvm & elig_vg & (state.vg_free >= l_size),
            _floor_slots(state.vg_free, l_size),
            0.0,
        )
        cap_lvm = jnp.sum(c_vg, axis=1)
        if ext_mats:
            # multi-claim: every pod reuses the round-start plan
            # (ev.lvm_alloc — the serial first-pod binpack); the per-node
            # intake is the tightest VG's slot count under that plan
            multi_lvm = jnp.sum(lvm_size > 0) > 1
            used_st = ev.lvm_alloc > 0
            slots_st = jnp.where(
                used_st,
                _floor_slots(state.vg_free, jnp.maximum(ev.lvm_alloc, 1e-30)),
                _BIG,
            )
            cap_m = jnp.where(
                jnp.any(used_st, axis=1), jnp.min(slots_st, axis=1), 0.0
            )
            cap_lvm = jnp.where(multi_lvm, cap_m, cap_lvm)
        cap = jnp.where(has_lvm, jnp.minimum(cap, cap_lvm), cap)
        perm_vg, ord_vg, cs_vg, cum_vg = _fill_order(c_vg, state.vg_free)

        di = jnp.argmax(dev_size)
        d_size, d_media = dev_size[di], dev_media[di]
        has_dev = d_size > 0
        # exclusive devices are unit-capacity containers visited in
        # ascending capacity (tightest-fit) — same fill machinery as VGs
        c_dev = jnp.where(
            has_dev
            & state.sdev_free
            & (statics.sdev_media == d_media)
            & (statics.sdev_cap >= d_size),
            1.0,
            0.0,
        )
        cap = jnp.where(has_dev, jnp.minimum(cap, jnp.sum(c_dev, axis=1)), cap)
        perm_dev, ord_dev, cs_dev, cum_dev = _fill_order(c_dev, statics.sdev_cap)
    if f.gpu:
        is_gpu = gpu_mem > 0
        free_g = jnp.where(statics.gpu_dev_exists, state.gpu_free, -1.0)
        c_gpu = jnp.where(
            is_gpu & (free_g >= gpu_mem), _floor_slots(free_g, gpu_mem), 0.0
        )
        cap_gpu = jnp.sum(c_gpu, axis=1)
        if ext_mats:
            gpu_multi = gpu_count > 1
            has_preset = jnp.sum(gpu_preset) > 0
            count_f = jnp.maximum(gpu_count.astype(jnp.float32), 1.0)
            # multi-GPU: identical pods consume consecutive prefixes of the
            # index-ordered share pool (module docstring) — intake is the
            # pool size over the per-pod share count
            cum_gpu_idx = jnp.cumsum(c_gpu, axis=1)  # [N, GD] index order
            cap_gpu = jnp.where(
                gpu_multi, jnp.floor(cap_gpu / count_f), cap_gpu
            )
            # preset: honored verbatim, never caps (resource caps and the
            # start-of-round gpu filter still bound the intake)
            cap_gpu = jnp.where(has_preset, _BIG, cap_gpu)
        cap = jnp.where(is_gpu, jnp.minimum(cap, cap_gpu), cap)
        perm_gpu, ord_gpu, cs_gpu, cum_gpu = _fill_order(c_gpu, free_g)

    if quota and t_cap:
        m_n = _quota_fill(
            statics, state, ev, g, cap, k,
            tsafe, tvalid, dom_sub, valid_sub, n_domains, flags,
        )
    else:
        cap = jnp.where(ev.m_all, cap, 0.0)
        if self_aff and t_cap:
            # colocate-with-self: outside the bootstrap, ev.m_all already
            # pins the round to the (round-constant) domains holding a
            # matching pod; in the bootstrap case (no matching pod
            # cluster-wide, filters.py first-pod escape) confine the round
            # to the domain the serial first pod would open — that of the
            # best-scoring feasible node
            saff = statics.s_match[g] & statics.a_aff_req[g] & tvalid
            t_star_a = jnp.argmax(saff).astype(jnp.int32)
            aff_terms = statics.a_aff_req[g] & tvalid
            total_match = jnp.sum(
                jnp.where(aff_terms, state.cnt_total[tsafe], 0.0)
            )
            dom_a = dom_sub[t_star_a]  # [N]
            best = jnp.argmax(jnp.where(cap > 0, ev.score, _NEG))
            d_star = dom_a[best]
            cap = jnp.where(
                total_match <= 0,
                jnp.where((dom_a == d_star) & (dom_a >= 0), cap, 0.0),
                cap,
            )

        # -- score slope: re-score after one hypothetical pod per node ----
        # score-only: the filter cascade need not rerun — the round keeps
        # its start-of-round masks (m_all) and the caps carry the hard
        # constraints. The hypothetical state is expressed as score_pod
        # overrides (free and the group's [Tc, N] cnt_match rows) — bumping
        # a copy of the full [T, N] count plane would copy T/Tc times the
        # touched data every round
        cnt_sub1 = None
        if t_cap:
            bump1 = jnp.where(valid_sub, statics.s_match[g][:, None], 0.0)
            cnt_sub1 = take_rows(state.cnt_match, terms_g) + bump1
        score1 = score_pod(
            statics,
            state,
            g,
            req,
            ev.m_all,
            flags,
            free=state.free - req[None, :],
            cnt_sub=cnt_sub1,
        )
        # slope clamped >= 0: the threshold search needs non-increasing
        # sequences; a genuinely increasing score (rare: balanced_allocation
        # improving) fills one node until capacity under serial semantics,
        # which slope 0 reproduces up to ties. The 1e6 ceiling keeps
        # pathological per-pod drops (free crossing zero) on a finite range.
        # the slope is taken storage-free (ev.score carries the per-node
        # Open-Local binpack term that score1 lacks) so the within-round
        # sequence stays arithmetic; the binpack term still ranks through s0
        slope = jnp.clip(
            jnp.where(ev.m_all, ev.score_nostorage - score1, 0.0), 0.0, 1e6
        )
        s0 = jnp.where(ev.m_all, ev.score, _NEG)

        # -- threshold search: pick the kf best virtual placements --------
        def counts(tau):
            c = jnp.where(
                s0 >= tau,
                jnp.where(
                    slope > 0,
                    jnp.floor((s0 - tau) / jnp.maximum(slope, 1e-30)) + 1.0,
                    cap,  # flat sequence: every slot ties at s0
                ),
                0.0,
            )
            return jnp.minimum(c, cap)

        kf = jnp.minimum(jnp.float32(k), jnp.sum(cap))
        hi = jnp.max(s0)
        # every node's lowest usable virtual slot bounds the k-th best from
        # below: count(lo) = sum(cap) >= kf holds by construction, and the
        # range stays tight (score-scale, not worst-case slope x k), so 40
        # bisection steps resolve far below any real score delta
        low_slot = s0 - slope * jnp.clip(cap - 1.0, 0.0, jnp.float32(k))
        lo = jnp.min(jnp.where(ev.m_all, low_slot, _BIG)) - 1.0

        def body(_, bounds):
            lo, hi = bounds
            mid = 0.5 * (lo + hi)
            over = jnp.sum(counts(mid)) > kf
            return jnp.where(over, mid, lo), jnp.where(over, hi, mid)

        lo, hi = jax.lax.fori_loop(0, 40, body, (lo, hi))
        m_n = counts(hi)  # ~kf placements, every slot scoring above hi
        # clamp overshoot (tie plateaus, k=0 padding) by ascending node index
        cum_m = jnp.cumsum(m_n)
        m_n = jnp.clip(kf - (cum_m - m_n), 0.0, m_n)
        # distribute the remaining tied slots by ascending node index (the
        # serial scan's lowest-index tie-break)
        extra_room = jnp.clip(counts(lo) - m_n, 0.0, None)
        cum = jnp.cumsum(extra_room)
        extra = jnp.clip(kf - jnp.sum(m_n) - (cum - extra_room), 0.0, extra_room)
        m_n = m_n + extra

    # -- batched state update --------------------------------------------
    updates = {"free": state.free - m_n[:, None] * req[None, :]}
    one = jnp.minimum(m_n, 1.0)  # nodes that received >= 1 pod
    if f.ports:
        updates["ports_used"] = state.ports_used + one[:, None] * statics.ports_req[g]
    if f.vols or f.attach:
        v_rw = statics.vol_rw_req[g]
        v_present = v_rw | statics.vol_ro_req[g] | statics.vol_att_req[g]
        updates["vols_any"] = state.vols_any + one[:, None] * v_present
        if f.vols:
            updates["vols_rw"] = state.vols_rw + one[:, None] * v_rw
    if t_cap:
        # per-domain totals of m_n over the group's relevant term rows,
        # broadcast back to every node sharing the domain — routed by key
        # structure: SMALL keys (zone-sized) ride a one-hot einsum over
        # compact per-key ids, UNIQUE keys (hostname) are their own sums,
        # and the [Tc, D] scatter+gather pair (measured ~7.6 ms per round
        # at 100k nodes) compiles in only when some key actually needs it
        topo_eff = jnp.where(tvalid, statics.term_topo[tsafe], -1)
        kind_sub = jnp.where(
            tvalid, statics.key_kind[jnp.clip(topo_eff, 0)], -1
        )  # [Tc]
        contrib = jnp.where(valid_sub, m_n[None, :], 0.0)
        dsm = jnp.where(
            (kind_sub == 1)[:, None],
            take_rows_i32(
                statics.node_dom_small, jnp.where(kind_sub == 1, topo_eff, -1)
            ),
            -1,
        )
        a_oh = jax.nn.one_hot(dsm, DOM_SMALL, dtype=jnp.float32)  # [Tc, N, B]
        sums = jnp.einsum(
            "tnb,tn->tb", a_oh, contrib, precision=jax.lax.Precision.HIGHEST
        )
        y = jnp.einsum(
            "tb,tnb->tn", sums, a_oh, precision=jax.lax.Precision.HIGHEST
        )
        add_n = jnp.where((kind_sub == 2)[:, None], contrib, y)  # [Tc, N]
        if f.dom_fallback:
            fb = (kind_sub == 0)[:, None]
            safe_d = jnp.where(valid_sub & fb, dom_sub, 0)
            t_idx = jnp.arange(t_cap)[:, None]
            contrib_fb = jnp.where(fb, contrib, 0.0)
            dom_m = jnp.zeros((t_cap, n_domains), jnp.float32).at[
                t_idx, safe_d
            ].add(contrib_fb)
            add_n = jnp.where(
                fb, jnp.where(valid_sub, dom_m[t_idx, safe_d], 0.0), add_n
            )

        def bump(arr, vals):
            return add_rows(arr, terms_g, vals[:, None] * add_n)

        s_match_g = statics.s_match[g].astype(jnp.float32)
        updates["cnt_match"] = bump(state.cnt_match, s_match_g)
        updates["cnt_total"] = state.cnt_total.at[tsafe].add(
            s_match_g * (jnp.where(valid_sub, 1.0, 0.0) @ m_n)
        )
        if f.interpod_req or f.interpod_pref:
            # own planes live on the compacted interpod axis (scan.py
            # schedule_step has the same mapping); -1 rows are inert
            # through the one-hot matmul
            ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)

            def bump_ip(arr, vals):
                return add_rows(arr, ip_eff, vals[:, None] * add_n)

        if f.interpod_req:
            updates["cnt_own_anti"] = bump_ip(
                state.cnt_own_anti, statics.a_anti_req[g].astype(jnp.float32)
            )
            updates["cnt_own_aff"] = bump_ip(
                state.cnt_own_aff, statics.a_aff_req[g].astype(jnp.float32)
            )
        if f.interpod_pref:
            updates["w_own_aff_pref"] = bump_ip(state.w_own_aff_pref, statics.w_aff_pref[g])
            updates["w_own_anti_pref"] = bump_ip(
                state.w_own_anti_pref, statics.w_anti_pref[g]
            )
    if f.storage:
        take_vg = _unsort_take(m_n, perm_vg, cs_vg, cum_vg)
        upd_vg = take_vg * l_size
        if ext_mats:
            upd_vg = jnp.where(multi_lvm, m_n[:, None] * ev.lvm_alloc, upd_vg)
        updates["vg_free"] = state.vg_free - upd_vg
        taken_dev = _unsort_take(m_n, perm_dev, cs_dev, cum_dev) > 0
        updates["sdev_free"] = state.sdev_free & ~taken_dev
    if f.gpu:
        take_gpu = _unsort_take(m_n, perm_gpu, cs_gpu, cum_gpu)
        upd_gpu = take_gpu * gpu_mem
        if ext_mats:
            # multi-GPU: the node's m_n pods jointly consume the first
            # m_n*count shares of the index-ordered pool
            total_sh = m_n * count_f
            prev_g = cum_gpu_idx - c_gpu
            pool_take = jnp.clip(
                jnp.minimum(cum_gpu_idx, total_sh[:, None]) - prev_g,
                0.0,
                c_gpu,
            )
            upd_gpu = jnp.where(gpu_multi, pool_take * gpu_mem, upd_gpu)
            upd_gpu = jnp.where(
                has_preset,
                m_n[:, None] * gpu_preset.astype(jnp.float32) * gpu_mem,
                upd_gpu,
            )
        updates["gpu_free"] = state.gpu_free - upd_gpu

    # -- expand per-node intake into per-slot assignments -----------------
    cum_slots = jnp.cumsum(m_n)
    assign = jnp.searchsorted(cum_slots, slots, side="right")
    valid_slot = slots < cum_slots[-1]
    a_safe = jnp.where(valid_slot, assign, 0)
    # the pod's rank within its node's intake drives the container pick
    ordinal = slots - (cum_slots[a_safe] - m_n[a_safe])

    def pick_container(order_x, cum_x):
        """Container index for each slot: rank r such that the node's sorted
        cumulative capacity first exceeds the pod's ordinal."""
        rank = jnp.sum(cum_x[a_safe] <= ordinal[:, None], axis=1)
        rank = jnp.clip(rank, 0, order_x.shape[1] - 1).astype(jnp.int32)
        return jnp.take_along_axis(order_x[a_safe], rank[:, None], axis=1)[:, 0]

    neg = jnp.full(slots.shape, -1, jnp.int32)
    vg_idx = dev_idx = gpu_idx = neg
    if f.storage:
        vg_idx = jnp.where(
            valid_slot & has_lvm, pick_container(ord_vg, cum_vg), -1
        ).astype(jnp.int32)
        dev_idx = jnp.where(
            valid_slot & has_dev, pick_container(ord_dev, cum_dev), -1
        ).astype(jnp.int32)
    if f.gpu:
        gpu_idx = jnp.where(
            valid_slot & is_gpu, pick_container(ord_gpu, cum_gpu), -1
        ).astype(jnp.int32)
    assign = jnp.where(valid_slot, assign, -1).astype(jnp.int32)
    if ext_mats:
        k_cap = slots.shape[0]
        v_n = state.vg_free.shape[1]
        gd_n = state.gpu_free.shape[1]
        lvm_mat = jnp.zeros((k_cap, v_n), jnp.float32)
        if f.storage:
            single_v = (
                jax.nn.one_hot(jnp.clip(vg_idx, 0), v_n, dtype=jnp.float32)
                * l_size
            )
            lvm_mat = jnp.where(
                multi_lvm, ev.lvm_alloc[a_safe], jnp.where(
                    (vg_idx >= 0)[:, None], single_v, 0.0
                )
            )
            lvm_mat = jnp.where(
                valid_slot[:, None] & has_lvm, lvm_mat, 0.0
            )
        gpu_mat = jnp.zeros((k_cap, gd_n), jnp.float32)
        if f.gpu:
            single_g = jnp.where(
                (gpu_idx >= 0)[:, None],
                jax.nn.one_hot(jnp.clip(gpu_idx, 0), gd_n, dtype=jnp.float32),
                0.0,
            )
            # per-slot share split: pool interval [ord*count, (ord+1)*count)
            # intersected with each device's round-start capacity interval
            cum_r = cum_gpu_idx[a_safe]  # [k_cap, GD]
            per_r = c_gpu[a_safe]
            start = ordinal * count_f
            multi_g = jnp.clip(
                jnp.minimum(cum_r, (start + count_f)[:, None])
                - jnp.maximum(cum_r - per_r, start[:, None]),
                0.0,
                per_r,
            )
            gmat = jnp.where(gpu_multi, multi_g, single_g)
            gmat = jnp.where(
                has_preset,
                jnp.broadcast_to(
                    gpu_preset.astype(jnp.float32)[None, :], (k_cap, gd_n)
                ),
                gmat,
            )
            gpu_mat = jnp.where(valid_slot[:, None] & is_gpu, gmat, 0.0)
        return state._replace(**updates), (assign, dev_idx, lvm_mat, gpu_mat)
    return state._replace(**updates), (assign, vg_idx, dev_idx, gpu_idx)


def rounds_scan(
    statics: StaticArrays,
    state: SchedState,
    seg_pods,  # pod-tuple arrays with a leading segment axis [S, ...]
    ks,  # [S] i32 run lengths (0 = padding)
    n_domains: int,
    k_cap: int,  # static max run length: bounds the per-segment output
    flags: StepFlags = StepFlags(),
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    """All consecutive bulk rounds as one lax.scan over the segment axis, so
    a batch of hundreds of deployment runs costs one dispatch and one
    [S, k_cap]-per-output result transfer instead of per-run round trips
    (the per-node intake [S, N] stays on device — at 100k nodes it would be
    a gigabyte-scale host copy). Returns (final_state, (assign, vg_idx,
    dev_idx, gpu_idx) each [S, k_cap]): slot j of segment s holds the node
    index of the segment's j-th placed pod (-1 beyond the placed count) and
    the extended-resource container its single claim landed on (-1 when the
    run has no such demand). With `ext_mats` the per-segment outputs are
    (assign, dev_idx, lvm_mat [S, k_cap, V], gpu_mat [S, k_cap, GD]) — see
    `_round_core`. Unjitted — the local engine jits it directly
    (`_round_place_many`), the sharded engine with mesh shardings
    (`parallel/sharded.py`)."""

    slots = jnp.arange(k_cap, dtype=jnp.float32)

    def body(state, xs):
        pod, k = xs
        return _round_core(
            statics, state, pod, k, slots, n_domains, flags, quota,
            self_aff, ext_mats,
        )

    return jax.lax.scan(body, state, (seg_pods, ks))


@partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9), donate_argnums=(1,))
def _round_place_many(
    statics: StaticArrays,
    state: SchedState,
    seg_pods,
    ks,
    n_domains: int,
    k_cap: int,
    flags: StepFlags = StepFlags(),
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    count_trace("rounds")
    return rounds_scan(
        statics, state, seg_pods, ks, n_domains, k_cap, flags, quota,
        self_aff, ext_mats,
    )


def rounds_scan_sliced(
    statics: StaticArrays,
    state: SchedState,
    rows,  # [r_pad] term rows carried by this chunk
    g_terms_c,  # [G, Tc] term incidence remapped onto the sliced row axis
    term_topo_c,  # [r_pad]
    ip_of_c,  # [r_pad]
    seg_pods,
    ks,
    n_domains: int,
    k_cap: int,
    flags: StepFlags = StepFlags(),
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    """`rounds_scan` with the count-plane row slice/unslice INSIDE the
    traced computation: one device call per chunk does gather → rounds →
    in-place scatter-back, where the eager formulation paid ~6 tunneled
    RPCs per chunk (each with fixed wire latency that dominated the
    stretch cost at 100k nodes — the device itself was ~98% idle).
    Unjitted; the local engine jits it (`_round_place_many_sliced`), the
    sharded engine with mesh shardings."""
    st_c = statics._replace(
        g_terms=g_terms_c, term_topo=term_topo_c, ip_of=ip_of_c
    )
    full_match, full_total = state.cnt_match, state.cnt_total
    state_c = state._replace(
        cnt_match=full_match[rows], cnt_total=full_total[rows]
    )
    state_c, outs = rounds_scan(
        st_c, state_c, seg_pods, ks, n_domains, k_cap, flags, quota,
        self_aff, ext_mats,
    )
    state_out = state_c._replace(
        cnt_match=full_match.at[rows].set(state_c.cnt_match),
        cnt_total=full_total.at[rows].set(state_c.cnt_total),
    )
    return state_out, outs


@partial(jax.jit, static_argnums=(8, 9, 10, 11, 12, 13), donate_argnums=(1,))
def _round_place_many_sliced(
    statics,
    state,
    rows,
    g_terms_c,
    term_topo_c,
    ip_of_c,
    seg_pods,
    ks,
    n_domains: int,
    k_cap: int,
    flags: StepFlags = StepFlags(),
    quota: bool = False,
    self_aff: bool = False,
    ext_mats: bool = False,
):
    count_trace("rounds")
    return rounds_scan_sliced(
        statics, state, rows, g_terms_c, term_topo_c, ip_of_c, seg_pods,
        ks, n_domains, k_cap, flags, quota, self_aff, ext_mats,
    )


class RoundsEngine(Engine):
    """Engine that places eligible same-spec pod runs in bulk rounds and
    routes everything else through the inherited serial scan.

    Drop-in for `Engine` in `simtpu.api.Simulator` via
    `simulate(..., engine_factory=RoundsEngine)` or `plan(..., bulk=True)`.
    """

    #: minimum run length worth a bulk round (shorter runs ride the scan)
    MIN_RUN = 8
    #: maximum pods per bulk round — longer runs split into consecutive
    #: rounds (bounds the [S, k_cap] output and keeps score slopes fresh)
    MAX_RUN = 4096

    def __init__(self, tensorizer):
        super().__init__(tensorizer)
        # Shape-bucket registry: variant key → set of (s_pad, k_cap, r_pad)
        # bulk-chunk shapes this engine (or any engine SHARING the dict —
        # the incremental planner hands one registry to its base, probe and
        # verify engines) has already dispatched, i.e. shapes whose
        # executables are warm. With `snap_shapes`, `_bulk_chunk` pads a
        # chunk UP into the cheapest dominating registered shape instead of
        # compiling its natural pow2 shape — the candidate probe sweep then
        # reuses one executable across every candidate count instead of
        # shape-specializing per probe.
        self.bulk_shapes: dict = {}
        self.snap_shapes: bool = False

    #: snap guard: never pad a chunk into a bucket more than this many times
    #: its natural pow2 segment count (each padded segment is a k=0 no-op
    #: round, which still costs a round of device work)
    SNAP_S_BLOWUP = 8
    #: snap guard on the round capacity: k_cap inflation is cheaper than
    #: segment inflation (the threshold search is k-independent; only the
    #: [k_cap] slot expansion and the [S, k_cap(, V)] outputs grow), but an
    #: unbounded pick could marry a tiny chunk to a MAX_RUN-sized bucket
    SNAP_K_BLOWUP = 64

    # group bulk-path classification codes (`_group_bulk_kind`)
    KIND_SERIAL = 0  # pod-by-pod serial scan only
    KIND_PLAIN = 1  # plain bulk round (threshold search)
    KIND_QUOTA = 2  # domain-quota bulk round (one self-matching hard term)
    KIND_AFF = 3  # self-affinity round (domain-restricted threshold search)

    def _group_bulk_kind(self, tensors, gid: int) -> int:
        """How a group's runs may be placed in bulk.

        PLAIN requires that the run's pods interact with each other only
        through resources/ports/volumes: no hard constraint term whose
        selector matches the run's own labels. Non-self-matching required
        (anti-)affinity and spread terms are round-CONSTANT — the run's own
        placements never change those terms' counts — so they stay on the
        bulk path, enforced by the start-of-round masks
        (`interpodaffinity/filtering.go`, `podtopologyspread/filtering.go`
        semantics; r2 conservatively serialized every required-affinity
        group).

        QUOTA handles exactly ONE self-matching hard term (DoNotSchedule
        spread and/or required anti-affinity on the same interned term) via
        the per-domain water-fill in `_quota_fill`.

        AFF handles exactly ONE self-matching required AFFINITY term
        (colocate-with-self) with no self-matching anti/spread term: the
        eligible-domain set is round-constant outside the bootstrap, so the
        plain threshold round applies under a domain restriction
        (`_round_core` self_aff). Multiple self-matching hard terms over
        different domain partitions remain serial — a joint quota over two
        partitions is a flow problem, not a fill.
        """
        s = tensors.s_match[gid]
        self_aff = s & tensors.a_aff_req[gid]
        self_as = s & (tensors.a_anti_req[gid] | (tensors.spread_hard[gid] > 0))
        n_aff = int(np.count_nonzero(self_aff))
        n_as = int(np.count_nonzero(self_as))
        if n_aff == 0 and n_as == 0:
            return self.KIND_PLAIN
        if n_aff == 0 and n_as == 1:
            return self.KIND_QUOTA
        if n_aff == 1 and n_as == 0:
            return self.KIND_AFF
        return self.KIND_SERIAL

    def _segments(self, batch, tensors):
        """Split the batch index space into ('bulk'|'scan', start, stop).

        Fully vectorized — this runs per batch on up to millions of pods:
        eligibility is a mask, run boundaries are change points of
        (group, req-row, eligible), and consecutive non-bulk runs merge.
        """
        p = len(batch.group)
        if p == 0:
            return []
        ext = batch.ext
        group = np.asarray(batch.group)
        eligible = (np.asarray(batch.pin) == -1) & ~np.asarray(batch.forced)
        # extended-resource pods consuming one slot of one container (a
        # single LVM claim, a single device claim, one GPU share) ride the
        # plain bulk path; multi-claim LVM, multi-GPU, and preset-index
        # pods ride the MATRIX variant (`mats`). Multi-device-claim pods
        # and gpu-mem-without-count pods keep the serial fallback (exact
        # failure reasons / no static per-pod device assignment exists).
        mats = np.zeros(p, bool)
        if ext["lvm_size"].shape[1]:
            mats |= (np.asarray(ext["lvm_size"]) > 0).sum(axis=1) > 1
            # a claim naming a VG no node carries never places; the serial
            # step produces its exact failure reason
            eligible &= ~(np.asarray(ext["lvm_vg"]) == -2).any(axis=1)
        if ext["dev_size"].shape[1]:
            eligible &= (np.asarray(ext["dev_size"]) > 0).sum(axis=1) <= 1
        gpu_mem = np.asarray(ext["gpu_mem"])
        gpu_count = np.asarray(ext["gpu_count"])
        has_gpu = gpu_mem > 0
        eligible &= ~has_gpu | (gpu_count >= 1)
        mats |= has_gpu & (gpu_count > 1)
        if ext["gpu_preset"].shape[1]:
            mats |= has_gpu & (np.asarray(ext["gpu_preset"]).sum(axis=1) > 0)
        group_kind = np.array(
            [self._group_bulk_kind(tensors, gid) for gid in range(len(tensors.groups))],
            np.int32,
        )
        kind = np.where(eligible, group_kind[group], self.KIND_SERIAL)
        mats &= kind != self.KIND_SERIAL

        change = np.zeros(p, bool)
        change[0] = True
        change[1:] = (
            (group[1:] != group[:-1])
            | np.any(batch.req[1:] != batch.req[:-1], axis=1)
            | (kind[1:] != kind[:-1])
            | (mats[1:] != mats[:-1])
        )
        # a run must be spec-homogeneous in its extended demands too (the
        # segment's first pod stands in for every pod of the run)
        for key in ("lvm_size", "lvm_vg", "dev_size", "dev_media", "gpu_preset"):
            arr = np.asarray(ext[key])
            if arr.shape[1]:
                change[1:] |= np.any(arr[1:] != arr[:-1], axis=1)
        for key in ("gpu_mem", "gpu_count"):
            arr = np.asarray(ext[key])
            change[1:] |= arr[1:] != arr[:-1]
        starts = np.flatnonzero(change)
        stops = np.append(starts[1:], p)
        segments = []
        names = {
            self.KIND_PLAIN: "bulk",
            self.KIND_QUOTA: "bulkq",
            self.KIND_AFF: "bulka",
        }
        for a, b in zip(starts.tolist(), stops.tolist()):
            if kind[a] != self.KIND_SERIAL and b - a >= self.MIN_RUN:
                name = names[kind[a]] + ("m" if mats[a] else "")
                for c in range(a, b, self.MAX_RUN):
                    segments.append((name, c, min(c + self.MAX_RUN, b)))
            elif segments and segments[-1][0] == "scan":
                segments[-1] = ("scan", segments[-1][1], b)
            else:
                segments.append(("scan", a, b))
        return segments

    # shared with the chunked serial scan (single home for the pod-tuple
    # padding invariants: pin=-1 / forced=True columns)
    _pad_pods = staticmethod(pad_pods_pow2)
    _pow2 = staticmethod(_pow2_up)

    def _aot_bulk(
        self, n_domains, k_cap, flags, quota=False, self_aff=False,
        ext_mats=False,
    ):
        """(pipeline key name, jit callable, static argument tail) for the
        multi-round bulk executable — the contract `Engine._aot_scan`
        documents, for the bulk path.  Overridden by the sharded subclass
        with its mesh-compiled callables (statics baked into the build)."""
        return "rounds", _round_place_many, (
            n_domains, k_cap, flags, quota, self_aff, ext_mats,
        )

    def _aot_bulk_sliced(
        self, n_domains, k_cap, flags, quota=False, self_aff=False,
        ext_mats=False,
    ):
        """The row-sliced counterpart of `_aot_bulk`."""
        return "rounds_sliced", _round_place_many_sliced, (
            n_domains, k_cap, flags, quota, self_aff, ext_mats,
        )

    def _bulk_call(
        self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
        quota=False, self_aff=False, ext_mats=False,
    ):
        """Dispatch one multi-round bulk call — through the precompile
        pipeline's registry when one is attached, else the plain jit."""
        name, fn, tail = self._aot_bulk(
            n_domains, k_cap, flags, quota, self_aff, ext_mats
        )
        args = (statics, state, seg_pods, ks)
        if self.pipeline is not None:
            return self.pipeline.call(
                name, tail, args, lambda: fn(*args, *tail)
            )
        return fn(*args, *tail)

    def _bulk_call_sliced(
        self, statics, state, rows, g_terms_c, term_topo_c, ip_of_c,
        seg_pods, ks, n_domains, k_cap, flags,
        quota=False, self_aff=False, ext_mats=False,
    ):
        """Dispatch one row-sliced multi-round bulk call — slice, rounds
        and scatter-back fused into one device call."""
        name, fn, tail = self._aot_bulk_sliced(
            n_domains, k_cap, flags, quota, self_aff, ext_mats
        )
        args = (
            statics, state, rows, g_terms_c, term_topo_c, ip_of_c,
            seg_pods, ks,
        )
        if self.pipeline is not None:
            return self.pipeline.call(
                name, tail, args, lambda: fn(*args, *tail)
            )
        return fn(*args, *tail)

    def _run_scan_segment(self, statics, state, pods, a, b, flags):
        # chunked + term-row-sliced (scan.run_scan_chunked): serial
        # fallback segments inside a bulk run get the same count-plane
        # compaction the bulk chunks do
        from .scan import run_scan_chunked

        return run_scan_chunked(
            statics,
            state,
            tuple(arr[a:b] for arr in pods),
            flags,
            self._current_tensors,
            np.asarray(self._current_batch.group)[a:b],
            scan_call=self._scan_call,
            prefetch=self._prefetch_pods,
            wave_call=self._wave_call if self.speculate else None,
        )

    #: carried-row budget per bulk chunk (padded to the next power of two):
    #: each chunk's scan carries only these many cnt-plane rows, so per-round
    #: state traffic is bounded regardless of how many workloads exist
    ROW_BUDGET = 224

    def _host_term_maps(self, tensors):
        from .scan import _compact_terms
        from .state import interpod_term_index

        g_terms, _ = _compact_terms(tensors)
        return g_terms, tensors.term_topo_key, interpod_term_index(tensors)

    #: max bulk runs per MATRIX chunk: bounds the [S, k_cap, V/GD] output
    #: transfer (plain chunks return [S, k_cap] indices and need no bound)
    MATS_CHUNK = 256

    def _chunk_runs(self, run, batch, tensors, max_segs=None):
        """Split a stretch of bulk runs into chunks whose union of relevant
        count-plane terms stays within ROW_BUDGET (and whose length stays
        within max_segs, for matrix rounds); yields (chunk, rows_p) where
        rows_p is the padded term-row list the chunk's scan carries
        (None = carry the full plane, for small term vocabularies)."""
        t = tensors.n_terms
        # chunking only pays when a budget-sized chunk pads to FEWER rows
        # than the full plane; otherwise every chunk would carry the plane
        # anyway and the split just multiplies dispatches
        if self._pow2(min(t, self.ROW_BUDGET)) >= t:
            if max_segs is None:
                yield run, None
            else:
                for c in range(0, len(run), max_segs):
                    yield run[c : c + max_segs], None
            return
        g_terms, _, _ = self._host_term_maps(tensors)
        group = np.asarray(batch.group)
        chunk, rows = [], set()
        for seg in run:
            seg_terms = {
                int(x) for x in g_terms[group[seg[1]]] if x >= 0
            }
            # never split off a chunk that would carry the full plane anyway
            # (rows already past the pow2-under-t point): keep extending it
            if chunk and (
                (
                    len(rows | seg_terms) > self.ROW_BUDGET
                    and self._pow2(len(rows)) < t
                )
                or (max_segs is not None and len(chunk) >= max_segs)
            ):
                yield chunk, self._pad_rows(sorted(rows), t)
                chunk, rows = [], set()
            chunk.append(seg)
            rows |= seg_terms
        if chunk:
            yield chunk, self._pad_rows(sorted(rows), t)

    def _pad_rows(self, rows, t, floor: int = 1):
        """Pad the row list to a power of two (at least `floor`) with
        DISTINCT unused term ids (their gathered values pass through the
        scan unchanged, so the scatter-back is a no-op for them; duplicate
        indices in a scatter would let a stale copy overwrite the updated
        row). Returns None when the target cannot fit in t: a clamped,
        non-pow2 row count would defeat the shape bucketing and recompile
        per chunk — carrying the full plane keeps the compiled-shape set
        bounded."""
        rows = np.asarray(rows, np.int32)
        u_pad = max(self._pow2(len(rows)), floor)
        if u_pad >= t:  # padding to >= the full plane = just carry the plane
            return None
        pad = u_pad - len(rows)
        if pad > 0:
            unused = np.setdiff1d(
                np.arange(t, dtype=np.int32), rows, assume_unique=False
            )[:pad]
            rows = np.concatenate([rows, unused])
        return rows

    @staticmethod
    def _kind_flags(bkind: str):
        """(quota, self_aff, ext_mats) for a bulk segment kind — the single
        mapping both the dispatcher and the AOT enumerator use."""
        return (
            bkind in ("bulkq", "bulkqm"),
            bkind in ("bulka", "bulkam"),
            bkind.endswith("m"),
        )

    @staticmethod
    def _stretch_group(segments, idx: int):
        """Consume the maximal run of consecutive NON-scan segments at
        `idx` into [(bulk kind, [same-kind segments]), ...]; returns
        (group_runs, next idx).  Shared by `_dispatch` and the AOT
        enumerator — the two walks must agree for the registry to hit."""
        group_runs = []
        while idx < len(segments) and segments[idx][0] != "scan":
            bkind = segments[idx][0]
            run = []
            while idx < len(segments) and segments[idx][0] == bkind:
                run.append(segments[idx])
                idx += 1
            group_runs.append((bkind, run))
        return group_runs, idx

    def _group_work_items(self, group_runs, batch, tensors):
        """Yield (chunk, rows_p, quota, self_aff, ext_mats) per bulk chunk
        of one stretch group, in dispatch order — the work list both the
        dispatcher executes and the AOT enumerator compiles ahead of it."""
        for bkind, run in group_runs:
            quota, self_aff, ext_mats = self._kind_flags(bkind)
            for chunk, rows_p in self._chunk_runs(
                run, batch, tensors,
                max_segs=self.MATS_CHUNK if ext_mats else None,
            ):
                yield chunk, rows_p, quota, self_aff, ext_mats

    def _chunk_shape(
        self, chunk, rows_p, tensors, flags,
        quota=False, self_aff=False, ext_mats=False, ks=None,
    ):
        """The (s_pad, k_cap, rows_p) shape one chunk of bulk runs will
        dispatch at, with bucket snapping and registry bookkeeping: snap
        the chunk's natural pow2 shape UP into the cheapest
        already-compiled dominating bucket of the same variant, so planner
        probes reuse warm executables across candidate sizes instead of
        shape-specializing per probe (padded segments are k=0 no-op
        rounds; padded term rows ride along unchanged).  Deterministic
        given the registry state — the AOT precompiler walks the same
        sequence ahead of the dispatches, so every shape it registers here
        is one the dispatch path can land on."""
        s_pad = self._pow2(len(chunk))
        if ks is None:
            ks = np.array([j0 - i0 for _, i0, j0 in chunk], np.int32)
        k_cap = self._pow2(int(ks.max()))
        t = int(tensors.n_terms)
        variant = (quota, self_aff, ext_mats, rows_p is not None, flags)
        r_nat = 0 if rows_p is None else len(rows_p)
        shapes = self.bulk_shapes.setdefault(variant, set())
        if self.snap_shapes:
            cand = [
                (s, k, rr)
                for (s, k, rr) in shapes
                if s >= s_pad
                and k >= k_cap
                and rr >= r_nat
                and s <= max(8, self.SNAP_S_BLOWUP * s_pad)
                and k <= max(self.MIN_RUN, self.SNAP_K_BLOWUP * k_cap)
            ]
            if cand:
                s_b, k_b, r_b = min(cand, key=lambda c: (c[0], c[2], c[1]))
                if rows_p is not None and r_b > r_nat:
                    grown = self._pad_rows(rows_p, t, floor=r_b)
                    if grown is not None and len(grown) == r_b:
                        rows_p = grown
                        s_pad, k_cap = s_b, k_b
                elif rows_p is None or r_b == r_nat:
                    s_pad, k_cap = s_b, k_b
        shapes.add((s_pad, k_cap, 0 if rows_p is None else len(rows_p)))
        return s_pad, k_cap, rows_p

    def _prepare_bulk_chunk(
        self, chunk, rows_p, pods, tensors, flags,
        quota=False, self_aff=False, ext_mats=False,
    ):
        """Everything one bulk chunk's dispatch needs, with the
        host→device transfers already started (non-blocking
        `_prefetch_pods`): building chunk i+1's work item right after
        chunk i dispatches overlaps its transfer with chunk i's round
        execution — the double-buffer half of the cold-start pipeline."""
        s_real = len(chunk)
        firsts = np.array([i0 for _, i0, _ in chunk], np.int32)
        ks = np.array([j0 - i0 for _, i0, j0 in chunk], np.int32)
        s_pad, k_cap, rows_p = self._chunk_shape(
            chunk, rows_p, tensors, flags, quota, self_aff, ext_mats, ks=ks
        )
        firsts = np.pad(firsts, (0, s_pad - s_real), constant_values=firsts[-1])
        ks = np.pad(ks, (0, s_pad - s_real))  # k=0 rounds are no-ops
        # pods stay host-side (build_pod_arrays): the gather is a cheap
        # numpy fancy-index and the bulk call transfers the [S, ...]
        # representatives — never the full batch
        seg_pods = tuple(arr[firsts] for arr in pods)
        work = {
            "chunk": chunk,
            "k_cap": k_cap,
            "ks": ks,
            "rows": rows_p,
            "quota": quota,
            "self_aff": self_aff,
            "ext_mats": ext_mats,
        }
        if rows_p is None:
            work["seg_pods"] = self._prefetch_pods(seg_pods)
        else:
            from .scan import remap_term_ids

            g_terms, term_topo, ip_of = self._host_term_maps(tensors)
            g_terms_chunk = remap_term_ids(g_terms, rows_p, tensors.n_terms)
            sliced = (
                rows_p, g_terms_chunk, term_topo[rows_p], ip_of[rows_p],
                seg_pods,
            )
            (
                work["rows"], work["g_terms_c"], work["term_topo_c"],
                work["ip_of_c"], work["seg_pods"],
            ) = self._prefetch_pods(sliced)
        return work

    def _dispatch_bulk_chunk(self, statics, state, work, tensors, flags):
        """Dispatch one prepared bulk chunk through _bulk_call(_sliced) —
        the single funnel every bulk dispatch (including the OOM-backoff
        replays) passes through, so one span here covers them all."""
        with span(
            "rounds.chunk",
            runs=len(work["chunk"]), pods=int(work["ks"].sum()),
        ):
            if work.get("g_terms_c") is None:
                return self._bulk_call(
                    statics, state, work["seg_pods"], work["ks"],
                    tensors.n_domains, work["k_cap"], flags, work["quota"],
                    work["self_aff"], work["ext_mats"],
                )
            return self._bulk_call_sliced(
                statics, state, work["rows"], work["g_terms_c"],
                work["term_topo_c"], work["ip_of_c"], work["seg_pods"],
                work["ks"], tensors.n_domains, work["k_cap"], flags,
                work["quota"], work["self_aff"], work["ext_mats"],
            )

    def _bulk_backoff(self, statics, state, work, pods, tensors, flags):
        """Replay an OOM'd bulk chunk as two half-chunks, each re-chunked
        through `_chunk_runs` so it carries its own term-row union
        (durable/backoff.py).  Splits the SEGMENT list only: each run
        still dispatches as its own consecutive rounds in the same order,
        so the round-start normalizers see the same states and placements
        are bit-identical.  A single round too large for memory
        propagates — a mid-run split would move the normalizer boundary
        (the MAX_RUN contract).  Returns (state, [(chunk, ext_mats,
        outs_dev), ...]) matching the dispatcher's pending-entry shape."""
        chunk = work["chunk"]
        quota, self_aff, ext_mats = (
            work["quota"], work["self_aff"], work["ext_mats"],
        )
        h = max(len(chunk) // 2, 1)
        record_backoff(len(chunk), h)
        batch = self._current_batch
        done = []
        for half in (chunk[:h], chunk[h:]):
            if not half:
                continue
            for sub, rows_p in self._chunk_runs(
                half, batch, tensors,
                max_segs=self.MATS_CHUNK if ext_mats else None,
            ):
                w2 = self._prepare_bulk_chunk(
                    sub, rows_p, pods, tensors, flags, quota, self_aff,
                    ext_mats,
                )
                try:
                    state, outs = self._dispatch_bulk_chunk(
                        statics, state, w2, tensors, flags
                    )
                    done.append((w2["chunk"], ext_mats, outs))
                except Exception as exc:
                    if not is_resource_exhausted(exc) or len(w2["chunk"]) <= 1:
                        raise
                    state, sub_done = self._bulk_backoff(
                        statics, state, w2, pods, tensors, flags
                    )
                    done.extend(sub_done)
        return state, done

    def _bulk_chunk(
        self, statics, state, chunk, rows_p, pods, tensors, flags,
        quota=False, self_aff=False, ext_mats=False,
    ):
        """Run one chunk of bulk runs through _bulk_call, carrying only the
        chunk's cnt-plane rows when rows_p is given."""
        work = self._prepare_bulk_chunk(
            chunk, rows_p, pods, tensors, flags, quota, self_aff, ext_mats
        )
        return self._dispatch_bulk_chunk(statics, state, work, tensors, flags)

    @staticmethod
    def _record_chunk(
        chunk, hosts, nodes, reasons, lvm_alloc, dev_take, gpu_shares,
        gpu_mem, lvm_sizes, dev_sizes, leftovers,
    ):
        assign_host, vg_host, dev_host, gpu_host = hosts
        for s, (_, i0, j0) in enumerate(chunk):
            row = assign_host[s]
            placed = int((row >= 0).sum())
            nodes[i0 : i0 + placed] = row[:placed]
            reasons[i0 : i0 + placed] = 0
            if placed:
                sel = np.arange(i0, i0 + placed)
                if lvm_sizes.shape[1] and lvm_sizes[i0].max() > 0:
                    vgs = vg_host[s, :placed]
                    ok_v = vgs >= 0
                    lvm_alloc[sel[ok_v], vgs[ok_v]] = lvm_sizes[i0].max()
                if dev_sizes.shape[1] and dev_sizes[i0].max() > 0:
                    devs = dev_host[s, :placed]
                    ok_d = devs >= 0
                    dev_take[sel[ok_d], devs[ok_d]] = True
                if gpu_mem[i0] > 0:
                    gpus = gpu_host[s, :placed]
                    ok_g = gpus >= 0
                    gpu_shares[sel[ok_g], gpus[ok_g]] = 1.0
            if placed < j0 - i0:
                leftovers.append((i0 + placed, j0))

    @staticmethod
    def _record_chunk_mats(
        chunk, hosts, nodes, reasons, lvm_alloc, dev_take, gpu_shares,
        dev_sizes, leftovers,
    ):
        """Record a MATRIX chunk: per-slot LVM/GPU allocation matrices come
        back dense; only the (single) device claim stays an index."""
        assign_host, dev_host, lvm_host, gpu_host = hosts
        for s, (_, i0, j0) in enumerate(chunk):
            row = assign_host[s]
            placed = int((row >= 0).sum())
            nodes[i0 : i0 + placed] = row[:placed]
            reasons[i0 : i0 + placed] = 0
            if placed:
                sel = np.arange(i0, i0 + placed)
                if lvm_alloc.shape[1]:
                    lvm_alloc[sel] = lvm_host[s, :placed]
                if dev_sizes.shape[1] and dev_sizes[i0].max() > 0:
                    devs = dev_host[s, :placed]
                    ok_d = devs >= 0
                    dev_take[sel[ok_d], devs[ok_d]] = True
                if gpu_shares.shape[1]:
                    gpu_shares[sel] = gpu_host[s, :placed]
            if placed < j0 - i0:
                leftovers.append((i0 + placed, j0))

    def _dispatch(self, statics: StaticArrays, state: SchedState, pods, flags):
        batch = self._current_batch
        tensors = self._current_tensors
        segments = self._segments(batch, tensors)
        p = len(batch.group)
        ext = batch.ext
        gpu_mem = np.asarray(ext["gpu_mem"])
        nodes = np.full(p, -1, np.int32)
        reasons = np.zeros(p, np.int32)
        v = statics.vg_cap.shape[1]
        sd = statics.sdev_cap.shape[1]
        gd = statics.gpu_dev_exists.shape[1]
        lvm_alloc = np.zeros((p, v), np.float32)
        dev_take = np.zeros((p, sd), bool)
        gpu_shares = np.zeros((p, gd), np.float32)

        idx = 0
        while idx < len(segments):
            kind, a, b = segments[idx]
            if kind == "scan":
                state, outs = self._run_scan_segment(statics, state, pods, a, b, flags)
                nodes[a:b], reasons[a:b] = outs[0], outs[1]
                lvm_alloc[a:b], dev_take[a:b], gpu_shares[a:b] = outs[2:5]
                idx += 1
                continue
            # batch consecutive same-kind bulk runs into compiled multi-round
            # calls ("bulk" = threshold rounds, "bulkq" = domain-quota
            # rounds — distinct compiled bodies), CHUNKED so each call's
            # scan carries only the count-plane rows its runs reference: a
            # round's state update scatters into the carried cnt planes, and
            # carrying the full [T, N] plane makes every round pay traffic
            # proportional to the number of workloads in the whole
            # simulation — the dominant device cost at 100k nodes. Rows are
            # gathered before and scattered back after each chunk (in
            # place, donated).
            #
            # Consecutive bulk STRETCHES of different kinds (a matrix run
            # next to a plain run next to a quota run, the shape of the
            # matrix mix) form one STRETCH GROUP: every chunk of every kind
            # dispatches back-to-back — the inter-chunk state dependency
            # stays device-side, the compiled bodies just alternate — and
            # ONE device_get materializes the whole group's outputs.  Each
            # blocking fetch costs a full tunnel round-trip (~100ms)
            # regardless of payload, and the per-stretch fetches were the
            # matrix point's measured floor (docs/status.md).  Leftovers
            # re-probe after the whole group — the same divergence class as
            # the pre-existing per-stretch deferral (reasons reflect the
            # more-constrained final state; a leftover that PLACES sees the
            # neighboring stretches' placements first).
            group_runs, idx = self._stretch_group(segments, idx)
            leftovers = []
            lvm_sizes = np.asarray(ext["lvm_size"])
            dev_sizes = np.asarray(ext["dev_size"])

            # dispatch every chunk first — jit calls are async, so the
            # tunnel pipelines all rounds; outputs materialize afterwards,
            # and the host record work overlaps the device queue instead of
            # synchronizing once per chunk.  Preparation runs one chunk
            # AHEAD of the dispatch point (double buffer): chunk i+1's pod
            # representatives start their non-blocking transfer while chunk
            # i's rounds execute.
            pending = []
            items = self._group_work_items(group_runs, batch, tensors)
            nxt = next(items, None)
            work = (
                self._prepare_bulk_chunk(
                    nxt[0], nxt[1], pods, tensors, flags, *nxt[2:]
                )
                if nxt is not None
                else None
            )
            while work is not None:
                try:
                    state, outs_dev = self._dispatch_bulk_chunk(
                        statics, state, work, tensors, flags
                    )
                    done = [(work["chunk"], work["ext_mats"], outs_dev)]
                except Exception as exc:
                    # OOM backoff: replay the chunk as half-chunks from the
                    # carried state (placements bit-identical — the split
                    # is at segment granularity; see _bulk_backoff)
                    if not is_resource_exhausted(exc) or len(work["chunk"]) <= 1:
                        raise
                    state, done = self._bulk_backoff(
                        statics, state, work, pods, tensors, flags
                    )
                # start the device→host copies NOW: the transfers ride the
                # tunnel concurrently with later dispatches, so the fetch
                # below waits on completion instead of paying one serial
                # round-trip per array
                for _, _, outs_dev_c in done:
                    for o in outs_dev_c:
                        if hasattr(o, "copy_to_host_async"):
                            o.copy_to_host_async()
                pending.extend(done)
                nxt = next(items, None)
                work = (
                    self._prepare_bulk_chunk(
                        nxt[0], nxt[1], pods, tensors, flags, *nxt[2:]
                    )
                    if nxt is not None
                    else None
                )
            # ONE device_get for the whole stretch group: the device queue
            # has already drained by the first fetch
            fetched = fetch_outputs([outs for _, _, outs in pending])
            for (chunk, ext_mats_c, _), outs_host in zip(pending, fetched):
                hosts = tuple(np.asarray(o) for o in outs_host)
                if ext_mats_c:
                    self._record_chunk_mats(
                        chunk, hosts, nodes, reasons, lvm_alloc, dev_take,
                        gpu_shares, dev_sizes, leftovers,
                    )
                else:
                    self._record_chunk(
                        chunk, hosts, nodes, reasons, lvm_alloc, dev_take,
                        gpu_shares, gpu_mem, lvm_sizes, dev_sizes, leftovers,
                    )
            # Leftovers re-check after the whole bulk stretch group, so
            # their reasons reflect the (more-constrained) final state. Leftover
            # pods of one run are IDENTICAL, and a failed serial step leaves
            # the state untouched, so ONE probe per run decides its whole
            # remainder (the all-fail case is O(1) probes per run; at
            # 1M-pod scale the per-pod re-check was the single largest
            # cost). The probes themselves are BATCHED: one scan runs the
            # first pod of every leftover run back-to-back — sequentially
            # identical to per-run dispatches while failures dominate (a
            # failed step is a state no-op), and each tunneled dispatch
            # costs more than the whole probe. When a mid-batch probe
            # PLACES, later probes ran against a state missing that run's
            # remainder: their placements (if any) are reverted through the
            # eviction delta scan and they re-probe next iteration, while
            # the placed run's remainder walks pod-by-pod exactly like the
            # serial engine.
            state = self._probe_leftovers(
                statics, state, pods, leftovers, flags,
                nodes, reasons, lvm_alloc, dev_take, gpu_shares,
            )
        return state, (nodes, reasons, lvm_alloc, dev_take, gpu_shares)

    def _probe_leftovers(
        self, statics, state, pods, leftovers, flags,
        nodes, reasons, lvm_alloc, dev_take, gpu_shares,
    ):
        from .scan import _apply_log_delta

        pending = list(leftovers)
        while pending:
            firsts = np.array([a for a, _ in pending], np.int32)
            state, outs = self._run_scan_segment_idx(
                statics, state, pods, firsts, flags
            )
            nodes_p, reasons_p, lvm_p, dev_p, gpu_p = outs
            placed_pos = np.flatnonzero(nodes_p >= 0)
            stop = int(placed_pos[0]) if len(placed_pos) else len(pending)
            # when placements dominate the batch, the revert-and-reprobe
            # loop degrades toward quadratic probe work — after committing
            # this iteration's prefix, finish the rest one run at a time
            # (the pre-batching path, linear in runs)
            go_serial = len(placed_pos) > 4
            for j in range(stop):
                a2, b2 = pending[j]
                nodes[a2:b2] = -1
                reasons[a2:b2] = reasons_p[j]
            if stop == len(pending):
                break
            # probes beyond the first placement saw a state missing the
            # placed run's remainder — revert any of their placements and
            # re-probe them next iteration
            revert = [int(j) for j in placed_pos if j > stop]
            if revert:
                v_pad = self._pow2(len(revert))
                r = pods[1].shape[1]
                g_a = np.zeros(v_pad, np.int32)
                n_a = np.zeros(v_pad, np.int32)
                w_a = np.zeros(v_pad, np.float32)
                req_a = np.zeros((v_pad, r), np.float32)
                vg_a = np.zeros((v_pad, lvm_p.shape[1]), np.float32)
                sd_a = np.zeros((v_pad, dev_p.shape[1]), bool)
                gp_a = np.zeros((v_pad, gpu_p.shape[1]), np.float32)
                for i, j in enumerate(revert):
                    g_a[i] = pods[0][firsts[j]]
                    n_a[i] = nodes_p[j]
                    w_a[i] = -1.0
                    req_a[i] = pods[1][firsts[j]]
                    vg_a[i] = lvm_p[j]
                    sd_a[i] = dev_p[j]
                    gp_a[i] = gpu_p[j] * pods[8][firsts[j]]
                state = _apply_log_delta(
                    statics, state, (g_a, n_a, w_a, req_a, vg_a, sd_a, gp_a)
                )
            a2, b2 = pending[stop]
            nodes[a2], reasons[a2] = nodes_p[stop], 0
            lvm_alloc[a2], dev_take[a2], gpu_shares[a2] = (
                lvm_p[stop], dev_p[stop], gpu_p[stop],
            )
            if a2 + 1 < b2:
                # the probe placed (e.g. a cross-group spread constraint
                # relaxed by intervening placements) — run the remainder as
                # one serial segment, exactly like the serial engine
                state, outs2 = self._run_scan_segment(
                    statics, state, pods, a2 + 1, b2, flags
                )
                nodes[a2 + 1 : b2], reasons[a2 + 1 : b2] = outs2[0], outs2[1]
                lvm_alloc[a2 + 1 : b2], dev_take[a2 + 1 : b2], gpu_shares[
                    a2 + 1 : b2
                ] = outs2[2:5]
            pending = pending[stop + 1 :]
            if go_serial:
                for a3, b3 in pending:
                    state, outs3 = self._run_scan_segment(
                        statics, state, pods, a3, a3 + 1, flags
                    )
                    nodes[a3], reasons[a3] = outs3[0][0], outs3[1][0]
                    lvm_alloc[a3], dev_take[a3], gpu_shares[a3] = (
                        outs3[2][0], outs3[3][0], outs3[4][0],
                    )
                    if nodes[a3] < 0:
                        nodes[a3 + 1 : b3] = -1
                        reasons[a3 + 1 : b3] = reasons[a3]
                    elif a3 + 1 < b3:
                        state, outs3 = self._run_scan_segment(
                            statics, state, pods, a3 + 1, b3, flags
                        )
                        nodes[a3 + 1 : b3] = outs3[0]
                        reasons[a3 + 1 : b3] = outs3[1]
                        lvm_alloc[a3 + 1 : b3] = outs3[2]
                        dev_take[a3 + 1 : b3] = outs3[3]
                        gpu_shares[a3 + 1 : b3] = outs3[4]
                return state
        return state

    def _run_scan_segment_idx(self, statics, state, pods, idx, flags):
        """One scan over an arbitrary index selection of the batch's pods
        (the batched leftover probes), padded like a contiguous segment."""
        seg = self._pad_pods(
            tuple(arr[idx] for arr in pods), self._pow2(len(idx))
        )
        state, outs = self._scan_call(statics, state, seg, flags)
        outs = fetch_outputs(outs)
        return state, tuple(np.asarray(o)[: len(idx)] for o in outs)

