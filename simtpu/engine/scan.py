"""The placement engine: sequential-equivalent scheduling as `lax.scan`.

Replaces the reference's pod-at-a-time handshake — fake-client Create →
channel block → scheduler goroutine filter/score over all nodes → bind event
(`pkg/simulator/simulator.go:219-244,334-353`; hot loop
`vendor/.../core/generic_scheduler.go:131-341,470`) — with one compiled scan:
each scan step is a full scheduling cycle (filter → score → select → state
update) over the whole node axis at once. Pods are strictly ordered like the
reference's serial loop, so placement semantics are sequential-equivalent.

Tie-breaking: the reference picks a random node among max scorers
(`generic_scheduler.go:188-209` reservoir sample); we take the lowest index —
deterministic, and placement-set-equivalent for conformance purposes
(SURVEY.md §7 'hard parts').
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensorize import ClusterTensors, PodBatch
from ..durable.backoff import is_resource_exhausted, record_backoff
from ..obs.metrics import REGISTRY
from ..obs.trace import instant, span
from ..kernels.filters import (
    attach_limits_ok,
    interpod_filter,
    ports_conflict_free,
    resources_fit,
    topology_spread_filter,
    volume_conflict_free,
)
from ..kernels.gpushare import gpu_plan
from ..kernels.scores import (
    MAX_NODE_SCORE,
    balanced_allocation,
    interpod_score,
    least_allocated,
    maxabs_normalize,
    minmax_normalize,
    selector_spread_compose,
    selector_spread_score,
    simon_share,
    spread_score_from_raw,
    taint_toleration_score,
    topology_spread_score,
)
from ..kernels.storage import device_plan, lvm_plan, open_local_score
from .state import (
    CompactState,
    SchedState,
    add_rows,
    apply_placement_deltas,
    apply_placement_deltas_compact,
    build_state,
    compact_delta_spec,
    compact_enabled,
    compact_spec,
    compress_state,
    delta_direct_enabled,
    expand_state,
    extend_state,
    extend_state_nodes,
    grow_plan_nodes,
    grow_plan_terms,
    interpod_term_index,
    node_dom_for,
    node_dom_small_for,
    pack_delta_entries,
    snap_pow2,
    state_nbytes,
    strip_term_padding,
    _pad_terms_kernel,
    take_rows,
    take_rows_i32,
    update_state_gauge,
)

# Failure-reason codes (host maps to messages mirroring the scheduler's
# "0/N nodes are available: ..." status strings, scheduler.go:500)
OK = 0
FAIL_STATIC = 1  # affinity / selector / taints / pin — no node passed
FAIL_RESOURCES = 2  # insufficient free resources on every remaining node
FAIL_INTERPOD = 3  # inter-pod (anti-)affinity rules
FAIL_NO_NODE = 4  # forced pod names an unknown node
FAIL_STORAGE = 5  # Open-Local LVM/device storage
FAIL_GPU = 6  # GPU-share memory/devices
FAIL_PORTS = 7  # requested host port already in use everywhere feasible
FAIL_SPREAD = 8  # topology spread maxSkew would be violated everywhere
FAIL_VOLUME = 9  # exclusive volume (EBS/GCE-PD/ISCSI/RBD) conflict everywhere
FAIL_ATTACH = 10  # node volume attach limits exceeded everywhere
FAIL_VOLUME_BIND = 11  # PVC missing / not bindable / PV zone mismatch

# Jit-trace counters: the traced bodies of the engine executables bump these
# once per (re)trace, i.e. once per distinct compiled shape signature — the
# observability behind the planner's compile accounting (PlanResult.compiles)
# and the compile-count regression tests. Host-side state mutated at trace
# time only; steady-state dispatches never touch it.  (With the background
# precompile pipeline, engine/precompile.py, AOT lowering on worker threads
# bumps these too — the counts then attribute a trace to whatever phase is
# active when the background lowering happens to run; the registry
# counters' lock keeps concurrent worker-thread traces from losing
# increments.)  The backing store is the obs metrics registry under
# `compile.<kind>` (read via `obs.metrics.family("compile",
# COMPILE_COUNT_KINDS)` — the ISSUE-8 alias views are gone).
COMPILE_COUNT_KINDS = ("scan", "rounds", "wave", "explain", "solve", "grow")


def count_trace(kind: str) -> None:
    REGISTRY.counter(f"compile.{kind}").inc()


# Blocking device→host fetch counters: every engine-path jax.device_get goes
# through fetch_outputs, so the bench can report how many tunnel round-trips
# a placement paid (each costs fixed wire latency regardless of payload —
# the matrix point's measured floor, docs/status.md) AND how many bytes they
# moved ("bytes" — the payload-side of the transfer audit; with it, a
# regression that grows the fetched tree shows up even when the round-trip
# count stays flat).  Backing store: registry counters `fetch.get` /
# `fetch.bytes` (ISSUE 8; read via `obs.metrics.family("fetch",
# FETCH_KEYS)`).
FETCH_KEYS = ("get", "bytes")
_FETCH_GET = REGISTRY.counter("fetch.get")
_FETCH_BYTES = REGISTRY.counter("fetch.bytes")


def fetch_outputs(tree):
    """jax.device_get with round-trip + byte accounting (one "get" bump per
    blocking fetch; "bytes" sums the materialized host payload).  Under
    tracing each fetch is a `fetch.get` span carrying its byte payload —
    the blocking device→host syncs are exactly the events a Perfetto
    timeline of a dispatch loop needs labeled."""
    _FETCH_GET.inc()
    with span("fetch.get") as sp:
        out = jax.device_get(tree)
        nbytes = sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(out)
            if hasattr(leaf, "nbytes")
        )
        sp.set(bytes=nbytes)
    _FETCH_BYTES.inc(nbytes)
    return out


# Speculative-wavefront telemetry (docs/speculation.md): bumped host-side
# from the accept flags each wavefront dispatch returns (they ride the
# chunk loop's one batched device→host fetch — no extra round-trips).
# "accepted" counts the longest correct prefix of each wavefront (the pods
# whose speculative state_0 placement matched the serial answer);
# "rollback_pods" counts the pods beyond the first divergence, whose
# speculative placements were discarded and whose results come from the
# verifier's pod-at-a-time serial replay; a "rollback" is a wavefront with
# at least one divergence.  Backing store: registry counters
# `wavefront.*` (ISSUE 8; read via `obs.metrics.family("wavefront",
# WAVE_KEYS)` — the legacy `wave_counts()` alias view is gone).
WAVE_KEYS = (
    "wavefronts", "pods", "accepted", "rollbacks", "rollback_pods",
    "draft_hard",
)
_WAVE = {k: REGISTRY.counter(f"wavefront.{k}") for k in WAVE_KEYS}


def wave_enabled() -> bool:
    """Default for Engine.speculate: SIMTPU_WAVEFRONT=0 disables the
    speculative wavefront dispatcher (1/unset = on; placements are
    bit-identical either way — the switch exists for A/B measurement)."""
    import os

    return os.environ.get("SIMTPU_WAVEFRONT", "1") != "0"


def wave_heavy_enabled() -> bool:
    """SIMTPU_WAVE_HEAVY=0 restricts wavefront drafting back to LEAN pods
    (no storage/GPU demand, no ports/volume groups).  1/unset drafts the
    heavy families too, through the hard verifier's per-step stage
    recomputes — placements are bit-identical either way; the switch
    exists for A/B measurement."""
    import os

    return os.environ.get("SIMTPU_WAVE_HEAVY", "1") != "0"


def fused_cascade_enabled() -> bool:
    """SIMTPU_FUSED_CASCADE=0 compiles the per-step filter/score cascade
    with one lax.cond per skippable stage (the pre-round-16 form); 1/unset
    merges adjacent same-shape conds into single wider branches so each
    serial step issues fewer kernels.  Placements are bit-identical either
    way (every skip constant equals the skipped kernel's degenerate
    output); the switch exists for A/B measurement."""
    import os

    return os.environ.get("SIMTPU_FUSED_CASCADE", "1") != "0"


REASON_TEXT = {
    FAIL_STATIC: "node(s) didn't match node selector/affinity or had untolerated taints",
    FAIL_RESOURCES: "insufficient cpu/memory/extended resources on every feasible node",
    FAIL_INTERPOD: "node(s) didn't satisfy inter-pod affinity/anti-affinity rules",
    FAIL_NO_NODE: "pod references a node that does not exist",
    FAIL_STORAGE: "insufficient open-local storage (LVM volume groups / exclusive devices)",
    FAIL_GPU: "insufficient GPU memory on every feasible node's devices",
    FAIL_PORTS: "node(s) didn't have free ports for the requested pod ports",
    FAIL_SPREAD: "node(s) didn't match pod topology spread constraints",
    FAIL_VOLUME: "node(s) had a volume attach conflict for the requested volumes",
    FAIL_ATTACH: "node(s) exceeded max volume count for the requested volumes",
    FAIL_VOLUME_BIND: (
        "persistentvolumeclaim not found, not bindable, or bound to a volume "
        "unreachable from the node's zone"
    ),
}


def _check_reason_text() -> None:
    """Exhaustiveness guard: every FAIL_* code must carry a REASON_TEXT
    entry, so `Simulator._record_failed`'s "unschedulable" fallback (and
    the incremental planner's copy of it) is provably unreachable — a new
    failure code without a message fails at import, not as a silent
    generic reason in a report."""
    codes = {
        v for k, v in globals().items()
        if k.startswith("FAIL_") and isinstance(v, int)
    }
    missing = codes - set(REASON_TEXT)
    if missing:
        raise AssertionError(
            f"FAIL_* codes without a REASON_TEXT entry: {sorted(missing)} — "
            "every failure code must render a real reason"
        )


_check_reason_text()


#: The filter cascade in registry evaluation order: (StepEval mask field,
#: failure code when that stage is the first to empty the candidate set).
#: SINGLE source of truth for `StepEval.fail_code`, the explain pass's
#: per-stage breakdown (simtpu/explain/breakdown.py), and the wavefront
#: verifier's substituted `fail_from` — the headline reason and the
#: explanation's first-failing stage can never drift (pinned by
#: tests/test_explain.py).  The final (m_all, FAIL_INTERPOD) entry is the
#: cascade default: a pod emptied only at the inter-pod stage.
FILTER_CASCADE = (
    ("m_static", FAIL_STATIC),
    ("m_ports", FAIL_PORTS),
    ("m_res", FAIL_RESOURCES),
    ("m_vol", FAIL_VOLUME),
    ("m_att", FAIL_ATTACH),
    ("m_bind", FAIL_VOLUME_BIND),
    ("m_storage", FAIL_STORAGE),
    ("m_gpu", FAIL_GPU),
    ("m_spread", FAIL_SPREAD),
    ("m_all", FAIL_INTERPOD),
)


class StaticArrays(NamedTuple):
    """Per-simulation constants handed to the jitted scan."""

    alloc: jnp.ndarray  # [N, R]
    static_mask: jnp.ndarray  # [G, N]
    vol_mask: jnp.ndarray  # [G, N] VolumeBinding+VolumeZone feasibility
    node_pref: jnp.ndarray  # [G, N]
    taint_intol: jnp.ndarray  # [G, N]
    static_score: jnp.ndarray  # [G, N] ImageLocality score
    avoid_pen: jnp.ndarray  # [G, N] NodePreferAvoidPods penalty (pre-weighted)
    # Node domains are stored per TOPOLOGY KEY, not per term: node_dom[k, n]
    # is node n's domain id for key k (-1 absent), and term_topo[t] maps a
    # term to its key. The step's [Tc, N] domain rows are a two-level gather
    # (node_dom[term_topo[tsafe]]) — a [T, N] materialization would cost
    # T/K x the memory (T grows with the number of workloads, K is ~2-3).
    node_dom: jnp.ndarray  # [K, N] node domain per topology key (-1 absent)
    term_topo: jnp.ndarray  # [T] topology-key index per term
    # same-domain reduction routing (engine/rounds.py round updates):
    # key_kind[k] = 1 small (one-hot einsum over ≤ DOM_SMALL compact ids in
    # node_dom_small), 2 unique-per-node (sum = value), 0 scatter fallback
    key_kind: jnp.ndarray  # [K]
    node_dom_small: jnp.ndarray  # [K, N] compact per-key domain id (-1 absent)
    # The four interpod "own" count planes in SchedState live on a compacted
    # axis of terms that actually appear in some group's (anti-)affinity:
    # ip_of[t] is a term's row there (-1 for spread/selector-spread terms).
    ip_of: jnp.ndarray  # [T] interpod-plane row per term (-1 none)
    # Term incidence is compacted per group: g_terms[g] lists the <= Tc term
    # indices relevant to group g (-1 pad), and every [G, Tc] matrix below is
    # aligned to those columns. The scan step row-gathers just those rows
    # from the [T, N] count state, so per-pod topology work is O(Tc x N)
    # instead of O(T x N) — T grows with the number of workloads
    # (SelectorSpread interns terms per controller), Tc stays small.
    g_terms: jnp.ndarray  # [G, Tc] relevant term indices (-1 pad)
    s_match: jnp.ndarray  # [G, Tc]
    a_aff_req: jnp.ndarray  # [G, Tc]
    a_anti_req: jnp.ndarray  # [G, Tc]
    w_aff_pref: jnp.ndarray  # [G, Tc]
    w_anti_pref: jnp.ndarray  # [G, Tc]
    spread_hard: jnp.ndarray  # [G, Tc] maxSkew (0 = inactive)
    spread_soft: jnp.ndarray  # [G, Tc] ScheduleAnyway multiplicity
    ss_host: jnp.ndarray  # [G, Tc] SelectorSpread hostname terms
    ss_zone: jnp.ndarray  # [G, Tc] SelectorSpread zone terms
    ports_req: jnp.ndarray  # [G, P] host-port request incidence
    vol_rw_req: jnp.ndarray  # [G, W] exclusive volume read-write incidence
    vol_ro_req: jnp.ndarray  # [G, W] exclusive volume read-only incidence
    vol_att_req: jnp.ndarray  # [G, W] attachable volume incidence
    vol_class_mask: jnp.ndarray  # [C, W] attach class of each volume
    attach_limits: jnp.ndarray  # [N, C] per-node attach limits
    # extended resources
    has_storage: jnp.ndarray  # [N]
    vg_cap: jnp.ndarray  # [N, V]
    vg_name_id: jnp.ndarray  # [N, V]
    sdev_cap: jnp.ndarray  # [N, SD]
    sdev_media: jnp.ndarray  # [N, SD]
    gpu_dev_exists: jnp.ndarray  # [N, GD]
    gpu_total: jnp.ndarray  # [N]
    # score-term weights (schedconfig.SchedulerConfig — the
    # --default-scheduler-config surface); order per simtpu/schedconfig.py
    score_w: jnp.ndarray  # [schedconfig.N_TERMS]
    # candidate-cluster membership: False rows are "not in this what-if
    # cluster" (used by the batched capacity sweep, simtpu/parallel/sweep.py,
    # which vmaps this field over candidate node counts)
    node_valid: jnp.ndarray  # [N]


def build_pod_arrays(batch: PodBatch, n_resources: int):
    """Pad the batch's request matrix to the cluster resource vocabulary and
    stack the per-pod arrays in the order `schedule_step` unpacks them.

    The single source of truth for the scan's pod-tuple layout — used by
    Engine.place, the batched sweep, the bench, and the graft entry.
    Returns (padded_req, pods_tuple). The tuple stays HOST-side (numpy): the
    rounds engine gathers run representatives and slices segments from it,
    and a device round-trip of million-pod arrays costs far more than the
    per-dispatch transfer of what is actually dispatched (jit transfers its
    own arguments).
    """
    req = batch.req
    if req.shape[1] < n_resources:
        req = np.pad(req, ((0, 0), (0, n_resources - req.shape[1])))
    ext = batch.ext
    pods = (
        np.asarray(batch.group, np.int32),
        np.asarray(req, np.float32),
        np.asarray(batch.pin, np.int32),
        np.asarray(batch.forced),
        np.asarray(ext["lvm_size"], np.float32),
        np.asarray(ext["lvm_vg"], np.int32),
        np.asarray(ext["dev_size"], np.float32),
        np.asarray(ext["dev_media"], np.int32),
        np.asarray(ext["gpu_mem"], np.float32),
        np.asarray(ext["gpu_count"], np.int32),
        np.asarray(ext["gpu_preset"], np.int32),
    )
    return req, pods


def _compact_terms(tensors: ClusterTensors):
    """Per-group relevant-term compaction (see StaticArrays.g_terms).
    Memoized on the tensors object — statics_from and the rounds engine's
    chunked dispatch both need it per place()."""
    cached = getattr(tensors, "_compact_cache", None)
    if cached is not None:
        return cached
    g_n, t_n = tensors.s_match.shape
    relevant = (
        tensors.s_match
        | tensors.a_aff_req
        | tensors.a_anti_req
        | (tensors.w_aff_pref != 0)
        | (tensors.w_anti_pref != 0)
        | (tensors.spread_hard > 0)
        | (tensors.spread_soft > 0)
        | tensors.ss_host
        | tensors.ss_zone
    )
    per_g = [np.flatnonzero(row) for row in relevant]
    t_cap = max((len(ids) for ids in per_g), default=0)
    t_cap = 1 << max(t_cap - 1, 0).bit_length() if t_cap else 0
    g_terms = np.full((g_n, t_cap), -1, np.int32)
    for gi, ids in enumerate(per_g):
        g_terms[gi, : len(ids)] = ids

    def compact(mat, dtype=None):
        out = np.zeros((g_n, t_cap), mat.dtype if dtype is None else dtype)
        for gi, ids in enumerate(per_g):
            out[gi, : len(ids)] = mat[gi, ids]
        return out

    object.__setattr__(tensors, "_compact_cache", (g_terms, compact))
    return g_terms, compact


def statics_from(tensors: ClusterTensors, sched_config=None) -> StaticArrays:
    """Device-resident per-simulation constants. Memoized on the tensors
    object: a fresh engine over the same frozen tensors (capacity probes,
    best-of-N benching) must not re-transfer ~GBs of [G, N] planes — on a
    tunneled TPU the transfer alone costs tens of seconds."""
    from ..schedconfig import DEFAULT_WEIGHTS

    cached = getattr(tensors, "_statics_cache", None)
    # the cached config is held by reference and compared with `is`: an id()
    # key would silently alias a recycled object address
    if cached is not None and cached[0] is sched_config:
        return cached[1]
    ext = tensors.ext
    g_terms, compact = _compact_terms(tensors)
    score_w = (
        sched_config.score_weights if sched_config is not None else DEFAULT_WEIGHTS
    )

    def dev(host_arr, dtype=None):
        """Device-resident copy; CONSTANT [G, N] planes collapse to one
        [1, N] row.  The score planes are all-zero (and vol_mask all-True)
        for most problems — shipping them as dense host buffers costs tens
        of seconds of tunnel transfer, and even device-side fills cost
        G x N x 4 B of HBM each (6.4 GB at 400k nodes x 1000 groups, the
        difference between fitting one chip and OOM).  Every consumer
        reads rows via `arr[g]`, and XLA's gather clamp maps any g onto
        the single constant row, so the collapse is read-transparent."""
        dt = dtype or host_arr.dtype
        if host_arr.size and host_arr.ndim == 2:
            first = host_arr.flat[0]
            if not host_arr.any():
                return jnp.zeros((1, host_arr.shape[1]), dt)
            if host_arr.dtype == bool and first and host_arr.all():
                return jnp.ones((1, host_arr.shape[1]), dt)
        return jnp.asarray(host_arr, dt)

    statics = StaticArrays(
        alloc=jnp.asarray(tensors.alloc, jnp.float32),
        static_mask=dev(tensors.static_mask),
        vol_mask=dev(tensors.vol_mask),
        node_pref=dev(tensors.node_pref_score),
        taint_intol=dev(tensors.taint_intolerable),
        static_score=dev(tensors.static_score, jnp.float32),
        avoid_pen=dev(tensors.avoid_pen, jnp.float32),
        node_dom=jnp.asarray(
            tensors.node_dom if tensors.node_dom.shape[0] else
            np.zeros((1, tensors.alloc.shape[0]), np.int32),
            jnp.int32,
        ),
        key_kind=jnp.asarray(
            tensors.key_kind
            if tensors.key_kind is not None and tensors.key_kind.shape[0]
            else np.zeros(1, np.int32),
            jnp.int32,
        ),
        node_dom_small=jnp.asarray(
            tensors.node_dom_small
            if tensors.node_dom_small is not None
            and tensors.node_dom_small.shape[0]
            else np.full((1, tensors.alloc.shape[0]), -1, np.int32),
            jnp.int32,
        ),
        term_topo=jnp.asarray(tensors.term_topo_key, jnp.int32),
        ip_of=jnp.asarray(interpod_term_index(tensors), jnp.int32),
        g_terms=jnp.asarray(g_terms),
        s_match=jnp.asarray(compact(tensors.s_match)),
        a_aff_req=jnp.asarray(compact(tensors.a_aff_req)),
        a_anti_req=jnp.asarray(compact(tensors.a_anti_req)),
        w_aff_pref=jnp.asarray(compact(tensors.w_aff_pref)),
        w_anti_pref=jnp.asarray(compact(tensors.w_anti_pref)),
        spread_hard=jnp.asarray(compact(tensors.spread_hard)),
        spread_soft=jnp.asarray(compact(tensors.spread_soft)),
        ss_host=jnp.asarray(compact(tensors.ss_host)),
        ss_zone=jnp.asarray(compact(tensors.ss_zone)),
        ports_req=jnp.asarray(tensors.ports),
        vol_rw_req=jnp.asarray(tensors.vol_rw),
        vol_ro_req=jnp.asarray(tensors.vol_ro),
        vol_att_req=jnp.asarray(tensors.vol_att),
        vol_class_mask=jnp.asarray(tensors.vol_class_mask),
        attach_limits=jnp.asarray(tensors.attach_limits),
        has_storage=jnp.asarray(ext.has_storage),
        vg_cap=jnp.asarray(ext.vg_cap, jnp.float32),
        vg_name_id=jnp.asarray(ext.vg_name_id, jnp.int32),
        sdev_cap=jnp.asarray(ext.sdev_cap, jnp.float32),
        sdev_media=jnp.asarray(ext.sdev_media, jnp.int32),
        gpu_dev_exists=jnp.asarray(ext.gpu_dev_total > 0),
        gpu_total=jnp.asarray(ext.gpu_total, jnp.float32),
        score_w=jnp.asarray(score_w, jnp.float32),
        node_valid=jnp.ones(tensors.alloc.shape[0], bool),
    )
    object.__setattr__(tensors, "_statics_cache", (sched_config, statics))
    return statics


class StepFlags(NamedTuple):
    """Statically-known problem features, used to compile reduced scan steps.

    Each False flag removes the corresponding kernels from the traced step
    entirely — the scan is launch-count-bound on small node counts, so pruning
    unused constraint families is the main single-pod throughput lever. All
    flags True (the default) compiles the fully general step.
    """

    ports: bool = True  # any group requests host ports
    vols: bool = True  # any exclusive-volume conflicts possible
    attach: bool = True  # any attachable volumes present
    spread_hard: bool = True  # any DoNotSchedule topology constraint
    spread_soft: bool = True  # any ScheduleAnyway constraint
    selector_spread: bool = True  # any SelectorSpread counting term
    interpod_req: bool = True  # any required (anti-)affinity term
    interpod_pref: bool = True  # any preferred (anti-)affinity weight
    storage: bool = True  # any Open-Local node storage or pod demand
    gpu: bool = True  # any GPU-share capacity or pod demand
    node_pref: bool = True  # any preferred node affinity weight
    taint_pref: bool = True  # any intolerable PreferNoSchedule taint
    static_score: bool = True  # any ImageLocality / preferAvoidPods signal
    # any topology key needing the scatter-fallback same-domain reduction
    # (neither ≤ DOM_SMALL domains nor unique-per-node); False removes the
    # [Tc, D] scatter/gather pair from the bulk round entirely
    dom_fallback: bool = True
    # merge adjacent same-shape lax.cond stages of the filter/score cascade
    # into single wider branches (fewer dispatches per serial step); the
    # merged form is bit-identical — every skip constant equals the skipped
    # kernel's degenerate output, so evaluating a dormant term inside a
    # taken branch reproduces the constant exactly
    fused: bool = True


def flags_from(tensors: ClusterTensors, batch_ext: dict) -> StepFlags:
    """Derive the reduced-step flags from concrete host-side arrays.

    `batch_ext` (PodBatch.ext) is required: the storage/gpu flags must see
    the batch's demands, or a storage-demanding pod on a storage-less
    cluster would compile a step that skips the Open-Local filter entirely.
    """
    ext = tensors.ext
    storage = bool(ext.has_storage.any())
    gpu = bool(ext.gpu_total.any())
    storage = storage or bool(
        np.asarray(batch_ext["lvm_size"]).size
        and np.asarray(batch_ext["lvm_size"]).max() > 0
    ) or bool(
        np.asarray(batch_ext["dev_size"]).size
        and np.asarray(batch_ext["dev_size"]).max() > 0
    )
    gpu = gpu or bool(np.asarray(batch_ext["gpu_mem"]).max(initial=0) > 0)
    kinds = tensors.key_kind if tensors.key_kind is not None else np.zeros(0)
    return StepFlags(
        dom_fallback=bool(np.any(kinds == 0)),
        ports=tensors.n_ports > 0,
        vols=bool(tensors.vol_rw.any() or tensors.vol_ro.any()),
        attach=bool(tensors.vol_att.any()),
        spread_hard=bool(tensors.spread_hard.any()),
        spread_soft=bool(tensors.spread_soft.any()),
        selector_spread=bool(tensors.ss_host.any() or tensors.ss_zone.any()),
        interpod_req=bool(tensors.a_aff_req.any() or tensors.a_anti_req.any()),
        interpod_pref=bool(tensors.w_aff_pref.any() or tensors.w_anti_pref.any()),
        storage=storage,
        gpu=gpu,
        node_pref=bool(tensors.node_pref_score.any()),
        taint_pref=bool(tensors.taint_intolerable.any()),
        static_score=bool(tensors.static_score.any() or tensors.avoid_pen.any()),
        fused=fused_cascade_enabled(),
    )


class StepEval(NamedTuple):
    """Everything one scheduling cycle derives before choosing a node:
    the mask cascade, the combined score, and the extended-resource plans.
    Shared by the serial scan (`schedule_step`) and the bulk rounds engine
    (`engine/rounds.py`), which evaluates it at round boundaries."""

    m_static: jnp.ndarray  # [N]
    m_ports: jnp.ndarray
    m_res: jnp.ndarray
    m_vol: jnp.ndarray
    m_att: jnp.ndarray
    m_bind: jnp.ndarray
    m_storage: jnp.ndarray
    m_gpu: jnp.ndarray
    m_spread: jnp.ndarray
    m_all: jnp.ndarray
    score: jnp.ndarray  # [N], -inf outside m_all
    score_nostorage: jnp.ndarray  # [N] score minus the Open-Local term
    lvm_alloc: jnp.ndarray  # [N, V]
    dev_take: jnp.ndarray  # [N, SD]
    gpu_shares: jnp.ndarray  # [N, GD]

    def fail_code(self) -> jnp.ndarray:
        """First mask stage that emptied the candidate set (the scheduler's
        '0/N nodes are available: <first failing filter>' status).  Walks
        FILTER_CASCADE — the module-level stage order the explain pass
        (simtpu/explain) shares, so the headline reason and the per-stage
        breakdown agree by construction."""
        fail = jnp.int32(FILTER_CASCADE[-1][1])
        for field, code in reversed(FILTER_CASCADE[:-1]):
            fail = jnp.where(jnp.any(getattr(self, field)), fail, code)
        return fail


def score_pod(
    statics: StaticArrays,
    state: SchedState,
    g,
    req,
    m_all,
    flags: StepFlags = StepFlags(),
    free=None,
    cnt_sub=None,
) -> jnp.ndarray:
    """The combined score sum for one pod spec over all nodes, -inf outside
    `m_all` (weights: registry.go:101-145 + Simon extension, overridable via
    --default-scheduler-config → statics.score_w).

    Every term skipped by a False flag is constant across nodes for such
    problems (normalizers map all-zero raw scores to a constant), so pruning
    preserves the argmax exactly. The Open-Local storage term is NOT included
    here — `filter_and_score` owns the storage plans and adds it into
    `StepEval.score`, keeping the storage-free base (`score_nostorage`)
    available to the bulk rounds engine's slope re-score (`engine/rounds.py`)
    without a second full pass.

    `free` / `cnt_sub` override `state.free` and the group's [Tc, N]
    cnt_match rows: the rounds engine scores a hypothetical
    one-pod-per-node state without materializing a bumped copy of the full
    [T, N] count plane (a copy is T/Tc times the touched data).
    """
    f = flags
    t_cap = statics.g_terms.shape[1]
    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        if cnt_sub is None:
            cnt_sub = take_rows(state.cnt_match, terms_g)
    fr = state.free if free is None else free
    w_ = statics.score_w
    score = w_[0] * least_allocated(fr, statics.alloc, req)
    score += w_[1] * balanced_allocation(fr, statics.alloc, req)
    # Simon score + the GPU-share score, which is the same dominant-share
    # formula (open-gpu-share.go:84-110): computed once, counted twice
    score += (w_[2] + w_[3]) * minmax_normalize(simon_share(statics.alloc, req), m_all)
    if f.node_pref:
        score += w_[4] * minmax_normalize(statics.node_pref[g], m_all)
    if f.taint_pref:
        score += w_[5] * taint_toleration_score(statics.taint_intol[g], m_all)
    n = statics.alloc.shape[0]
    # the three count-plane terms below are each individually skippable per
    # pod (lax.cond) — collected as (weight index, live predicate, live fn,
    # skip-constant fn) so the fused cascade can merge them into ONE cond
    soft_terms = []
    if (f.interpod_pref or f.interpod_req) and t_cap:
        # per-pod skip: a pod whose group carries no interpod terms gets
        # raw 0 → maxabs-normalized 0 — identical constants without
        # streaming the [Tc, N] own planes
        def _ipa_term():
            # [Tc] rows in the compacted own planes; -1 (non-interpod/pad)
            # gathers as zeros through the one-hot matmul
            ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)
            raw_ipa = interpod_score(
                cnt_sub,
                take_rows(state.cnt_own_aff, ip_eff),
                take_rows(state.w_own_aff_pref, ip_eff),
                take_rows(state.w_own_anti_pref, ip_eff),
                statics.s_match[g],
                statics.w_aff_pref[g],
                statics.w_anti_pref[g],
            )
            return maxabs_normalize(raw_ipa, m_all)

        # symmetric terms count: existing pods' preferred (anti-)affinity
        # reaches a pod with no own terms through s_match on the interpod
        # own planes, so the skip predicate includes that clause
        ip_eff_s = jnp.where(tvalid, statics.ip_of[tsafe], -1)
        has_ip = (
            jnp.any(statics.w_aff_pref[g] != 0)
            | jnp.any(statics.w_anti_pref[g] != 0)
            | jnp.any(statics.a_aff_req[g])
            | jnp.any(statics.a_anti_req[g])
            | jnp.any(statics.s_match[g] & (ip_eff_s >= 0))
        )
        soft_terms.append(
            (6, has_ip, _ipa_term, lambda: jnp.zeros(n, score.dtype))
        )
    if f.spread_soft and t_cap:
        # PodTopologySpread soft constraints, registry weight 2 by default:
        # zero soft terms → raw 0 → the inverse-min-max degenerates to the
        # constant MAX_NODE_SCORE; skip the [Tc, N] stream for such pods
        soft_terms.append((
            7,
            jnp.any(statics.spread_soft[g] > 0),
            lambda: topology_spread_score(cnt_sub, statics.spread_soft[g], m_all),
            lambda: jnp.full(n, MAX_NODE_SCORE, score.dtype),
        ))
    if f.selector_spread and t_cap:
        # SelectorSpread (default workload/service spreading, weight 1):
        # zero ss terms → max counts 0 → constant MAX_NODE_SCORE
        soft_terms.append((
            8,
            jnp.any(statics.ss_host[g]) | jnp.any(statics.ss_zone[g]),
            lambda: selector_spread_score(
                cnt_sub, statics.ss_host[g], statics.ss_zone[g], m_all
            ),
            lambda: jnp.full(n, MAX_NODE_SCORE, score.dtype),
        ))
    if f.fused and len(soft_terms) > 1:
        # one cond for every count-plane term: a dormant term evaluated in
        # the live branch reproduces its skip constant exactly (see the
        # per-term notes above), so the merge is bit-identical while
        # dispatching one branch pair instead of three
        any_live = soft_terms[0][1]
        for _, pred, _, _ in soft_terms[1:]:
            any_live = any_live | pred
        vals = jax.lax.cond(
            any_live,
            lambda _: tuple(fn() for _, _, fn, _ in soft_terms),
            lambda _: tuple(fn() for _, _, _, fn in soft_terms),
            None,
        )
        for (wi, _, _, _), val in zip(soft_terms, vals):
            score += w_[wi] * val
    else:
        for wi, pred, live, skip in soft_terms:
            score += w_[wi] * jax.lax.cond(
                pred,
                lambda _, fn=live: fn(),
                lambda _, fn=skip: fn(),
                None,
            )
    # ImageLocality + NodePreferAvoidPods (static per group)
    if f.static_score:
        score += w_[9] * statics.static_score[g] + w_[11] * statics.avoid_pen[g]
    return jnp.where(m_all, score, -jnp.inf)


def filter_and_score(
    statics: StaticArrays, state: SchedState, pod, flags: StepFlags = StepFlags()
) -> StepEval:
    """Run the full filter cascade and score sum for one pod vs every node."""
    (
        g,
        req,
        pin,
        forced,
        lvm_size,
        lvm_vg,
        dev_size,
        dev_media,
        gpu_mem,
        gpu_count,
        gpu_preset,
    ) = pod
    n = statics.alloc.shape[0]
    node_ids = jnp.arange(n)
    t_cap = statics.g_terms.shape[1]
    f = flags

    # row-gather the group's relevant slice of the per-node count state and
    # domain map ([Tc, N] each) via one-hot matmuls (take_rows): -1 padding
    # rows gather as zeros, and tvalid gates the domain validity
    if t_cap:
        terms_g = statics.g_terms[g]  # [Tc]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        dom_sub = take_rows_i32(
            statics.node_dom, jnp.where(tvalid, statics.term_topo[tsafe], -1)
        )
        valid_sub = (dom_sub >= 0) & tvalid[:, None]
        cnt_sub = take_rows(state.cnt_match, terms_g)

    static_m = statics.static_mask[g]
    # pin: -1 = unpinned, -2 = pinned to a nonexistent node (matches nothing)
    pin_m = jnp.where(pin >= 0, node_ids == pin, pin > -2)
    m_static = static_m & pin_m & statics.node_valid
    # NodePorts precedes NodeResourcesFit in the registry filter order
    m_ports = m_static
    if f.ports:
        m_ports = m_static & ports_conflict_free(
            state.ports_used, statics.ports_req[g]
        )
    m_res = m_ports & resources_fit(state.free, req)

    # VolumeRestrictions then NodeVolumeLimits follow NodeResourcesFit in the
    # registry filter order
    m_vol = m_res
    if f.vols:
        m_vol = m_res & volume_conflict_free(
            state.vols_any, state.vols_rw, statics.vol_rw_req[g], statics.vol_ro_req[g]
        )
    m_att = m_vol
    if f.attach:
        m_att = m_vol & attach_limits_ok(
            state.vols_any,
            statics.vol_att_req[g],
            statics.vol_class_mask,
            statics.attach_limits,
        )

    # VolumeBinding + VolumeZone (precomputed per group; PVC/PV/SC objects
    # never change during a simulation)
    m_bind = m_att & statics.vol_mask[g]

    # Open-Local storage (plugin Filter, open-local.go:50-91): pods that need
    # storage only fit nodes carrying the storage annotation.  The planning
    # kernels stream [N, V]/[N, SD] planes — a large share of the per-step
    # cost at 100k nodes — so a storage-free pod skips them via lax.cond
    # (exact: with zero claims lvm_plan/device_plan return all-fits + zero
    # allocations, so the branch outputs are identical constants).
    m_storage = m_bind
    if f.storage:
        needs_storage = jnp.any(lvm_size > 0) | jnp.any(dev_size > 0)

        if f.fused:
            # fused form: plan + the raw Open-Local score share ONE branch
            # pair.  The skip branch's raw 0 min-max-normalizes to exactly
            # 0 — the split form's separate score-skip constant — so the
            # later storage term needs no second cond
            def _storage_plan(_):
                lvm_ok, lvm_alloc = lvm_plan(
                    state.vg_free, statics.vg_name_id, lvm_size, lvm_vg
                )
                dev_ok, dev_take, dev_tight = device_plan(
                    state.sdev_free,
                    statics.sdev_cap,
                    statics.sdev_media,
                    dev_size,
                    dev_media,
                )
                raw = open_local_score(
                    lvm_alloc,
                    statics.vg_cap,
                    dev_tight,
                    jnp.sum(lvm_size > 0),
                    jnp.sum(dev_size > 0),
                )
                return statics.has_storage & lvm_ok & dev_ok, lvm_alloc, dev_take, raw

            def _storage_skip(_):
                return (
                    jnp.ones(n, bool),
                    jnp.zeros_like(statics.vg_cap),
                    jnp.zeros(statics.sdev_cap.shape, bool),
                    jnp.zeros(n, statics.vg_cap.dtype),
                )

            storage_ok, lvm_alloc, dev_take, storage_raw = jax.lax.cond(
                needs_storage, _storage_plan, _storage_skip, None
            )
        else:

            def _storage_plan(_):
                lvm_ok, lvm_alloc = lvm_plan(
                    state.vg_free, statics.vg_name_id, lvm_size, lvm_vg
                )
                dev_ok, dev_take, dev_tight = device_plan(
                    state.sdev_free,
                    statics.sdev_cap,
                    statics.sdev_media,
                    dev_size,
                    dev_media,
                )
                return statics.has_storage & lvm_ok & dev_ok, lvm_alloc, dev_take, dev_tight

            def _storage_skip(_):
                return (
                    jnp.ones(n, bool),
                    jnp.zeros_like(statics.vg_cap),
                    jnp.zeros(statics.sdev_cap.shape, bool),
                    jnp.zeros(n, statics.vg_cap.dtype),
                )

            storage_ok, lvm_alloc, dev_take, dev_tight = jax.lax.cond(
                needs_storage, _storage_plan, _storage_skip, None
            )
        m_storage = m_bind & storage_ok
    else:
        lvm_alloc = jnp.zeros_like(statics.vg_cap)
        dev_take = jnp.zeros(statics.sdev_cap.shape, bool)

    # GPU share (plugin Filter, open-gpu-share.go:51-81); same per-pod skip —
    # non-GPU pods fit everywhere with zero shares by gpu_plan's own contract
    m_gpu = m_storage
    if f.gpu:
        is_gpu_pod = gpu_mem > 0

        def _gpu_plan(_):
            return gpu_plan(
                state.gpu_free,
                statics.gpu_dev_exists,
                statics.gpu_total,
                gpu_mem,
                gpu_count,
                gpu_preset,
            )

        def _gpu_skip(_):
            return jnp.ones(n, bool), jnp.zeros_like(state.gpu_free)

        gpu_ok, gpu_shares = jax.lax.cond(is_gpu_pod, _gpu_plan, _gpu_skip, None)
        m_gpu = m_storage & gpu_ok
    else:
        gpu_shares = jnp.zeros_like(state.gpu_free)

    # PodTopologySpread hard constraints (filtering.go); eligible-domain
    # minimum taken over nodes passing the pod's static filters.
    # maxSkew 0 = inactive on every term → all-True; per-pod skip of
    # the [Tc, N] streams (lax.cond)
    sh_active = f.spread_hard and t_cap
    ir_active = f.interpod_req and t_cap
    if sh_active:
        has_spread = jnp.any(statics.spread_hard[g] > 0)

        def _spread_filter():
            return topology_spread_filter(
                cnt_sub, valid_sub, statics.spread_hard[g], m_static
            )

    if ir_active:
        ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)

        def _ip_filter():
            return interpod_filter(
                cnt_sub,
                take_rows(state.cnt_own_anti, ip_eff),
                valid_sub,
                jnp.where(tvalid, state.cnt_total[tsafe], 0.0),
                statics.s_match[g],
                statics.a_aff_req[g],
                statics.a_anti_req[g],
            )

        # the filter can bite a pod with NO required terms of its own when
        # an existing pod's anti-affinity selects it (sym_violated), so the
        # skip predicate includes s_match on interpod-owned terms
        touches_ip = (
            jnp.any(statics.a_aff_req[g])
            | jnp.any(statics.a_anti_req[g])
            | jnp.any(statics.s_match[g] & tvalid & (ip_eff >= 0))
        )

    if f.fused and sh_active and ir_active:
        # fused form: one branch pair for both [Tc, N]-streaming filters.
        # A dormant filter evaluated in the live branch is all-True (zero
        # maxSkew / no touching terms), matching its skip constant exactly
        spread_m, ip_m = jax.lax.cond(
            has_spread | touches_ip,
            lambda _: (_spread_filter(), _ip_filter()),
            lambda _: (jnp.ones(n, bool), jnp.ones(n, bool)),
            None,
        )
        m_spread = m_gpu & spread_m
        m_all = m_spread & ip_m
    else:
        m_spread = m_gpu
        if sh_active:
            m_spread = m_gpu & jax.lax.cond(
                has_spread,
                lambda _: _spread_filter(),
                lambda _: jnp.ones(n, bool),
                None,
            )
        m_all = m_spread
        if ir_active:
            m_all = m_spread & jax.lax.cond(
                touches_ip,
                lambda _: _ip_filter(),
                lambda _: jnp.ones(n, bool),
                None,
            )
    feasible = jnp.any(m_all)

    # the Open-Local term is computed outside score_pod so the storage-free
    # base score comes for free (the bulk rounds engine needs it for its
    # within-round slope without a second full score pass)
    score = score_pod(statics, state, g, req, m_all, flags)
    storage_term = 0.0
    if f.storage:
        if f.fused:
            # the fused storage cond already produced the raw score (0 for
            # storage-free pods, which min-max-normalizes to exactly 0 —
            # the split form's skip constant); only the cheap [N] normalize
            # remains outside the branch
            storage_term = statics.score_w[10] * minmax_normalize(
                storage_raw, m_all
            )
        else:
            # zero claims → open_local_score is all-zero → the normalized
            # term is exactly 0 everywhere; skip the [N, V] streams for
            # such pods
            def _storage_term(_):
                storage_raw = open_local_score(
                    lvm_alloc,
                    statics.vg_cap,
                    dev_tight,
                    jnp.sum(lvm_size > 0),
                    jnp.sum(dev_size > 0),
                )
                return statics.score_w[10] * minmax_normalize(storage_raw, m_all)

            storage_term = jax.lax.cond(
                needs_storage,
                _storage_term,
                lambda _: jnp.zeros(n, statics.vg_cap.dtype),
                None,
            )

    return StepEval(
        m_static=m_static,
        m_ports=m_ports,
        m_res=m_res,
        m_vol=m_vol,
        m_att=m_att,
        m_bind=m_bind,
        m_storage=m_storage,
        m_gpu=m_gpu,
        m_spread=m_spread,
        m_all=m_all,
        score=score + storage_term,
        score_nostorage=score,
        lvm_alloc=lvm_alloc,
        dev_take=dev_take,
        gpu_shares=gpu_shares,
    )


def schedule_step(
    statics: StaticArrays, state: SchedState, pod, flags: StepFlags = StepFlags()
) -> Tuple[SchedState, Tuple[jnp.ndarray, jnp.ndarray]]:
    """One scheduling cycle for one pod against every node."""
    (
        g,
        req,
        pin,
        forced,
        lvm_size,
        lvm_vg,
        dev_size,
        dev_media,
        gpu_mem,
        gpu_count,
        gpu_preset,
    ) = pod
    f = flags
    use_topo = (
        f.spread_hard or f.spread_soft or f.selector_spread
        or f.interpod_req or f.interpod_pref
    )
    t_cap = statics.g_terms.shape[1] if use_topo else 0
    ev = filter_and_score(statics, state, pod, flags)
    lvm_alloc, dev_take, gpu_shares = ev.lvm_alloc, ev.dev_take, ev.gpu_shares
    feasible = jnp.any(ev.m_all)

    chosen = jnp.where(forced, pin, jnp.argmax(ev.score).astype(jnp.int32))
    # forced pods must still land on a node of THIS candidate cluster: the
    # batched sweep expands DaemonSet pods for every clone node, and a clone
    # outside the candidate must not absorb state updates (topology counts,
    # free resources) that would corrupt smaller candidates
    placed = jnp.where(
        forced, (pin >= 0) & statics.node_valid[jnp.clip(pin, 0)], feasible
    )
    reason = jnp.where(
        placed, OK, jnp.where(forced, FAIL_NO_NODE, ev.fail_code())
    ).astype(jnp.int32)

    # -- state update (no-op when not placed) -----------------------------
    safe = jnp.clip(chosen, 0)
    w = jnp.where(placed, 1.0, 0.0)
    updates = {"free": state.free.at[safe].add(-req * w)}
    if f.ports:
        updates["ports_used"] = state.ports_used.at[safe].add(
            statics.ports_req[g] * w
        )
    if f.vols or f.attach:
        v_rw = statics.vol_rw_req[g]
        v_present = v_rw | statics.vol_ro_req[g] | statics.vol_att_req[g]
        updates["vols_any"] = state.vols_any.at[safe].add(v_present * w)
        if f.vols:
            updates["vols_rw"] = state.vols_rw.at[safe].add(v_rw * w)
    if f.storage:
        updates["vg_free"] = state.vg_free.at[safe].add(-lvm_alloc[safe] * w)
        updates["sdev_free"] = state.sdev_free.at[safe].set(
            state.sdev_free[safe] & ~(dev_take[safe] & placed)
        )
    if f.gpu:
        updates["gpu_free"] = state.gpu_free.at[safe].add(
            -gpu_shares[safe] * gpu_mem * w
        )
    pod_lvm_alloc = lvm_alloc[safe] * w
    pod_dev_take = dev_take[safe] & placed
    pod_gpu_shares = gpu_shares[safe] * w

    if t_cap:
        # same-domain increment on the group's relevant term rows only:
        # every node sharing the chosen node's domain for term t gains the
        # pod's incidence — a [Tc, N] compare + matmul row add (add_rows)
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        dom_sub = take_rows_i32(
            statics.node_dom, jnp.where(tvalid, statics.term_topo[tsafe], -1)
        )
        valid_sub = (dom_sub >= 0) & tvalid[:, None]
        dom_chosen = dom_sub[:, safe]  # [Tc]
        valid_chosen = (dom_chosen >= 0) & tvalid & placed  # [Tc]
        same = (
            valid_sub
            & (dom_sub == dom_chosen[:, None])
            & valid_chosen[:, None]
        )
        inc = jnp.where(same, 1.0, 0.0)  # [Tc, N]

        def bump(arr, vals):
            return add_rows(arr, terms_g, vals[:, None] * inc)

        updates["cnt_match"] = bump(state.cnt_match, statics.s_match[g])
        updates["cnt_total"] = state.cnt_total.at[tsafe].add(
            statics.s_match[g] * jnp.where(valid_chosen, 1.0, 0.0)
        )
        if f.interpod_req or f.interpod_pref:
            # the own planes live on the compacted interpod axis; -1 rows
            # (non-interpod terms) are inert through the one-hot matmul
            ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)

            def bump_ip(arr, vals):
                return add_rows(arr, ip_eff, vals[:, None] * inc)

        if f.interpod_req:
            updates["cnt_own_anti"] = bump_ip(state.cnt_own_anti, statics.a_anti_req[g])
            updates["cnt_own_aff"] = bump_ip(state.cnt_own_aff, statics.a_aff_req[g])
        if f.interpod_pref:
            updates["w_own_aff_pref"] = bump_ip(
                state.w_own_aff_pref, statics.w_aff_pref[g]
            )
            updates["w_own_anti_pref"] = bump_ip(
                state.w_own_anti_pref, statics.w_anti_pref[g]
            )
    new_state = state._replace(**updates)

    out_node = jnp.where(placed, chosen, -1)
    return new_state, (out_node, reason, pod_lvm_alloc, pod_dev_take, pod_gpu_shares)


@partial(jax.jit, static_argnums=(3,), donate_argnums=(1,))
def _run_scan(statics: StaticArrays, state: SchedState, pods, flags: StepFlags = StepFlags()):
    count_trace("scan")
    return jax.lax.scan(partial(schedule_step, statics, flags=flags), state, pods)


# -- chunked + sliced serial scan -------------------------------------------
#
# At 100k nodes x thousands of interned terms, each scan step's memory
# traffic dominates the per-pod cost (~172 pods/s at the north-star shape,
# BENCH_r04): the [T, N] count-plane reads/writes AND the per-step `arr[g]`
# row gathers from six [G, N] statics planes (profiled at ~1 GB/s effective
# on the tunneled backend).  But one pod only ever touches its GROUP's few
# term rows, and consecutive pods overwhelmingly share a group — so the
# scan runs in chunks that carry ONLY (a) the union of their pods' term
# rows (a [rows<=256, N] count plane instead of [T, N]; one gather + one
# in-place scatter per context change) and (b) the chunk's group rows of
# every group-indexed statics array (a [<=64, N] plane instead of
# [G=1000, N]).  The same compaction the bulk engine's `_chunk_runs`
# applies to rounds (rounds.py), applied to the serial referee.
# Placements are bit-identical: a step reads/writes term rows only through
# `statics.g_terms[g]` and group rows only through the remapped pod `g`.

_SCAN_CHUNK = 1024  # pods per dispatch (pow2-padded tail; bounded shapes)
_SCAN_ROW_BUDGET = 224  # target carried term rows (pow2-padded, like rounds)
_SCAN_GROUP_BUDGET = 64  # target carried group rows (pow2-padded)

#: statics fields whose LEADING axis is the group axis — the chunked scan
#: slices these to the chunk's group set, turning every per-step `arr[g]`
#: row gather (six of them are [G, N] planes) into a row pick from a
#: [<=64, ...] array.  Keep in sync with StaticArrays / statics_from.
_GROUP_FIELDS = (
    "static_mask",
    "vol_mask",
    "node_pref",
    "taint_intol",
    "static_score",
    "avoid_pen",
    "g_terms",
    "s_match",
    "a_aff_req",
    "a_anti_req",
    "w_aff_pref",
    "w_anti_pref",
    "spread_hard",
    "spread_soft",
    "ss_host",
    "ss_zone",
    "ports_req",
    "vol_rw_req",
    "vol_ro_req",
    "vol_att_req",
)


def _pow2_up(x: int) -> int:
    return 1 << max(x - 1, 0).bit_length()


@jax.jit
def _gather_rows_tuple(arrs, gs):
    """Row-gather each array in `arrs` (one fused device call per slice
    context; passing whole StaticArrays through jit would copy every
    untouched field on the way out)."""
    return tuple(a[gs] for a in arrs)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(plane: jnp.ndarray, rows: jnp.ndarray, values: jnp.ndarray):
    """plane[rows] = values, in place (the full plane is donated — an eager
    .at[].set would copy the whole plane per flush)."""
    return plane.at[rows].set(values)


def pad_pods_pow2(seg, target: int):
    """Pad pod-tuple arrays to `target` rows with inert pods: forced with
    pin=-1 never places and never touches state (schedule_step's forced
    path), so padded scan segments are placement-neutral.  Pow2 targets keep
    the compiled-shape set bounded (each length is a separate executable)."""
    pad = target - seg[0].shape[0]
    if pad <= 0:
        return seg
    out = []
    for idx, arr in enumerate(seg):
        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        if idx == 2:  # pin
            out.append(jnp.pad(arr, widths, constant_values=-1))
        elif idx == 3:  # forced
            out.append(jnp.pad(arr, widths, constant_values=True))
        else:
            out.append(jnp.pad(arr, widths))
    return tuple(out)


def remap_term_ids(g_terms: np.ndarray, rows: np.ndarray, t: int) -> np.ndarray:
    """Remap a [., Tc] term-incidence matrix onto the sliced row axis given
    by `rows` (-1 padding passes through).  Single home for the inverse
    remap both the chunked scan and the bulk chunks rely on — the sliced
    and bulk paths must never drift on the padding convention."""
    inv = np.zeros(t, np.int32)
    inv[rows] = np.arange(len(rows), dtype=np.int32)
    return np.where(g_terms >= 0, inv[np.clip(g_terms, 0, None)], -1).astype(
        np.int32
    )


def pad_row_ids(rows: np.ndarray, t: int):
    """Pad a sorted term-row list to a power of two with DISTINCT unused
    term ids (their values ride along unchanged; duplicates would let a
    stale copy win the scatter-back).  None = carrying the full plane is
    cheaper (pow2 would reach t anyway)."""
    rows = np.asarray(rows, np.int32)
    u_pad = _pow2_up(len(rows))
    if u_pad >= t:
        return None
    pad = u_pad - len(rows)
    if pad > 0:
        unused = np.setdiff1d(np.arange(t, dtype=np.int32), rows)[:pad]
        rows = np.concatenate([rows, unused])
    return rows


def _sliced_statics_fields(statics, rows_p):
    """The group-axis statics fields a chunk context actually gathers:
    `g_terms` is excluded when a host-remapped copy replaces it (row-sliced
    contexts) and [1, N]-collapsed constant planes are never gathered.
    Shared by run_scan_chunked and the precompile shape enumerator
    (engine/precompile.py) — the two must agree on the sliced shapes or the
    AOT executables would never match a dispatch signature."""
    fields = _GROUP_FIELDS
    if rows_p is not None:
        fields = tuple(f for f in fields if f != "g_terms")
    return tuple(f for f in fields if getattr(statics, f).shape[0] > 1)


def plan_scan_chunks(
    groups: np.ndarray,
    tensors,
    flags: StepFlags,
    chunk: int = None,
    row_budget: int = None,
    wave_ok: np.ndarray = None,
):
    """The deterministic chunk plan of a chunked serial scan: yields
    (c0, c1, gs_p, rows_p, waves) per chunk, where gs_p is the padded group
    set the chunk's statics are sliced to (None = full planes), rows_p the
    padded term-row list its count planes carry (None = full plane), and
    waves the chunk's wavefront sub-plan — absolute (a, b) ranges dispatched
    through the speculative wavefront executable instead of the general
    scan (empty without `wave_ok`, the per-pod eligibility mask from
    `wave_pod_mask`).

    Single source of truth for the chunk contexts — `run_scan_chunked`
    executes this plan, and the AOT precompiler (engine/precompile.py)
    walks the same plan to enumerate the executables a run will need
    before the first dispatch."""
    chunk = _SCAN_CHUNK if chunk is None else chunk
    row_budget = _SCAN_ROW_BUDGET if row_budget is None else row_budget
    n = groups.shape[0]
    t = int(tensors.n_terms)
    use_topo = (
        flags.spread_hard
        or flags.spread_soft
        or flags.selector_spread
        or flags.interpod_req
        or flags.interpod_pref
    )
    row_sliceable = bool(t) and use_topo and _pow2_up(min(t, row_budget)) < t
    g_total = len(tensors.groups)  # statics planes may be [1, N]-collapsed
    group_sliceable = _pow2_up(min(g_total, _SCAN_GROUP_BUDGET)) < g_total
    g_terms_host = _compact_terms(tensors)[0] if row_sliceable else None
    wave_hard = _wave_group_hard(tensors) if wave_ok is not None else None
    wave_pref = _wave_group_pref(tensors) if wave_ok is not None else None
    use_ip = flags.interpod_req or flags.interpod_pref
    for c0 in range(0, n, chunk):
        c1 = min(c0 + chunk, n)
        gs = np.unique(groups[c0:c1])
        gs_p = None
        if group_sliceable and len(gs) <= _SCAN_GROUP_BUDGET:
            # duplicate padding is fine here: the group axis is read-only
            pad = _pow2_up(len(gs)) - len(gs)
            gs_p = np.concatenate([gs, np.repeat(gs[-1:], pad)]).astype(np.int32)
        rows_p = None
        if row_sliceable:
            rows = np.unique(g_terms_host[gs])
            rows = rows[rows >= 0]
            if len(rows) <= row_budget:
                rows_p = pad_row_ids(np.sort(rows), t)
        waves = (
            _plan_waves(
                groups, wave_ok, c0, c1, wave_hard, wave_pref,
                use_topo, use_ip,
            )
            if wave_ok is not None
            else []
        )
        yield c0, c1, gs_p, rows_p, waves


def run_scan_chunked(
    statics: StaticArrays,
    state: SchedState,
    pods,
    flags: StepFlags,
    tensors,
    groups: np.ndarray,
    scan_call=None,
    chunk: int = None,
    row_budget: int = None,
    prefetch=None,
    wave_call=None,
):
    """Serial-equivalent scan over `pods`, dispatched in pow2 chunks whose
    count planes are sliced to each chunk's term-row union.

    `groups` is the host-side group id per pod (drives the row unions).
    `scan_call(statics, state, seg, flags)` defaults to the compiled
    `_run_scan`; engines pass their sharded variants.  `prefetch` (a
    pytree→pytree callable, typically a non-blocking jax.device_put) is
    applied to the next pod segment right after the current one dispatches,
    so the host→device transfer rides the queue while the current segment
    executes (double-buffered streaming — at most one prepared segment is
    in flight ahead of the dispatch point).  With `wave_call` (the
    speculative wavefront executable, `_run_wavefront`'s calling
    convention), eligible same-group runs inside each chunk dispatch
    through it instead of the general scan — placements stay bit-identical
    and the accept flags feed the wavefront.* counters.  Returns (final_state, host
    output tuple) — outputs are numpy, truncated to the real pod count."""
    call = scan_call or _run_scan
    n = groups.shape[0]
    if n == 0:  # preserve _run_scan's total contract (empty outputs)
        state, outs = call(statics, state, pods, flags)
        return state, tuple(np.asarray(o) for o in fetch_outputs(outs))
    t = int(tensors.n_terms)
    g_total = len(tensors.groups)
    wave_ok = (
        wave_eligibility(pods, groups, tensors) if wave_call is not None else None
    )
    plan = list(
        plan_scan_chunks(groups, tensors, flags, chunk, row_budget, wave_ok)
    )
    # flatten the chunk plan into dispatches: each chunk's wavefront runs
    # interleave with the general-scan remainders, in pod order
    dispatches = []  # (plan index, kind, a, b, (hard, pref)), [a, b) absolute
    for i, (c0, c1, _, _, waves) in enumerate(plan):
        for seg in flatten_wave_segments(c0, c1, waves):
            dispatches.append((i,) + seg)

    inv_g_cache = {}

    def prep_range(i, a, b):
        """Host-gather + pad + (optionally) start the device transfer of
        [a, b)'s pod segment under chunk plan entry i's group slicing.
        Pure function of the plan — safe to run one dispatch ahead of the
        dispatch point, and re-entrant for the OOM-backoff replays."""
        gs_p = plan[i][2]
        seg_arrays = [arr[a:b] for arr in pods]
        if gs_p is not None:
            inv_g = inv_g_cache.get(i)
            if inv_g is None:
                inv_g = np.zeros(g_total, np.int32)
                inv_g[gs_p] = np.arange(len(gs_p), dtype=np.int32)
                inv_g_cache[i] = inv_g
            seg_arrays[0] = inv_g[np.asarray(seg_arrays[0])]
        seg = pad_pods_pow2(tuple(seg_arrays), _pow2_up(b - a))
        return prefetch(seg) if prefetch is not None else seg

    def prep_seg(di):
        """Dispatch di's prepared pod segment (see prep_range)."""
        i, _, a, b, _ = dispatches[di]
        return prep_range(i, a, b)

    # active slice context: the (group set, term-row set) the current
    # eff_statics / sliced count planes were built for
    ctx_key = None
    ctx_rows = None  # term rows carried in the sliced count planes
    full_match = full_total = None

    def flush(state):
        nonlocal ctx_rows, full_match, full_total
        if ctx_rows is None:
            return state
        rows_dev = jnp.asarray(ctx_rows)
        state = state._replace(
            cnt_match=_scatter_rows(full_match, rows_dev, state.cnt_match),
            cnt_total=_scatter_rows(full_total, rows_dev, state.cnt_total),
        )
        ctx_rows, full_match, full_total = None, None, None
        return state

    def backoff_scan(state, i, a, b, eff):
        """Replay [a, b) through the general scan in halves after a
        RESOURCE_EXHAUSTED (durable/backoff.py): scan chunking is
        serial-equivalent, so any split replays to bit-identical
        placements, and the pow2 halves re-snap into existing shape
        buckets.  Returns (state, [(outs, real, None), ...])."""
        mid = a + (b - a) // 2
        entries = []
        for x, y in ((a, mid), (mid, b)):
            try:
                with span("scan.chunk", pods=int(y - x), backoff=True):
                    state, outs = call(eff, state, prep_range(i, x, y), flags)
                entries.append((outs, y - x, None))
            except Exception as exc:
                if not is_resource_exhausted(exc) or y - x <= 1:
                    raise
                record_backoff(y - x, (y - x + 1) // 2)
                state, sub = backoff_scan(state, i, x, y, eff)
                entries.extend(sub)
        return state, entries

    outs_dev = []
    eff_statics = statics
    g_terms_host = _compact_terms(tensors)[0]
    next_seg = prep_seg(0)
    for di, (i, kind, a, b, w_mode) in enumerate(dispatches):
        _, _, gs_p, rows_p, _ = plan[i]
        key = (
            None if gs_p is None else gs_p.tobytes(),
            None if rows_p is None else rows_p.tobytes(),
        )
        if key != ctx_key:
            # consecutive chunks usually share a (group, rows) context —
            # re-slice only when it actually changes
            state = flush(state)
            eff_statics = statics
            if gs_p is not None:
                gs_dev = jnp.asarray(gs_p)
                fields = _sliced_statics_fields(statics, rows_p)
                sliced = _gather_rows_tuple(
                    tuple(getattr(statics, f) for f in fields), gs_dev
                )
                eff_statics = eff_statics._replace(**dict(zip(fields, sliced)))
                if rows_p is not None:
                    eff_statics = eff_statics._replace(
                        g_terms=jnp.asarray(
                            remap_term_ids(g_terms_host[gs_p], rows_p, t)
                        )
                    )
            elif rows_p is not None:
                eff_statics = eff_statics._replace(
                    g_terms=jnp.asarray(
                        remap_term_ids(g_terms_host, rows_p, t)
                    )
                )
            if rows_p is not None:
                ip_of = interpod_term_index(tensors)
                eff_statics = eff_statics._replace(
                    term_topo=jnp.asarray(tensors.term_topo_key[rows_p]),
                    ip_of=jnp.asarray(ip_of[rows_p]),
                )
                rows_dev = jnp.asarray(rows_p)
                full_match, full_total = state.cnt_match, state.cnt_total
                state = state._replace(
                    cnt_match=state.cnt_match[rows_dev],
                    cnt_total=state.cnt_total[rows_dev],
                )
                ctx_rows = rows_p
            ctx_key = key
        seg = next_seg
        try:
            if kind == "wave":
                with span("scan.wave", pods=int(b - a)):
                    state, outs, accepts = wave_call(
                        eff_statics, state, seg, flags,
                        wave_static_spec(
                            tensors, w_mode[0], w_mode[1], w_mode[2]
                        ),
                    )
                if w_mode[0]:
                    _WAVE["draft_hard"].inc(int(b - a))
            else:
                with span("scan.chunk", pods=int(b - a)):
                    state, outs = call(eff_statics, state, seg, flags)
                accepts = None
            entries = [(outs, b - a, accepts)]
        except Exception as exc:
            # OOM backoff: halve the segment and replay from the carried
            # state through the general scan (an OOM'd WAVEFRONT also
            # replays through the scan — placements are bit-identical by
            # the speculation contract, it merely forfeits that run's
            # accept telemetry).  Single-pod segments cannot shrink.
            if not is_resource_exhausted(exc) or b - a <= 1:
                raise
            record_backoff(b - a, (b - a + 1) // 2)
            state, entries = backoff_scan(state, i, a, b, eff_statics)
        # double buffer: the next segment starts its transfer while this
        # one executes (the dispatch above is async)
        if di + 1 < len(dispatches):
            next_seg = prep_seg(di + 1)
        # keep outputs on device: a per-chunk device_get would sync the
        # tunnel once per chunk; all dispatches queue first and one
        # batched transfer materializes everything afterwards
        outs_dev.extend(entries)
    state = flush(state)
    fetched = fetch_outputs([(o, acc) for o, _, acc in outs_dev])
    outs_host = []
    for (seg_outs, accepts_h), (_, real, _) in zip(fetched, outs_dev):
        outs_host.append(tuple(np.asarray(o)[:real] for o in seg_outs))
        if accepts_h is not None:
            acc = np.asarray(accepts_h)[:real]
            prefix = int(real) if acc.all() else int(acc.argmin())
            _WAVE["wavefronts"].inc()
            _WAVE["pods"].inc(int(real))
            _WAVE["accepted"].inc(prefix)
            if prefix < real:
                _WAVE["rollbacks"].inc()
                _WAVE["rollback_pods"].inc(int(real) - prefix)
                instant(
                    "wave.rollback",
                    pods=int(real) - prefix, accepted=prefix,
                )
    if len(outs_host) == 1:
        return state, outs_host[0]
    merged = tuple(
        np.concatenate([seg_outs[i] for seg_outs in outs_host])
        for i in range(len(outs_host[0]))
    )
    return state, merged


# -- speculative wavefront scan ---------------------------------------------
#
# The serial referee's remaining cost after chunking is the per-pod step
# itself: every `lax.scan` step re-gathers the pod's group rows from ~20
# sliced statics planes, streams the chunk's carried count rows through
# one-hot matmuls, and drags the storage/GPU/ports/volumes machinery along
# even for pods that use none of it.  But the pod sequence is dominated by
# RUNS — consecutive pods of one group (1000-replica deployments) whose
# feasible-node sets and resource deltas interact only through `free` and
# the group's OWN handful of topology terms.  The wavefront dispatcher
# exploits that, speculative-decoding style (docs/speculation.md):
#
# 1. A host-side planner partitions each chunk's pod sequence into
#    wavefronts: maximal same-group runs of LEAN pods (unpinned, unforced,
#    no storage/GPU demand, a group requesting no host ports or volumes —
#    `wave_pod_mask`).  Everything else stays on the general serial scan.
# 2. One jitted call per wavefront (`_run_wavefront`) places the whole run:
#    the speculative step evaluates the run's spec ONCE against the
#    wavefront-start state (the batched placement every pod would get if
#    the run's pods could not interact at all), then a compiled VERIFIER
#    replays the serial tie-break order pod-at-a-time over a reduced carry —
#    `free` plus the group's own [Tc, N] count-row slices, with every
#    group-row gather hoisted out of the loop — emitting each pod's exact
#    serial placement plus an accept flag (speculation == serial).
# 3. Accept-longest-prefix: pods up to the first divergence kept their
#    speculative placement (the accept flags prove it); every pod beyond it
#    is rolled back and takes the verifier's replayed serial answer.  The
#    committed state is always the verifier's — placements are bit-identical
#    to the pod-at-a-time scan by construction, and the `wavefront.*`
#    registry counters report the acceptance rate and rollback volume.
#
# Bit-exactness rests on three pinned facts: (a) the verifier computes the
# same kernel calls in the same order as `filter_and_score`/`score_pod` on
# inputs that are bitwise equal (take_rows' one-hot matmul reproduces plane
# rows exactly, and the per-pod lax.cond skips it replaces are themselves
# exact — a zero-term group's skipped kernels return the same constants the
# unconditional kernels do); (b) a lean pod's storage/GPU/ports/volumes
# stages reduce to the same all-true masks and zero plans the general step's
# skip branches produce; (c) the carried count-row slices hold small
# integers (counts and integer preference weights), so folding their deltas
# back into the full planes is float-exact below 2^24.

#: minimum run length worth a wavefront dispatch (shorter runs stay on the
#: general scan; mirrors RoundsEngine.MIN_RUN's reasoning)
_WAVE_MIN = 8

# heavy-drafting stage bits (wave_eligibility / the hard verifier's per-step
# recomputes): a run whose pods carry any of these families is still
# draftable — the hard verifier re-evaluates exactly the flagged stages per
# step instead of relying on the lean run-constant hoists
WAVE_HEAVY_PORTS = 1  # group requests host ports
WAVE_HEAVY_VOLS = 2  # group has volume conflicts / attach limits
WAVE_HEAVY_STORAGE = 4  # pod demands Open-Local LVM / device storage
WAVE_HEAVY_GPU = 8  # pod demands GPU shares


def wave_group_mask(tensors) -> np.ndarray:
    """[G] bool — groups whose pods can ride a wavefront: no host-port and
    no volume requests, so two run members can only interact through free
    resources and the group's own topology terms (both carried exactly by
    the verifier).  Memoized on the tensors object."""
    cached = getattr(tensors, "_wave_group_cache", None)
    if cached is not None:
        return cached
    g_n = len(tensors.groups)
    ok = np.ones(g_n, bool)
    if tensors.n_ports:
        ok &= ~tensors.ports.any(axis=1)
    if tensors.n_vols:
        ok &= ~(
            tensors.vol_rw.any(axis=1)
            | tensors.vol_ro.any(axis=1)
            | tensors.vol_att.any(axis=1)
        )
    object.__setattr__(tensors, "_wave_group_cache", ok)
    return ok


def wave_pod_mask(pods, groups: np.ndarray, tensors) -> np.ndarray:
    """[P] bool — pods eligible for wavefront placement.  With heavy
    drafting on (the default) only pinned/forced pods are excluded: the
    hard verifier recomputes the storage/GPU/ports/volume stages per step,
    so those families draft too.  With SIMTPU_WAVE_HEAVY=0 the pre-round-16
    LEAN restriction applies: no storage/GPU demand, unpinned, unforced,
    and of a port/volume-free group.  Pure host-side numpy over the pod
    tuple (`build_pod_arrays` layout)."""
    ok = (np.asarray(pods[2]) == -1) & ~np.asarray(pods[3])
    if wave_heavy_enabled():
        return ok
    lvm = np.asarray(pods[4])
    if lvm.size:
        ok &= lvm.max(axis=1) <= 0
    dev = np.asarray(pods[6])
    if dev.size:
        ok &= dev.max(axis=1) <= 0
    ok &= np.asarray(pods[8]) <= 0
    ok &= wave_group_mask(tensors)[groups]
    return ok


def wave_group_heavy(tensors) -> np.ndarray:
    """[G] int16 — per-group heavy stage bits (WAVE_HEAVY_PORTS / _VOLS):
    which group-level constraint families the hard verifier must recompute
    per step for a run of this group.  Memoized on the tensors object."""
    cached = getattr(tensors, "_wave_heavy_cache", None)
    if cached is not None:
        return cached
    g_n = len(tensors.groups)
    bits = np.zeros(g_n, np.int16)
    if tensors.n_ports:
        bits |= np.where(
            tensors.ports.any(axis=1), WAVE_HEAVY_PORTS, 0
        ).astype(np.int16)
    if tensors.n_vols:
        vol = (
            tensors.vol_rw.any(axis=1)
            | tensors.vol_ro.any(axis=1)
            | tensors.vol_att.any(axis=1)
        )
        bits |= np.where(vol, WAVE_HEAVY_VOLS, 0).astype(np.int16)
    object.__setattr__(tensors, "_wave_heavy_cache", bits)
    return bits


def wave_eligibility(pods, groups: np.ndarray, tensors) -> np.ndarray:
    """[P] int16 — -1 for wavefront-ineligible pods, else the heavy stage
    bits the verifier needs for that pod (0 = pure LEAN).  The planner
    breaks runs on value changes, so a run is homogeneous in both group and
    heavy bits."""
    ok = wave_pod_mask(pods, groups, tensors)
    bits = np.zeros(len(ok), np.int16)
    if wave_heavy_enabled():
        bits = wave_group_heavy(tensors)[groups].astype(np.int16)
        stor = np.zeros(len(ok), bool)
        lvm = np.asarray(pods[4])
        if lvm.size:
            stor |= lvm.max(axis=1) > 0
        dev = np.asarray(pods[6])
        if dev.size:
            stor |= dev.max(axis=1) > 0
        bits = bits | np.where(stor, WAVE_HEAVY_STORAGE, 0).astype(np.int16)
        bits = bits | np.where(
            np.asarray(pods[8]) > 0, WAVE_HEAVY_GPU, 0
        ).astype(np.int16)
    return np.where(ok, bits, -1).astype(np.int16)


def _wave_group_hard(tensors) -> np.ndarray:
    """[G] bool — the group owns a hard constraint term (DoNotSchedule skew
    or required (anti-)affinity incidence): its wavefronts take the
    hard-mode verifier, whose masks are recomputed per step.  Everything
    else rides the lean verifier.  Memoized on the tensors object."""
    cached = getattr(tensors, "_wave_hard_cache", None)
    if cached is not None:
        return cached
    g_n = len(tensors.groups)
    hard = np.zeros(g_n, bool)
    if tensors.n_terms:
        hard = (
            (tensors.spread_hard > 0).any(axis=1)
            | tensors.a_aff_req.any(axis=1)
            | tensors.a_anti_req.any(axis=1)
        )
    object.__setattr__(tensors, "_wave_hard_cache", hard)
    return hard


def _wave_group_pref(tensors) -> np.ndarray:
    """[G] bool — the group's own interpod preference weights move its
    interpod raw while it places (lean verifier's `pref` specialization:
    without it the interpod term is wavefront-constant between mask
    flips).  Memoized on the tensors object."""
    cached = getattr(tensors, "_wave_pref_cache", None)
    if cached is not None:
        return cached
    g_n = len(tensors.groups)
    pref = np.zeros(g_n, bool)
    if tensors.n_terms:
        pref = (
            (tensors.w_aff_pref != tensors.w_anti_pref) & tensors.s_match
        ).any(axis=1)
    object.__setattr__(tensors, "_wave_pref_cache", pref)
    return pref


def _plan_waves(
    groups: np.ndarray, wave_ok: np.ndarray, c0: int, c1: int,
    hard_g: np.ndarray, pref_g: np.ndarray, use_topo: bool, use_ip: bool,
):
    """Maximal same-group, same-eligibility runs of wavefront-eligible pods
    within [c0, c1), length >= _WAVE_MIN, as absolute
    (a, b, hard, pref, heavy) entries.  `wave_ok` is wave_eligibility's
    int16 coding (-1 ineligible, else heavy bits); a bool mask (True/False
    → 1/0 under comparison) keeps the pre-round-16 LEAN behaviour.  Any
    heavy bit forces the hard verifier — the per-step stage recomputes live
    there."""
    g = groups[c0:c1]
    if g.shape[0] == 0:
        return []
    ok = np.asarray(wave_ok[c0:c1])
    if ok.dtype == bool:
        ok = np.where(ok, 0, -1).astype(np.int16)
    brk = np.flatnonzero((g[1:] != g[:-1]) | (ok[1:] != ok[:-1])) + 1
    starts = np.concatenate([[0], brk])
    ends = np.concatenate([brk, [len(g)]])
    return [
        (
            int(c0 + a),
            int(c0 + b),
            (use_topo and bool(hard_g[g[a]])) or int(ok[a]) != 0,
            use_ip and bool(pref_g[g[a]]),
            int(ok[a]),
        )
        for a, b in zip(starts, ends)
        if ok[a] >= 0 and b - a >= _WAVE_MIN
    ]


def flatten_wave_segments(c0: int, c1: int, waves):
    """One chunk's dispatch order: ('scan'|'wave', a, b, mode) segments,
    wavefront runs interleaved with the general-scan remainders in pod
    order (mode = (hard, pref, heavy) for waves, None for scan).  The SINGLE
    source of the per-chunk dispatch sequence — run_scan_chunked executes
    it and the AOT enumerator (engine/precompile.py) walks the same list,
    so the precompiled signatures can never drift from the dispatched
    ones."""
    segs = []
    pos = c0
    for wa, wb, w_hard, w_pref, w_heavy in waves:
        if wa > pos:
            segs.append(("scan", pos, wa, None))
        segs.append(("wave", wa, wb, (w_hard, w_pref, w_heavy)))
        pos = wb
    if pos < c1:
        segs.append(("scan", pos, c1, None))
    return segs


def wavefront_scan(
    statics: StaticArrays,
    state: SchedState,
    pods,
    flags: StepFlags = StepFlags(),
    hard: bool = False,
    pref: bool = False,
    heavy: int = 0,
    key_kinds=None,
    n_domains: int = 1,
):
    """Place one same-group lean wavefront (see the section comment).

    Returns (new_state, (node, reason, lvm_alloc, dev_take, gpu_shares),
    accepts): the output tuple matches `_run_scan`'s per-pod layout (the
    extended-resource planes are exact zeros for lean pods, the same values
    the general step's skip branches emit), and `accepts[i]` is True when
    pod i's serial placement equals the speculative wavefront-start answer
    (`node[0]` — every pod of an identical-spec run drafts the same
    argmax).  Padded rows (inert forced pods, `pad_pods_pow2`) never touch
    state and report node -1, exactly like the general scan.

    Two statically specialized verifiers (the planner picks per run):

    - `hard=False` (LEAN): the run owns no hard constraint term (no
      DoNotSchedule skew, no required (anti-)affinity), so the feasibility
      mask can only change where `free` changes — the node the previous
      placement touched.  The verify scan carries [N] vectors only: the
      row-maintained fit mask and free-score, the carried normalized static
      terms (renormalized via lax.cond on the rare fit-mask flip), and the
      group's summed count raws (selector-spread host/zone, soft-spread,
      interpod), updated per step through a [K, N] same-domain indicator
      per topology KEY (K ≈ 2) instead of [Tc, N] per-term streams.  The
      full count planes are reconstructed once post-scan from the choice
      sequence (a per-key domain histogram — exact: counts and preference
      weights are small integers, so every reordered sum is float-exact).
    - `hard=True`: the run owns a quota/affinity domain (hard skew or
      required (anti-)affinity terms), whose masks move domain-wide per
      placement — the verifier recomputes the full filter cascade per step
      over the group's [Tc, N] slices, exactly like the general step.

    `n_domains` (static) sizes the post-scan domain histogram.

    `heavy` (static bits, WAVE_HEAVY_*) marks the constraint families the
    run carries that the lean hoists cannot cover: the planner forces
    hard=True for any heavy run, and the hard verifier re-evaluates exactly
    the flagged stages per step (ports / volume+attach masks against the
    carried occupancy planes, the Open-Local storage planner, the GPU-share
    planner) — the same kernels, flag gating, and skip-branch structure as
    `filter_and_score`, so drafted placements stay bit-identical."""
    g_arr, req_arr, pin_arr, forced_arr = pods[0], pods[1], pods[2], pods[3]
    f = flags
    n = statics.alloc.shape[0]
    g = g_arr[0]
    heavy = int(heavy)
    heavy_ports = bool(heavy & WAVE_HEAVY_PORTS) and f.ports
    heavy_vols = bool(heavy & WAVE_HEAVY_VOLS) and (f.vols or f.attach)
    heavy_storage = bool(heavy & WAVE_HEAVY_STORAGE) and f.storage
    heavy_gpu = bool(heavy & WAVE_HEAVY_GPU) and f.gpu
    use_topo = (
        f.spread_hard or f.spread_soft or f.selector_spread
        or f.interpod_req or f.interpod_pref
    )
    t_cap = statics.g_terms.shape[1] if use_topo else 0
    carry_ip = bool(t_cap) and (f.interpod_req or f.interpod_pref)
    w_ = statics.score_w
    alloc = statics.alloc

    # -- hoisted group rows, state slices, and run invariants (once per
    # wavefront; the general step recomputes all of these per pod) ---------
    # every real pod of the run is unpinned (planner guarantee), so pin_m
    # is all-true and m_static is run-constant; padded rows are forced and
    # never read the masks
    m_static = statics.static_mask[g] & statics.node_valid
    node_pref_g = statics.node_pref[g]
    taint_g = statics.taint_intol[g]
    sscore_g = statics.static_score[g]
    avoid_g = statics.avoid_pen[g]
    # identical specs share one raw Simon score (static allocatable only)
    simon_raw = simon_share(alloc, req_arr[0])
    # wavefront-constant filter stages: the run adds no ports or volumes,
    # so NodePorts / VolumeRestrictions / NodeVolumeLimits cannot change
    # while it places; a lean pod's storage and GPU planners reduce to
    # their skip branches (all-true masks, zero plans).  Boolean AND is
    # exact, so pre-folding the constant stages is mask-identical.
    # heavy stages opt OUT of the hoist (their occupancy planes move while
    # the run places — the hard verifier recomputes them per step against
    # the carried planes); the all-true placeholder keeps the folds inert
    ports_ok = (
        ports_conflict_free(state.ports_used, statics.ports_req[g])
        if f.ports and not heavy_ports
        else jnp.ones(n, bool)
    )
    vol_ok = (
        volume_conflict_free(
            state.vols_any, state.vols_rw,
            statics.vol_rw_req[g], statics.vol_ro_req[g],
        )
        if f.vols and not heavy_vols
        else jnp.ones(n, bool)
    )
    att_ok = (
        attach_limits_ok(
            state.vols_any, statics.vol_att_req[g],
            statics.vol_class_mask, statics.attach_limits,
        )
        if f.attach and not heavy_vols
        else jnp.ones(n, bool)
    )
    vol_mask_g = statics.vol_mask[g]
    m_ports = m_static & ports_ok
    post_res = vol_ok & att_ok & vol_mask_g  # m_res -> m_bind fold
    # heavy group rows (run-constant: same group throughout the run)
    if heavy_ports:
        want_ports = statics.ports_req[g]
    if heavy_vols:
        v_rw_g = statics.vol_rw_req[g]
        v_ro_g = statics.vol_ro_req[g]
        v_att_g = statics.vol_att_req[g]
        v_present_g = v_rw_g | v_ro_g | v_att_g
    # identical specs ⇒ NodeResourcesFit and the two free-dependent score
    # terms change ONLY at the node the previous placement touched: both
    # are carried whole and row-updated per step (the kernels are row-
    # independent, so a [1, R]-slice recompute is bit-identical to the
    # full-width pass the general step pays)
    req0 = req_arr[0]
    m_fit0 = resources_fit(state.free, req0)
    fscore0 = w_[0] * least_allocated(state.free, alloc, req0)
    fscore0 = fscore0 + w_[1] * balanced_allocation(state.free, alloc, req0)

    if t_cap:
        terms_g = statics.g_terms[g]
        tvalid = terms_g >= 0
        tsafe = jnp.clip(terms_g, 0)
        term_keys = jnp.where(tvalid, statics.term_topo[tsafe], -1)
        ip_eff = jnp.where(tvalid, statics.ip_of[tsafe], -1)
        s_match_g = statics.s_match[g]
        a_aff_g = statics.a_aff_req[g]
        a_anti_g = statics.a_anti_req[g]
        w_aff_g = statics.w_aff_pref[g]
        w_anti_g = statics.w_anti_pref[g]
        spread_hard_g = statics.spread_hard[g]
        spread_soft_g = statics.spread_soft[g]
        ss_host_g = statics.ss_host[g]
        ss_zone_g = statics.ss_zone[g]
        dom_sub = take_rows_i32(statics.node_dom, term_keys)
        valid_sub = (dom_sub >= 0) & tvalid[:, None]
        cnt_sub0 = take_rows(state.cnt_match, terms_g)
        ct0 = jnp.where(tvalid, state.cnt_total[tsafe], 0.0)
    if carry_ip:
        own0 = tuple(
            take_rows(plane, ip_eff)
            for plane in (
                state.cnt_own_anti, state.cnt_own_aff,
                state.w_own_aff_pref, state.w_own_anti_pref,
            )
        )

    def fail_from(m_res, m_spread, extra=None):
        """StepEval.fail_code's reversed cascade with the lean-pod stage
        identities (m_vol/m_att = m_res & hoisted conds, m_storage =
        m_gpu = m_bind) substituted."""
        m_vol = m_res & vol_ok
        m_att = m_vol & att_ok
        m_bind = m_att & statics.vol_mask[g]
        fail = jnp.int32(FAIL_INTERPOD)
        for mask, code in (
            (m_spread, FAIL_SPREAD),
            (m_bind, FAIL_GPU),
            (m_bind, FAIL_STORAGE),
            (m_bind, FAIL_VOLUME_BIND),
            (m_att, FAIL_ATTACH),
            (m_vol, FAIL_VOLUME),
            (m_res, FAIL_RESOURCES),
            (m_ports, FAIL_PORTS),
            (m_static, FAIL_STATIC),
        ):
            fail = jnp.where(jnp.any(mask), fail, code)
        return fail

    def free_rows_update(free, m_fit, fscore, safe, req, placed):
        """Row-maintain the carried fit mask and free-score terms: only the
        touched node's free changed, and the kernels are row-independent,
        so a [1, R]-slice recompute reproduces the full pass's row bits.
        Padded (forced) pods never place, so the carry is untouched by
        their zero req rows.  Returns (m_fit, fscore, prev_fit, fit_row)."""
        free_row = free[safe][None, :]
        alloc_row = alloc[safe][None, :]
        fit_row = resources_fit(free_row, req)[0]
        frow = w_[0] * least_allocated(free_row, alloc_row, req)
        frow = frow + w_[1] * balanced_allocation(free_row, alloc_row, req)
        prev_fit = m_fit[safe]
        m_fit = m_fit.at[safe].set(jnp.where(placed, fit_row, prev_fit))
        fscore = fscore.at[safe].set(jnp.where(placed, frow[0], fscore[safe]))
        return m_fit, fscore, prev_fit, fit_row

    if hard or heavy:
        xs = [req_arr, pin_arr, forced_arr]
        if heavy_storage:
            xs += [pods[4], pods[5], pods[6], pods[7]]
        if heavy_gpu:
            xs += [pods[8], pods[9], pods[10]]
        new_state, nodes, reasons, hextras = _wave_verify_hard(
            statics, state, tuple(xs), f,
            locals(),
        )
    else:
        new_state, nodes, reasons = _wave_verify_lean(
            statics, state, (req_arr, pin_arr, forced_arr), f,
            locals(), pref, key_kinds, n_domains,
        )
        hextras = {}

    w_pods = nodes.shape[0]
    # heavy runs report real per-pod extended-resource plans (the hard
    # verifier's per-step planners); lean runs report the exact zeros the
    # general step's skip branches emit
    outs = (
        nodes,
        reasons,
        hextras.get(
            "lvm",
            jnp.zeros((w_pods, statics.vg_cap.shape[1]), statics.vg_cap.dtype),
        ),
        hextras.get(
            "dev", jnp.zeros((w_pods, state.sdev_free.shape[1]), bool)
        ),
        hextras.get(
            "gpu",
            jnp.zeros((w_pods, state.gpu_free.shape[1]), state.gpu_free.dtype),
        ),
    )
    # the speculative wavefront placement is the state_0 answer — what one
    # batched step would assign every pod of the identical-spec run; the
    # first verify step IS that eval, so nodes[0] is the draft
    accepts = nodes == nodes[0]
    return new_state, outs, accepts


def _wave_verify_hard(statics, state, xs, f, env):
    """The hard-mode verifier: full per-step recompute of the group's
    [Tc, N] filter/score slices (quota/affinity domains move domain-wide
    per placement).  `env` carries wavefront_scan's hoists.

    Heavy stage bits (env['heavy_*']) additionally carry the matching
    occupancy planes (ports_used / vols / vg_free / sdev_free / gpu_free)
    through the scan and re-evaluate exactly those cascade stages per step
    — the same kernels and skip-branch structure as `filter_and_score`, so
    storage/GPU/ports/volume runs place bit-identically to the serial
    scan.  Returns (state, nodes, reasons, extras) with extras holding the
    per-pod heavy resource plans ('lvm'/'dev'/'gpu') when carried."""
    (m_static, m_ports, post_res, simon_raw, node_pref_g, taint_g, sscore_g,
     avoid_g, m_fit0, fscore0, w_, alloc, fail_from, free_rows_update) = (
        env["m_static"], env["m_ports"], env["post_res"], env["simon_raw"],
        env["node_pref_g"], env["taint_g"], env["sscore_g"], env["avoid_g"],
        env["m_fit0"], env["fscore0"], env["w_"], env["alloc"],
        env["fail_from"], env["free_rows_update"],
    )
    t_cap = env["t_cap"]
    carry_ip = env["carry_ip"]
    n = env["n"]
    vol_ok, att_ok, vol_mask_g = env["vol_ok"], env["att_ok"], env["vol_mask_g"]
    heavy_ports = env["heavy_ports"]
    heavy_vols = env["heavy_vols"]
    heavy_storage = env["heavy_storage"]
    heavy_gpu = env["heavy_gpu"]
    if heavy_ports:
        want_ports = env["want_ports"]
    if heavy_vols:
        v_rw_g, v_ro_g, v_att_g, v_present_g = (
            env["v_rw_g"], env["v_ro_g"], env["v_att_g"], env["v_present_g"]
        )
    if t_cap:
        (terms_g, tvalid, tsafe, dom_sub, valid_sub, ip_eff, s_match_g,
         a_aff_g, a_anti_g, w_aff_g, w_anti_g, spread_hard_g, spread_soft_g,
         ss_host_g, ss_zone_g, cnt_sub0, ct0) = (
            env["terms_g"], env["tvalid"], env["tsafe"], env["dom_sub"],
            env["valid_sub"], env["ip_eff"], env["s_match_g"], env["a_aff_g"],
            env["a_anti_g"], env["w_aff_g"], env["w_anti_g"],
            env["spread_hard_g"], env["spread_soft_g"], env["ss_host_g"],
            env["ss_zone_g"], env["cnt_sub0"], env["ct0"],
        )
    if carry_ip:
        own0 = env["own0"]

    def vstep(carry, x):
        it_x = iter(x)
        req = next(it_x)
        pin = next(it_x)
        forced = next(it_x)
        if heavy_storage:
            lvm_size = next(it_x)
            lvm_vg = next(it_x)
            dev_size = next(it_x)
            dev_media = next(it_x)
        if heavy_gpu:
            gpu_mem = next(it_x)
            gpu_count = next(it_x)
            gpu_preset = next(it_x)
        it = iter(carry)
        free = next(it)
        m_fit = next(it)
        fscore = next(it)
        if t_cap:
            cnt_sub = next(it)
            ct = next(it)
        if carry_ip:
            own_anti, own_aff, w_own_a, w_own_n = (
                next(it), next(it), next(it), next(it)
            )
        if heavy_ports:
            ports_used = next(it)
        if heavy_vols:
            vols_any = next(it)
            if f.vols:
                vols_rw = next(it)
        if heavy_storage:
            vg_free = next(it)
            sdev_free = next(it)
        if heavy_gpu:
            gpu_free = next(it)
        # filter cascade — same stage structure (and flag gating) as
        # filter_and_score: hoisted run-constant masks for the lean stages,
        # per-step kernel recomputes against the carried occupancy planes
        # for the heavy ones (boolean AND is associative, so folding the
        # constant factors early is mask-identical)
        mp = (
            m_ports & ports_conflict_free(ports_used, want_ports)
            if heavy_ports
            else m_ports
        )
        m_res = mp & m_fit
        vc = (
            volume_conflict_free(vols_any, vols_rw, v_rw_g, v_ro_g)
            if heavy_vols and f.vols
            else vol_ok
        )
        al = (
            attach_limits_ok(
                vols_any, v_att_g,
                statics.vol_class_mask, statics.attach_limits,
            )
            if heavy_vols and f.attach
            else att_ok
        )
        m_vol = m_res & vc
        m_att = m_vol & al
        m_bind = m_att & vol_mask_g
        if heavy_storage:
            needs_storage = jnp.any(lvm_size > 0) | jnp.any(dev_size > 0)

            # fused plan+raw branch pair — bit-identical to the general
            # step's split conds (the skip raw 0 normalizes to exactly 0)
            def _storage_plan(_):
                lvm_ok, lvm_alloc = lvm_plan(
                    vg_free, statics.vg_name_id, lvm_size, lvm_vg
                )
                dev_ok, dev_take, dev_tight = device_plan(
                    sdev_free,
                    statics.sdev_cap,
                    statics.sdev_media,
                    dev_size,
                    dev_media,
                )
                raw = open_local_score(
                    lvm_alloc,
                    statics.vg_cap,
                    dev_tight,
                    jnp.sum(lvm_size > 0),
                    jnp.sum(dev_size > 0),
                )
                return (
                    statics.has_storage & lvm_ok & dev_ok,
                    lvm_alloc, dev_take, raw,
                )

            def _storage_skip(_):
                return (
                    jnp.ones(n, bool),
                    jnp.zeros_like(statics.vg_cap),
                    jnp.zeros(statics.sdev_cap.shape, bool),
                    jnp.zeros(n, statics.vg_cap.dtype),
                )

            storage_ok, lvm_alloc, dev_take, storage_raw = jax.lax.cond(
                needs_storage, _storage_plan, _storage_skip, None
            )
            m_storage = m_bind & storage_ok
        else:
            m_storage = m_bind
        if heavy_gpu:
            is_gpu_pod = gpu_mem > 0

            def _gpu_plan(_):
                return gpu_plan(
                    gpu_free,
                    statics.gpu_dev_exists,
                    statics.gpu_total,
                    gpu_mem,
                    gpu_count,
                    gpu_preset,
                )

            def _gpu_skip(_):
                return jnp.ones(n, bool), jnp.zeros_like(gpu_free)

            gpu_ok, gpu_shares = jax.lax.cond(
                is_gpu_pod, _gpu_plan, _gpu_skip, None
            )
            m_gpu = m_storage & gpu_ok
        else:
            m_gpu = m_storage
        m_spread = m_gpu
        if f.spread_hard and t_cap:
            # unconditional kernel == the general step's lax.cond: with no
            # active skew terms every node passes (active = max_skew > 0)
            m_spread = m_gpu & topology_spread_filter(
                cnt_sub, valid_sub, spread_hard_g, m_static
            )
        m_all = m_spread
        if f.interpod_req and t_cap:
            m_all = m_spread & interpod_filter(
                cnt_sub, own_anti, valid_sub, ct,
                s_match_g, a_aff_g, a_anti_g,
            )
        feasible = jnp.any(m_all)
        # score — identical term order and kernels as score_pod; the
        # per-pod cond skips it replaces return the same constants the
        # unconditional kernels produce for term-free rows
        score = fscore
        score = score + (w_[2] + w_[3]) * minmax_normalize(simon_raw, m_all)
        if f.node_pref:
            score += w_[4] * minmax_normalize(node_pref_g, m_all)
        if f.taint_pref:
            score += w_[5] * taint_toleration_score(taint_g, m_all)
        if (f.interpod_pref or f.interpod_req) and t_cap:
            raw_ipa = interpod_score(
                cnt_sub, own_aff, w_own_a, w_own_n,
                s_match_g, w_aff_g, w_anti_g,
            )
            score += w_[6] * maxabs_normalize(raw_ipa, m_all)
        if f.spread_soft and t_cap:
            score += w_[7] * topology_spread_score(cnt_sub, spread_soft_g, m_all)
        if f.selector_spread and t_cap:
            score += w_[8] * selector_spread_score(
                cnt_sub, ss_host_g, ss_zone_g, m_all
            )
        if f.static_score:
            score += w_[9] * sscore_g + w_[11] * avoid_g
        score = jnp.where(m_all, score, -jnp.inf)
        if heavy_storage:
            # StepEval.score adds the Open-Local term after the -inf mask;
            # identical accumulation position keeps the argmax bit-exact
            score = score + w_[10] * minmax_normalize(storage_raw, m_all)

        chosen = jnp.where(forced, pin, jnp.argmax(score).astype(jnp.int32))
        placed = jnp.where(
            forced, (pin >= 0) & statics.node_valid[jnp.clip(pin, 0)], feasible
        )
        if heavy_ports or heavy_vols or heavy_storage or heavy_gpu:
            # the lean fail_from's substituted identities no longer hold —
            # walk StepEval.fail_code's reversed cascade on the per-step
            # stage masks directly
            def _fail_walk(_):
                fl = jnp.int32(FAIL_INTERPOD)
                for mask, code in (
                    (m_spread, FAIL_SPREAD),
                    (m_gpu, FAIL_GPU),
                    (m_storage, FAIL_STORAGE),
                    (m_bind, FAIL_VOLUME_BIND),
                    (m_att, FAIL_ATTACH),
                    (m_vol, FAIL_VOLUME),
                    (m_res, FAIL_RESOURCES),
                    (mp, FAIL_PORTS),
                    (m_static, FAIL_STATIC),
                ):
                    fl = jnp.where(jnp.any(mask), fl, code)
                return fl

            fail = jax.lax.cond(
                placed | forced, lambda _: jnp.int32(OK), _fail_walk, None
            )
        else:
            fail = jax.lax.cond(
                placed | forced,
                lambda _: jnp.int32(OK),
                lambda _: fail_from(m_res, m_spread),
                None,
            )
        reason = jnp.where(
            placed, OK, jnp.where(forced, FAIL_NO_NODE, fail)
        ).astype(jnp.int32)

        # state update — schedule_step's update block on the reduced carry
        safe = jnp.clip(chosen, 0)
        w = jnp.where(placed, 1.0, 0.0)
        free = free.at[safe].add(-req * w)
        m_fit, fscore, _, _ = free_rows_update(
            free, m_fit, fscore, safe, req, placed
        )
        if heavy_ports:
            ports_used = ports_used.at[safe].add(want_ports * w)
        if heavy_vols:
            vols_any = vols_any.at[safe].add(v_present_g * w)
            if f.vols:
                vols_rw = vols_rw.at[safe].add(v_rw_g * w)
        if heavy_storage:
            vg_free = vg_free.at[safe].add(-lvm_alloc[safe] * w)
            sdev_free = sdev_free.at[safe].set(
                sdev_free[safe] & ~(dev_take[safe] & placed)
            )
        if heavy_gpu:
            gpu_free = gpu_free.at[safe].add(-gpu_shares[safe] * gpu_mem * w)
        out_carry = [free, m_fit, fscore]
        if t_cap:
            dom_chosen = dom_sub[:, safe]
            valid_chosen = (dom_chosen >= 0) & tvalid & placed
            same = (
                valid_sub
                & (dom_sub == dom_chosen[:, None])
                & valid_chosen[:, None]
            )
            inc = jnp.where(same, 1.0, 0.0)
            cnt_sub = cnt_sub + s_match_g[:, None] * inc
            ct = ct + s_match_g * jnp.where(valid_chosen, 1.0, 0.0)
            out_carry += [cnt_sub, ct]
        if carry_ip:
            if f.interpod_req:
                own_anti = own_anti + a_anti_g[:, None] * inc
                own_aff = own_aff + a_aff_g[:, None] * inc
            if f.interpod_pref:
                w_own_a = w_own_a + w_aff_g[:, None] * inc
                w_own_n = w_own_n + w_anti_g[:, None] * inc
            out_carry += [own_anti, own_aff, w_own_a, w_own_n]
        if heavy_ports:
            out_carry.append(ports_used)
        if heavy_vols:
            out_carry.append(vols_any)
            if f.vols:
                out_carry.append(vols_rw)
        if heavy_storage:
            out_carry += [vg_free, sdev_free]
        if heavy_gpu:
            out_carry.append(gpu_free)
        out_node = jnp.where(placed, chosen, -1)
        out = (out_node, reason)
        # per-pod extended-resource plans — schedule_step's output triplet
        # entries for the carried heavy families
        if heavy_storage:
            out += (lvm_alloc[safe] * w, dev_take[safe] & placed)
        if heavy_gpu:
            out += (gpu_shares[safe] * w,)
        return tuple(out_carry), out

    carry0 = [state.free, m_fit0, fscore0]
    if t_cap:
        carry0 += [cnt_sub0, ct0]
    if carry_ip:
        carry0 += list(own0)
    if heavy_ports:
        carry0.append(state.ports_used)
    if heavy_vols:
        carry0.append(state.vols_any)
        if f.vols:
            carry0.append(state.vols_rw)
    if heavy_storage:
        carry0 += [state.vg_free, state.sdev_free]
    if heavy_gpu:
        carry0.append(state.gpu_free)
    carry_f, ys = jax.lax.scan(vstep, tuple(carry0), xs)
    nodes, reasons = ys[0], ys[1]
    extra_ys = list(ys[2:])
    hextras = {}
    if heavy_storage:
        hextras["lvm"] = extra_ys.pop(0)
        hextras["dev"] = extra_ys.pop(0)
    if heavy_gpu:
        hextras["gpu"] = extra_ys.pop(0)

    # fold the reduced carry back into the full state.  The count-row
    # deltas are small integers (counts / integer preference weights), so
    # plane + (final - initial) is float-exact — bit-identical to having
    # updated the full planes in place.
    it = iter(carry_f)
    updates = {"free": next(it)}
    next(it)  # m_fit — derived, not part of SchedState
    next(it)  # fscore — derived, not part of SchedState
    if t_cap:
        cnt_f = next(it)
        ct_f = next(it)
        updates["cnt_match"] = add_rows(state.cnt_match, terms_g, cnt_f - cnt_sub0)
        updates["cnt_total"] = state.cnt_total.at[tsafe].add(
            jnp.where(tvalid, ct_f - ct0, 0.0)
        )
    if carry_ip:
        own_f = (next(it), next(it), next(it), next(it))
        if f.interpod_req:
            updates["cnt_own_anti"] = add_rows(
                state.cnt_own_anti, ip_eff, own_f[0] - own0[0]
            )
            updates["cnt_own_aff"] = add_rows(
                state.cnt_own_aff, ip_eff, own_f[1] - own0[1]
            )
        if f.interpod_pref:
            updates["w_own_aff_pref"] = add_rows(
                state.w_own_aff_pref, ip_eff, own_f[2] - own0[2]
            )
            updates["w_own_anti_pref"] = add_rows(
                state.w_own_anti_pref, ip_eff, own_f[3] - own0[3]
            )
    # heavy occupancy planes were updated in place through the carry —
    # the final carried values ARE the new planes
    if heavy_ports:
        updates["ports_used"] = next(it)
    if heavy_vols:
        updates["vols_any"] = next(it)
        if f.vols:
            updates["vols_rw"] = next(it)
    if heavy_storage:
        updates["vg_free"] = next(it)
        updates["sdev_free"] = next(it)
    if heavy_gpu:
        updates["gpu_free"] = next(it)
    return state._replace(**updates), nodes, reasons, hextras


def _wave_verify_lean(statics, state, xs, f, env, pref, key_kinds, n_domains):
    """The lean-mode verifier: no hard constraint term is owned by the run,
    so the feasibility mask moves only with the row-maintained fit mask and
    the count-dependent score terms reduce to carried raws updated through
    same-domain bookkeeping.  `env` carries wavefront_scan's hoists; `pref`
    (static) is whether the run carries interpod preference weights that
    move its own interpod raw; `key_kinds` (static tuple, None = generic)
    enables the TABULAR carry when every topology key is either
    unique-per-node (kind 2) or small-domain (kind 1, ≤ DOM_SMALL ids in
    node_dom_small).

    Carried invariants (each exact, each refreshed only when its inputs
    can actually have changed):
    - m_all / feasible: change only when a placement flips the fit mask
      row of its node (everything else in the cascade is run-constant);
      between flips the chosen node stays feasible, so the masked
      selector-spread maxima advance by a scalar `maximum` — max is
      order-free, hence bit-identical to the full reduction.
    - normalized static terms (Simon / node-affinity / taint) and, without
      `pref`, the interpod term: depend on m_all (and a then-constant raw)
      only — renormalized inside the one flip cond.
    - count raws: every raw is an integer combination of per-domain
      placement counts, so TABULAR mode carries only a per-node placement
      counter (kind-2 keys) and a [K1, DOM_SMALL] domain histogram (kind-1
      keys), updated O(1) per step, and re-materializes each raw inline —
      integer sums are float-exact under any regrouping, so the
      materialized raw is bit-identical to the step-by-step bumps.
      Generic mode (a kind-0 scatter-fallback key exists) carries the full
      [N] raws and advances them through a per-key indicator matmul."""
    (m_static, m_ports, post_res, simon_raw, node_pref_g, taint_g, sscore_g,
     avoid_g, m_fit0, fscore0, w_, alloc, fail_from, free_rows_update) = (
        env["m_static"], env["m_ports"], env["post_res"], env["simon_raw"],
        env["node_pref_g"], env["taint_g"], env["sscore_g"], env["avoid_g"],
        env["m_fit0"], env["fscore0"], env["w_"], env["alloc"],
        env["fail_from"], env["free_rows_update"],
    )
    t_cap = env["t_cap"]
    n = statics.alloc.shape[0]
    node_dom = statics.node_dom  # [K, N]
    key_n = node_dom.shape[0]
    key_valid = node_dom >= 0
    has_ss = bool(t_cap) and f.selector_spread
    has_soft = bool(t_cap) and f.spread_soft
    has_ip = bool(t_cap) and (f.interpod_req or f.interpod_pref)
    hp = jax.lax.Precision.HIGHEST  # integer-count matmuls must stay exact

    if t_cap:
        (terms_g, tvalid, tsafe, term_keys, ip_eff, s_match_g, w_aff_g,
         w_anti_g, spread_soft_g, ss_host_g, ss_zone_g, cnt_sub0, ct0,
         valid_sub) = (
            env["terms_g"], env["tvalid"], env["tsafe"], env["term_keys"],
            env["ip_eff"], env["s_match_g"], env["w_aff_g"], env["w_anti_g"],
            env["spread_soft_g"], env["ss_host_g"], env["ss_zone_g"],
            env["cnt_sub0"], env["ct0"], env["valid_sub"],
        )
        s_match_f = s_match_g.astype(jnp.float32)
        # per-key coefficient folds: every term of one topology key shares
        # the same same-domain indicator, so the per-step raw deltas
        # collapse to [K]-coefficient combinations of per-key counts
        key_oh = jax.nn.one_hot(term_keys, key_n, dtype=jnp.float32)
    # the run owns no required (anti-)affinity term, so the interpod
    # filter's inputs (the own-anti planes and the run-invariant
    # a_aff/a_anti rows) cannot change while it places — the mask the
    # general step recomputes per pod is wavefront-constant
    ip_mask = jnp.ones(n, bool)
    if bool(t_cap) and f.interpod_req:
        ip_mask = interpod_filter(
            cnt_sub0,
            env["own0"][0] if env["carry_ip"]
            else take_rows(state.cnt_own_anti, ip_eff),
            valid_sub, ct0, s_match_g, env["a_aff_g"], env["a_anti_g"],
        )
    m_nofit = m_ports & post_res & ip_mask
    m_all0 = m_nofit & m_fit0
    feasible0 = jnp.any(m_all0)

    def _norm_terms(m_all):
        out = [minmax_normalize(simon_raw, m_all)]
        if f.node_pref:
            out.append(minmax_normalize(node_pref_g, m_all))
        if f.taint_pref:
            out.append(taint_toleration_score(taint_g, m_all))
        return tuple(out)

    raw0s = []
    coefs = []
    if has_ss:
        any_zone = jnp.any(ss_zone_g)
        raw0s += [
            ss_host_g.astype(jnp.float32) @ cnt_sub0,
            ss_zone_g.astype(jnp.float32) @ cnt_sub0,
        ]
        coefs += [
            jnp.matmul(ss_host_g * s_match_f, key_oh, precision=hp),
            jnp.matmul(ss_zone_g * s_match_f, key_oh, precision=hp),
        ]
    if has_soft:
        soft_slot = len(raw0s)
        raw0s.append(spread_soft_g @ cnt_sub0)
        coefs.append(jnp.matmul(spread_soft_g * s_match_f, key_oh, precision=hp))
    ipa_raw0 = None
    if has_ip:
        own0 = env["own0"]
        ipa_raw0 = interpod_score(
            cnt_sub0, own0[1], own0[2], own0[3],
            s_match_g, w_aff_g, w_anti_g,
        )
        if pref:
            # one placement bumps both the incoming count and the
            # symmetric owner weight by the same per-key amount — hence 2x
            raw0s.append(ipa_raw0)
            coefs.append(2.0 * jnp.matmul(
                (w_aff_g - w_anti_g) * s_match_f, key_oh, precision=hp
            ))
    coef_mat = jnp.stack(coefs) if coefs else None  # [V, K]
    n_raws = len(raw0s)
    tab = key_kinds is not None and n_raws > 0
    if tab:
        k1_keys = tuple(k for k, kd in enumerate(key_kinds) if kd == 1)
        k2_keys = tuple(k for k, kd in enumerate(key_kinds) if kd == 2)
        kv2 = [jnp.where(key_valid[k], 1.0, 0.0) for k in k2_keys]
        dsmall = [statics.node_dom_small[k] for k in k1_keys]
        from ..core.tensorize import DOM_SMALL

        def tab_rows(cnttab):
            """Per-kind-1-key domain histogram gathered onto the node axis
            (masked where the key is absent)."""
            return [
                jnp.where(d >= 0, cnttab[j][jnp.clip(d, 0)], 0.0)
                for j, d in enumerate(dsmall)
            ]

        def materialize(v, placecnt, trows):
            """Raw v at the current step — raw0 plus the integer-exact
            per-key count combinations."""
            r = raw0s[v]
            for j, k in enumerate(k2_keys):
                r = r + coef_mat[v, k] * (placecnt * kv2[j])
            for j, k in enumerate(k1_keys):
                r = r + coef_mat[v, k] * trows[j]
            return r

        def value_at(v, safe, placecnt, cnttab):
            """materialize(v)[safe] from the table components (O(1))."""
            val = raw0s[v][safe]
            for j, k in enumerate(k2_keys):
                val = val + coef_mat[v, k] * (placecnt[safe] * kv2[j][safe])
            for j, k in enumerate(k1_keys):
                d = dsmall[j][safe]
                val = val + coef_mat[v, k] * jnp.where(
                    d >= 0, cnttab[j, jnp.clip(d, 0)], 0.0
                )
            return val

    def _flip_terms(m_all):
        """Everything that must be refreshed when the mask changes: the
        normalized static terms and the constant-raw interpod term."""
        out = list(_norm_terms(m_all))
        if has_ip and not pref:
            out.append(maxabs_normalize(ipa_raw0, m_all))
        return tuple(out)

    terms0 = _flip_terms(m_all0)
    scal0 = (feasible0,)
    if has_ss:
        scal0 += (
            jnp.max(jnp.where(m_all0, raw0s[0], 0.0)),
            jnp.max(jnp.where(m_all0, raw0s[1], 0.0)),
        )
    if tab:
        count0 = (jnp.zeros(n, jnp.float32),) if k2_keys else ()
        count0 += (
            (jnp.zeros((len(k1_keys), DOM_SMALL), jnp.float32),)
            if k1_keys
            else ()
        )
    else:
        count0 = tuple(raw0s)

    def lstep(carry, x):
        req, pin, forced = x
        it = iter(carry)
        free = next(it)
        m_fit = next(it)
        fscore = next(it)
        m_all = next(it)
        terms = tuple(next(it) for _ in terms0)
        scal = tuple(next(it) for _ in scal0)
        counts = [next(it) for _ in count0]
        feasible = scal[0]
        if tab:
            ci = iter(counts)
            placecnt = next(ci) if k2_keys else None
            cnttab = next(ci) if k1_keys else None
            trows = tab_rows(cnttab) if k1_keys else []
            raws = [materialize(v, placecnt, trows) for v in range(n_raws)]
        else:
            raws = counts
        ti = iter(terms)
        score = fscore
        score = score + (w_[2] + w_[3]) * next(ti)
        if f.node_pref:
            score += w_[4] * next(ti)
        if f.taint_pref:
            score += w_[5] * next(ti)
        if has_ip:
            if pref:
                score += w_[6] * maxabs_normalize(raws[-1], m_all)
            else:
                score += w_[6] * next(ti)
        if has_soft:
            score += w_[7] * spread_score_from_raw(raws[soft_slot], m_all)
        if has_ss:
            score += w_[8] * selector_spread_compose(
                raws[0], raws[1], scal[1], scal[2], any_zone
            )
        if f.static_score:
            score += w_[9] * sscore_g + w_[11] * avoid_g
        score = jnp.where(m_all, score, -jnp.inf)

        chosen = jnp.where(forced, pin, jnp.argmax(score).astype(jnp.int32))
        placed = jnp.where(
            forced, (pin >= 0) & statics.node_valid[jnp.clip(pin, 0)], feasible
        )
        # the lean spread stage is m_bind (no skew terms) and must NOT
        # fold in ip_mask: a pod emptied by existing pods' required
        # anti-affinity reports FAIL_INTERPOD (the cascade default), not
        # FAIL_SPREAD — exactly like StepEval.fail_code
        fail = jax.lax.cond(
            placed | forced,
            lambda _: jnp.int32(OK),
            lambda _: fail_from(
                m_ports & m_fit, (m_ports & m_fit) & post_res
            ),
            None,
        )
        reason = jnp.where(
            placed, OK, jnp.where(forced, FAIL_NO_NODE, fail)
        ).astype(jnp.int32)

        safe = jnp.clip(chosen, 0)
        w = jnp.where(placed, 1.0, 0.0)
        free = free.at[safe].add(-req * w)
        m_fit, fscore, prev_fit, fit_row = free_rows_update(
            free, m_fit, fscore, safe, req, placed
        )
        if tab:
            if k2_keys:
                placecnt = placecnt.at[safe].add(w)
            if k1_keys:
                for j in range(len(k1_keys)):
                    d = dsmall[j][safe]
                    cnttab = cnttab.at[j, jnp.clip(d, 0)].add(
                        jnp.where((d >= 0) & placed, 1.0, 0.0)
                    )
            new_counts = ((placecnt,) if k2_keys else ()) + (
                (cnttab,) if k1_keys else ()
            )
        elif n_raws:
            # same-domain indicator per topology key for the chosen node;
            # every carried raw advances by its per-key coefficient dot
            dom_ch = node_dom[:, safe]  # [K]
            keyinc = (
                key_valid
                & (node_dom == dom_ch[:, None])
                & ((dom_ch >= 0) & placed)[:, None]
            )
            deltas = jnp.matmul(
                coef_mat, jnp.where(keyinc, 1.0, 0.0), precision=hp
            )  # [V, N]
            new_counts = tuple(r + deltas[v] for v, r in enumerate(raws))
        else:
            new_counts = ()
        m_all = m_all.at[safe].set(
            jnp.where(placed, m_nofit[safe] & fit_row, m_all[safe])
        )
        # between flips the chosen node stays feasible, so the masked
        # maxima advance through it alone (max is order-free — exact)
        if has_ss:
            if tab:
                ch_safe = value_at(
                    0, safe, placecnt if k2_keys else None, cnttab
                )
                cz_safe = value_at(
                    1, safe, placecnt if k2_keys else None, cnttab
                )
            else:
                ch_safe = new_counts[0][safe]
                cz_safe = new_counts[1][safe]
            scal = (
                scal[0],
                jnp.where(placed, jnp.maximum(scal[1], ch_safe), scal[1]),
                jnp.where(placed, jnp.maximum(scal[2], cz_safe), scal[2]),
            )
        # refresh the mask-dependent carries only when the placement
        # actually flipped its node's fit row
        flip = placed & (fit_row != prev_fit)

        def _refresh(args):
            m_all_, counts_ = args[0], args[3]
            out = (jnp.any(m_all_),)
            if has_ss:
                if tab:
                    ci_ = iter(counts_)
                    pc_ = next(ci_) if k2_keys else None
                    ct_ = next(ci_) if k1_keys else None
                    tr_ = tab_rows(ct_) if k1_keys else []
                    ch_ = materialize(0, pc_, tr_)
                    cz_ = materialize(1, pc_, tr_)
                else:
                    ch_, cz_ = counts_[0], counts_[1]
                out += (
                    jnp.max(jnp.where(m_all_, ch_, 0.0)),
                    jnp.max(jnp.where(m_all_, cz_, 0.0)),
                )
            return _flip_terms(m_all_), out

        terms, scal = jax.lax.cond(
            flip, _refresh, lambda args: (args[1], args[2]),
            (m_all, terms, scal, tuple(new_counts)),
        )
        out_node = jnp.where(placed, chosen, -1)
        return (
            (free, m_fit, fscore, m_all)
            + tuple(terms) + tuple(scal) + tuple(new_counts),
            (out_node, reason),
        )

    carry0 = (
        (state.free, m_fit0, fscore0, m_all0) + terms0 + scal0 + count0
    )
    carry_f, (nodes, reasons) = jax.lax.scan(lstep, carry0, xs)
    updates = {"free": carry_f[0]}

    # -- post-scan fold of the count planes ------------------------------
    # Reconstruct each term's domain-count delta from the choice sequence:
    # a per-key histogram of the chosen nodes' domains, gathered back onto
    # the node axis.  Counts and preference weights are small integers, so
    # the reordered sums are bit-identical to the step-by-step bumps the
    # general scan applies.
    if t_cap:
        placed_arr = nodes >= 0
        safe_arr = jnp.clip(nodes, 0)
        dom_ch = node_dom[:, safe_arr]  # [K, W]
        val = jnp.where((dom_ch >= 0) & placed_arr[None, :], 1.0, 0.0)
        kidx = jnp.arange(key_n)[:, None]
        dtab = jnp.zeros((key_n, max(n_domains, 1)), jnp.float32)
        dtab = dtab.at[kidx, jnp.clip(dom_ch, 0)].add(val)
        keysum = jnp.take_along_axis(dtab, jnp.clip(node_dom, 0), axis=1)
        keysum = jnp.where(key_valid, keysum, 0.0)  # [K, N]
        totals = val.sum(axis=1)  # [K] placed pods with a valid domain
        delta_t = jnp.where(
            tvalid[:, None], keysum[jnp.clip(term_keys, 0)], 0.0
        )  # [Tc, N]
        tot_t = jnp.where(tvalid, totals[jnp.clip(term_keys, 0)], 0.0)
        updates["cnt_match"] = add_rows(
            state.cnt_match, terms_g, s_match_f[:, None] * delta_t
        )
        updates["cnt_total"] = state.cnt_total.at[tsafe].add(s_match_f * tot_t)
        # the run owns no required terms (lean), so only the preferred-
        # weight owner planes can change
        if f.interpod_pref:
            updates["w_own_aff_pref"] = add_rows(
                state.w_own_aff_pref, ip_eff, w_aff_g[:, None] * delta_t
            )
            updates["w_own_anti_pref"] = add_rows(
                state.w_own_anti_pref, ip_eff, w_anti_g[:, None] * delta_t
            )
    return state._replace(**updates), nodes, reasons


@partial(jax.jit, static_argnums=(3, 4, 5, 6, 7, 8), donate_argnums=(1,))
def _run_wavefront(
    statics: StaticArrays,
    state: SchedState,
    pods,
    flags: StepFlags = StepFlags(),
    hard: bool = False,
    pref: bool = False,
    heavy: int = 0,
    key_kinds=None,
    n_domains: int = 1,
):
    count_trace("wave")
    return wavefront_scan(
        statics, state, pods, flags, hard, pref, heavy, key_kinds, n_domains
    )


def default_wave_call(statics, state, seg, flags, spec):
    """run_scan_chunked's engine-less wave_call (the bench and tests use
    it directly): the plain-jit wavefront dispatch."""
    return _run_wavefront(statics, state, seg, flags, *spec)


def wave_static_spec(tensors, hard: bool, pref: bool, heavy: int = 0) -> tuple:
    """The static specialization tail of one wavefront dispatch:
    (hard, pref, heavy, key_kinds, n_domains).  key_kinds is the
    per-topology-key reduction kind tuple when every key supports the
    tabular carry (kinds 1/2), else None (generic carried raws); `heavy`
    is the run's WAVE_HEAVY_* stage-recompute bits (0 = pure lean)."""
    kinds = tensors.key_kind
    key_kinds = None
    if kinds is not None and kinds.shape[0] and bool((kinds != 0).all()):
        key_kinds = tuple(int(x) for x in kinds)
    return hard, pref, int(heavy), key_kinds, max(int(tensors.n_domains), 1)


# Batch apply/undo of placement deltas lives in engine/state.py
# (`apply_placement_deltas`); the module-level alias keeps the historical
# monkeypatch point (tests) and the preemption call sites stable.
_apply_log_delta = apply_placement_deltas


class Engine:
    """Host-side driver: threads the placement log across app batches.

    One Engine per simulation (the reference builds a fresh Simulator per
    `Simulate` call, `pkg/simulator/core.go:64-70`).
    """

    def __init__(self, tensorizer):
        self.tensorizer = tensorizer
        #: optional schedconfig.SchedulerConfig (score-weight overrides)
        self.sched_config = None
        #: optional engine.precompile.AotPipeline — when set, dispatches
        #: route through its registry of background-compiled executables
        #: (engine/precompile.py); None = plain jit dispatch
        self.pipeline = None
        #: speculative wavefront dispatch of same-group lean runs (the
        #: verify-and-rollback batcher, docs/speculation.md).  Placements
        #: are bit-identical on or off; SIMTPU_WAVEFRONT=0 flips the
        #: default for A/B measurement.
        self.speculate = wave_enabled()
        #: carry the between-dispatch state in the domain-tabular compact
        #: layout (engine/state.py CompactState): kind-1 topology keys'
        #: count rows as [Rt, D] histograms, integer dtypes.  Placements
        #: are bit-identical on or off (expansion is one exact gather);
        #: SIMTPU_COMPACT=0 flips the default for A/B measurement.
        self.compact = compact_enabled()
        #: optional [N] host bool mask — False rows are out of this
        #: engine's cluster (failed nodes under fault injection,
        #: simtpu/faults/drain.py).  ANDed into statics.node_valid at every
        #: place(), composing with subclass masks (MaskedRoundsEngine's
        #: candidate mask, the sharded engines' dead-row padding); the
        #: preemption proposer (api.py) reads the same attribute so masked
        #: nodes are never proposed as landing sites.
        self.node_valid = None
        self.placed_group: List[int] = []
        self.placed_node: List[int] = []
        self.placed_req: List[np.ndarray] = []
        # extended-resource placement log, keyed parallel to placed_node
        self.ext_log = {
            "node": [],
            "vg_alloc": [],
            "sdev_take": [],
            "gpu_shares": [],
            "gpu_mem": [],
        }
        self.last_state: SchedState = None
        self._last_vocab = None  # vocabulary sizes behind last_state
        self._state_dirty = False  # log surgery (preemption) invalidates reuse
        #: append-only vocabulary growth (warm-engine serving): when set,
        #: the carried state lives DENSE with its term axes pre-padded to
        #: pow2 shape buckets, and a vocabulary growth extends it in place
        #: (engine/state.py extend_state) instead of rebuilding from the
        #: log.  Placements are bit-identical on or off; enable via
        #: enable_grow() (serve sessions, the replay runtime).
        self.grow = False
        self._grow_ref = None  # carried layout: t/ti/ip_terms/caps/n

    def log_req_matrix(self, r: int) -> np.ndarray:
        """The placement log's request rows padded to the r-column resource
        vocabulary — the ONE assembly shared by the state rebuild here and
        the fault sweep's delta sources (simtpu/faults/sweep.py), so a new
        log column cannot silently diverge them."""
        if not self.placed_req:
            return np.zeros((0, r), np.float32)
        return np.stack(
            [np.pad(q, (0, r - q.shape[0])) for q in self.placed_req]
        ).astype(np.float32)

    @staticmethod
    def state_vocab(tensors) -> tuple:
        """The vocabulary tuple a carried state is valid under — the single
        source of truth for Engine.place's reuse check, the eviction delta
        guard, and the incremental planner's snapshot injection (a field
        added to one but not the others would silently validate a stale
        state)."""
        return (
            tensors.alloc.shape[1],
            tensors.n_terms,
            tensors.n_ports,
            tensors.n_vols,
            int((interpod_term_index(tensors) >= 0).sum()),
            # the node axis participates since add_clone_nodes can grow it
            # mid-simulation (append-only vocabulary growth, ISSUE 20)
            tensors.alloc.shape[0],
        )

    # -- append-only vocabulary growth (warm-engine serving) -------------

    def enable_grow(self) -> None:
        """Switch this engine to grow mode: the carried state stays DENSE
        with its term axes pre-padded to pow2 shape buckets, and a
        vocabulary growth extends it in place (one `extend_state` call)
        instead of forcing the O(P·T) from-log rebuild.  Compression is
        disabled — the compact plan is keyed to the exact term partition
        and would re-trace per vocabulary size, defeating the
        trace-once-per-bucket contract.  Placements are bit-identical
        either way (tests/test_grow.py)."""
        self.grow = True
        self.compact = False

    def _grow_layout(self, tensors) -> dict:
        """The bucket layout of a grow-mode carry built over `tensors`."""
        ip_terms = np.flatnonzero(interpod_term_index(tensors) >= 0)
        t, ti = tensors.n_terms, len(ip_terms)
        return {
            "t": t,
            "ti": ti,
            "ip_terms": ip_terms,
            "t_cap": snap_pow2(t) if t else 0,
            "ti_cap": snap_pow2(ti) if ti else 0,
            "n": tensors.alloc.shape[0],
        }

    def _enter_grow_buckets(self, tensors, dense_state):
        """Pad a freshly built exact-shape state into its term buckets and
        record the carried layout."""
        ref = self._grow_layout(tensors)
        state = _pad_terms_kernel(ref["t_cap"], ref["ti_cap"], dense_state)
        self._grow_ref = ref
        return state

    def _try_extend_carry(self, tensors, vocab):
        """Extend the carried (bucket-padded) state to a grown vocabulary;
        None when the change is not an in-place append (resource/port/
        volume axes or the node axis moved — rare, rebuild instead)."""
        old = self._last_vocab
        ref = self._grow_ref
        if old is None or ref is None:
            return None
        r1, t1, p1, w1, _ti1, n1 = vocab
        r0, _t0, p0, w0, _ti0, n0 = old
        if (r1, p1, w1, n1) != (r0, p0, w0, n0) or n1 != ref["n"]:
            return None
        if t1 < ref["t"]:
            return None
        plan = grow_plan_terms(
            tensors,
            ref["t"],
            ref["ip_terms"],
            np.asarray(self.placed_group, np.int32),
            np.asarray(self.placed_node, np.int32),
        )
        promoted = (
            plan["t_cap"] != ref["t_cap"] or plan["ti_cap"] != ref["ti_cap"]
        )
        # the extension donates the carry: mark dirty across the call so a
        # failure never leaves a dead buffer looking reusable
        self._state_dirty = True
        state = extend_state(self.last_state, plan)
        self._state_dirty = False
        self._grow_ref = {
            "t": plan["t"],
            "ti": plan["ti"],
            "ip_terms": plan["ip_terms"],
            "t_cap": plan["t_cap"],
            "ti_cap": plan["ti_cap"],
            "n": n1,
        }
        REGISTRY.counter("grow.extends").inc()
        if promoted:
            REGISTRY.counter("grow.bucket_promotions").inc()
        return state

    def grow_nodes(self) -> bool:
        """Extend the carried state to the tensorizer's grown node axis
        (after `Tensorizer.add_clone_nodes`) — counts of pods already
        placed in a domain a clone joins appear on the clone's columns.
        Returns False (carry invalidated, next place() rebuilds from the
        log) when no extendable grow-mode carry exists."""
        tensors = self.tensorizer.freeze()
        vocab = self.state_vocab(tensors)
        if self._last_vocab == vocab and not self._state_dirty:
            return True  # node axis did not actually move
        ref = self._grow_ref
        if (
            not self.grow
            or ref is None
            or self.last_state is None
            or self._state_dirty
            or isinstance(self.last_state, CompactState)
            or self._last_vocab is None
            # only the node axis may have moved
            or self._last_vocab[:5] != vocab[:5]
            or tensors.alloc.shape[0] < ref["n"]
        ):
            self._last_vocab = None
            return False
        plan = grow_plan_nodes(
            tensors,
            ref["n"],
            np.asarray(self.placed_group, np.int32),
            np.asarray(self.placed_node, np.int32),
            ref["t_cap"],
            ref["ti_cap"],
        )
        self._state_dirty = True
        self.last_state = extend_state_nodes(self.last_state, plan, tensors)
        self._state_dirty = False
        self._grow_ref = dict(ref, n=plan["n"])
        self._last_vocab = vocab
        REGISTRY.counter("grow.node_extends").inc()
        return True

    def _aot_scan(self, flags: StepFlags):
        """(pipeline key name, jit callable, static argument tail) for the
        serial-scan executable.  The AOT precompiler and `_scan_call` must
        agree on this triple: the pipeline lowers `fn.lower(*dynamic,
        *tail)` on a worker thread and the dispatch path calls the compiled
        result with the dynamic args alone.  The sharded engines override
        it with their mesh-compiled callables (tail already closed over)."""
        return "scan", _run_scan, (flags,)

    def _aot_wave(self, flags: StepFlags, spec: tuple):
        """(pipeline key name, jit callable, static tail) for the
        speculative wavefront executable (`spec` = wave_static_spec) — the
        `_aot_scan` analog; the sharded engines override it with their
        mesh-compiled variants."""
        return "wave", _run_wavefront, (flags,) + spec

    @staticmethod
    def _prefetch_pods(tree):
        """Start the (non-blocking) host→device transfer of a prepared pod
        segment — the double-buffer lever of run_scan_chunked and the bulk
        chunk loop.  The sharded engines override this with a no-op: their
        jits shard replicated inputs on entry, and a copy committed to one
        device would fight the mesh layout."""
        return jax.device_put(tree)

    def _precompile_shapes(self, statics_sds, state_sds):
        """Map (statics, state) ShapeDtypeStruct trees to the shapes
        `_dispatch` actually sees — identity here; the mesh engines pad the
        node axis to the shard multiple (parallel/sharded.py)."""
        return statics_sds, state_sds

    # -- compact carried-state plumbing ----------------------------------
    # The carry that crosses place() boundaries (and preemption's delta
    # path) travels domain-tabular (engine/state.py section comment);
    # dispatch loops always see the dense SchedState, expanded by one
    # jitted gather.  The sharded engines override _compress_call /
    # _expand_call with mesh-sharded variants so the carried compact
    # planes keep their node-axis layout between batches.

    def _active_compact_spec(self, tensors):
        """The compaction plan when this engine should carry compact state
        (None = carry dense: the A/B switch is off, or no topology key is
        tabular so there is nothing to compact)."""
        if not self.compact:
            return None
        spec = compact_spec(tensors)
        return spec if spec.enabled else None

    def _compress_call(self, spec_dev, state):
        return compress_state(spec_dev, state)

    def _expand_call(self, spec_dev, cstate, nds):
        return expand_state(spec_dev, cstate, nds)

    def _delta_direct_call(self, statics, dspec, ndom, nds, cstate, entries):
        return apply_placement_deltas_compact(
            statics, dspec, ndom, nds, cstate, entries
        )

    def _expand_carry(self, tensors, cstate: CompactState) -> SchedState:
        """Dense view of a compact carry (padded node_dom_small follows the
        carry's own node axis — sharded carries stay shard-padded)."""
        spec = compact_spec(tensors)
        REGISTRY.counter("state.expand").inc()
        return self._expand_call(
            spec.dev, cstate, node_dom_small_for(tensors, cstate.free.shape[0])
        )

    def _store_state(self, tensors, final_state: SchedState):
        """Compress (when active) and gauge the carry place() stores."""
        dense_bytes = sum(state_nbytes(final_state).values())
        spec = self._active_compact_spec(tensors)
        if spec is None:
            stored = final_state
        else:
            REGISTRY.counter("state.compress").inc()
            stored = self._compress_call(spec.dev, final_state)
        update_state_gauge(stored, dense_bytes)
        return stored

    def carried_state(self) -> SchedState:
        """The engine's carried state in dense SchedState form (read-only
        peek: expansion never donates the carry).  Consumers that thread
        the carry into their own dispatches — the fault sweep's base
        state, direct delta tests — go through this instead of touching
        last_state, whose representation is a layout choice."""
        state = self.last_state
        if state is not None and self._state_dirty:
            # a dispatch failed mid-flight: a dense carry may already be
            # donated (dead buffers — reading them is an opaque
            # deleted-array error deep in the consumer), and even an
            # intact compact carry no longer reflects the log; fail at
            # the API with the actual precondition instead
            raise ValueError(
                "carried_state(): a dispatch failed after the carry was "
                "handed to it, so the carry is invalidated (dense layouts "
                "donate it outright); place() again (which rebuilds from "
                "the placement log) before reading it"
            )
        if state is None:
            return state
        tensors = self.tensorizer.freeze()
        if self._last_vocab != self.state_vocab(tensors):
            # a compact carry's domain partition is keyed to the vocabulary
            # it was compressed under — expanding against re-frozen tensors
            # with new terms would gather with mismatched index shapes; a
            # dense carry would merely read stale, but raising only under
            # one layout would let the SIMTPU_COMPACT A/B change API
            # behavior for the same caller mistake, so both refuse
            raise ValueError(
                "carried_state(): the carry predates a vocabulary change "
                "(add_pods interned new terms/groups); place() the pending "
                "batch first, or rebuild from the placement log"
            )
        if isinstance(state, CompactState):
            state = self._expand_carry(tensors, state)
        if self.grow and self._grow_ref is not None:
            # grow-mode carries are bucket-padded; consumers get the
            # exact-shape view
            state = strip_term_padding(
                state, self._grow_ref["t"], self._grow_ref["ti"]
            )
        return state

    def _scan_call(self, statics, state, seg, flags):
        """Dispatch one compiled scan segment — through the precompile
        pipeline's registry when one is attached, else the plain jit."""
        name, fn, tail = self._aot_scan(flags)
        args = (statics, state, seg)
        if self.pipeline is not None:
            return self.pipeline.call(
                name, tail, args, lambda: fn(*args, *tail)
            )
        return fn(*args, *tail)

    def _wave_call(self, statics, state, seg, flags, spec):
        """Dispatch one compiled wavefront — through the precompile
        pipeline's registry when one is attached, else the plain jit."""
        name, fn, tail = self._aot_wave(flags, spec)
        args = (statics, state, seg)
        if self.pipeline is not None:
            return self.pipeline.call(
                name, tail, args, lambda: fn(*args, *tail)
            )
        return fn(*args, *tail)

    def _dispatch(
        self, statics: StaticArrays, state: SchedState, pods, flags: StepFlags
    ):
        """Run the scan in pow2 chunks with term-row-sliced count planes
        (run_scan_chunked), speculative wavefronts riding eligible runs.
        `ShardedEngine` (simtpu/parallel) overrides `_scan_call` /
        `_aot_wave` to lay the node axis out across a device mesh; the
        chunking composes."""
        return run_scan_chunked(
            statics,
            state,
            pods,
            flags,
            self._current_tensors,
            np.asarray(self._current_batch.group),
            scan_call=self._scan_call,
            prefetch=self._prefetch_pods,
            wave_call=self._wave_call if self.speculate else None,
        )

    def place(self, batch: PodBatch):
        """Schedule one batch.

        Returns (node index per pod [-1 = failed], reason codes, extras) where
        extras carries each pod's extended-resource allocation at its node
        (LVM per-VG bytes, device take mask, GPU device shares).
        """
        tensors = self.tensorizer.freeze()
        # batch context for _dispatch overrides (RoundsEngine segments pods
        # by group/spec and needs the frozen tensors without re-freezing)
        self._current_batch = batch
        self._current_tensors = tensors
        r = tensors.alloc.shape[1]
        req, pods = build_pod_arrays(batch, r)
        # carry the previous batch's final state forward when nothing that
        # shapes it changed; a grown vocabulary (new groups may retro-match
        # new terms) or log surgery (preemption) forces the full rebuild.
        # The interpod-plane count participates: a new group can mark an
        # ALREADY-interned term as interpod-used without growing n_terms,
        # which reshapes the compacted own planes.
        vocab = self.state_vocab(tensors)
        if (
            self.last_state is not None
            and not self._state_dirty
            and self._last_vocab == vocab
        ):
            state = self.last_state
            if isinstance(state, CompactState):
                # one-gather expansion back to the dense in-kernel form;
                # the compact carry itself is NOT donated, so a failed
                # dispatch below leaves it intact for the log fallback
                state = self._expand_carry(tensors, state)
        else:
            state = None
            if (
                self.grow
                and self.last_state is not None
                and not self._state_dirty
                and not isinstance(self.last_state, CompactState)
            ):
                # append-only vocabulary growth: extend the carried planes
                # in place instead of rebuilding from the log
                state = self._try_extend_carry(tensors, vocab)
            if state is None:
                if self.grow and self._grow_ref is not None:
                    REGISTRY.counter("grow.rebuilds").inc()
                state = build_state(
                    tensors,
                    np.asarray(self.placed_group, np.int32),
                    np.asarray(self.placed_node, np.int32),
                    self.log_req_matrix(r),
                    self.ext_log,
                )
                if self.grow:
                    state = self._enter_grow_buckets(tensors, state)
        statics = statics_from(tensors, self.sched_config)
        if self.node_valid is not None:
            # fault/what-if masking: dead rows no pod can select — the same
            # lever the capacity sweep vmaps over (parallel/sweep.py)
            statics = statics._replace(
                node_valid=statics.node_valid
                & jnp.asarray(np.asarray(self.node_valid, bool))
            )
        ext = batch.ext
        flags = flags_from(tensors, batch.ext)
        # a donating dispatch can invalidate `state`'s buffers before raising
        # (RoundsEngine makes several donating calls per batch); mark dirty so
        # a retry rebuilds from the log instead of reusing a dead buffer
        self._state_dirty = True
        final_state, (nodes, reasons, lvm_alloc, dev_take, gpu_shares) = self._dispatch(
            statics, state, pods, flags
        )
        # the dense final state simply goes unreferenced after this call —
        # compression deliberately does NOT donate it (int32 outputs cannot
        # alias f32 inputs; see the audit note on compress_state); what is
        # stored — and what every later expansion reproduces bit-identically
        # — is the domain-tabular carry
        self.last_state = self._store_state(tensors, final_state)
        # cache bookkeeping only after a successful dispatch: a failed run
        # must not leave the reuse branch validating a stale/donated state
        self._last_vocab = vocab
        self._state_dirty = False
        nodes = np.asarray(nodes)
        reasons = np.asarray(reasons)
        lvm_alloc = np.asarray(lvm_alloc)
        dev_take = np.asarray(dev_take)
        gpu_shares = np.asarray(gpu_shares)
        ok = np.flatnonzero(nodes >= 0)
        self.placed_group.extend(np.asarray(batch.group)[ok].tolist())
        self.placed_node.extend(nodes[ok].tolist())
        self.placed_req.extend(req[ok])
        self.ext_log["node"].extend(nodes[ok].tolist())
        self.ext_log["vg_alloc"].extend(lvm_alloc[ok])
        self.ext_log["sdev_take"].extend(dev_take[ok])
        self.ext_log["gpu_shares"].extend(gpu_shares[ok])
        self.ext_log["gpu_mem"].extend(np.asarray(ext["gpu_mem"])[ok].tolist())
        return nodes, reasons, {
            "lvm_alloc": lvm_alloc,
            "dev_take": dev_take,
            "gpu_shares": gpu_shares,
        }

    # -- preemption support -------------------------------------------------
    # The placement log is the functional analog of the scheduler cache;
    # evicting a victim = deleting its log entry (build_state recounts all
    # derived state from the log on the next batch).

    def _apply_saved_delta(self, saved: dict, sign: float) -> None:
        """Incrementally apply an eviction (sign=-1) or its undo (sign=+1)
        to the carried device state, so preemption does not force a full
        build_state from the placement log. Falls back to marking the state
        dirty (rebuild on next place) when no reusable state exists."""
        entries = saved["entries"]
        if (
            self.last_state is None
            or self._state_dirty
            or not entries
        ):
            self._state_dirty = True
            return
        tensors = self.tensorizer.freeze()
        r = tensors.alloc.shape[1]
        if self._last_vocab != self.state_vocab(tensors):
            self._state_dirty = True
            return
        packed = pack_delta_entries(
            entries,
            r,
            tensors.ext.vg_cap.shape[1],
            tensors.ext.sdev_cap.shape[1],
            tensors.ext.gpu_dev_total.shape[1],
            sign,
        )
        statics = statics_from(tensors, self.sched_config)
        state = self.last_state
        if isinstance(state, CompactState) and delta_direct_enabled():
            # direct compact-delta apply: scatter the packed deltas straight
            # into the compact carry (per-domain histogram adds for kind-1
            # term rows, dense row updates for kind-0/2) — no
            # expand→apply→recompress round-trip.  Exact under the same
            # domain-constancy invariant compression relies on.  The apply
            # is non-donating (plan/incremental shares compact snapshots
            # across probe engines), so a failure leaves the carry intact —
            # but mirror the dirty guard anyway: a half-applied log is
            # unrepresentable, a dirty flag is cheap.
            n_carry = state.free.shape[0]
            self._state_dirty = True
            self.last_state = self._delta_direct_call(
                statics,
                compact_delta_spec(tensors),
                node_dom_for(tensors, n_carry),
                node_dom_small_for(tensors, n_carry),
                state,
                packed,
            )
            self._state_dirty = False
            REGISTRY.counter("state.delta_direct").inc()
            return
        if isinstance(state, CompactState):
            state = self._expand_carry(tensors, state)
        # a DENSE carry is donated to the delta dispatch below (the compact
        # branch only donates its fresh expansion); mirror place()'s guard
        # so a failure mid-delta forces the from-log rebuild instead of a
        # later dispatch on a deleted buffer
        self._state_dirty = True
        self.last_state = self._store_state(
            tensors, _apply_log_delta(statics, state, packed)
        )
        self._state_dirty = False

    def remove_placements(self, indices: List[int]) -> dict:
        """Delete log entries at `indices`; returns an undo token."""
        idx = sorted(set(indices))
        ext = self.ext_log
        saved = {
            "indices": idx,
            "entries": [
                (
                    self.placed_group[i],
                    self.placed_node[i],
                    self.placed_req[i],
                    ext["node"][i],
                    ext["vg_alloc"][i],
                    ext["sdev_take"][i],
                    ext["gpu_shares"][i],
                    ext["gpu_mem"][i],
                )
                for i in idx
            ],
        }
        for i in reversed(idx):
            del self.placed_group[i]
            del self.placed_node[i]
            del self.placed_req[i]
            for key in ("node", "vg_alloc", "sdev_take", "gpu_shares", "gpu_mem"):
                del ext[key][i]
        self._apply_saved_delta(saved, sign=-1.0)
        return saved

    def restore_placements(self, saved: dict) -> None:
        """Undo a remove_placements (entries return to their positions)."""
        ext = self.ext_log
        for i, entry in zip(saved["indices"], saved["entries"]):
            g, node, req, enode, vg, sdev, gpu_sh, gpu_mem = entry
            self.placed_group.insert(i, g)
            self.placed_node.insert(i, node)
            self.placed_req.insert(i, req)
            ext["node"].insert(i, enode)
            ext["vg_alloc"].insert(i, vg)
            ext["sdev_take"].insert(i, sdev)
            ext["gpu_shares"].insert(i, gpu_sh)
            ext["gpu_mem"].insert(i, gpu_mem)
        self._apply_saved_delta(saved, sign=1.0)
