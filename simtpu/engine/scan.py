"""The placement engine: sequential-equivalent scheduling as `lax.scan`.

Replaces the reference's pod-at-a-time handshake — fake-client Create →
channel block → scheduler goroutine filter/score over all nodes → bind event
(`pkg/simulator/simulator.go:219-244,334-353`; hot loop
`vendor/.../core/generic_scheduler.go:131-341,470`) — with one compiled scan:
each scan step is a full scheduling cycle (filter → score → select → state
update) over the whole node axis at once. Pods are strictly ordered like the
reference's serial loop, so placement semantics are sequential-equivalent.

Tie-breaking: the reference picks a random node among max scorers
(`generic_scheduler.go:188-209` reservoir sample); we take the lowest index —
deterministic, and placement-set-equivalent for conformance purposes
(SURVEY.md §7 'hard parts').
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensorize import ClusterTensors, PodBatch
from ..kernels.filters import interpod_filter, resources_fit
from ..kernels.scores import (
    balanced_allocation,
    interpod_score,
    least_allocated,
    maxabs_normalize,
    minmax_normalize,
    simon_share,
    taint_toleration_score,
)
from .state import SchedState, build_state

# Failure-reason codes (host maps to messages mirroring the scheduler's
# "0/N nodes are available: ..." status strings, scheduler.go:500)
OK = 0
FAIL_STATIC = 1  # affinity / selector / taints / pin — no node passed
FAIL_RESOURCES = 2  # insufficient free resources on every remaining node
FAIL_INTERPOD = 3  # inter-pod (anti-)affinity rules
FAIL_NO_NODE = 4  # forced pod names an unknown node

REASON_TEXT = {
    FAIL_STATIC: "node(s) didn't match node selector/affinity or had untolerated taints",
    FAIL_RESOURCES: "insufficient cpu/memory/extended resources on every feasible node",
    FAIL_INTERPOD: "node(s) didn't satisfy inter-pod affinity/anti-affinity rules",
    FAIL_NO_NODE: "pod references a node that does not exist",
}


class StaticArrays(NamedTuple):
    """Per-simulation constants handed to the jitted scan."""

    alloc: jnp.ndarray  # [N, R]
    static_mask: jnp.ndarray  # [G, N]
    node_pref: jnp.ndarray  # [G, N]
    taint_intol: jnp.ndarray  # [G, N]
    node_dom: jnp.ndarray  # [K, N]
    term_topo: jnp.ndarray  # [T]
    s_match: jnp.ndarray  # [G, T]
    a_aff_req: jnp.ndarray  # [G, T]
    a_anti_req: jnp.ndarray  # [G, T]
    w_aff_pref: jnp.ndarray  # [G, T]
    w_anti_pref: jnp.ndarray  # [G, T]


def statics_from(tensors: ClusterTensors) -> StaticArrays:
    return StaticArrays(
        alloc=jnp.asarray(tensors.alloc, jnp.float32),
        static_mask=jnp.asarray(tensors.static_mask),
        node_pref=jnp.asarray(tensors.node_pref_score),
        taint_intol=jnp.asarray(tensors.taint_intolerable),
        node_dom=jnp.asarray(tensors.node_dom, jnp.int32),
        term_topo=jnp.asarray(tensors.term_topo_key, jnp.int32),
        s_match=jnp.asarray(tensors.s_match),
        a_aff_req=jnp.asarray(tensors.a_aff_req),
        a_anti_req=jnp.asarray(tensors.a_anti_req),
        w_aff_pref=jnp.asarray(tensors.w_aff_pref),
        w_anti_pref=jnp.asarray(tensors.w_anti_pref),
    )


def schedule_step(
    statics: StaticArrays, state: SchedState, pod
) -> Tuple[SchedState, Tuple[jnp.ndarray, jnp.ndarray]]:
    """One scheduling cycle for one pod against every node."""
    g, req, pin, forced = pod
    n = statics.alloc.shape[0]
    node_ids = jnp.arange(n)

    static_m = statics.static_mask[g]
    pin_m = jnp.where(pin >= 0, node_ids == pin, True)
    m_static = static_m & pin_m
    m_res = m_static & resources_fit(state.free, req)
    m_all = m_res & interpod_filter(
        state.cnt_match,
        state.cnt_own_anti,
        statics.node_dom,
        statics.term_topo,
        statics.s_match[g],
        statics.a_aff_req[g],
        statics.a_anti_req[g],
    )
    feasible = jnp.any(m_all)

    # -- scores (weights: registry.go:101-145 + Simon extension) ----------
    score = least_allocated(state.free, statics.alloc, req)
    score += balanced_allocation(state.free, statics.alloc, req)
    score += minmax_normalize(simon_share(statics.alloc, req), m_all)
    score += minmax_normalize(statics.node_pref[g], m_all)
    score += taint_toleration_score(statics.taint_intol[g], m_all)
    raw_ipa = interpod_score(
        state.cnt_match,
        state.cnt_own_aff,
        state.w_own_aff_pref,
        state.w_own_anti_pref,
        statics.node_dom,
        statics.term_topo,
        statics.s_match[g],
        statics.w_aff_pref[g],
        statics.w_anti_pref[g],
    )
    score += maxabs_normalize(raw_ipa, m_all)
    score = jnp.where(m_all, score, -jnp.inf)

    chosen = jnp.where(forced, pin, jnp.argmax(score).astype(jnp.int32))
    placed = jnp.where(forced, pin >= 0, feasible)
    reason = jnp.where(
        placed,
        OK,
        jnp.where(
            forced,
            FAIL_NO_NODE,
            jnp.where(
                ~jnp.any(m_static),
                FAIL_STATIC,
                jnp.where(~jnp.any(m_res), FAIL_RESOURCES, FAIL_INTERPOD),
            ),
        ),
    ).astype(jnp.int32)

    # -- state update (no-op when not placed) -----------------------------
    safe = jnp.clip(chosen, 0)
    w = jnp.where(placed, 1.0, 0.0)
    free = state.free.at[safe].add(-req * w)

    t_count = statics.term_topo.shape[0]
    if t_count:
        dom_t = statics.node_dom[statics.term_topo, safe]  # [T]
        valid = (dom_t >= 0) & placed
        dsafe = jnp.where(dom_t >= 0, dom_t, 0)
        t_idx = jnp.arange(t_count)
        vw = jnp.where(valid, 1.0, 0.0)

        def bump(arr, vals):
            return arr.at[t_idx, dsafe].add(vals * vw)

        new_state = SchedState(
            free=free,
            cnt_match=bump(state.cnt_match, statics.s_match[g]),
            cnt_own_anti=bump(state.cnt_own_anti, statics.a_anti_req[g]),
            cnt_own_aff=bump(state.cnt_own_aff, statics.a_aff_req[g]),
            w_own_aff_pref=bump(state.w_own_aff_pref, statics.w_aff_pref[g]),
            w_own_anti_pref=bump(state.w_own_anti_pref, statics.w_anti_pref[g]),
        )
    else:
        new_state = state._replace(free=free)

    out_node = jnp.where(placed, chosen, -1)
    return new_state, (out_node, reason)


@partial(jax.jit, static_argnums=(), donate_argnums=(1,))
def _run_scan(statics: StaticArrays, state: SchedState, pods):
    return jax.lax.scan(partial(schedule_step, statics), state, pods)


class Engine:
    """Host-side driver: threads the placement log across app batches.

    One Engine per simulation (the reference builds a fresh Simulator per
    `Simulate` call, `pkg/simulator/core.go:64-70`).
    """

    def __init__(self, tensorizer):
        self.tensorizer = tensorizer
        self.placed_group: List[int] = []
        self.placed_node: List[int] = []
        self.placed_req: List[np.ndarray] = []

    def place(self, batch: PodBatch) -> Tuple[np.ndarray, np.ndarray]:
        """Schedule one batch; returns (node index per pod [-1 = failed],
        reason codes)."""
        tensors = self.tensorizer.freeze()
        r = tensors.alloc.shape[1]
        req = batch.req
        if req.shape[1] < r:
            req = np.pad(req, ((0, 0), (0, r - req.shape[1])))
        state = build_state(
            tensors,
            np.asarray(self.placed_group, np.int32),
            np.asarray(self.placed_node, np.int32),
            (
                np.stack([np.pad(q, (0, r - q.shape[0])) for q in self.placed_req])
                if self.placed_req
                else np.zeros((0, r), np.float32)
            ),
        )
        statics = statics_from(tensors)
        pods = (
            jnp.asarray(batch.group),
            jnp.asarray(req, jnp.float32),
            jnp.asarray(batch.pin, jnp.int32),
            jnp.asarray(batch.forced),
        )
        _, (nodes, reasons) = _run_scan(statics, state, pods)
        nodes = np.asarray(nodes)
        reasons = np.asarray(reasons)
        for i in range(len(nodes)):
            if nodes[i] >= 0:
                self.placed_group.append(int(batch.group[i]))
                self.placed_node.append(int(nodes[i]))
                self.placed_req.append(req[i])
        return nodes, reasons
