"""Synthetic cluster/workload generation for benchmarks and scale tests.

The reference ships only small hand-written example clusters
(`example/cluster/demo_1`, 4 nodes); its implied scaling axis is
pods × nodes (SURVEY.md §6). This module manufactures arbitrarily large
clusters and app lists with the full constraint mix — zone labels, taints +
tolerations, node selectors, preferred node affinity, inter-pod
anti-affinity, GPU-share nodes, Open-Local storage nodes — so the engine,
sweep, and bench exercise every kernel at any N.

Deterministic: all choices derive from `seed` via numpy's Generator.
"""

from __future__ import annotations

import json
from typing import List, Tuple

import numpy as np

from .core.objects import AppResource, ResourceTypes


def make_node(
    name: str,
    cpu_milli: int,
    mem_gib: int,
    labels=None,
    taints=None,
    gpu: Tuple[int, int] = None,  # (count, mem_mib_per_device)
    storage_gib: Tuple[int, ...] = (),
    device_gib: Tuple[Tuple[int, str], ...] = (),  # (gib, "ssd"|"hdd") each
) -> dict:
    alloc = {
        "cpu": f"{cpu_milli}m",
        "memory": f"{mem_gib}Gi",
        "pods": "256",
    }
    annotations = {}
    if gpu:
        count, mem = gpu
        alloc["alibabacloud.com/gpu-count"] = str(count)
        alloc["alibabacloud.com/gpu-mem"] = f"{count * mem}Mi"
    if storage_gib or device_gib:
        annotations["simon/node-local-storage"] = json.dumps(
            {
                "vgs": [
                    {"name": f"vg{j}", "capacity": g * (1 << 30), "requested": 0}
                    for j, g in enumerate(storage_gib)
                ],
                "devices": [
                    {
                        "device": f"/dev/sd{chr(ord('b') + j)}",
                        "capacity": g * (1 << 30),
                        "mediaType": media,
                        "isAllocated": False,
                    }
                    for j, (g, media) in enumerate(device_gib)
                ],
            }
        )
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": dict(labels or {}), "annotations": annotations},
        "spec": ({"taints": taints} if taints else {}),
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }


def make_deployment(
    name: str,
    replicas: int,
    cpu_milli: int,
    mem_mib: int,
    namespace: str = "bench",
    node_selector=None,
    tolerations=None,
    anti_affinity_topo: str = None,
    anti_affinity_required: bool = False,  # required vs preferred anti-affinity
    affinity_topo: str = None,  # required SELF-affinity (colocate-with-self)
    spread_topo: str = None,  # topologySpreadConstraints topology key
    spread_hard: bool = False,  # DoNotSchedule vs ScheduleAnyway
    gpu_mem_mib: int = 0,
    gpu_count: int = 1,  # GPU shares per pod (multi-GPU when > 1)
    gpu_index: str = None,  # preset gpu-index annotation, e.g. "0-1"
    lvm_gib=0,  # int (one claim) or tuple of ints (multi-claim)
    device_gib: int = 0,  # exclusive-SSD claim size
    host_port: int = 0,  # hostPort on the container (NodePorts conflicts)
    priority: int = None,  # spec.priority (preemption-relevant mixes)
) -> dict:
    labels = {"app": name}
    requests = {"cpu": f"{cpu_milli}m", "memory": f"{mem_mib}Mi"}
    container = {"name": "c", "image": "app", "resources": {"requests": requests}}
    if host_port:
        container["ports"] = [{"containerPort": host_port, "hostPort": host_port}]
    spec = {"containers": [container]}
    if priority is not None:
        spec["priority"] = int(priority)
    if node_selector:
        spec["nodeSelector"] = dict(node_selector)
    if tolerations:
        spec["tolerations"] = list(tolerations)
    if anti_affinity_topo:
        term = {
            "labelSelector": {"matchLabels": labels},
            "topologyKey": anti_affinity_topo,
        }
        if anti_affinity_required:
            # hard self-anti: at most one replica per topology domain — the
            # "one per node/zone" pattern (requiredDuringScheduling)
            anti = {"requiredDuringSchedulingIgnoredDuringExecution": [term]}
        else:
            anti = {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": 100, "podAffinityTerm": term}
                ]
            }
        spec["affinity"] = {"podAntiAffinity": anti}
    if affinity_topo:
        # required colocate-with-self: every replica must share a domain
        # with a pod matching the workload's own labels
        aff_term = {
            "labelSelector": {"matchLabels": labels},
            "topologyKey": affinity_topo,
        }
        spec.setdefault("affinity", {})["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [aff_term]
        }
    if spread_topo:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 1,
                "topologyKey": spread_topo,
                "whenUnsatisfiable": (
                    "DoNotSchedule" if spread_hard else "ScheduleAnyway"
                ),
                "labelSelector": {"matchLabels": labels},
            }
        ]
    # pod labels/annotations come from the OWNER's metadata, not the
    # template's (SetObjectMetaFromObject copies owner.GetLabels()/
    # GetAnnotations(), utils.go:336-346; the gpushare example carries its
    # gpu annotations on the workload metadata accordingly)
    annotations = {}
    if gpu_mem_mib:
        annotations["alibabacloud.com/gpu-mem"] = f"{gpu_mem_mib}Mi"
        annotations["alibabacloud.com/gpu-count"] = str(gpu_count)
        if gpu_index:
            annotations["alibabacloud.com/gpu-index"] = gpu_index
    volumes = []
    for gib in (lvm_gib,) if isinstance(lvm_gib, int) else tuple(lvm_gib):
        if gib:
            # unnamed-VG LVM volumes → binpack across node VGs
            # (common.go:59-107); a tuple makes a multi-claim pod
            volumes.append(
                {"kind": "LVM", "scName": "open-local-lvm", "size": gib * (1 << 30)}
            )
    if device_gib:
        # exclusive-device claim (media resolved via the SC catalog)
        volumes.append(
            {
                "kind": "SSD",
                "scName": "open-local-device-ssd",
                "size": device_gib * (1 << 30),
            }
        )
    if volumes:
        annotations["simon/pod-local-storage"] = json.dumps({"volumes": volumes})
    meta = {"name": name, "namespace": namespace, "labels": dict(labels)}
    if annotations:
        meta["annotations"] = annotations
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": meta,
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {"metadata": {"labels": dict(labels)}, "spec": spec},
        },
    }


def synth_cluster(
    n_nodes: int,
    seed: int = 0,
    zones: int = 8,
    taint_frac: float = 0.1,
    gpu_frac: float = 0.0,
    storage_frac: float = 0.0,
    racks_per_zone: int = 4,
) -> ResourceTypes:
    """A cluster of `n_nodes` heterogeneous nodes across `zones` zones,
    each node also labeled with a rack failure domain nested in its zone
    (`simtpu.io/rack`, the key `simtpu/faults` domain scenarios target)."""
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(n_nodes):
        zone = f"zone-{i % zones}"
        labels = {
            "topology.kubernetes.io/zone": zone,
            "kubernetes.io/hostname": f"node-{i:06d}",
        }
        taints = None
        if rng.random() < taint_frac:
            taints = [
                {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}
            ]
        gpu = None
        if rng.random() < gpu_frac:
            gpu = (int(rng.integers(2, 9)), 16384)
        storage = ()
        devices = ()
        if rng.random() < storage_frac:
            # 1-2 VGs exercises the multi-container binpack fill
            storage = tuple(
                int(rng.integers(200, 1000)) for _ in range(int(rng.integers(1, 3)))
            )
            if rng.random() < 0.5:
                devices = tuple(
                    (int(rng.integers(100, 500)), "ssd")
                    for _ in range(int(rng.integers(1, 4)))
                )
        cpu = int(rng.choice([16000, 32000, 64000, 96000]))
        mem = int(rng.choice([64, 128, 256, 384]))
        nodes.append(
            make_node(f"node-{i:06d}", cpu, mem, labels, taints, gpu, storage, devices)
        )
    if racks_per_zone > 0:
        # rack failure-domain labels, drawn AFTER the whole per-node stream
        # so every pre-existing seed's draws (and the placements/fuzz
        # scenarios pinned to them) are unchanged — append-only RNG use
        rack_of = rng.integers(racks_per_zone, size=n_nodes)
        for i, node in enumerate(nodes):
            node["metadata"]["labels"]["simtpu.io/rack"] = (
                f"zone-{i % zones}-rack-{int(rack_of[i])}"
            )
    res = ResourceTypes()
    res.nodes = nodes
    if storage_frac > 0:
        # the device SCs the pod claims name (media resolved from parameters)
        res.storage_classes = [
            {
                "kind": "StorageClass",
                "metadata": {"name": "open-local-device-ssd"},
                "parameters": {"mediaType": "ssd"},
            },
            {
                "kind": "StorageClass",
                "metadata": {"name": "open-local-device-hdd"},
                "parameters": {"mediaType": "hdd"},
            },
        ]
    return res


def synth_apps(
    n_pods: int,
    seed: int = 1,
    zones: int = 8,
    pods_per_deployment: int = 50,
    selector_frac: float = 0.2,
    toleration_frac: float = 0.1,
    anti_affinity_frac: float = 0.2,
    anti_affinity_hard_frac: float = 0.0,  # fraction OF anti workloads required
    spread_frac: float = 0.0,
    spread_hard_frac: float = 0.0,  # fraction OF spread workloads DoNotSchedule
    gpu_frac: float = 0.0,
    gpu_multi_frac: float = 0.0,  # fraction OF gpu workloads with count 2-4
    storage_frac: float = 0.0,
    storage_device_frac: float = 0.3,  # fraction OF storage workloads claiming
    # an exclusive device (the rest binpack LVM)
    lvm_multi_frac: float = 0.0,  # fraction OF LVM workloads with 2-3 claims
    affinity_frac: float = 0.0,  # required colocate-with-self workloads
) -> List[AppResource]:
    """App list totalling ~n_pods pods across deployments with mixed
    constraints (the `complicate` example writ large)."""
    rng = np.random.default_rng(seed)
    apps: List[AppResource] = []
    made = 0
    d = 0
    resources = ResourceTypes()
    while made < n_pods:
        replicas = min(pods_per_deployment, n_pods - made)
        kw = {}
        roll = rng.random()
        if roll < gpu_frac:
            kw["gpu_mem_mib"] = int(rng.choice([4096, 8192, 16384]))
            # draw only when enabled so pre-existing seeds' streams (and the
            # fuzz scenarios pinned to them) are unchanged
            if gpu_multi_frac and rng.random() < gpu_multi_frac:
                kw["gpu_count"] = int(rng.integers(2, 5))
                kw["gpu_mem_mib"] = 4096
        elif roll < gpu_frac + storage_frac:
            if rng.random() < storage_device_frac:
                kw["device_gib"] = int(rng.integers(50, 200))
            else:
                kw["lvm_gib"] = int(rng.integers(5, 40))
                if lvm_multi_frac and rng.random() < lvm_multi_frac:
                    kw["lvm_gib"] = tuple(
                        int(rng.integers(5, 30))
                        for _ in range(int(rng.integers(2, 4)))
                    )
        if rng.random() < selector_frac:
            kw["node_selector"] = {
                "topology.kubernetes.io/zone": f"zone-{int(rng.integers(zones))}"
            }
        if rng.random() < toleration_frac:
            kw["tolerations"] = [
                {"key": "dedicated", "operator": "Exists", "effect": "NoSchedule"}
            ]
        if rng.random() < anti_affinity_frac:
            kw["anti_affinity_topo"] = "kubernetes.io/hostname"
            # draw only when enabled so pre-existing seeds' streams (and the
            # fuzz scenarios pinned to them) are unchanged
            if anti_affinity_hard_frac and rng.random() < anti_affinity_hard_frac:
                kw["anti_affinity_required"] = True
        if affinity_frac and rng.random() < affinity_frac:
            kw["affinity_topo"] = "topology.kubernetes.io/zone"
        # draw only when enabled so pre-existing seeds' random streams (and
        # the scenarios fuzz tests pinned to them) are unchanged
        if spread_frac and rng.random() < spread_frac:
            kw["spread_topo"] = "topology.kubernetes.io/zone"
            kw["spread_hard"] = bool(spread_hard_frac) and rng.random() < spread_hard_frac
        resources.deployments.append(
            make_deployment(
                f"dep-{d:05d}",
                replicas,
                int(rng.choice([250, 500, 1000, 2000])),
                int(rng.choice([256, 512, 1024, 4096])),
                **kw,
            )
        )
        made += replicas
        d += 1
    apps.append(AppResource(name="synthetic", resource=resources))
    return apps


def make_trace(
    n_nodes: int,
    n_pods: int,
    seed: int = 0,
    days: float = 1.0,
    zones: int = 8,
    mean_gang: int = 8,
    duration_mean_s: float = 3600.0,
    duration_sigma: float = 0.8,
    priority_classes: Tuple[int, ...] = (0, 10, 100),
    priority_weights: Tuple[float, ...] = (0.7, 0.2, 0.1),
    cron_jobs: int = 2,
    elastic_frac: float = 0.0,
    node_event_frac: float = 0.0,
    forever_frac: float = 0.05,
    autoscale_pool: int = 0,
    autoscale_interval_s: float = 1800.0,
    autoscale_target_util: float = 0.6,
    selector_frac: float = 0.1,
    anti_affinity_frac: float = 0.15,
) -> dict:
    """A seeded Alibaba-shaped arrival trace for `simtpu replay`
    (timeline/events.py `trace_from_doc` consumes the returned document;
    `json.dumps` of it is a valid trace file).

    Shape: Poisson-ish gang arrivals (exponential inter-arrival gaps)
    over a `days`-long horizon, lognormal service durations, geometric-ish
    gang sizes around `mean_gang`, a priority-class mix, `cron_jobs`
    CronJob entries firing real cron schedules, and (opt-in) elastic
    HPA-scalable workloads, node maintenance windows, and a template-node
    autoscaler pool.

    Determinism: every choice derives from `seed` via one Generator, and
    optional features draw ONLY when enabled (the same append-only RNG
    discipline as `synth_cluster`'s rack labels) — enabling a new knob
    never perturbs the arrival stream an existing seed already pinned.
    Workload constraint mixes stay soft (preferred anti-affinity, node
    selectors): admission pressure comes from capacity, which keeps the
    end-state audit exact under out-of-order admissions
    (docs/timeline.md §determinism).
    """
    rng = np.random.default_rng(seed)
    horizon = float(days) * 86400.0
    jobs = []
    t = 0.0
    made = 0
    est_gangs = max(n_pods // max(mean_gang, 1), 1)
    mean_gap = horizon * 0.8 / est_gangs
    j = 0
    while made < n_pods:
        t += float(rng.exponential(mean_gap))
        if t >= horizon:
            break
        size = int(min(1 + rng.geometric(1.0 / max(mean_gang, 1)),
                       4 * mean_gang, n_pods - made))
        dur = float(rng.lognormal(np.log(duration_mean_s), duration_sigma))
        prio = int(rng.choice(priority_classes, p=priority_weights))
        kw = {}
        if rng.random() < selector_frac:
            kw["node_selector"] = {
                "topology.kubernetes.io/zone": f"zone-{int(rng.integers(zones))}"
            }
        if rng.random() < anti_affinity_frac:
            kw["anti_affinity_topo"] = "kubernetes.io/hostname"
        dep = make_deployment(
            f"tj-{j:05d}",
            size,
            int(rng.choice([250, 500, 1000, 2000])),
            int(rng.choice([256, 512, 1024, 4096])),
            priority=prio,
            **kw,
        )
        job = {
            "name": f"tj-{j:05d}",
            "t_s": round(t, 3),
            "priority": prio,
            "workload": dep,
        }
        if rng.random() >= forever_frac:
            job["duration_s"] = round(max(dur, 60.0), 3)
        # draw only when enabled: pre-existing seeds' streams (and the
        # replays pinned to them) are unchanged when the knob is off
        if elastic_frac and rng.random() < elastic_frac:
            lo = max(1, size // 2)
            hi = min(2 * size, 4 * mean_gang)
            usage = [
                [0.0, round(float(rng.uniform(0.3, 0.5)), 3)],
                [round(horizon * 0.3, 3), round(float(rng.uniform(0.7, 0.95)), 3)],
                [round(horizon * 0.7, 3), round(float(rng.uniform(0.35, 0.6)), 3)],
            ]
            job["elastic"] = {"min": lo, "max": hi, "usage": usage}
        jobs.append(job)
        made += size
        j += 1

    crons = []
    for c in range(int(cron_jobs)):
        expr = str(rng.choice(
            ["*/15 * * * *", "0 * * * *", "30 */2 * * *", "0 */6 * * *"]
        ))
        completions = int(rng.integers(1, max(mean_gang // 2, 2)))
        cj = {
            "apiVersion": "batch/v1",
            "kind": "CronJob",
            "metadata": {"name": f"cron-{c:03d}", "namespace": "bench"},
            "spec": {
                "schedule": expr,
                "jobTemplate": {
                    "spec": {
                        "completions": completions,
                        "template": {
                            "spec": {
                                "containers": [
                                    {
                                        "name": "c",
                                        "image": "app",
                                        "resources": {
                                            "requests": {
                                                "cpu": f"{int(rng.choice([250, 500]))}m",
                                                "memory": f"{int(rng.choice([256, 512]))}Mi",
                                            }
                                        },
                                    }
                                ]
                            }
                        },
                    }
                },
            },
        }
        crons.append(
            {
                "cron_job": cj,
                "duration_s": round(float(rng.uniform(300.0, 1200.0)), 3),
                "priority": 0,
            }
        )

    node_events = []
    if node_event_frac:
        k = max(1, int(node_event_frac * n_nodes))
        victims = rng.choice(n_nodes, size=min(k, n_nodes), replace=False)
        for v in sorted(int(x) for x in victims):
            t_down = float(rng.uniform(0.1, 0.7)) * horizon
            window = float(rng.lognormal(np.log(3600.0), 0.5))
            name = f"node-{v:06d}"
            node_events.append(
                {"t_s": round(t_down, 3), "down": [name]}
            )
            t_up = t_down + max(window, 300.0)
            if t_up < horizon:
                node_events.append({"t_s": round(t_up, 3), "up": [name]})

    doc = {
        "version": 1,
        "seed": int(seed),
        "horizon_s": horizon,
        "cluster": {
            "synth": {"n_nodes": int(n_nodes), "seed": int(seed),
                      "zones": int(zones)}
        },
        "jobs": jobs,
        "cron_jobs": crons,
        "node_events": node_events,
    }
    if autoscale_pool:
        doc["autoscale"] = {
            "interval_s": float(autoscale_interval_s),
            "target_util": float(autoscale_target_util),
            "pool": int(autoscale_pool),
            "node": make_node(
                "timeline-pool-template",
                32000,
                128,
                labels={"topology.kubernetes.io/zone": "zone-0"},
            ),
        }
    return doc
