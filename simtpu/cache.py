"""Persistent XLA compilation cache.

The reference pays no compilation cost (a Go binary is ahead-of-time
compiled); simtpu's cold path is XLA-compile-dominated — the north-star
first run costs ~2 minutes of compilation against a ~10 s warm run, and the
one-shot CLI user (`simtpu apply`, the reference's only UX,
`pkg/apply/apply.go:88`) always pays cold. Wiring JAX's persistent
compilation cache lets a fresh process reuse executables compiled by any
earlier run on the same machine/topology, collapsing cold → warm + a few
seconds of cache reads.

The planner's shape bucketing (`plan/incremental.py`, `engine/rounds.py
RoundsEngine.snap_shapes`) is the other half of the cold-path attack: probe
and verify executables are padded into the same deterministic shape buckets
on every run, so a cold `simtpu apply` finds the whole probe sweep's
round/scan bodies already in this cache instead of compiling
per-candidate-size specializations the previous process never produced.

Enabled by default for the CLI, the bench, and the test suite. Knobs:

- ``SIMTPU_COMPILATION_CACHE``: cache directory; ``0``/``off`` disables.
  Default ``~/.cache/simtpu/xla``.
- cache entries are written for every compilation taking >= 0.5 s (the
  engine's scan/round bodies all cost seconds to compile; tiny dispatches
  stay out of the cache).

Call :func:`enable_compilation_cache` BEFORE the first jit dispatch —
config flags apply to compilations that happen after the call.

ACCELERATOR BACKENDS ONLY: on the CPU backend the cache is left off —
jax 0.9.0's XLA:CPU ahead-of-time executable loader records compile-time
machine features that this host's runtime detection doesn't re-derive
(`+prefer-no-gather` etc.), and deserializing such an entry SEGFAULTS the
process (observed killing the test suite mid-run). CPU compiles are cheap
anyway; the 2-minute cold path the cache exists for is the TPU one.
"""

from __future__ import annotations

import os
import sys

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "simtpu", "xla"
)


def _skip_note(reason: str) -> None:
    """One stderr line whenever the persistent cache stays off — a silently
    disabled cache looks exactly like a slow cold path, and cold-path
    triage should never have to guess which one it is."""
    print(f"simtpu: persistent compilation cache off ({reason})", file=sys.stderr)


def enable_compilation_cache(path: str = None) -> str | None:
    """Point JAX's persistent compilation cache at `path` (default:
    $SIMTPU_COMPILATION_CACHE or ~/.cache/simtpu/xla). Returns the cache
    directory, or None when disabled — via SIMTPU_COMPILATION_CACHE=0/off
    or because the backend is CPU (see module docstring); every disabled
    exit says so on stderr."""
    import jax

    env = os.environ.get("SIMTPU_COMPILATION_CACHE", "")
    if env.lower() in ("0", "off", "false", "none", "no", "disabled"):
        _skip_note(f"SIMTPU_COMPILATION_CACHE={env}")
        return None
    try:
        if jax.default_backend() == "cpu":
            # ACCELERATOR ONLY — the XLA:CPU deserialize segfault (module
            # docstring); the note keeps the gating observable
            _skip_note("CPU backend: the XLA:CPU executable loader "
                       "segfaults on cache deserialization")
            return None
    except Exception as exc:
        _skip_note(f"backend probe failed: {type(exc).__name__}")
        return None
    cache_dir = path or env or _DEFAULT_DIR
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # cache regardless of executable size (the default also caches
        # everything; pinned for stability across jax versions)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # the dir flag LAST: it alone activates the cache, so a partial
        # failure above leaves the cache fully off and the None return honest
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as exc:  # cache is an optimization — never fail the run
        _skip_note(f"setup failed: {type(exc).__name__}: {exc}")
        return None
    return cache_dir
