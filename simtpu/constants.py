"""Framework-wide constants.

Mirrors `pkg/type/const.go:7-52` and the storage-class name table in
`pkg/utils/const.go:10-22`, plus the GPU-share resource names from the vendored
open-gpu-share (`vendor/github.com/alibaba/open-gpu-share/pkg/utils/const.go:3-9`).
"""

SIMON_PLUGIN = "Simon"
OPEN_LOCAL_PLUGIN = "Open-Local"
OPEN_GPU_SHARE_PLUGIN = "Open-Gpu-Share"
NEW_NODE_NAME_PREFIX = "simon"
DEFAULT_SCHEDULER_NAME = "default-scheduler"

ANNO_WORKLOAD_KIND = "simon/workload-kind"
ANNO_WORKLOAD_NAME = "simon/workload-name"
ANNO_WORKLOAD_NAMESPACE = "simon/workload-namespace"
ANNO_NODE_LOCAL_STORAGE = "simon/node-local-storage"
ANNO_POD_LOCAL_STORAGE = "simon/pod-local-storage"
ANNO_NODE_GPU_SHARE = "simon/node-gpu-share"

LABEL_NEW_NODE = "simon/new-node"
LABEL_APP_NAME = "simon/app-name"
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_ZONE_BETA = "failure-domain.beta.kubernetes.io/zone"
# failure-domain label the fault subsystem (simtpu/faults) keys rack-outage
# scenarios off; kubernetes standardizes no rack key, so simtpu owns one
LABEL_RACK = "simtpu.io/rack"

ENV_MAX_CPU = "MaxCPU"
ENV_MAX_MEMORY = "MaxMemory"
ENV_MAX_VG = "MaxVG"

NOTES_FILE_SUFFIX = "NOTES.txt"
SEPARATE_SYMBOL = "-"
WORKLOAD_HASH_DIGITS = 10
POD_HASH_DIGITS = 5
MAX_NUM_NEW_NODE = 100

# workload kind names (pkg/type/const.go:36-43)
KIND_POD = "Pod"
KIND_DEPLOYMENT = "Deployment"
KIND_RS = "ReplicaSet"
KIND_RC = "ReplicationController"
KIND_STS = "StatefulSet"
KIND_DS = "DaemonSet"
KIND_JOB = "Job"
KIND_CRON_JOB = "CronJob"

# open-local / yoda storage-class names (pkg/utils/const.go:10-22)
SC_LVM = ("open-local-lvm", "yoda-lvm-default")
SC_DEVICE_SSD = (
    "open-local-device-ssd",
    "open-local-mountpoint-ssd",
    "yoda-mountpoint-ssd",
    "yoda-device-ssd",
)
SC_DEVICE_HDD = (
    "open-local-device-hdd",
    "open-local-mountpoint-hdd",
    "yoda-mountpoint-hdd",
    "yoda-device-hdd",
)

# open-gpu-share resource names (vendor open-gpu-share utils/const.go:3-9)
RES_GPU_MEM = "alibabacloud.com/gpu-mem"
RES_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_POD_GPU_MEM = "alibabacloud.com/gpu-mem"
ANNO_POD_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_POD_GPU_INDEX = "alibabacloud.com/gpu-index"
LABEL_GPU_CARD_MODEL = "alibabacloud.com/gpu-card-model"

# terminal colors for progress output (pkg/utils/const.go:3-8)
COLOR_RESET = "\033[0m"
COLOR_RED = "\033[31m"
COLOR_GREEN = "\033[32m"
COLOR_YELLOW = "\033[33m"
COLOR_CYAN = "\033[36m"
