"""Warm snapshot sessions with crash-safe checkpoint recovery (ISSUE 14).

A session is one loaded cluster snapshot held warm for interactive
what-if queries: the ingested objects (cluster / apps / newNode template,
parsed once), the tensorized problem, and a `PlacedCluster` base
placement whose engine carry every drain/resilience sweep reads — the
compact carried state of PR 5, amortized across requests instead of
re-paid per CLI run.

Durability contract (the robustness headline): every session checkpoints
through `durable/checkpoint.py` at creation — a `meta` record (where the
snapshot came from) plus a `base` record (the full placement vectors,
the same shape the incremental planner persists per candidate).  After a
kill -9, the restarted daemon re-indexes the session directories and
rehydrates each session on first use WITHOUT re-dispatching: the pod-name
stream is re-seeded from the session fingerprint
(`durable.checkpoint.name_seed`), expansion + tensorization re-run
deterministically, and the engine's placement log + carried state are
rebuilt from the recorded vectors (`build_state` — bit-identical to the
dispatched carry by the donated-state reuse guard's pinned contract, the
same replay the planners' `--resume` rides).

Session ids are the first 12 hex digits of the problem fingerprint, so
loading the same snapshot twice is idempotent and recovery needs no
separate id↔problem index.  Eviction (capacity or memory pressure) drops
only the in-memory state — the checkpoint stays, and the next query
rehydrates.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..durable.checkpoint import (
    CheckpointError,
    CheckpointMismatch,
    PlanCheckpoint,
    file_digest,
    name_seed,
    plan_fingerprint,
)
from ..obs.metrics import REGISTRY
from ..obs.trace import span
from .errors import AuditRejected, BadRequest, NotFound

log = logging.getLogger("simtpu.serve")

#: checkpoint `kind` stamp for session records — a plan checkpoint can
#: never be mistaken for a session and vice versa (CheckpointMismatch)
SESSION_KIND = "serve-session"

#: session id length (hex digits of the problem fingerprint)
SID_LEN = 12

_SESSIONS_GAUGE = REGISTRY.gauge("serve.sessions")
_RECOVERED = REGISTRY.counter("serve.recovered")
_EVICTIONS = REGISTRY.counter("serve.evictions")

#: serializes pod-name-stream seeding + expansion PROCESS-WIDE: generated
#: pod names draw from one global RNG (workloads/expand.py), and every
#: bit-identity contract in the daemon — served-vs-one-shot fit answers,
#: kill -9 rehydration — rests on the seeding owner holding the stream
#: for its whole expansion.  Session creation/rehydration (here) and the
#: batcher's fit/capacity queries (batching.py) all take it.
EXPAND_LOCK = threading.Lock()

#: counter names surfaced in the per-query `engine.grow` block — defined
#: beside the growth kernels so the CLI can report them without
#: importing the daemon (the off-path zero-cost pin)
from ..engine.state import GROW_COUNTERS  # noqa: E402  (re-export)


def warm_serve_enabled() -> bool:
    """SIMTPU_SERVE_WARM gate (default ON): serve sessions keep ONE warm
    grow-mode engine and APPEND query pods into its vocabulary
    (`Tensorizer.add_pods` + `Engine._try_extend_carry`) instead of
    re-running the Applier + a from-scratch tensorize per request — the
    append-only vocabulary growth fast path (ISSUE 20).  Off = the
    pre-warm behavior: every fit query pays a full `simulate()`."""
    return os.environ.get("SIMTPU_SERVE_WARM", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def _grow_engine(tz):
    """Engine factory for warm sessions: the bulk rounds engine in grow
    mode (dense carry, term axes pre-padded to pow2 buckets), so a query
    that grows the vocabulary extends the carried state in place."""
    from ..engine.rounds import RoundsEngine

    eng = RoundsEngine(tz)
    eng.enable_grow()
    return eng


def grow_doc(session: Optional["Session"] = None) -> Dict[str, object]:
    """The `engine.grow` response block: warm-path counters
    (`engine.state.grow_counters_doc`) plus the serving session's live
    bucket layout."""
    from ..engine.state import grow_counters_doc

    doc: Dict[str, object] = grow_counters_doc()
    if session is not None:
        doc["warm"] = bool(session.warm)
        ref = getattr(session.pc.engine, "_grow_ref", None)
        if ref:
            doc["buckets"] = {
                "terms": int(ref["t"]),
                "t_cap": int(ref["t_cap"]),
                "interpod": int(ref["ti"]),
                "ti_cap": int(ref["ti_cap"]),
                "nodes": int(ref["n"]),
            }
    return doc


class Session:
    """One warm snapshot: ingested objects + placed base + per-session
    lock (engine access serializes on it — the engine's placement log and
    carried state are single-writer structures)."""

    def __init__(
        self,
        sid: str,
        fingerprint: str,
        config_path: str,
        cluster,
        apps,
        new_node: Optional[dict],
        sched_config,
        pc,
        audit: Optional[dict] = None,
        recovered: bool = False,
        warm: bool = False,
    ):
        self.sid = sid
        self.fingerprint = fingerprint
        self.config_path = config_path
        self.cluster = cluster
        self.apps = apps
        self.new_node = new_node
        self.sched_config = sched_config
        self.pc = pc
        self.audit = audit
        self.recovered = recovered
        self.warm = warm
        # capacity fast-path overlay (batching._run_capacity_warm): the
        # cloned tensorizer + node-extended carry, cached per clone-count
        # bucket so repeat capacity queries re-probe without re-growing
        self.cap_overlay: Dict[int, object] = {}
        # name-stream fast-forward (batching._run_fit_warm): the widths
        # of every pod-name draw the one-shot path consumes expanding
        # the cluster + session apps BEFORE the query app — recorded
        # once, replayed per query so warm answers carry the exact pod
        # names the legacy simulate() path would have generated
        self.name_draws = None
        self.lock = threading.RLock()
        self.created_unix = time.time()
        self.last_used = time.monotonic()
        self.queries = 0
        # node name -> index, for drain masks
        self.node_index = {
            (n.get("metadata") or {}).get("name", f"node[{i}]"): i
            for i, n in enumerate(cluster.nodes)
        }

    def touch(self, n: int = 1) -> None:
        """Mark `n` queries served (a coalesced batch touches once with
        its width, so the summary's per-session count stays honest)."""
        self.last_used = time.monotonic()
        self.queries += n

    def summary(self) -> Dict[str, object]:
        nodes = np.asarray(self.pc.nodes)
        return {
            "session": self.sid,
            "config": self.config_path,
            "nodes": int(len(self.cluster.nodes)),
            "pods": int(len(nodes)),
            "placed": int((nodes >= 0).sum()),
            "unplaced": int((nodes < 0).sum()),
            "queries": int(self.queries),
            "recovered": bool(self.recovered),
            "created_unix": self.created_unix,
            "audit_ok": bool(self.audit.get("ok")) if self.audit else None,
            "has_new_node": self.new_node is not None,
            "warm": bool(self.warm),
        }


def _extras_rows(pc) -> Dict[str, np.ndarray]:
    """Row-parallel extended-resource vectors of a fresh base placement,
    rebuilt from the engine log (a fresh `place_cluster` appends placed
    pods in batch order, the `PlacedCluster.log_row` contract) — the
    payload of the `base` checkpoint record, mirroring what the
    incremental planner persists per candidate."""
    tensors = pc.tensors
    ext = pc.engine.ext_log
    p = len(pc.nodes)
    lvm = np.zeros((p, tensors.ext.vg_cap.shape[1]), np.float32)
    dev = np.zeros((p, tensors.ext.sdev_cap.shape[1]), bool)
    gpu = np.zeros((p, tensors.ext.gpu_dev_total.shape[1]), np.float32)
    for j, row in enumerate(pc.log_row):
        lvm[row] = np.asarray(ext["vg_alloc"][j], np.float32)
        dev[row] = np.asarray(ext["sdev_take"][j], bool)
        gpu[row] = np.asarray(ext["gpu_shares"][j], np.float32)
    return {"lvm": lvm, "dev": dev, "gpu": gpu}


def _replay_placed_cluster(
    cluster, apps, rec, sched_config, extended_resources=(), warm=False
):
    """A `PlacedCluster` equivalent to one that just ran the recorded
    base placement: tensorization re-runs (deterministic given the
    re-seeded name stream, and with the SAME extended-resource terms the
    creation-time tensorization used — the recorded lvm/dev/gpu vectors
    carry those widths), the engine's log and carried state rebuild from
    the record — no dispatch (the planners' checkpoint-replay contract,
    plan/incremental.py `replay_engine`)."""
    from ..engine.rounds import RoundsEngine
    from ..engine.state import build_state
    from ..faults.drain import PlacedCluster
    from ..parallel.sweep import assemble_planning_problem

    tz, _all_nodes, _n_base, ordered = assemble_planning_problem(
        cluster, apps, cluster.nodes[0], 0, tuple(extended_resources)
    )
    batch = tz.add_pods(ordered)
    tensors = tz.freeze()
    nodes = np.asarray(rec["nodes"])
    reasons = np.asarray(rec["reasons"])
    if nodes.shape[0] != len(batch.pods):
        raise CheckpointMismatch(
            f"session base record covers {nodes.shape[0]} pods, the "
            f"re-expanded snapshot has {len(batch.pods)}; refusing to "
            "rehydrate (the snapshot files changed since the checkpoint)"
        )
    eng = _grow_engine(tz) if warm else RoundsEngine(tz)
    eng.sched_config = sched_config
    r = tensors.alloc.shape[1]
    req_pad = batch.req
    if req_pad.shape[1] < r:
        req_pad = np.pad(req_pad, ((0, 0), (0, r - req_pad.shape[1])))
    ok = np.flatnonzero(nodes >= 0)
    lvm = np.asarray(rec["lvm"], np.float32)
    dev = np.asarray(rec["dev"], bool)
    gpu = np.asarray(rec["gpu"], np.float32)
    eng.placed_group = np.asarray(batch.group)[ok].tolist()
    eng.placed_node = nodes[ok].tolist()
    eng.placed_req = list(req_pad[ok])
    eng.ext_log = {
        "node": nodes[ok].tolist(),
        "vg_alloc": list(lvm[ok]),
        "sdev_take": list(dev[ok]),
        "gpu_shares": list(gpu[ok]),
        "gpu_mem": np.asarray(batch.ext["gpu_mem"])[ok].tolist(),
    }
    dense = build_state(
        tensors,
        np.asarray(eng.placed_group, np.int32),
        np.asarray(eng.placed_node, np.int32),
        eng.log_req_matrix(r),
        eng.ext_log,
    )
    if warm:
        # a rehydrated warm session carries the SAME bucket-padded dense
        # state a fresh warm placement would — queries append either way
        dense = eng._enter_grow_buckets(tensors, dense)
    eng.last_state = eng._store_state(tensors, dense)
    eng._last_vocab = eng.state_vocab(tensors)
    eng._state_dirty = False
    return PlacedCluster(
        tz=tz, tensors=tensors, batch=batch, engine=eng,
        nodes=nodes, reasons=reasons,
    )


class SessionStore:
    """Thread-safe session registry with checkpoint-backed recovery.

    `state_dir` "" disables durability (sessions are memory-only and die
    with the process — the bench/ephemeral mode); otherwise each session
    owns `state_dir/<sid>/` with the durable/checkpoint.py layout."""

    def __init__(
        self,
        state_dir: str = "",
        max_sessions: int = 8,
        audit: Optional[bool] = None,
        sched_config_path: str = "",
        extended_resources: Sequence[str] = (),
        progress=None,
    ):
        self.state_dir = state_dir
        self.max_sessions = max(int(max_sessions), 1)
        self.audit = audit
        self.sched_config_path = sched_config_path
        self.extended_resources = tuple(extended_resources)
        self._say = progress or (lambda msg: None)
        self._lock = threading.Lock()
        self._sessions: Dict[str, Session] = {}
        # sid -> config path for every session this store can produce —
        # includes evicted and crash-recovered ones not yet rehydrated
        self._known: Dict[str, str] = {}
        # sid -> Event for a creation in progress: concurrent loads of
        # the same snapshot wait for the winner instead of each paying
        # the full placement + audit and discarding all but one
        self._pending: Dict[str, threading.Event] = {}
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)

    # -- ingest ------------------------------------------------------------

    def _load_problem(self, config_path: str):
        """Ingest one simon config into (cluster, apps, new_node,
        sched_config) — the Applier's loaders, so serve sessions accept
        exactly what `simtpu apply -f` accepts (charts included)."""
        from ..plan.capacity import Applier, ApplierOptions

        try:
            applier = Applier(ApplierOptions(
                simon_config=config_path,
                default_scheduler_config=self.sched_config_path,
                extended_resources=list(self.extended_resources),
            ))
            cluster = applier.load_cluster()
            apps = applier.load_apps()
            new_node = None
            try:
                new_node = applier.load_new_node()
            except (ValueError, FileNotFoundError, OSError):
                # newNode is optional for a session: without it only the
                # capacity endpoint refuses (BadRequest), fit/drain/
                # resilience queries need no template
                pass
            sched_config = applier._sched_config()
        except (ValueError, OSError) as exc:
            # OSError covers the whole client-controlled-path family
            # (FileNotFoundError, PermissionError, IsADirectoryError...):
            # a bad snapshot path is the client's 400, never a 500 bug
            # report with a flight bundle behind it
            raise BadRequest(f"snapshot ingest failed: {exc}") from exc
        if not cluster.nodes:
            raise BadRequest(
                f"snapshot {config_path!r} has no nodes; nothing to serve"
            )
        return cluster, apps, new_node, sched_config

    def _fingerprint(self, cluster, apps, new_node) -> str:
        return plan_fingerprint(
            cluster, apps, new_node,
            extra={
                "serve": SESSION_KIND,
                "extended_resources": list(self.extended_resources),
                "sched_config": file_digest(self.sched_config_path),
            },
        )

    def _place_base(self, fingerprint: str, cluster, apps, sched_config):
        """The session's base placement: deterministic (name stream
        seeded from the fingerprint, so creation and recovery expand
        identical pods) and audited before anything is served from it."""
        from ..audit.checker import (
            audit_enabled,
            audit_placed_cluster,
            inject_divergence_enabled,
        )
        from ..faults import place_cluster
        from ..workloads.expand import seed_name_hashes

        warm = warm_serve_enabled()
        with EXPAND_LOCK, span(
            "serve.place_base", nodes=len(cluster.nodes)
        ):
            seed_name_hashes(name_seed(fingerprint))
            pc = place_cluster(
                cluster, apps,
                extended_resources=self.extended_resources,
                sched_config=sched_config,
                # warm sessions place through ONE grow-mode engine whose
                # carry later queries append into (bit-identical
                # placements either way, tests/test_grow.py)
                engine_factory=_grow_engine if warm else None,
            )
        audit_doc = None
        want_audit = audit_enabled() if self.audit is None else self.audit
        if want_audit:
            pc, audit_doc, hard_fail = audit_placed_cluster(
                pc, self._say, inject=inject_divergence_enabled()
            )
            if hard_fail is not None:
                raise AuditRejected(
                    f"session base placement failed certification: "
                    f"{hard_fail}"
                )
        return pc, audit_doc

    # -- lifecycle ---------------------------------------------------------

    def create(self, config_path: str):
        """Load a snapshot into a session (idempotent: the same problem
        returns the existing session).  Returns (session, created)."""
        if not config_path or not isinstance(config_path, str):
            raise BadRequest("body must carry {'config': '<path>'}")
        cluster, apps, new_node, sched_config = self._load_problem(
            config_path
        )
        fingerprint = self._fingerprint(cluster, apps, new_node)
        sid = fingerprint[:SID_LEN]
        while True:
            with self._lock:
                got = self._sessions.get(sid)
                if got is not None:
                    got.touch()
                    return got, False
                pending = self._pending.get(sid)
                if pending is None:
                    self._pending[sid] = threading.Event()
                    break
            # another thread is already building this exact session:
            # wait for its result instead of duplicating the placement
            pending.wait(timeout=600.0)
        try:
            sdir = self._session_dir(sid)
            if sdir and os.path.isdir(sdir):
                # an evicted (or pre-crash) session with a checkpoint on
                # disk: rehydrate (zero dispatches) instead of re-paying
                # the full placement + audit — re-loading IS the daemon's
                # whole amortization story.  A broken checkpoint falls
                # through to a fresh placement below.
                try:
                    return self._rehydrate(sid, config_path), False
                except (BadRequest, NotFound, CheckpointError,
                        CheckpointMismatch) as exc:
                    log.warning(
                        "serve: session %s checkpoint unusable (%s); "
                        "re-placing fresh", sid, exc,
                    )
            session = self._build_fresh(
                None, config_path,
                problem=(cluster, apps, new_node, sched_config),
                fingerprint=fingerprint,
            )
            return session, True
        finally:
            with self._lock:
                done = self._pending.pop(sid, None)
            if done is not None:
                done.set()

    def _build_fresh(
        self,
        expected_sid: Optional[str],
        config_path: str,
        problem=None,
        fingerprint: Optional[str] = None,
    ) -> Session:
        """Full fresh build of one session (ingest unless handed in,
        place, audit, checkpoint, insert) — the shared tail of `create`
        and of `get`'s broken-checkpoint fallback.  `expected_sid`
        guards the fallback: a rebuild whose fingerprint no longer
        matches the requested session means the snapshot files changed,
        and silently answering from a different problem would be worse
        than the 400."""
        if problem is None:
            problem = self._load_problem(config_path)
        cluster, apps, new_node, sched_config = problem
        if fingerprint is None:
            fingerprint = self._fingerprint(cluster, apps, new_node)
        sid = fingerprint[:SID_LEN]
        if expected_sid is not None and sid != expected_sid:
            raise BadRequest(
                f"session {expected_sid!r} cannot be rebuilt: the "
                f"snapshot files changed (they now define problem "
                f"{sid}); delete the session and reload"
            )
        pc, audit_doc = self._place_base(
            fingerprint, cluster, apps, sched_config
        )
        session = Session(
            sid, fingerprint, config_path, cluster, apps, new_node,
            sched_config, pc, audit=audit_doc, warm=warm_serve_enabled(),
        )
        self._checkpoint(session)
        with self._lock:
            raced = self._sessions.get(sid)
            if raced is not None:
                # a concurrent get() rehydrated it first — keep the
                # copy queries may already hold
                return raced
            self._evict_for_capacity_locked()
            self._sessions[sid] = session
            self._known[sid] = config_path
            _SESSIONS_GAUGE.set(len(self._sessions))
        self._say(f"session {sid} loaded from {config_path}")
        return session

    def get(self, sid: str) -> Session:
        """The live session, rehydrating from its checkpoint when it was
        evicted or belongs to a pre-crash incarnation of the daemon.
        Concurrent misses on one sid dedup through `_pending`, exactly
        like `create`: a post-crash burst of K queries pays ONE
        rehydration, not K."""
        while True:
            with self._lock:
                got = self._sessions.get(sid)
                if got is not None:
                    return got
                config_path = self._known.get(sid)
                if config_path is None:
                    raise NotFound(
                        f"no session {sid!r} (load the snapshot first)"
                    )
                pending = self._pending.get(sid)
                if pending is None:
                    self._pending[sid] = threading.Event()
                    break
            pending.wait(timeout=600.0)
        try:
            return self._rehydrate(sid, config_path)
        except CheckpointError as exc:
            # a corrupt/incomplete checkpoint must not turn this sid
            # into a permanent 500: rebuild fresh, exactly as create()
            # does for the same condition (the fingerprint guard inside
            # keeps a CHANGED snapshot a 400, not a silent swap)
            log.warning(
                "serve: session %s checkpoint unusable (%s); "
                "re-placing fresh", sid, exc,
            )
            return self._build_fresh(sid, config_path)
        finally:
            with self._lock:
                done = self._pending.pop(sid, None)
            if done is not None:
                done.set()

    def delete(self, sid: str) -> None:
        with self._lock:
            if sid not in self._sessions and sid not in self._known:
                raise NotFound(f"no session {sid!r}")
            self._sessions.pop(sid, None)
            self._known.pop(sid, None)
            _SESSIONS_GAUGE.set(len(self._sessions))
        sdir = self._session_dir(sid)
        if sdir and os.path.isdir(sdir):
            import shutil

            shutil.rmtree(sdir, ignore_errors=True)

    def list(self) -> List[Dict[str, object]]:
        with self._lock:
            live = [s.summary() for s in self._sessions.values()]
            cold = [
                {"session": sid, "config": cfg, "cold": True}
                for sid, cfg in self._known.items()
                if sid not in self._sessions
            ]
        return sorted(live, key=lambda d: d["session"]) + sorted(
            cold, key=lambda d: d["session"]
        )

    # -- durability --------------------------------------------------------

    def _session_dir(self, sid: str) -> str:
        return os.path.join(self.state_dir, sid) if self.state_dir else ""

    def _checkpoint(self, session: Session) -> None:
        """Persist the session's identity + base placement atomically
        (durable/checkpoint.py — EINTR/rename races retried once, ENOSPC
        loud).  No state dir = memory-only session."""
        sdir = self._session_dir(session.sid)
        if not sdir:
            return
        ck = PlanCheckpoint(
            sdir, kind=SESSION_KIND, fingerprint=session.fingerprint
        )
        ck.put(
            "meta", 0,
            config=session.config_path,
            sched_config=self.sched_config_path,
            extended_resources=json.dumps(list(self.extended_resources)),
        )
        nodes = np.asarray(session.pc.nodes)
        extras = _extras_rows(session.pc)
        ck.put(
            "base", 0,
            nodes=nodes, reasons=np.asarray(session.pc.reasons),
            lvm=extras["lvm"], dev=extras["dev"], gpu=extras["gpu"],
        )

    def recover(self) -> List[str]:
        """Index every session directory under `state_dir` (the restart
        path).  Rehydration itself is lazy — the first query against a
        recovered sid pays the replay; indexing is just a manifest read,
        so restart is O(sessions) metadata, not O(sessions) placements."""
        if not self.state_dir or not os.path.isdir(self.state_dir):
            return []
        found = []
        for sid in sorted(os.listdir(self.state_dir)):
            sdir = os.path.join(self.state_dir, sid)
            mpath = os.path.join(sdir, "manifest.json")
            if not os.path.isfile(mpath):
                continue
            try:
                with open(mpath) as f:
                    man = json.load(f)
                if man.get("kind") != SESSION_KIND:
                    continue
                ck = PlanCheckpoint(
                    sdir, kind=SESSION_KIND,
                    fingerprint=man.get("fingerprint", ""), resume=True,
                )
                meta = ck.get("meta", 0)
                if meta is None or ck.get("base", 0) is None:
                    raise CheckpointError(
                        f"session {sid}: meta/base record missing"
                    )
                config_path = str(meta["config"])
            except (CheckpointError, CheckpointMismatch, OSError,
                    ValueError) as exc:
                log.warning(
                    "serve: skipping unrecoverable session dir %s (%s)",
                    sdir, exc,
                )
                continue
            with self._lock:
                self._known[sid] = config_path
            found.append(sid)
        if found:
            self._say(
                f"recovered {len(found)} session(s) from {self.state_dir} "
                "(rehydrated on first use)"
            )
        return found

    def _rehydrate(self, sid: str, config_path: str) -> Session:
        """Rebuild one session from its checkpoint: re-ingest, re-seed the
        name stream, re-tensorize, replay the recorded placement into a
        fresh engine — bit-identical carried state, zero dispatches."""
        sdir = self._session_dir(sid)
        if not sdir or not os.path.isdir(sdir):
            raise NotFound(
                f"session {sid!r} was evicted and has no checkpoint to "
                "rehydrate from; load the snapshot again"
            )
        from ..workloads.expand import seed_name_hashes

        cluster, apps, new_node, sched_config = self._load_problem(
            config_path
        )
        fingerprint = self._fingerprint(cluster, apps, new_node)
        try:
            ck = PlanCheckpoint(
                sdir, kind=SESSION_KIND, fingerprint=fingerprint,
                resume=True,
            )
            rec = ck.get("base", 0)
            if rec is None:
                raise CheckpointError(
                    f"session {sid}: base record missing"
                )
        except CheckpointMismatch as exc:
            raise BadRequest(
                f"session {sid!r} cannot rehydrate: {exc} (the snapshot "
                "files changed since the checkpoint; delete and reload)"
            ) from exc
        warm = warm_serve_enabled()
        with EXPAND_LOCK, span("serve.rehydrate", sid=sid):
            seed_name_hashes(name_seed(fingerprint))
            pc = _replay_placed_cluster(
                cluster, apps, rec, sched_config,
                extended_resources=self.extended_resources, warm=warm,
            )
        session = Session(
            sid, fingerprint, config_path, cluster, apps, new_node,
            sched_config, pc, recovered=True, warm=warm,
        )
        _RECOVERED.inc()
        with self._lock:
            raced = self._sessions.get(sid)
            if raced is not None:
                return raced
            self._evict_for_capacity_locked()
            self._sessions[sid] = session
            self._known[sid] = config_path
            _SESSIONS_GAUGE.set(len(self._sessions))
        self._say(f"session {sid} rehydrated from checkpoint")
        return session

    # -- eviction ----------------------------------------------------------

    def _evict_for_capacity_locked(self) -> None:
        """Drop least-recently-used in-memory sessions past the cap (the
        caller holds `_lock` and is about to insert one).  Checkpointed
        sessions stay recoverable; memory-only ones are gone for good —
        both count in `serve.evictions`."""
        while len(self._sessions) >= self.max_sessions:
            victim = min(
                self._sessions.values(), key=lambda s: s.last_used
            )
            self._sessions.pop(victim.sid)
            if not self.state_dir:
                self._known.pop(victim.sid, None)
            _EVICTIONS.inc()
            log.warning(
                "serve: evicted session %s (capacity %d); it %s",
                victim.sid, self.max_sessions,
                "rehydrates from checkpoint on next use"
                if self.state_dir else "was memory-only and is gone",
            )
        _SESSIONS_GAUGE.set(len(self._sessions))

    def evict_idle(self, keep: Sequence[str] = ()) -> int:
        """Memory-pressure valve: drop every in-memory session except
        `keep` (the one mid-query).  Called when a served dispatch
        exhausted the OOM chunk-halving backoff — shedding warm state is
        the graceful degradation; the checkpoints make it survivable.

        Best-effort by design: queries still queued for an evicted
        session keep it alive through their own references until the
        (single) worker drains them, and the next request against it
        rehydrates a fresh copy — so the reclaim lands once the short
        queue empties, which is also when the 503's Retry-After tells
        clients to come back."""
        kept = set(keep)
        with self._lock:
            victims = [
                sid for sid in self._sessions if sid not in kept
            ]
            for sid in victims:
                self._sessions.pop(sid)
                if not self.state_dir:
                    self._known.pop(sid, None)
                _EVICTIONS.inc()
            _SESSIONS_GAUGE.set(len(self._sessions))
        if victims:
            log.warning(
                "serve: memory pressure — evicted %d idle session(s): %s",
                len(victims), ", ".join(victims),
            )
        return len(victims)
