"""The `simtpu serve` daemon: stdlib HTTP front-end over the session
store and the coalescing batcher (ISSUE 14).

Stack: `http.server.ThreadingHTTPServer` (one thread per connection — no
new dependencies) parses and validates; admitted queries cross one
bounded queue to the single dispatch worker (`batching.Batcher`); the
HTTP thread blocks on the query's completion event with the request's
deadline.  The daemon's robustness contract, endpoint by endpoint:

- every query carries a cooperative deadline (`durable/deadline.py`);
  expiry answers a structured 504 body — with the capacity search's
  partial result when the `RunControl` salvaged one — and the in-flight
  dispatch completes harmlessly off-wire;
- a full queue answers 429 + Retry-After, touching nothing admitted;
- OOM rides the chunk-halving backoff inside every dispatcher; exhausted
  backoff evicts idle sessions (rehydratable from checkpoint) and
  answers 503 + Retry-After;
- SIGTERM flips /readyz to 503 and refuses new work (503 Degraded) while
  the probe endpoints keep answering, drains the queue and every
  in-flight request, then releases the port and exits 0; a second signal
  abandons the drain;
- kill -9 loses nothing durable: sessions checkpoint at creation and
  rehydrate bit-identically on the next daemon (session.py);
- 500s (bugs, by the taxonomy's design rule) dump a flight-recorder
  bundle (obs/flight.py) with the request context — structured 503/504
  responses deliberately do not — and every request runs under a
  `serve.request` span.

Routes (all bodies JSON):

    GET    /healthz                   process liveness
    GET    /readyz                    accepting? (503 while draining)
    GET    /metrics                   full PR-8 registry snapshot
    GET    /v1/sessions               list sessions (live + recoverable)
    POST   /v1/sessions               {"config": path} -> load snapshot
    GET    /v1/sessions/<sid>         session summary
    DELETE /v1/sessions/<sid>         drop session + checkpoint
    POST   /v1/sessions/<sid>/fit         {"workloads": [...]|"app": path}
    POST   /v1/sessions/<sid>/drain       {"nodes": ["name", ...]}
    POST   /v1/sessions/<sid>/capacity    {"workloads": ...?, "max_new_nodes"?}
    POST   /v1/sessions/<sid>/resilience  {"spec": "k=1", "samples"?, "seed"?}

Every POST query accepts `"deadline_s"` (default: the daemon's
`--default-deadline`).  Error bodies follow `errors.error_doc` and the
status table `errors.HTTP_TAXONOMY` (docs/serving.md).
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence, Tuple

from ..durable.deadline import RunControl
from ..obs.metrics import REGISTRY, SCHEMA_VERSION
from ..obs.trace import span
from .batching import QUERY_KINDS, Batcher, Query
from .errors import (
    BadRequest,
    DeadlineExceeded,
    Degraded,
    InternalError,
    NotFound,
    ServeError,
    error_doc,
)
from .session import SessionStore

log = logging.getLogger("simtpu.serve")

_TIMEOUTS = REGISTRY.counter("serve.timeouts")
_ERRORS = REGISTRY.counter("serve.errors")
_DRAINING = REGISTRY.gauge("serve.draining")

#: request-body ceiling: bodies buffer in RAM before validation, so an
#: uncapped Content-Length would bypass every admission/memory valve
MAX_BODY_BYTES = 8 << 20


@dataclass
class ServeOptions:
    """Daemon configuration (the `simtpu serve` flags)."""

    host: str = "127.0.0.1"
    port: int = 8090  # 0 = ephemeral; the chosen port is printed/attr
    state_dir: str = ""  # "" = memory-only sessions (no crash recovery)
    max_sessions: int = 8
    queue_depth: int = 64
    default_deadline_s: float = 30.0
    #: extra wall the handler waits past the deadline for the worker's
    #: cooperative partial (a capacity search returns it at the next
    #: candidate boundary) before answering 504 with partial=null
    grace_s: float = 0.5
    coalesce_window_s: float = 0.0
    audit: Optional[bool] = None
    sched_config: str = ""
    extended_resources: Sequence[str] = ()
    #: drain budget on SIGTERM before in-flight work is abandoned
    drain_timeout_s: float = 30.0


class _Httpd(ThreadingHTTPServer):
    daemon_threads = True  # stragglers must not block a forced exit
    app: "SimtpuServer" = None


class SimtpuServer:
    """One daemon instance: session store + batcher + HTTP listener.
    Usable in-process (tests, loadgen) or via `serve_main` (CLI)."""

    def __init__(self, opts: ServeOptions, progress=None):
        self.opts = opts
        self._say = progress or (lambda msg: None)
        self.store = SessionStore(
            state_dir=opts.state_dir,
            max_sessions=opts.max_sessions,
            audit=opts.audit,
            sched_config_path=opts.sched_config,
            extended_resources=opts.extended_resources,
            progress=self._say,
        )
        self.batcher = Batcher(
            self.store,
            queue_depth=opts.queue_depth,
            coalesce_window_s=opts.coalesce_window_s,
        )
        self.httpd: Optional[_Httpd] = None
        self.port: Optional[int] = None
        self.draining = False
        self._t0 = time.monotonic()
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._serve_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._shutdown_once = threading.Lock()
        self._shutdown_started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Bind, recover checkpointed sessions, start the worker and the
        accept loop (on a background thread).  Returns the bound port."""
        self.store.recover()
        self.batcher.start()
        self.httpd = _Httpd(
            (self.opts.host, self.opts.port), _Handler
        )
        self.httpd.app = self
        self.port = self.httpd.server_address[1]
        _DRAINING.set(0)
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="simtpu-serve-accept",
            daemon=True,
        )
        self._serve_thread.start()
        return self.port

    def request_shutdown(self, reason: str = "shutdown") -> None:
        """Begin a graceful drain (idempotent): stop accepting, let the
        queue and in-flight requests finish, then release the port.  Runs
        on its own thread — callable from a signal handler."""
        with self._shutdown_once:
            if self._shutdown_started:
                return
            self._shutdown_started = True
        self.draining = True
        _DRAINING.set(1)
        self._say(f"serve: draining ({reason})")
        threading.Thread(
            target=self._graceful_stop, name="simtpu-serve-drain",
            daemon=True,
        ).start()

    def _graceful_stop(self) -> None:
        # order matters: the listener stays up through the drain so
        # /healthz + /readyz keep answering (the load-balancer contract —
        # readyz flipped to 503 the moment `draining` was set, and new
        # mutating requests answer 503 Degraded); only once the queue and
        # in-flight requests are done does the accept loop stop and the
        # port release
        budget = self.opts.drain_timeout_s
        t0 = time.monotonic()
        self.batcher.stop(drain=True, timeout=budget)
        with self._inflight_cv:
            while self._inflight > 0:
                left = budget - (time.monotonic() - t0)
                if left <= 0:
                    log.warning(
                        "serve: drain budget exhausted with %d request(s) "
                        "in flight; abandoning them", self._inflight,
                    )
                    break
                self._inflight_cv.wait(timeout=min(left, 0.5))
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
        self._stopped.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until a requested shutdown completed."""
        return self._stopped.wait(timeout)

    def force_stop(self) -> None:
        """Abandon the drain: fail the backlog fast and release the port
        (second-signal path; also the tests' cleanup)."""
        self.draining = True
        _DRAINING.set(1)
        self.batcher.stop(drain=False, timeout=1.0)
        if self.httpd is not None:
            try:
                self.httpd.shutdown()
                self.httpd.server_close()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        self._stopped.set()

    # -- request accounting ------------------------------------------------

    def enter(self) -> None:
        with self._inflight_cv:
            self._inflight += 1

    def leave(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._t0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: _Httpd

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: N802 — stdlib signature
        log.debug("serve: %s %s", self.address_string(), fmt % args)

    def _send(self, status: int, doc: dict, retry_after=None) -> None:
        body = json.dumps(doc).encode()
        try:
            self.send_response(int(status))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header(
                    "Retry-After", str(max(int(retry_after), 1))
                )
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            # the client gave up (reset/timeout mid-response): nothing
            # to salvage, and a routine disconnect must NOT escape to the
            # 500 path and masquerade as a daemon bug with a flight
            # bundle behind it
            self.close_connection = True

    def _fail(self, exc: ServeError, context: str) -> None:
        _ERRORS.inc()
        if exc.status == 500:
            # a 500 is a bug report: leave the post-mortem bundle behind
            # (spans + registry + the request context), never raise.
            # 503/504 are deliberately excluded — they are STRUCTURED
            # responses of the taxonomy (load shedding, deadlines), and
            # a deadline-heavy workload must not fill the disk with
            # bundles one routine response at a time
            from ..obs.flight import dump_flight

            dump_flight(
                f"serve {exc.code}: {exc}", exc.status,
                extra={"serve_request": context},
            )
        self._send(exc.status, error_doc(exc), retry_after=exc.retry_after)

    def _body(self) -> dict:
        raw = self._raw_body
        if not raw:
            return {}
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise BadRequest(f"body is not valid JSON: {exc}") from exc
        if not isinstance(doc, dict):
            raise BadRequest("body must be a JSON object")
        return doc

    # -- routing -----------------------------------------------------------

    def _route(self) -> Tuple[str, ...]:
        path = self.path.split("?", 1)[0].rstrip("/")
        return tuple(p for p in path.split("/") if p)

    def _dispatch(self, method: str) -> None:
        app = self.server.app
        parts = self._route()
        context = f"{method} {self.path}"
        # consume the request body UP FRONT, whatever route or error the
        # request hits: protocol_version is HTTP/1.1 (keep-alive), and an
        # error response sent with unread body bytes still in the socket
        # would desync the connection — the leftover bytes would parse as
        # the client's next request line.  Both a malformed and an
        # oversized Content-Length are the client's structured 400 (the
        # connection closes: the body was not, or must not be, read)
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True
            self._fail(
                BadRequest("Content-Length must be an integer"), context
            )
            return
        if length > MAX_BODY_BYTES:
            self.close_connection = True
            self._fail(
                BadRequest(
                    f"body too large ({length} bytes; the limit is "
                    f"{MAX_BODY_BYTES})"
                ),
                context,
            )
            return
        self._raw_body = self.rfile.read(length) if length > 0 else b""
        app.enter()
        try:
            with span("serve.request", method=method, path=self.path):
                self._handle(app, method, parts)
        except ServeError as exc:
            self._fail(exc, context)
        except Exception as exc:  # noqa: BLE001 — taxonomy boundary
            log.exception("serve: unhandled error on %s", context)
            self._fail(
                InternalError(f"{type(exc).__name__}: {exc}"), context
            )
        finally:
            app.leave()

    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    # -- handlers ----------------------------------------------------------

    def _handle(self, app: SimtpuServer, method: str, parts) -> None:
        if method == "GET" and parts == ("healthz",):
            self._send(200, {
                "ok": True,
                "uptime_s": round(app.uptime_s, 3),
                "schema_version": SCHEMA_VERSION,
            })
            return
        if method == "GET" and parts == ("readyz",):
            if app.draining:
                self._send(
                    503,
                    {"ready": False, "reason": "draining"},
                    retry_after=5,
                )
            else:
                self._send(200, {"ready": True})
            return
        if method == "GET" and parts == ("metrics",):
            self._send(200, {
                "schema_version": SCHEMA_VERSION,
                "metrics": REGISTRY.snapshot(),
            })
            return
        if len(parts) >= 2 and parts[0] == "v1" and parts[1] == "sessions":
            self._sessions(app, method, parts[2:])
            return
        raise NotFound(f"no route {method} {self.path!r}")

    def _sessions(self, app: SimtpuServer, method: str, rest) -> None:
        if app.draining and method != "GET":
            raise Degraded(
                "daemon is draining; retry against the next instance",
                retry_after=5,
            )
        if not rest:
            if method == "GET":
                self._send(200, {"sessions": app.store.list()})
                return
            if method == "POST":
                body = self._body()
                session, created = app.store.create(
                    str(body.get("config", ""))
                )
                self._send(201 if created else 200, session.summary())
                return
            raise NotFound(f"no route {method} /v1/sessions")
        sid = rest[0]
        if len(rest) == 1:
            if method == "GET":
                self._send(200, app.store.get(sid).summary())
                return
            if method == "DELETE":
                app.store.delete(sid)
                self._send(200, {"ok": True, "deleted": sid})
                return
            raise NotFound(f"no route {method} on a session")
        if len(rest) == 2 and method == "POST":
            kind = rest[1]
            if kind not in QUERY_KINDS:
                raise NotFound(
                    f"unknown query kind {kind!r} "
                    f"(one of {', '.join(QUERY_KINDS)})"
                )
            self._query(app, sid, kind, self._body())
            return
        raise NotFound(f"no route {method} {self.path!r}")

    def _query(self, app: SimtpuServer, sid, kind, payload) -> None:
        deadline = payload.pop("deadline_s", None)
        if deadline is None:
            deadline = app.opts.default_deadline_s
        try:
            deadline = float(deadline)
        except (TypeError, ValueError):
            raise BadRequest(
                f"deadline_s must be a number, got {deadline!r}"
            ) from None
        if deadline < 0:
            raise BadRequest("deadline_s must be >= 0")
        session = app.store.get(sid)
        query = Query(
            kind=kind, session=session, payload=payload,
            control=RunControl(deadline=deadline),
        )
        app.batcher.submit(query)  # Overloaded -> 429, Degraded -> 503
        if query.done.wait(timeout=deadline):
            self._complete(query)
            return
        # deadline passed on the wire: ask the in-flight work to stop at
        # its next cooperative boundary, give it `grace_s` to hand back a
        # structured partial, then answer 504 either way — the dispatch
        # finishes off-wire and the daemon is unharmed
        query.control.trigger("deadline")
        done = query.done.wait(timeout=app.opts.grace_s)
        if done and not isinstance(query.error, DeadlineExceeded):
            # the dispatch finished inside the grace window with a REAL
            # outcome (result or a non-deadline error): answer it — a
            # slightly late answer beats a 504 that throws it away
            self._complete(query)
            return
        _TIMEOUTS.inc()
        partial = None
        if isinstance(query.error, DeadlineExceeded):
            partial = query.error.extra.get("partial")
        self._fail(
            DeadlineExceeded(
                f"deadline of {deadline:g}s exceeded",
                extra={"partial": partial, "kind": kind},
            ),
            f"POST /v1/sessions/{sid}/{kind}",
        )

    def _complete(self, query: Query) -> None:
        if query.error is None:
            self._send(200, query.result)
            return
        if isinstance(query.error, DeadlineExceeded):
            _TIMEOUTS.inc()
        err = (
            query.error
            if isinstance(query.error, ServeError)
            else InternalError(str(query.error))
        )
        self._fail(
            err, f"POST {self.path} ({query.kind})"
        )


def serve_main(opts: ServeOptions, progress=None) -> int:
    """Blocking CLI entry: start, print the bound address, run until
    SIGTERM/SIGINT drains (exit 0).  A second signal abandons the drain
    (exit 1)."""
    say = progress or (lambda msg: print(msg, flush=True))
    server = SimtpuServer(opts, progress=say)
    port = server.start()
    say(
        f"simtpu serve: listening on http://{opts.host}:{port} "
        f"(sessions={opts.max_sessions}, queue={opts.queue_depth}, "
        f"deadline={opts.default_deadline_s:g}s, "
        f"state={opts.state_dir or 'memory-only'})"
    )
    hard = {"n": 0}

    def on_signal(signum, frame):
        hard["n"] += 1
        name = signal.Signals(signum).name
        if hard["n"] > 1:
            log.warning("serve: second %s — abandoning drain", name)
            server.force_stop()
            return
        server.request_shutdown(reason=name)

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, on_signal)
        except ValueError:  # not the main thread (tests)
            break
    try:
        while not server.wait(timeout=0.5):
            pass
    finally:
        for sig, old in prev.items():
            signal.signal(sig, old)
    say("simtpu serve: drained; bye")
    return 0 if hard["n"] <= 1 else 1
