"""The serve error taxonomy and its HTTP mapping (ISSUE 14).

One hierarchy, one table: every failure class a served request can hit
maps to exactly one HTTP status + machine-readable `code`, the same way
the one-shot CLI's failure classes map to exit codes (docs/robustness.md
— the two tables cross-reference each other in docs/serving.md).  The
daemon never answers a request with a traceback: anything not in the
taxonomy is an `InternalError` (500) with a flight-recorder bundle
behind it (obs/flight.py).

The design rule mirrors the CLI's: a *structured* failure is part of the
API (400/404/429/503/504 bodies are stable JSON documents clients
dispatch on), while a 500 is a bug report.
"""

from __future__ import annotations

from typing import Dict, Optional


class ServeError(Exception):
    """Base of the served-failure taxonomy.  `status` is the HTTP code,
    `code` the stable machine-readable discriminator in the JSON body,
    `retry_after` an optional Retry-After header value in seconds, and
    `extra` additional body fields (e.g. a partial result)."""

    status = 500
    code = "internal"

    def __init__(
        self,
        message: str,
        retry_after: Optional[float] = None,
        extra: Optional[Dict[str, object]] = None,
    ):
        super().__init__(message)
        self.retry_after = retry_after
        self.extra = extra or {}


class BadRequest(ServeError):
    """Malformed body/params, unknown query kind, or an ingest-rejected
    spec (`SpecError`, bad fault spec) — the client's problem, one
    actionable line, exactly like the CLI's exit-1 `fail_early` path."""

    status = 400
    code = "bad_request"


class NotFound(ServeError):
    """Unknown session id or route."""

    status = 404
    code = "not_found"


class Overloaded(ServeError):
    """Admission control shed this request: the bounded query queue is
    full.  In-flight and queued work is untouched — the 429 is the
    pressure-release valve, not a failure of anything already admitted."""

    status = 429
    code = "overloaded"


class Degraded(ServeError):
    """The daemon is shedding state to survive — draining for SIGTERM, or
    a served dispatch exhausted the OOM chunk-halving backoff and idle
    sessions were evicted (they rehydrate from checkpoint on next use).
    Always carries Retry-After: the condition is transient by design."""

    status = 503
    code = "degraded"


class DeadlineExceeded(ServeError):
    """The request's deadline expired.  504-style by analogy (the
    *upstream work*, not a proxy, timed out); the body is a structured
    partial document — `partial` carries whatever the cooperative
    interrupt salvaged (a capacity search's best-verified candidate, the
    CLI exit-3 contract) or null when nothing completed.  The dispatch
    keeps running to completion on the worker; the daemon is unharmed."""

    status = 504
    code = "deadline"


class AuditRejected(ServeError):
    """The independent placement auditor (simtpu/audit) refused to
    certify the answer AND the serial-exact fallback did not certify
    either — the served analog of CLI exit 4's hard case.  Nothing
    uncertified is ever served."""

    status = 500
    code = "audit"


class InternalError(ServeError):
    """Everything outside the taxonomy.  The handler wraps the original
    exception's one-line repr and dumps a flight bundle."""

    status = 500
    code = "internal"


#: status/code table for docs/serving.md + the error-taxonomy test —
#: ONE source for the mapping so docs and behavior cannot drift
HTTP_TAXONOMY = {
    cls.code: cls.status
    for cls in (
        BadRequest,
        NotFound,
        Overloaded,
        Degraded,
        DeadlineExceeded,
        AuditRejected,
        InternalError,
    )
}


def error_doc(exc: ServeError) -> Dict[str, object]:
    """The stable JSON body of a failed request."""
    doc: Dict[str, object] = {
        "ok": False,
        "error": exc.code,
        "message": str(exc),
    }
    if exc.retry_after is not None:
        doc["retry_after_s"] = round(float(exc.retry_after), 3)
    doc.update(exc.extra)
    return doc
