"""Admission control + request coalescing for the simtpu daemon.

Two robustness mechanisms live here:

1. ADMISSION: the query queue is bounded.  A full queue sheds the new
   request with a 429 (`errors.Overloaded`) — queued and in-flight work
   is never touched, so overload degrades arrival rate, not correctness.

2. COALESCING: queued sweep-shaped queries (drain what-ifs, resilience
   assessments) against the SAME session collapse into one vmapped
   dispatch.  A drain query is one scenario row; a resilience query is a
   generated scenario set — both are `[S, N]` masks, so a burst of K
   queries becomes `stack_scenarios` + ONE `sweep_scenarios` call
   instead of K engine round-trips (the scenario-axis trick
   `faults/sweep.py` already proves out, re-used on the request axis).
   Answers are sliced back out per query and are bit-identical to the
   serial one-query-at-a-time path because scenario rows are independent
   (the sweep-vs-serial-oracle pin, tests/test_faults.py).

Fit and capacity queries never coalesce (their pod sets differ); they
amortize through the session's warm ingest and the process-global
compile caches instead.

Deadlines are cooperative (`durable/deadline.py`): each query carries a
`RunControl` whose clock starts at submission, so queue wait counts
against the budget.  The worker drops queries already past deadline
before dispatching, `plan_capacity` polls the control at candidate
boundaries (a capacity query's 504 carries the structured partial), and
a sweep that outlives its callers simply completes into the void — the
daemon is unharmed either way.

Memory pressure: every dispatch already rides the OOM chunk-halving
backoff (durable/backoff.py, inside the scan/rounds/sweep dispatchers).
When even that exhausts, the batcher evicts idle sessions (they
rehydrate from checkpoint) and answers 503 `Degraded` with Retry-After —
shed state, keep the process.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..durable.backoff import is_resource_exhausted
from ..durable.checkpoint import name_seed
from ..durable.deadline import PlanInterrupted, RunControl
from ..obs.metrics import REGISTRY
from ..obs.trace import span
from .errors import (
    BadRequest,
    DeadlineExceeded,
    Degraded,
    InternalError,
    Overloaded,
    ServeError,
)
from .session import (
    EXPAND_LOCK as _EXPAND_LOCK,
    Session,
    SessionStore,
    grow_doc,
)

log = logging.getLogger("simtpu.serve")

#: query kinds that compile to scenario rows and may share one dispatch
SWEEP_KINDS = ("drain", "resilience")

#: all query kinds the batcher executes
QUERY_KINDS = ("fit", "drain", "capacity", "resilience")

#: hard cap on queries fused into one sweep dispatch (the scenario-chunk
#: machinery below it re-chunks for memory anyway; this caps latency skew)
MAX_BATCH = 64

#: Retry-After (seconds) stamped on load-shed and degraded responses
RETRY_AFTER_S = 2.0

#: server-side ceiling on a resilience query's per-term sample budget:
#: `samples` also gates the exhaustive C(n,k) branch of
#: faults/scenarios.k_node_scenarios, so an uncapped (or <= 0 =
#: "exhaustive") client value could enumerate terabytes of combinations
#: host-side — outside the XLA RESOURCE_EXHAUSTED path the OOM backoff
#: protects
MAX_SWEEP_SAMPLES = 4096

_REQUESTS = REGISTRY.counter("serve.requests")
_BATCHES = REGISTRY.counter("serve.batches")
_COALESCED = REGISTRY.counter("serve.coalesced")
_SHED = REGISTRY.counter("serve.shed")
_OOM_DEGRADED = REGISTRY.counter("serve.oom_degraded")
#: engine sweep dispatches the daemon issued — the coalescing pin reads
#: serve.sweeps against serve.requests: K fused queries bump requests K
#: times and sweeps once (tests/test_serve.py, `make bench-serve`)
_SWEEPS = REGISTRY.counter("serve.sweeps")
_QUEUE_DEPTH = REGISTRY.gauge("serve.queue_depth")
_REQUEST_S = REGISTRY.histogram("serve.request_s")
#: warm-engine serving (ISSUE 20): queries answered by APPENDING into the
#: session's grow-mode engine (zero re-tensorization), and the genuine
#: vocabulary-class misses that fell back to the full legacy path — the
#: acceptance pin is retensorize_fallbacks == 0 on the loadgen mix
_WARM_FITS = REGISTRY.counter("serve.warm_fits")
_WARM_CAPACITY = REGISTRY.counter("serve.warm_capacity")
_RETENSORIZE = REGISTRY.counter("grow.retensorize_fallbacks")

# pod-name-stream serialization lives in session.EXPAND_LOCK (imported
# above as _EXPAND_LOCK): session creation/rehydration and the
# fit/capacity expansions below must never interleave RNG draws


@dataclass
class Query:
    """One admitted request, handed from the HTTP thread to the worker
    and completed through `done`/`result`/`error`."""

    kind: str
    session: Session
    payload: Dict[str, object]
    control: RunControl
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, object]] = None
    error: Optional[Exception] = None
    coalesced: bool = False
    t_submit: float = field(default_factory=time.perf_counter)

    @property
    def fingerprint(self) -> str:
        """Deterministic per-request fingerprint: seeds the pod-name
        stream for fit/capacity expansion, so the served answer is
        reproducible (and test-pinnable) as a one-shot run with the same
        seed."""
        h = hashlib.sha256()
        h.update(self.session.fingerprint.encode())
        h.update(self.kind.encode())
        h.update(json.dumps(self.payload, sort_keys=True, default=str).encode())
        return h.hexdigest()

    def finish(self, result=None, error=None) -> None:
        if error is not None:
            self.error = error
        else:
            self.result = result
        _REQUEST_S.observe(time.perf_counter() - self.t_submit)
        self.done.set()


def int_field(payload: Dict[str, object], key: str, default: int) -> int:
    """Integer body field, or the taxonomy's 400 — client garbage must
    never escape as a 500 bug report (with a flight bundle behind it)."""
    value = payload.get(key, default)
    try:
        return int(value)
    except (TypeError, ValueError):
        raise BadRequest(
            f"{key!r} must be an integer, got {value!r}"
        ) from None


def app_from_payload(payload: Dict[str, object], name: str = "query"):
    """An `AppResource` from a request body: inline `workloads` (a list
    of manifest dicts — JSON is already the object form) or an `app`
    path on the daemon's filesystem (the CLI-config workflow)."""
    from ..core.objects import AppResource, ResourceTypes

    workloads = payload.get("workloads")
    app_path = payload.get("app")
    if bool(workloads) == bool(app_path):
        raise BadRequest(
            "body must carry exactly one of 'workloads' (inline manifest "
            "list) or 'app' (a path readable by the daemon)"
        )
    if workloads:
        if not isinstance(workloads, list) or not all(
            isinstance(w, dict) for w in workloads
        ):
            raise BadRequest("'workloads' must be a list of manifest objects")
        resources = ResourceTypes()
        for obj in workloads:
            resources.add(obj)
        return AppResource(name=str(payload.get("name", name)), resource=resources)
    from ..io.yaml_loader import load_resources

    try:
        return AppResource(
            name=str(payload.get("name", name)),
            resource=load_resources(str(app_path)),
        )
    except (OSError, ValueError) as exc:
        raise BadRequest(f"cannot load app from {app_path!r}: {exc}") from exc


class Batcher:
    """Bounded queue + one dispatch worker.

    One worker by design: engine dispatch is serial on the backend
    anyway, a second dispatch thread would only interleave the pod-name
    stream and contend for the device — concurrency lives in the
    HTTP threads (ThreadingHTTPServer) and inside each vmapped dispatch."""

    def __init__(
        self,
        store: SessionStore,
        queue_depth: int = 64,
        coalesce_window_s: float = 0.0,
    ):
        self.store = store
        self.queue_depth = max(int(queue_depth), 1)
        self.coalesce_window_s = max(float(coalesce_window_s), 0.0)
        self._dq: deque = deque()
        self._cv = threading.Condition()
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Event()
        self._idle.set()

    # -- admission ---------------------------------------------------------

    def submit(self, query: Query) -> None:
        """Admit or shed.  Shedding raises `Overloaded` (HTTP 429) and
        touches nothing already admitted."""
        with self._cv:
            if self._stopping:
                raise Degraded(
                    "daemon is draining; retry against the next instance",
                    retry_after=RETRY_AFTER_S,
                )
            if len(self._dq) >= self.queue_depth:
                _SHED.inc()
                raise Overloaded(
                    f"query queue is full ({self.queue_depth} deep); "
                    "retry after the backlog drains",
                    retry_after=RETRY_AFTER_S,
                )
            _REQUESTS.inc()
            self._dq.append(query)
            _QUEUE_DEPTH.set(len(self._dq))
            self._idle.clear()
            self._cv.notify()

    # -- worker ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="simtpu-serve-worker", daemon=True
        )
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Stop the worker.  With `drain`, admitted queries complete
        first (the SIGTERM contract: in-flight work always finishes);
        without, the backlog is failed fast with `Degraded`."""
        with self._cv:
            self._stopping = True
            if not drain:
                while self._dq:
                    q = self._dq.popleft()
                    q.finish(error=Degraded(
                        "daemon shut down before this query ran",
                        retry_after=RETRY_AFTER_S,
                    ))
                _QUEUE_DEPTH.set(0)
                self._idle.set()
            self._cv.notify_all()
        drained = self._idle.wait(timeout)
        # snapshot the thread: stop() may race a concurrent stop() (the
        # second-SIGTERM force path vs the graceful-drain thread), and
        # joining an already-joined thread is harmless while reading a
        # torn None is not
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            self._thread = None
        return drained

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._execute(batch)
            except BaseException:  # noqa: BLE001 — the worker must survive
                log.exception("serve: batch execution escaped; replying 500")
                for q in batch:
                    if not q.done.is_set():
                        q.finish(error=InternalError(
                            "internal error; see the daemon log"
                        ))

    def _take_batch(self) -> Optional[List[Query]]:
        """Pop the next query plus everything queued that can share its
        dispatch.  Returns None when stopping with an empty queue."""
        with self._cv:
            while not self._dq:
                self._idle.set()
                if self._stopping:
                    return None
                self._cv.wait(timeout=0.5)
            first = self._dq.popleft()
            batch = [first]
            if first.kind in SWEEP_KINDS:
                self._coalesce_locked(first, batch)
            _QUEUE_DEPTH.set(len(self._dq))
        if (
            first.kind in SWEEP_KINDS
            and self.coalesce_window_s > 0
            and len(batch) < MAX_BATCH
            and not self._stopping
        ):
            # optional micro-window for bursty clients whose requests
            # arrive a hair apart; default 0 = coalesce only what is
            # already queued (no added latency for lone queries)
            t_end = time.monotonic() + self.coalesce_window_s
            while len(batch) < MAX_BATCH and time.monotonic() < t_end:
                time.sleep(min(0.002, self.coalesce_window_s))
                with self._cv:
                    self._coalesce_locked(first, batch)
                    _QUEUE_DEPTH.set(len(self._dq))
        return batch

    def _coalesce_locked(self, first: Query, batch: List[Query]) -> None:
        keep: deque = deque()
        while self._dq and len(batch) < MAX_BATCH:
            q = self._dq.popleft()
            if q.kind in SWEEP_KINDS and q.session is first.session:
                q.coalesced = True
                batch.append(q)
            else:
                keep.append(q)
        keep.extend(self._dq)
        self._dq.clear()
        self._dq.extend(keep)

    # -- execution ---------------------------------------------------------

    def _execute(self, batch: List[Query]) -> None:
        live = []
        for q in batch:
            try:
                q.control.check()
            except PlanInterrupted as exc:
                # expired while queued: answer the structured timeout
                # without burning a dispatch on it
                q.finish(error=DeadlineExceeded(
                    f"deadline expired before dispatch ({exc.reason})",
                    extra={"partial": None},
                ))
            else:
                live.append(q)
        if not live:
            return
        # counted on LIVE queries only: expired/malformed riders never
        # touch a dispatch, and the coalesce metrics are a CI pin —
        # rider-only "coalescing" must not satisfy it
        _BATCHES.inc()
        session = live[0].session
        try:
            with session.lock:
                session.touch(len(live))
                if live[0].kind in SWEEP_KINDS:
                    self._run_sweep_batch(session, live)
                else:
                    for q in live:
                        self._run_single(q)
        except Exception as exc:  # noqa: BLE001 — taxonomy-mapped below
            err = self._map_error(exc, session)
            for q in live:
                if not q.done.is_set():
                    q.finish(error=err)

    def _map_error(self, exc: Exception, session: Session) -> Exception:
        if isinstance(exc, ServeError):
            return exc
        if is_resource_exhausted(exc):
            # the chunk-halving backoff inside the dispatchers already
            # retried down to single-row chunks and still could not fit:
            # shed warm state (sessions rehydrate from checkpoint) and
            # tell clients to back off — the process survives
            _OOM_DEGRADED.inc()
            evicted = self.store.evict_idle(keep=(session.sid,))
            return Degraded(
                "memory pressure: a served dispatch exhausted its OOM "
                f"backoff; evicted {evicted} idle session(s), retry "
                "shortly",
                retry_after=RETRY_AFTER_S,
            )
        # deliberately NO blanket ValueError/KeyError -> 400 mapping: an
        # error escaping a dispatch that no validation layer claimed is
        # OUR bug, and blaming the client would also skip the 500 path's
        # flight bundle.  Client-input errors are wrapped as BadRequest
        # at their sources (_scenarios_for, app_from_payload, the
        # SpecError catch in _run_fit).
        log.exception("serve: unexpected error executing query")
        return InternalError(f"{type(exc).__name__}: {exc}")

    # -- sweep-shaped queries (coalescible) --------------------------------

    def _scenarios_for(self, q: Query):
        from ..faults import generate_scenarios
        from ..faults.scenarios import ScenarioSet

        session = q.session
        n = len(session.cluster.nodes)
        if q.kind == "drain":
            names = q.payload.get("nodes")
            if not isinstance(names, list) or not names:
                raise BadRequest(
                    "drain body must carry {'nodes': ['<name-or-index>', ...]}"
                )
            mask = np.zeros(n, bool)
            for name in names:
                if isinstance(name, bool):
                    raise BadRequest(f"bad node reference {name!r}")
                if isinstance(name, int):
                    # index form, for clients that only know the node
                    # count (tools/serve_loadgen.py)
                    if not 0 <= name < n:
                        raise BadRequest(
                            f"node index {name} out of range [0, {n})"
                        )
                    mask[name] = True
                    continue
                idx = session.node_index.get(str(name))
                if idx is None:
                    raise BadRequest(f"unknown node {name!r} in this snapshot")
                mask[idx] = True
            return ScenarioSet(
                masks=mask[None, :],
                labels=(f"drain:{','.join(str(x) for x in names)}",),
                kind="mixed",
                k=int(mask.sum()),
            )
        spec = str(q.payload.get("spec", "k=1"))
        samples = int_field(q.payload, "samples", 256)
        if not 1 <= samples <= MAX_SWEEP_SAMPLES:
            raise BadRequest(
                f"samples must be in [1, {MAX_SWEEP_SAMPLES}] (got "
                f"{samples}; <= 0 would force exhaustive C(n,k) "
                "enumeration host-side)"
            )
        seed = int_field(q.payload, "seed", 0)
        try:
            return generate_scenarios(
                session.cluster.nodes, spec, samples=samples, seed=seed
            )
        except ValueError as exc:
            raise BadRequest(f"bad fault spec {spec!r}: {exc}") from exc

    def _run_sweep_batch(self, session: Session, batch: List[Query]) -> None:
        """K queued sweep queries → ONE vmapped dispatch: build each
        query's scenario rows, stack, sweep once, slice the answers back
        out.  Rows are independent, so slices are bit-identical to the
        one-query-at-a-time answers (the sweep-vs-serial-oracle pin)."""
        from ..faults import sweep_scenarios
        from ..faults.scenarios import stack_scenarios

        sets, ranges, valid = [], [], []
        s0 = 0
        for q in batch:
            try:
                scen = self._scenarios_for(q)
            except ServeError as exc:
                # one malformed query must not poison its batch
                q.finish(error=exc)
                continue
            sets.append(scen)
            ranges.append((s0, s0 + len(scen)))
            valid.append(q)
            s0 += len(scen)
        if not valid:
            return
        if len(valid) > 1:
            _COALESCED.inc(len(valid) - 1)
        with span(
            "serve.sweep", queries=len(valid), scenarios=int(s0),
            sid=session.sid,
        ):
            _SWEEPS.inc()
            sweep = sweep_scenarios(session.pc, stack_scenarios(sets))
        batch_doc = {
            "batched_queries": len(valid),
            "batch_scenarios": int(s0),
        }
        for q, (a, b) in zip(valid, ranges):
            if q.kind == "drain":
                q.finish(result=self._drain_doc(session, sweep, a, batch_doc))
            else:
                q.finish(result=self._resilience_doc(sweep, a, b, batch_doc))

    def _drain_doc(self, session, sweep, row: int, batch_doc) -> dict:
        unplaced_rows = sweep.requeue_rows[row][
            (sweep.requeue_nodes[row] < 0) & (sweep.requeue_rows[row] >= 0)
        ]
        pods = session.pc.batch.pods
        doc = {
            "ok": True,
            "kind": "drain",
            "label": sweep.scenarios.labels[row],
            "evicted": int(sweep.evicted[row]),
            "lost": int(sweep.lost[row]),
            "requeued": int(sweep.requeued[row]),
            "unplaced": int(sweep.unplaced[row]),
            "survived": bool(sweep.unplaced[row] == 0),
            "unplaced_pods": [
                ((pods[int(r)].get("metadata") or {}).get("name", f"pod[{r}]"))
                for r in unplaced_rows[:50]
            ],
        }
        doc.update(batch_doc)
        return doc

    def _resilience_doc(self, sweep, a: int, b: int, batch_doc) -> dict:
        unplaced = sweep.unplaced[a:b]
        survived = int((unplaced == 0).sum())
        order = np.argsort(-unplaced, kind="stable")[:5]
        doc = {
            "ok": True,
            "kind": "resilience",
            "scenarios": int(b - a),
            "survived": survived,
            "survival_rate": round(float(survived) / (b - a), 4) if b > a else 1.0,
            "evicted_total": int(sweep.evicted[a:b].sum()),
            "unplaced_max": int(unplaced.max()) if b > a else 0,
            "worst": [
                [sweep.scenarios.labels[a + int(s)], int(unplaced[s])]
                for s in order
                if unplaced[s] > 0
            ],
        }
        doc.update(batch_doc)
        return doc

    # -- singleton queries -------------------------------------------------

    def _run_single(self, q: Query) -> None:
        try:
            if q.kind == "fit":
                q.finish(result=self._run_fit(q))
            elif q.kind == "capacity":
                q.finish(result=self._run_capacity(q))
            else:
                q.finish(error=BadRequest(f"unknown query kind {q.kind!r}"))
        except ServeError as exc:
            q.finish(error=exc)
        except PlanInterrupted as exc:
            q.finish(error=DeadlineExceeded(
                f"deadline expired mid-query ({exc.reason})",
                extra={"partial": None},
            ))

    def _run_fit(self, q: Query) -> dict:
        """Does this app fit? — the FULL one-shot `simulate()` semantics
        (preemption included) over the session's WHOLE snapshot: cluster
        workloads AND the session's app list place first, then the query
        app, so every endpoint of a session answers against the same
        cluster state.  The pod-name stream is seeded from the request
        fingerprint: the served answer is bit-identical to a one-shot
        run with the same seed (the acceptance pin), and compiled
        executables stay warm across requests because the cluster shapes
        repeat.  The verdict (`fits`, `unscheduled*`, `placements`)
        covers the QUERY app's pods; strands among the snapshot's own
        pods are reported separately as `session_unscheduled`."""
        from .. import constants as C
        from ..api import simulate
        from ..audit.checker import audit_enabled
        from ..core.objects import AppResource
        from ..workloads.expand import seed_name_hashes
        from ..workloads.validate import SpecError

        session = q.session
        app = app_from_payload(q.payload)
        existing = {a.name for a in session.apps}
        qname = app.name
        while qname in existing:
            # the app-name label is the query/session discriminator
            # below — keep suffixing until genuinely unique (a session
            # may itself contain '<name>-query')
            qname = f"{qname}-query"
        if qname != app.name:
            app = AppResource(name=qname, resource=app.resource)
        want_audit = (
            audit_enabled() if self.store.audit is None else self.store.audit
        )
        if session.warm:
            doc = self._run_fit_warm(q, app, want_audit)
            if doc is not None:
                return doc
            # a genuine vocabulary-class miss (preemption semantics the
            # warm base cannot honor) — pay the legacy full simulate
            _RETENSORIZE.inc()
        with span("serve.fit", sid=session.sid):
            with _EXPAND_LOCK:
                seed_name_hashes(name_seed(q.fingerprint))
                try:
                    result = simulate(
                        session.cluster, list(session.apps) + [app],
                        extended_resources=self.store.extended_resources,
                        sched_config=session.sched_config,
                        audit=want_audit,
                    )
                except SpecError as exc:
                    # a malformed inline workload is the client's 400;
                    # anything else escaping simulate() is OUR bug and
                    # surfaces as the taxonomy's 500 + flight bundle
                    raise BadRequest(f"fit query rejected: {exc}") from exc

        def is_query(pod: dict) -> bool:
            labels = (pod.get("metadata") or {}).get("labels") or {}
            return labels.get(C.LABEL_APP_NAME) == app.name

        q_unscheduled = [
            u for u in result.unscheduled_pods if is_query(u.pod)
        ]
        unscheduled = [
            {"pod": (u.pod.get("metadata") or {}).get("name", ""),
             "reason": u.reason}
            for u in q_unscheduled[:50]
        ]
        placements = {}
        for s in result.node_status:
            names = sorted(
                p["metadata"]["name"] for p in s.pods if is_query(p)
            )
            if names:
                placements[s.node["metadata"]["name"]] = names
        doc = {
            "ok": True,
            "kind": "fit",
            "app": app.name,
            "fits": not q_unscheduled,
            "unscheduled": len(q_unscheduled),
            "session_unscheduled": len(result.unscheduled_pods)
            - len(q_unscheduled),
            "preempted": len(result.preempted_pods),
            "unscheduled_pods": unscheduled,
            "placements": placements,
            "fingerprint": q.fingerprint,
        }
        doc["engine"] = {"grow": grow_doc(session)}
        if result.audit is not None:
            doc["audit"] = result.audit.counters()
        return doc

    def _expand_query_app(self, session: Session, app) -> list:
        """The query app's pods, expanded EXACTLY as `simulate()` would
        (workload expansion + DaemonSet rows + the app-name label +
        deterministic sort) — the caller owns the name-stream seed."""
        from .. import constants as C
        from ..api import _sort_app_pods
        from ..core.objects import set_label
        from ..workloads.expand import (
            get_valid_pods_exclude_daemonset,
            make_valid_pods_by_daemonset,
        )
        from ..workloads.validate import SpecError

        try:
            pods = get_valid_pods_exclude_daemonset(app.resource)
            for ds in app.resource.daemon_sets:
                pods.extend(
                    make_valid_pods_by_daemonset(ds, session.cluster.nodes)
                )
        except SpecError as exc:
            raise BadRequest(f"fit query rejected: {exc}") from exc
        for pod in pods:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        return _sort_app_pods(pods)

    def _base_name_draws(self, session: Session) -> tuple:
        """The name-suffix draws `simulate()` consumes expanding the
        cluster workloads and session apps BEFORE it reaches the query
        app, recorded once per session (the structure is deterministic).
        The warm fit path fast-forwards the freshly seeded stream past
        them so its query pods carry the exact names the legacy one-shot
        path would have generated (the bit-identity pin).  Caller holds
        the expand lock."""
        from ..workloads.expand import (
            get_valid_pods_exclude_daemonset,
            make_valid_pods_by_daemonset,
            record_name_draws,
        )

        if session.name_draws is None:
            cluster = session.cluster

            def burn():
                get_valid_pods_exclude_daemonset(cluster)
                for ds in cluster.daemon_sets:
                    make_valid_pods_by_daemonset(ds, cluster.nodes)
                for sapp in session.apps:
                    get_valid_pods_exclude_daemonset(sapp.resource)
                    for ds in sapp.resource.daemon_sets:
                        make_valid_pods_by_daemonset(ds, cluster.nodes)

            session.name_draws = record_name_draws(burn)
        return session.name_draws

    def _run_fit_warm(self, q: Query, app, want_audit: bool) -> Optional[dict]:
        """Zero-retensorize fit query: append the query app's pods into
        the session's warm grow-mode engine (`Tensorizer.add_pods` +
        `Engine.place`, whose carry EXTENDS in place on vocabulary
        growth), read the verdict, then undo the appended placements
        (`remove_placements` — one signed log delta) so the session base
        is untouched for the next query.  Returns None on a genuine
        vocabulary-class miss — query pods carrying priorities need the
        legacy `simulate()` path's DefaultPreemption semantics, which a
        frozen warm base cannot honor (docs/serving.md)."""
        from ..audit.checker import extras_from_log
        from ..engine.scan import REASON_TEXT
        from ..workloads.expand import advance_name_stream, seed_name_hashes

        session = q.session
        pc = session.pc
        eng, tz = pc.engine, pc.tz
        if not getattr(eng, "grow", False):
            return None
        with span("serve.fit_warm", sid=session.sid):
            with _EXPAND_LOCK:
                draws = self._base_name_draws(session)
                seed_name_hashes(name_seed(q.fingerprint))
                advance_name_stream(draws)
                pods = self._expand_query_app(session, app)
            if any((p.get("spec") or {}).get("priority") for p in pods):
                return None
            _WARM_FITS.inc()
            # base extras snapshot BEFORE the query rows join the log —
            # extras_from_log sizes itself to the base placement
            base_ext = extras_from_log(pc) if want_audit else None
            batch = tz.add_pods(pods)
            log_start = len(eng.placed_group)
            try:
                nodes, reasons, extras = eng.place(batch)
            except BaseException:
                # strip partially appended entries; the engine's dirty-
                # state guard already forces the next place() to rebuild
                # from the (restored) log
                del eng.placed_group[log_start:]
                del eng.placed_node[log_start:]
                del eng.placed_req[log_start:]
                for key in (
                    "node", "vg_alloc", "sdev_take", "gpu_shares", "gpu_mem",
                ):
                    del eng.ext_log[key][log_start:]
                raise
            try:
                tensors = tz.freeze()
                failed = np.flatnonzero(nodes < 0)
                unscheduled = [
                    {
                        "pod": (batch.pods[int(i)].get("metadata") or {}).get(
                            "name", f"pod[{i}]"
                        ),
                        "reason": REASON_TEXT.get(
                            int(reasons[int(i)]), str(int(reasons[int(i)]))
                        ),
                    }
                    for i in failed[:50]
                ]
                placements: Dict[str, list] = {}
                for i in np.flatnonzero(nodes >= 0):
                    name = (batch.pods[int(i)].get("metadata") or {}).get(
                        "name", f"pod[{i}]"
                    )
                    placements.setdefault(
                        tensors.node_names[int(nodes[int(i)])], []
                    ).append(name)
                for names in placements.values():
                    names.sort()
                doc = {
                    "ok": True,
                    "kind": "fit",
                    "app": app.name,
                    "fits": not len(failed),
                    "unscheduled": int(len(failed)),
                    "session_unscheduled": int((pc.nodes < 0).sum()),
                    "preempted": 0,
                    "unscheduled_pods": unscheduled,
                    "placements": placements,
                    "fingerprint": q.fingerprint,
                    "warm": True,
                }
                if want_audit:
                    report = self._audit_overlay(
                        tensors,
                        [(pc.batch, pc.nodes, base_ext),
                         (batch, nodes, extras)],
                    )
                    doc["audit"] = report.counters()
                doc["engine"] = {"grow": grow_doc(session)}
                return doc
            finally:
                # undo the query rows — the delta path restores the carry
                # bit-identically (tests/test_grow.py) — and refresh the
                # PlacedCluster's frozen view so the NEXT sweep/fit reads
                # the carry against the grown vocabulary instead of
                # rebuilding from the log
                eng.remove_placements(
                    list(range(log_start, len(eng.placed_group)))
                )
                pc.tensors = tz.freeze()

    def _audit_overlay(self, tensors, layers, node_valid=None):
        """One audit pass over stacked placements: each layer is a
        (batch, nodes, extras) triple; entries concatenate in placement
        order (base first), so prefix-replay checks see base occupancy
        under the query rows exactly as one combined placement would."""
        from ..audit.checker import (
            _entries_from_batch,
            _Entries,
            audit_placement,
        )

        parts = [
            _entries_from_batch(tensors, b, n, e) for b, n, e in layers
        ]
        offsets = np.cumsum([0] + [len(b.pods) for b, _n, _e in layers[:-1]])
        merged = _Entries(
            g=np.concatenate([p.g for p in parts]),
            n=np.concatenate([p.n for p in parts]),
            req=np.concatenate([p.req for p in parts]),
            forced=np.concatenate([p.forced for p in parts]),
            pin=np.concatenate([p.pin for p in parts]),
            lvm=np.concatenate([p.lvm for p in parts]),
            sdev=np.concatenate([p.sdev for p in parts]),
            gpu=np.concatenate([p.gpu for p in parts]),
            rows=np.concatenate(
                [p.rows + off for p, off in zip(parts, offsets)]
            ),
            names=sum((p.names or [] for p in parts), []),
        )
        return audit_placement(
            tensors, None, None, node_valid=node_valid, entries=merged
        )

    def _run_capacity(self, q: Query) -> dict:
        """Minimum newNode clones for the given workloads — the planner's
        own search with the query's cooperative deadline at candidate
        boundaries.  A deadline-expired search answers 504 with the
        structured partial (best candidate verified so far), the exit-3
        contract over HTTP."""
        from ..plan.capacity import plan_capacity
        from ..workloads.expand import seed_name_hashes
        from ..workloads.validate import SpecError

        session = q.session
        if session.new_node is None:
            raise BadRequest(
                "this snapshot has no newNode template; capacity planning "
                "needs one (spec.newNode in the Config CR)"
            )
        apps = (
            [app_from_payload(q.payload)]
            if (q.payload.get("workloads") or q.payload.get("app"))
            else session.apps
        )
        from .. import constants as C

        max_new = int_field(q.payload, "max_new_nodes", 64)
        if not 1 <= max_new <= C.MAX_NUM_NEW_NODE:
            # the search tensorizes base + max_new candidate nodes up
            # front — an uncapped client value is a host-OOM lever
            raise BadRequest(
                f"max_new_nodes must be in [1, {C.MAX_NUM_NEW_NODE}], "
                f"got {max_new}"
            )
        if session.warm and apps is session.apps:
            # session-apps payload: the base placement already covers
            # every pod, so capacity reduces to completing the STRANDED
            # rows on extend_state-grown template clones — no Applier,
            # no re-tensorize, no base re-place
            doc = self._run_capacity_warm(q, max_new)
            if doc is not None:
                return doc
            _RETENSORIZE.inc()
        with span("serve.capacity", sid=session.sid):
            with _EXPAND_LOCK:
                seed_name_hashes(name_seed(q.fingerprint))
                try:
                    plan = plan_capacity(
                        session.cluster, apps, session.new_node,
                        max_new_nodes=max_new,
                        extended_resources=self.store.extended_resources,
                        sched_config=session.sched_config,
                        control=q.control,
                        audit=self.store.audit,
                    )
                except SpecError as exc:
                    raise BadRequest(
                        f"capacity query rejected: {exc}"
                    ) from exc
        doc = {
            "ok": bool(plan.success),
            "kind": "capacity",
            "success": bool(plan.success),
            "nodes_added": int(plan.nodes_added),
            "message": plan.message,
            "partial": bool(plan.partial),
            "probes": {str(k): v for k, v in sorted(plan.probes.items())},
            "fingerprint": q.fingerprint,
        }
        doc["engine"] = {"grow": grow_doc(session)}
        if plan.audit:
            doc["audit"] = plan.audit
        if plan.partial:
            raise DeadlineExceeded(
                plan.message or "capacity search interrupted by deadline",
                extra={"partial": doc},
            )
        return doc

    def _capacity_overlay(self, session: Session, m: int) -> dict:
        """Build (once per clone-count bucket, cached on the session) the
        warm capacity overlay: a deep copy of the session tensorizer with
        `m` template clones appended via `Tensorizer.add_clone_nodes`,
        the clone DaemonSet rows, and the session's carried state
        extended onto the grown node axis (`extend_state_nodes`) — the
        pristine snapshot every probe injects a copy of.  The session's
        own tensorizer/engine are NEVER touched: later fit/drain queries
        must not see (or land on) hypothetical nodes.  Raises
        `GrowRefused` (caller falls back to the legacy full search) when
        the template would change a vocabulary class the append contract
        cannot absorb."""
        import copy

        from ..engine.rounds import RoundsEngine
        from ..engine.scan import Engine
        from ..engine.state import build_state
        from ..plan.capacity import new_fake_nodes
        from ..plan.incremental import _copy_state
        from ..workloads.expand import (
            make_valid_pods_by_daemonset,
            seed_name_hashes,
        )

        ov = session.cap_overlay.get(m)
        if ov is not None:
            return ov
        pc = session.pc
        eng = pc.engine
        n_base = pc.tz.freeze().alloc.shape[0]
        clones = new_fake_nodes(session.new_node, m)
        tz2 = copy.deepcopy(pc.tz)
        tz2.add_clone_nodes(clones)
        with _EXPAND_LOCK:
            # clone DS pod names draw from the session+bucket seed, so the
            # cached overlay is deterministic across daemon incarnations
            seed_name_hashes(
                name_seed(f"{session.fingerprint}/capacity/{m}")
            )
            all_ds = list(session.cluster.daemon_sets)
            for a in session.apps:
                all_ds += a.resource.daemon_sets
            ds_pods = []
            for ds in all_ds:
                ds_pods.extend(make_valid_pods_by_daemonset(ds, clones))
        batch_ds = tz2.add_pods(ds_pods)
        # DS pods are clone-pinned (matchFields hostname), the incremental
        # planner's own mapping (plan/incremental.py)
        clone_of = np.asarray(batch_ds.pin, np.int64) - n_base
        ov_eng = RoundsEngine(tz2)
        ov_eng.enable_grow()
        ov_eng.sched_config = session.sched_config
        ov_eng.placed_group = list(eng.placed_group)
        ov_eng.placed_node = list(eng.placed_node)
        ov_eng.placed_req = list(eng.placed_req)
        ov_eng.ext_log = {k: list(v) for k, v in eng.ext_log.items()}
        ov_eng.last_state = _copy_state(eng.last_state)
        ov_eng._grow_ref = dict(eng._grow_ref)
        ov_eng._last_vocab = eng._last_vocab
        ov_eng._state_dirty = False
        tensors2 = tz2.freeze()
        if not ov_eng.grow_nodes():
            # the clone DaemonSets interned new vocabulary beyond the node
            # axis — rebuild the overlay carry once from the copied log
            dense = build_state(
                tensors2,
                np.asarray(ov_eng.placed_group, np.int32),
                np.asarray(ov_eng.placed_node, np.int32),
                ov_eng.log_req_matrix(tensors2.alloc.shape[1]),
                ov_eng.ext_log,
            )
            ov_eng.last_state = ov_eng._enter_grow_buckets(tensors2, dense)
        ov = {
            "tz2": tz2,
            "tensors2": tensors2,
            "vocab2": Engine.state_vocab(tensors2),
            "snapshot": ov_eng.last_state,
            "batch_ds": batch_ds,
            "clone_of": clone_of,
            "n_base": n_base,
            # chunk-shape registry shared across probes: every probe pads
            # its bulk segments to the same pow2 buckets, so the first
            # probe's executables serve the rest (plan/incremental idiom)
            "shapes": {},
        }
        session.cap_overlay[m] = ov
        return ov

    def _run_capacity_warm(self, q: Query, max_new: int) -> Optional[dict]:
        """Session-reusing capacity fast path: the base placement is
        FROZEN (it is the session's own, already audited), template
        clones join via append-only node growth, and probes k = 1..max
        complete only the stranded rows over an injected copy of the
        node-extended carry — the incremental planner's probe semantics
        (plan/incremental.py) served warm.  Returns None to fall back to
        the legacy full `plan_capacity` search (counted as a
        retensorize fallback)."""
        from ..core.tensorize import GrowRefused, slice_batch
        from ..engine.rounds import RoundsEngine
        from ..engine.state import snap_pow2
        from ..plan.incremental import _copy_state

        from .. import constants as C

        session = q.session
        pc = session.pc
        eng = pc.engine
        if (
            not getattr(eng, "grow", False)
            or eng._grow_ref is None
            or eng.last_state is None
            or eng._state_dirty
        ):
            return None
        from ..audit.checker import audit_enabled

        want_audit = (
            audit_enabled() if self.store.audit is None else self.store.audit
        )
        strands = np.flatnonzero(np.asarray(pc.nodes) < 0)
        base_doc = {
            "kind": "capacity",
            "fingerprint": q.fingerprint,
            "warm": True,
        }
        try:
            # same cooperative contract as the legacy search: an
            # already-expired deadline answers the structured 504 before
            # any probe (or the zero-strand short-circuit) runs
            q.control.check()
        except PlanInterrupted as exc:
            doc = dict(
                base_doc, ok=False, success=False, nodes_added=0,
                message=f"warm capacity search interrupted ({exc.reason})",
                partial=True, probes={},
            )
            raise DeadlineExceeded(
                doc["message"], extra={"partial": doc}
            ) from exc
        if not len(strands):
            _WARM_CAPACITY.inc()
            doc = dict(
                base_doc, ok=True, success=True, nodes_added=0,
                message="all pods already placed in the session base",
                partial=False, probes={},
            )
            if want_audit:
                from ..audit.checker import extras_from_log

                report = self._audit_overlay(
                    pc.tz.freeze(),
                    [(pc.batch, pc.nodes, extras_from_log(pc))],
                )
                doc["audit"] = report.counters()
            doc["engine"] = {"grow": grow_doc(session)}
            return doc
        m = min(snap_pow2(max_new), C.MAX_NUM_NEW_NODE)
        try:
            ov = self._capacity_overlay(session, m)
        except GrowRefused as exc:
            log.info(
                "serve: warm capacity refused for session %s (%s); "
                "falling back to the full search", session.sid, exc,
            )
            return None
        _WARM_CAPACITY.inc()
        tensors2 = ov["tensors2"]
        n_base, clone_of = ov["n_base"], ov["clone_of"]
        n2 = tensors2.alloc.shape[0]
        strand_batch = slice_batch(pc.batch, strands)
        # resource lower bound: the strands must at least FIT the added
        # template capacity — probes below it cannot succeed
        demand = np.asarray(pc.batch.req, np.float64)[strands].sum(axis=0)
        cap = np.asarray(tensors2.alloc[n_base], np.float64)[: demand.shape[0]]
        with np.errstate(divide="ignore", invalid="ignore"):
            need = np.where(demand > 0, demand / np.maximum(cap, 1e-30), 0.0)
        need_max = float(need.max()) if need.size else 0.0
        lb = 1
        if np.isfinite(need_max) and need_max > 1:
            lb = min(int(np.ceil(need_max - 1e-9)), max_new)
        probes: Dict[int, int] = {}
        best = None
        with span(
            "serve.capacity_warm", sid=session.sid, strands=int(len(strands)),
        ):
            for k in range(lb, max_new + 1):
                try:
                    q.control.check()
                except PlanInterrupted as exc:
                    doc = dict(
                        base_doc, ok=False, success=False, nodes_added=0,
                        message=f"warm capacity search interrupted "
                        f"({exc.reason})", partial=True,
                        probes={str(i): v for i, v in sorted(probes.items())},
                    )
                    raise DeadlineExceeded(
                        doc["message"], extra={"partial": doc}
                    ) from exc
                mask = np.zeros(n2, bool)
                mask[: n_base + k] = True
                pe = RoundsEngine(ov["tz2"])
                pe.enable_grow()
                pe.sched_config = session.sched_config
                pe.node_valid = mask
                pe.bulk_shapes = ov["shapes"]
                pe.snap_shapes = True
                pe.last_state = _copy_state(ov["snapshot"])
                pe._last_vocab = ov["vocab2"]
                pe._state_dirty = False
                failed = 0
                ds_idx = np.flatnonzero((clone_of >= 0) & (clone_of < k))
                ds_run = None
                if len(ds_idx):
                    # clone DS overhead lands first — the infra rows a
                    # real scale-up pays before workload pods arrive
                    bds = slice_batch(ov["batch_ds"], ds_idx)
                    nds, _rds, eds = pe.place(bds)
                    ds_run = (bds, np.asarray(nds), eds)
                    failed += int((np.asarray(nds) < 0).sum())
                ns, _rs, es = pe.place(strand_batch)
                ns = np.asarray(ns)
                failed += int((ns < 0).sum())
                probes[k] = failed
                if failed == 0:
                    best = (k, ns, es, ds_run, mask)
                    break
        if best is None:
            doc = dict(
                base_doc, ok=False, success=False, nodes_added=0,
                message=f"cannot complete {len(strands)} stranded pod(s) "
                f"within {max_new} template node(s)",
                partial=False,
                probes={str(i): v for i, v in sorted(probes.items())},
            )
            doc["engine"] = {"grow": grow_doc(session)}
            return doc
        k, ns, es, ds_run, mask = best
        doc = dict(
            base_doc, ok=True, success=True, nodes_added=int(k),
            message=f"completed {len(strands)} stranded pod(s) on {k} "
            "cloned node(s) over the warm session base",
            partial=False,
            probes={str(i): v for i, v in sorted(probes.items())},
        )
        if want_audit:
            from ..audit.checker import extras_from_log

            layers = [(pc.batch, pc.nodes, extras_from_log(pc))]
            if ds_run is not None:
                layers.append(ds_run)
            layers.append((strand_batch, ns, es))
            report = self._audit_overlay(
                tensors2, layers, node_valid=mask
            )
            doc["audit"] = report.counters()
        doc["engine"] = {"grow": grow_doc(session)}
        return doc
