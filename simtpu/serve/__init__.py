"""`simtpu serve`: a hardened long-lived simulation service (ISSUE 14).

The one-shot CLI pays ingest, tensorization, and (on CPU) compilation on
every run; this package turns the simulator into a persistent daemon for
Tesserae-style interactive what-if traffic — "does this app fit",
"capacity after this drain", "resilience at k=2" — against warm cluster
snapshots, with request batching: queued sweep-shaped queries against
the same snapshot coalesce into ONE vmapped dispatch (the scenario-axis
trick of faults/sweep.py, applied to the request axis).

The daemon is first and foremost a robustness artifact; the layer map:

- `errors`   — the failure taxonomy and its HTTP mapping (the served
               twin of docs/robustness.md's exit-code table);
- `session`  — warm snapshot sessions, checkpointed through
               durable/checkpoint.py, rehydrated bit-identically after
               kill -9, evictable under pressure;
- `batching` — bounded-queue admission (429), request coalescing,
               cooperative deadlines, OOM graceful degradation;
- `server`   — the stdlib ThreadingHTTPServer front-end, SIGTERM drain,
               /healthz /readyz /metrics, spans + flight bundles.

IMPORT CONTRACT: nothing outside `simtpu serve` imports this package —
the daemon-off cost of serving is provably zero (no import, no behavior
change on any CLI path; pinned by tests/test_serve.py, the same pattern
as the explain off-path pin).
"""

from .errors import (
    AuditRejected,
    BadRequest,
    DeadlineExceeded,
    Degraded,
    HTTP_TAXONOMY,
    InternalError,
    NotFound,
    Overloaded,
    ServeError,
    error_doc,
)
from .server import ServeOptions, SimtpuServer, serve_main
from .session import Session, SessionStore

__all__ = [
    "AuditRejected",
    "BadRequest",
    "DeadlineExceeded",
    "Degraded",
    "HTTP_TAXONOMY",
    "InternalError",
    "NotFound",
    "Overloaded",
    "ServeError",
    "ServeOptions",
    "Session",
    "SessionStore",
    "SimtpuServer",
    "error_doc",
    "serve_main",
]
