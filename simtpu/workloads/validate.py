"""Object validation.

The reference runs the full upstream API validation on every generated pod and
node (`pkg/utils/utils.go:516-529,654-668` → k8s.io/kubernetes validation). We
validate the subset of invariants the simulator actually depends on; anything
violating them raises ValidationError before tensorization, so the engine never
sees malformed inputs.
"""

from __future__ import annotations

import re

from ..core.objects import meta, name_of, namespace_of, pod_containers, pod_requests

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")


class ValidationError(ValueError):
    pass


def _validate_name(name: str, what: str) -> None:
    if not name or len(name) > 253 or not _DNS1123.match(name):
        raise ValidationError(f"invalid {what} name: {name!r}")


def validate_pod(pod: dict) -> None:
    _validate_name(name_of(pod), "pod")
    _validate_name(namespace_of(pod), "namespace")
    containers = pod_containers(pod)
    if not containers:
        raise ValidationError(f"pod {name_of(pod)} has no containers")
    seen = set()
    for c in containers:
        cname = c.get("name")
        if not cname:
            raise ValidationError(f"pod {name_of(pod)} has a container without a name")
        if cname in seen:
            raise ValidationError(f"pod {name_of(pod)} has duplicate container name {cname}")
        seen.add(cname)
    for k, v in pod_requests(pod).items():
        if v < 0:
            raise ValidationError(f"pod {name_of(pod)} has negative request {k}={v}")
    restart = (pod.get("spec") or {}).get("restartPolicy", "Always")
    if restart not in ("Always", "OnFailure", "Never"):
        raise ValidationError(f"pod {name_of(pod)} has invalid restartPolicy {restart!r}")


def validate_node(node: dict) -> None:
    _validate_name(name_of(node), "node")
    labels = meta(node).get("labels") or {}
    from ..constants import LABEL_HOSTNAME

    if LABEL_HOSTNAME in labels and labels[LABEL_HOSTNAME] != name_of(node):
        # mirror of upstream rule: hostname label, when present, must equal name
        # (the reference sets it explicitly in MakeValidNodeByNode, utils.go:505)
        raise ValidationError(
            f"node {name_of(node)}: hostname label {labels[LABEL_HOSTNAME]!r} != name"
        )
