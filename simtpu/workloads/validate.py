"""Object validation.

The reference runs the full upstream API validation on every generated pod
and node (`pkg/utils/utils.go:516-529,654-668` →
k8s.io/kubernetes/pkg/apis/core/validation).  We enforce the slice of those
rules whose violation would otherwise change SCHEDULING semantics silently
— malformed labels/selectors (match nothing they should), bad affinity
operators (tensorize would treat them as no-match), out-of-range host
ports, invalid spread constraints, unparseable or negative quantities —
plus the basic object-identity rules.  Anything violating them raises
ValidationError before tensorization, so the engine never sees malformed
inputs; everything upstream validates beyond scheduling relevance
(security contexts, probes, env, image syntax, ...) is deliberately out of
scope and documented so.
"""

from __future__ import annotations

import re

from ..core.objects import (
    meta,
    name_of,
    namespace_of,
    pod_containers,
    pod_requests,
    pod_spec,
)

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
# label VALUE / qualified-name NAME part: alphanumeric ends, [-_.] inside
_LABEL_PART = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")

_SELECTOR_OPS = {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"}
_TOLERATION_OPS = {"", "Equal", "Exists"}
_TAINT_EFFECTS = {"", "NoSchedule", "PreferNoSchedule", "NoExecute"}
_UNSATISFIABLE = {"DoNotSchedule", "ScheduleAnyway"}
_PROTOCOLS = {"TCP", "UDP", "SCTP"}


class ValidationError(ValueError):
    pass


class SpecError(ValidationError):
    """A malformed workload spec, carrying enough context to fix it.

    Before this class, a bad resource quantity or malformed field
    surfaced as a raw `ValueError` traceback mid-tensorize with no hint
    of WHICH manifest was broken.  A SpecError renders as one actionable
    line — `<file>: <Kind> <ns>/<name>: <field.path>: <reason>` — and
    `simtpu apply` prints exactly that (plus the same line under --json's
    "message") instead of a stack.

    The reason is raised where the malformed value is seen (with the
    field path when known); the expansion boundary
    (`expand.spec_context`) attaches the workload kind/name and source
    file, which only the ingest layer knows."""

    def __init__(
        self,
        reason: str,
        source: str = None,
        kind: str = None,
        name: str = None,
        field: str = None,
    ):
        self.reason = reason
        self.source = source
        self.kind = kind
        self.name = name
        self.field = field
        super().__init__(reason)

    def attach(
        self, source: str = None, kind: str = None, name: str = None
    ) -> "SpecError":
        """Fill ingest context the raise site didn't know (missing attrs
        only — the innermost context wins)."""
        self.source = self.source or source
        self.kind = self.kind or kind
        self.name = self.name or name
        return self

    def __str__(self) -> str:
        parts = []
        if self.source:
            parts.append(str(self.source))
        if self.kind or self.name:
            parts.append(f"{self.kind or 'object'} {self.name or '?'}")
        if self.field:
            parts.append(self.field)
        parts.append(self.reason)
        return ": ".join(parts)


def _validate_name(name: str, what: str) -> None:
    if not name or len(name) > 253 or not _DNS1123.match(name):
        raise ValidationError(f"invalid {what} name: {name!r}")


def _validate_label_key(key, where: str) -> None:
    """Qualified-name rule (`apimachinery validation.IsQualifiedName`):
    optional DNS-subdomain prefix + '/', name part <= 63 chars."""
    if not isinstance(key, str) or not key:
        raise ValidationError(f"{where}: empty or non-string label key")
    prefix, sep, name = key.rpartition("/")
    if sep and (not prefix or len(prefix) > 253 or not _DNS1123.match(prefix)):
        # upstream rejects "/name" outright: a present slash demands a
        # non-empty valid DNS-subdomain prefix (IsQualifiedName)
        raise ValidationError(f"{where}: invalid label key prefix {prefix!r}")
    if not name or len(name) > 63 or not _LABEL_PART.match(name):
        raise ValidationError(f"{where}: invalid label key {key!r}")


def _validate_label_value(value, key, where: str) -> None:
    """`validation.IsValidLabelValue`: empty, or <= 63 chars of the label
    charset — scheduling matches string-compare these, so a malformed value
    would silently never match a well-formed selector."""
    if not isinstance(value, str):
        raise ValidationError(f"{where}: non-string label value for {key!r}")
    if value and (len(value) > 63 or not _LABEL_PART.match(value)):
        raise ValidationError(f"{where}: invalid label value {value!r} for {key!r}")


def _validate_labels(labels: dict, where: str) -> None:
    for k, v in (labels or {}).items():
        _validate_label_key(k, where)
        _validate_label_value(v, k, where)


_LABEL_SELECTOR_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist"})


def _validate_match_expressions(
    exprs, where: str, allowed_ops: frozenset = frozenset(_SELECTOR_OPS)
) -> None:
    """NodeSelectorRequirement / LabelSelectorRequirement rules
    (`apivalidation ValidateNodeSelectorRequirement`,
    `metav1validation.ValidateLabelSelector`): the KEY is a qualified name,
    operator in `allowed_ops`; Exists/DoesNotExist take no values; In/NotIn
    need label-valid values; Gt/Lt take exactly one integer."""
    for req in exprs or []:
        _validate_label_key(req.get("key"), where)
        op = req.get("operator")
        if op not in allowed_ops:
            raise ValidationError(f"{where}: invalid selector operator {op!r}")
        values = req.get("values") or []
        if op in ("Exists", "DoesNotExist") and values:
            raise ValidationError(f"{where}: operator {op} must not carry values")
        if op in ("In", "NotIn"):
            if not values:
                raise ValidationError(f"{where}: operator {op} requires values")
            for v in values:
                _validate_label_value(v, req.get("key"), where)
        if op in ("Gt", "Lt"):
            if len(values) != 1:
                raise ValidationError(f"{where}: operator {op} takes exactly one value")
            try:
                int(values[0])
            except (TypeError, ValueError):
                raise ValidationError(
                    f"{where}: operator {op} value {values[0]!r} is not an integer"
                )


def _validate_match_fields(fields, where: str) -> None:
    """NodeSelectorTerm.matchFields (`apivalidation
    ValidateNodeFieldSelectorRequirement`): only metadata.name, operator
    In, exactly one value — tensorize evaluates these (DaemonSet pinning),
    so a malformed term would silently match nothing."""
    for req in fields or []:
        if req.get("key") != "metadata.name":
            raise ValidationError(
                f"{where}: matchFields key must be metadata.name, got {req.get('key')!r}"
            )
        if req.get("operator") != "In":
            raise ValidationError(
                f"{where}: matchFields operator must be In, got {req.get('operator')!r}"
            )
        if len(req.get("values") or []) != 1:
            raise ValidationError(f"{where}: matchFields takes exactly one value")


def _validate_label_selector(sel: dict, where: str) -> None:
    """LabelSelector rules (`metav1validation.ValidateLabelSelector`)."""
    for k, v in ((sel or {}).get("matchLabels") or {}).items():
        _validate_label_key(k, where)
        _validate_label_value(v, k, where)
    _validate_match_expressions(
        (sel or {}).get("matchExpressions"), where, _LABEL_SELECTOR_OPS
    )


def _validate_affinity(pod: dict) -> None:
    who = f"pod {name_of(pod)}"
    aff = pod_spec(pod).get("affinity") or {}
    node_aff = aff.get("nodeAffinity") or {}
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        _validate_match_expressions(
            term.get("matchExpressions"), f"{who} nodeAffinity"
        )
        _validate_match_fields(term.get("matchFields"), f"{who} nodeAffinity")
    for pref in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        _validate_match_expressions(
            (pref.get("preference") or {}).get("matchExpressions"),
            f"{who} nodeAffinity preference",
        )
    for kind in ("podAffinity", "podAntiAffinity"):
        block = aff.get(kind) or {}
        for term in block.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
            if not term.get("topologyKey"):
                raise ValidationError(f"{who} {kind}: required term without topologyKey")
            _validate_label_selector(term.get("labelSelector"), f"{who} {kind}")
        for w in block.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            term = w.get("podAffinityTerm") or {}
            if not term.get("topologyKey"):
                raise ValidationError(f"{who} {kind}: preferred term without topologyKey")
            _validate_label_selector(term.get("labelSelector"), f"{who} {kind}")


def _validate_spread(pod: dict) -> None:
    who = f"pod {name_of(pod)}"
    for c in pod_spec(pod).get("topologySpreadConstraints") or []:
        try:
            skew = int(c.get("maxSkew"))
        except (TypeError, ValueError):
            skew = 0
        if skew < 1:
            raise ValidationError(f"{who}: topologySpreadConstraint maxSkew must be >= 1")
        if not c.get("topologyKey"):
            raise ValidationError(f"{who}: topologySpreadConstraint without topologyKey")
        if c.get("whenUnsatisfiable") not in _UNSATISFIABLE:
            raise ValidationError(
                f"{who}: invalid whenUnsatisfiable {c.get('whenUnsatisfiable')!r}"
            )
        _validate_label_selector(c.get("labelSelector"), f"{who} spread")


def _validate_tolerations(pod: dict) -> None:
    who = f"pod {name_of(pod)}"
    for t in pod_spec(pod).get("tolerations") or []:
        if t.get("operator", "") not in _TOLERATION_OPS:
            raise ValidationError(
                f"{who}: invalid toleration operator {t.get('operator')!r}"
            )
        if t.get("operator") == "Exists" and t.get("value"):
            raise ValidationError(f"{who}: Exists toleration must not carry a value")
        if t.get("effect", "") not in _TAINT_EFFECTS:
            raise ValidationError(f"{who}: invalid toleration effect {t.get('effect')!r}")


def _validate_ports(pod: dict) -> None:
    who = f"pod {name_of(pod)}"
    for c in pod_containers(pod):
        for p in c.get("ports") or []:
            host = p.get("hostPort")
            if host is not None:
                try:
                    ok = 0 < int(host) <= 65535
                except (TypeError, ValueError):
                    ok = False
                if not ok:
                    raise ValidationError(f"{who}: invalid hostPort {host!r}")
            proto = p.get("protocol", "TCP")
            if proto not in _PROTOCOLS:
                raise ValidationError(f"{who}: invalid port protocol {proto!r}")


def _validate_quantities(pod: dict) -> None:
    """Resource quantities, walked per container so a bad value reports
    its exact FIELD PATH (`spec.containers[1].resources.requests.cpu`)
    instead of the raw `unparseable quantity` ValueError the aggregated
    `pod_requests` sum would throw mid-tensorize."""
    from ..core.quantity import parse_quantity

    spec = pod_spec(pod)
    walks = [
        (f"spec.containers[{i}]", c)
        for i, c in enumerate(spec.get("containers") or [])
    ] + [
        (f"spec.initContainers[{i}]", c)
        for i, c in enumerate(spec.get("initContainers") or [])
    ]
    entries = [
        (f"{where}.resources.{section}.{k}", v)
        for where, c in walks
        for section in ("requests", "limits")
        for k, v in ((c.get("resources") or {}).get(section) or {}).items()
    ] + [
        (f"spec.overhead.{k}", v)
        for k, v in (spec.get("overhead") or {}).items()
    ]
    for field, v in entries:
        try:
            q = parse_quantity(v)
        except Exception:
            raise SpecError(
                f"unparseable resource quantity {v!r}", field=field
            ) from None
        if q < 0:
            raise SpecError(f"negative resource quantity {v!r}", field=field)


def validate_pod(pod: dict) -> None:
    _validate_name(name_of(pod), "pod")
    _validate_name(namespace_of(pod), "namespace")
    _validate_labels(meta(pod).get("labels"), f"pod {name_of(pod)}")
    containers = pod_containers(pod)
    if not containers:
        raise ValidationError(f"pod {name_of(pod)} has no containers")
    seen = set()
    for c in containers:
        cname = c.get("name")
        if not cname:
            raise ValidationError(f"pod {name_of(pod)} has a container without a name")
        if cname in seen:
            raise ValidationError(f"pod {name_of(pod)} has duplicate container name {cname}")
        seen.add(cname)
    _validate_quantities(pod)
    restart = (pod.get("spec") or {}).get("restartPolicy", "Always")
    if restart not in ("Always", "OnFailure", "Never"):
        raise ValidationError(f"pod {name_of(pod)} has invalid restartPolicy {restart!r}")
    for k, v in (pod_spec(pod).get("nodeSelector") or {}).items():
        _validate_label_key(k, f"pod {name_of(pod)} nodeSelector")
        _validate_label_value(v, k, f"pod {name_of(pod)} nodeSelector")
    _validate_affinity(pod)
    _validate_spread(pod)
    _validate_tolerations(pod)
    _validate_ports(pod)


def validate_node(node: dict) -> None:
    _validate_name(name_of(node), "node")
    _validate_labels(meta(node).get("labels"), f"node {name_of(node)}")
    from ..constants import LABEL_HOSTNAME
    from ..core.quantity import parse_quantity

    labels = meta(node).get("labels") or {}
    if LABEL_HOSTNAME in labels and labels[LABEL_HOSTNAME] != name_of(node):
        # mirror of upstream rule: hostname label, when present, must equal name
        # (the reference sets it explicitly in MakeValidNodeByNode, utils.go:505)
        raise ValidationError(
            f"node {name_of(node)}: hostname label {labels[LABEL_HOSTNAME]!r} != name"
        )
    for section in ("allocatable", "capacity"):
        for k, v in ((node.get("status") or {}).get(section) or {}).items():
            try:
                q = parse_quantity(v)
            except Exception:
                raise ValidationError(
                    f"node {name_of(node)}: unparseable {section} quantity {k}={v!r}"
                )
            if q < 0:
                raise ValidationError(
                    f"node {name_of(node)}: negative {section} quantity {k}={v!r}"
                )
