"""Cron-expression parsing shared by CronJob surfaces.

Two consumers, one grammar (the satellite contract of ISSUE 15): the
static expansion path (`expand.make_valid_pods_by_cron_job`) validates
`spec.schedule` through the same parser the timeline's firing generator
(`simtpu/timeline/events.py`) walks to materialize real fire times — a
schedule the static path accepts can never blow up mid-replay, and a
malformed one fails both surfaces with the same one-line `SpecError`
field path.

Grammar: the standard 5-field crontab line `minute hour day-of-month
month day-of-week`, each field `*`, a number, a range `a-b`, a step
`*/n` or `a-b/n`, or a comma list of those.  Names (`jan`, `mon`) and
the `@hourly` macros follow the Kubernetes CronJob controller's
accepted forms (robfig/cron v3 standard parser).  Day-of-month and
day-of-week compose with cron's classic OR rule: when BOTH are
restricted, a time matches if EITHER does.

Simulation time is seconds from an epoch; fire-time enumeration walks
whole minutes from a base wall-clock anchored at the Unix epoch (UTC) —
deterministic, timezone-free, and documented in docs/timeline.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime, timedelta, timezone
from typing import List, Optional, Tuple

from .validate import SpecError

#: (low, high) inclusive bounds per field, in field order
_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
_FIELD_NAMES = ("minute", "hour", "day-of-month", "month", "day-of-week")

_MONTH_NAMES = {
    name: i + 1
    for i, name in enumerate(
        ("jan", "feb", "mar", "apr", "may", "jun",
         "jul", "aug", "sep", "oct", "nov", "dec")
    )
}
_DOW_NAMES = {
    name: i
    for i, name in enumerate(("sun", "mon", "tue", "wed", "thu", "fri", "sat"))
}

#: the @-macros the Kubernetes controller accepts (robfig/cron); @reboot
#: deliberately absent — a simulated cluster has no boot instant
_MACROS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}


def _atom(token: str, idx: int) -> int:
    """One numeric atom of field `idx`, with month/dow name support and
    cron's `7 == Sunday` alias."""
    low, high = _BOUNDS[idx]
    names = _MONTH_NAMES if idx == 3 else _DOW_NAMES if idx == 4 else None
    if names is not None and token.lower() in names:
        return names[token.lower()]
    if not token.isdigit():
        raise ValueError(f"{_FIELD_NAMES[idx]}: not a number: {token!r}")
    v = int(token)
    if idx == 4 and v == 7:
        v = 0
    if not low <= v <= high:
        raise ValueError(
            f"{_FIELD_NAMES[idx]}: {v} outside [{low}, {high}]"
        )
    return v


def _parse_field(field: str, idx: int) -> Tuple[frozenset, bool]:
    """One cron field -> (allowed value set, was-unrestricted)."""
    low, high = _BOUNDS[idx]
    allowed = set()
    star = False
    if not field:
        raise ValueError(f"{_FIELD_NAMES[idx]}: empty field")
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            if not step_s.isdigit() or int(step_s) < 1:
                raise ValueError(
                    f"{_FIELD_NAMES[idx]}: bad step {step_s!r}"
                )
            step = int(step_s)
        if part == "*":
            a, b = low, high
            if step == 1:
                star = True
        elif "-" in part:
            a_s, _, b_s = part.partition("-")
            a, b = _atom(a_s, idx), _atom(b_s, idx)
            if b < a:
                raise ValueError(
                    f"{_FIELD_NAMES[idx]}: inverted range {part!r}"
                )
        else:
            a = b = _atom(part, idx)
        allowed.update(range(a, b + 1, step))
    return frozenset(allowed), star


@dataclass(frozen=True)
class CronSchedule:
    """A parsed 5-field schedule: per-field allowed-value sets plus the
    dom/dow restriction flags the OR rule needs."""

    expr: str
    minutes: frozenset
    hours: frozenset
    doms: frozenset
    months: frozenset
    dows: frozenset
    dom_star: bool
    dow_star: bool

    def matches(self, dt: datetime) -> bool:
        """Whole-minute match (seconds ignored, as cron does)."""
        if dt.minute not in self.minutes or dt.hour not in self.hours:
            return False
        if dt.month not in self.months:
            return False
        dom_ok = dt.day in self.doms
        dow_ok = ((dt.weekday() + 1) % 7) in self.dows  # cron: Sunday = 0
        if self.dom_star or self.dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok  # both restricted: classic cron OR

    def next_fire(self, after_s: float, limit_days: int = 366 * 4) -> Optional[float]:
        """The first fire time STRICTLY after `after_s` (seconds from the
        Unix epoch, UTC), or None when none exists within `limit_days`
        (an impossible dom/month combination, e.g. `0 0 31 2 *`)."""
        dt = datetime.fromtimestamp(float(after_s), tz=timezone.utc)
        dt = dt.replace(second=0, microsecond=0) + timedelta(minutes=1)
        end = dt + timedelta(days=limit_days)
        while dt < end:
            if dt.month not in self.months:
                # skip to the 1st of the next month in one hop
                if dt.month == 12:
                    dt = dt.replace(year=dt.year + 1, month=1, day=1,
                                    hour=0, minute=0)
                else:
                    dt = dt.replace(month=dt.month + 1, day=1, hour=0,
                                    minute=0)
                continue
            dom_ok = dt.day in self.doms
            dow_ok = ((dt.weekday() + 1) % 7) in self.dows
            day_ok = (
                (dom_ok and dow_ok)
                if (self.dom_star or self.dow_star)
                else (dom_ok or dow_ok)
            )
            if not day_ok:
                dt = (dt + timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if dt.hour not in self.hours:
                dt = (dt + timedelta(hours=1)).replace(minute=0)
                continue
            if dt.minute not in self.minutes:
                dt += timedelta(minutes=1)
                continue
            return dt.timestamp()
        return None


def parse_schedule(expr: str, field: str = "spec.schedule") -> CronSchedule:
    """Parse one CronJob schedule, raising a `SpecError` (one actionable
    line through `expand.spec_context`) on any malformed input."""
    if not isinstance(expr, str) or not expr.strip():
        raise SpecError("empty cron schedule", field=field)
    text = _MACROS.get(expr.strip().lower(), expr.strip())
    fields = text.split()
    if len(fields) != 5:
        raise SpecError(
            f"cron schedule needs 5 fields (minute hour dom month dow), "
            f"got {len(fields)}: {expr!r}",
            field=field,
        )
    try:
        minutes, _ = _parse_field(fields[0], 0)
        hours, _ = _parse_field(fields[1], 1)
        doms, dom_star = _parse_field(fields[2], 2)
        months, _ = _parse_field(fields[3], 3)
        dows, dow_star = _parse_field(fields[4], 4)
    except ValueError as exc:
        raise SpecError(
            f"bad cron schedule {expr!r}: {exc}", field=field
        ) from None
    return CronSchedule(
        expr=expr,
        minutes=minutes,
        hours=hours,
        doms=doms,
        months=months,
        dows=dows,
        dom_star=dom_star,
        dow_star=dow_star,
    )


def fire_times(
    schedule: CronSchedule,
    start_s: float,
    end_s: float,
    starting_deadline_s: Optional[float] = None,
    max_fires: int = 100_000,
) -> List[float]:
    """Every fire time in the half-open window `(start_s, end_s]`,
    seconds from the Unix epoch.

    `starting_deadline_s` mirrors `spec.startingDeadlineSeconds`: when
    the window opens, the controller catches up AT MOST the single most
    recent missed run whose schedule time lies within the deadline
    (`cronjob_controllerv2.go` starts only the latest missed run; older
    ones are skipped).  That one fire surfaces at its ORIGINAL schedule
    time (<= start_s; the replay loop admits it at window start).
    `max_fires` bounds a pathological `* * * * *` over a huge window
    loudly rather than silently truncating."""
    out: List[float] = []
    if starting_deadline_s is not None:
        # latest missed run in [start_s - deadline, start_s]
        t = float(start_s) - float(starting_deadline_s)
        missed = None
        while True:
            nxt = schedule.next_fire(t)
            if nxt is None or nxt > start_s:
                break
            missed = nxt
            t = nxt
        if missed is not None:
            out.append(missed)
    t = float(start_s)
    while True:
        nxt = schedule.next_fire(t)  # strictly after t: (start_s, end_s]
        if nxt is None or nxt > end_s:
            break
        out.append(nxt)
        if len(out) > max_fires:
            raise ValueError(
                f"cron schedule {schedule.expr!r} fires more than "
                f"{max_fires} times in the window; shrink the horizon"
            )
        t = nxt
    return out


def cron_job_schedule(cronjob: dict, field: str = "spec.schedule") -> CronSchedule:
    """The parsed schedule of one CronJob object (SpecError on absence —
    the API server rejects a CronJob without spec.schedule too)."""
    expr = (cronjob.get("spec") or {}).get("schedule")
    if expr is None:
        raise SpecError("CronJob has no spec.schedule", field=field)
    return parse_schedule(expr, field=field)


def cron_job_suspended(cronjob: dict) -> bool:
    """`spec.suspend: true` — the controller creates no Jobs while set."""
    return bool((cronjob.get("spec") or {}).get("suspend"))
