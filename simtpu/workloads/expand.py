"""Workload → Pod expansion (kube-controller-manager emulation).

Host-side pass that turns every workload kind into the concrete pods the
scheduler will see, mirroring `pkg/utils/utils.go:133-497`:

- Deployment → synthetic ReplicaSet → pods (`utils.go:133-136,185-195`)
- ReplicaSet / ReplicationController → pods, replicas default 1 (`:138-183`)
- CronJob → synthetic Job → pods (`:197-241`)
- Job → pods, completions default 1 (`:202-227`)
- StatefulSet → pods named `{name}-{ordinal}` with local-storage annotations
  from volumeClaimTemplates (`:243-316`)
- DaemonSet → one pod per matching node, pinned via a `metadata.name`
  MatchFields node-affinity term (`:356-395,861-906`)

Pod metadata comes from the *owner's* metadata (labels/annotations of the
workload object, not the pod template — `SetObjectMetaFromObject`,
`utils.go:318-347`), with a random hash suffix on the name.
"""

from __future__ import annotations

import contextlib
import json
import random
import re
from typing import List, Optional

from .. import constants as C
from ..core.match import node_should_run_pod
from ..core.objects import (
    ResourceTypes,
    annotations_of,
    deep_copy,
    ensure_meta,
    labels_of,
    name_of,
    namespace_of,
    set_annotation,
)
from ..core.quantity import parse_quantity
from .validate import SpecError, ValidationError, validate_node, validate_pod

_rng = random.Random()


@contextlib.contextmanager
def spec_context(kind: str, obj: dict):
    """Attach ingest context to spec failures raised while expanding one
    workload (docs/robustness.md, structured ingest diagnostics).

    A `SpecError` raised deeper (it knows the field path, not the
    workload) gains the kind/name/source-file; a plain ValidationError or
    ValueError (bad quantity in a storage template, malformed JSON
    annotation...) is wrapped whole.  The result renders as ONE
    actionable line in `simtpu apply` instead of a mid-tensorize
    traceback."""
    name = f"{namespace_of(obj)}/{name_of(obj)}"
    source = obj.get(SOURCE_KEY)
    try:
        yield
    except SpecError as exc:
        raise exc.attach(source=source, kind=kind, name=name)
    except (ValidationError, ValueError) as exc:
        raise SpecError(
            str(exc), source=source, kind=kind, name=name
        ) from exc


#: top-level key the YAML loader stamps each decoded object with so spec
#: diagnostics can name the manifest file; never part of the k8s object
#: model, and nothing downstream iterates top-level keys
SOURCE_KEY = "__simtpu_source__"


def seed_name_hashes(seed: Optional[int]) -> None:
    """Make generated pod-name suffixes reproducible (tests, planner sweeps)."""
    global _rng
    _rng = random.Random(seed)


class _RecordingRandom(random.Random):
    """RNG stand-in that notes the bit width of every draw (the widths
    vary: workload-level suffixes are longer than pod-level ones)."""

    def __init__(self):
        super().__init__(0)
        self.widths = []

    def getrandbits(self, k: int) -> int:
        self.widths.append(k)
        return super().getrandbits(k)


def record_name_draws(fn) -> tuple:
    """Run `fn` (an expansion) against a throwaway recording RNG and
    return the bit widths of every name-suffix draw it made.  The draw
    STRUCTURE is deterministic — it depends on the workload tree, never
    on the drawn values — so the recording replays exactly via
    `advance_name_stream` under any seed.  The caller's stream is
    untouched (restored on exit)."""
    global _rng
    prev = _rng
    rec = _RecordingRandom()
    _rng = rec
    try:
        fn()
    finally:
        _rng = prev
    return tuple(rec.widths)


def advance_name_stream(widths) -> None:
    """Fast-forward the current name stream past `widths` (a
    `record_name_draws` recording) without expanding anything — the
    warm serve path's replacement for re-expanding the session base
    before each query app (serve/batching.py)."""
    for k in widths:
        _rng.getrandbits(k)


def _hash_suffix(digits: int) -> str:
    """Random hex suffix, shaped like the reference's sha256-of-random-token
    prefix (`utils.GetSHA256HashCode`, utils.go:531-536). Drawn directly from
    the RNG: hashing a 10-char random token per pod was ~90% of million-pod
    expansion time, and the hash of a random token is just a random hex
    string — same alphabet, same length, same independence."""
    return "%0*x" % (digits, _rng.getrandbits(digits * 4))


def _object_meta_from_owner(owner: dict, owner_kind: str, gen_pod: bool) -> dict:
    """Pod/workload metadata derived from its owner (`utils.go:318-347`)."""
    digits = C.POD_HASH_DIGITS if gen_pod else C.WORKLOAD_HASH_DIGITS
    m = {
        "name": f"{name_of(owner)}{C.SEPARATE_SYMBOL}{_hash_suffix(digits)}",
        "namespace": namespace_of(owner),
        "generateName": name_of(owner),
        "ownerReferences": [
            {
                "kind": owner_kind,
                "name": name_of(owner),
                "controller": True,
            }
        ],
    }
    if labels_of(owner):
        m["labels"] = dict(labels_of(owner))
    if annotations_of(owner):
        m["annotations"] = dict(annotations_of(owner))
    return m


def _pod_from_template(owner: dict, owner_kind: str) -> dict:
    spec = deep_copy(((owner.get("spec") or {}).get("template") or {}).get("spec") or {})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": _object_meta_from_owner(owner, owner_kind, gen_pod=True),
        "spec": spec,
    }


def make_valid_pod(pod: dict) -> dict:
    """Normalize a pod the way the reference does (`utils.go:407-489`).

    Defaults namespace/DNSPolicy/RestartPolicy/SchedulerName, strips probes,
    env, volume mounts and image-pull secrets (irrelevant to scheduling),
    converts PVC volumes to hostPath, then validates.
    """
    pod = deep_copy(pod)
    pod.pop(SOURCE_KEY, None)  # ingest-only provenance, not pod model
    m = ensure_meta(pod)
    m.setdefault("labels", {})
    m.setdefault("annotations", {})
    if not m.get("namespace"):
        m["namespace"] = "default"
    spec = pod.setdefault("spec", {})
    spec.setdefault("dnsPolicy", "ClusterFirst")
    spec.setdefault("restartPolicy", "Always")
    if not spec.get("schedulerName"):
        spec["schedulerName"] = C.DEFAULT_SCHEDULER_NAME
    spec.pop("imagePullSecrets", None)
    for clist in ("initContainers", "containers"):
        for c in spec.get(clist) or []:
            c.setdefault("terminationMessagePolicy", "FallbackToLogsOnError")
            c.setdefault("imagePullPolicy", "IfNotPresent")
            c.pop("volumeMounts", None)
            c.pop("env", None)
            c.pop("livenessProbe", None)
            c.pop("readinessProbe", None)
            c.pop("startupProbe", None)
    for vol in spec.get("volumes") or []:
        if "persistentVolumeClaim" in vol:
            vol.pop("persistentVolumeClaim")
            vol["hostPath"] = {"path": "/tmp"}
    validate_pod(pod)
    return pod


def add_workload_info(pod: dict, kind: str, name: str, namespace: str) -> dict:
    """Annotate the pod with its source workload (`utils.go:492-497`)."""
    set_annotation(pod, C.ANNO_WORKLOAD_KIND, kind)
    set_annotation(pod, C.ANNO_WORKLOAD_NAME, name)
    set_annotation(pod, C.ANNO_WORKLOAD_NAMESPACE, namespace)
    return pod


def _replicas(obj: dict, field: str = "replicas", default: int = 1) -> int:
    val = (obj.get("spec") or {}).get(field)
    return default if val is None else int(val)


def _clone_pod(proto: dict, name: str) -> dict:
    """Cheap per-replica instance of a normalized prototype pod.

    Replicas of one workload share their (immutable after normalization)
    nested spec structure — containers, tolerations, selectors — and get
    fresh metadata plus a fresh top-level spec dict (placement recording sets
    `spec.nodeName` per pod). This replaces a per-replica deep copy, which
    dominated expansion time at 100k+ pods.
    """
    m = proto["metadata"]
    meta = dict(m)
    meta["name"] = name
    meta["labels"] = dict(m.get("labels") or {})
    meta["annotations"] = dict(m.get("annotations") or {})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta,
        "spec": dict(proto["spec"]),
    }


def _prototype(owner: dict, owner_kind: str) -> dict:
    """Normalize + annotate one pod for the workload; replicas clone it."""
    pod = make_valid_pod(_pod_from_template(owner, owner_kind))
    return add_workload_info(pod, owner_kind, name_of(owner), namespace_of(owner))


def _expand_run(owner: dict, kind: str, count: int) -> List[dict]:
    """`count` clones of the owner's normalized prototype, hash-named."""
    proto = _prototype(owner, kind)
    base = name_of(owner)
    return [
        _clone_pod(proto, f"{base}{C.SEPARATE_SYMBOL}{_hash_suffix(C.POD_HASH_DIGITS)}")
        for _ in range(count)
    ]


def make_valid_pods_by_replica_set(rs: dict) -> List[dict]:
    return _expand_run(rs, C.KIND_RS, _replicas(rs))


def generate_replica_set_from_deployment(deploy: dict) -> dict:
    """Deployment → its ReplicaSet (`utils.go:185-195`)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSet",
        "metadata": _object_meta_from_owner(deploy, C.KIND_DEPLOYMENT, gen_pod=False),
        "spec": {
            "selector": (deploy.get("spec") or {}).get("selector"),
            "replicas": _replicas(deploy),
            "template": (deploy.get("spec") or {}).get("template"),
        },
    }


def make_valid_pods_by_deployment(deploy: dict) -> List[dict]:
    return make_valid_pods_by_replica_set(generate_replica_set_from_deployment(deploy))


def make_valid_pods_by_replication_controller(rc: dict) -> List[dict]:
    return _expand_run(rc, C.KIND_RC, _replicas(rc))


def make_valid_pods_by_job(job: dict) -> List[dict]:
    return _expand_run(job, C.KIND_JOB, _replicas(job, "completions"))


def generate_job_from_cron_job(cronjob: dict) -> dict:
    """CronJob → one Job instance (`utils.go:229-241`).

    Job metadata (incl. annotations) comes from the CronJob's own metadata via
    SetObjectMetaFromObject — the reference builds an `instantiate=manual`
    annotation map at `utils.go:230-234` but never attaches it, so we mirror
    the observable behavior and attach nothing extra.
    """
    job_template = (cronjob.get("spec") or {}).get("jobTemplate") or {}
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": _object_meta_from_owner(cronjob, C.KIND_CRON_JOB, gen_pod=False),
        "spec": deep_copy(job_template.get("spec") or {}),
    }


def make_valid_pods_by_cron_job(cronjob: dict) -> List[dict]:
    """Static CronJob expansion: one Job instance, UNLESS the CronJob is
    suspended (`spec.suspend: true` — the controller creates no Jobs while
    set, so the static snapshot must not schedule one either; the old
    behavior emitted a Job regardless, ISSUE 15 satellite).  The schedule
    is validated through the shared cron parser (`workloads/cron.py`) —
    the same grammar the timeline's firing generator walks, so a spec the
    static path accepts can never blow up mid-replay."""
    from .cron import cron_job_schedule, cron_job_suspended

    if (cronjob.get("spec") or {}).get("schedule") is not None:
        cron_job_schedule(cronjob)  # SpecError (one line) on malformed
    if cron_job_suspended(cronjob):
        return []
    return make_valid_pods_by_job(generate_job_from_cron_job(cronjob))


def make_valid_pods_by_stateful_set(sts: dict) -> List[dict]:
    """STS pods are named `{sts}-{ordinal}` and carry the volume-claim storage
    annotation (`utils.go:243-316`)."""
    proto = _prototype(sts, C.KIND_STS)
    pods = [
        _clone_pod(proto, f"{name_of(sts)}-{ordinal}")
        for ordinal in range(_replicas(sts))
    ]
    set_storage_annotation_on_pods(
        pods, (sts.get("spec") or {}).get("volumeClaimTemplates") or [], name_of(sts)
    )
    return pods


def set_storage_annotation_on_pods(pods: List[dict], vcts: List[dict], sts_name: str) -> None:
    """Translate volumeClaimTemplates into the `simon/pod-local-storage`
    annotation (`utils.go:273-316`). Unrecognized storage classes are skipped."""
    volumes = []
    for pvc in vcts:
        sc = (pvc.get("spec") or {}).get("storageClassName")
        if sc is None:
            continue
        size = parse_quantity(
            (((pvc.get("spec") or {}).get("resources") or {}).get("requests") or {}).get("storage")
        )
        if sc in C.SC_LVM:
            kind = "LVM"
        elif sc in C.SC_DEVICE_SSD:
            kind = "SSD"
        elif sc in C.SC_DEVICE_HDD:
            kind = "HDD"
        else:
            continue
        volumes.append({"size": str(int(size)), "kind": kind, "scName": sc})
    payload = json.dumps({"volumes": volumes})
    for pod in pods:
        set_annotation(pod, C.ANNO_POD_LOCAL_STORAGE, payload)


def set_daemonset_node_affinity(pod: dict, node_name: str) -> None:
    """Pin a daemon pod to its node via a `metadata.name` MatchFields term
    (`utils.go:861-906`), replacing any existing required terms' fields."""
    req = {"key": "metadata.name", "operator": "In", "values": [node_name]}
    spec = pod.setdefault("spec", {})
    affinity = spec.setdefault("affinity", {})
    node_aff = affinity.setdefault("nodeAffinity", {})
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not required or not required.get("nodeSelectorTerms"):
        node_aff["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [{"matchFields": [req]}]
        }
        return
    for term in required["nodeSelectorTerms"]:
        term["matchFields"] = [req]


def _pin_daemon_clone(proto: dict, node_name: str) -> dict:
    """Clone the DaemonSet prototype and pin it to one node: the affinity
    subtree is the only per-node spec difference, so it alone is deep-copied."""
    pod = _clone_pod(
        proto,
        f"{proto['metadata']['generateName']}{C.SEPARATE_SYMBOL}"
        f"{_hash_suffix(C.POD_HASH_DIGITS)}",
    )
    if "affinity" in pod["spec"]:
        pod["spec"]["affinity"] = deep_copy(pod["spec"]["affinity"])
    set_daemonset_node_affinity(pod, node_name)
    return pod


def new_daemon_pod(ds: dict, node_name: str) -> dict:
    """One DaemonSet pod pinned to node_name (`utils.go:372-385`)."""
    return _pin_daemon_clone(_prototype(ds, C.KIND_DS), node_name)


def make_valid_pods_by_daemonset(ds: dict, nodes: List[dict]) -> List[dict]:
    """One pod per node that should run it (`utils.go:356-370`)."""
    with spec_context(C.KIND_DS, ds):
        proto = _prototype(ds, C.KIND_DS)
    pods = []
    for node in nodes:
        pod = _pin_daemon_clone(proto, name_of(node))
        if node_should_run_pod(node, pod):
            pods.append(pod)
    return pods


def make_valid_pod_by_pod(pod: dict) -> dict:
    return make_valid_pod(pod)


def make_valid_node_by_node(node: dict, node_name: str) -> dict:
    """Clone a template node under a new hostname (`utils.go:499-513`)."""
    node = deep_copy(node)
    ensure_meta(node)["name"] = node_name
    ensure_meta(node).setdefault("labels", {})[C.LABEL_HOSTNAME] = node_name
    ensure_meta(node).setdefault("annotations", {})
    validate_node(node)
    return node


def check_duplicate_workloads(resources: ResourceTypes) -> None:
    """Reject duplicate workload names within one ingest at VALIDATE time.

    Two Deployments both named `foo` (one ResourceTypes — e.g. two files
    under the same app directory) would silently shadow each other during
    tensorize: both expand, their pods land in one group vocabulary, and
    nothing downstream can tell which manifest produced which pod.  A
    `SpecError` naming BOTH source files is the actionable surface
    (docs/robustness.md, structured ingest diagnostics)."""
    seen: dict = {}
    buckets = [
        ("Pod", resources.pods),
        (C.KIND_DEPLOYMENT, resources.deployments),
        (C.KIND_RS, resources.replica_sets),
        (C.KIND_RC, resources.replication_controllers),
        (C.KIND_STS, resources.stateful_sets),
        (C.KIND_DS, resources.daemon_sets),
        (C.KIND_JOB, resources.jobs),
        (C.KIND_CRON_JOB, resources.cron_jobs),
    ]
    for kind, items in buckets:
        for item in items:
            full = f"{namespace_of(item)}/{name_of(item)}"
            src = item.get(SOURCE_KEY) or "<in-memory>"
            prev = seen.get((kind, full))
            if prev is not None:
                raise SpecError(
                    f"duplicate {kind} name within one ingest (also "
                    f"defined in {prev}); later definitions would "
                    "silently shadow during tensorize — rename one",
                    source=src,
                    kind=kind,
                    name=full,
                )
            seen[(kind, full)] = src


def get_valid_pods_exclude_daemonset(resources: ResourceTypes) -> List[dict]:
    """Expand every non-DaemonSet workload (`pkg/simulator/utils.go:111-135`).

    Order matters and matches the reference: bare pods, deployments, replica
    sets, replication controllers, stateful sets, jobs, cron jobs.
    """
    check_duplicate_workloads(resources)
    pods: List[dict] = []
    pod_src: dict = {}  # "ns/name" -> source file of the producing workload
    expanders = [
        (resources.pods, "Pod", lambda it: [make_valid_pod_by_pod(it)]),
        (resources.deployments, C.KIND_DEPLOYMENT, make_valid_pods_by_deployment),
        (resources.replica_sets, C.KIND_RS, make_valid_pods_by_replica_set),
        (
            resources.replication_controllers,
            C.KIND_RC,
            make_valid_pods_by_replication_controller,
        ),
        (resources.stateful_sets, C.KIND_STS, make_valid_pods_by_stateful_set),
        (resources.jobs, C.KIND_JOB, make_valid_pods_by_job),
        (resources.cron_jobs, C.KIND_CRON_JOB, make_valid_pods_by_cron_job),
    ]
    for items, kind, expander in expanders:
        for item in items:
            with spec_context(kind, item):
                new = expander(item)
            src = item.get(SOURCE_KEY) or "<in-memory>"
            for pod in new:
                full = f"{namespace_of(pod)}/{name_of(pod)}"
                prev = pod_src.get(full)
                if prev is not None:
                    # only names that really came from the random-suffix
                    # scheme (`<generateName>-<POD_HASH_DIGITS hex>`) may
                    # re-draw: STS ordinal pods also CARRY generateName
                    # but are named `{name}-{ordinal}` deterministically —
                    # renaming one would break the ordinal identity its
                    # volume claims were computed against
                    gen = (pod.get("metadata") or {}).get("generateName")
                    if gen and not re.fullmatch(
                        re.escape(f"{gen}{C.SEPARATE_SYMBOL}")
                        + f"[0-9a-f]{{{C.POD_HASH_DIGITS}}}",
                        name_of(pod),
                    ):
                        gen = None
                    if not gen:
                        # explicitly-named pods (bare Pods, STS ordinals)
                        # colliding is a spec bug — shadowing during
                        # tensorize would silently drop one
                        raise SpecError(
                            "pod name collides within one ingest (a pod "
                            f"of the same name comes from {prev}); "
                            "rename one of the workloads",
                            source=src,
                            kind=kind,
                            name=f"{namespace_of(item)}/{name_of(item)}",
                            field=f"pod {full}",
                        )
                    # random-suffix collision on a GENERATED name — a
                    # birthday certainty at million-pod scale (5 hex
                    # digits per owner), not a user error: re-draw from
                    # the same deterministic stream until unique, so
                    # nothing downstream (preemption keys, audit logs,
                    # checkpoints) ever sees two pods shadowing one name
                    while full in pod_src:
                        pod["metadata"]["name"] = (
                            f"{gen}{C.SEPARATE_SYMBOL}"
                            f"{_hash_suffix(C.POD_HASH_DIGITS)}"
                        )
                        full = f"{namespace_of(pod)}/{name_of(pod)}"
                pod_src[full] = src
            pods.extend(new)
    return pods
