"""Offline Helm chart rendering.

Mirrors `pkg/chart/chart.go:18-118` (`ProcessChart` → load, override name,
coalesce values, render templates offline, drop NOTES.txt, drop hooks, sort
manifests in Helm's InstallOrder, drop empties). The reference links the Helm
v3 engine; no helm binary exists in this image, so this module implements the
Go-template subset Helm charts actually use for manifests: field access
(`.Values.a.b`, `$.` root), `if / else if / else / end`, comments, pipelines,
and the common sprig-lite functions (`int`, `quote`, `default`, `indent`,
`nindent`, `toYaml`, `upper`, `lower`, `trim`, `printf`).

Unsupported constructs raise `ChartRenderError` naming the template file, so
a chart outside the subset fails loudly rather than mis-rendering.
"""

from __future__ import annotations

import io
import os
import re
import tarfile
from typing import Any, Dict, List, Optional, Tuple

import yaml

NOTES_SUFFIX = "NOTES.txt"

# helm.sh/helm/v3/pkg/releaseutil/kind_sorter.go InstallOrder
INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob", "Ingress",
    "APIService",
]
_KIND_RANK = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartRenderError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Template engine (Go text/template subset)
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


class _Node:
    pass


class _Text(_Node):
    def __init__(self, s):
        self.s = s


class _Expr(_Node):
    def __init__(self, src):
        self.src = src


class _If(_Node):
    def __init__(self):
        # [(cond_src | None for else, [children])]
        self.branches: List[Tuple[Optional[str], List[_Node]]] = []


def _parse(template: str, where: str) -> List[_Node]:
    """Split into text/action nodes, honoring {{- and -}} whitespace trim."""
    pos = 0
    tokens: List[Tuple[str, str]] = []  # ("text", s) | ("action", src)
    for m in _ACTION_RE.finditer(template):
        text = template[pos : m.start()]
        if m.group(1) == "-":
            text = text.rstrip()
        if tokens and tokens[-1][0] == "trim-next":
            tokens.pop()
            text = text.lstrip()
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        if m.group(3) == "-":
            tokens.append(("trim-next", ""))
        pos = m.end()
    tail = template[pos:]
    if tokens and tokens[-1][0] == "trim-next":
        tokens.pop()
        tail = tail.lstrip()
    tokens.append(("text", tail))

    root: List[_Node] = []
    stack: List[Tuple[List[_Node], Optional[_If]]] = [(root, None)]
    for kind, payload in tokens:
        children = stack[-1][0]
        if kind == "text":
            if payload:
                children.append(_Text(payload))
            continue
        src = payload
        if src.startswith("/*"):
            continue
        if src.startswith("if "):
            node = _If()
            node.branches.append((src[3:].strip(), []))
            children.append(node)
            stack.append((node.branches[-1][1], node))
        elif src.startswith("else if "):
            _, node = stack.pop()
            if node is None:
                raise ChartRenderError(f"{where}: 'else if' outside if")
            node.branches.append((src[8:].strip(), []))
            stack.append((node.branches[-1][1], node))
        elif src == "else":
            _, node = stack.pop()
            if node is None:
                raise ChartRenderError(f"{where}: 'else' outside if")
            node.branches.append((None, []))
            stack.append((node.branches[-1][1], node))
        elif src == "end":
            _, node = stack.pop()
            if node is None:
                raise ChartRenderError(f"{where}: unmatched 'end'")
        elif re.match(r"^(range|with|define|block|template|include)\b", src):
            raise ChartRenderError(
                f"{where}: unsupported template construct '{src.split()[0]}'"
            )
        else:
            children.append(_Expr(src))
    if len(stack) != 1:
        raise ChartRenderError(f"{where}: unclosed 'if'")
    return root


def _tokenize_expr(src: str, where: str) -> List[str]:
    out, i, n = [], 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
        elif c in "\"'`":
            j = i + 1
            while j < n and src[j] != c:
                j += 2 if src[j] == "\\" else 1
            out.append(src[i : j + 1])
            i = j + 1
        elif c == "|":
            out.append("|")
            i += 1
        elif c == "(" or c == ")":
            out.append(c)
            i += 1
        else:
            j = i
            while j < n and not src[j].isspace() and src[j] not in "|()":
                j += 1
            out.append(src[i:j])
            i = j
    return out


def _lookup(path: str, ctx: dict, where: str):
    cur: Any = ctx
    for part in path.split(".")[1:]:  # leading "" from the dot
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _to_yaml(v) -> str:
    return yaml.safe_dump(v, default_flow_style=False).rstrip("\n")


_FUNCS = {
    "int": lambda a: int(float(a)) if a not in (None, "") else 0,
    "quote": lambda a: '"%s"' % str(a).replace('"', '\\"'),
    "squote": lambda a: "'%s'" % a,
    "upper": lambda a: str(a).upper(),
    "lower": lambda a: str(a).lower(),
    "trim": lambda a: str(a).strip(),
    "toYaml": _to_yaml,
    "default": lambda d, v=None: v if _truthy(v) else d,
    "indent": lambda n, s: "\n".join(" " * int(n) + l for l in str(s).splitlines()),
    "nindent": lambda n, s: "\n" + "\n".join(" " * int(n) + l for l in str(s).splitlines()),
    "printf": lambda fmt, *a: _go_printf(fmt, *a),
    "not": lambda a: not _truthy(a),
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
}


def _go_printf(fmt, *args):
    return re.sub(r"%[sdvq]", "{}", str(fmt)).format(*args)


def _truthy(v) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict)) and len(v) == 0:
        return False
    return True


def _eval_atom(tok: str, ctx: dict, where: str):
    if tok.startswith(".") or tok.startswith("$."):
        return _lookup(tok[1:] if tok.startswith("$") else tok, ctx, where)
    if tok == "$" or tok == ".":
        return ctx
    if tok[:1] in "\"'`":
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    if tok == "nil":
        return None
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok  # bare word (function name handled by caller)


def _eval_stage(tokens: List[str], piped, ctx: dict, where: str):
    """One pipeline stage: `fn a b` or a single atom; `piped` is appended as
    the last argument (Go pipeline semantics)."""
    if not tokens:
        raise ChartRenderError(f"{where}: empty pipeline stage")
    head = tokens[0]
    if head in _FUNCS:
        args = [_eval_atom(t, ctx, where) for t in tokens[1:]]
        if piped is not _SENTINEL:
            args.append(piped)
        try:
            return _FUNCS[head](*args)
        except Exception as exc:
            raise ChartRenderError(f"{where}: {head}(...) failed: {exc}") from exc
    if len(tokens) != 1 or piped is not _SENTINEL:
        raise ChartRenderError(f"{where}: unknown function '{head}'")
    return _eval_atom(head, ctx, where)


_SENTINEL = object()


def _eval_expr(src: str, ctx: dict, where: str):
    tokens = _tokenize_expr(src, where)
    if "(" in tokens or ")" in tokens:
        raise ChartRenderError(f"{where}: parenthesized expressions unsupported")
    stages: List[List[str]] = [[]]
    for tok in tokens:
        if tok == "|":
            stages.append([])
        else:
            stages[-1].append(tok)
    val = _SENTINEL
    for stage in stages:
        val = _eval_stage(stage, val, ctx, where)
    return val


def _format(v) -> str:
    if v is None:
        return "<no value>"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _render_nodes(nodes: List[_Node], ctx: dict, out: List[str], where: str):
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Expr):
            out.append(_format(_eval_expr(node.src, ctx, where)))
        elif isinstance(node, _If):
            for cond, children in node.branches:
                if cond is None or _truthy(_eval_expr(cond, ctx, where)):
                    _render_nodes(children, ctx, out, where)
                    break


def render_template(template: str, ctx: dict, where: str = "<template>") -> str:
    out: List[str] = []
    _render_nodes(_parse(template, where), ctx, out, where)
    return "".join(out)


# ---------------------------------------------------------------------------
# Chart loading (directory or .tgz, like helm loader.Load)
# ---------------------------------------------------------------------------


def _load_chart_files(chart_path: str) -> Dict[str, str]:
    """Relative path → content for Chart.yaml, values.yaml, templates/*."""
    files: Dict[str, str] = {}
    if os.path.isdir(chart_path):
        for root, _dirs, names in os.walk(chart_path):
            for name in names:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, chart_path)
                with open(full, "r", encoding="utf-8") as fh:
                    files[rel] = fh.read()
    elif tarfile.is_tarfile(chart_path):
        with tarfile.open(chart_path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                rel = member.name.split("/", 1)[-1]  # strip top-level dir
                data = tf.extractfile(member).read().decode("utf-8")
                files[rel] = data
    else:
        raise ChartRenderError(f"{chart_path}: not a chart directory or archive")
    if "Chart.yaml" not in files:
        raise ChartRenderError(f"{chart_path}: no Chart.yaml")
    return files


def process_chart(name: str, chart_path: str) -> List[str]:
    """Render a chart into YAML manifest strings in InstallOrder.

    `name` overrides the chart name (`chart.go:24`
    `chartRequested.Metadata.Name = name`), which also becomes
    `.Release.Name` (`chart.go:59` uses `chrt.Name()`).
    """
    files = _load_chart_files(chart_path)
    metadata = yaml.safe_load(files["Chart.yaml"]) or {}
    chart_type = metadata.get("type") or ""
    if chart_type not in ("", "application"):
        # checkIfInstallable (chart.go:45-51)
        raise ChartRenderError(f"{chart_type} charts are not installable")
    metadata["name"] = name
    values = yaml.safe_load(files.get("values.yaml", "")) or {}
    ctx = {
        "Values": values,
        "Chart": {**metadata, "Name": name},
        "Release": {
            "Name": name,
            "Namespace": "default",
            "Revision": 1,
            "Service": "Helm",
        },
        "Capabilities": {"KubeVersion": {"Version": "v1.20.5", "Major": "1", "Minor": "20"}},
    }

    docs: List[Tuple[int, int, str]] = []  # (kind_rank, seq, content)
    seq = 0
    for rel in sorted(files):
        parts = rel.split(os.sep)
        if parts[0] != "templates" or len(parts) < 2:
            continue
        base = parts[-1]
        if base.startswith("_") or rel.endswith(NOTES_SUFFIX):
            continue  # partials and NOTES.txt (chart.go:92-103)
        rendered = render_template(files[rel], ctx, where=rel)
        for doc in re.split(r"(?m)^---\s*$", rendered):
            if not doc.strip():
                continue  # empty manifests removed (chart.go:105-107)
            try:
                obj = yaml.safe_load(doc)
            except yaml.YAMLError as exc:
                raise ChartRenderError(f"{rel}: rendered invalid YAML: {exc}") from exc
            if not isinstance(obj, dict):
                continue
            annotations = (obj.get("metadata") or {}).get("annotations") or {}
            if "helm.sh/hook" in annotations:
                # the reference discards hooks (chart.go:110 drops the first
                # return of SortManifests)
                continue
            rank = _KIND_RANK.get(obj.get("kind"), len(INSTALL_ORDER))
            docs.append((rank, seq, doc.strip("\n")))
            seq += 1
    docs.sort(key=lambda t: (t[0], t[1]))
    return [content for _rank, _seq, content in docs]
