"""Offline Helm chart rendering.

Mirrors `pkg/chart/chart.go:18-118` (`ProcessChart` → load, override name,
coalesce values, render templates offline, drop NOTES.txt, drop hooks, sort
manifests in Helm's InstallOrder, drop empties). The reference links the Helm
v3 engine; no helm binary exists in this image, so this module implements the
Go-template subset Helm charts actually use for manifests: field access
(`.Values.a.b`, `$.` root), `if / else if / else / end`, `range` (with
`$i, $v :=` variable forms and `else`), `with`, variables (`$x := expr`,
`$x = expr`), `define` / `include` / `template` / `block` partials
(`_helpers.tpl` registers into a chart-wide namespace), parenthesized
pipelines, comments, and a sprig-lite function set (`quote`, `default`,
`indent`/`nindent`, `toYaml`/`toJson`, `printf`, string/list/dict/arithmetic
helpers, `required`, `tpl`, `lookup`).

Unsupported constructs raise `ChartRenderError` naming the template file, so
a chart outside the subset fails loudly rather than mis-rendering.
"""

from __future__ import annotations

import os
import re
import tarfile
from typing import Any, Dict, List, Optional, Tuple

import yaml

NOTES_SUFFIX = "NOTES.txt"

# helm.sh/helm/v3/pkg/releaseutil/kind_sorter.go InstallOrder
INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob", "Ingress",
    "APIService",
]
_KIND_RANK = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartRenderError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Template engine (Go text/template subset)
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


class _Node:
    pass


class _Text(_Node):
    def __init__(self, s):
        self.s = s


class _Expr(_Node):
    def __init__(self, src):
        self.src = src


class _If(_Node):
    def __init__(self):
        # [(cond_src | None for else, [children])]
        self.branches: List[Tuple[Optional[str], List[_Node]]] = []


class _Range(_Node):
    def __init__(self, idx_var, val_var, src):
        self.idx_var = idx_var  # $i name or None
        self.val_var = val_var  # $v name or None
        self.src = src
        self.body: List[_Node] = []
        self.else_body: List[_Node] = []


class _With(_Node):
    def __init__(self, src):
        self.src = src
        self.body: List[_Node] = []
        self.else_body: List[_Node] = []


class _Var(_Node):
    def __init__(self, name, src, declare):
        self.name = name  # without the $
        self.src = src
        self.declare = declare  # := vs =


class _Define(_Node):
    def __init__(self, name, render_in_place=False, arg_src=None):
        self.name = name
        self.body: List[_Node] = []
        self.render_in_place = render_in_place  # block vs define
        self.arg_src = arg_src  # block's pipeline argument (None for define)


class _TemplateCall(_Node):
    def __init__(self, name, arg_src):
        self.name = name
        self.arg_src = arg_src  # None = no-arg form (dot is nil in Go)


_VAR_STMT_RE = re.compile(r"^\$([\w]+)\s*(:?=)\s*(.*)$", re.DOTALL)
_RANGE_VARS_RE = re.compile(
    r"^range\s+\$([\w]+)\s*(?:,\s*\$([\w]+)\s*)?:=\s*(.*)$", re.DOTALL
)
_TEMPLATE_RE = re.compile(r'^(template|block)\s+("[^"]*"|`[^`]*`)\s*(.*)$', re.DOTALL)
_DEFINE_RE = re.compile(r'^define\s+("[^"]*"|`[^`]*`)\s*$')


def _parse(template: str, where: str) -> List[_Node]:
    """Split into text/action nodes, honoring {{- and -}} whitespace trim."""
    pos = 0
    tokens: List[Tuple[str, str]] = []  # ("text", s) | ("action", src)
    for m in _ACTION_RE.finditer(template):
        text = template[pos : m.start()]
        if m.group(1) == "-":
            text = text.rstrip()
        if tokens and tokens[-1][0] == "trim-next":
            tokens.pop()
            text = text.lstrip()
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        if m.group(3) == "-":
            tokens.append(("trim-next", ""))
        pos = m.end()
    tail = template[pos:]
    if tokens and tokens[-1][0] == "trim-next":
        tokens.pop()
        tail = tail.lstrip()
    tokens.append(("text", tail))

    root: List[_Node] = []
    # stack entries: (children_list, owner node | None); `else`/`else if`
    # re-target the list according to the owner's type
    stack: List[Tuple[List[_Node], Optional[_Node]]] = [(root, None)]
    for kind, payload in tokens:
        children = stack[-1][0]
        if kind == "text":
            if payload:
                children.append(_Text(payload))
            continue
        src = payload
        if src.startswith("/*"):
            continue
        if src.startswith("if "):
            node = _If()
            node.branches.append((src[3:].strip(), []))
            children.append(node)
            stack.append((node.branches[-1][1], node))
        elif src.startswith("else if "):
            _, node = stack.pop()
            if not isinstance(node, _If):
                raise ChartRenderError(f"{where}: 'else if' outside if")
            if node.branches and node.branches[-1][0] is None:
                raise ChartRenderError(f"{where}: 'else if' after 'else'")
            node.branches.append((src[8:].strip(), []))
            stack.append((node.branches[-1][1], node))
        elif src == "else":
            prev_list, node = stack.pop()
            if isinstance(node, _If):
                if node.branches and node.branches[-1][0] is None:
                    raise ChartRenderError(f"{where}: duplicate 'else'")
                node.branches.append((None, []))
                stack.append((node.branches[-1][1], node))
            elif isinstance(node, (_Range, _With)):
                if prev_list is node.else_body:
                    raise ChartRenderError(f"{where}: duplicate 'else'")
                stack.append((node.else_body, node))
            else:
                raise ChartRenderError(f"{where}: 'else' outside if/range/with")
        elif src == "end":
            _, node = stack.pop()
            if node is None:
                raise ChartRenderError(f"{where}: unmatched 'end'")
        elif src.startswith("range ") or src == "range":
            m2 = _RANGE_VARS_RE.match(src)
            if m2:
                idx_var, val_var, expr = m2.group(1), m2.group(2), m2.group(3)
                if val_var is None:
                    # `range $v := x` — single variable binds the VALUE
                    idx_var, val_var = None, idx_var
            else:
                idx_var = val_var = None
                expr = src[len("range") :].strip()
                if not expr:
                    raise ChartRenderError(f"{where}: range needs an argument")
            node = _Range(idx_var, val_var, expr)
            children.append(node)
            stack.append((node.body, node))
        elif src.startswith("with "):
            node = _With(src[5:].strip())
            children.append(node)
            stack.append((node.body, node))
        elif src.startswith("define ") or src.startswith("block "):
            is_block = src.startswith("block ")
            if is_block:
                m2 = _TEMPLATE_RE.match(src)
                if not m2:
                    raise ChartRenderError(f"{where}: malformed block '{src}'")
                node = _Define(
                    m2.group(2)[1:-1],
                    render_in_place=True,
                    arg_src=m2.group(3).strip() or None,
                )
            else:
                m2 = _DEFINE_RE.match(src)
                if not m2:
                    raise ChartRenderError(f"{where}: malformed define '{src}'")
                node = _Define(m2.group(1)[1:-1])
            children.append(node)
            stack.append((node.body, node))
        elif src.startswith("template ") or src.startswith("template\t"):
            m2 = _TEMPLATE_RE.match(src)
            if not m2:
                raise ChartRenderError(f"{where}: malformed template '{src}'")
            arg = m2.group(3).strip()
            children.append(_TemplateCall(m2.group(2)[1:-1], arg or None))
        else:
            m2 = _VAR_STMT_RE.match(src)
            if m2:
                children.append(
                    _Var(m2.group(1), m2.group(3).strip(), m2.group(2) == ":=")
                )
            else:
                children.append(_Expr(src))
    if len(stack) != 1:
        raise ChartRenderError(f"{where}: unclosed control structure")
    return root


def _tokenize_expr(src: str, where: str) -> List[str]:
    out, i, n = [], 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
        elif c in "\"'`":
            j = i + 1
            while j < n and src[j] != c:
                j += 2 if src[j] == "\\" else 1
            out.append(src[i : j + 1])
            i = j + 1
        elif c == "|":
            out.append("|")
            i += 1
        elif c == "(" or c == ")":
            out.append(c)
            i += 1
        else:
            j = i
            while j < n and not src[j].isspace() and src[j] not in "|()":
                j += 1
            out.append(src[i:j])
            i = j
    return out


class _Scope:
    """Dot + root + lexically chained variables (Go template semantics:
    variables declared in a block are visible until its `end`)."""

    __slots__ = ("dot", "root", "vars", "parent")

    def __init__(self, dot, root, parent=None):
        self.dot = dot
        self.root = root
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def child(self, dot=None):
        return _Scope(self.dot if dot is None else dot, self.root, self)

    def get_var(self, name, where):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise ChartRenderError(f"{where}: undefined variable '${name}'")

    def set_var(self, name, value, declare, where):
        if declare:
            self.vars[name] = value
            return
        s = self
        while s is not None:
            if name in s.vars:
                s.vars[name] = value
                return
            s = s.parent
        raise ChartRenderError(f"{where}: assignment to undeclared '${name}'")


class _Env:
    """Per-chart render environment: the define namespace (shared by every
    template file, like Helm's single template tree)."""

    def __init__(self, defines: Optional[Dict[str, List[_Node]]] = None):
        self.defines: Dict[str, List[_Node]] = defines if defines is not None else {}

    def include(self, name, dot, where):
        body = self.defines.get(name)
        if body is None:
            raise ChartRenderError(f"{where}: include of undefined template '{name}'")
        out: List[str] = []
        # Go text/template rebinds $ to each execution's data argument, so
        # inside an included template $ IS the passed dot, not the chart root
        scope = _Scope(dot, dot)
        _render_nodes(body, scope, self, out, where)
        return "".join(out)


def _field_path(value, path: str, where: str):
    for part in path.split("."):
        if not part:
            continue
        if isinstance(value, dict):
            value = value.get(part)
        else:
            value = getattr(value, part, None)
        if value is None:
            return None
    return value


def _to_yaml(v) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _sprig_trunc(n, s):
    n = int(n)
    s = str(s)
    return s[:n] if n >= 0 else s[n:]


def _sprig_dict(*kv):
    if len(kv) % 2:
        raise ValueError("dict needs an even number of arguments")
    return {str(kv[i]): kv[i + 1] for i in range(0, len(kv), 2)}


def _required(msg, v=None):
    if v is None or v == "":
        raise ValueError(str(msg))
    return v


def _deep_merge(dst, *srcs):
    """sprig merge: deep merge into dst; dst's values win, nested maps
    merge recursively."""
    out = dict(dst or {})
    for src in srcs:
        for k, v in (src or {}).items():
            if k in out and isinstance(out[k], dict) and isinstance(v, dict):
                out[k] = _deep_merge(out[k], v)
            elif k not in out:
                out[k] = v
    return out


def _int_strict(x):
    """Integer operand for sprig arithmetic: non-integral or non-numeric
    operands raise rather than silently truncating (the fail-loud contract —
    Go/sprig would coerce through int64, changing the value). Ints pass
    through exactly (never via float, which rounds above 2^53); integral
    floats and numeric strings are accepted."""
    if isinstance(x, int) and not isinstance(x, bool):
        return x
    if isinstance(x, str):
        try:
            return int(x)  # exact for arbitrarily large integer strings
        except ValueError:
            pass  # "3.0" falls through to the float path
    try:
        f = float(x)
    except (TypeError, ValueError):
        raise ChartRenderError(f"non-numeric operand {x!r} to integer arithmetic")
    if f != int(f):
        raise ChartRenderError(f"non-integral operand {x!r} to integer arithmetic")
    return int(f)


_FUNCS = {
    "int": lambda a: int(float(a)) if a not in (None, "") else 0,
    "int64": lambda a: int(float(a)) if a not in (None, "") else 0,
    "float64": lambda a: float(a) if a not in (None, "") else 0.0,
    "quote": lambda a: '"%s"' % str(a if a is not None else "").replace('"', '\\"'),
    "squote": lambda a: "'%s'" % (a if a is not None else ""),
    "upper": lambda a: str(a).upper(),
    "lower": lambda a: str(a).lower(),
    "title": lambda a: str(a).title(),
    "trim": lambda a: str(a).strip(),
    "trimSuffix": lambda suf, s: str(s)[: -len(suf)] if suf and str(s).endswith(suf) else str(s),
    "trimPrefix": lambda pre, s: str(s)[len(pre):] if str(s).startswith(pre) else str(s),
    "trunc": _sprig_trunc,
    "abbrev": lambda n, s: (str(s)[: int(n) - 3] + "...") if len(str(s)) > int(n) else str(s),
    "replace": lambda old, new, s: str(s).replace(old, new),
    "contains": lambda sub, s: sub in str(s),
    "hasPrefix": lambda pre, s: str(s).startswith(pre),
    "hasSuffix": lambda suf, s: str(s).endswith(suf),
    "repeat": lambda n, s: str(s) * int(n),
    "nospace": lambda s: re.sub(r"\s", "", str(s)),
    "toYaml": _to_yaml,
    "toJson": lambda v: __import__("json").dumps(v),
    "fromYaml": lambda s: yaml.safe_load(s) or {},
    "toString": lambda a: _format(a) if not isinstance(a, str) else a,
    "default": lambda d, v=None: v if _truthy(v) else d,
    "coalesce": lambda *a: next((x for x in a if _truthy(x)), None),
    "ternary": lambda t, f, c: t if _truthy(c) else f,
    "empty": lambda a: not _truthy(a),
    "required": _required,
    "fail": lambda msg: (_ for _ in ()).throw(ValueError(str(msg))),
    "indent": lambda n, s: "\n".join(" " * int(n) + ln for ln in str(s).splitlines()),
    "nindent": lambda n, s: "\n" + "\n".join(" " * int(n) + ln for ln in str(s).splitlines()),
    "printf": lambda fmt, *a: _go_printf(fmt, *a),
    "print": lambda *a: "".join(_format(x) for x in a),
    "println": lambda *a: "".join(_format(x) for x in a) + "\n",
    "not": lambda a: not _truthy(a),
    "eq": lambda a, *b: any(a == x for x in b),
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "and": lambda *a: next((x for x in a if not _truthy(x)), a[-1] if a else None),
    "or": lambda *a: next((x for x in a if _truthy(x)), a[-1] if a else None),
    "add": lambda *a: sum(_int_strict(x) for x in a),
    "add1": lambda a: _int_strict(a) + 1,
    "sub": lambda a, b: _int_strict(a) - _int_strict(b),
    "mul": lambda *a: __import__("math").prod(_int_strict(x) for x in a),
    "div": lambda a, b: _int_strict(a) // _int_strict(b),
    "mod": lambda a, b: _int_strict(a) % _int_strict(b),
    "max": lambda *a: max(_int_strict(x) for x in a),
    "min": lambda *a: min(_int_strict(x) for x in a),
    "len": lambda a: len(a) if a is not None else 0,
    "list": lambda *a: list(a),
    "dict": _sprig_dict,
    "get": lambda d, k: (d or {}).get(str(k), ""),
    "hasKey": lambda d, k: str(k) in (d or {}),
    "keys": lambda *ds: sorted(k for d in ds for k in (d or {})),
    "pluck": lambda k, *ds: [d[k] for d in ds if k in (d or {})],
    "merge": _deep_merge,
    "join": lambda sep, xs: str(sep).join(_format(x) for x in (xs or [])),
    "splitList": lambda sep, s: str(s).split(sep),
    "split": lambda sep, s: {f"_{i}": p for i, p in enumerate(str(s).split(sep))},
    "first": lambda xs: xs[0] if xs else None,
    "last": lambda xs: xs[-1] if xs else None,
    "rest": lambda xs: list(xs[1:]) if xs else [],
    "initial": lambda xs: list(xs[:-1]) if xs else [],
    "append": lambda xs, x: list(xs or []) + [x],
    "prepend": lambda xs, x: [x] + list(xs or []),
    "uniq": lambda xs: list(dict.fromkeys(xs or [])),
    "sortAlpha": lambda xs: sorted(str(x) for x in (xs or [])),
    "b64enc": lambda s: __import__("base64").b64encode(str(s).encode()).decode(),
    "b64dec": lambda s: __import__("base64").b64decode(str(s)).decode(),
    "sha256sum": lambda s: __import__("hashlib").sha256(str(s).encode()).hexdigest(),
    "kindIs": lambda kind, v: {
        "map": isinstance(v, dict),
        "slice": isinstance(v, list),
        "string": isinstance(v, str),
        "bool": isinstance(v, bool),
        "int": isinstance(v, int) and not isinstance(v, bool),
        "float64": isinstance(v, float),
        "invalid": v is None,
    }.get(str(kind), False),
    # offline render: no cluster to query (helm template does the same)
    "lookup": lambda *a: {},
}


def _go_printf(fmt, *args):
    out = []
    it = iter(args)
    i, n = 0, len(str(fmt))
    fmt = str(fmt)
    while i < n:
        c = fmt[i]
        if c == "%" and i + 1 < n:
            spec = fmt[i + 1]
            if spec == "%":
                out.append("%")
            elif spec == "q":
                out.append('"%s"' % _format(next(it, "")))
            elif spec == "d":
                # integral floats and numeric strings render as the integer;
                # a non-integral operand raises (Go would emit an
                # %!d(float64=...) error marker — fail loud instead)
                out.append(str(_int_strict(next(it, 0))))
            elif spec == "f":
                out.append("%f" % float(next(it, 0.0)))  # Go's 6-decimal default
            elif spec in "sv":
                out.append(_format(next(it, "")))
            else:
                raise ChartRenderError(f"printf: unsupported verb %{spec}")
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _truthy(v) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict)) and len(v) == 0:
        return False
    return True


def _eval_atom(tok: str, scope: _Scope, where: str):
    if tok == "." or tok == "$":
        return scope.dot if tok == "." else scope.root
    if tok.startswith("$."):
        return _field_path(scope.root, tok[2:], where)
    if tok.startswith("$"):
        name, _, rest = tok[1:].partition(".")
        val = scope.get_var(name, where)
        return _field_path(val, rest, where) if rest else val
    if tok.startswith("."):
        return _field_path(scope.dot, tok[1:], where)
    if tok[:1] in "\"'`":
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    if tok == "nil":
        return None
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok  # bare word (function name handled by caller)


_SENTINEL = object()


def _parse_operands(tokens: List[str], pos: int, where: str):
    """Parse one pipeline stage's operands until `|`, `)` or EOF. Each
    operand is ("atom", tok) or ("pipe", stages)."""
    ops = []
    n = len(tokens)
    while pos < n and tokens[pos] not in ("|", ")"):
        if tokens[pos] == "(":
            stages, pos = _parse_stages(tokens, pos + 1, where)
            if pos >= n or tokens[pos] != ")":
                raise ChartRenderError(f"{where}: unclosed '('")
            pos += 1
            ops.append(("pipe", stages))
        else:
            ops.append(("atom", tokens[pos]))
            pos += 1
    return ops, pos


def _parse_stages(tokens: List[str], pos: int, where: str):
    """Parse a pipeline (stages separated by `|`) until `)` or EOF."""
    stages = []
    while True:
        ops, pos = _parse_operands(tokens, pos, where)
        stages.append(ops)
        if pos < len(tokens) and tokens[pos] == "|":
            pos += 1
            continue
        return stages, pos


def _eval_operand(op, scope: _Scope, env: _Env, where: str):
    kind, payload = op
    if kind == "pipe":
        return _eval_stages(payload, scope, env, where)
    return _eval_atom(payload, scope, where)


def _eval_stage(ops, piped, scope: _Scope, env: _Env, where: str):
    """One pipeline stage: `fn a b` or a single operand; `piped` is appended
    as the last argument (Go pipeline semantics)."""
    if not ops:
        raise ChartRenderError(f"{where}: empty pipeline stage")
    head_kind, head = ops[0]
    if head_kind == "atom" and (head in _FUNCS or head in ("include", "tpl", "template")):
        args = [_eval_operand(op, scope, env, where) for op in ops[1:]]
        if piped is not _SENTINEL:
            args.append(piped)
        try:
            if head == "include":
                return env.include(
                    str(args[0]), args[1] if len(args) > 1 else None, where
                )
            if head == "tpl":
                # render a string as a template against the given context
                tpl_src, dot = str(args[0]), args[1] if len(args) > 1 else None
                out: List[str] = []
                _render_nodes(_parse(tpl_src, where), _Scope(dot, dot), env, out, where)
                return "".join(out)
            if head == "template":
                raise ChartRenderError(
                    f"{where}: 'template' is a statement; use 'include' in pipelines"
                )
            return _FUNCS[head](*args)
        except ChartRenderError:
            raise
        except Exception as exc:
            raise ChartRenderError(f"{where}: {head}(...) failed: {exc}") from exc
    if len(ops) != 1 or piped is not _SENTINEL:
        raise ChartRenderError(f"{where}: unknown function '{head}'")
    return _eval_operand(ops[0], scope, env, where)


def _eval_stages(stages, scope: _Scope, env: _Env, where: str):
    val = _SENTINEL
    for stage in stages:
        val = _eval_stage(stage, val, scope, env, where)
    return val


def _eval_expr(src: str, scope: _Scope, env: _Env, where: str):
    tokens = _tokenize_expr(src, where)
    stages, pos = _parse_stages(tokens, 0, where)
    if pos != len(tokens):
        raise ChartRenderError(f"{where}: unexpected '{tokens[pos]}' in '{src}'")
    return _eval_stages(stages, scope, env, where)


def _format(v) -> str:
    if v is None:
        return "<no value>"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _range_items(val, where: str):
    """(key-or-index, value) pairs; maps iterate in sorted key order like Go
    text/template."""
    if val is None:
        return []
    if isinstance(val, dict):
        return [(k, val[k]) for k in sorted(val, key=str)]
    if isinstance(val, (list, tuple)):
        return list(enumerate(val))
    if isinstance(val, str):
        return list(enumerate(val))
    if isinstance(val, int):
        return list(enumerate(range(val)))  # Go 1.22 range-over-int
    raise ChartRenderError(f"{where}: cannot range over {type(val).__name__}")


def _render_nodes(nodes: List[_Node], scope: _Scope, env: _Env, out: List[str], where: str):
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Expr):
            out.append(_format(_eval_expr(node.src, scope, env, where)))
        elif isinstance(node, _Var):
            scope.set_var(
                node.name, _eval_expr(node.src, scope, env, where), node.declare, where
            )
        elif isinstance(node, _If):
            for cond, children in node.branches:
                if cond is None or _truthy(_eval_expr(cond, scope, env, where)):
                    _render_nodes(children, scope.child(), env, out, where)
                    break
        elif isinstance(node, _Range):
            items = _range_items(_eval_expr(node.src, scope, env, where), where)
            if not items:
                _render_nodes(node.else_body, scope.child(), env, out, where)
                continue
            for k, v in items:
                body_scope = scope.child(dot=v)
                if node.idx_var:
                    body_scope.vars[node.idx_var] = k
                if node.val_var:
                    body_scope.vars[node.val_var] = v
                _render_nodes(node.body, body_scope, env, out, where)
        elif isinstance(node, _With):
            val = _eval_expr(node.src, scope, env, where)
            if _truthy(val):
                _render_nodes(node.body, scope.child(dot=val), env, out, where)
            else:
                _render_nodes(node.else_body, scope.child(), env, out, where)
        elif isinstance(node, _Define):
            env.defines.setdefault(node.name, node.body)
            if node.render_in_place:  # block = define + immediate render
                dot = (
                    _eval_expr(node.arg_src, scope, env, where)
                    if node.arg_src is not None
                    else scope.dot
                )
                out.append(env.include(node.name, dot, where))
        elif isinstance(node, _TemplateCall):
            dot = (
                _eval_expr(node.arg_src, scope, env, where)
                if node.arg_src is not None
                else None
            )
            out.append(env.include(node.name, dot, where))


def collect_defines(template: str, where: str, env: _Env) -> List[_Node]:
    """Parse a template file and register every `define` into the chart-wide
    namespace (Helm parses all files into one template tree, so partials in
    `_helpers.tpl` are visible everywhere). Returns the parse for reuse."""
    nodes = _parse(template, where)

    def walk(ns):
        for nd in ns:
            if isinstance(nd, _Define):
                env.defines.setdefault(nd.name, nd.body)
                walk(nd.body)
            elif isinstance(nd, _If):
                for _, children in nd.branches:
                    walk(children)
            elif isinstance(nd, (_Range, _With)):
                walk(nd.body)
                walk(nd.else_body)

    walk(nodes)
    return nodes


def render_template(
    template: str, ctx: dict, where: str = "<template>", env: Optional[_Env] = None
) -> str:
    out: List[str] = []
    _render_nodes(
        _parse(template, where), _Scope(ctx, ctx), env or _Env(), out, where
    )
    return "".join(out)


# ---------------------------------------------------------------------------
# Chart loading (directory or .tgz, like helm loader.Load)
# ---------------------------------------------------------------------------


def _load_chart_files(chart_path: str) -> Dict[str, str]:
    """Relative path → content for Chart.yaml, values.yaml, templates/*."""
    files: Dict[str, str] = {}
    if os.path.isdir(chart_path):
        for root, _dirs, names in os.walk(chart_path):
            for name in names:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, chart_path)
                with open(full, "r", encoding="utf-8") as fh:
                    files[rel] = fh.read()
    elif tarfile.is_tarfile(chart_path):
        with tarfile.open(chart_path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                rel = member.name.split("/", 1)[-1]  # strip top-level dir
                data = tf.extractfile(member).read().decode("utf-8")
                files[rel] = data
    else:
        raise ChartRenderError(f"{chart_path}: not a chart directory or archive")
    if "Chart.yaml" not in files:
        raise ChartRenderError(f"{chart_path}: no Chart.yaml")
    return files


def process_chart(name: str, chart_path: str) -> List[str]:
    """Render a chart into YAML manifest strings in InstallOrder.

    `name` overrides the chart name (`chart.go:24`
    `chartRequested.Metadata.Name = name`), which also becomes
    `.Release.Name` (`chart.go:59` uses `chrt.Name()`).
    """
    files = _load_chart_files(chart_path)
    metadata = yaml.safe_load(files["Chart.yaml"]) or {}
    chart_type = metadata.get("type") or ""
    if chart_type not in ("", "application"):
        # checkIfInstallable (chart.go:45-51)
        raise ChartRenderError(f"{chart_type} charts are not installable")
    metadata["name"] = name
    values = yaml.safe_load(files.get("values.yaml", "")) or {}
    ctx = {
        "Values": values,
        "Chart": {**metadata, "Name": name},
        "Release": {
            "Name": name,
            "Namespace": "default",
            "Revision": 1,
            "Service": "Helm",
        },
        "Capabilities": {"KubeVersion": {"Version": "v1.20.5", "Major": "1", "Minor": "20"}},
    }

    # pass 1: parse every template file and register defines into one
    # chart-wide namespace (Helm's single template tree — `_helpers.tpl`
    # partials are visible from every manifest)
    env = _Env()
    parsed: Dict[str, List[_Node]] = {}
    for rel in sorted(files):
        parts = rel.split(os.sep)
        if parts[0] != "templates" or len(parts) < 2:
            continue
        if rel.endswith(NOTES_SUFFIX):
            continue
        parsed[rel] = collect_defines(files[rel], rel, env)

    docs: List[Tuple[int, int, str]] = []  # (kind_rank, seq, content)
    seq = 0
    for rel in sorted(parsed):
        base = rel.split(os.sep)[-1]
        if base.startswith("_"):
            continue  # partials render nothing themselves (chart.go:92-103)
        out: List[str] = []
        _render_nodes(parsed[rel], _Scope(ctx, ctx), env, out, rel)
        rendered = "".join(out)
        for doc in re.split(r"(?m)^---\s*$", rendered):
            if not doc.strip():
                continue  # empty manifests removed (chart.go:105-107)
            try:
                obj = yaml.safe_load(doc)
            except yaml.YAMLError as exc:
                raise ChartRenderError(f"{rel}: rendered invalid YAML: {exc}") from exc
            if not isinstance(obj, dict):
                continue
            annotations = (obj.get("metadata") or {}).get("annotations") or {}
            if "helm.sh/hook" in annotations:
                # the reference discards hooks (chart.go:110 drops the first
                # return of SortManifests)
                continue
            rank = _KIND_RANK.get(obj.get("kind"), len(INSTALL_ORDER))
            docs.append((rank, seq, doc.strip("\n")))
            seq += 1
    docs.sort(key=lambda t: (t[0], t[1]))
    return [content for _rank, _seq, content in docs]
