"""Pod-ordering heuristics — the `pkg/algo` queues as sort keys.

The reference defines three `SchedulingQueueSort` implementations
(`pkg/algo/algo.go:4-8`): GreedQueue (DRF-style dominant share, descending,
`greed.go:10-83`), AffinityQueue (nodeSelector pods first, `affinity.go:8-23`)
and TolerationQueue (tolerations pods first, `toleration.go:7-21`).
`ScheduleApp` always applies Affinity then Toleration
(`pkg/simulator/simulator.go:172-176`); GreedQueue exists behind the
`--use-greed` flag but is never constructed outside tests — we expose it as a
working sort here.

Sorting is host-side (argsort keys over the pod list), not a device kernel:
ordering decides the scan's pod axis order before compilation.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .core.objects import node_allocatable, pod_node_name, pod_requests


def share(alloc: float, total: float) -> float:
    """`algo.Share` (`greed.go:69-83`): alloc/total with 0/0 → 0, x/0 → 1."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def cluster_total_resources(nodes: Sequence[dict]) -> Dict[str, float]:
    """Summed allocatable cpu+memory (`greed.go:16-33`)."""
    total = {"cpu": 0.0, "memory": 0.0}
    for node in nodes:
        alloc = node_allocatable(node)
        total["cpu"] += alloc.get("cpu", 0.0)
        total["memory"] += alloc.get("memory", 0.0)
    return total


def pod_dominant_share(pod: dict, total: Dict[str, float]) -> float:
    """`calculatePodShare` (`greed.go:50-67`): max share over cpu/memory."""
    req = pod_requests(pod)
    if not req:
        return 0.0
    return max(share(req.get(r, 0.0), total[r]) for r in ("cpu", "memory"))


def greed_sort(pods: List[dict], nodes: Sequence[dict]) -> List[dict]:
    """GreedQueue order: pods with a nodeName first, then descending dominant
    share of cluster-total resources (`greed.go:37-48`). Stable."""
    total = cluster_total_resources(nodes)
    return sorted(
        pods,
        key=lambda p: (
            0 if pod_node_name(p) else 1,
            -pod_dominant_share(p, total),
        ),
    )


def affinity_sort(pods: List[dict]) -> List[dict]:
    """AffinityQueue: pods with a nodeSelector first (`affinity.go:21-23`)."""
    return sorted(
        pods, key=lambda p: (p.get("spec") or {}).get("nodeSelector") is None
    )


def toleration_sort(pods: List[dict]) -> List[dict]:
    """TolerationQueue: pods with tolerations first (`toleration.go:19-21`)."""
    return sorted(
        pods, key=lambda p: not (p.get("spec") or {}).get("tolerations")
    )
