"""ctypes loader for the native host-path accelerators.

Builds `src/simtpu_native.cpp` with g++ on first import (cached next to the
source, rebuilt when the source changes) and exposes:

- ``parse_quantities(values) -> np.ndarray`` — batch k8s quantity parsing;
- ``scatter_add_rows(dst, idx, src)`` — ``dst[idx[i], :] += src[i, :]``;
- ``scatter_add_flat(dst, idx, vals)`` — ``dst.ravel()[idx[i]] += vals[i]``.

Everything degrades gracefully: ``available()`` is False when no compiler
exists or the build fails, and every caller keeps a pure-numpy fallback — the
package stays importable on a machine with no toolchain.

``SIMTPU_NATIVE=0`` forces ``available() -> False`` and routes every entry
point through its pure-python/numpy fallback even when the library builds —
the A/B lever behind the fallback-parity tests (tests/test_native.py) and a
production escape hatch if a host's toolchain miscompiles.  The env var is
read per call, so tests can flip it without reloading the module.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "simtpu_native.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_BUILD_DIR, f"simtpu_native_{digest}.so")
    if os.path.exists(out):
        return out
    # build into a unique temp file so concurrent importers (pytest-xdist)
    # can't interleave writes; os.replace makes publication atomic. An
    # unwritable package dir (read-only install) just means numpy fallback.
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
        os.close(fd)
    except OSError:
        return None
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.chmod(tmp, 0o755)  # mkstemp's 0600 would break shared installs
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.simtpu_parse_quantities.restype = ctypes.c_longlong
    lib.simtpu_parse_quantities.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.simtpu_scatter_add_rows.restype = None
    lib.simtpu_scatter_add_rows.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong,
    ]
    lib.simtpu_scatter_add_flat.restype = None
    lib.simtpu_scatter_add_flat.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong,
    ]
    _lib = lib
    return _lib


def _enabled() -> Optional[ctypes.CDLL]:
    """The loaded library, or None when it is unavailable OR disabled via
    SIMTPU_NATIVE=0 — the one gate every entry point consults."""
    if os.environ.get("SIMTPU_NATIVE", "1") == "0":
        return None
    return _load()


def available() -> bool:
    return _enabled() is not None


def parse_quantities(values: Sequence) -> np.ndarray:
    """Batch-parse k8s quantities; raises ValueError on any unparseable entry
    (same contract as quantity.parse_quantity). None → 0.0."""
    lib = _enabled()
    if lib is None:
        from ..core.quantity import parse_quantity

        return np.array([parse_quantity(v) for v in values], np.float64)
    n = len(values)
    arr = (ctypes.c_char_p * n)()
    for i, v in enumerate(values):
        if v is None:
            arr[i] = None
        elif isinstance(v, bytes):
            arr[i] = v
        else:
            arr[i] = str(v).encode("utf-8")
    out = np.empty(n, np.float64)
    bad = lib.simtpu_parse_quantities(
        arr, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    )
    if bad:
        culprits = [values[i] for i in np.flatnonzero(np.isnan(out))[:3]]
        raise ValueError(f"unparseable quantities, e.g. {culprits!r}")
    return out


def scatter_add_rows(dst: np.ndarray, idx: np.ndarray, src: np.ndarray) -> bool:
    """dst[idx[i], :] += src[i, :] in place. Returns False (caller must fall
    back to np.add.at) when the native library is unavailable."""
    lib = _enabled()
    if lib is None:
        return False
    # dst must be updated in place: a contiguity copy would be silently lost
    assert dst.dtype == np.float32 and dst.ndim == 2 and dst.flags.c_contiguous
    idx = np.ascontiguousarray(idx, np.int32)
    src = np.ascontiguousarray(src, np.float32)
    assert src.shape == (len(idx), dst.shape[1])
    lib.simtpu_scatter_add_rows(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dst.shape[0],
        dst.shape[1],
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        len(idx),
    )
    return True


def scatter_add_flat(dst: np.ndarray, idx: np.ndarray, vals: np.ndarray) -> bool:
    """dst.ravel()[idx[i]] += vals[i] in place; False → caller falls back."""
    lib = _enabled()
    if lib is None:
        return False
    assert dst.dtype == np.float32 and dst.flags.c_contiguous
    idx = np.ascontiguousarray(idx, np.int64)
    vals = np.ascontiguousarray(vals, np.float32)
    lib.simtpu_scatter_add_flat(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dst.size,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        len(idx),
    )
    return True
