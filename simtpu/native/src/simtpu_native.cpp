// Native host-path accelerators for simtpu.
//
// The TPU engine's compute path is JAX/XLA/Pallas; this library speeds the
// *host* runtime around it — the role the reference fills with compiled Go
// (`go.mod:1-3`, static CGO_ENABLED=0 build): ingesting manifests and
// maintaining the placement-log bookkeeping that rebuilds scan state
// (`simtpu/engine/state.py`, the analog of the scheduler cache
// `vendor/k8s.io/kubernetes/pkg/scheduler/internal/cache/cache.go:57`).
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 dependency).
//
// Build: g++ -O3 -shared -fPIC -o simtpu_native.so simtpu_native.cpp

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// k8s resource-quantity suffix multipliers (apimachinery resource.Quantity
// grammar, mirrored from simtpu/core/quantity.py — the two tables must stay
// in sync; tests/test_native.py cross-checks them on a corpus).
double suffix_mult(const char* s, bool* ok) {
  *ok = true;
  if (s[0] == '\0') return 1.0;
  if (s[1] == '\0') {
    switch (s[0]) {
      case 'n': return 1e-9;
      case 'u': return 1e-6;
      case 'm': return 1e-3;
      case 'k': return 1e3;
      case 'M': return 1e6;
      case 'G': return 1e9;
      case 'T': return 1e12;
      case 'P': return 1e15;
      case 'E': return 1e18;
    }
  } else if (s[1] == 'i' && s[2] == '\0') {
    switch (s[0]) {
      case 'K': return 1024.0;
      case 'M': return 1048576.0;
      case 'G': return 1073741824.0;
      case 'T': return 1099511627776.0;
      case 'P': return 1125899906842624.0;
      case 'E': return 1152921504606846976.0;
    }
  }
  *ok = false;
  return 0.0;
}

}  // namespace

extern "C" {

// Parse n quantity strings into out[n]. Unparseable entries become NaN and
// count toward the return value (the Python wrapper raises on nonzero).
// NULL entries parse to 0.0 (Python-side None).
long long simtpu_parse_quantities(const char* const* strs, long long n,
                                  double* out) {
  long long bad = 0;
  for (long long i = 0; i < n; ++i) {
    const char* raw = strs[i];
    if (raw == nullptr) {
      out[i] = 0.0;
      continue;
    }
    // strip ascii whitespace
    while (*raw != '\0' && std::isspace(static_cast<unsigned char>(*raw))) ++raw;
    size_t len = std::strlen(raw);
    while (len > 0 && std::isspace(static_cast<unsigned char>(raw[len - 1]))) --len;
    if (len == 0) {
      out[i] = 0.0;
      continue;
    }
    // split at the last digit/dot (quantity.py's suffix scan)
    size_t cut = len;
    while (cut > 0) {
      char c = raw[cut - 1];
      if (std::isdigit(static_cast<unsigned char>(c)) || c == '.') break;
      --cut;
    }
    char suffix[8] = {0};
    size_t suffix_len = len - cut;
    bool suffix_ok = suffix_len < sizeof(suffix);
    double mult = 1.0;
    if (suffix_ok) {
      std::memcpy(suffix, raw + cut, suffix_len);
      mult = suffix_mult(suffix, &suffix_ok);
    }
    char* end = nullptr;
    if (suffix_ok && cut > 0) {
      // number part (may itself be scientific like "1.5e3" — but the suffix
      // scan stops at the trailing digit, so "12e6" lands here with suffix "")
      char numbuf[64];
      if (cut >= sizeof(numbuf)) {
        out[i] = NAN;
        ++bad;
        continue;
      }
      std::memcpy(numbuf, raw, cut);
      numbuf[cut] = '\0';
      double v = std::strtod(numbuf, &end);
      if (end == numbuf || *end != '\0') {
        out[i] = NAN;
        ++bad;
      } else {
        out[i] = v * mult;
      }
    } else {
      // unknown suffix: accept only if the whole string is a valid float
      // (scientific notation), mirroring quantity.py's fallback
      char allbuf[64];
      if (len >= sizeof(allbuf)) {
        out[i] = NAN;
        ++bad;
        continue;
      }
      std::memcpy(allbuf, raw, len);
      allbuf[len] = '\0';
      double v = std::strtod(allbuf, &end);
      if (end == allbuf || *end != '\0') {
        out[i] = NAN;
        ++bad;
      } else {
        out[i] = v;
      }
    }
  }
  return bad;
}

// dst[idx[i], :] += src[i, :]  — the unbuffered row-scatter `np.add.at`
// performs ~50x slower; used to rebuild free/ports/volume state from the
// placement log (engine/state.py build_state).
void simtpu_scatter_add_rows(float* dst, long long n_rows, long long n_cols,
                             const int32_t* idx, const float* src,
                             long long n_src) {
  for (long long i = 0; i < n_src; ++i) {
    long long r = idx[i];
    if (r < 0 || r >= n_rows) continue;
    float* drow = dst + r * n_cols;
    const float* srow = src + i * n_cols;
    for (long long c = 0; c < n_cols; ++c) drow[c] += srow[c];
  }
}

// dst[idx[i]] += vals[i] over a flattened target — the generic form used for
// the [T, D] topology-count rebuilds (indices pre-flattened host-side).
void simtpu_scatter_add_flat(float* dst, long long dst_len,
                             const int64_t* idx, const float* vals,
                             long long n) {
  for (long long i = 0; i < n; ++i) {
    int64_t j = idx[i];
    if (j < 0 || j >= dst_len) continue;
    dst[j] += vals[i];
  }
}

}  // extern "C"
