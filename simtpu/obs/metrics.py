"""One metrics registry for every simtpu counter family (ISSUE 8).

Before this module, telemetry lived in five ad-hoc module-global dicts —
`engine/scan.py`'s TRACE/FETCH/WAVE counters, `engine/state.py`'s carried
state gauge, `durable/backoff.py`'s OOM counters — each with its own
snapshot function, naming style, and consumer wiring (bench poked the
globals, the CLI assembled the `--json` engine block by hand).  The
registry gives them ONE home with stable dotted names, typed instruments,
and a uniform snapshot/delta protocol the CLI's `metrics` block and
bench's JSON line both read.

The legacy snapshot functions (`fetch_counts()`, `trace_counts()`,
`wave_counts()`, `backoff_counts()`, `state_gauge()`) were kept for one
release as alias views and are now REMOVED (ISSUE 13): the registry is
the only read surface — `REGISTRY.value(name)`, `snapshot(prefix)`, or
the `family(prefix, keys)` helper below for the flat short-key shape the
old functions returned.

Instruments:
- `Counter`  — monotone int, `inc(n)`; thread-safe (bumped from the AOT
  pool threads and the dispatch loop concurrently).
- `Gauge`    — last-write-wins value of any JSON-serializable type
  (ints, bools, per-plane byte dicts).
- `Histogram` — count/total/min/max summary of observed samples (span
  wall-clocks, byte sizes); no buckets — the Perfetto trace is the
  distribution view, the histogram is the cheap always-on summary.

Naming: `<family>.<field>`, lowercase, dots as the only separator —
`fetch.get`, `fetch.bytes`, `compile.scan`, `wavefront.rollback_pods`,
`backoff.events`, `state.carried_bytes`, `audit.total_violations`,
`device.peak_bytes`.  The full table lives in docs/observability.md.
"""

from __future__ import annotations

import threading
from typing import Dict

#: bump when the `--json` metrics block (or any stable name in it)
#: changes layout — downstream consumers pin on this, not on key probing
#: (`simtpu version --json` reports it next to the package version).
#: 2 = ISSUE 13: the versioned `explain` block joins the --json document,
#: `explain.*`/`compile.explain` instruments join the registry, and the
#: one-release legacy alias views are gone
SCHEMA_VERSION = 2


class Counter:
    """Monotone integer counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins value (any JSON-serializable type)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0

    def set(self, value) -> None:
        self._value = value

    @property
    def value(self):
        return self._value


class Histogram:
    """count/total/min/max summary of observed samples."""

    __slots__ = ("name", "count", "total", "min", "max", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._lock = lock

    def observe(self, sample: float) -> None:
        with self._lock:
            self.count += 1
            self.total += sample
            if self.min is None or sample < self.min:
                self.min = sample
            if self.max is None or sample > self.max:
                self.max = sample

    @property
    def value(self) -> Dict[str, object]:
        return {
            "count": self.count,
            "total": self.total,
            "min": self.min,
            "max": self.max,
        }


class MetricsRegistry:
    """Process-wide instrument registry.

    Instruments are created on first use and live for the process (the
    same lifetime the legacy module globals had — counters are monotone
    over a run; consumers wanting per-phase numbers snapshot before and
    `delta_since` after, which is exactly how the CLI's `metrics` block
    and the Applier's engine aliases are built, guaranteeing the two are
    bit-equal)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, self._lock)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, not {cls.__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    # -- read side ---------------------------------------------------------

    def value(self, name: str, default=0):
        """Current value of one instrument (counters default to 0 when
        never bumped — reading must not create instruments)."""
        inst = self._instruments.get(name)
        return default if inst is None else inst.value

    def snapshot(self, prefix: str = "") -> Dict[str, object]:
        """Flat name → value dict of every registered instrument (dict
        values are copied — the snapshot never aliases live state)."""
        out = {}
        for name, inst in sorted(self._instruments.items()):
            if prefix and not name.startswith(prefix):
                continue
            v = inst.value
            out[name] = dict(v) if isinstance(v, dict) else v
        return out

    def delta_since(self, before: Dict[str, object]) -> Dict[str, object]:
        """Snapshot minus `before`: counters and histogram count/total
        subtract, gauges report their CURRENT value (a gauge is a level,
        not a flow — `state.carried_bytes` after a plan is the carry's
        size, not a difference), instruments absent from `before` report
        verbatim."""
        now = self.snapshot()
        out = {}
        for name, v in now.items():
            inst = self._instruments.get(name)
            b = before.get(name)
            if isinstance(inst, Counter) and isinstance(b, int):
                out[name] = v - b
            elif isinstance(inst, Histogram) and isinstance(b, dict):
                out[name] = {
                    "count": v["count"] - b.get("count", 0),
                    "total": v["total"] - b.get("total", 0.0),
                    "min": v["min"],
                    "max": v["max"],
                }
            else:
                out[name] = v
        return out

    def reset(self) -> None:
        """Drop every instrument — TEST-ONLY (production counters are
        process-monotone by contract; resetting under a live dispatch
        loop would skew every open snapshot delta)."""
        with self._lock:
            self._instruments = {}


#: the process-wide registry every simtpu counter family lives in
REGISTRY = MetricsRegistry()


def family(prefix: str, keys) -> Dict[str, object]:
    """Read `<prefix>.<key>` for each key as one flat short-key dict —
    the shape the removed pre-registry snapshot functions exposed
    (e.g. `family("fetch", ("get", "bytes"))`); never-bumped counters
    read 0 rather than registering."""
    return {k: REGISTRY.value(f"{prefix}.{k}") for k in keys}
